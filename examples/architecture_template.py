"""Decoupled player/learner architecture template (reference
``examples/architecture_template.py``, which builds a 3-role torch-collective
pipeline; see SURVEY.md §3.3).

The TPU-native decoupling is thread + queue based inside the single-controller
process instead of one torch process per role: the PLAYER steps the envs on the
host and feeds rollouts through a bounded queue; the LEARNER runs the jitted
update on the device mesh and publishes fresh params back through a second queue.
Use this skeleton to build your own decoupled algorithm — the shipped
``ppo_decoupled`` / ``sac_decoupled`` entries follow exactly this structure
(``sheeprl_tpu/algos/ppo/ppo_decoupled.py``).

Run:  python examples/architecture_template.py
"""

from __future__ import annotations

import queue
import threading

import jax
import jax.numpy as jnp
import numpy as np


def learner(rollout_q: queue.Queue, param_q: queue.Queue, stop: threading.Event) -> None:
    """Consume rollouts, run the jitted update, publish params."""

    @jax.jit
    def update(params, batch):
        # your loss/grad/optimizer step here
        return jax.tree.map(lambda p: p + 0.01 * batch["reward"].mean(), params)

    params = {"w": jnp.zeros(())}
    while not stop.is_set():
        try:
            batch = rollout_q.get(timeout=1.0)
        except queue.Empty:
            continue
        if batch is None:  # player finished
            break
        params = update(params, batch)
        # Publish without blocking the training loop: replace the stale snapshot if
        # the player has not picked it up yet.
        snapshot = jax.device_get(params)
        try:
            param_q.put_nowait(snapshot)
        except queue.Full:
            try:
                param_q.get_nowait()  # evict the stale snapshot …
            except queue.Empty:
                pass
            try:
                param_q.put_nowait(snapshot)  # … and publish the fresh one
            except queue.Full:
                pass


def player(
    rollout_q: queue.Queue, param_q: queue.Queue, total_steps: int, learner_thread: threading.Thread
) -> None:
    """Step the env with the freshest published params, enqueue rollouts."""
    params = {"w": np.zeros(())}
    rng = np.random.default_rng(0)
    for _ in range(total_steps):
        try:
            params = param_q.get_nowait()  # refresh when the learner published
        except queue.Empty:
            pass
        rollout = {"obs": rng.normal(size=(8, 4)), "reward": rng.normal(size=(8,))}
        while True:  # bounded put applies backpressure — but never outlive a dead learner
            if not learner_thread.is_alive():
                raise RuntimeError("learner thread died; aborting player")
            try:
                rollout_q.put(rollout, timeout=1.0)
                break
            except queue.Full:
                continue
    while learner_thread.is_alive():  # same guard for the shutdown sentinel
        try:
            rollout_q.put(None, timeout=1.0)
            break
        except queue.Full:
            continue


def main() -> None:
    rollout_q: queue.Queue = queue.Queue(maxsize=2)
    param_q: queue.Queue = queue.Queue(maxsize=1)
    stop = threading.Event()
    t = threading.Thread(target=learner, args=(rollout_q, param_q, stop), daemon=True)
    t.start()
    try:
        player(rollout_q, param_q, total_steps=32, learner_thread=t)
    finally:
        stop.set()  # before join: the event is what makes the learner exit
    t.join(timeout=30)
    print("decoupled template finished")


if __name__ == "__main__":
    main()
