"""Print the observation/action space an env config produces after the full
``make_env`` wrapper pipeline (reference ``examples/observation_space.py``).

    python examples/observation_space.py env=gym env.id=CartPole-v1
    python examples/observation_space.py env=discrete_dummy algo=dreamer_v3
"""

from __future__ import annotations

import sys

from sheeprl_tpu.config.core import compose
from sheeprl_tpu.utils.env import make_env


def main() -> None:
    overrides = sys.argv[1:] or ["env=discrete_dummy"]
    if not any(o.startswith(("exp=", "algo=")) for o in overrides):
        overrides.append("algo=ppo")  # any algo satisfies the mandatory group
    # only the env subtree matters here; satisfy the other required values
    overrides = ["algo.total_steps=1", "algo.per_rank_batch_size=1", "buffer.size=1", *overrides]
    cfg = compose(overrides=overrides)
    if not (cfg.algo.cnn_keys.encoder or cfg.algo.mlp_keys.encoder):
        # vector keys only by default: requesting "rgb" from a vector-only env would
        # drag in a render-based pixel pipeline (and pygame) just to print the space
        cfg.algo.mlp_keys.encoder = ["state"]
    env = make_env(cfg, seed=cfg.seed, rank=0)()
    try:
        print(f"env.id          = {cfg.env.id}")
        print(f"observation space:")
        for name, space in env.observation_space.spaces.items():
            print(f"  {name:20s} {space}")
        print(f"action space    = {env.action_space}")
    finally:
        env.close()


if __name__ == "__main__":
    main()
