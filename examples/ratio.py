"""Demonstrate the replay-ratio governor (reference ``examples/ratio.py``):
``Ratio(r)`` is called with the cumulative policy-step count each iteration and
returns how many gradient steps to run so the long-run gradient-steps /
policy-steps ratio converges to ``r`` — including fractional ratios, where whole
gradient steps are emitted only when enough policy steps have accumulated.

    python examples/ratio.py
"""

from sheeprl_tpu.utils.utils import Ratio

if __name__ == "__main__":
    for r in (1.0, 0.5, 0.0625):
        ratio = Ratio(r)
        policy_step, grad_steps = 0, 0
        per_iter = 4  # e.g. 4 envs x 1 step
        for _ in range(64):
            policy_step += per_iter
            grad_steps += ratio(policy_step)
        print(f"target ratio {r:<8} achieved {grad_steps / policy_step:.4f} "
              f"({grad_steps} gradient steps over {policy_step} policy steps)")
