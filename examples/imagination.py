"""Decode a trained DreamerV3 world model's imagination to PNG strips.

Parity artifact for the reference's ``notebooks/dreamer_v3_imagination.ipynb``:
load a checkpoint, run the trained (greedy) player for ``context`` real env steps
so the RSSM posterior locks onto the episode, then let the world model imagine
``horizon`` steps on its own — actions chosen by the trained actor on the imagined
latents, next stochastic states from the prior (no observations) — and decode
everything back to pixels.

The output strip has three rows:

1. real frames (the env's ground truth over the context + horizon window);
2. posterior reconstructions (what the world model decodes while it still SEES
   the frames — reconstruction quality);
3. the same context reconstructions followed by the pure imagination rollout
   (what the behaviour learns from — dream quality).

Usage::

    python examples/imagination.py checkpoint_path=<run>/checkpoints/ckpt_N \
        [context=5] [horizon=15] [out=imagination.png] [env overrides...]
"""

from __future__ import annotations

import sys

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    overrides = sys.argv[1:]
    opts = {"context": 5, "horizon": 15, "out": "imagination.png"}
    passthrough = []
    for ov in overrides:
        key = ov.partition("=")[0]
        if key in opts:
            val = ov.partition("=")[2]
            opts[key] = int(val) if key != "out" else val
        else:
            passthrough.append(ov)
    if opts["context"] < 1 or opts["horizon"] < 1:
        raise SystemExit("context and horizon must both be >= 1 (the imagination rollout starts from the last posterior)")

    from sheeprl_tpu.algos.dreamer_v3.agent import (
        PlayerState,
        WorldModel,
        build_agent,
        make_player_step,
        parse_actions_dim,
    )
    from sheeprl_tpu.checkpoint.manager import CheckpointManager
    from sheeprl_tpu.cli import _load_checkpoint_cfg
    from sheeprl_tpu.parallel.mesh import make_mesh_context
    from sheeprl_tpu.utils.env import make_env

    cfg, ckpt_path = _load_checkpoint_cfg(passthrough, "checkpoint_path")
    cfg.env.capture_video = False
    ctx = make_mesh_context(cfg)

    env = make_env(cfg, cfg.seed, 0, None, "imagination")()
    obs_space = env.observation_space
    is_continuous, actions_dim = parse_actions_dim(env.action_space)
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    if not cnn_keys:
        raise SystemExit("imagination decoding needs at least one pixel key (algo.cnn_keys.encoder)")

    world_model, actor, critic, params, _ = build_agent(ctx, actions_dim, is_continuous, cfg, obs_space)
    params = ctx.replicate(CheckpointManager.load(ckpt_path, templates={"params": jax.device_get(params)})["params"])
    player_step = jax.jit(
        make_player_step(world_model, actor, actions_dim, cfg.algo.world_model.discrete_size),
        static_argnames=("greedy",),
    )

    stoch_size = cfg.algo.world_model.stochastic_size * cfg.algo.world_model.discrete_size
    rec_size = cfg.algo.world_model.recurrent_model.recurrent_state_size
    state = PlayerState(
        recurrent_state=jnp.zeros((1, rec_size)),
        stochastic_state=jnp.zeros((1, stoch_size)),
        actions=jnp.zeros((1, int(sum(actions_dim)))),
    )

    def obs_tree(o):
        t = {}
        for k in cnn_keys:
            v = np.asarray(o[k])
            t[k] = jnp.asarray(v.reshape(1, -1, *v.shape[-2:]))
        for k in mlp_keys:
            t[k] = jnp.asarray(np.asarray(o[k], np.float32).reshape(1, -1))
        return t

    wm = params["world_model"]
    key = jax.random.PRNGKey(cfg.seed)

    def _to_rgb3(frame: np.ndarray) -> np.ndarray:
        """[C, H, W] uint8 -> 3-channel: tile grayscale, keep the first 3 of stacks."""
        if frame.shape[0] < 3:
            frame = np.repeat(frame[-1:], 3, axis=0)
        return frame[:3]

    # Scaling for float observations, decided ONCE from the env's declared range
    # (a per-frame min() heuristic would flicker between branches on bright frames).
    _space = env.observation_space[cnn_keys[0]]
    _lo, _hi = float(np.min(_space.low)), float(np.max(_space.high))
    _span = (_hi - _lo) if np.isfinite(_hi - _lo) and _hi > _lo else 1.0

    def _to_uint8(raw: np.ndarray) -> np.ndarray:
        if np.issubdtype(raw.dtype, np.floating):
            raw = np.clip((raw - _lo) * (255.0 / _span), 0, 255)
        return raw.astype(np.uint8)

    def decode_frame(stoch, recurrent):
        latent = jnp.concatenate([stoch, recurrent], -1)
        recon = world_model.apply(wm, latent, method=WorldModel.decode)
        img = np.asarray(recon[cnn_keys[0]][0], np.float32)  # [C, H, W], ~[-0.5, 0.5]
        return _to_rgb3(np.clip((img + 0.5) * 255.0, 0, 255).astype(np.uint8))

    # --- context: real steps through the trained player (posterior latents)
    obs, _ = env.reset(seed=cfg.seed)
    is_first = jnp.ones((1, 1))
    real_frames, recon_frames = [], []
    total = opts["context"] + opts["horizon"]
    for t in range(total):
        key, sub = jax.random.split(key)
        actions, stored, state = player_step(params, state, obs_tree(obs), is_first, sub, greedy=True)
        is_first = jnp.zeros((1, 1))
        raw = np.asarray(obs[cnn_keys[0]]).reshape(-1, *np.asarray(obs[cnn_keys[0]]).shape[-2:])
        real_frames.append(_to_rgb3(_to_uint8(raw)))
        recon_frames.append(decode_frame(state.stochastic_state, state.recurrent_state))
        if t == opts["context"] - 1:
            break_state = state  # imagination starts from the last posterior
        acts = jax.device_get(actions)
        env_action = (
            np.asarray(acts[0][0])
            if is_continuous
            else (np.asarray(acts[0][0]).argmax(-1) if len(actions_dim) == 1 else np.stack([np.asarray(a[0]).argmax(-1) for a in acts], -1))
        )
        obs, _, terminated, truncated, _ = env.step(env_action)
        if terminated or truncated:
            obs, _ = env.reset()
            is_first = jnp.ones((1, 1))
    env.close()

    # --- imagination: prior-only rollout from the end of the context
    stoch, recurrent = break_state.stochastic_state, break_state.recurrent_state
    imag_frames = recon_frames[: opts["context"]]
    for _ in range(opts["horizon"]):
        key, k_act, k_dyn = jax.random.split(key, 3)
        latent = jnp.concatenate([stoch, recurrent], -1)
        acts, _ = actor.apply(params["actor"], latent, k_act, False, None)
        action = jnp.concatenate(acts, -1)
        stoch, recurrent = world_model.apply(wm, stoch, recurrent, action, k_dyn, method=WorldModel.imagination)
        imag_frames.append(decode_frame(stoch, recurrent))

    # --- compose the three-row strip
    def row(frames):
        return np.concatenate([np.transpose(f[:3], (1, 2, 0)) for f in frames], axis=1)

    rows = [row(real_frames), row(recon_frames), row(imag_frames)]
    strip = np.concatenate(rows, axis=0)
    try:
        import cv2

        cv2.imwrite(opts["out"], cv2.cvtColor(strip, cv2.COLOR_RGB2BGR))
    except ImportError:
        from PIL import Image

        Image.fromarray(strip).save(opts["out"])
    print(
        f"wrote {opts['out']}: rows = real | posterior recon | imagination "
        f"({opts['context']} context + {opts['horizon']} imagined steps)"
    )


if __name__ == "__main__":
    main()
