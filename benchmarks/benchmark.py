"""Wall-clock benchmark harness (reference: ``/root/reference/benchmarks/benchmark.py``).

Runs a ``*_benchmarks`` experiment end-to-end through the real CLI and prints the
elapsed seconds — the number the reference's README SB3-comparison table reports
(BASELINE.md).  Unlike the reference (edit-the-source to switch algorithms), the
experiment is a CLI argument:

    python benchmarks/benchmark.py exp=ppo_benchmarks
    python benchmarks/benchmark.py exp=dreamer_v3_benchmarks mesh.devices=8

Extra ``key=value`` overrides pass straight through to the config system.
"""

from __future__ import annotations

import os
import sys
import time

if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from sheeprl_tpu.cli import run

    args = sys.argv[1:]
    if not any(a.startswith("exp=") for a in args):
        args = ["exp=ppo_benchmarks", *args]
    tic = time.perf_counter()
    run(args)
    print(f"{time.perf_counter() - tic:.2f}")
