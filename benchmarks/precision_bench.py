"""Precision-tier benchmark: bf16 mixed-precision training and int8 serving A/B
against their f32 baselines (howto/precision.md).

Three BENCH-style JSON rows on stdout (``benchmarks/bench_compare.py`` pins the
directions: ``precision_*`` is higher-better by prefix, and the throughput rows
ride the existing ``anakin_``/``serve_`` higher-better prefixes):

* ``anakin_bf16_steps_per_sec`` — env-steps/s of the fused PPO Anakin iteration
  under ``algo.precision=bf16`` (params/optimizer f32, compute bf16), with the
  f32 run of the SAME program and the speedup ratio riding as extras.  The mesh
  is pinned to fp32 so the algo knob is the ONLY difference between the tiers.
* ``serve_int8_replies_per_sec`` — replies/s of the continuously-batched policy
  server under ``serve.precision=int8`` (weight-only per-channel quantization,
  dequant fused into the act dispatch), f32 replies/s and the ratio as extras.
  Same transport, same AOT ladder, same closed-loop clients.
* ``precision_parity_action_agreement`` — the int8 server's parity stamp vs its
  f32 reference reload (greedy action agreement on seeded random observations):
  the acceptance floor is 0.99, and a DROP in this row is the regression.

Serving is benchmarked on a freshly-initialised tiny PPO checkpoint (serving
cost is weight-agnostic); training throughput on the pure-JAX CartPole.

Usage::

    python benchmarks/precision_bench.py
    python benchmarks/precision_bench.py --num-envs 64 --iters 20 --clients 8
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional

if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("SHEEPRL_TPU_QUIET", "1")

import gymnasium as gym  # noqa: E402
import jax  # noqa: E402
import numpy as np  # noqa: E402

from sheeprl_tpu.config.core import compose  # noqa: E402
from sheeprl_tpu.parallel.mesh import MeshContext, build_mesh  # noqa: E402

MODEL_NAME = "precision_bench_ppo"

TINY_PPO = [
    "exp=ppo",
    "env=jax_cartpole",
    "algo.mlp_keys.encoder=[state]",
    "algo.cnn_keys.encoder=[]",
    "algo.dense_units=64",
    "algo.mlp_layers=2",
    "algo.encoder.mlp_features_dim=64",
    "env.num_envs=1",
    "env.capture_video=False",
]


def bench_anakin_precision(precision: str, num_envs: int, rollout_steps: int, iters: int, seed: int = 0) -> float:
    """Env-steps/s of the fused PPO Anakin iteration at ``algo.precision=<tier>``
    (mesh pinned fp32 so the algo knob is the only difference)."""
    from sheeprl_tpu.algos.ppo.agent import build_agent
    from sheeprl_tpu.algos.ppo.ppo import PPOTrainFns
    from sheeprl_tpu.engine.anakin import init_episode_stats, make_ppo_anakin_iteration, reset_envs
    from sheeprl_tpu.envs.jax import make_jax_env

    cfg = compose(
        overrides=[
            "exp=ppo",
            "env=jax_cartpole",
            "algo.anakin=True",
            "algo.mlp_keys.encoder=[state]",
            f"env.num_envs={num_envs}",
            f"algo.rollout_steps={rollout_steps}",
            f"algo.per_rank_batch_size={max(rollout_steps * num_envs // 4, 1)}",
            "algo.update_epochs=4",
            "env.capture_video=False",
            "buffer.memmap=False",
            "mesh.precision=fp32",
            f"algo.precision={precision}",
        ]
    )
    ctx = MeshContext(mesh=build_mesh(devices=jax.devices()[:1]), precision="fp32", seed=seed)
    env = make_jax_env("cartpole")
    env_params = env.default_params()
    obs_space = gym.spaces.Dict({"state": env.observation_space(env_params)})
    agent, params = build_agent(ctx, env.action_space(env_params), obs_space, cfg)
    fns = PPOTrainFns(ctx, agent, cfg, ["state"], max(iters, 1))
    opt_state = ctx.replicate(fns.opt.init(params))
    iteration = make_ppo_anakin_iteration(env, env_params, agent, fns, cfg, "state")
    dispatch = jax.jit(iteration, donate_argnums=(0,))

    env_state, obs0 = reset_envs(env, env_params, num_envs, jax.random.PRNGKey(seed))
    carry = {
        "params": params,
        "opt_state": opt_state,
        "env_state": env_state,
        "obs": obs0,
        "key": jax.random.PRNGKey(seed + 1),
        "episode_stats": init_episode_stats(num_envs),
    }
    carry, metrics = dispatch(carry, 0.2, 0.0)  # warmup/compile
    jax.device_get(metrics)
    t0 = time.perf_counter()
    for _ in range(iters):
        carry, metrics = dispatch(carry, 0.2, 0.0)
    jax.device_get(metrics)
    elapsed = time.perf_counter() - t0
    return iters * rollout_steps * num_envs / elapsed


def build_artifact(tmp: Path):
    """Checkpoint + register an untrained tiny PPO policy; returns
    ``(registry_dir, obs_template)``."""
    from sheeprl_tpu.checkpoint.manager import CheckpointManager
    from sheeprl_tpu.config.core import save_config
    from sheeprl_tpu.utils.env import make_env
    from sheeprl_tpu.utils.model_manager import LocalModelManager
    from sheeprl_tpu.utils.policy import build_policy

    cfg = compose(config_name="config", overrides=TINY_PPO)
    env = make_env(cfg, 0, 0, None, "precision_bench")()
    ctx = MeshContext(mesh=build_mesh(devices=jax.devices()[:1]), precision="fp32", seed=0)
    policy, params = build_policy(ctx, cfg, env.observation_space, env.action_space)
    env.close()

    ckpt_path = CheckpointManager(tmp / "run" / "checkpoints").save(0, {"params": params})
    save_config(cfg, tmp / "run" / "config.yaml")
    registry = tmp / "registry"
    LocalModelManager(registry_dir=str(registry)).register_model(str(ckpt_path), MODEL_NAME)
    return registry, policy.obs_template


def bench_serve_precision(registry: Path, obs_template, precision: str, clients: int, requests: int):
    """In-process server at ``serve.precision=<tier>`` driven by closed-loop
    clients; returns ``(replies_per_sec, parity_stamp_or_None)``."""
    from sheeprl_tpu.serve.client import PolicyClient
    from sheeprl_tpu.serve.server import PolicyServer

    cfg = compose(
        config_name="serve_cli",
        overrides=[
            f"serve.policies=[{MODEL_NAME}:1]",
            f"model_manager.registry_dir={registry}",
            "serve.host=127.0.0.1",
            "serve.port=0",
            f"serve.max_batch_size={max(clients, 1)}",
            "serve.log_every_s=0",
            f"serve.precision={precision}",
        ],
    )
    server = PolicyServer(cfg)
    thread = threading.Thread(target=server.run, daemon=True)
    thread.start()
    deadline = time.monotonic() + 120.0
    while server.listener is None:
        if time.monotonic() > deadline:
            raise TimeoutError("server never started listening")
        time.sleep(0.01)

    obs = {k: np.zeros(shape, dtype=np.dtype(dtype)) for k, (shape, dtype) in obs_template.items()}
    replies = [0] * clients
    errors: List[Exception] = []
    barrier = threading.Barrier(clients + 1)

    def worker(idx: int) -> None:
        try:
            client = PolicyClient("127.0.0.1", server.listener.port)
            barrier.wait()
            for _ in range(requests):
                client.act(obs, MODEL_NAME, timeout=60)
                replies[idx] += 1
            client.close()
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(e)
            try:
                barrier.abort()
            except Exception:
                pass

    try:
        threads = [threading.Thread(target=worker, args=(i,), daemon=True) for i in range(clients)]
        for t in threads:
            t.start()
        barrier.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
    finally:
        server.shutdown()
        thread.join(timeout=60)
    if errors:
        raise RuntimeError(f"{len(errors)} client(s) failed: {errors[0]}")
    stamp = server.parity.get(f"{MODEL_NAME}:1")
    return sum(replies) / wall if wall > 0 else 0.0, stamp


def main(argv: Optional[List[str]] = None) -> Dict[str, float]:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--num-envs", type=int, default=32)
    parser.add_argument("--rollout", type=int, default=64)
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument("--clients", type=int, default=4)
    parser.add_argument("--requests", type=int, default=50, help="round-trips per client")
    args = parser.parse_args(argv)

    f32_sps = bench_anakin_precision("f32", args.num_envs, args.rollout, args.iters)
    bf16_sps = bench_anakin_precision("bf16", args.num_envs, args.rollout, args.iters)

    tmp = Path(tempfile.mkdtemp(prefix="precision_bench_"))
    registry, obs_template = build_artifact(tmp)
    f32_rps, _ = bench_serve_precision(registry, obs_template, "f32", args.clients, args.requests)
    int8_rps, stamp = bench_serve_precision(registry, obs_template, "int8", args.clients, args.requests)

    rows = [
        {
            "metric": "anakin_bf16_steps_per_sec",
            "value": round(bf16_sps, 1),
            "unit": (
                f"env_steps/s, fused PPO Anakin iteration at algo.precision=bf16 "
                f"({args.num_envs} envs x {args.rollout} rollout, mesh pinned fp32, 1 chip)"
            ),
            "f32_steps_per_sec": round(f32_sps, 1),
            "bf16_speedup_vs_f32": round(bf16_sps / f32_sps, 2) if f32_sps > 0 else None,
        },
        {
            "metric": "serve_int8_replies_per_sec",
            "value": round(int8_rps, 2),
            "unit": (
                f"replies/s, continuous batching at serve.precision=int8 "
                f"({args.clients} closed-loop clients x {args.requests} requests)"
            ),
            "f32_replies_per_sec": round(f32_rps, 2),
            "int8_speedup_vs_f32": round(int8_rps / f32_rps, 2) if f32_rps > 0 else None,
        },
        {
            "metric": "precision_parity_action_agreement",
            "value": round(float(stamp["action_agreement"]), 4) if stamp else None,
            "unit": "fraction of greedy actions agreeing, int8 server vs f32 reference (floor 0.99)",
            "n_obs": stamp["n_obs"] if stamp else None,
        },
    ]
    for row in rows:
        print(json.dumps(row))
    return {row["metric"]: row["value"] for row in rows}


if __name__ == "__main__":
    main()
