"""Sebulba topology throughput: multi-process actor/learner vs the thread path.

Emits BENCH-style JSON rows on stdout (``benchmarks/bench_compare.py`` treats
every ``sebulba_*`` metric as higher-better):

* ``sebulba_env_steps_per_sec`` — steady-state acting throughput of the
  2-actor placement (1-actor and the single-process thread-decoupled baseline
  ride as extras, plus the 2-actor/1-actor ``actor_scaling`` ratio);
* ``sebulba_learner_grad_steps_per_sec`` — steady-state gradient-step rate of
  the Sebulba learner while blocks stream in over the transport.

Method — two different clocks, both chosen so startup variance cannot pollute
the rate:

* **Sebulba** runs once per variant and the rate comes from the learner
  summary's ``grad_step_trace`` (``SHEEPRL_TPU_SEBULBA_SUMMARY``): one
  ``[t, cumulative_grad_steps]`` entry per consumed block, each block carrying
  ``env.num_envs`` env steps.  The rate is measured over the SECOND HALF of
  the trace — steady state, after actor connect/compile and the learner's
  train-fn compile, which otherwise dominate short runs and vary by seconds
  between runs.
* The **thread baseline** has no in-loop clock, so it runs twice and uses the
  whole-process wall delta ``(steps_big - steps_small)/(wall_big -
  wall_small)`` — spawn/JAX-init/compile cancel.  Its loop is fast (~1 ms/step
  at these shapes), so the budgets must be large (``--thread-steps-*``,
  default 512/4096) for the loop delta to rise above run-to-run startup noise;
  at Sebulba-sized budgets the delta is ~10 ms of noise on two ~45 s runs and
  the resulting "rate" is garbage.

Usage::

    python benchmarks/sebulba_bench.py
    python benchmarks/sebulba_bench.py --steps 160 \
        --thread-steps-small 512 --thread-steps-big 4096
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

os.environ.setdefault("SHEEPRL_TPU_QUIET", "1")

BASE_OVERRIDES = [
    "exp=sac_decoupled",
    "env=continuous_dummy",
    "algo.mlp_keys.encoder=[state]",
    "algo.hidden_size=8",
    "algo.per_rank_batch_size=8",
    "algo.learning_starts=8",
    "algo.replay_ratio=0.5",
    "algo.run_test=False",
    "buffer.size=4096",
    "dry_run=False",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "checkpoint.every=100000",
    "checkpoint.save_last=False",
    "metric.log_every=100000",
    "metric.disable_timer=True",
    "buffer.memmap=False",
]


def _child_env(summary: Optional[str] = None) -> Dict[str, str]:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.pop("SHEEPRL_TPU_SEBULBA_SUMMARY", None)
    if summary:
        env["SHEEPRL_TPU_SEBULBA_SUMMARY"] = summary
    return env


def _run_thread(total_steps: int, log_root: str) -> float:
    """Thread-decoupled baseline: returns whole-process wall seconds."""
    t0 = time.perf_counter()
    subprocess.run(
        [sys.executable, "-m", "sheeprl_tpu", *BASE_OVERRIDES,
         f"algo.total_steps={total_steps}", f"log_root={log_root}"],
        cwd=REPO,
        env=_child_env(),
        check=True,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.STDOUT,
    )
    return time.perf_counter() - t0


def _run_sebulba(total_steps: int, num_actors: int, log_root: str) -> Dict[str, float]:
    """Sebulba placement: returns the learner summary (wall/env-steps/grad-steps)."""
    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as f:
        summary_path = f.name
    try:
        subprocess.run(
            [sys.executable, "-m", "sheeprl_tpu.sebulba", *BASE_OVERRIDES,
             f"algo.total_steps={total_steps}",
             f"log_root={log_root}",
             f"distributed.num_actors={num_actors}",
             "distributed.connect_timeout_s=60"],
            cwd=REPO,
            env=_child_env(summary_path),
            check=True,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT,
        )
        with open(summary_path) as f:
            return json.load(f)
    finally:
        os.unlink(summary_path)


def _rate(steps_small: float, wall_small: float, steps_big: float, wall_big: float) -> float:
    dt = wall_big - wall_small
    return (steps_big - steps_small) / dt if dt > 0 else 0.0


def _steady_rates(summary: Dict[str, float], envs_per_block: int) -> "tuple[float, float]":
    """(env_steps/s, grad_steps/s) over the second half of the block trace.

    ``grad_step_trace`` holds one ``[t, cumulative_grad_steps]`` entry per
    consumed block; each block carries ``envs_per_block`` env steps.  Measuring
    from the trace midpoint discards actor connect + compile and the learner's
    own train compile — the seconds-scale, run-to-run-variable startup that a
    short run's total wall is dominated by."""
    trace = summary["grad_step_trace"]
    if len(trace) < 4:
        return 0.0, 0.0
    k = len(trace) // 2
    (t0, g0), (t1, g1) = trace[k], trace[-1]
    dt = t1 - t0
    if dt <= 0:
        return 0.0, 0.0
    return (len(trace) - 1 - k) * envs_per_block / dt, (g1 - g0) / dt


def main(argv: Optional[List[str]] = None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--steps", type=int, default=160, help="sebulba variant step budget")
    parser.add_argument("--thread-steps-small", type=int, default=512)
    parser.add_argument("--thread-steps-big", type=int, default=4096)
    args = parser.parse_args(argv)

    tmp = tempfile.mkdtemp(prefix="sebulba_bench_")
    steps, envs_per_block = args.steps, 2  # env.num_envs in BASE_OVERRIDES

    t1, t2 = args.thread_steps_small, args.thread_steps_big
    thread_sps = _rate(t1, _run_thread(t1, f"{tmp}/t1"), t2, _run_thread(t2, f"{tmp}/t2"))

    one = _run_sebulba(steps, 1, f"{tmp}/a1")
    one_sps, _ = _steady_rates(one, envs_per_block)

    two = _run_sebulba(steps, 2, f"{tmp}/a2")
    two_sps, two_gsps = _steady_rates(two, envs_per_block)

    print(json.dumps({
        "metric": "sebulba_learner_grad_steps_per_sec",
        "value": round(two_gsps, 3),
        "unit": f"grad_steps/s (sebulba learner, 2 actor processes, batch 8, {steps} steps, steady-state)",
        "xfer_bytes_received": int(two["bytes_received"]),
        "xfer_bytes_published": int(two["bytes_published"]),
        "publishes": int(two["publishes"]),
    }))
    print(json.dumps({
        "metric": "sebulba_env_steps_per_sec",
        "value": round(two_sps, 3),
        "unit": f"env_steps/s (2 actor processes x 2 envs, dummy env, {steps} steps, steady-state)",
        "one_actor_env_steps_per_sec": round(one_sps, 3),
        "thread_decoupled_env_steps_per_sec": round(thread_sps, 3),
        "actor_scaling_2x_over_1x": round(two_sps / one_sps, 3) if one_sps > 0 else None,
        "speedup_vs_thread_decoupled": round(two_sps / thread_sps, 3) if thread_sps > 0 else None,
    }))

    # Fleet-exporter overhead rides along (BENCH_OBS=0 skips it): the telemetry
    # plane's ≤2% step-time budget, measured against a live loopback aggregator.
    if os.environ.get("BENCH_OBS", "1") != "0":
        from obs_overhead_bench import run_bench as _obs_run_bench

        print(json.dumps(_obs_run_bench()))

    # Race-detector overhead rides along too (BENCH_RACE=0 skips it): the
    # jaxlint-threads runtime half instrumented over a producer/consumer
    # queue workload, detector-on vs detector-off.
    if os.environ.get("BENCH_RACE", "1") != "0":
        from race_detect_bench import run_bench as _race_run_bench

        print(json.dumps(_race_run_bench()))


if __name__ == "__main__":
    main()
