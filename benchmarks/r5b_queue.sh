#!/bin/bash
# Round-5b chip-job queue: reward-learning evidence for the four algorithms that
# still only had dry-run smoke coverage (VERDICT r4 weak #6 tail): A2C,
# PPO-recurrent (velocity-masked, so the recurrence is load-bearing), DroQ
# (utd=20 sample efficiency on its native HalfCheetah), and SAC-AE (pixels).
# Cheapest first so partial progress still yields evidence; stop launching after
# the cutoff so the chip is free for the end-of-round bench.
#
# Usage: bash benchmarks/r5b_queue.sh <cutoff_epoch_seconds>

set -u
cd /root/repo
CUTOFF=${1:?usage: r5b_queue.sh <cutoff_epoch>}
export MUJOCO_GL=egl
mkdir -p logs

run_if_time() { # name estimated_minutes command...
    local name=$1 est=$2; shift 2
    local now=$(date +%s)
    if (( now + est * 60 > CUTOFF )); then
        echo "[$name] SKIPPED: $(date -u) + ${est}m would pass cutoff" | tee -a logs/r5b_queue.log
        return 1
    fi
    echo "[$name] START $(date -u)" | tee -a logs/r5b_queue.log
    "$@" > "logs/${name}_stdout.log" 2>&1
    local rc=$?
    echo "[$name] END rc=$rc $(date -u)" | tee -a logs/r5b_queue.log
    return 0
}

# 1. A2C on CartPole-v1 states. CPU: per-step policy calls for a 64-unit MLP
#    are dominated by the chip-tunnel RTT (~0.2 s/vector-step measured), so the
#    state-based on-policy jobs run on host CPU; the chip jobs below amortize
#    the RTT with large scanned update blocks.
run_if_time a2c_cartpole_r5 40 \
    env JAX_PLATFORMS=cpu python -m sheeprl_tpu exp=a2c env.id=CartPole-v1 \
    "algo.mlp_keys.encoder=[state]" "algo.cnn_keys.encoder=[]" \
    algo.total_steps=262144 env.num_envs=4 env.sync_env=True \
    metric.log_every=4096 checkpoint.every=131072 seed=42 \
    run_name=a2c_cartpole_r5 log_root=/root/repo/logs/a2c_cartpole_r5

# 2. PPO-recurrent on velocity-masked CartPole-v1 (memory task; ~30 min).
run_if_time ppo_rec_mask_r5 60 \
    env JAX_PLATFORMS=cpu python -m sheeprl_tpu exp=ppo_recurrent env.id=CartPole-v1 \
    "algo.mlp_keys.encoder=[state]" "algo.cnn_keys.encoder=[]" \
    env.mask_velocities=True algo.total_steps=262144 env.num_envs=4 env.sync_env=True \
    metric.log_every=4096 checkpoint.every=131072 seed=42 \
    run_name=ppo_rec_mask_r5 log_root=/root/repo/logs/ppo_rec_mask_r5

# 3. DroQ on HalfCheetah-v4 states, utd=20, 50K env steps (the paper's
#    sample-efficiency regime); on the chip: the 80-update scanned block per
#    vector step amortizes the tunnel RTT.
run_if_time droq_cheetah_r5 120 \
    python -m sheeprl_tpu exp=droq algo.total_steps=50000 env.num_envs=4 env.sync_env=True \
    "algo.mlp_keys.encoder=[state]" "algo.cnn_keys.encoder=[]" \
    buffer.size=100000 metric.log_every=2000 checkpoint.every=25000 seed=42 \
    run_name=droq_cheetah_r5 log_root=/root/repo/logs/droq_cheetah_r5

# 4. SAC-AE on cartpole_swingup pixels (paper hyperparams: action_repeat 8;
#    500K env frames = 62.5K policy steps, replay_ratio 1).
run_if_time sac_ae_cartpole_r5 180 \
    python -m sheeprl_tpu exp=sac_ae env.id=cartpole_swingup \
    env.num_envs=4 env.sync_env=True env.action_repeat=8 env.max_episode_steps=-1 \
    algo.total_steps=62500 "algo.cnn_keys.encoder=[rgb]" "algo.mlp_keys.encoder=[]" \
    buffer.size=100000 buffer.checkpoint=True \
    metric.log_every=2000 checkpoint.every=31250 seed=42 \
    run_name=sac_ae_cartpole_r5 log_root=/root/repo/logs/sac_ae_cartpole_r5

echo "[r5b queue] DONE $(date -u)" | tee -a logs/r5b_queue.log
