"""Collect a learning-run's evidence into LEARNING_r{N}.json.

Parses the TensorBoard events of a finished (or running) training run and emits the
round's learning artifact: reward curve, final greedy test reward, steady train
throughput, and the run's provenance.

Usage::

    python benchmarks/collect_learning.py <run_version_dir> <out.json> \
        [--task "dm_control walker_walk, pixels only"] [--notes "..."]
"""

from __future__ import annotations

import argparse
import glob
import json
import os


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("run_dir", help="the run's version_N directory (holds the tfevents file)")
    ap.add_argument("out", help="output JSON path")
    ap.add_argument("--task", default="")
    ap.add_argument("--notes", default="")
    args = ap.parse_args()

    from tensorboard.backend.event_processing.event_accumulator import EventAccumulator

    ea = EventAccumulator(args.run_dir, size_guidance={"scalars": 0})
    ea.Reload()
    tags = ea.Tags()["scalars"]

    def series(tag):
        return [(s.step, round(float(s.value), 2)) for s in ea.Scalars(tag)] if tag in tags else []

    rewards = series("Rewards/rew_avg")
    test_rewards = series("Test/cumulative_reward")
    sps = [v for _, v in series("Time/sps_train")]
    steady_sps = round(sum(sps[2:]) / max(len(sps[2:]), 1), 2) if len(sps) > 4 else (sps[-1] if sps else None)

    cfg_path = os.path.join(os.path.dirname(args.run_dir.rstrip("/")), "..", "config.yaml")
    for cand in (os.path.join(args.run_dir, "config.yaml"), cfg_path):
        if os.path.isfile(cand):
            cfg_path = cand
            break
    cfg = {}
    try:
        import yaml

        with open(cfg_path) as f:
            cfg = yaml.safe_load(f)
    except Exception:
        pass

    out = {
        "task": args.task or f"{cfg.get('env', {}).get('id', '?')} (pixels)",
        "algo": f"{cfg.get('algo', {}).get('name', '?')}, buffer.device={cfg.get('buffer', {}).get('device')}, 1 TPU chip",
        "policy_steps": int(cfg.get("algo", {}).get("total_steps", 0)),
        "env_frames": int(cfg.get("algo", {}).get("total_steps", 0)) * int(cfg.get("env", {}).get("action_repeat", 1)),
        "action_repeat": int(cfg.get("env", {}).get("action_repeat", 1)),
        "train_reward_curve": rewards,
        "final_test_reward": test_rewards[-1][1] if test_rewards else None,
        "steady_sps_train_during_run": steady_sps,
        "notes": args.notes,
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({k: v for k, v in out.items() if k != "train_reward_curve"}, indent=1))
    print(f"curve points: {len(rewards)} → {args.out}")


if __name__ == "__main__":
    main()
