"""CI fleet-smoke client driver (.github/workflows/cpu-tests.yaml "Fleet smoke").

Drives a running serving fleet (front + >= 2 replicas under the fleet
supervisor) through its front, then SIGKILLs one replica *while its requests
are in flight* and keeps driving: the front must reroute the orphaned requests
so every accepted request still gets a reply — the zero-loss contract, chaos
edition.  Asserts:

* every client round-trip succeeds (the :class:`FleetClient` retry layer plus
  the front's rerouting absorb the kill — zero lost replies);
* the front's ``front_status.json`` reports ``rerouted > 0`` (the kill actually
  exercised the reroute path, it didn't land between requests);
* replies carry the fleet stamps (``replica`` + ``front_ms`` on top of the
  replica's own SLO stamps).

The workflow step then SIGTERMs the supervisor and asserts the front summary
(accepted == replied, errors == 0) and that ``obs.top --once`` shows both
replica slots — respawn included.

Usage::

    python benchmarks/fleet_smoke_clients.py <front_ready_file> <fleet_dir>
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from pathlib import Path

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CLIENTS = 4
REQUESTS_PER_CLIENT = 60
REPLIES_BEFORE_KILL = 40


def _wait_for_file(path: Path, timeout_s: float = 300.0) -> dict:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if path.is_file():
            try:
                return json.loads(path.read_text())
            except (json.JSONDecodeError, OSError):
                pass  # mid-replace; retry
        time.sleep(0.2)
    raise TimeoutError(f"no readable JSON at {path} within {timeout_s}s")


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    front_ready, fleet_dir = Path(argv[0]), Path(argv[1])

    import numpy as np

    from sheeprl_tpu.serve.client import FleetClient

    port = _wait_for_file(front_ready)["port"]
    endpoint = ("127.0.0.1", port)

    # Replicas AOT-compile on boot: wait until the front sees >= 2 live
    # non-canary replicas before starting the clock on the chaos scenario.
    probe = FleetClient([endpoint], timeout_s=10.0)
    deadline = time.monotonic() + 300.0
    while True:
        pong = probe.ping()
        live = {
            name: info
            for name, info in (pong.get("fleet", {}).get("replicas") or {}).items()
            if info.get("alive") and not info.get("canary")
        }
        if len(live) >= 2 and pong.get("policies"):
            break
        if time.monotonic() > deadline:
            raise TimeoutError(f"fleet never reached 2 live replicas: {pong}")
        time.sleep(0.25)
    policy = pong["policies"][0]

    obs = {"state": np.zeros(4, dtype=np.float32)}  # jax_cartpole observation
    replies = [0] * CLIENTS
    stamps: list = []
    errors: list = []

    def worker(idx: int) -> None:
        try:
            with FleetClient([endpoint], timeout_s=60.0, session=f"smoke{idx}") as client:
                for _ in range(REQUESTS_PER_CLIENT):
                    _, meta = client.act(obs, policy, timeout=60)
                    replies[idx] += 1
                    stamps.append(meta)
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,), daemon=True) for i in range(CLIENTS)]
    for t in threads:
        t.start()
    while sum(replies) < REPLIES_BEFORE_KILL:
        if errors:
            raise RuntimeError(f"client failed before the kill: {errors[0]}")
        time.sleep(0.01)

    # Pick a victim from the manager's replica records, preferring one the
    # front currently has requests in flight on (so the kill provably orphans
    # work), and SIGKILL it — no drain, no goodbye.
    records_dir = fleet_dir / "replicas"
    records = {}
    for path in sorted(records_dir.glob("*.json")):
        try:
            rec = json.loads(path.read_text())
        except (json.JSONDecodeError, OSError):
            continue
        if not rec.get("canary"):
            records[rec["name"]] = rec
    victim = None
    kill_deadline = time.monotonic() + 10.0
    while victim is None:
        pong = probe.ping()
        fleet = pong.get("fleet", {}).get("replicas") or {}
        busy = [n for n, info in fleet.items() if n in records and info.get("inflight", 0) > 0]
        if busy:
            victim = records[busy[0]]
        elif time.monotonic() > kill_deadline:
            victim = next(iter(records.values()))  # kill *someone* mid-drive
        else:
            time.sleep(0.005)
    os.kill(int(victim["pid"]), signal.SIGKILL)
    print(f"fleet smoke: SIGKILLed replica {victim['name']} (pid {victim['pid']}) mid-flight")

    for t in threads:
        t.join(timeout=180)
    if errors:
        raise RuntimeError(f"client failed: {errors[0]}")
    assert sum(replies) == CLIENTS * REQUESTS_PER_CLIENT, replies
    probe.close()

    for meta in stamps:
        assert meta.get("replica"), meta  # the front stamps which replica served it
        assert meta["front_ms"] >= 0, meta
    served_by = sorted({meta["replica"] for meta in stamps})

    # The reroute must have actually happened: the front's status file keeps
    # the counter (status ticks every serve.fleet.status_interval_s).
    status = None
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline:
        try:
            status = json.loads((fleet_dir / "front_status.json").read_text())
        except (OSError, json.JSONDecodeError):
            status = None
        if status and status.get("rerouted", 0) > 0:
            break
        time.sleep(0.25)
    assert status is not None, "front never wrote front_status.json"
    assert status.get("rerouted", 0) > 0, f"kill did not exercise rerouting: {status}"

    print(
        f"fleet smoke: {sum(replies)} replies across {CLIENTS} clients, "
        f"served by {served_by}, rerouted={status['rerouted']}, zero lost"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
