"""Replay data-path benchmark for the SAC family: grad-steps/s of the three
replay feeds at SAC and DroQ (UTD-20) shapes.

* ``host_per_step``  — the naive off-policy loop: every gradient step pays a host
  replay sample, its own host→device transfer, and its own jit dispatch (the
  per-step overhead the ISSUE-5 fused blocks exist to remove);
* ``host_block``     — the repo's pre-ring default: one ``[G, B]`` block sampled
  and shipped per iteration, consumed by a scanned jit (1 host sample + 1
  transfer + 1 dispatch per block; DroQ adds the separate actor dispatch);
* ``device_ring``    — ``buffer.device=True``: HBM transition ring + fused
  scanned block (``data/device_buffer.py`` + ``FusedRingDispatcher``) — in-jit
  uniform index sampling from the carried key, zero per-step host work, ONE
  donated dispatch per block (DroQ's critic scan + actor tail included).

Emits one BENCH-style JSON row per (algo, path) on stdout plus speedup rows
(feeds ``benchmarks/bench_compare.py``):

    python benchmarks/replay_bench.py
    python benchmarks/replay_bench.py --batch 256 --hidden 256 --blocks 20
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional

import numpy as np

if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("SHEEPRL_TPU_QUIET", "1")

import gymnasium as gym  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from sheeprl_tpu.config.core import compose  # noqa: E402
from sheeprl_tpu.data.buffers import ReplayBuffer  # noqa: E402
from sheeprl_tpu.data.device_buffer import DeviceTransitionRing  # noqa: E402
from sheeprl_tpu.parallel.mesh import MeshContext, build_mesh  # noqa: E402
from sheeprl_tpu.utils.blocks import FusedRingDispatcher  # noqa: E402


def _copy(tree):
    return jax.tree.map(jnp.copy, tree)


def _fill_buffer(args, n_envs=4, rows=512, seed=0):
    rng = np.random.default_rng(seed)
    rb = ReplayBuffer(rows, n_envs, obs_keys=("obs",))
    rb.seed(seed)
    ring = DeviceTransitionRing(
        rows,
        n_envs,
        {
            "obs": ((args.obs_dim,), jnp.float32),
            "next_obs": ((args.obs_dim,), jnp.float32),
            "actions": ((args.act_dim,), jnp.float32),
            "rewards": ((1,), jnp.float32),
            "dones": ((1,), jnp.float32),
        },
    )
    for t in range(rows):
        row = {
            "obs": rng.random((1, n_envs, args.obs_dim)).astype(np.float32),
            "next_obs": rng.random((1, n_envs, args.obs_dim)).astype(np.float32),
            "actions": rng.random((1, n_envs, args.act_dim)).astype(np.float32),
            "rewards": rng.random((1, n_envs, 1)).astype(np.float32),
            "dones": np.zeros((1, n_envs, 1), np.float32),
        }
        ring.add_step(row, rb._pos, rb.rows_added)
        rb.add(row)
    return rb, ring


def _host_batch(rb, batch: int, n: int) -> Dict[str, jax.Array]:
    sample = rb.sample(batch * n)
    return {
        "obs": jnp.asarray(sample["obs"].reshape(n, batch, -1)),
        "next_obs": jnp.asarray(sample["next_obs"].reshape(n, batch, -1)),
        "actions": jnp.asarray(sample["actions"].reshape(n, batch, -1)),
        "rewards": jnp.asarray(sample["rewards"].reshape(n, batch, 1)),
        "dones": jnp.asarray(sample["dones"].reshape(n, batch, 1)),
    }


def _time_blocks(run_block, carry, blocks: int, warmup: int = 2):
    for i in range(warmup):
        carry = run_block(carry, i)
    jax.block_until_ready(carry)
    t0 = time.perf_counter()
    for i in range(warmup, warmup + blocks):
        carry = run_block(carry, i)
    jax.block_until_ready(carry)
    return time.perf_counter() - t0


def bench_sac_family(algo: str, args) -> Dict[str, float]:
    """grad-steps/s for the three data paths; ``algo`` is "sac" (G=1 per block)
    or "droq" (G=utd critic steps + the actor update per block)."""
    from sheeprl_tpu.algos.sac.agent import SACActor, build_agent

    utd = args.utd if algo == "droq" else 1
    cfg = compose(
        overrides=[
            f"exp={algo}",
            "env=continuous_dummy",
            "algo.mlp_keys.encoder=[state]",
            f"algo.hidden_size={args.hidden}",
            f"algo.per_rank_batch_size={args.batch}",
        ]
    )
    ctx = MeshContext(mesh=build_mesh(devices=jax.devices()[:1]), precision="fp32", seed=0)
    obs_space = gym.spaces.Dict({"state": gym.spaces.Box(-1.0, 1.0, (args.obs_dim,), np.float32)})
    act_space = gym.spaces.Box(-1.0, 1.0, (args.act_dim,), np.float32)
    rb, ring = _fill_buffer(args)

    if algo == "sac":
        from sheeprl_tpu.algos.sac.sac import make_sac_fused_builder, make_sac_train_fn

        actor, critic, params = build_agent(ctx, act_space, obs_space, cfg)
        actor_opt, critic_opt, alpha_opt, train_fn = make_sac_train_fn(actor, critic, cfg, act_space)
        _, _, _, builder = make_sac_fused_builder(actor, critic, cfg, act_space, ring, args.batch)
        opt_state = {
            "actor": actor_opt.init(params["actor"]),
            "critic": critic_opt.init(params["critic"]),
            "alpha": alpha_opt.init(params["log_alpha"]),
        }

        def host_block(carry, i, n):
            batches = _host_batch(rb, args.batch, n)
            p, o, _ = train_fn(carry[0], carry[1], batches, jax.random.PRNGKey(i), jnp.asarray(i * n))
            return (p, o)

    else:
        from sheeprl_tpu.algos.droq.droq import (
            DroQCriticEnsemble,
            make_droq_fused_builder,
            make_droq_train_fns,
        )

        actor = SACActor(act_dim=args.act_dim, hidden_size=args.hidden, dtype=ctx.compute_dtype)
        critic = DroQCriticEnsemble(
            n_critics=cfg.algo.critic.n, hidden_size=args.hidden, dropout=cfg.algo.critic.dropout,
            dtype=ctx.compute_dtype,
        )
        d_o, d_a = jnp.zeros((1, args.obs_dim)), jnp.zeros((1, args.act_dim))
        params = {
            "actor": actor.init(ctx.rng(), d_o),
            "critic": critic.init({"params": ctx.rng(), "dropout": ctx.rng()}, d_o, d_a),
            "log_alpha": jnp.asarray(0.0, jnp.float32),
        }
        params["critic_target"] = jax.tree.map(jnp.copy, params["critic"])
        actor_opt, critic_opt, alpha_opt, train_critics_fn, train_actor_fn = make_droq_train_fns(
            actor, critic, cfg, act_space
        )
        _, _, _, builder = make_droq_fused_builder(actor, critic, cfg, act_space, ring, args.batch)
        opt_state = {
            "actor": actor_opt.init(params["actor"]),
            "critic": critic_opt.init(params["critic"]),
            "alpha": alpha_opt.init(params["log_alpha"]),
        }

        def host_block(carry, i, n):
            batches = _host_batch(rb, args.batch, n)
            actor_batch = {"obs": jnp.asarray(rb.sample(args.batch)["obs"].reshape(args.batch, -1))}
            p, o, _ = train_critics_fn(
                carry[0], carry[1], batches, jax.random.PRNGKey(i), jnp.asarray(i * n)
            )
            p, o, _ = train_actor_fn(p, o, actor_batch, jax.random.PRNGKey(10_000 + i))
            return (p, o)

    carry0 = (params, opt_state)
    rates: Dict[str, float] = {}

    # host sampling + transfer + dispatch PER GRADIENT STEP
    def per_step(carry, i):
        for g in range(utd):
            carry = host_block(carry, i * utd + g, 1)
        return carry

    elapsed = _time_blocks(per_step, _copy(carry0), args.blocks)
    rates["host_per_step"] = args.blocks * utd / elapsed

    # one [G, B] host block per iteration (the pre-ring default)
    elapsed = _time_blocks(lambda c, i: host_block(c, i, utd), _copy(carry0), args.blocks)
    rates["host_block"] = args.blocks * utd / elapsed

    # device ring + fused scanned block (ONE donated dispatch per iteration)
    fused = FusedRingDispatcher(
        builder, base_key=jax.random.PRNGKey(0), last_sensitive=algo == "droq"
    )
    filled, rows_added = len(rb), rb.rows_added

    def ring_block(carry, i):
        return fused.dispatch(carry, ring.arrays, filled, rows_added, utd, i * utd)

    elapsed = _time_blocks(ring_block, {"params": _copy(params), "opt_state": _copy(opt_state)},
                           args.blocks)
    rates["device_ring"] = args.blocks * utd / elapsed
    return rates


def main(argv: Optional[List[str]] = None) -> Dict[str, Dict[str, float]]:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--batch", type=int, default=128)
    parser.add_argument("--hidden", type=int, default=256)
    parser.add_argument("--obs-dim", type=int, default=17)
    parser.add_argument("--act-dim", type=int, default=6)
    parser.add_argument("--utd", type=int, default=20, help="DroQ gradient steps per env step")
    parser.add_argument("--blocks", type=int, default=10, help="measured iterations per path")
    parser.add_argument("--algos", type=str, default="sac,droq")
    parser.add_argument("--json-out", type=str, default=None)
    args = parser.parse_args(argv)

    all_rates: Dict[str, Dict[str, float]] = {}
    rows = []
    for algo in [a.strip() for a in args.algos.split(",") if a.strip()]:
        shape = (
            f"batch {args.batch} x obs {args.obs_dim} x hidden {args.hidden}"
            + (f", UTD {args.utd}" if algo == "droq" else "")
        )
        rates = bench_sac_family(algo, args)
        all_rates[algo] = rates
        for path, rate in rates.items():
            rows.append(
                {
                    "metric": f"{algo}_replay_{path}_grad_steps_per_sec",
                    "value": round(rate, 2),
                    "unit": f"grad_steps/s ({shape})",
                }
            )
        if rates.get("host_per_step", 0) > 0:
            rows.append(
                {
                    "metric": f"{algo}_replay_device_ring_speedup_vs_per_step",
                    "value": round(rates["device_ring"] / rates["host_per_step"], 3),
                    "unit": f"x ({shape})",
                }
            )
    for row in rows:
        print(json.dumps(row))
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(rows, f, indent=1)
    return all_rates


if __name__ == "__main__":
    main()
