"""Perf-attribution plane overhead bench (PR-19 acceptance: <=2%).

A/B of the same jitted workload with ``obs.perf`` instrumentation + goodput
ledger ON vs OFF.  The workload is sized to ~1 ms/step (a 512x512 matmul chain)
— far smaller than any real training dispatch, so the measured overhead is an
upper bound on what a real run pays per update:

* ``perf_overhead_pct`` — steady-state per-step overhead of the ``instrument``
  wrapper (call counting) plus one ``PerfPlane.flush`` per log window, as a
  percentage of the uninstrumented step time.  Lower is better; the acceptance
  bar is 2%.
* ``perf_mfu`` / ``goodput_fraction`` — the plane's own figures on the bench
  workload, direction-pinned higher-better in ``bench_compare.py`` so a
  regression in attribution coverage (e.g. cost models silently missing)
  shows up as a drop.

Runs standalone (``python benchmarks/perf_overhead_bench.py``) or via
``bench.py`` (``BENCH_PERF=0`` skips).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import List, Optional

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

STEPS = int(os.environ.get("BENCH_PERF_STEPS", "300"))
FLUSH_EVERY = 50  # PerfPlane.flush cadence, matching a metric.log_every window


def _make_step():
    import jax
    import jax.numpy as jnp

    def step(x):
        for _ in range(8):
            x = jnp.tanh(x @ x)
        return x

    return jax.jit(step), jnp.ones((512, 512), jnp.float32)


def _run(instrumented: bool) -> dict:
    import jax

    from sheeprl_tpu.config.core import DotDict
    from sheeprl_tpu.obs import perf as obs_perf

    obs_perf.reset()
    cfg = DotDict({"obs": {"perf": {"enabled": instrumented}}})
    step, x = _make_step()
    if instrumented:
        step = obs_perf.instrument(cfg, "bench/perf_overhead", step)
    plane = obs_perf.PerfPlane(cfg) if instrumented else None

    # Warmup: compile + (instrumented) one-time cost-model registration.
    out = x
    for _ in range(5):
        out = step(out)
    jax.block_until_ready(out)

    t0 = time.perf_counter()
    t_window = t0
    out = x
    for i in range(STEPS):
        out = step(out)
        if plane is not None:
            plane.observe_step()
            if (i + 1) % FLUSH_EVERY == 0:
                jax.block_until_ready(out)
                now = time.perf_counter()
                plane.flush({"Time/train_time": now - t_window})
                t_window = now
    jax.block_until_ready(out)
    elapsed = time.perf_counter() - t0

    row = {"seconds_per_step": elapsed / STEPS}
    if plane is not None:
        report = plane.report()
        row["mfu"] = float(report["mfu"])
        row["goodput"] = float(report["goodput"])
    return row


def main(argv: Optional[List[str]] = None) -> None:
    del argv
    off = _run(instrumented=False)
    on = _run(instrumented=True)
    overhead_pct = (on["seconds_per_step"] / off["seconds_per_step"] - 1.0) * 100.0
    print(
        json.dumps(
            {
                "metric": "perf_overhead_pct",
                "value": round(overhead_pct, 3),
                "unit": (
                    f"% step-time overhead of obs.perf instrument+ledger "
                    f"(~{off['seconds_per_step'] * 1e3:.2f} ms/step workload, {STEPS} steps); "
                    "lower is better, budget 2%"
                ),
                "budget_pct": 2.0,
                "within_budget": bool(overhead_pct <= 2.0),
                "off_ms_per_step": round(off["seconds_per_step"] * 1e3, 4),
                "on_ms_per_step": round(on["seconds_per_step"] * 1e3, 4),
            }
        )
    )
    print(
        json.dumps(
            {
                "metric": "perf_mfu",
                "value": round(on.get("mfu", 0.0), 5),
                "unit": "model FLOPs utilization of the bench workload (perf plane's own gauge)",
            }
        )
    )
    print(
        json.dumps(
            {
                "metric": "goodput_fraction",
                "value": round(on.get("goodput", 0.0), 5),
                "unit": "compute+env fraction of wall clock (perf plane's goodput ledger)",
            }
        )
    )


if __name__ == "__main__":
    main()
