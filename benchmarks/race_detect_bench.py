#!/usr/bin/env python
"""Race-detector overhead: what does lock instrumentation cost a threaded loop?

The jaxlint-threads runtime detector (``sheeprl_tpu/analysis/threads/runtime.py``)
promises observation-only semantics at one bookkeeping dict hit per nested
acquisition.  This bench A/Bs a sebulba-shaped producer/consumer workload —
N producer threads feeding a bounded ``queue.Queue`` with a lock-guarded stats
counter, exactly the publish/consume bookkeeping shape — with the real
``threading`` factories vs the detector globally installed (so the queue's
*internal* condition locks are instrumented too, which is what a real
``SHEEPRL_TPU_RACE_DETECT=1`` run pays):

    overhead_pct = (wall_instrumented - wall_bare) / wall_bare * 100

Emits one BENCH-style JSON row, ``race_detect_overhead_pct`` — direction-pinned
lower-better by exact name in ``benchmarks/bench_compare.py``.  Runs as part of
``benchmarks/sebulba_bench.py`` unless ``BENCH_RACE=0``.

Usage::

    python benchmarks/race_detect_bench.py [--items 20000] [--threads 4] [--repeats 3]
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import sys
import threading
import time
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _spin(work_s: float) -> None:
    """Busy-spin stand-in for per-item work (env stepping / block processing) —
    without it the workload is ~100% lock operations and the row measures raw
    wrapper cost instead of what a real run pays."""
    deadline = time.perf_counter() + work_s
    while time.perf_counter() < deadline:
        pass


def _workload(items_per_thread: int, n_threads: int, work_s: float) -> None:
    """Producer/consumer round trip: the locks and queue are constructed INSIDE
    the measured region so the currently-installed factories apply."""
    q: "queue.Queue[int]" = queue.Queue(maxsize=64)
    lock = threading.Lock()
    stats = {"produced": 0, "consumed": 0}

    def producer() -> None:
        for i in range(items_per_thread):
            _spin(work_s)
            q.put(i)
            with lock:
                stats["produced"] += 1

    def consumer() -> None:
        for _ in range(items_per_thread * n_threads):
            q.get()
            _spin(work_s)
            with lock:
                stats["consumed"] += 1

    threads = [threading.Thread(target=producer) for _ in range(n_threads)]
    threads.append(threading.Thread(target=consumer))
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert stats["produced"] == stats["consumed"] == items_per_thread * n_threads


def _measure(items_per_thread: int, n_threads: int, work_s: float) -> float:
    t0 = time.perf_counter()
    _workload(items_per_thread, n_threads, work_s)
    return time.perf_counter() - t0


def run_bench(items: int = 20000, n_threads: int = 4, repeats: int = 3, work_us: float = 50.0) -> dict:
    from sheeprl_tpu.analysis.threads import runtime as race_runtime

    items_per_thread = max(items // n_threads, 1)
    work_s = work_us / 1e6
    detector = race_runtime.RaceDetector(held_threshold_ms=0.0)  # no long-hold noise
    bare: List[float] = []
    inst: List[float] = []
    _measure(items_per_thread // 4 or 1, n_threads, work_s)  # warmup: threads + allocator
    try:
        for _ in range(repeats):  # interleave so drift hits both arms equally
            bare.append(_measure(items_per_thread, n_threads, work_s))
            race_runtime.install(detector)
            try:
                inst.append(_measure(items_per_thread, n_threads, work_s))
            finally:
                race_runtime.uninstall()
    finally:
        race_runtime.uninstall()
    overhead = (min(inst) - min(bare)) / min(bare) * 100.0
    counts = detector.counts()
    return {
        "metric": "race_detect_overhead_pct",
        "value": round(max(overhead, 0.0), 3),
        "unit": (
            f"% wall-time overhead (lower is better; {n_threads} producers + 1 consumer, "
            f"{items_per_thread * n_threads} queue round trips at ~{work_us:.0f}us work/item, "
            f"best-of-{repeats}, detector globally installed vs real threading factories)"
        ),
        "acquisitions": counts["acquisitions"],
        "edges": counts["edges"],
        "cycles": counts["cycles"],
    }


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--items", type=int, default=int(os.environ.get("BENCH_RACE_ITEMS", "20000")))
    parser.add_argument("--threads", type=int, default=4)
    parser.add_argument("--repeats", type=int, default=3)
    parser.add_argument(
        "--work-us", type=float, default=float(os.environ.get("BENCH_RACE_WORK_US", "50"))
    )
    args = parser.parse_args(argv)
    print(
        json.dumps(
            run_bench(
                items=args.items, n_threads=args.threads, repeats=args.repeats, work_us=args.work_us
            )
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
