"""Serving-fleet benchmark: front + N replicas, replica-count scaling, fleet p99.

Emits BENCH-style JSON rows on stdout (``benchmarks/bench_compare.py`` pins the
directions: ``fleet_*`` is higher-better by prefix, ``fleet_p99_ms`` pinned
lower-better by exact name):

* ``fleet_replies_per_sec`` — replies/s through the fleet front at the highest
  replica count, with the per-replica-count sweep (``rps_1_replica``,
  ``rps_2_replicas``, ...) and the scaling ratio max-vs-1 riding as extras.
  Every request crosses the front: the sweep isolates what adding replicas buys
  *after* paying the routing hop, which is the number capacity planning needs.
* ``fleet_p99_ms`` — end-to-end p99 (front accept → reply send) from the
  front's exit summary at the highest replica count, front p50 and the share of
  rerouted requests as extras.

All replicas share one persistent compile cache, so replica 2..N start warm —
the same mechanism the autoscaler leans on for fast scale-up.  The served
artifact is the untrained tiny PPO from ``serve_bench`` (serving cost does not
depend on how good the weights are).

Usage::

    python benchmarks/fleet_bench.py
    python benchmarks/fleet_bench.py --clients 16 --requests 50 --max-replicas 2
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("SHEEPRL_TPU_QUIET", "1")

from serve_bench import MODEL_NAME, Replica, build_artifact  # noqa: E402


def _child_env() -> Dict[str, str]:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    for var in ("SHEEPRL_TPU_SERVE_SUMMARY", "SHEEPRL_TPU_FLEET_SUMMARY", "SHEEPRL_TPU_FLEET"):
        env.pop(var, None)
    return env


class Front:
    """One fleet-front subprocess over a static replica list."""

    def __init__(self, workdir: Path, endpoints: List[str]):
        self.ready_file = workdir / "front_ready.json"
        self.summary_file = workdir / "front_summary.json"
        workdir.mkdir(parents=True, exist_ok=True)
        args = [
            sys.executable, "-m", "sheeprl_tpu.serve.fleet",
            "serve.fleet.enabled=True",
            f"serve.fleet.replicas=[{','.join(endpoints)}]",
            f"serve.fleet.dir={workdir}",
            "serve.fleet.host=127.0.0.1",
            "serve.fleet.port=0",
            f"serve.fleet.ready_file={self.ready_file}",
            f"serve.fleet.summary_path={self.summary_file}",
        ]
        self.proc = subprocess.Popen(
            args, cwd=REPO, env=_child_env(),
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        )

    def wait_ready(self, timeout_s: float = 60.0) -> Dict:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.ready_file.is_file():
                try:
                    return json.loads(self.ready_file.read_text())
                except json.JSONDecodeError:  # mid-replace; retry
                    time.sleep(0.05)
                    continue
            if self.proc.poll() is not None:
                raise RuntimeError(f"front died during startup (rc={self.proc.returncode})")
            time.sleep(0.05)
        raise TimeoutError(f"front not ready within {timeout_s}s")

    def stop(self) -> Dict:
        """SIGTERM → drain → exit 75; returns the front's exit summary."""
        self.proc.send_signal(signal.SIGTERM)
        rc = self.proc.wait(timeout=120)
        if rc != 75:
            raise RuntimeError(f"expected front drain exit code 75, got {rc}")
        return json.loads(self.summary_file.read_text())


def drive_fleet_clients(
    port: int, obs_template: Dict[str, tuple], clients: int, requests: int
) -> Tuple[float, int]:
    """``clients`` closed-loop FleetClients x ``requests`` round-trips each."""
    import numpy as np

    from sheeprl_tpu.serve.client import FleetClient

    obs = {
        k: np.zeros(shape, dtype=np.dtype(dtype)) for k, (shape, dtype) in obs_template.items()
    }
    replies = [0] * clients
    errors: List[Exception] = []
    barrier = threading.Barrier(clients + 1)

    def worker(idx: int) -> None:
        try:
            with FleetClient([("127.0.0.1", port)]) as client:
                client.ping()  # connect before the clock starts
                barrier.wait()
                for _ in range(requests):
                    client.act(obs, MODEL_NAME, timeout=60)
                    replies[idx] += 1
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(e)
            try:
                barrier.abort()
            except Exception:
                pass

    threads = [threading.Thread(target=worker, args=(i,), daemon=True) for i in range(clients)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        raise RuntimeError(f"{len(errors)} client(s) failed: {errors[0]}")
    return wall, sum(replies)


def run_fleet(
    tmp: Path,
    registry: Path,
    cache_dir: Path,
    obs_template: Dict[str, tuple],
    n_replicas: int,
    clients: int,
    requests: int,
    max_batch: int,
) -> Tuple[float, Dict]:
    """Spawn ``n_replicas`` + one front, drive the clients through the front,
    tear everything down; returns ``(replies_per_sec, front_summary)``."""
    workdir = tmp / f"fleet_{n_replicas}r"
    replicas = [
        Replica(registry, workdir / f"replica{i}", max_batch, cache_dir)
        for i in range(n_replicas)
    ]
    front = None
    try:
        endpoints = [f"127.0.0.1:{r.wait_ready()['port']}" for r in replicas]
        front = Front(workdir / "front", endpoints)
        ready = front.wait_ready()
        wall, total = drive_fleet_clients(ready["port"], obs_template, clients, requests)
        summary = front.stop()
        front = None
        if summary["replied"] != total or summary["errors"]:
            raise RuntimeError(f"front lost replies: drove {total}, summary {summary}")
        return (total / wall if wall > 0 else 0.0), summary
    finally:
        if front is not None:
            front.proc.kill()
        for r in replicas:
            if r.proc.poll() is None:
                try:
                    r.stop()
                except Exception:
                    r.proc.kill()


def main(argv: Optional[List[str]] = None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--clients", type=int, default=16)
    parser.add_argument("--requests", type=int, default=50, help="round-trips per client")
    parser.add_argument("--max-replicas", type=int, default=2, help="sweep 1..N replicas")
    parser.add_argument("--max-batch", type=int, default=16)
    args = parser.parse_args(argv)

    tmp = Path(tempfile.mkdtemp(prefix="fleet_bench_"))
    registry, obs_template = build_artifact(tmp)
    cache_dir = tmp / "xla_cache"

    sweep: Dict[int, float] = {}
    summary: Dict = {}
    for n in range(1, args.max_replicas + 1):
        sweep[n], summary = run_fleet(
            tmp, registry, cache_dir, obs_template, n,
            args.clients, args.requests, args.max_batch,
        )

    top_n = max(sweep)
    extras = {
        f"rps_{n}_replica{'s' if n > 1 else ''}": round(rps, 2) for n, rps in sweep.items()
    }
    print(json.dumps({
        "metric": "fleet_replies_per_sec",
        "value": round(sweep[top_n], 2),
        "unit": (
            f"replies/s through the fleet front, {top_n} replicas, "
            f"{args.clients} closed-loop clients x {args.requests} requests"
        ),
        **extras,
        "scaling_vs_1_replica": round(sweep[top_n] / sweep[1], 2) if sweep.get(1) else None,
    }))
    p99 = summary.get("p99_ms")
    p50 = summary.get("p50_ms")
    print(json.dumps({
        "metric": "fleet_p99_ms",
        "value": round(p99, 3) if isinstance(p99, (int, float)) else None,
        "unit": f"ms front accept->reply p99, {top_n} replicas, {args.clients} clients",
        "p50_ms": round(p50, 3) if isinstance(p50, (int, float)) else None,
        "rerouted": summary.get("rerouted", 0),
        "replied": summary.get("replied", 0),
    }))


if __name__ == "__main__":
    main()
