"""Serve-tier benchmark: continuous batching vs naive dispatch + cold/warm start.

Emits BENCH-style JSON rows on stdout (``benchmarks/bench_compare.py`` pins the
directions: ``serve_*`` is higher-better by prefix, with ``serve_p99_ms`` and
``serve_startup_seconds`` pinned lower-better by exact name):

* ``serve_throughput_rps`` — replies/s of the continuously-batched server at
  ``--clients`` closed-loop clients, with the NAIVE one-request-per-dispatch
  baseline (``serve.max_batch_size=1``: the ladder collapses to ``[1]``, so
  every request is its own dispatch) and the speedup ratio riding as extras.
  Same transport, same AOT precompile, same clients — the ONLY difference is
  the batching policy, so the ratio isolates what continuous batching buys.
* ``serve_p99_ms`` — the batched server's end-to-end p99 (enqueue→reply send)
  from its exit summary, naive p99 as an extra.
* ``serve_startup_seconds`` — spawn→ready wall of a WARM replica start (value)
  vs the COLD start that populated the persistent compile cache (extra): the
  AOT ladder deserializes from disk instead of recompiling.

The served artifact is built without training: a freshly-initialised tiny PPO
agent on ``jax_cartpole`` is checkpointed and registered — serving cost does not
depend on how good the weights are.

Usage::

    python benchmarks/serve_bench.py
    python benchmarks/serve_bench.py --clients 32 --requests 100 --max-batch 32
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("SHEEPRL_TPU_QUIET", "1")

MODEL_NAME = "serve_bench_ppo"

TINY_PPO = [
    "exp=ppo",
    "env=jax_cartpole",
    "algo.mlp_keys.encoder=[state]",
    "algo.cnn_keys.encoder=[]",
    "algo.dense_units=16",
    "algo.mlp_layers=1",
    "algo.encoder.mlp_features_dim=16",
    "env.num_envs=1",
    "env.capture_video=False",
]


def build_artifact(tmp: Path) -> Tuple[Path, Dict[str, tuple]]:
    """Checkpoint + register an untrained tiny PPO policy; returns
    ``(registry_dir, obs_template)``."""
    import jax

    from sheeprl_tpu.config.core import compose, save_config
    from sheeprl_tpu.checkpoint.manager import CheckpointManager
    from sheeprl_tpu.parallel.mesh import MeshContext, build_mesh
    from sheeprl_tpu.utils.env import make_env
    from sheeprl_tpu.utils.model_manager import LocalModelManager
    from sheeprl_tpu.utils.policy import build_policy

    cfg = compose(config_name="config", overrides=TINY_PPO)
    env = make_env(cfg, 0, 0, None, "serve_bench")()
    ctx = MeshContext(mesh=build_mesh(devices=jax.devices()[:1]), precision="fp32", seed=0)
    policy, params = build_policy(ctx, cfg, env.observation_space, env.action_space)
    env.close()

    ckpt_path = CheckpointManager(tmp / "run" / "checkpoints").save(0, {"params": params})
    save_config(cfg, tmp / "run" / "config.yaml")
    registry = tmp / "registry"
    LocalModelManager(registry_dir=str(registry)).register_model(str(ckpt_path), MODEL_NAME)
    return registry, policy.obs_template


def _child_env() -> Dict[str, str]:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.pop("SHEEPRL_TPU_SERVE_SUMMARY", None)
    return env


class Replica:
    """One server subprocess: spawn, wait-ready, SIGTERM-drain, summary."""

    def __init__(self, registry: Path, workdir: Path, max_batch: int, cache_dir: Path):
        self.ready_file = workdir / "ready.json"
        self.summary_file = workdir / "summary.json"
        workdir.mkdir(parents=True, exist_ok=True)
        args = [
            sys.executable, "-m", "sheeprl_tpu.serve",
            f"serve.policies=[{MODEL_NAME}:latest]",
            f"model_manager.registry_dir={registry}",
            "serve.host=127.0.0.1",
            "serve.port=0",
            f"serve.max_batch_size={max_batch}",
            f"serve.ready_file={self.ready_file}",
            f"serve.summary_path={self.summary_file}",
            "serve.log_every_s=0",
            "compile_cache.enabled=True",
            f"compile_cache.dir={cache_dir}",
        ]
        self.t_spawn = time.perf_counter()
        self.proc = subprocess.Popen(
            args, cwd=REPO, env=_child_env(),
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        )
        self.startup_seconds: Optional[float] = None
        self.ready: Optional[Dict] = None

    def wait_ready(self, timeout_s: float = 300.0) -> Dict:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.ready_file.is_file():
                try:
                    self.ready = json.loads(self.ready_file.read_text())
                except json.JSONDecodeError:  # mid-replace; retry
                    time.sleep(0.05)
                    continue
                self.startup_seconds = time.perf_counter() - self.t_spawn
                return self.ready
            if self.proc.poll() is not None:
                raise RuntimeError(f"server died during startup (rc={self.proc.returncode})")
            time.sleep(0.05)
        raise TimeoutError(f"server not ready within {timeout_s}s")

    def stop(self) -> Dict:
        """SIGTERM → drain → exit 75; returns the exit summary."""
        self.proc.send_signal(signal.SIGTERM)
        rc = self.proc.wait(timeout=120)
        if rc != 75:
            raise RuntimeError(f"expected drain exit code 75, got {rc}")
        return json.loads(self.summary_file.read_text())


def drive_clients(
    port: int, obs_template: Dict[str, tuple], clients: int, requests: int
) -> Tuple[float, int]:
    """``clients`` closed-loop threads x ``requests`` round-trips each; returns
    ``(wall_seconds, total_replies)``."""
    import numpy as np

    from sheeprl_tpu.serve.client import PolicyClient

    obs = {
        k: np.zeros(shape, dtype=np.dtype(dtype)) for k, (shape, dtype) in obs_template.items()
    }
    replies = [0] * clients
    errors: List[Exception] = []
    barrier = threading.Barrier(clients + 1)

    def worker(idx: int) -> None:
        try:
            client = PolicyClient("127.0.0.1", port)
            barrier.wait()
            for _ in range(requests):
                client.act(obs, MODEL_NAME, timeout=60)
                replies[idx] += 1
            client.close()
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(e)
            try:
                barrier.abort()
            except Exception:
                pass

    threads = [threading.Thread(target=worker, args=(i,), daemon=True) for i in range(clients)]
    for t in threads:
        t.start()
    barrier.wait()  # all clients connected: the clock measures serving, not connects
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        raise RuntimeError(f"{len(errors)} client(s) failed: {errors[0]}")
    return wall, sum(replies)


def main(argv: Optional[List[str]] = None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--clients", type=int, default=32)
    parser.add_argument("--requests", type=int, default=100, help="round-trips per client")
    parser.add_argument("--max-batch", type=int, default=32)
    args = parser.parse_args(argv)

    tmp = Path(tempfile.mkdtemp(prefix="serve_bench_"))
    registry, obs_template = build_artifact(tmp)
    cache_dir = tmp / "xla_cache"

    # -- cold start: empty persistent cache, every ladder bucket compiles.
    replica = Replica(registry, tmp / "cold", args.max_batch, cache_dir)
    replica.wait_ready()
    cold_startup = replica.startup_seconds
    replica.stop()

    # -- warm start: same cache dir, the ladder deserializes from disk.
    replica = Replica(registry, tmp / "warm", args.max_batch, cache_dir)
    ready = replica.wait_ready()
    warm_startup = replica.startup_seconds

    # -- continuous batching throughput on the warm replica.
    wall, total = drive_clients(ready["port"], obs_template, args.clients, args.requests)
    batched_rps = total / wall if wall > 0 else 0.0
    batched_summary = replica.stop()
    batched = batched_summary["policies"][f"{MODEL_NAME}:1"]["metrics"]

    # -- naive baseline: one request per dispatch (ladder [1]), same everything.
    replica = Replica(registry, tmp / "naive", 1, cache_dir)
    ready = replica.wait_ready()
    n_wall, n_total = drive_clients(ready["port"], obs_template, args.clients, args.requests)
    naive_rps = n_total / n_wall if n_wall > 0 else 0.0
    naive_summary = replica.stop()
    naive = naive_summary["policies"][f"{MODEL_NAME}:1"]["metrics"]

    print(json.dumps({
        "metric": "serve_throughput_rps",
        "value": round(batched_rps, 2),
        "unit": (
            f"replies/s (continuous batching, max_batch={args.max_batch}, "
            f"{args.clients} closed-loop clients x {args.requests} requests)"
        ),
        "naive_rps": round(naive_rps, 2),
        "speedup_vs_naive": round(batched_rps / naive_rps, 2) if naive_rps > 0 else None,
        "batch_fill": round(batched.get("Serve/batch_fill", 0.0), 3),
        "replies": total,
        "recompiles": batched_summary["recompiles"],
    }))
    print(json.dumps({
        "metric": "serve_p99_ms",
        "value": round(batched.get("Serve/latency_ms/p99", float("nan")), 3),
        "unit": f"ms enqueue->reply p99 (continuous batching, {args.clients} clients)",
        "p50_ms": round(batched.get("Serve/latency_ms/p50", float("nan")), 3),
        "naive_p99_ms": round(naive.get("Serve/latency_ms/p99", float("nan")), 3),
    }))
    print(json.dumps({
        "metric": "serve_startup_seconds",
        "value": round(warm_startup, 2),
        "unit": "s spawn->ready, warm persistent compile cache",
        "cold_startup_seconds": round(cold_startup, 2),
        "warm_speedup": round(cold_startup / warm_startup, 2) if warm_startup else None,
    }))


if __name__ == "__main__":
    main()
