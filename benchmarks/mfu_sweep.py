"""Train-only MFU sweep over DreamerV3 model sizes (VERDICT r3 #5).

Round 3 left MFU at ~0.17 for size S with the unmeasured claim that the T=64 RSSM /
H=15 imagination scans are latency-bound at S and that larger models lift arithmetic
intensity.  This probe measures grad-steps/s + MFU for sizes S/M/L (same batch 16 ×
seq 64 × 64×64×3 config) on the real chip and prints one JSON line per size, feeding
``PROFILE_r04.md``.

Usage: ``python benchmarks/mfu_sweep.py [S M L S:64]`` — ``SIZE:BATCH`` entries
override the batch size (default 16), probing the arithmetic-intensity lever.

FLOPs and peak figures come from the perf attribution plane
(``sheeprl_tpu/obs/perf.py``) via ``bench.bench_train_only`` — one MFU
definition shared with the in-run ``Perf/mfu`` gauge.
"""

import json
import sys

sys.path.insert(0, ".")

from bench import bench_train_only  # noqa: E402


def main() -> None:
    entries = sys.argv[1:] or ["S", "M", "L"]
    for entry in entries:
        size, _, batch = entry.partition(":")
        batch = int(batch) if batch else 16
        gsps, mfu = bench_train_only(size, batch=batch)
        print(
            json.dumps(
                {"size": size, "batch": batch, "grad_steps_per_sec": round(gsps, 4), "mfu": round(mfu, 4)}
            ),
            flush=True,
        )


if __name__ == "__main__":
    main()
