"""Anakin throughput benchmark: on-device fused-scan env stepping + training vs
the host vector-env path, on the pure-JAX CartPole (ISSUE-6 / ROADMAP item 1).

Three measurements, each a BENCH-style JSON row on stdout (feeds
``benchmarks/bench_compare.py``; all rows are higher-better):

* ``anakin_cartpole_steps_per_sec`` — raw env-steps/s of N vmapped
  :class:`~sheeprl_tpu.envs.jax.cartpole.CartPole` instances auto-reset-stepping
  inside one jitted ``lax.scan`` (random actions drawn in-jit).  Two host
  baselines ride as extras, both stepping gymnasium ``CartPole-v1``:
  ``host_sync_vector_steps_per_sec`` is THE path the training loops pay today —
  the repo's own ``make_vector_env`` ``SyncVectorEnv`` wrapper stack (dict-obs
  coercion, episode statistics, TimeLimit) at the presets' default env count
  (``--host-envs``, default 4) — so ``speedup_vs_host`` is ROADMAP item 1's
  "100-1000x current env throughput" acceptance row; ``host_raw_gym_saturated``
  is bare ``gym.make`` under ``SyncVectorEnv`` at a saturating env count (the
  python step loop plateaus near 90k steps/s on this class of machine no matter
  how many envs — exactly the single-core wall the Anakin mode removes), with
  ``speedup_vs_raw_gym_saturated`` the conservative lower bound;
* ``anakin_ppo_grad_steps_per_sec`` — grad-steps/s of the FULL fused PPO
  iteration (collection scan + GAE + the scanned minibatch update, ONE donated
  dispatch per iteration), with the implied env-steps/s as an extra;
* ``anakin_population_steps_per_sec`` — env-steps/s of the POPULATION PPO
  dispatch (ISSUE-8 / ROADMAP item 4): ``--members`` independent members — each
  with its own params/optimizer/env states/PRNG streams — trained in one
  donated dispatch via the member axis (``engine/population.py``).
  ``per_member_efficiency`` is K-member throughput ÷ (K × single-member
  throughput): 1.0 means K seeds ride for free, 0.5 means K members cost 2×
  one member — the per-dispatch and per-scan-step overheads amortizing across
  the population is exactly Podracer's "multiple agents per chip" win;
* ``anakin_compile_seconds`` — first-dispatch (trace+compile) seconds of the
  fused PPO program in a FRESH subprocess with a persistent XLA compilation
  cache (``compile_cache.{enabled,dir}``): the first run compiles cold and
  fills the cache, the second deserializes — the row's value is the WARM
  seconds (lower-better; ``cold_seconds``/``speedup`` ride as extras).  This is
  ROADMAP item 3's fleet cold-start story measured end to end.

Usage::

    python benchmarks/anakin_bench.py
    python benchmarks/anakin_bench.py --num-envs 64 --steps 4096 --host-steps 512
    python benchmarks/anakin_bench.py --members 16 --pop-envs 16
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional

import numpy as np

if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("SHEEPRL_TPU_QUIET", "1")

import gymnasium as gym  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from sheeprl_tpu.config.core import compose  # noqa: E402
from sheeprl_tpu.envs.jax import make_jax_env  # noqa: E402
from sheeprl_tpu.parallel.mesh import MeshContext, build_mesh  # noqa: E402


def _time_vector_env(envs, num_envs: int, steps: int, seed: int = 0) -> float:
    envs.reset(seed=seed)
    rng = np.random.default_rng(seed)
    actions = rng.integers(0, 2, (steps, num_envs))
    t0 = time.perf_counter()
    for t in range(steps):
        envs.step(actions[t])
    elapsed = time.perf_counter() - t0
    envs.close()
    return steps * num_envs / elapsed


def bench_host_sync_vector(num_envs: int, steps: int, seed: int = 0) -> float:
    """Env-steps/s of the host path the training loops ACTUALLY pay: gymnasium
    ``CartPole-v1`` through the repo's ``make_vector_env`` ``SyncVectorEnv``
    wrapper stack, with random actions."""
    from sheeprl_tpu.utils.env import make_vector_env

    cfg = compose(
        overrides=[
            "exp=ppo",
            "env=gym",
            "env.id=CartPole-v1",
            "algo.mlp_keys.encoder=[state]",
            f"env.num_envs={num_envs}",
            "env.capture_video=False",
            "env.sync_env=True",
            "buffer.memmap=False",
        ]
    )
    return _time_vector_env(make_vector_env(cfg, seed, 0), num_envs, steps, seed)


def bench_host_raw_gym(num_envs: int, steps: int, seed: int = 0) -> float:
    """Env-steps/s of bare ``gym.make`` under ``SyncVectorEnv`` — no repo
    wrappers, the host python loop's best case."""
    envs = gym.vector.SyncVectorEnv([lambda: gym.make("CartPole-v1") for _ in range(num_envs)])
    return _time_vector_env(envs, num_envs, steps, seed)


def bench_anakin_env_steps(num_envs: int, steps: int, seed: int = 0) -> float:
    """Env-steps/s of the vmapped pure-JAX CartPole auto-reset-stepping inside one
    jitted scan, random actions drawn in-jit (no policy — the raw env ceiling).
    Per-step keys/actions derive in ONE bulk threefry before the scan instead of
    per-step ``split`` chains — same distribution, ~1.5x on CPU where the PRNG
    hashing is a visible fraction of the tiny physics."""
    env = make_jax_env("cartpole")
    params = env.default_params()
    vstep = jax.vmap(env.step_autoreset, in_axes=(None, 0, 0, 0))

    @jax.jit
    def rollout(env_state, key):
        k_act, k_step = jax.random.split(key)
        actions = jax.random.randint(k_act, (steps, num_envs), 0, 2, dtype=jnp.int32)
        step_keys = jax.random.split(k_step, steps * num_envs).reshape(steps, num_envs, 2)

        def step(env_state, x):
            a, ks = x
            env_state, _obs, reward, _done, _info = vstep(params, env_state, a, ks)
            return env_state, reward

        env_state, rewards = jax.lax.scan(step, env_state, (actions, step_keys))
        return env_state, rewards.sum()

    keys = jax.random.split(jax.random.PRNGKey(seed), num_envs)
    env_state, _ = jax.vmap(env.reset, in_axes=(None, 0))(params, keys)
    env_state, total = rollout(env_state, jax.random.PRNGKey(seed + 1))  # warmup/compile
    jax.device_get(total)
    t0 = time.perf_counter()
    env_state, total = rollout(env_state, jax.random.PRNGKey(seed + 2))
    jax.device_get(total)
    elapsed = time.perf_counter() - t0
    return steps * num_envs / elapsed


def bench_anakin_ppo(num_envs: int, rollout_steps: int, iters: int, seed: int = 0) -> Dict[str, float]:
    """Grad-steps/s + env-steps/s of the full fused PPO Anakin iteration (the
    program ``engine/anakin.py`` dispatches per update)."""
    from sheeprl_tpu.algos.ppo.agent import build_agent
    from sheeprl_tpu.algos.ppo.ppo import PPOTrainFns
    from sheeprl_tpu.engine.anakin import init_episode_stats, make_ppo_anakin_iteration, reset_envs

    cfg = compose(
        overrides=[
            "exp=ppo",
            "env=jax_cartpole",
            "algo.anakin=True",
            "algo.mlp_keys.encoder=[state]",
            f"env.num_envs={num_envs}",
            f"algo.rollout_steps={rollout_steps}",
            f"algo.per_rank_batch_size={max(rollout_steps * num_envs // 4, 1)}",
            "algo.update_epochs=4",
            "env.capture_video=False",
            "buffer.memmap=False",
        ]
    )
    ctx = MeshContext(mesh=build_mesh(devices=jax.devices()[:1]), precision="fp32", seed=seed)
    env = make_jax_env("cartpole")
    env_params = env.default_params()
    obs_space = gym.spaces.Dict({"state": env.observation_space(env_params)})
    agent, params = build_agent(ctx, env.action_space(env_params), obs_space, cfg)
    fns = PPOTrainFns(ctx, agent, cfg, ["state"], max(iters, 1))
    opt_state = ctx.replicate(fns.opt.init(params))
    iteration = make_ppo_anakin_iteration(env, env_params, agent, fns, cfg, "state")
    dispatch = jax.jit(iteration, donate_argnums=(0,))

    env_state, obs0 = reset_envs(env, env_params, num_envs, jax.random.PRNGKey(seed))
    carry = {
        "params": params,
        "opt_state": opt_state,
        "env_state": env_state,
        "obs": obs0,
        "key": jax.random.PRNGKey(seed + 1),
        "episode_stats": init_episode_stats(num_envs),
    }
    carry, metrics = dispatch(carry, 0.2, 0.0)  # warmup/compile
    jax.device_get(metrics)
    t0 = time.perf_counter()
    for _ in range(iters):
        carry, metrics = dispatch(carry, 0.2, 0.0)
    jax.device_get(metrics)
    elapsed = time.perf_counter() - t0
    env_steps = iters * rollout_steps * num_envs
    grad_steps = iters * fns.grad_steps_per_update
    return {
        "grad_steps_per_sec": grad_steps / elapsed,
        "env_steps_per_sec": env_steps / elapsed,
    }


def _population_setup(num_envs: int, rollout_steps: int, seed: int):
    """Tiny-net fused PPO iteration + per-member carry builder (shared by the
    population bench and the compile probe).  Small shapes on purpose: the
    population win IS the fixed-overhead amortization, measured where a single
    member underuses the chip."""
    from sheeprl_tpu.algos.ppo.agent import build_agent
    from sheeprl_tpu.algos.ppo.ppo import PPOTrainFns
    from sheeprl_tpu.engine.anakin import init_episode_stats, make_ppo_anakin_iteration, reset_envs

    cfg = compose(
        overrides=[
            "exp=ppo",
            "env=jax_cartpole",
            "algo.anakin=True",
            "algo.mlp_keys.encoder=[state]",
            f"env.num_envs={num_envs}",
            f"algo.rollout_steps={rollout_steps}",
            f"algo.per_rank_batch_size={max(rollout_steps * num_envs // 4, 1)}",
            "algo.update_epochs=1",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.encoder.mlp_features_dim=8",
            "env.capture_video=False",
            "buffer.memmap=False",
        ]
    )
    ctx = MeshContext(mesh=build_mesh(devices=jax.devices()[:1]), precision="fp32", seed=seed)
    env = make_jax_env("cartpole")
    env_params = env.default_params()
    obs_space = gym.spaces.Dict({"state": env.observation_space(env_params)})
    agent, params = build_agent(ctx, env.action_space(env_params), obs_space, cfg)
    fns = PPOTrainFns(ctx, agent, cfg, ["state"], 8)
    iteration = make_ppo_anakin_iteration(env, env_params, agent, fns, cfg, "state")

    def member_carry(m: int):
        p = jax.tree.map(jnp.copy, params)
        env_state, obs0 = reset_envs(env, env_params, num_envs, jax.random.fold_in(jax.random.PRNGKey(seed), m))
        return {
            "params": p,
            "opt_state": fns.opt.init(p),
            "env_state": env_state,
            "obs": obs0,
            "key": jax.random.fold_in(jax.random.PRNGKey(seed + 1), m),
            "episode_stats": init_episode_stats(num_envs),
        }

    return iteration, member_carry


def _time_dispatch(dispatch, carry, args, iters: int) -> float:
    carry, metrics = dispatch(carry, *args)  # warmup/compile
    jax.device_get(metrics)
    t0 = time.perf_counter()
    for _ in range(iters):
        carry, metrics = dispatch(carry, *args)
    jax.device_get(metrics)
    return (time.perf_counter() - t0) / iters


def bench_anakin_population(
    members: int, num_envs: int, rollout_steps: int, iters: int, seed: int = 0
) -> Dict[str, float]:
    """Env-steps/s of the K-member population PPO dispatch vs K × the
    single-member rate (per-member efficiency), both over the default
    bit-exact ``lax.map`` member axis."""
    from sheeprl_tpu.engine.population import population_transform, stack_members

    iteration, member_carry = _population_setup(num_envs, rollout_steps, seed)
    steps = rollout_steps * num_envs

    single = jax.jit(iteration, donate_argnums=(0,))
    t_single = _time_dispatch(single, member_carry(0), (0.2, 0.0), iters)

    stacked = stack_members([member_carry(m) for m in range(members)])
    pop = jax.jit(population_transform(iteration, vectorize=False, n_args=2), donate_argnums=(0,))
    coefs = (jnp.full((members,), 0.2, jnp.float32), jnp.zeros((members,), jnp.float32))
    t_pop = _time_dispatch(pop, stacked, coefs, iters)

    single_sps = steps / t_single
    pop_sps = members * steps / t_pop
    return {
        "pop_steps_per_sec": pop_sps,
        "single_steps_per_sec": single_sps,
        "per_member_efficiency": pop_sps / (members * single_sps),
    }


def _compile_probe(num_envs: int, rollout_steps: int, cache_dir: Optional[str]) -> None:
    """Child-process half of the compile bench: optionally enable the persistent
    cache, then time the FIRST dispatch (trace + compile + execute) of the fused
    PPO program and print one JSON line."""
    if cache_dir:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    iteration, member_carry = _population_setup(num_envs, rollout_steps, seed=0)
    dispatch = jax.jit(iteration, donate_argnums=(0,))
    t0 = time.perf_counter()
    carry, metrics = dispatch(member_carry(0), 0.2, 0.0)
    jax.device_get(metrics)
    print(json.dumps({"first_dispatch_seconds": time.perf_counter() - t0}))


def bench_compile_cache(num_envs: int, rollout_steps: int) -> Dict[str, float]:
    """Cold-vs-warm first-dispatch seconds across two fresh subprocesses sharing
    one persistent XLA compilation cache directory."""
    import subprocess
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="anakin_xla_cache_")
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "SHEEPRL_TPU_QUIET": "1"}
    times = []
    try:
        for _ in range(2):
            proc = subprocess.run(
                [
                    sys.executable,
                    os.path.abspath(__file__),
                    "--compile-probe",
                    "--compile-cache-dir", cache_dir,
                    "--pop-envs", str(num_envs),
                    "--pop-rollout", str(rollout_steps),
                ],
                capture_output=True,
                text=True,
                env=env,
                timeout=600,
            )
            if proc.returncode != 0:
                raise RuntimeError(f"compile probe failed: {proc.stderr[-500:]}")
            row = json.loads(proc.stdout.strip().splitlines()[-1])
            times.append(float(row["first_dispatch_seconds"]))
    finally:
        import shutil

        shutil.rmtree(cache_dir, ignore_errors=True)
    cold, warm = times
    return {"cold_seconds": cold, "warm_seconds": warm, "speedup": cold / max(warm, 1e-9)}


def main(argv: Optional[List[str]] = None) -> Dict[str, float]:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--num-envs", type=int, default=int(os.environ.get("BENCH_ANAKIN_ENVS", "1024")))
    parser.add_argument("--steps", type=int, default=int(os.environ.get("BENCH_ANAKIN_STEPS", "2048")))
    parser.add_argument("--host-steps", type=int, default=int(os.environ.get("BENCH_ANAKIN_HOST_STEPS", "512")))
    parser.add_argument("--rollout-steps", type=int, default=128)
    parser.add_argument("--ppo-envs", type=int, default=int(os.environ.get("BENCH_ANAKIN_PPO_ENVS", "64")))
    parser.add_argument("--iters", type=int, default=int(os.environ.get("BENCH_ANAKIN_ITERS", "8")))
    parser.add_argument(
        "--host-envs",
        type=int,
        default=4,
        help="env count for the 'current training config' host baseline (the env/default.yaml num_envs)",
    )
    parser.add_argument(
        "--members", type=int, default=int(os.environ.get("BENCH_ANAKIN_MEMBERS", "16")),
        help="population size K for the anakin_population_steps_per_sec row",
    )
    parser.add_argument("--pop-envs", type=int, default=int(os.environ.get("BENCH_ANAKIN_POP_ENVS", "16")))
    parser.add_argument("--pop-rollout", type=int, default=32)
    parser.add_argument("--pop-iters", type=int, default=int(os.environ.get("BENCH_ANAKIN_POP_ITERS", "6")))
    parser.add_argument("--skip-population", action="store_true", help="skip the population row")
    parser.add_argument(
        "--compile-bench", type=int, default=int(os.environ.get("BENCH_ANAKIN_COMPILE", "1")),
        help="1 = emit the anakin_compile_seconds cold-vs-warm row (2 subprocesses); 0 = skip",
    )
    parser.add_argument("--compile-probe", action="store_true", help=argparse.SUPPRESS)
    parser.add_argument("--compile-cache-dir", default=None, help=argparse.SUPPRESS)
    args = parser.parse_args(argv)

    if args.compile_probe:  # child-process mode of bench_compile_cache
        _compile_probe(args.pop_envs, args.pop_rollout, args.compile_cache_dir)
        return {}

    host_sps = bench_host_sync_vector(args.host_envs, args.host_steps)
    raw_envs = min(args.num_envs, 64)  # the python loop saturates long before 64
    host_raw = bench_host_raw_gym(raw_envs, max(args.host_steps // 2, 16))
    anakin_sps = bench_anakin_env_steps(args.num_envs, args.steps)
    rows = [
        {
            "metric": "anakin_cartpole_steps_per_sec",
            "value": round(anakin_sps, 1),
            "unit": f"env_steps/s ({args.num_envs} vmapped jax CartPole in one jitted scan, 1 chip)",
            "host_sync_vector_steps_per_sec": round(host_sps, 1),
            "host_envs": args.host_envs,
            "speedup_vs_host": round(anakin_sps / host_sps, 1),
            "host_raw_gym_saturated_steps_per_sec": round(host_raw, 1),
            "host_raw_gym_envs": raw_envs,
            "speedup_vs_raw_gym_saturated": round(anakin_sps / host_raw, 1),
        }
    ]
    ppo = bench_anakin_ppo(args.ppo_envs, args.rollout_steps, args.iters)
    rows.append(
        {
            "metric": "anakin_ppo_grad_steps_per_sec",
            "value": round(ppo["grad_steps_per_sec"], 1),
            "unit": (
                f"grad_steps/s (fused collect+GAE+update dispatch, {args.ppo_envs} envs x "
                f"{args.rollout_steps} rollout, 1 chip)"
            ),
            "anakin_ppo_env_steps_per_sec": round(ppo["env_steps_per_sec"], 1),
        }
    )
    if not args.skip_population:
        pop = bench_anakin_population(args.members, args.pop_envs, args.pop_rollout, args.pop_iters)
        rows.append(
            {
                "metric": "anakin_population_steps_per_sec",
                "value": round(pop["pop_steps_per_sec"], 1),
                "unit": (
                    f"env_steps/s across all members ({args.members} members x {args.pop_envs} envs x "
                    f"{args.pop_rollout} rollout, fused population PPO dispatch, lax.map member axis, 1 chip)"
                ),
                "members": args.members,
                "single_member_steps_per_sec": round(pop["single_steps_per_sec"], 1),
                # K-member throughput / (K x single-member): 1.0 = K seeds ride free
                "per_member_efficiency": round(pop["per_member_efficiency"], 3),
            }
        )
    if args.compile_bench:
        cc = bench_compile_cache(args.pop_envs, args.pop_rollout)
        rows.append(
            {
                "metric": "anakin_compile_seconds",
                "value": round(cc["warm_seconds"], 3),
                "unit": (
                    "seconds to first fused-PPO dispatch in a fresh process with a WARM persistent "
                    "XLA compilation cache (compile_cache.enabled; lower is better)"
                ),
                "cold_seconds": round(cc["cold_seconds"], 3),
                "warm_speedup": round(cc["speedup"], 2),
            }
        )
    for row in rows:
        print(json.dumps(row))
    return {row["metric"]: row["value"] for row in rows}


if __name__ == "__main__":
    main()
