"""Anakin throughput benchmark: on-device fused-scan env stepping + training vs
the host vector-env path, on the pure-JAX CartPole (ISSUE-6 / ROADMAP item 1).

Three measurements, each a BENCH-style JSON row on stdout (feeds
``benchmarks/bench_compare.py``; all rows are higher-better):

* ``anakin_cartpole_steps_per_sec`` — raw env-steps/s of N vmapped
  :class:`~sheeprl_tpu.envs.jax.cartpole.CartPole` instances auto-reset-stepping
  inside one jitted ``lax.scan`` (random actions drawn in-jit).  Two host
  baselines ride as extras, both stepping gymnasium ``CartPole-v1``:
  ``host_sync_vector_steps_per_sec`` is THE path the training loops pay today —
  the repo's own ``make_vector_env`` ``SyncVectorEnv`` wrapper stack (dict-obs
  coercion, episode statistics, TimeLimit) at the presets' default env count
  (``--host-envs``, default 4) — so ``speedup_vs_host`` is ROADMAP item 1's
  "100-1000x current env throughput" acceptance row; ``host_raw_gym_saturated``
  is bare ``gym.make`` under ``SyncVectorEnv`` at a saturating env count (the
  python step loop plateaus near 90k steps/s on this class of machine no matter
  how many envs — exactly the single-core wall the Anakin mode removes), with
  ``speedup_vs_raw_gym_saturated`` the conservative lower bound;
* ``anakin_ppo_grad_steps_per_sec`` — grad-steps/s of the FULL fused PPO
  iteration (collection scan + GAE + the scanned minibatch update, ONE donated
  dispatch per iteration), with the implied env-steps/s as an extra.

Usage::

    python benchmarks/anakin_bench.py
    python benchmarks/anakin_bench.py --num-envs 64 --steps 4096 --host-steps 512
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional

import numpy as np

if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("SHEEPRL_TPU_QUIET", "1")

import gymnasium as gym  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from sheeprl_tpu.config.core import compose  # noqa: E402
from sheeprl_tpu.envs.jax import make_jax_env  # noqa: E402
from sheeprl_tpu.parallel.mesh import MeshContext, build_mesh  # noqa: E402


def _time_vector_env(envs, num_envs: int, steps: int, seed: int = 0) -> float:
    envs.reset(seed=seed)
    rng = np.random.default_rng(seed)
    actions = rng.integers(0, 2, (steps, num_envs))
    t0 = time.perf_counter()
    for t in range(steps):
        envs.step(actions[t])
    elapsed = time.perf_counter() - t0
    envs.close()
    return steps * num_envs / elapsed


def bench_host_sync_vector(num_envs: int, steps: int, seed: int = 0) -> float:
    """Env-steps/s of the host path the training loops ACTUALLY pay: gymnasium
    ``CartPole-v1`` through the repo's ``make_vector_env`` ``SyncVectorEnv``
    wrapper stack, with random actions."""
    from sheeprl_tpu.utils.env import make_vector_env

    cfg = compose(
        overrides=[
            "exp=ppo",
            "env=gym",
            "env.id=CartPole-v1",
            "algo.mlp_keys.encoder=[state]",
            f"env.num_envs={num_envs}",
            "env.capture_video=False",
            "env.sync_env=True",
            "buffer.memmap=False",
        ]
    )
    return _time_vector_env(make_vector_env(cfg, seed, 0), num_envs, steps, seed)


def bench_host_raw_gym(num_envs: int, steps: int, seed: int = 0) -> float:
    """Env-steps/s of bare ``gym.make`` under ``SyncVectorEnv`` — no repo
    wrappers, the host python loop's best case."""
    envs = gym.vector.SyncVectorEnv([lambda: gym.make("CartPole-v1") for _ in range(num_envs)])
    return _time_vector_env(envs, num_envs, steps, seed)


def bench_anakin_env_steps(num_envs: int, steps: int, seed: int = 0) -> float:
    """Env-steps/s of the vmapped pure-JAX CartPole auto-reset-stepping inside one
    jitted scan, random actions drawn in-jit (no policy — the raw env ceiling).
    Per-step keys/actions derive in ONE bulk threefry before the scan instead of
    per-step ``split`` chains — same distribution, ~1.5x on CPU where the PRNG
    hashing is a visible fraction of the tiny physics."""
    env = make_jax_env("cartpole")
    params = env.default_params()
    vstep = jax.vmap(env.step_autoreset, in_axes=(None, 0, 0, 0))

    @jax.jit
    def rollout(env_state, key):
        k_act, k_step = jax.random.split(key)
        actions = jax.random.randint(k_act, (steps, num_envs), 0, 2, dtype=jnp.int32)
        step_keys = jax.random.split(k_step, steps * num_envs).reshape(steps, num_envs, 2)

        def step(env_state, x):
            a, ks = x
            env_state, _obs, reward, _done, _info = vstep(params, env_state, a, ks)
            return env_state, reward

        env_state, rewards = jax.lax.scan(step, env_state, (actions, step_keys))
        return env_state, rewards.sum()

    keys = jax.random.split(jax.random.PRNGKey(seed), num_envs)
    env_state, _ = jax.vmap(env.reset, in_axes=(None, 0))(params, keys)
    env_state, total = rollout(env_state, jax.random.PRNGKey(seed + 1))  # warmup/compile
    jax.device_get(total)
    t0 = time.perf_counter()
    env_state, total = rollout(env_state, jax.random.PRNGKey(seed + 2))
    jax.device_get(total)
    elapsed = time.perf_counter() - t0
    return steps * num_envs / elapsed


def bench_anakin_ppo(num_envs: int, rollout_steps: int, iters: int, seed: int = 0) -> Dict[str, float]:
    """Grad-steps/s + env-steps/s of the full fused PPO Anakin iteration (the
    program ``engine/anakin.py`` dispatches per update)."""
    from sheeprl_tpu.algos.ppo.agent import build_agent
    from sheeprl_tpu.algos.ppo.ppo import PPOTrainFns
    from sheeprl_tpu.engine.anakin import init_episode_stats, make_ppo_anakin_iteration, reset_envs

    cfg = compose(
        overrides=[
            "exp=ppo",
            "env=jax_cartpole",
            "algo.anakin=True",
            "algo.mlp_keys.encoder=[state]",
            f"env.num_envs={num_envs}",
            f"algo.rollout_steps={rollout_steps}",
            f"algo.per_rank_batch_size={max(rollout_steps * num_envs // 4, 1)}",
            "algo.update_epochs=4",
            "env.capture_video=False",
            "buffer.memmap=False",
        ]
    )
    ctx = MeshContext(mesh=build_mesh(devices=jax.devices()[:1]), precision="fp32", seed=seed)
    env = make_jax_env("cartpole")
    env_params = env.default_params()
    obs_space = gym.spaces.Dict({"state": env.observation_space(env_params)})
    agent, params = build_agent(ctx, env.action_space(env_params), obs_space, cfg)
    fns = PPOTrainFns(ctx, agent, cfg, ["state"], max(iters, 1))
    opt_state = ctx.replicate(fns.opt.init(params))
    iteration = make_ppo_anakin_iteration(env, env_params, agent, fns, cfg, "state")
    dispatch = jax.jit(iteration, donate_argnums=(0,))

    env_state, obs0 = reset_envs(env, env_params, num_envs, jax.random.PRNGKey(seed))
    carry = {
        "params": params,
        "opt_state": opt_state,
        "env_state": env_state,
        "obs": obs0,
        "key": jax.random.PRNGKey(seed + 1),
        "episode_stats": init_episode_stats(num_envs),
    }
    carry, metrics = dispatch(carry, 0.2, 0.0)  # warmup/compile
    jax.device_get(metrics)
    t0 = time.perf_counter()
    for _ in range(iters):
        carry, metrics = dispatch(carry, 0.2, 0.0)
    jax.device_get(metrics)
    elapsed = time.perf_counter() - t0
    env_steps = iters * rollout_steps * num_envs
    grad_steps = iters * fns.grad_steps_per_update
    return {
        "grad_steps_per_sec": grad_steps / elapsed,
        "env_steps_per_sec": env_steps / elapsed,
    }


def main(argv: Optional[List[str]] = None) -> Dict[str, float]:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--num-envs", type=int, default=int(os.environ.get("BENCH_ANAKIN_ENVS", "1024")))
    parser.add_argument("--steps", type=int, default=int(os.environ.get("BENCH_ANAKIN_STEPS", "2048")))
    parser.add_argument("--host-steps", type=int, default=int(os.environ.get("BENCH_ANAKIN_HOST_STEPS", "512")))
    parser.add_argument("--rollout-steps", type=int, default=128)
    parser.add_argument("--ppo-envs", type=int, default=int(os.environ.get("BENCH_ANAKIN_PPO_ENVS", "64")))
    parser.add_argument("--iters", type=int, default=int(os.environ.get("BENCH_ANAKIN_ITERS", "8")))
    parser.add_argument(
        "--host-envs",
        type=int,
        default=4,
        help="env count for the 'current training config' host baseline (the env/default.yaml num_envs)",
    )
    args = parser.parse_args(argv)

    host_sps = bench_host_sync_vector(args.host_envs, args.host_steps)
    raw_envs = min(args.num_envs, 64)  # the python loop saturates long before 64
    host_raw = bench_host_raw_gym(raw_envs, max(args.host_steps // 2, 16))
    anakin_sps = bench_anakin_env_steps(args.num_envs, args.steps)
    rows = [
        {
            "metric": "anakin_cartpole_steps_per_sec",
            "value": round(anakin_sps, 1),
            "unit": f"env_steps/s ({args.num_envs} vmapped jax CartPole in one jitted scan, 1 chip)",
            "host_sync_vector_steps_per_sec": round(host_sps, 1),
            "host_envs": args.host_envs,
            "speedup_vs_host": round(anakin_sps / host_sps, 1),
            "host_raw_gym_saturated_steps_per_sec": round(host_raw, 1),
            "host_raw_gym_envs": raw_envs,
            "speedup_vs_raw_gym_saturated": round(anakin_sps / host_raw, 1),
        }
    ]
    ppo = bench_anakin_ppo(args.ppo_envs, args.rollout_steps, args.iters)
    rows.append(
        {
            "metric": "anakin_ppo_grad_steps_per_sec",
            "value": round(ppo["grad_steps_per_sec"], 1),
            "unit": (
                f"grad_steps/s (fused collect+GAE+update dispatch, {args.ppo_envs} envs x "
                f"{args.rollout_steps} rollout, 1 chip)"
            ),
            "anakin_ppo_env_steps_per_sec": round(ppo["env_steps_per_sec"], 1),
        }
    )
    for row in rows:
        print(json.dumps(row))
    return {row["metric"]: row["value"] for row in rows}


if __name__ == "__main__":
    main()
