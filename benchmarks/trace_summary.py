#!/usr/bin/env python
"""Fold a Chrome-trace JSON (exported by ``sheeprl_tpu.obs``) OR a flight-recorder
blackbox event log into a per-phase table.

Usage:
    python benchmarks/trace_summary.py <log_dir>/trace.json [--json]
    python benchmarks/trace_summary.py <log_dir>/blackbox/events.jsonl [--json]

Per span name: call count, total time, share of the top-level (depth-0) wall clock, and
p50/p95/p99 latencies.  ``--json`` emits the same table as a JSON object for BENCH
report collection scripts.

Blackbox event JSONL (one JSON object per line, ``obs/flight_recorder.py``) is
detected automatically: ``span`` events feed the same per-phase table (depth from
the recorder), every other event kind is summarized by count — so one tool reads
both live traces and post-mortem dumps.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List


def _load_blackbox_events(path: str) -> List[Dict[str, Any]]:
    """Parse a flight-recorder events.jsonl file into a list of event dicts."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                event = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(event, dict) and "kind" in event:
                events.append(event)
    return events


def _is_blackbox_log(path: str) -> bool:
    if path.endswith(".jsonl"):
        return True
    with open(path) as f:
        head = f.read(2048).lstrip()
    if not head.startswith("{"):
        return False
    try:
        first = json.loads(head.splitlines()[0])
    except json.JSONDecodeError:
        return False
    return isinstance(first, dict) and "kind" in first


def summarize_blackbox(path: str) -> Dict[str, Any]:
    """Blackbox events -> the same per-phase summary shape as :func:`summarize`,
    plus an ``events`` section counting the non-span kinds (restarts, recompiles,
    metric flushes, strict trips) that tell the crash story."""
    raw = _load_blackbox_events(path)
    phases: Dict[str, List[float]] = {}
    kinds: Dict[str, int] = {}
    top_level_total = 0.0
    for event in raw:
        if event.get("kind") == "span":
            dur_ms = float(event.get("dur_ms", 0.0))
            phases.setdefault(str(event.get("name", "?")), []).append(dur_ms)
            if int(event.get("depth", 0)) == 0:
                top_level_total += dur_ms
        else:
            kinds[str(event["kind"])] = kinds.get(str(event["kind"]), 0) + 1
    summary = _phase_rows(path, phases, top_level_total)
    summary["events"] = dict(sorted(kinds.items(), key=lambda kv: -kv[1]))
    span = [e.get("ts") for e in raw if isinstance(e.get("ts"), (int, float))]
    if span:
        summary["window_s"] = max(span) - min(span)
    return summary


def summarize(path: str) -> Dict[str, Any]:
    if _is_blackbox_log(path):
        return summarize_blackbox(path)
    with open(path) as f:
        doc = json.load(f)
    events = [e for e in doc.get("traceEvents", []) if e.get("ph") == "X"]
    phases: Dict[str, List[float]] = {}
    top_level_total = 0.0
    for e in events:
        dur_ms = float(e.get("dur", 0.0)) / 1e3
        phases.setdefault(e["name"], []).append(dur_ms)
        if e.get("args", {}).get("depth", 0) == 0:
            top_level_total += dur_ms
    return _phase_rows(path, phases, top_level_total)


def _phase_rows(path: str, phases: Dict[str, List[float]], top_level_total: float) -> Dict[str, Any]:
    rows = {}
    for name, durs in phases.items():
        durs = sorted(durs)

        def pct(q: float) -> float:
            if len(durs) == 1:
                return durs[0]
            idx = q / 100.0 * (len(durs) - 1)
            lo = int(idx)
            hi = min(lo + 1, len(durs) - 1)
            return durs[lo] + (durs[hi] - durs[lo]) * (idx - lo)

        total = sum(durs)
        rows[name] = {
            "count": len(durs),
            "total_ms": total,
            "share": total / top_level_total if top_level_total > 0 else 0.0,
            "p50_ms": pct(50),
            "p95_ms": pct(95),
            "p99_ms": pct(99),
        }
    return {
        "trace": path,
        "top_level_total_ms": top_level_total,
        "phases": dict(sorted(rows.items(), key=lambda kv: -kv[1]["total_ms"])),
    }


def format_table(summary: Dict[str, Any]) -> str:
    headers = ("phase", "count", "total_ms", "share", "p50_ms", "p95_ms", "p99_ms")
    rows = [
        (
            name,
            str(r["count"]),
            f"{r['total_ms']:.2f}",
            f"{r['share'] * 100:.1f}%",
            f"{r['p50_ms']:.3f}",
            f"{r['p95_ms']:.3f}",
            f"{r['p99_ms']:.3f}",
        )
        for name, r in summary["phases"].items()
    ]
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h) for i, h in enumerate(headers)]
    lines = [
        "  ".join(h.ljust(w) for h, w in zip(headers, widths)),
        "-" * (sum(widths) + 2 * (len(widths) - 1)),
    ]
    for r in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    lines.append(f"top-level wall clock: {summary['top_level_total_ms']:.2f} ms")
    if summary.get("events"):
        lines.append("")
        lines.append("flight-recorder events:")
        for kind, count in summary["events"].items():
            lines.append(f"  {kind}: {count}")
        if "window_s" in summary:
            lines.append(f"  (window: {summary['window_s']:.1f} s)")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("trace", help="Chrome-trace JSON (<log_dir>/trace.json) or blackbox events.jsonl")
    parser.add_argument("--json", action="store_true", help="emit JSON instead of a table")
    args = parser.parse_args(argv)
    summary = summarize(args.trace)
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        print(format_table(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
