#!/usr/bin/env python
"""Fold a Chrome-trace JSON (exported by ``sheeprl_tpu.obs``), a flight-recorder
blackbox event log, OR a fleet timeline into a per-phase / per-role table.

Usage:
    python benchmarks/trace_summary.py <log_dir>/trace.json [--json]
    python benchmarks/trace_summary.py <log_dir>/blackbox/events.jsonl [--json]
    python benchmarks/trace_summary.py <run_dir>/fleet/timeline.jsonl [--json]
    python benchmarks/trace_summary.py <run_dir>/fleet/trace_fleet.json [--json]
    python benchmarks/trace_summary.py <log_dir>/perf_report.json [--json]

Per span name: call count, total time, share of the top-level (depth-0) wall clock, and
p50/p95/p99 latencies.  ``--json`` emits the same table as a JSON object for BENCH
report collection scripts.

Blackbox event JSONL (one JSON object per line, ``obs/flight_recorder.py``) is
detected automatically: ``span`` events feed the same per-phase table (depth from
the recorder), every other event kind is summarized by count — so one tool reads
both live traces and post-mortem dumps.

Fleet inputs (``sheeprl_tpu/obs/fleet.py``) are detected automatically too: a
timeline JSONL (rows tagged ``{role, actor_id, generation, ...}`` with a
``metrics`` dict) folds into one row per process slot — last throughput rates,
queue depth / staleness gauges, and the publish→apply weight-propagation latency
(``Sebulba/publish_apply_ms``) correlated across roles by the shared trace id.
A *merged* multi-process Chrome trace (``trace_fleet.json``) groups phases per
process using its ``process_name`` metadata; single-process traces render
exactly as before.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List


def _load_blackbox_events(path: str) -> List[Dict[str, Any]]:
    """Parse a flight-recorder events.jsonl file into a list of event dicts."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                event = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(event, dict) and "kind" in event:
                events.append(event)
    return events


def _first_json_line(path: str) -> Any:
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    return json.loads(line)
    except (OSError, json.JSONDecodeError):
        return None
    return None


def _is_fleet_timeline(path: str) -> bool:
    """Fleet timeline rows carry the tag schema + a metrics dict (no "kind"), so
    this check must run BEFORE the blackbox sniff — both are JSONL."""
    if not path.endswith((".jsonl", ".json")):
        return False
    first = _first_json_line(path)
    return isinstance(first, dict) and "role" in first and "metrics" in first


def summarize_fleet(path: str) -> Dict[str, Any]:
    """Fleet timeline -> one row per process slot (``role`` + ``actor_id``).

    Counters were already folded into ``<name>_per_s`` rates by the aggregator;
    this keeps each slot's *peak* rates (every exporter's close-time flush drives
    the last-row rate to ~0, so "last" would always read as drained) and last
    gauges, plus the mean publish→apply latency — the cross-process
    weight-propagation figure the correlated trace ids make meaningful."""
    slots: Dict[str, Dict[str, Any]] = {}
    trace_id = None
    walls: List[float] = []
    n_rows = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(row, dict) or "role" not in row:
                continue
            n_rows += 1
            trace_id = row.get("trace_id") or trace_id
            wall = row.get("wall_clock")
            if isinstance(wall, (int, float)):
                walls.append(float(wall))
            key = f"{row.get('role')}{row.get('actor_id', 0)}"
            slot = slots.setdefault(
                key,
                {
                    "role": row.get("role"),
                    "actor_id": row.get("actor_id", 0),
                    "rows": 0,
                    "generations": set(),
                    "pids": set(),
                    "publish_apply_ms": [],
                    "last": {},
                },
            )
            slot["rows"] += 1
            slot["generations"].add(row.get("generation", 0))
            if row.get("pid") is not None:
                slot["pids"].add(row["pid"])
            metrics = row.get("metrics") or {}
            apply_ms = metrics.get("Sebulba/publish_apply_ms")
            if isinstance(apply_ms, (int, float)):
                slot["publish_apply_ms"].append(float(apply_ms))
            peaks = slot.setdefault("peak_rates", {})
            for name, value in metrics.items():
                if name.endswith("_per_s") and isinstance(value, (int, float)):
                    peaks[name] = max(peaks.get(name, 0.0), float(value))
            slot["last"] = metrics
    for slot in slots.values():
        slot["generations"] = sorted(slot["generations"])
        slot["pids"] = sorted(slot["pids"])
        samples = slot.pop("publish_apply_ms")
        slot["publish_apply_ms_mean"] = sum(samples) / len(samples) if samples else None
        last = slot.pop("last")
        slot["rates"] = slot.pop("peak_rates", {})
        slot["gauges"] = {
            k: v for k, v in last.items() if not k.endswith("_per_s") and "/" in k
        }
    order = {"learner": 0, "actor": 1, "front": 2, "serve": 3}
    return {
        "timeline": path,
        "trace_id": trace_id,
        "rows": n_rows,
        "window_s": (max(walls) - min(walls)) if len(walls) > 1 else 0.0,
        "slots": dict(
            sorted(slots.items(), key=lambda kv: (order.get(str(kv[1]["role"]), 9), kv[0]))
        ),
    }


def format_fleet_table(summary: Dict[str, Any]) -> str:
    headers = ("slot", "role", "rows", "gens", "grad/s", "env/s", "pub->apply_ms", "gauges")
    rows = []
    for key, slot in summary["slots"].items():
        rates = slot["rates"]
        apply_ms = slot["publish_apply_ms_mean"]
        gauges = ", ".join(
            f"{name.split('/', 1)[1]}={value:.3g}" for name, value in sorted(slot["gauges"].items())
        )
        rows.append(
            (
                key,
                str(slot["role"]),
                str(slot["rows"]),
                ",".join(str(g) for g in slot["generations"]),
                f"{rates['grad_steps_per_s']:.2f}" if "grad_steps_per_s" in rates else "-",
                f"{rates['env_steps_per_s']:.2f}" if "env_steps_per_s" in rates else "-",
                f"{apply_ms:.2f}" if apply_ms is not None else "-",
                gauges or "-",
            )
        )
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h) for i, h in enumerate(headers)]
    lines = [
        "  ".join(h.ljust(w) for h, w in zip(headers, widths)),
        "-" * (sum(widths) + 2 * (len(widths) - 1)),
    ]
    for r in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    lines.append(
        f"fleet: {summary['rows']} rows over {summary['window_s']:.1f} s"
        + (f" (trace_id={summary['trace_id']})" if summary.get("trace_id") else "")
    )
    return "\n".join(lines)


def _is_perf_report(path: str) -> bool:
    """Perf-attribution reports (``obs/perf.py`` perf_report.json) are a single
    JSON object with the plane's headline keys — no traceEvents, no JSONL."""
    if not path.endswith(".json"):
        return False
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return False
    return isinstance(doc, dict) and "mfu" in doc and "goodput_fractions" in doc


def summarize_perf(path: str) -> Dict[str, Any]:
    """perf_report.json -> headline MFU/goodput figures + a per-cost-model table
    (FLOPs per call, call count, share of total attributed FLOPs)."""
    with open(path) as f:
        doc = json.load(f)
    models = doc.get("cost_models") or {}
    total_flops = sum(m.get("flops", 0.0) * m.get("calls", 0) for m in models.values()) or 0.0
    rows = {}
    for name, model in sorted(
        models.items(), key=lambda kv: -(kv[1].get("flops", 0.0) * kv[1].get("calls", 0))
    ):
        attributed = model.get("flops", 0.0) * model.get("calls", 0)
        rows[name] = {
            "calls": int(model.get("calls", 0)),
            "flops_per_call": float(model.get("flops", 0.0)),
            "share": attributed / total_flops if total_flops > 0 else 0.0,
        }
    return {
        "perf_report": path,
        "role": doc.get("role"),
        "device_kind": doc.get("device_kind"),
        "elapsed_s": doc.get("elapsed_s"),
        "mfu": doc.get("mfu"),
        "hbm_bw_util": doc.get("hbm_bw_util"),
        "achieved_flops_per_sec": doc.get("achieved_flops_per_sec"),
        "goodput": doc.get("goodput"),
        "goodput_fractions": doc.get("goodput_fractions") or {},
        "anomalies": doc.get("anomalies", 0),
        "cost_models": rows,
    }


def format_perf_table(summary: Dict[str, Any]) -> str:
    lines = [
        f"perf report {summary['perf_report']}  role={summary.get('role')}  "
        f"device={summary.get('device_kind') or '?'}",
        f"mfu={summary.get('mfu', 0.0):.4f}  hbm_bw_util={summary.get('hbm_bw_util', 0.0):.4f}  "
        f"goodput={summary.get('goodput', 0.0):.4f}  anomalies={summary.get('anomalies', 0)}  "
        f"elapsed={summary.get('elapsed_s', 0.0):.1f}s",
        "goodput: "
        + "  ".join(
            f"{cat}={frac * 100:.1f}%" for cat, frac in sorted(summary["goodput_fractions"].items())
        ),
    ]
    rows = [
        (name, str(r["calls"]), f"{r['flops_per_call']:.3g}", f"{r['share'] * 100:.1f}%")
        for name, r in summary["cost_models"].items()
    ]
    headers = ("cost model", "calls", "flops/call", "share")
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h) for i, h in enumerate(headers)]
    lines.append("  ".join(h.ljust(w) for h, w in zip(headers, widths)))
    lines.append("-" * (sum(widths) + 2 * (len(widths) - 1)))
    for r in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    return "\n".join(lines)


def _is_blackbox_log(path: str) -> bool:
    if path.endswith(".jsonl"):
        return True
    with open(path) as f:
        head = f.read(2048).lstrip()
    if not head.startswith("{"):
        return False
    try:
        first = json.loads(head.splitlines()[0])
    except json.JSONDecodeError:
        return False
    return isinstance(first, dict) and "kind" in first


def summarize_blackbox(path: str) -> Dict[str, Any]:
    """Blackbox events -> the same per-phase summary shape as :func:`summarize`,
    plus an ``events`` section counting the non-span kinds (restarts, recompiles,
    metric flushes, strict trips) that tell the crash story."""
    raw = _load_blackbox_events(path)
    phases: Dict[str, List[float]] = {}
    kinds: Dict[str, int] = {}
    top_level_total = 0.0
    for event in raw:
        if event.get("kind") == "span":
            dur_ms = float(event.get("dur_ms", 0.0))
            phases.setdefault(str(event.get("name", "?")), []).append(dur_ms)
            if int(event.get("depth", 0)) == 0:
                top_level_total += dur_ms
        else:
            kinds[str(event["kind"])] = kinds.get(str(event["kind"]), 0) + 1
    summary = _phase_rows(path, phases, top_level_total)
    summary["events"] = dict(sorted(kinds.items(), key=lambda kv: -kv[1]))
    span = [e.get("ts") for e in raw if isinstance(e.get("ts"), (int, float))]
    if span:
        summary["window_s"] = max(span) - min(span)
    return summary


def summarize(path: str) -> Dict[str, Any]:
    if _is_perf_report(path):
        return summarize_perf(path)
    if _is_fleet_timeline(path):
        return summarize_fleet(path)
    if _is_blackbox_log(path):
        return summarize_blackbox(path)
    with open(path) as f:
        doc = json.load(f)
    events = [e for e in doc.get("traceEvents", []) if e.get("ph") == "X"]
    # A merged fleet trace spans several processes: group phases per process
    # using the process_name metadata.  Single-process traces (the common case,
    # and what the tests pin) keep their bare phase names.
    labels = {
        e.get("pid"): str((e.get("args") or {}).get("name", e.get("pid")))
        for e in doc.get("traceEvents", [])
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    multi = len({e.get("pid") for e in events}) > 1
    phases: Dict[str, List[float]] = {}
    top_level_total = 0.0
    for e in events:
        dur_ms = float(e.get("dur", 0.0)) / 1e3
        name = e["name"]
        if multi:
            name = f"[{labels.get(e.get('pid'), e.get('pid'))}] {name}"
        phases.setdefault(name, []).append(dur_ms)
        if e.get("args", {}).get("depth", 0) == 0:
            top_level_total += dur_ms
    return _phase_rows(path, phases, top_level_total)


def _phase_rows(path: str, phases: Dict[str, List[float]], top_level_total: float) -> Dict[str, Any]:
    rows = {}
    for name, durs in phases.items():
        durs = sorted(durs)

        def pct(q: float) -> float:
            if len(durs) == 1:
                return durs[0]
            idx = q / 100.0 * (len(durs) - 1)
            lo = int(idx)
            hi = min(lo + 1, len(durs) - 1)
            return durs[lo] + (durs[hi] - durs[lo]) * (idx - lo)

        total = sum(durs)
        rows[name] = {
            "count": len(durs),
            "total_ms": total,
            "share": total / top_level_total if top_level_total > 0 else 0.0,
            "p50_ms": pct(50),
            "p95_ms": pct(95),
            "p99_ms": pct(99),
        }
    return {
        "trace": path,
        "top_level_total_ms": top_level_total,
        "phases": dict(sorted(rows.items(), key=lambda kv: -kv[1]["total_ms"])),
    }


def format_table(summary: Dict[str, Any]) -> str:
    headers = ("phase", "count", "total_ms", "share", "p50_ms", "p95_ms", "p99_ms")
    rows = [
        (
            name,
            str(r["count"]),
            f"{r['total_ms']:.2f}",
            f"{r['share'] * 100:.1f}%",
            f"{r['p50_ms']:.3f}",
            f"{r['p95_ms']:.3f}",
            f"{r['p99_ms']:.3f}",
        )
        for name, r in summary["phases"].items()
    ]
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h) for i, h in enumerate(headers)]
    lines = [
        "  ".join(h.ljust(w) for h, w in zip(headers, widths)),
        "-" * (sum(widths) + 2 * (len(widths) - 1)),
    ]
    for r in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    lines.append(f"top-level wall clock: {summary['top_level_total_ms']:.2f} ms")
    if summary.get("events"):
        lines.append("")
        lines.append("flight-recorder events:")
        for kind, count in summary["events"].items():
            lines.append(f"  {kind}: {count}")
        if "window_s" in summary:
            lines.append(f"  (window: {summary['window_s']:.1f} s)")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("trace", help="Chrome-trace JSON (<log_dir>/trace.json) or blackbox events.jsonl")
    parser.add_argument("--json", action="store_true", help="emit JSON instead of a table")
    args = parser.parse_args(argv)
    summary = summarize(args.trace)
    if args.json:
        print(json.dumps(summary, indent=2))
    elif "perf_report" in summary:
        print(format_perf_table(summary))
    elif "slots" in summary:
        print(format_fleet_table(summary))
    else:
        print(format_table(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
