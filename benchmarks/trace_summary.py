#!/usr/bin/env python
"""Fold a Chrome-trace JSON (exported by ``sheeprl_tpu.obs``) into a per-phase table.

Usage:
    python benchmarks/trace_summary.py <log_dir>/trace.json [--json]

Per span name: call count, total time, share of the top-level (depth-0) wall clock, and
p50/p95/p99 latencies.  ``--json`` emits the same table as a JSON object for BENCH
report collection scripts.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List


def summarize(path: str) -> Dict[str, Any]:
    with open(path) as f:
        doc = json.load(f)
    events = [e for e in doc.get("traceEvents", []) if e.get("ph") == "X"]
    phases: Dict[str, List[float]] = {}
    top_level_total = 0.0
    for e in events:
        dur_ms = float(e.get("dur", 0.0)) / 1e3
        phases.setdefault(e["name"], []).append(dur_ms)
        if e.get("args", {}).get("depth", 0) == 0:
            top_level_total += dur_ms
    rows = {}
    for name, durs in phases.items():
        durs = sorted(durs)

        def pct(q: float) -> float:
            if len(durs) == 1:
                return durs[0]
            idx = q / 100.0 * (len(durs) - 1)
            lo = int(idx)
            hi = min(lo + 1, len(durs) - 1)
            return durs[lo] + (durs[hi] - durs[lo]) * (idx - lo)

        total = sum(durs)
        rows[name] = {
            "count": len(durs),
            "total_ms": total,
            "share": total / top_level_total if top_level_total > 0 else 0.0,
            "p50_ms": pct(50),
            "p95_ms": pct(95),
            "p99_ms": pct(99),
        }
    return {
        "trace": path,
        "top_level_total_ms": top_level_total,
        "phases": dict(sorted(rows.items(), key=lambda kv: -kv[1]["total_ms"])),
    }


def format_table(summary: Dict[str, Any]) -> str:
    headers = ("phase", "count", "total_ms", "share", "p50_ms", "p95_ms", "p99_ms")
    rows = [
        (
            name,
            str(r["count"]),
            f"{r['total_ms']:.2f}",
            f"{r['share'] * 100:.1f}%",
            f"{r['p50_ms']:.3f}",
            f"{r['p95_ms']:.3f}",
            f"{r['p99_ms']:.3f}",
        )
        for name, r in summary["phases"].items()
    ]
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h) for i, h in enumerate(headers)]
    lines = [
        "  ".join(h.ljust(w) for h, w in zip(headers, widths)),
        "-" * (sum(widths) + 2 * (len(widths) - 1)),
    ]
    for r in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    lines.append(f"top-level wall clock: {summary['top_level_total_ms']:.2f} ms")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("trace", help="Chrome-trace JSON file (e.g. <log_dir>/trace.json)")
    parser.add_argument("--json", action="store_true", help="emit JSON instead of a table")
    args = parser.parse_args(argv)
    summary = summarize(args.trace)
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        print(format_table(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
