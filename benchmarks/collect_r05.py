"""Assemble LEARNING_r05.json: the multi-seed walker replication + the round's
additional learning runs, from their TensorBoard event files.

Usage::

    python benchmarks/collect_r05.py out.json

Run directories are discovered under ``logs/``; seeds/tasks are read from each
run's ``config.yaml``.  Reruns are safe — the newest version_N of each run wins.
"""

from __future__ import annotations

import glob
import json
import os
import sys

import yaml


def read_run(version_dir: str) -> dict:
    from tensorboard.backend.event_processing.event_accumulator import EventAccumulator

    ea = EventAccumulator(version_dir, size_guidance={"scalars": 0})
    ea.Reload()
    tags = ea.Tags()["scalars"]

    def series(tag):
        return [(s.step, round(float(s.value), 2)) for s in ea.Scalars(tag)] if tag in tags else []

    with open(os.path.join(version_dir, "config.yaml")) as f:
        cfg = yaml.safe_load(f)
    sps = [v for _, v in series("Time/sps_train")]
    steady = round(sum(sps[2:]) / max(len(sps[2:]), 1), 2) if len(sps) > 4 else (sps[-1] if sps else None)
    test_rewards = series("Test/cumulative_reward")
    return {
        "seed": cfg.get("seed"),
        "algo": cfg.get("algo", {}).get("name"),
        "env": cfg.get("env", {}).get("id"),
        "policy_steps": int(cfg.get("algo", {}).get("total_steps", 0)),
        "env_frames": int(cfg.get("algo", {}).get("total_steps", 0)) * int(cfg.get("env", {}).get("action_repeat", 1)),
        "train_reward_curve": series("Rewards/rew_avg"),
        "final_test_reward": test_rewards[-1][1] if test_rewards else None,
        "steady_sps_train_during_run": steady,
        "run_dir": version_dir,
    }


def flag_incomplete(run: dict, fraction: float = 0.9) -> dict:
    """Mark a run whose logged curve stops well short of its configured total
    steps: ``"incomplete": true`` plus an explanatory note suffix.  Complete runs
    always flush a final metric window at ~total_steps, so a last curve step
    below ``fraction * policy_steps`` means the run died/was killed early and its
    numbers must not be cited as final."""
    curve = run.get("train_reward_curve") or []
    total = int(run.get("policy_steps") or 0)
    last_step = int(curve[-1][0]) if curve else 0
    if total > 0 and last_step < fraction * total:
        run["incomplete"] = True
        suffix = (
            f". RUN INCOMPLETE: logged curve stops at policy step {last_step} of {total}"
            f"{' and there is no final test reward' if run.get('final_test_reward') is None else ''}"
            " — rerun before citing"
        )
        if suffix.strip(". ") not in (run.get("notes") or ""):
            run["notes"] = (run.get("notes") or "").rstrip(". ") + suffix
    return run


def latest_version(pattern: str):
    def version_num(path: str) -> int:
        tail = path.rstrip("/").rsplit("_", 1)[-1]
        return int(tail) if tail.isdigit() else -1

    runs = sorted(glob.glob(pattern, recursive=True), key=lambda p: (version_num(p), p))
    return runs[-1] if runs else None


def main() -> None:
    out_path = sys.argv[1] if len(sys.argv) > 1 else "LEARNING_r05.json"
    root = os.path.dirname(os.path.abspath(__file__)) + "/../logs"

    # --- walker multi-seed replication (r5 seeds; r4 seed 42 cited from LEARNING_r04)
    seeds = []
    for d in sorted(glob.glob(f"{root}/walker_r5_s*/runs/**/version_*", recursive=True)):
        try:
            seeds.append(read_run(d))
        except Exception as exc:
            print(f"skip {d}: {exc}", file=sys.stderr)
    r4 = {}
    try:
        with open(f"{root}/../LEARNING_r04.json") as f:
            r4 = json.load(f)
    except Exception:
        pass

    finals = [s["final_test_reward"] for s in seeds if s["final_test_reward"] is not None]
    if r4.get("final_test_reward") is not None:
        finals = finals + [r4["final_test_reward"]]
    walker = {
        "task": "dm_control walker_walk, pixels only (64x64x3 rgb), 400K frames",
        "algo": "dreamer_v3 (size S), buffer.device=True, 1 TPU chip",
        "protocol": "3 seeds total: r4 seed 42 (LEARNING_r04.json, greedy 866.4) + the r5 seeds below, identical config",
        "seeds_this_round": seeds,
        "r4_seed42_final_test_reward": r4.get("final_test_reward"),
        "all_seed_final_test_rewards": finals,
        "mean_final_test_reward": round(sum(finals) / len(finals), 1) if finals else None,
        "range_final_test_reward": [min(finals), max(finals)] if finals else None,
        "published_band": "DreamerV3 walker_walk ~800-900 at this frame budget (solves ~950 at 1M frames)",
        "command": "MUJOCO_GL=egl python -m sheeprl_tpu exp=dreamer_v3_dmc_walker_walk algo.total_steps=200000 buffer.device=True mesh.devices=1 metric.log_every=2000 checkpoint.every=20000 seed=<1337|5>",
        "throughput_note": "r5 seeds ran at 15-16.5 grad-steps/s e2e steady on an idle host (~2h20m per 200K-step run vs r4's 4.2h) after the PROFILE_r05 fixes",
    }

    # --- additional runs (P2E comparison, DV1/DV2 reward learning)
    commands = {
        "p2e_expl_r5": "MUJOCO_GL=egl python -m sheeprl_tpu exp=p2e_dv3_expl_dmc_cartpole_swingup_sparse buffer.device=True mesh.devices=1 seed=42",
        "p2e_fntn_r5": "MUJOCO_GL=egl python -m sheeprl_tpu exp=p2e_dv3_fntn_dmc_cartpole_swingup_sparse buffer.device=True mesh.devices=1 seed=42 checkpoint.exploration_ckpt_path=<p2e_expl_r5 ckpt_75000>",
        "dv2_cartpole_r5": "MUJOCO_GL=egl python -m sheeprl_tpu exp=dreamer_v2 env=dmc env.id=cartpole_swingup env.num_envs=4 env.action_repeat=2 env.max_episode_steps=-1 algo.total_steps=150000 algo.cnn_keys.encoder=[rgb] algo.mlp_keys.encoder=[] buffer.size=500000 buffer.checkpoint=True buffer.device=True mesh.devices=1 seed=42",
        "dv1_cartpole_r5": "MUJOCO_GL=egl python -m sheeprl_tpu exp=dreamer_v1 env=dmc env.id=cartpole_swingup env.num_envs=4 env.action_repeat=2 env.max_episode_steps=-1 algo.total_steps=150000 algo.cnn_keys.encoder=[rgb] algo.mlp_keys.encoder=[] buffer.size=500000 buffer.checkpoint=True buffer.device=True mesh.devices=1 seed=42",
    }
    notes = {
        "p2e_expl_r5": "pure-curiosity exploration: extrinsic reward LOGGED but unused by the exploration actor; its rise (to ~250 avg, zero-shot task actor 247 greedy) shows the explorer reaches the reward region on its own",
        "p2e_fntn_r5": "finetuning from the exploration checkpoint+buffer: NO zero-reward phase (first window, 8K frames, already 318 train avg) vs plain DV3's ~40K frames of zero (LEARNING_r04); greedy 804 at 200K finetuning frames vs DV3's 643 at 300K frames",
        "dv2_cartpole_r5": "clear reward learning (0 -> ~350 train avg) but below DV3-level: DV2's defaults are Atari-tuned (discrete-latent, Atari actor entropy); the reference's own DV2 results are Atari/Crafter only",
        "dv1_cartpole_r5": "DreamerV1 on its native domain (DMC pixels, the paper's setting)",
    }
    additional = []
    for name in ("p2e_expl_r5", "p2e_fntn_r5", "dv2_cartpole_r5", "dv1_cartpole_r5"):
        d = latest_version(f"{root}/{name}/runs/**/version_*")
        if d:
            try:
                run = read_run(d)
                run["label"] = name
                run["command"] = commands.get(name, "")
                run["notes"] = notes.get(name, "")
                additional.append(flag_incomplete(run))
            except Exception as exc:
                print(f"skip {name}: {exc}", file=sys.stderr)

    # Merge-preserving write: labels this script did not (re)produce — e.g. the
    # r5b runs merged by collect_r05b.py, or runs whose log dirs were cleaned —
    # are kept from the existing file instead of being silently dropped.
    out = {}
    if os.path.exists(out_path):
        try:
            with open(out_path) as f:
                out = json.load(f)
        except Exception as exc:
            print(f"ignoring unreadable {out_path}: {exc}", file=sys.stderr)
            out = {}
    produced = {r["label"] for r in additional}
    preserved = [r for r in out.get("additional_runs", []) if r.get("label") not in produced]
    out["walker_multiseed"] = walker
    out["additional_runs"] = preserved + additional
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
    slim = {
        "walker_seeds": [(s["seed"], s["final_test_reward"]) for s in seeds],
        "mean": walker["mean_final_test_reward"],
        "range": walker["range_final_test_reward"],
        "additional": [(r["label"], r["final_test_reward"]) for r in additional],
    }
    print(json.dumps(slim, indent=1))
    print(f"-> {out_path}")


if __name__ == "__main__":
    main()
