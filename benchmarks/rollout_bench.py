"""Vector-env backend benchmark: env-steps/s for SyncVectorEnv vs AsyncVectorEnv
vs EnvPool at DreamerV3 walker shapes (4 envs, 64x64x3 uint8 pixels + a small
proprio vector, 6-dim continuous actions).

The env is a dummy pixel env with a configurable simulated step cost
(``--step-ms``, default 2 ms ≈ the single-env MuJoCo+GL cost PROFILE_r05 §1
measured per DreamerV3 walker step at action_repeat 2).  On a multi-core host
the pool's concurrent workers should sustain >=2x the serial SyncVectorEnv
rate at that cost; ``--step-ms 0`` measures pure dispatch/IPC overhead instead.

Emits one JSON row per backend on stdout, shaped like the ``BENCH_*.json``
trajectory entries (``{"metric", "value", "unit", ...}``), plus a speedup row:

    python benchmarks/rollout_bench.py
    python benchmarks/rollout_bench.py --num-envs 8 --steps 500 --step-ms 5
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Callable, Dict, List, Optional

import gymnasium as gym
import numpy as np

if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sheeprl_tpu.envs.dummy import ContinuousDummyEnv  # noqa: E402


class _SimStepCost(gym.Wrapper):
    """Busy-wait a fixed per-step cost: emulates single-core MuJoCo+GL work
    (sleep() would under-represent SyncVectorEnv, which pays the cost serially
    on a real simulator whether or not the GIL is released)."""

    def __init__(self, env: gym.Env, step_ms: float):
        super().__init__(env)
        self._cost_s = step_ms / 1e3

    def step(self, action):
        if self._cost_s > 0:
            end = time.perf_counter() + self._cost_s
            while time.perf_counter() < end:
                pass
        return self.env.step(action)


def make_thunks(num_envs: int, step_ms: float, screen_size: int, ep_len: int) -> List[Callable[[], gym.Env]]:
    def thunk() -> gym.Env:
        env = ContinuousDummyEnv(image_size=(3, screen_size, screen_size), n_steps=ep_len, action_dim=6)
        return _SimStepCost(env, step_ms)

    return [thunk for _ in range(num_envs)]


def _build(backend: str, thunks, num_workers: Optional[int]):
    from gymnasium.vector import AsyncVectorEnv, AutoresetMode, SyncVectorEnv

    if backend == "sync":
        return SyncVectorEnv(thunks, autoreset_mode=AutoresetMode.SAME_STEP)
    if backend == "async":
        return AsyncVectorEnv(thunks, autoreset_mode=AutoresetMode.SAME_STEP)
    if backend == "pool":
        from sheeprl_tpu.rollout import EnvPool

        return EnvPool(thunks, num_workers=num_workers, step_timeout_s=120.0)
    raise ValueError(f"unknown backend {backend!r}")


def bench_backend(backend: str, args) -> float:
    thunks = make_thunks(args.num_envs, args.step_ms, args.screen_size, args.ep_len)
    envs = _build(backend, thunks, args.num_workers)
    try:
        envs.reset(seed=42)
        actions = np.zeros((args.num_envs, 6), dtype=np.float32)
        for _ in range(args.warmup_steps):
            envs.step(actions)
        t0 = time.perf_counter()
        for _ in range(args.steps):
            envs.step(actions)
        elapsed = time.perf_counter() - t0
    finally:
        envs.close()
    return args.steps * args.num_envs / elapsed if elapsed > 0 else float("inf")


def main(argv: Optional[List[str]] = None) -> Dict[str, float]:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--num-envs", type=int, default=4)
    parser.add_argument("--num-workers", type=int, default=None)
    parser.add_argument("--steps", type=int, default=300)
    parser.add_argument("--warmup-steps", type=int, default=10)
    parser.add_argument("--step-ms", type=float, default=2.0)
    parser.add_argument("--screen-size", type=int, default=64)
    parser.add_argument("--ep-len", type=int, default=1000)
    parser.add_argument("--backends", type=str, default="sync,async,pool")
    parser.add_argument("--json-out", type=str, default=None)
    args = parser.parse_args(argv)

    shape_note = (
        f"{args.num_envs} envs, {args.screen_size}x{args.screen_size}x3 uint8 + 10-dim proprio, "
        f"{args.step_ms:g}ms sim step, {os.cpu_count()} host CPUs"
    )
    rates: Dict[str, float] = {}
    rows = []
    for backend in [b.strip() for b in args.backends.split(",") if b.strip()]:
        rates[backend] = bench_backend(backend, args)
        rows.append(
            {
                "metric": f"rollout_env_steps_per_sec_{backend}",
                "value": round(rates[backend], 2),
                "unit": f"env-steps/s ({shape_note})",
            }
        )
    if "sync" in rates and "pool" in rates and rates["sync"] > 0:
        rows.append(
            {
                "metric": "rollout_envpool_speedup_vs_sync",
                "value": round(rates["pool"] / rates["sync"], 3),
                "unit": f"x ({shape_note})",
            }
        )
    for row in rows:
        print(json.dumps(row))
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(rows, f, indent=1)
    return rates


if __name__ == "__main__":
    main()
