#!/usr/bin/env python
"""Diff two ``BENCH_*.json`` reports and flag per-metric regressions.

Usage:
    python benchmarks/bench_compare.py BENCH_r04.json BENCH_r05.json [--threshold 0.10] [--json]
    python benchmarks/bench_compare.py --latest 2 [--strict]

A BENCH report is the collector's dict whose ``tail`` embeds one JSON object per
benchmark metric (``{"metric": ..., "value": ..., "unit": ...}``); bare
JSON/JSONL files of such rows are accepted too.  For each metric present in both
reports the relative change is computed and classified:

* throughput-like metrics (the default) regress when the value DROPS by more
  than ``--threshold``;
* latency-like metrics (name/unit contains ``ms``, ``time``, ``latency`` or
  ``seconds``) regress when the value RISES by more than ``--threshold``.

A metric present in the baseline but missing from the latest report is a
DROPPED metric — reported loudly (a silently-vanished benchmark is not a pass),
and treated like a regression under ``--strict``.

Exit code is 0 unless ``--strict`` is given and regressions (or dropped metrics)
were found — CI wires this as a non-blocking warning step
(``continue-on-error``), so a slow metric shows up in the job log without
failing the build.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

_LOWER_BETTER_HINTS = ("ms", "latency", "time", "seconds")
# Explicit direction pins beat the unit-text heuristic: every anakin_* row
# (benchmarks/anakin_bench.py), sebulba_* row (benchmarks/sebulba_bench.py),
# serve_* row (benchmarks/serve_bench.py) and precision_* row
# (benchmarks/precision_bench.py — parity/agreement fractions AND the bf16/int8
# throughputs ride the anakin_/serve_ prefixes) and fleet_* row
# (benchmarks/fleet_bench.py) is higher-better regardless of what its unit
# string mentions...
_HIGHER_BETTER_PREFIXES = ("anakin_", "sebulba_", "serve_", "precision_", "fleet_")
# ...EXCEPT the wall-clock/latency rows, which are durations: exact-name pins
# win over the prefix pins (serve_p99_ms / fleet_p99_ms are latency SLOs,
# serve_startup_seconds is the cold/warm replica start time — all regress when
# they RISE).
_LOWER_BETTER_METRICS = (
    "anakin_compile_seconds",
    "checkpoint_save_seconds",
    "fleet_p99_ms",
    "obs_fleet_overhead_pct",
    "perf_overhead_pct",
    "race_detect_overhead_pct",
    "resume_restore_seconds",
    "serve_p99_ms",
    "serve_startup_seconds",
)
# Exact-name higher-better pins (beat the unit-hint heuristic, whose "time"/
# "wall clock" words would otherwise misread these): the perf-attribution
# plane's own figures regress when they DROP — a fall in perf_mfu or
# goodput_fraction means lost utilization or lost useful-work share.
_HIGHER_BETTER_METRICS = (
    "goodput_fraction",
    "perf_mfu",
)


def extract_metrics(path: str) -> Dict[str, Tuple[float, str]]:
    """``{metric: (value, unit)}`` from a BENCH report (or bare JSON/JSONL rows)."""
    with open(path) as f:
        text = f.read()
    rows: List[dict] = []
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if isinstance(doc, dict) and "metric" in doc:
        rows = [doc]
    elif isinstance(doc, list):
        rows = [r for r in doc if isinstance(r, dict) and "metric" in r]
    elif isinstance(doc, dict):
        text = doc.get("tail", "") or ""
    if not rows:
        for line in text.splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(row, dict) and "metric" in row and "value" in row:
                rows.append(row)
    out: Dict[str, Tuple[float, str]] = {}
    for row in rows:
        try:
            out[str(row["metric"])] = (float(row["value"]), str(row.get("unit", "")))
        except (TypeError, ValueError):
            continue
    return out


def lower_is_better(metric: str, unit: str) -> bool:
    if str(metric).lower() in _LOWER_BETTER_METRICS:
        return True
    if str(metric).lower() in _HIGHER_BETTER_METRICS:
        return False
    if str(metric).lower().startswith(_HIGHER_BETTER_PREFIXES):
        return False
    blob = f"{metric} {unit}".lower()
    return any(hint in blob for hint in _LOWER_BETTER_HINTS)


def compare(base_path: str, new_path: str, threshold: float = 0.10) -> dict:
    base = extract_metrics(base_path)
    new = extract_metrics(new_path)
    rows = []
    for name in sorted(set(base) & set(new)):
        b, unit = base[name]
        n, _ = new[name]
        change = (n - b) / abs(b) if b else float("inf") if n else 0.0
        lower = lower_is_better(name, unit)
        regressed = (change > threshold) if lower else (change < -threshold)
        rows.append(
            {
                "metric": name,
                "base": b,
                "new": n,
                "change": change,
                "direction": "lower-better" if lower else "higher-better",
                "regressed": regressed,
            }
        )
    dropped = sorted(set(base) - set(new))
    return {
        "base": base_path,
        "new": new_path,
        "threshold": threshold,
        # A metric present in the baseline but ABSENT from the latest report is
        # not a pass — it means the benchmark silently stopped being measured
        # (renamed row, crashed collector, skipped env gate).  Surface it as
        # loudly as a regression; --strict fails on it.
        "only_in_base": dropped,
        "dropped_metrics": dropped,
        "only_in_new": sorted(set(new) - set(base)),
        "rows": rows,
        "regressions": [r["metric"] for r in rows if r["regressed"]],
    }


def format_table(report: dict) -> str:
    lines = [
        f"bench_compare: {os.path.basename(report['base'])} -> "
        f"{os.path.basename(report['new'])} (threshold {report['threshold'] * 100:.0f}%)"
    ]
    if not report["rows"]:
        lines.append("no common metrics found")
        return "\n".join(lines)
    headers = ("metric", "base", "new", "change", "verdict")
    table = [
        (
            r["metric"],
            f"{r['base']:.4g}",
            f"{r['new']:.4g}",
            f"{r['change'] * 100:+.1f}%",
            "REGRESSED" if r["regressed"] else "ok",
        )
        for r in report["rows"]
    ]
    widths = [max(len(h), *(len(t[i]) for t in table)) for i, h in enumerate(headers)]
    lines.append("  ".join(h.ljust(w) for h, w in zip(headers, widths)))
    lines.append("-" * (sum(widths) + 2 * (len(widths) - 1)))
    for t in table:
        lines.append("  ".join(c.ljust(w) for c, w in zip(t, widths)))
    dropped = report.get("dropped_metrics", report["only_in_base"])
    if dropped:
        lines.append(
            f"WARNING: {len(dropped)} metric(s) present in the baseline DISAPPEARED "
            "from the latest report — a silently-dropped benchmark is not a pass:"
        )
        for name in dropped:
            lines.append(f"  DROPPED: {name}")
    for name in report["only_in_new"]:
        lines.append(f"(new metric: {name})")
    if report["regressions"]:
        lines.append(f"{len(report['regressions'])} regression(s): {', '.join(report['regressions'])}")
    else:
        lines.append("no regressions")
    return "\n".join(lines)


def _latest_bench_files(n: int, root: str = ".") -> List[str]:
    files = sorted(glob.glob(os.path.join(root, "BENCH_*.json")))
    return files[-n:]


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    parser.add_argument("base", nargs="?", help="baseline BENCH_*.json")
    parser.add_argument("new", nargs="?", help="candidate BENCH_*.json")
    parser.add_argument("--latest", type=int, metavar="N", help="compare the two newest of the N latest BENCH_*.json in the CWD")
    parser.add_argument("--threshold", type=float, default=0.10, help="relative regression threshold (default 0.10)")
    parser.add_argument("--json", action="store_true", help="emit the JSON report")
    parser.add_argument(
        "--strict", action="store_true", help="exit 1 when regressions or dropped metrics are found"
    )
    args = parser.parse_args(argv)

    if args.latest:
        files = _latest_bench_files(args.latest)
        if len(files) < 2:
            print(f"bench_compare: need at least two BENCH_*.json files, found {files}")
            return 0
        base_path, new_path = files[-2], files[-1]
    elif args.base and args.new:
        base_path, new_path = args.base, args.new
    else:
        parser.error("provide two BENCH files or --latest N")

    report = compare(base_path, new_path, threshold=args.threshold)
    print(json.dumps(report, indent=1) if args.json else format_table(report))
    if report["dropped_metrics"]:
        print(
            f"bench_compare: WARNING — dropped metric(s): {', '.join(report['dropped_metrics'])}",
            file=sys.stderr,
        )
    return 1 if args.strict and (report["regressions"] or report["dropped_metrics"]) else 0


if __name__ == "__main__":
    sys.exit(main())
