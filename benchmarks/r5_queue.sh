#!/bin/bash
# Round-5 chip-job queue: run the remaining learning runs back-to-back on the one
# TPU chip, newest evidence first, and stop launching new jobs after the cutoff so
# the chip is free for the end-of-round bench.
#
# Usage: bash benchmarks/r5_queue.sh <cutoff_epoch_seconds>

set -u
cd /root/repo
CUTOFF=${1:?usage: r5_queue.sh <cutoff_epoch>}
export MUJOCO_GL=egl

run_if_time() { # name estimated_minutes command...
    local name=$1 est=$2; shift 2
    local now=$(date +%s)
    if (( now + est * 60 > CUTOFF )); then
        echo "[$name] SKIPPED: $(date -u) + ${est}m would pass cutoff" | tee -a logs/r5_queue.log
        return 1
    fi
    echo "[$name] START $(date -u)" | tee -a logs/r5_queue.log
    "$@" > "logs/${name}_stdout.log" 2>&1
    local rc=$?
    echo "[$name] END rc=$rc $(date -u)" | tee -a logs/r5_queue.log
    return 0
}

# 1. P2E-DV3 exploration on the sparse task (~150K frames).
run_if_time p2e_expl_r5 55 \
    python -m sheeprl_tpu exp=p2e_dv3_expl_dmc_cartpole_swingup_sparse \
    buffer.device=True mesh.devices=1 seed=42 \
    run_name=p2e_expl_r5 log_root=/root/repo/logs/p2e_expl_r5

# 2. P2E-DV3 finetuning from the exploration checkpoint (~200K frames).
EXPL_CKPT=$(ls -d logs/p2e_expl_r5/runs/*/*/*/version_0/checkpoints/ckpt_* 2>/dev/null | sort -V | tail -1)
if [ -n "${EXPL_CKPT:-}" ]; then
    run_if_time p2e_fntn_r5 70 \
        python -m sheeprl_tpu exp=p2e_dv3_fntn_dmc_cartpole_swingup_sparse \
        buffer.device=True mesh.devices=1 seed=42 \
        "checkpoint.exploration_ckpt_path=/root/repo/$EXPL_CKPT" \
        run_name=p2e_fntn_r5 log_root=/root/repo/logs/p2e_fntn_r5
else
    echo "[p2e_fntn_r5] SKIPPED: no exploration checkpoint found" | tee -a logs/r5_queue.log
fi

# 3. DreamerV2 reward learning on cartpole_swingup pixels (~300K frames).
run_if_time dv2_cartpole_r5 95 \
    python -m sheeprl_tpu exp=dreamer_v2 env=dmc env.id=cartpole_swingup \
    env.num_envs=4 env.action_repeat=2 env.max_episode_steps=-1 \
    algo.total_steps=150000 "algo.cnn_keys.encoder=[rgb]" "algo.mlp_keys.encoder=[]" \
    buffer.size=500000 buffer.checkpoint=True buffer.device=True mesh.devices=1 \
    metric.log_every=2000 checkpoint.every=50000 seed=42 \
    run_name=dv2_cartpole_r5 log_root=/root/repo/logs/dv2_cartpole_r5

# 4. DreamerV1 reward learning on cartpole_swingup pixels (~300K frames).
run_if_time dv1_cartpole_r5 95 \
    python -m sheeprl_tpu exp=dreamer_v1 env=dmc env.id=cartpole_swingup \
    env.num_envs=4 env.action_repeat=2 env.max_episode_steps=-1 \
    algo.total_steps=150000 "algo.cnn_keys.encoder=[rgb]" "algo.mlp_keys.encoder=[]" \
    buffer.size=500000 buffer.checkpoint=True buffer.device=True mesh.devices=1 \
    metric.log_every=2000 checkpoint.every=50000 seed=42 \
    run_name=dv1_cartpole_r5 log_root=/root/repo/logs/dv1_cartpole_r5

echo "[queue] DONE $(date -u)" | tee -a logs/r5_queue.log
