"""CI serve-smoke client driver (.github/workflows/cpu-tests.yaml "Serve smoke").

Reads the replica's ready file, streams requests from 4 closed-loop client
threads, asserts the SLO stamps are on every reply, then SIGTERMs the server
PID *while requests are in flight* — each client ends on a ``draining`` reply
or a closed channel, never a lost reply.  The workflow step then asserts the
server exited 75 with ``accepted == replied`` in its summary.

The optional third argument pins the replica's precision tier: the ready file
must carry that ``precision`` and, for a non-f32 tier, a parity stamp vs the
f32 reference with >= 0.99 greedy action agreement (howto/precision.md).

Usage::

    python benchmarks/serve_smoke_clients.py <ready_file> <server_pid> [precision]
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from pathlib import Path

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CLIENTS = 4
REPLIES_BEFORE_SIGTERM = 100


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    ready_file, server_pid = Path(argv[0]), int(argv[1])
    expected_precision = argv[2] if len(argv) > 2 else None

    import numpy as np

    from sheeprl_tpu.distributed.transport import ChannelClosed
    from sheeprl_tpu.serve.client import PolicyClient, ServerDraining, wait_for_server

    deadline = time.monotonic() + 300.0
    while not ready_file.is_file():
        if time.monotonic() > deadline:
            raise TimeoutError(f"no ready file at {ready_file}")
        time.sleep(0.2)
    ready = json.loads(ready_file.read_text())
    port = ready["port"]
    if expected_precision is not None:
        assert ready["precision"] == expected_precision, ready
        if expected_precision != "f32":
            for name, stamp in ready["parity"].items():
                assert stamp["reference"] == "f32", (name, stamp)
                assert stamp["action_agreement"] >= 0.99, (name, stamp)
            assert ready["parity"], "non-f32 replica published no parity stamp"
    wait_for_server("127.0.0.1", port)

    obs = {"state": np.zeros(4, dtype=np.float32)}  # jax_cartpole observation
    replies = [0] * CLIENTS
    stamps: list = []
    errors: list = []

    def worker(idx: int) -> None:
        try:
            with PolicyClient("127.0.0.1", port) as client:
                while True:
                    _, meta = client.act(obs, "smoke_ppo", timeout=60)
                    replies[idx] += 1
                    stamps.append(meta)
        except (ServerDraining, ChannelClosed, ConnectionError, TimeoutError, OSError):
            pass  # the replica drained out from under us: a clean ending
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,), daemon=True) for i in range(CLIENTS)]
    for t in threads:
        t.start()
    while sum(replies) < REPLIES_BEFORE_SIGTERM:
        if errors:
            raise RuntimeError(f"client failed before SIGTERM: {errors[0]}")
        time.sleep(0.01)

    os.kill(server_pid, signal.SIGTERM)  # drain begins with requests in flight
    for t in threads:
        t.join(timeout=60)
    if errors:
        raise RuntimeError(f"client failed: {errors[0]}")

    for meta in stamps:
        assert meta["p99_ms"] > 0, meta  # the rolling latency SLO stamp
        assert meta["bucket"] >= 1 and meta["infer_ms"] > 0, meta
    print(
        f"serve smoke: {sum(replies)} replies across {CLIENTS} clients, "
        f"last p99={stamps[-1]['p99_ms']:.2f}ms bucket={stamps[-1]['bucket']}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
