"""CI serve-smoke client driver (.github/workflows/cpu-tests.yaml "Serve smoke").

Reads the replica ready file(s), streams requests from 4 closed-loop
:class:`~sheeprl_tpu.serve.client.FleetClient` threads, asserts the SLO stamps
are on every reply, then SIGTERMs the server PID *while requests are in
flight* — each client ends on the fleet client exhausting its bounded retries
against the draining endpoint(s), never a lost reply.  The workflow step then
asserts the server exited 75 with ``accepted == replied`` in its summary.

The first argument accepts a comma-separated list of ready files: with more
than one, every client fails over between the endpoints (the FleetClient
rotates on ``draining``/dead-connection), so the same driver smokes a single
replica or a hand-rolled multi-replica set.

The optional third argument pins the replica's precision tier: the ready file
must carry that ``precision`` and, for a non-f32 tier, a parity stamp vs the
f32 reference with >= 0.99 greedy action agreement (howto/precision.md).

Usage::

    python benchmarks/serve_smoke_clients.py <ready_file[,ready_file...]> <server_pid> [precision]
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from pathlib import Path

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CLIENTS = 4
REPLIES_BEFORE_SIGTERM = 100


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    ready_files = [Path(p) for p in argv[0].split(",") if p]
    server_pid = int(argv[1])
    expected_precision = argv[2] if len(argv) > 2 else None

    import numpy as np

    from sheeprl_tpu.serve.client import FleetClient, wait_for_server

    endpoints = []
    for ready_file in ready_files:
        deadline = time.monotonic() + 300.0
        while not ready_file.is_file():
            if time.monotonic() > deadline:
                raise TimeoutError(f"no ready file at {ready_file}")
            time.sleep(0.2)
        ready = json.loads(ready_file.read_text())
        endpoints.append(("127.0.0.1", ready["port"]))
        if expected_precision is not None:
            assert ready["precision"] == expected_precision, ready
            if expected_precision != "f32":
                for name, stamp in ready["parity"].items():
                    assert stamp["reference"] == "f32", (name, stamp)
                    assert stamp["action_agreement"] >= 0.99, (name, stamp)
                assert ready["parity"], "non-f32 replica published no parity stamp"
    for host, port in endpoints:
        wait_for_server(host, port)

    obs = {"state": np.zeros(4, dtype=np.float32)}  # jax_cartpole observation
    replies = [0] * CLIENTS
    stamps: list = []
    errors: list = []

    def worker(idx: int) -> None:
        try:
            # Bounded retries: once every endpoint is draining/dead the act
            # raises ConnectionError quickly instead of spinning forever.
            with FleetClient(endpoints, max_attempts=4, backoff_max_s=0.5) as client:
                while True:
                    _, meta = client.act(obs, "smoke_ppo", timeout=60)
                    replies[idx] += 1
                    stamps.append(meta)
        except ConnectionError:
            pass  # the replica(s) drained out from under us: a clean ending
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,), daemon=True) for i in range(CLIENTS)]
    for t in threads:
        t.start()
    while sum(replies) < REPLIES_BEFORE_SIGTERM:
        if errors:
            raise RuntimeError(f"client failed before SIGTERM: {errors[0]}")
        time.sleep(0.01)

    os.kill(server_pid, signal.SIGTERM)  # drain begins with requests in flight
    for t in threads:
        t.join(timeout=60)
    if errors:
        raise RuntimeError(f"client failed: {errors[0]}")

    for meta in stamps:
        assert meta["p99_ms"] > 0, meta  # the rolling latency SLO stamp
        assert meta["bucket"] >= 1 and meta["infer_ms"] > 0, meta
    print(
        f"serve smoke: {sum(replies)} replies across {CLIENTS} clients "
        f"({len(endpoints)} endpoint(s)), "
        f"last p99={stamps[-1]['p99_ms']:.2f}ms bucket={stamps[-1]['bucket']}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
