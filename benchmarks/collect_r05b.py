"""Merge the round-5b learning runs (A2C, PPO-recurrent masked, DroQ, SAC-AE)
into ``LEARNING_r05.json`` ``additional_runs``.

Unlike ``collect_r05.py`` (which rebuilds the file from ``logs/``), this script
*merges*: the committed walker replication and P2E/DV1/DV2 entries are kept
as-is (their run dirs may have been cleaned), and each r5b run found under
``logs/`` is appended — replacing any earlier entry with the same label, so
reruns are safe.

Usage::

    python benchmarks/collect_r05b.py [LEARNING_r05.json]
"""

from __future__ import annotations

import json
import os
import sys

from collect_r05 import flag_incomplete, latest_version, read_run  # noqa: E402

COMMANDS = {
    "a2c_cartpole_r5": (
        "JAX_PLATFORMS=cpu python -m sheeprl_tpu exp=a2c env.id=CartPole-v1 algo.mlp_keys.encoder=[state] "
        "algo.cnn_keys.encoder=[] algo.total_steps=262144 env.num_envs=4 env.sync_env=True seed=42"
    ),
    "ppo_rec_mask_r5": (
        "JAX_PLATFORMS=cpu python -m sheeprl_tpu exp=ppo_recurrent env.id=CartPole-v1 "
        "algo.mlp_keys.encoder=[state] algo.cnn_keys.encoder=[] "
        "env.mask_velocities=True algo.total_steps=262144 env.num_envs=4 env.sync_env=True seed=42"
    ),
    "droq_cheetah_r5": (
        "MUJOCO_GL=egl python -m sheeprl_tpu exp=droq algo.total_steps=50000 "
        "algo.mlp_keys.encoder=[state] algo.cnn_keys.encoder=[] "
        "env.num_envs=4 env.sync_env=True buffer.size=100000 seed=42"
    ),
    "sac_ae_cartpole_r5": (
        "MUJOCO_GL=egl python -m sheeprl_tpu exp=sac_ae env.id=cartpole_swingup "
        "env.num_envs=4 env.sync_env=True env.action_repeat=8 env.max_episode_steps=-1 "
        "algo.total_steps=62500 algo.cnn_keys.encoder=[rgb] algo.mlp_keys.encoder=[] "
        "buffer.size=100000 buffer.checkpoint=True seed=42"
    ),
}
NOTES = {
    "a2c_cartpole_r5": (
        "A2C reward learning on CartPole-v1 states (64-unit tanh MLPs, RMSpropTF); "
        "500 is the env maximum. Host-CPU run: per-step policy calls on tiny MLPs "
        "are chip-tunnel-RTT-bound, so state-based on-policy runs stay on host"
    ),
    "ppo_rec_mask_r5": (
        "PPO-recurrent on VELOCITY-MASKED CartPole: the observation hides velocities, "
        "so above-random reward requires the LSTM to integrate position history — "
        "the recurrence is load-bearing, not decorative. Host-CPU run (see a2c note)"
    ),
    "droq_cheetah_r5": (
        "DroQ on its native HalfCheetah-v4 (gym states), replay_ratio 20 + dropout "
        "critics: the utd-20 sample-efficiency regime the paper targets; 50K env steps "
        "on the chip (the per-step 80-update scanned block amortizes the tunnel RTT)"
    ),
    "sac_ae_cartpole_r5": (
        "SAC-AE from pixels on cartpole_swingup (paper hyperparams: action_repeat 8, "
        "deterministic AE regulariser), configured for 500K env frames"
    ),
}


def main() -> None:
    out_path = sys.argv[1] if len(sys.argv) > 1 else "LEARNING_r05.json"
    root = os.path.dirname(os.path.abspath(__file__)) + "/../logs"

    with open(out_path) as f:
        out = json.load(f)
    additional = out.setdefault("additional_runs", [])

    for name in COMMANDS:
        d = latest_version(f"{root}/{name}/runs/**/version_*")
        if not d:
            print(f"no run dir for {name}", file=sys.stderr)
            continue
        try:
            run = read_run(d)
        except Exception as exc:
            print(f"skip {name}: {exc}", file=sys.stderr)
            continue
        run["label"] = name
        run["command"] = COMMANDS[name]
        run["notes"] = NOTES[name]
        # Truncated runs (curve stops short of the configured total steps) are
        # merged with "incomplete": true so their numbers are never cited as final
        # (the first sac_ae_cartpole_r5 merge shipped a 2000-of-62500-step run
        # unlabeled — advisor finding r5).
        flag_incomplete(run)
        additional[:] = [r for r in additional if r.get("label") != name]
        additional.append(run)

    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps([(r["label"], r["final_test_reward"]) for r in additional], indent=1))
    print(f"-> {out_path}")


if __name__ == "__main__":
    main()
