#!/usr/bin/env python
"""Fleet-exporter overhead: what does per-step telemetry cost the hot loop?

The fleet plane's contract (``sheeprl_tpu/obs/fleet.py``) is that per-step
bookkeeping is two dict writes under a lock — the framed TCP send happens on the
exporter's daemon thread at ``obs.fleet.interval_s`` cadence, never on the step
path.  This bench A/Bs a simulated training step loop (a calibrated ~2 ms
busy-spin standing in for a jitted update at small-model CPU scale — the WORST
case for relative overhead; real TPU steps are longer) with and without a live
exporter wired to a real in-process :class:`FleetAggregator` over loopback TCP:

    overhead_pct = (wall_with_exporter - wall_bare) / wall_bare * 100

Emits one BENCH-style JSON row, ``obs_fleet_overhead_pct`` — direction-pinned
lower-better by exact name in ``benchmarks/bench_compare.py``, acceptance
ceiling 2% (also asserted in ``tests/test_obs/test_fleet.py``).  Runs as part of
``benchmarks/sebulba_bench.py`` unless ``BENCH_OBS=0``.

Usage::

    python benchmarks/obs_overhead_bench.py [--steps 400] [--step-ms 2.0] [--repeats 3]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import tempfile
import time
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _step(work_s: float) -> int:
    """Deterministic busy-spin: the stand-in for one jitted training step."""
    deadline = time.perf_counter() + work_s
    spins = 0
    while time.perf_counter() < deadline:
        spins += 1
    return spins


def _measure(steps: int, work_s: float, exporter=None) -> float:
    t0 = time.perf_counter()
    for i in range(steps):
        _step(work_s)
        if exporter is not None:
            # Exactly what the learner loop records per consumed block.
            exporter.counter("grad_steps", i)
            exporter.counter("env_steps", i * 64)
            exporter.gauge("Sebulba/queue_depth", i % 7)
            exporter.gauge("Sebulba/param_staleness_steps", i % 3)
    return time.perf_counter() - t0


def run_bench(steps: int = 400, step_ms: float = 2.0, repeats: int = 3) -> dict:
    from sheeprl_tpu.distributed.transport import connect
    from sheeprl_tpu.obs.fleet import FleetAggregator, FleetExporter

    work_s = step_ms / 1000.0
    tmp = tempfile.mkdtemp(prefix="obs_overhead_bench_")
    agg = FleetAggregator(tmp)
    host, port = agg.address.rsplit(":", 1)
    tags = {
        "role": "learner",
        "actor_id": 0,
        "generation": 0,
        "host": socket.gethostname(),
        "pid": os.getpid(),
        "trace_id": "bench",
    }
    exporter = FleetExporter(tags, channel=connect(host, int(port), timeout_s=5.0), interval_s=0.25)
    try:
        bare: List[float] = []
        with_exp: List[float] = []
        _measure(steps // 4, work_s)  # warmup: timer + allocator settle
        for _ in range(repeats):  # interleave so drift hits both arms equally
            bare.append(_measure(steps, work_s))
            with_exp.append(_measure(steps, work_s, exporter))
        overhead = (min(with_exp) - min(bare)) / min(bare) * 100.0
    finally:
        exporter.close()
        agg.close()
    return {
        "metric": "obs_fleet_overhead_pct",
        "value": round(max(overhead, 0.0), 3),
        "unit": (
            f"% step-time overhead (lower is better; {steps} x {step_ms}ms simulated "
            f"steps, best-of-{repeats}, live aggregator over loopback)"
        ),
        "rows_exported": agg.rows_written,
    }


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--steps", type=int, default=int(os.environ.get("BENCH_OBS_STEPS", "400")))
    parser.add_argument(
        "--step-ms", type=float, default=float(os.environ.get("BENCH_OBS_STEP_MS", "2.0"))
    )
    parser.add_argument("--repeats", type=int, default=3)
    args = parser.parse_args(argv)
    print(json.dumps(run_bench(steps=args.steps, step_ms=args.step_ms, repeats=args.repeats)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
