"""Microbench: the RSSM recurrent step at DreamerV3 size-S shapes (VERDICT r4 #4).

Compares three implementations of the 64-step training-shape scan
(forward + backward, B=16, K=1024, H=512 — the T=64 world-model unroll's exact
per-step shapes) on the current backend:

  a. ``xla``        — plain XLA step (matmul + LN + gates, ``reference_gru_step``);
  b. ``post_fused`` — XLA matmul + Pallas post-matmul LN/gate kernel (``ops/gru.py``);
  c. ``full_fused`` — one VMEM-resident Pallas kernel incl. the matmul
                      (``ops/rssm_step.py``).

Prints one JSON line with ms/scan and steps/s for each, plus the implied ceiling:
the per-step latency floor x 64 steps is the minimum wall-clock of the world-model
scan regardless of what the rest of the train step does.

Usage: ``python benchmarks/fused_step_bench.py [T] [B]`` (defaults 64, 16).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))  # repo root, after site pkgs resolve

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    T = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    B = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    K_IN, H = 512, 512  # size S: input projection width and recurrent size
    K = K_IN + H

    from sheeprl_tpu.ops.gru import fused_layernorm_gru
    from sheeprl_tpu.ops.rssm_step import fused_gru_step, reference_gru_step

    rng = np.random.default_rng(0)
    dtype = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    xs = jnp.asarray(rng.normal(size=(T, B, K_IN)).astype(np.float32), dtype)
    w = jnp.asarray(rng.normal(size=(K, 3 * H)).astype(np.float32) * 0.02, dtype)
    gamma = jnp.ones((3 * H,), jnp.float32)
    beta = jnp.zeros((3 * H,), jnp.float32)

    def scan_loss(step_fn):
        def run(w_):
            def step(h, x):
                h2 = step_fn(jnp.concatenate([x, h.astype(dtype)], -1), h, w_, gamma, beta)
                return h2.astype(jnp.float32), h2

            _, hs = jax.lax.scan(step, jnp.zeros((B, H)), xs)
            return jnp.sum(hs.astype(jnp.float32) ** 2)

        return jax.jit(jax.grad(run))

    def post_fused_step(xh, h, w_, gamma_, beta_):
        proj = jnp.dot(xh, w_, preferred_element_type=jnp.float32)
        return fused_layernorm_gru(proj, h.astype(jnp.float32), gamma_, beta_)

    results = {}
    for name, fn in (
        ("xla", reference_gru_step),
        ("post_fused", post_fused_step),
        ("full_fused", fused_gru_step),
    ):
        f = scan_loss(fn)
        g = f(w)
        jax.device_get(g)  # full sync (block_until_ready is unreliable over axon)
        n = 20
        t0 = time.perf_counter()
        for _ in range(n):
            g = f(w)
        jax.device_get(g)
        ms = (time.perf_counter() - t0) / n * 1000.0
        results[name] = {"ms_per_scan": round(ms, 3), "us_per_step": round(ms * 1000.0 / T, 1)}

    base = results["xla"]["ms_per_scan"]
    for name in results:
        results[name]["speedup_vs_xla"] = round(base / results[name]["ms_per_scan"], 3)
    print(
        json.dumps(
            {
                "bench": "rssm_step_scan_fwd_bwd",
                "backend": jax.default_backend(),
                "shape": {"T": T, "B": B, "K": K, "H": H, "dtype": str(dtype.__name__)},
                **results,
            }
        )
    )


if __name__ == "__main__":
    main()
