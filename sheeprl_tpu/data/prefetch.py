"""Async host→device batch prefetch (SURVEY §7 "hard parts": host env stepping can
starve the TPU; double-buffer the sampled batches so the device never waits on
host-side replay sampling + transfer).

``AsyncBatchPrefetcher`` keeps ONE sample request in flight on a worker thread: while
the accelerator executes the current block of gradient steps, the worker draws the next
``n`` gradient steps' worth of batches and ships them to the device.
``make_replay_prefetcher``'s sampler produces a LIST of per-step ``[T, B, ...]`` batch
dicts (each ``device_put`` separately, so step g executes while slice g+1 transfers);
``get(n)`` returns the staged block when the staged COUNT covers ``n`` (slicing off the
extra steps) and immediately queues the next request.

Coherency: the worker samples under ``self.lock``; training loops must wrap their
``rb.add(...)`` calls with the same lock so the worker never reads a row mid-write.
The staged block is sampled one iteration early — with replay buffers of ≥10⁴
transitions the one-step staleness of the sampling distribution is negligible (the
data itself is identical; only the newest iteration's rows are excluded).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Optional


class AsyncBatchPrefetcher:
    def __init__(self, sample_fn: Callable[[int], Any], slice_fn: Optional[Callable[[Any, int], Any]] = None):
        self.lock = threading.Lock()
        self._sample_fn = sample_fn
        # How to cut a staged block down to n steps (for an oscillating Ratio).
        # Default: list prefix / leading-axis slice of every leaf.  Loops whose block
        # mixes per-step and per-block parts (e.g. DroQ's critic block + one actor
        # batch) pass their own.
        self._slice_fn = slice_fn
        self._req: "queue.Queue[Optional[int]]" = queue.Queue(maxsize=1)
        self._res: "queue.Queue[Any]" = queue.Queue(maxsize=1)
        self._pending_n: Optional[int] = None
        self._thread = threading.Thread(target=self._work, name="batch-prefetch", daemon=True)
        self._thread.start()

    def _work(self) -> None:
        while True:
            n = self._req.get()
            if n is None:
                return
            try:
                with self.lock:
                    block = self._sample_fn(n)
            except Exception as exc:  # surfaced on the consumer's next get()
                block = exc
            self._res.put(block)

    def get(self, n: int, stage_next: bool = True) -> Any:
        """Return an ``n``-sample block; staged if the in-flight request matches,
        sampled synchronously otherwise (e.g. when the Ratio governor changes n).
        Pass ``stage_next=False`` on the final iteration so no discarded block is
        sampled/transferred after the run ends."""
        if self._pending_n is not None and self._pending_n >= n:
            staged_n = self._pending_n
            block = self._res.get()
            self._pending_n = None
            if isinstance(block, Exception):
                raise block
            if staged_n > n:
                # Oscillating Ratio (e.g. 1,2,1,2,...): reuse the staged block's
                # first n samples instead of discarding the whole transfer.
                if self._slice_fn is not None:
                    block = self._slice_fn(block, n)
                elif isinstance(block, list):
                    block = block[:n]
                else:
                    import jax

                    block = jax.tree.map(lambda x: x[:n], block)
        else:
            if self._pending_n is not None:
                self._res.get()  # drain the too-small in-flight block
                self._pending_n = None
            with self.lock:
                block = self._sample_fn(n)
        if stage_next:
            self._req.put(n)
            self._pending_n = n
        return block

    def close(self) -> None:
        if self._pending_n is not None:
            try:
                self._res.get(timeout=10)
            except queue.Empty:
                pass
            self._pending_n = None
        try:
            self._req.put_nowait(None)
        except queue.Full:
            pass


def maybe_prefetcher(cfg, sample_fn: Callable[[int], Any], slice_fn=None, enabled: bool = True):
    """The SAC-family loops' prefetcher gate: ``(prefetcher_or_None, rb_lock)``.

    ``enabled=False`` (the device-resident transition ring is active — see
    ``data/device_buffer.py``) skips the prefetcher entirely: sampling happens
    inside the fused train block, so there is nothing to stage host-side.  Loops
    must still take the returned lock around ``rb.add`` (a null context when no
    worker thread exists)."""
    import contextlib

    if enabled and cfg.algo.get("async_prefetch", True):
        prefetcher = AsyncBatchPrefetcher(sample_fn, slice_fn=slice_fn)
        return prefetcher, prefetcher.lock
    return None, contextlib.nullcontext()


def make_replay_prefetcher(rb, ctx, cfg, batch_size: int, sequence_length: int):
    """The training loops' standard setup: a sampler closure drawing ``n`` gradient
    steps' worth of ``[T, B]`` batches, wrapped in a prefetcher when
    ``algo.async_prefetch`` is on.  Returns ``(prefetcher_or_None, rb_lock,
    sample_block)`` — loops must take ``rb_lock`` around every ``rb.add``.

    The block is shipped as a LIST of per-step batches, each ``device_put``
    separately: the first gradient step can launch as soon as its own slice lands
    instead of waiting for the whole ``[n, T, B]`` transfer (the async dispatch of
    step g then overlaps the transfer of slice g+1)."""
    import contextlib

    import numpy as np

    def sample_block(n: int):
        block = rb.sample(batch_size, sequence_length=sequence_length, n_samples=n)
        out = []
        for g in range(n):
            step = {k: np.ascontiguousarray(v[g]) for k, v in block.items()}
            # [T, B, ...] slices, batch axis 1 over the data mesh; multi-process
            # ranks contribute their local chunk of the global batch (put_batch
            # assembles the global array — see MeshContext.put_batch).
            out.append(ctx.put_batch(step, batch_axis=1))
        return out

    if cfg.algo.get("async_prefetch", True):
        prefetcher = AsyncBatchPrefetcher(sample_block)
        return prefetcher, prefetcher.lock, sample_block
    return None, contextlib.nullcontext(), sample_block
