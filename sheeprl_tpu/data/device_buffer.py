"""Device-resident replay mirror: keep the replay data in HBM, ship only indices.

The reference samples on the host and ships every batch to the accelerator
(``/root/reference/sheeprl/data/buffers.py`` + ``sample_tensors``).  At DreamerV3's
Atari shapes that is ~12 MB per gradient step of mostly-redundant pixels, and on a
remote TPU the host→device link (not the MXU) becomes the training bottleneck.

TPU-native answer: the replay rows live ON the device.

* every row appended to the host buffer is also scattered into a ``[capacity,
  n_envs, ...]`` device ring via a DONATED jitted update (in-place, no copy of the
  ring) — ~12 KB/env/step uplink instead of ~12 MB/grad-step;
* sampling draws only (env, start) INDEX pairs on the host (same validity logic as
  the host buffer) and gathers the ``[T, B]`` batch inside the jitted train block —
  an HBM gather, three orders of magnitude faster than the tunnel;
* the host buffer stays the source of truth for checkpoint/resume; ``load_from``
  rebuilds the mirror after a resume.

**Data parallelism**: with ``mesh.data > 1`` the ring's env axis is sharded over the
``data`` mesh axis — each data shard owns a contiguous block of envs' rows.  Index
sampling is per-shard (batch element ``j`` draws only from the envs its shard owns),
so the in-jit gather is purely shard-local via ``shard_map``: no collective touches
the ring, and the gathered ``[T, B]`` batch comes out sharded over ``data`` exactly
like the host path's ``put_batch(..., batch_axis=1)`` batches.  Scatter writes are
likewise shard-local (full-env masked updates).  This is what lets the flagship fast
path compose with DP on a multi-chip host (the v4-8 north star) instead of falling
back to host sampling.

**Multi-process** (v4-32-class): each process keeps a LOCAL ring over its own
devices' slice of the ``data`` axis (scatter stays process-local and collective-free
— episode ends, and therefore terminal-row scatters, happen at process-divergent
iterations), and the SPMD train block sees a zero-copy GLOBAL view assembled with
``jax.make_array_from_single_device_arrays``.  Index arrays are likewise per-process
sampled and globalized with ``jax.make_array_from_process_local_data`` — value
divergence lives in array *shards*, which is exactly what GSPMD permits, never in
replicated scalars.  See :class:`MultiProcessDeviceReplayMirror`.

The mirror requires the whole buffer to fit in HBM next to the model: ~1.2 GB for
the 100K-transition Atari-100K config — comfortable on any current TPU.  Enabled by
``buffer.device: True`` (the flagship default); loops fall back to host sampling +
prefetch when disabled.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from sheeprl_tpu.parallel.mesh import shard_map_compat


def gather_sequences(
    mirror: Dict[str, jax.Array],
    envs: jax.Array,
    starts: jax.Array,
    sequence_length: int,
    row_shapes: Dict[str, Sequence[int]],
) -> Dict[str, jax.Array]:
    """In-jit gather of ``[T, B, ...]`` sequences from ``[n_envs, cap, flat]`` rings.

    ``envs``/``starts``: ``[B]`` int32; rows wrap modulo capacity (the host-side
    index sampling guarantees wrapped sequences never cross the write cursor).
    ``row_shapes`` restores each key's logical per-row shape after the gather
    (rows are stored FLAT — see :class:`DeviceReplayMirror` for the layout
    rationale).  Inside ``shard_map`` the same code runs on the shard-local ring
    with shard-local env ids.
    """
    out = {}
    for k, buf in mirror.items():
        cap = buf.shape[1]
        t_idx = (starts[:, None] + jnp.arange(sequence_length, dtype=starts.dtype)) % cap  # [B, T]
        picked = buf[envs[:, None], t_idx]  # [B, T, flat]
        seq = jnp.swapaxes(picked, 0, 1)  # [T, B, flat]
        out[k] = seq.reshape(sequence_length, envs.shape[0], *row_shapes[k])
    return out


def _masked_row_update(
    bufs: Dict[str, jax.Array], rows: Dict[str, jax.Array], positions: jax.Array, mask: jax.Array
) -> Dict[str, jax.Array]:
    """``bufs[k][e, positions[e]] = rows[k][e]`` for every env ``e`` with
    ``mask[e]``.  Unmasked envs are skipped by aiming their update OUT OF BOUNDS
    (``mode="drop"``) — a PURE scatter, never reading the ring: a read-blend-write
    formulation defeats the donation aliasing and doubles the ring's HBM footprint
    at compile time.  One aligned update per env also keeps the scatter local to
    the env shard under ``shard_map`` — a sparse scatter over an env subset would
    make GSPMD reshard the ring."""
    out = {}
    for k, buf in bufs.items():
        cap = buf.shape[1]
        env_ar = jnp.arange(buf.shape[0], dtype=positions.dtype)
        pos = jnp.where(mask, positions, cap)  # cap = out of bounds -> dropped
        out[k] = buf.at[env_ar, pos].set(rows[k], mode="drop")
    return out


class DeviceReplayMirror:
    """Device ring mirroring an ``EnvIndependentReplayBuffer``'s rows.

    ``specs``: ``{key: (shape, dtype)}`` per-row (no leading axes).  All write
    positions are tracked by the caller (the host buffer's per-env cursors).

    **Storage layout** (TPU-critical): rows are stored FLAT and env-leading —
    ``[n_envs, capacity, prod(shape)]``.  TPU arrays are tiled on their last two
    dims ((8,128) f32 / (32,128) u8); the naive ``[cap, n_envs, C, H, W]`` layout
    pads 64-wide pixel rows 2× and ``[cap, n_envs, 1]`` scalar rings up to 256×,
    which blows a 6 GB Atari-scale ring past chip HBM at compile time.  With the
    flat layout the last two dims are ``(capacity, flat)`` — both large and
    tile-aligned, ~zero padding.  Gathers reshape back to the logical row shape
    in-jit (free).

    ``mesh``/``dp``: when ``dp > 1`` the leading env axis is sharded over the
    mesh's ``data`` axis (``n_envs % dp == 0`` required); scatter and gather run
    shard-local via ``shard_map``.
    """

    def __init__(
        self,
        capacity: int,
        n_envs: int,
        specs: Dict[str, Tuple[Sequence[int], Any]],
        mesh=None,
        dp: int = 1,
    ):
        self.capacity = int(capacity)
        self.n_envs = int(n_envs)
        self.specs = dict(specs)
        self.dp = int(dp) if mesh is not None else 1
        self.mesh = mesh if self.dp > 1 else None
        if self.dp > 1 and self.n_envs % self.dp != 0:
            raise ValueError(
                f"the data axis ({dp}) must divide n_envs={n_envs} for an env-sharded mirror"
            )
        self.env_sharding = NamedSharding(self.mesh, P("data")) if self.dp > 1 else None
        self._flat = {k: int(np.prod(shape)) for k, (shape, dtype) in specs.items()}
        self._row_shapes = {k: tuple(shape) for k, (shape, dtype) in specs.items()}
        # rings are placed straight into their final (possibly env-sharded) layout
        # from host zeros — building them on-device first would transiently
        # allocate the full unsharded ring on device 0
        self.arrays: Dict[str, jax.Array] = {
            k: self._device(np.zeros((self.n_envs, self.capacity, self._flat[k]), np.dtype(dtype)))
            for k, (shape, dtype) in specs.items()
        }
        self._scatter = self._make_scatter()

    def _device(self, x):
        # always commits to device: a host ndarray left in ``arrays`` would be
        # re-uploaded by every subsequent jitted dispatch
        return jax.device_put(x, self.env_sharding) if self.env_sharding is not None else jax.device_put(x)

    def _make_scatter(self):
        if self.dp <= 1:
            return jax.jit(_masked_row_update, donate_argnums=(0,))
        fn = shard_map_compat(
            _masked_row_update,
            mesh=self.mesh,
            in_specs=(P("data"), P("data"), P("data"), P("data")),
            out_specs=P("data"),
        )
        return jax.jit(fn, donate_argnums=(0,))

    @property
    def nbytes(self) -> int:
        return sum(int(np.prod(a.shape)) * a.dtype.itemsize for a in self.arrays.values())

    def add(self, data: Dict[str, np.ndarray], envs: Sequence[int], positions: Sequence[int]) -> None:
        """Scatter one row per selected env: ``data[k]`` is ``[1, len(envs), ...]``
        (the loops' step_data layout); ``positions[i]`` is env ``envs[i]``'s write
        cursor BEFORE the host add.  The update ships a full ``[n_envs]``-aligned
        row block with a write mask (static shapes, shard-local under dp>1);
        unselected envs are masked no-ops.  Shipping the full block costs host
        memcpy + uplink for every env even on subset writes — the right trade at
        current ``n_envs`` (one static scatter program); a compacted per-bucket
        scatter only pays off if ``n_envs`` grows well past the env-farm sizes
        the presets use."""
        env_sel = np.asarray(envs, np.intp)
        mask = np.zeros(self.n_envs, bool)
        mask[env_sel] = True
        pos_arr = np.zeros(self.n_envs, np.int32)
        pos_arr[env_sel] = np.asarray(positions, np.int64) % self.capacity
        row_tree = {}
        for k in self.arrays:
            _, dtype = self.specs[k]
            rows = np.zeros((self.n_envs, self._flat[k]), dtype)
            rows[env_sel] = np.asarray(data[k])[0].reshape(len(env_sel), self._flat[k])
            row_tree[k] = rows
        self.arrays = self._scatter(self.arrays, row_tree, pos_arr, mask)

    def load_from(self, host_rb) -> None:
        """Rebuild the mirror from an ``EnvIndependentReplayBuffer`` (resume path):
        one bulk transfer per key, placed with the mirror's sharding."""
        for k in self.arrays:
            host = np.zeros(self.arrays[k].shape, self.specs[k][1])
            for e, sub in enumerate(host_rb.buffer):
                arr = np.asarray(sub._buf[k])  # [cap, 1, ...]
                rows = min(arr.shape[0], self.capacity)
                host[e, :rows] = arr[:rows, 0].reshape(rows, self._flat[k])
            self.arrays[k] = self._device(host)

    def load_from_dense(self, host_arrays: Dict[str, np.ndarray]) -> None:
        """Rebuild from dense ``[cap, n_envs, ...]`` host arrays — the resume path
        for loops built on the plain :class:`~sheeprl_tpu.data.buffers.ReplayBuffer`
        (SAC-AE), whose storage is already mirror-shaped."""
        for k in self.arrays:
            src = np.asarray(host_arrays[k])
            rows = min(src.shape[0], self.capacity)
            host = np.zeros(self.arrays[k].shape, self.specs[k][1])
            host[:, :rows] = np.moveaxis(src[:rows].reshape(rows, self.n_envs, self._flat[k]), 0, 1)
            self.arrays[k] = self._device(host)

    def make_gather_fn(self, sequence_length: int, out_sharding=None):
        """The in-jit batch gather for :class:`~sheeprl_tpu.utils.blocks.
        IndexedBlockDispatcher`.  ``dp > 1``: shard-local gather via ``shard_map``
        — batch element ``j`` lives on the shard owning env ``envs[j]`` (the
        sharded sampler guarantees the alignment), and global env ids reduce to
        local ones by ``% E_local`` because each shard owns a contiguous env
        block.  Output ``[T, B, ...]`` is sharded over ``data`` on the batch axis,
        identical to the host path's ``put_batch(..., batch_axis=1)``.

        ``out_sharding``: optional ``[T, B, ...]`` batch sharding of the CONSUMING
        train step, applied to every gathered leaf via ``with_sharding_constraint``.
        Needed when the gather mesh is not the training mesh (e.g. the pure-DP
        mirror mesh feeding a DP×TP train step): the gathered obs batch otherwise
        carries the mirror's sharding into the train program as a constant, and
        GSPMD only discovers the mismatch deep inside the BACKWARD pass (the obs
        target of the reconstruction loss), where it logs an `[SPMD] Involuntary
        full rematerialization` and replicates the tensor as a last resort.  An
        explicit constraint at the gather boundary turns that into one clean
        forward reshard instead."""
        shapes = self._row_shapes
        gather_mesh = self._gather_mesh()

        def constrain(tree):
            if out_sharding is None:
                return tree
            return jax.tree.map(lambda x: jax.lax.with_sharding_constraint(x, out_sharding), tree)

        if gather_mesh is None:
            return lambda m, e, s: constrain(gather_sequences(m, e, s, sequence_length, row_shapes=shapes))
        # envs per shard — same count locally and globally (contiguous env blocks),
        # so global env ids reduce to shard-local rows by the same modulus.
        e_local = self.n_envs // max(self.dp, 1)

        def local_gather(mirror, envs, starts):
            return gather_sequences(mirror, envs % e_local, starts, sequence_length, row_shapes=shapes)

        sharded_gather = shard_map_compat(
            local_gather,
            mesh=gather_mesh,
            in_specs=(P("data"), P("data"), P("data")),
            out_specs=P(None, "data"),
        )
        return lambda m, e, s: constrain(sharded_gather(m, e, s))

    def _gather_mesh(self):
        """Mesh the batch gather shard_maps over (None = unsharded single-device
        gather).  The multi-process subclass returns the GLOBAL mesh here while
        scatters stay on the local one."""
        return self.mesh if self.dp > 1 else None

    def make_transition_gather_fn(self):
        """In-jit ``[n, B]`` transition-row gather (SAC-AE's batch shape): returns
        ``closure(mirror_arrays, idxs, envs) -> {key: [n, B, *row_shape]}``.
        Single-chip (the transition mirror is not sharded)."""
        shapes = self._row_shapes

        def gather(mirror, idxs, envs):
            out = {}
            for k, buf in mirror.items():
                picked = buf[envs, idxs]  # [n, B, flat]
                out[k] = picked.reshape(*idxs.shape, *shapes[k])
            return out

        return gather

    def host_rows(self, key: str) -> np.ndarray:
        """Fetch ring ``key`` as ``[cap, n_envs, *row_shape]`` numpy (test/debug
        accessor for the logical layout)."""
        arr = np.asarray(jax.device_get(self.arrays[key]))  # [n_envs, cap, flat]
        return np.moveaxis(arr, 0, 1).reshape(self.capacity, self.n_envs, *self._row_shapes[key])


STAMP_KEY = "_stamp"

#: ring keys eligible for reduced-precision storage (buffer.store_dtype): the
#: wide observation planes.  Actions/rewards/dones are a rounding error of the
#: ring's HBM footprint and stay at their declared dtype.
STORE_DTYPE_KEYS = ("obs", "next_obs")


def resolve_store_dtype(spec) -> Optional[Any]:
    """Map ``buffer.store_dtype`` (``null`` | ``f32`` | ``bf16``) to a dtype, or
    ``None`` for full-precision storage."""
    if spec is None:
        return None
    key = str(spec).lower()
    if key in ("", "none", "null", "f32", "fp32", "float32"):
        return None
    if key in ("bf16", "bfloat16"):
        return jnp.bfloat16
    raise ValueError(f"Unknown buffer.store_dtype {spec!r}; expected null, f32 or bf16")


class DeviceTransitionRing(DeviceReplayMirror):
    """Device-resident uniform-replay ring for FLAT transition batches — the SAC
    family's (sac / sac_decoupled / sac_ae / droq) analogue of the Dreamer loops'
    sequence mirror.

    Differences from the base mirror:

    * rows are whole transitions (obs / next_obs / action / reward / done), so
      sampling is a ``[B]`` row gather, not a ``[T, B]`` sequence gather;
    * index sampling happens **inside the jit** from the train block's carried PRNG
      key (:meth:`sample_indices` / :meth:`make_sample_gather`) — the host ships
      only the ``filled`` row count, so a whole UTD block of gradient steps runs as
      ONE dispatch with zero per-step host work;
    * every scatter also stamps the written rows with the buffer's cumulative
      added-row counter (``STAMP_KEY`` ring), so ``Health/replay_age_{mean,max}``
      are computed in-jit and ride the block's metrics pytree — the host-side
      ``sample_age_metrics`` path never runs on the device path.

    Single-chip by design (the flat ring is not ``shard_map``'d); the shared
    ``device_replay_enabled(..., allow_dp=False)`` gate falls back to host sampling
    under data parallelism or multi-process meshes.

    ``store_dtype`` (``buffer.store_dtype``): optional reduced-precision storage
    for the float observation planes (``obs``/``next_obs``) — bf16 halves the
    ring's HBM footprint; sampled batches cast back to the declared dtype
    INSIDE the jit (one fused convert on the gathered rows, not on the ring).
    """

    def __init__(
        self,
        capacity: int,
        n_envs: int,
        specs: Dict[str, Tuple[Sequence[int], Any]],
        store_dtype: Optional[Any] = None,
    ):
        specs = dict(specs)
        if STAMP_KEY in specs:
            raise ValueError(f"spec key {STAMP_KEY!r} is reserved for the ring's write stamps")
        self._batch_keys = tuple(specs)
        # Sampled batches come back at the key's DECLARED dtype; only the ring
        # storage (and the scan writer's cast) uses store_dtype.
        self._sample_cast: Dict[str, Any] = {}
        if store_dtype is not None:
            for k in STORE_DTYPE_KEYS:
                if k in specs and jnp.issubdtype(jnp.dtype(specs[k][1]), jnp.floating):
                    self._sample_cast[k] = specs[k][1]
                    specs[k] = (specs[k][0], store_dtype)
        self.store_dtype = store_dtype
        specs[STAMP_KEY] = ((1,), jnp.int32)
        super().__init__(capacity, n_envs, specs)

    def add_step(self, data: Dict[str, np.ndarray], position: int, rows_added: int) -> None:
        """Scatter one transition row for EVERY env at ring slot ``position`` (the
        host buffer's write cursor BEFORE its own add), donated in-place.
        ``data[k]`` is ``[1, n_envs, ...]`` (the loops' step_data layout);
        ``rows_added`` is the host buffer's cumulative added-row counter BEFORE the
        add — it becomes the written rows' staleness stamp."""
        pos = np.full(self.n_envs, int(position) % self.capacity, np.int32)
        mask = np.ones(self.n_envs, bool)
        rows = {}
        for k in self._batch_keys:
            rows[k] = np.ascontiguousarray(
                np.asarray(data[k])[0].reshape(self.n_envs, self._flat[k]),
                dtype=np.dtype(self.specs[k][1]),
            )
        rows[STAMP_KEY] = np.full((self.n_envs, 1), int(rows_added), np.int32)
        self.arrays = self._scatter(self.arrays, rows, pos, mask)

    def load_from_transitions(self, host_arrays: Dict[str, np.ndarray], stamps: Optional[np.ndarray] = None) -> None:
        """Rebuild from dense ``[cap, n_envs, ...]`` host arrays (resume path:
        the ``ReplayBuffer`` storage is already ring-shaped).  ``stamps`` is the
        host buffer's per-row stamp vector (``ReplayBuffer.row_stamps``), shared
        across envs — restores sensible ``Health/replay_age_*`` after a resume."""
        for k in self._batch_keys:
            src = np.asarray(host_arrays[k])
            rows = min(src.shape[0], self.capacity)
            host = np.zeros(self.arrays[k].shape, self.specs[k][1])
            host[:, :rows] = np.moveaxis(src[:rows].reshape(rows, self.n_envs, self._flat[k]), 0, 1)
            self.arrays[k] = self._device(host)
        st = np.zeros(self.arrays[STAMP_KEY].shape, np.int32)
        if stamps is not None:
            rows = min(len(stamps), self.capacity)
            st[:, :rows, 0] = np.asarray(stamps[:rows], np.int64)
        self.arrays[STAMP_KEY] = self._device(st)

    def population_arrays(self, size: int) -> Dict[str, jax.Array]:
        """Fresh ring arrays with a LEADING MEMBER AXIS — ``[size, n_envs, cap,
        flat]`` zeros per key — for the population Anakin engine
        (``engine/population.py``): K independent members' replay rings carried
        through one fused scan.  Built directly at the stacked shape (stacking
        K copies of ``self.arrays`` would transiently allocate K extra rings).
        :meth:`make_scan_writer` / :meth:`make_sample_gather` operate on one
        member's slice, so the engine's member transform (``lax.map`` /
        ``vmap``) applies them across the axis unchanged."""
        return {
            k: self._device(
                np.zeros((int(size), self.n_envs, self.capacity, self._flat[k]), np.dtype(self.specs[k][1]))
            )
            for k in self.arrays
        }

    def make_scan_writer(self):
        """Pure in-scan analogue of :meth:`add_step`, for loops that carry the ring
        arrays THROUGH a fused scan instead of scattering from host (the Anakin
        engine, ``sheeprl_tpu/engine/anakin.py``): ``write(arrays, rows,
        rows_added) -> arrays`` writes one transition row for every env at the
        (traced) slot ``rows_added % capacity`` and stamps the rows with
        ``rows_added`` so ``Health/replay_age_*`` keep working off the same
        :meth:`make_sample_gather`.  ``rows[k]`` is ``[n_envs, *row_shape]``;
        ``rows_added`` is the cumulative added-row counter BEFORE the write."""
        batch_keys = self._batch_keys
        flat = self._flat
        specs = self.specs
        cap = self.capacity
        n_envs = self.n_envs

        def write(arrays, rows, rows_added):
            pos = jnp.mod(jnp.asarray(rows_added, jnp.int32), cap)
            out = dict(arrays)
            for k in batch_keys:
                row = rows[k].reshape(n_envs, flat[k]).astype(specs[k][1])
                out[k] = arrays[k].at[:, pos].set(row)
            stamp = jnp.full((n_envs, 1), 0, jnp.int32) + jnp.asarray(rows_added, jnp.int32)
            out[STAMP_KEY] = arrays[STAMP_KEY].at[:, pos].set(stamp)
            return out

        return write

    def sample_indices(self, filled, key, batch_size: int):
        """The exact in-jit uniform index draw the fused train blocks run: ``[B]``
        (env, row) int32 pairs, rows uniform over ``[0, filled)`` and envs uniform
        over ``[0, n_envs)`` — the same distribution as the host buffer's
        ``sample()`` (jittable; deterministic under a fixed key)."""
        k_row, k_env = jax.random.split(key)
        rows = jax.random.randint(k_row, (batch_size,), 0, jnp.maximum(filled, 1), dtype=jnp.int32)
        envs = jax.random.randint(k_env, (batch_size,), 0, self.n_envs, dtype=jnp.int32)
        return envs, rows

    def make_sample_gather(self, batch_size: int):
        """``closure(arrays, filled, rows_added, key) -> (batch, age_metrics)``:
        in-jit uniform sampling + HBM row gather + staleness stats, for use inside
        a scanned train block.  ``batch[k]`` is ``[B, *row_shape]``."""
        shapes = {k: self._row_shapes[k] for k in self._batch_keys}
        batch_keys = self._batch_keys
        sample_cast = dict(self._sample_cast)

        def sample_gather(arrays, filled, rows_added, key):
            envs, rows = self.sample_indices(filled, key, batch_size)
            batch = {}
            for k in batch_keys:
                picked = arrays[k][envs, rows]  # [B, flat]
                if k in sample_cast:  # store_dtype plane: cast the BATCH, not the ring
                    picked = picked.astype(sample_cast[k])
                batch[k] = picked.reshape(batch_size, *shapes[k])
            ages = (rows_added - 1) - arrays[STAMP_KEY][envs, rows, 0]
            age_metrics = {
                "Health/replay_age_mean": jnp.mean(ages).astype(jnp.float32),
                "Health/replay_age_max": jnp.max(ages).astype(jnp.float32),
            }
            return batch, age_metrics

        return sample_gather


def make_transition_ring(ctx, cfg, rb, specs: Dict[str, Tuple[Sequence[int], Any]]):
    """The SAC family's ``buffer.device`` wiring: a :class:`DeviceTransitionRing`
    when the shared gate admits it (single chip, no DP), else ``None`` (the loops
    then keep host sampling + the async prefetcher)."""
    if not device_replay_enabled(ctx, cfg, allow_dp=False):
        return None
    return DeviceTransitionRing(
        rb.buffer_size, rb.n_envs, specs, store_dtype=resolve_store_dtype(cfg.buffer.get("store_dtype"))
    )


def _data_axis_devices(mesh) -> list:
    """Devices along the mesh's ``data`` axis, in axis order (requires the pure-DP
    topology the multi-process mirror supports: ``model == sequence == 1``)."""
    return list(mesh.devices.reshape(-1))


def _local_data_block(mesh):
    """This process's contiguous block of the global ``data`` axis, or ``None`` if
    its devices are not contiguous/aligned (the mirror then cannot map its env block
    onto the axis).  Returns ``(local_devices_in_axis_order, block_start)``."""
    devs = _data_axis_devices(mesh)
    me = jax.process_index()
    idxs = [i for i, d in enumerate(devs) if d.process_index == me]
    if not idxs or idxs != list(range(idxs[0], idxs[0] + len(idxs))):
        return None
    return [devs[i] for i in idxs], idxs[0]


class MultiProcessDeviceReplayMirror(DeviceReplayMirror):
    """Per-process LOCAL ring + zero-copy GLOBAL view for multi-process (multi-host)
    data parallelism.

    Design constraints this satisfies (why the r4 gate existed):

    * **Scatters must not be collective.**  Terminal-row adds fire when an episode
      ends — at different iterations on different processes.  A global SPMD scatter
      would deadlock; here every scatter runs on the process's OWN devices only
      (the base class, over a local ``data`` submesh), so processes scatter freely.
    * **The train block must stay SPMD.**  All processes dispatch the same jitted
      block in lockstep (gradient counts derive from the global policy-step count).
      Its replay inputs carry the per-process divergence as array SHARDS: the ring
      is re-exposed per dispatch as a global ``[world×n_envs, cap, flat]`` array via
      ``jax.make_array_from_single_device_arrays`` (metadata only — no copy, the
      shards ARE the local ring's buffers), and the per-process sampled index
      arrays become batch-sharded global arrays via
      ``jax.make_array_from_process_local_data``.
    * **Gathers never cross processes.**  Batch element ``j`` samples only from the
      env block its shard owns (``sample_index_block`` per-shard sampling +
      rank-offset ids), so the global-mesh ``shard_map`` gather is shard-local —
      identical math to the single-process DP path, just over the global mesh.

    In-place safety: a dispatch's global view references the same HBM buffers the
    next iteration's (donating) scatter overwrites — safe for the same reason the
    single-process path is: per-device program queues execute in dispatch order.
    """

    def __init__(self, capacity: int, n_envs_local: int, specs, global_mesh):
        shape = dict(global_mesh.shape)
        if shape.get("model", 1) > 1 or shape.get("sequence", 1) > 1:
            raise ValueError(
                "MultiProcessDeviceReplayMirror supports pure data parallelism only "
                f"(got mesh {dict(global_mesh.shape)}) — the env ring has no model/"
                "sequence dimension to shard over"
            )
        block = _local_data_block(global_mesh)
        if block is None:
            raise ValueError("process's devices are not a contiguous block of the data axis")
        local_devs, block_start = block
        k = len(local_devs)
        self._global_mesh = global_mesh
        self._world = jax.process_count()
        self._block_start = block_start
        local_mesh = (
            jax.sharding.Mesh(np.asarray(local_devs).reshape(k), axis_names=("data",)) if k > 1 else None
        )
        super().__init__(capacity, n_envs_local, specs, mesh=local_mesh, dp=k)
        self.local_dp = k
        # Global env ids must follow the DATA-AXIS position of this process's
        # device block, not its process index: global_view() places rows by
        # device, so if the axis were not process-ordered, a process_index-based
        # offset would silently gather other processes' rows.
        self.env_offset = block_start * (n_envs_local // k)
        self._view_shardings = {
            key: NamedSharding(global_mesh, P("data", None, None)) for key in specs
        }
        self._index_sharding = NamedSharding(global_mesh, P(None, "data"))

    @property
    def global_envs(self) -> int:
        return self.n_envs * self._world

    def _gather_mesh(self):
        return self._global_mesh

    def global_view(self) -> Dict[str, jax.Array]:
        """The SPMD train block's ring input: global env-sharded arrays whose shards
        are the CURRENT local ring buffers (metadata-only assembly, per dispatch)."""
        out = {}
        for k, arr in self.arrays.items():
            shards = [s.data for s in arr.addressable_shards]
            out[k] = jax.make_array_from_single_device_arrays(
                (self.global_envs, self.capacity, self._flat[k]), self._view_shardings[k], shards
            )
        return out

    def globalize_indices(self, envs: np.ndarray, starts: np.ndarray):
        """Per-process ``[G, B_local]`` int32 index blocks (LOCAL env ids) → global
        ``[G, world×B_local]`` batch-sharded arrays with global env ids."""
        genvs = np.ascontiguousarray(envs + self.env_offset, np.int32)
        gstarts = np.ascontiguousarray(starts, np.int32)
        g, b_local = genvs.shape
        shape = (g, b_local * self._world)
        return (
            jax.make_array_from_process_local_data(self._index_sharding, genvs, shape),
            jax.make_array_from_process_local_data(self._index_sharding, gstarts, shape),
        )


def device_replay_enabled(ctx, cfg, require_sequential: bool = False, allow_dp: bool = True) -> bool:
    """The ``buffer.device`` gate shared by every device-replay consumer.  Every
    fallback logs why, so a requested device buffer never degrades silently.
    Requirements:

    * for DV2, sequential buffers only (the episode buffer stays on host);
    * under data parallelism, ``num_envs`` and the batch size must divide the
      (per-process) ``data`` axis so the env-sharded ring and the per-shard
      sampler line up — or, for loops whose mirror is not sharded
      (``allow_dp=False``, SAC-AE's transition mirror), any ``data > 1`` or
      multi-process topology falls back;
    * multi-process additionally needs a pure-DP mesh (``model == sequence == 1``)
      with each process's devices a contiguous block of the ``data`` axis — the
      :class:`MultiProcessDeviceReplayMirror` topology.
    """
    import logging

    if not bool(cfg.buffer.get("device", False)):
        return False
    log = logging.getLogger(__name__)
    if require_sequential and str(cfg.buffer.get("type", "sequential")).lower() != "sequential":
        log.warning(
            "buffer.device=True supports only buffer.type=sequential (the episode "
            "buffer stays on host); falling back to host sampling."
        )
        return False
    world = jax.process_count()
    if not allow_dp and (ctx.data_parallel_size > 1 or world > 1):
        log.warning(
            "buffer.device=True is single-chip for this algorithm (its mirror is "
            "not sharded); falling back to host-side sampling with the async "
            "prefetcher."
        )
        return False
    if world > 1:
        if ctx.mesh.shape["model"] > 1 or ctx.mesh.shape["sequence"] > 1:
            log.warning(
                "buffer.device=True over multiple processes supports pure data "
                "parallelism only (mesh.model = mesh.sequence = 1); falling back "
                "to host-side sampling."
            )
            return False
        block = _local_data_block(ctx.mesh)
        if block is None:
            log.warning(
                "buffer.device=True needs each process's devices to form a "
                "contiguous block of the data axis; falling back to host-side "
                "sampling."
            )
            return False
        k = len(block[0])
        if cfg.env.num_envs % k != 0 or cfg.algo.per_rank_batch_size % k != 0:
            log.warning(
                "buffer.device=True with %d local devices on the data axis needs "
                "env.num_envs (%d) and algo.per_rank_batch_size (%d) divisible by "
                "it; falling back to host-side sampling.",
                k,
                cfg.env.num_envs,
                cfg.algo.per_rank_batch_size,
            )
            return False
        return True
    dp = ctx.data_parallel_size
    if dp > 1 and (cfg.env.num_envs % dp != 0 or cfg.algo.per_rank_batch_size % dp != 0):
        log.warning(
            "buffer.device=True with mesh.data=%d needs env.num_envs (%d) and "
            "algo.per_rank_batch_size (%d) to divide the data axis; falling back "
            "to host-side sampling.",
            dp,
            cfg.env.num_envs,
            cfg.algo.per_rank_batch_size,
        )
        return False
    return True


def make_rb_add(mirror: Optional[DeviceReplayMirror], rb, rb_lock, num_envs: int):
    """The loops' row-append: host add + device-mirror scatter at each target env's
    pre-add cursor.  The env-subset argument is passed POSITIONALLY — the
    EnvIndependentReplayBuffer and EpisodeBuffer name it differently."""

    def rb_add(data, indices=None, validate_args=False):
        if mirror is not None:
            envs_sel = list(indices) if indices is not None else list(range(num_envs))
            positions = [rb.buffer[e]._pos for e in envs_sel]
            mirror.add(data, envs_sel, positions)
        with rb_lock:
            rb.add(data, indices, validate_args=validate_args)

    return rb_add


def sample_index_block(rb, batch_size: int, sequence_length: int, n: int, dp: int = 1):
    """``n`` gradient steps' worth of (env, start) index pairs as ``[n, B]`` arrays
    for :class:`~sheeprl_tpu.utils.blocks.IndexedBlockDispatcher`.

    ``dp > 1``: the batch is drawn per data shard — element ``j`` (in shard
    ``j // (B//dp)``) samples only from the env block that shard owns, so the
    sharded gather never crosses shards.

    Per-shard sampleability is guaranteed by the prefill gate (``cli.py``
    ``check_configs``: learning_starts must leave EVERY env's sub-buffer a full
    sequence) plus the loops' write pattern (every env appends a row every
    iteration; done-index adds only append EXTRA rows) — so no shard's env block
    can hold fewer rows than the gate checked, including after a resume.
    """
    if dp <= 1:
        idx = [rb.sample_idx(batch_size, sequence_length) for _ in range(n)]
        return np.stack([e for e, _ in idx]), np.stack([s for _, s in idx])
    if batch_size % dp != 0 or rb.n_envs % dp != 0:
        # device_replay_enabled guards the training loops; direct callers (dryrun,
        # tests) must fail loudly rather than leave np.empty tails as garbage ids.
        raise ValueError(
            f"sharded index sampling needs batch_size ({batch_size}) and n_envs "
            f"({rb.n_envs}) divisible by dp ({dp})"
        )
    e_local = rb.n_envs // dp
    b_local = batch_size // dp
    envs = np.empty((n, batch_size), np.intp)
    starts = np.empty((n, batch_size), np.intp)
    for g in range(n):
        for s in range(dp):
            e, st = rb.sample_idx(b_local, sequence_length, env_range=range(s * e_local, (s + 1) * e_local))
            envs[g, s * b_local : (s + 1) * b_local] = e
            starts[g, s * b_local : (s + 1) * b_local] = st
    return envs, starts


def _algo_name(cfg) -> str:
    """Best-effort ``cfg.algo.name`` for perf cost-model registration keys."""
    try:
        return str(cfg.algo.name)
    except Exception:
        return "train"


def make_device_replay(
    ctx,
    cfg,
    rb,
    cnn_keys,
    mlp_keys,
    obs_space,
    act_dim_sum: int,
    step_fn,
    dispatcher_kwargs: Optional[dict] = None,
    require_sequential: bool = False,
):
    """One-stop wiring for the Dreamer-family loops — the single implementation of
    the device-vs-host replay data path.

    Returns ``(dispatcher, mirror, prefetcher, run_block, rb_add)``:

    * device path (``buffer.device=True``, single process): an
      :class:`~sheeprl_tpu.utils.blocks.IndexedBlockDispatcher` gathering from the
      HBM mirror in-jit (env-sharded over ``data`` when ``mesh.data > 1``), fed
      index-only sampling; no prefetcher;
    * host path: a :class:`~sheeprl_tpu.utils.blocks.BlockDispatcher` fed by the
      async double-buffered prefetcher.

    ``run_block(carry, n, start_count, stage_next=True)`` runs one iteration's
    ``n``-step gradient block through whichever path is active and returns the new
    carry — the ONE place the mirror-vs-host dispatch logic lives (the loops just
    call it).

    ``step_fn``/``dispatcher_kwargs`` are the loop's per-step train closure and its
    cadence options (``target_update_freq``, ``count_offset``); call AFTER the
    replay buffer exists, and call ``mirror.load_from(rb)`` after a resume restores
    the host buffer.
    """
    import contextlib

    from sheeprl_tpu.data.prefetch import make_replay_prefetcher
    from sheeprl_tpu.obs import flight_recorder
    from sheeprl_tpu.obs import perf as obs_perf
    from sheeprl_tpu.utils.blocks import BlockDispatcher, IndexedBlockDispatcher

    kwargs = dict(dispatcher_kwargs or {})
    kwargs.setdefault("base_key", ctx.rng())
    batch_size = cfg.algo.per_rank_batch_size
    seq_len = cfg.algo.per_rank_sequence_length

    # Flight recorder (obs/flight_recorder.py): every dispatched gradient block
    # stages its inputs (device-array references — no sync, no copy) so a crash
    # dumps the offending block.  The algorithm's main() registers the replay
    # target; the block cadence needed to re-execute it exactly is recorded here.
    recorder = flight_recorder.get_active()
    base_key = kwargs["base_key"]
    if recorder is not None:
        recorder.arm_replay(
            None,
            block_kwargs={
                "target_update_freq": int(kwargs.get("target_update_freq", 1)),
                "count_offset": int(kwargs.get("count_offset", 1)),
                "max_chunk": int(kwargs.get("max_chunk", 8)),
            },
        )

    if device_replay_enabled(ctx, cfg, require_sequential=require_sequential):
        mirror = make_mirror_for(
            rb,
            cnn_keys,
            mlp_keys,
            obs_space,
            [("actions", act_dim_sum), ("rewards", 1), ("terminated", 1), ("truncated", 1), ("is_first", 1)],
            ctx=ctx,
        )
        multiprocess = isinstance(mirror, MultiProcessDeviceReplayMirror)
        # Pin the gathered batch to the TRAIN mesh's batch sharding: when the
        # mirror's (pure-DP) mesh differs from the training mesh, the reshard
        # happens once at the gather boundary instead of as an involuntary full
        # rematerialization inside the backward pass (see make_gather_fn).
        dispatcher = IndexedBlockDispatcher(
            step_fn,
            gather_fn=mirror.make_gather_fn(seq_len, out_sharding=ctx.sharding(None, "data")),
            globalize=mirror.globalize_indices if multiprocess else None,
            **kwargs,
        )
        dispatcher._block = obs_perf.instrument(cfg, f"{_algo_name(cfg)}/train_block", dispatcher._block)
        prefetcher, rb_lock = None, contextlib.nullcontext()
        dp = mirror.local_dp if multiprocess else mirror.dp

        def run_block(carry, n: int, start_count: int, stage_next: bool = True):
            envs_idx, starts_idx = sample_index_block(rb, batch_size, seq_len, n, dp=dp)
            if recorder is not None:
                # Mirror rings are donated per scatter, so row references cannot
                # outlive the dispatch: stage the sampled indices (the dump then
                # carries state + indices; the batch is reconstructible from the
                # host buffer, which stays the source of truth).
                recorder.stage_step(
                    carry=carry,
                    base_key=base_key,
                    scalars={
                        "start_count": int(start_count),
                        "n_steps": int(n),
                        "envs_idx": np.asarray(envs_idx).tolist(),
                        "starts_idx": np.asarray(starts_idx).tolist(),
                    },
                )
            arrays = mirror.global_view() if multiprocess else mirror.arrays
            return dispatcher.dispatch(carry, arrays, envs_idx, starts_idx, start_count)

    else:
        mirror = None
        dispatcher = BlockDispatcher(step_fn, **kwargs)
        dispatcher._block = obs_perf.instrument(cfg, f"{_algo_name(cfg)}/train_block", dispatcher._block)
        prefetcher, rb_lock, sample_block = make_replay_prefetcher(rb, ctx, cfg, batch_size, seq_len)

        def run_block(carry, n: int, start_count: int, stage_next: bool = True):
            sample = prefetcher.get(n, stage_next=stage_next) if prefetcher is not None else sample_block(n)
            if recorder is not None:  # device-array references only: no host sync
                recorder.stage_step(
                    batches=sample,
                    carry=carry,
                    base_key=base_key,
                    scalars={"start_count": int(start_count), "n_steps": len(sample)},
                )
            return dispatcher.dispatch(carry, sample, start_count)

    # rb_lock stays internal: rb_add (below) and the prefetcher's sampler are the
    # only buffer accessors, so the loops never need to lock rb themselves.
    rb_add = make_rb_add(mirror, rb, rb_lock, rb.n_envs)
    return dispatcher, mirror, prefetcher, run_block, rb_add


def make_mirror_for(rb, cnn_keys, mlp_keys, obs_space, extra_float_keys, ctx=None) -> DeviceReplayMirror:
    """Build a mirror matching the Dreamer loops' row layout (``_obs_row``): pixel
    keys are stored ``[C_total, H, W]`` uint8 (decoded to float on device inside
    the train step), vector keys flat float32, scalar keys float32 ``[dim]``.
    With a ``ctx`` whose mesh has ``data > 1``, the ring is env-sharded over it."""
    specs: Dict[str, Tuple[Sequence[int], Any]] = {}
    for k in cnn_keys:
        shape = obs_space[k].shape
        specs[k] = ((int(np.prod(shape[:-2])), *shape[-2:]), jnp.uint8)
    for k in mlp_keys:
        specs[k] = ((int(np.prod(obs_space[k].shape)),), jnp.float32)
    for k, dim in extra_float_keys:
        specs[k] = ((int(dim),), jnp.float32)
    if ctx is not None and jax.process_count() > 1:
        return MultiProcessDeviceReplayMirror(rb.buffer_size, rb.n_envs, specs, global_mesh=ctx.mesh)
    mesh = ctx.mesh if ctx is not None and ctx.data_parallel_size > 1 else None
    dp = ctx.data_parallel_size if ctx is not None else 1
    return DeviceReplayMirror(rb.buffer_size, rb.n_envs, specs, mesh=mesh, dp=dp)
