"""Device-resident replay mirror: keep the replay data in HBM, ship only indices.

The reference samples on the host and ships every batch to the accelerator
(``/root/reference/sheeprl/data/buffers.py`` + ``sample_tensors``).  At DreamerV3's
Atari shapes that is ~12 MB per gradient step of mostly-redundant pixels, and on a
remote TPU the host→device link (not the MXU) becomes the training bottleneck.

TPU-native answer: the replay rows live ON the device.

* every row appended to the host buffer is also scattered into a ``[capacity,
  n_envs, ...]`` device ring via a DONATED jitted update (in-place, no copy of the
  ring) — ~12 KB/env/step uplink instead of ~12 MB/grad-step;
* sampling draws only (env, start) INDEX pairs on the host (same validity logic as
  the host buffer) and gathers the ``[T, B]`` batch inside the jitted train block —
  an HBM gather, three orders of magnitude faster than the tunnel;
* the host buffer stays the source of truth for checkpoint/resume; ``load_from``
  rebuilds the mirror after a resume.

The mirror requires the whole buffer to fit in HBM next to the model: ~1.2 GB for
the 100K-transition Atari-100K config — comfortable on any current TPU.  Enabled by
``buffer.device: True`` (the flagship default); loops fall back to host sampling +
prefetch when disabled.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_rows_tree(
    bufs: Dict[str, jax.Array], rows: Dict[str, jax.Array], envs: jax.Array, positions: jax.Array
) -> Dict[str, jax.Array]:
    """In-place ``bufs[k][positions[i], envs[i]] = rows[k][i]`` for every key in ONE
    dispatch (donated — no ring copy; per-key calls would each pay the dispatch
    overhead that dominates remote-TPU hosts)."""
    return {k: bufs[k].at[positions, envs].set(rows[k]) for k in bufs}


def gather_sequences(
    mirror: Dict[str, jax.Array], envs: jax.Array, starts: jax.Array, sequence_length: int
) -> Dict[str, jax.Array]:
    """In-jit gather of ``[T, B, ...]`` sequences from ``[cap, n_envs, ...]`` rings.

    ``envs``/``starts``: ``[B]`` int32; rows wrap modulo capacity (the host-side
    index sampling guarantees wrapped sequences never cross the write cursor).
    """
    out = {}
    for k, buf in mirror.items():
        cap = buf.shape[0]
        t_idx = (starts[:, None] + jnp.arange(sequence_length, dtype=starts.dtype)) % cap  # [B, T]
        picked = buf[t_idx, envs[:, None]]  # [B, T, ...]
        out[k] = jnp.swapaxes(picked, 0, 1)  # [T, B, ...]
    return out


class DeviceReplayMirror:
    """Device ring mirroring an ``EnvIndependentReplayBuffer``'s rows.

    ``specs``: ``{key: (shape, dtype)}`` per-row (no leading axes).  All write
    positions are tracked by the caller (the host buffer's per-env cursors).
    """

    def __init__(self, capacity: int, n_envs: int, specs: Dict[str, Tuple[Sequence[int], Any]]):
        self.capacity = int(capacity)
        self.n_envs = int(n_envs)
        self.specs = dict(specs)
        self.arrays: Dict[str, jax.Array] = {
            k: jnp.zeros((self.capacity, self.n_envs, *shape), dtype) for k, (shape, dtype) in specs.items()
        }

    @property
    def nbytes(self) -> int:
        return sum(int(np.prod(a.shape)) * a.dtype.itemsize for a in self.arrays.values())

    def add(self, data: Dict[str, np.ndarray], envs: Sequence[int], positions: Sequence[int]) -> None:
        """Scatter one row per selected env: ``data[k]`` is ``[1, len(envs), ...]``
        (the loops' step_data layout); ``positions[i]`` is env ``envs[i]``'s write
        cursor BEFORE the host add.  Static shapes: pad to ``n_envs`` by repeating
        the first target (idempotent duplicate write)."""
        n = len(envs)
        pad = self.n_envs - n
        env_arr = np.asarray(list(envs) + [envs[0]] * pad, np.int32)
        pos_arr = np.asarray([p % self.capacity for p in positions] + [positions[0] % self.capacity] * pad, np.int32)
        row_tree = {}
        for k in self.arrays:
            rows = np.asarray(data[k])[0]  # [n, ...]
            if pad:
                rows = np.concatenate([rows, np.repeat(rows[:1], pad, axis=0)], 0)
            row_tree[k] = rows.reshape(self.n_envs, *self.specs[k][0]).astype(self.specs[k][1])
        self.arrays = _scatter_rows_tree(self.arrays, row_tree, env_arr, pos_arr)

    def load_from(self, host_rb) -> None:
        """Rebuild the mirror from an ``EnvIndependentReplayBuffer`` (resume path):
        one bulk transfer per key."""
        for k in self.arrays:
            host = np.zeros(self.arrays[k].shape, self.specs[k][1])
            for e, sub in enumerate(host_rb.buffer):
                arr = np.asarray(sub._buf[k])  # [cap, 1, ...]
                rows = min(arr.shape[0], self.capacity)
                host[:rows, e] = arr[:rows, 0].reshape(rows, *self.specs[k][0])
            self.arrays[k] = jax.device_put(host)


def device_replay_enabled(ctx, cfg, require_sequential: bool = False) -> bool:
    """The ``buffer.device`` gate shared by the Dreamer loops: single-chip only
    (the mirror is not sharded) and — for DV2 — sequential buffers only.  Every
    fallback logs why, so a requested device buffer never degrades silently."""
    import logging

    if not bool(cfg.buffer.get("device", False)):
        return False
    log = logging.getLogger(__name__)
    if require_sequential and str(cfg.buffer.get("type", "sequential")).lower() != "sequential":
        log.warning(
            "buffer.device=True supports only buffer.type=sequential (the episode "
            "buffer stays on host); falling back to host sampling."
        )
        return False
    if ctx.data_parallel_size > 1:
        log.warning(
            "buffer.device=True is single-chip only (the mirror is not sharded); "
            "falling back to host-side sampling with the async prefetcher."
        )
        return False
    return True


def make_rb_add(mirror: Optional[DeviceReplayMirror], rb, rb_lock, num_envs: int):
    """The loops' row-append: host add + device-mirror scatter at each target env's
    pre-add cursor.  The env-subset argument is passed POSITIONALLY — the
    EnvIndependentReplayBuffer and EpisodeBuffer name it differently."""

    def rb_add(data, indices=None, validate_args=False):
        if mirror is not None:
            envs_sel = list(indices) if indices is not None else list(range(num_envs))
            positions = [rb.buffer[e]._pos for e in envs_sel]
            mirror.add(data, envs_sel, positions)
        with rb_lock:
            rb.add(data, indices, validate_args=validate_args)

    return rb_add


def sample_index_block(rb, batch_size: int, sequence_length: int, n: int):
    """``n`` gradient steps' worth of (env, start) index pairs as ``[n, B]`` arrays
    for :class:`~sheeprl_tpu.utils.blocks.IndexedBlockDispatcher`."""
    idx = [rb.sample_idx(batch_size, sequence_length) for _ in range(n)]
    return np.stack([e for e, _ in idx]), np.stack([s for _, s in idx])


def make_device_replay(
    ctx,
    cfg,
    rb,
    cnn_keys,
    mlp_keys,
    obs_space,
    act_dim_sum: int,
    step_fn,
    dispatcher_kwargs: Optional[dict] = None,
    require_sequential: bool = False,
):
    """One-stop wiring for the Dreamer-family loops — the single implementation of
    the device-vs-host replay data path.

    Returns ``(dispatcher, mirror, prefetcher, rb_lock, sample_block, rb_add)``:

    * device path (``buffer.device=True``, single chip): an
      :class:`~sheeprl_tpu.utils.blocks.IndexedBlockDispatcher` gathering from the
      HBM mirror in-jit; no prefetcher (sampling is index-only);
    * host path: a :class:`~sheeprl_tpu.utils.blocks.BlockDispatcher` fed by the
      async double-buffered prefetcher.

    ``step_fn``/``dispatcher_kwargs`` are the loop's per-step train closure and its
    cadence options (``target_update_freq``, ``count_offset``); call AFTER the
    replay buffer exists, and call ``mirror.load_from(rb)`` after a resume restores
    the host buffer.
    """
    import contextlib

    from sheeprl_tpu.data.prefetch import make_replay_prefetcher
    from sheeprl_tpu.utils.blocks import BlockDispatcher, IndexedBlockDispatcher

    kwargs = dict(dispatcher_kwargs or {})
    kwargs.setdefault("base_key", ctx.rng())
    batch_size = cfg.algo.per_rank_batch_size
    seq_len = cfg.algo.per_rank_sequence_length

    if device_replay_enabled(ctx, cfg, require_sequential=require_sequential):
        mirror = make_mirror_for(
            rb,
            cnn_keys,
            mlp_keys,
            obs_space,
            [("actions", act_dim_sum), ("rewards", 1), ("terminated", 1), ("truncated", 1), ("is_first", 1)],
        )
        dispatcher = IndexedBlockDispatcher(
            step_fn,
            gather_fn=lambda m, e, s: gather_sequences(m, e, s, seq_len),
            **kwargs,
        )
        prefetcher, rb_lock, sample_block = None, contextlib.nullcontext(), None
    else:
        mirror = None
        dispatcher = BlockDispatcher(step_fn, **kwargs)
        prefetcher, rb_lock, sample_block = make_replay_prefetcher(rb, ctx, cfg, batch_size, seq_len)

    rb_add = make_rb_add(mirror, rb, rb_lock, rb.n_envs)
    return dispatcher, mirror, prefetcher, rb_lock, sample_block, rb_add


def make_mirror_for(rb, cnn_keys, mlp_keys, obs_space, extra_float_keys) -> DeviceReplayMirror:
    """Build a mirror matching the Dreamer loops' row layout (``_obs_row``): pixel
    keys are stored ``[C_total, H, W]`` uint8 (decoded to float on device inside
    the train step), vector keys flat float32, scalar keys float32 ``[dim]``."""
    specs: Dict[str, Tuple[Sequence[int], Any]] = {}
    for k in cnn_keys:
        shape = obs_space[k].shape
        specs[k] = ((int(np.prod(shape[:-2])), *shape[-2:]), jnp.uint8)
    for k in mlp_keys:
        specs[k] = ((int(np.prod(obs_space[k].shape)),), jnp.float32)
    for k, dim in extra_float_keys:
        specs[k] = ((int(dim),), jnp.float32)
    return DeviceReplayMirror(rb.buffer_size, rb.n_envs, specs)
