"""Host-side replay buffers.

TPU-native re-design of ``/root/reference/sheeprl/data/buffers.py``: storage is numpy
(optionally memmap) on the host with layout ``[buffer_size, n_envs, ...]``; sampling is
numpy; ``sample_tensors`` returns **JAX device arrays** (optionally placed with an
explicit ``sharding`` so the batch lands pre-sharded over a ``data`` mesh axis).  The
device never touches buffer bookkeeping — all control flow stays on the host, which keeps
the jitted train step free of dynamic shapes.

Buffer classes and their contracts (mirroring reference ``buffers.py``):

* ``ReplayBuffer`` (``:20-360``) — circular dict-of-ndarray store; uniform sampling with
  validity masking around the write cursor; ``sample_next_obs`` pairs o/o'.
* ``SequentialReplayBuffer`` (``:363-526``) — contiguous length-T sequences ignoring
  episode bounds; output ``[n_samples, sequence_length, batch_size, ...]``.
* ``EnvIndependentReplayBuffer`` (``:529-743``) — one sub-buffer per env, supporting
  decoupled adds via ``indices``.
* ``EpisodeBuffer`` (``:746-1155``) — whole-episode store with open-episode assembly,
  oldest-episode eviction and ``prioritize_ends`` sampling.
"""

from __future__ import annotations

import os
import shutil
import typing
import uuid
from pathlib import Path
from typing import Any, Dict, Optional, Sequence, Tuple, Type

import numpy as np

from sheeprl_tpu.utils.memmap import MemmapArray

if typing.TYPE_CHECKING:
    import jax


def _np(v: Any) -> np.ndarray:
    return v.array if isinstance(v, MemmapArray) else np.asarray(v)


class ReplayBuffer:
    batch_axis: int = 1

    def __init__(
        self,
        buffer_size: int,
        n_envs: int = 1,
        obs_keys: Sequence[str] = ("observations",),
        memmap: bool = False,
        memmap_dir: Optional[os.PathLike] = None,
        memmap_mode: str = "r+",
        **kwargs: Any,
    ):
        if buffer_size <= 0:
            raise ValueError(f"The buffer size must be greater than zero, got: {buffer_size}")
        if n_envs <= 0:
            raise ValueError(f"The number of environments must be greater than zero, got: {n_envs}")
        self._buffer_size = buffer_size
        self._n_envs = n_envs
        self._obs_keys = tuple(obs_keys)
        self._memmap = memmap
        self._memmap_dir = Path(memmap_dir) if memmap_dir is not None else None
        self._memmap_mode = memmap_mode
        if self._memmap:
            if memmap_mode not in ("r+", "w+", "c", "copyonwrite", "readwrite", "write"):
                raise ValueError(
                    "Accepted values for memmap_mode are 'r+', 'readwrite', 'w+', 'write', 'c' or 'copyonwrite'."
                )
            if self._memmap_dir is None:
                raise ValueError("memmap=True requires a `memmap_dir`.")
            self._memmap_dir.mkdir(parents=True, exist_ok=True)
        self._buf: Dict[str, np.ndarray | MemmapArray] = {}
        self._pos = 0
        self._full = False
        self._rng = np.random.default_rng()
        # Replay staleness bookkeeping (obs/health.py Health/replay_age_* gauges):
        # per-row write stamps in cumulative added-row units.  Host-side integers
        # only — sampling records the most recent batch's age stats, never touching
        # the device.
        self._stamps = np.zeros(buffer_size, np.int64)
        self._rows_added = 0
        self._last_sample_ages: Optional[Tuple[float, float]] = None

    # -- properties ---------------------------------------------------------
    @property
    def buffer(self) -> Dict[str, np.ndarray]:
        return {k: _np(v) for k, v in self._buf.items()}

    @property
    def buffer_size(self) -> int:
        return self._buffer_size

    @property
    def full(self) -> bool:
        return self._full

    @full.setter
    def full(self, value: bool) -> None:
        self._full = bool(value)

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def empty(self) -> bool:
        return (not self._full) and self._pos == 0

    @property
    def is_memmap(self) -> bool:
        return self._memmap

    def __len__(self) -> int:
        return self._buffer_size if self._full else self._pos

    def seed(self, seed: Optional[int] = None) -> None:
        self._rng = np.random.default_rng(seed)

    # -- storage ------------------------------------------------------------
    def _init_storage(self, key: str, shape: Sequence[int], dtype: np.dtype) -> None:
        full_shape = (self._buffer_size, self._n_envs, *shape)
        if self._memmap:
            filename = self._memmap_dir / f"{key}.memmap"
            self._buf[key] = MemmapArray(dtype=dtype, shape=full_shape, mode=self._memmap_mode, filename=filename)
        else:
            self._buf[key] = np.zeros(full_shape, dtype=dtype)

    def add(self, data: "ReplayBuffer" | Dict[str, np.ndarray], validate_args: bool = False) -> None:
        """Append ``[T, n_envs, ...]`` arrays, wrapping circularly (reference ``:193-221``)."""
        if isinstance(data, ReplayBuffer):
            data = data.buffer
        if validate_args:
            if not isinstance(data, dict):
                raise ValueError(f"`data` must be a dictionary of numpy arrays, got {type(data)}")
            shapes = {k: np.asarray(v).shape[:2] for k, v in data.items()}
            if len(set(shapes.values())) > 1:
                raise RuntimeError(f"Every array in `data` must agree on [T, n_envs]: {shapes}")
            for k, v in data.items():
                if np.asarray(v).ndim < 2:
                    raise RuntimeError(f"`data[{k}]` must have shape [T, n_envs, ...], got {np.asarray(v).shape}")
                if np.asarray(v).shape[1] != self._n_envs:
                    raise RuntimeError(f"`data[{k}]` has n_envs={np.asarray(v).shape[1]}, expected {self._n_envs}")
        first = next(iter(data.values()))
        steps = np.asarray(first).shape[0]
        for k, v in data.items():
            v = np.asarray(v)
            if k not in self._buf:
                self._init_storage(k, v.shape[2:], v.dtype)
            buf = self._buf[k]
            if steps >= self._buffer_size:
                # Only the trailing window survives.
                buf[:] = np.moveaxis(v[-self._buffer_size :], 0, 0)
                continue
            idxes = (self._pos + np.arange(steps)) % self._buffer_size
            buf[idxes] = v
        if steps >= self._buffer_size:
            self._stamps[:] = self._rows_added + steps - self._buffer_size + np.arange(self._buffer_size)
            self._rows_added += steps
            self._pos = 0
            self._full = True
        else:
            self._stamps[(self._pos + np.arange(steps)) % self._buffer_size] = self._rows_added + np.arange(steps)
            self._rows_added += steps
            new_pos = self._pos + steps
            if new_pos >= self._buffer_size:
                self._full = True
            self._pos = new_pos % self._buffer_size

    # -- staleness ----------------------------------------------------------
    @property
    def rows_added(self) -> int:
        """Cumulative rows ever added (the staleness clock).  The device-resident
        transition ring (``data/device_buffer.py``) stamps its scatters with this
        counter so in-jit ``Health/replay_age_*`` matches the host bookkeeping."""
        return int(self._rows_added)

    @property
    def row_stamps(self) -> np.ndarray:
        """Per-row write stamps in cumulative added-row units (read-only copy;
        resume path of the device transition ring)."""
        return self._stamps.copy()

    def _note_sample_ages(self, rows: np.ndarray) -> None:
        """Record the age distribution of the rows just sampled.  Age = rows added
        to this buffer since the sampled row was written (0 = freshest possible)."""
        if self._rows_added == 0:
            return
        ages = (self._rows_added - 1) - self._stamps[np.asarray(rows).reshape(-1)]
        self._last_sample_ages = (float(ages.mean()), float(ages.max()))

    def sample_age_metrics(self) -> Dict[str, float]:
        """``Health/replay_age_*`` gauges of the most recent sample, in buffer-add
        steps (see ``obs/health.py``); empty until something was sampled."""
        if self._last_sample_ages is None:
            return {}
        mean, mx = self._last_sample_ages
        return {"Health/replay_age_mean": mean, "Health/replay_age_max": mx}

    # -- sampling -----------------------------------------------------------
    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        **kwargs: Any,
    ) -> Dict[str, np.ndarray]:
        """Uniformly sample ``[n_samples, batch_size, ...]`` transitions (reference ``:223-288``)."""
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be greater than 0")
        if self.empty:
            raise ValueError("No sample has been added to the buffer. Please add at least one via `add()`")
        batch_dim = batch_size * n_samples
        if self._full:
            if sample_next_obs:
                # Exclude _pos - 1: its "next" entry (at _pos) is the oldest element,
                # i.e. an unrelated transition across the write cursor.
                idxes = (self._rng.integers(0, self._buffer_size - 1, size=batch_dim) + self._pos) % self._buffer_size
            else:
                idxes = self._rng.integers(0, self._buffer_size, size=batch_dim)
        else:
            upper = self._pos - 1 if sample_next_obs else self._pos
            if upper <= 0:
                raise ValueError("Not enough data to sample next observations")
            idxes = self._rng.integers(0, upper, size=batch_dim)
        return self._gather(idxes, batch_size, n_samples, sample_next_obs, clone)

    def _gather(
        self, idxes: np.ndarray, batch_size: int, n_samples: int, sample_next_obs: bool, clone: bool
    ) -> Dict[str, np.ndarray]:
        env_idxes = self._rng.integers(0, self._n_envs, size=idxes.shape[0])
        self._note_sample_ages(idxes)
        rows64 = idxes.astype(np.int64)
        env64 = env_idxes.astype(np.int64)
        out: Dict[str, np.ndarray] = {}
        from sheeprl_tpu import native

        for k, v in self._buf.items():
            arr = _np(v)
            picked = native.gather_rows(arr, rows64, env64)  # GIL-releasing C gather
            if picked is None:
                picked = arr[idxes, env_idxes]
                if clone:
                    picked = picked.copy()
            out[k] = picked.reshape(n_samples, batch_size, *arr.shape[2:])
            if sample_next_obs and k in self._obs_keys:
                nxt = native.gather_rows(arr, (rows64 + 1) % self._buffer_size, env64)
                if nxt is None:
                    nxt = arr[(idxes + 1) % self._buffer_size, env_idxes]
                    if clone:
                        nxt = nxt.copy()
                out[f"next_{k}"] = nxt.reshape(n_samples, batch_size, *arr.shape[2:])
        return out

    def sample_transition_idx(self, batch_size: int, n_samples: int = 1) -> "Tuple[np.ndarray, np.ndarray]":
        """Index-only analogue of :meth:`sample` (``sample_next_obs=False``) for the
        device-resident mirror: the same uniform (row, env) distribution, returned
        as ``[n_samples, batch_size]`` index arrays instead of data."""
        if self.empty:
            raise ValueError("No sample has been added to the buffer. Please add at least one via `add()`")
        batch_dim = batch_size * n_samples
        upper = self._buffer_size if self._full else self._pos
        idxes = self._rng.integers(0, upper, size=batch_dim)
        env_idxes = self._rng.integers(0, self._n_envs, size=batch_dim)
        self._note_sample_ages(idxes)
        return idxes.reshape(n_samples, batch_size), env_idxes.reshape(n_samples, batch_size)

    def sample_tensors(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        n_samples: int = 1,
        dtype: Optional[Any] = None,
        sharding: Optional["jax.sharding.Sharding"] = None,
        **kwargs: Any,
    ) -> Dict[str, "jax.Array"]:
        """Sample and move to device (reference ``sample_tensors`` ``:291-326``)."""
        samples = self.sample(batch_size=batch_size, sample_next_obs=sample_next_obs, n_samples=n_samples, **kwargs)
        return to_device(samples, dtype=dtype, sharding=sharding)

    def to_tensor(self, dtype: Optional[Any] = None, clone: bool = False, **kwargs: Any) -> Dict[str, "jax.Array"]:
        return to_device({k: _np(v).copy() if clone else _np(v) for k, v in self._buf.items()}, dtype=dtype)

    # -- dict access --------------------------------------------------------
    def __getitem__(self, key: str) -> np.ndarray:
        if not isinstance(key, str):
            raise TypeError("ReplayBuffer keys must be strings")
        return _np(self._buf[key])

    def __setitem__(self, key: str, value: np.ndarray) -> None:
        value = np.asarray(value)
        if value.shape[:2] != (self._buffer_size, self._n_envs):
            raise RuntimeError(
                f"Value shape {value.shape} incompatible with buffer [{self._buffer_size}, {self._n_envs}, ...]"
            )
        if key not in self._buf:
            self._init_storage(key, value.shape[2:], value.dtype)
        self._buf[key][:] = value

    def __contains__(self, key: str) -> bool:
        return key in self._buf

    # -- checkpoint state ---------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        """Memmap-backed storage checkpoints as a flushed metadata REFERENCE (the
        :class:`~sheeprl_tpu.utils.memmap.MemmapArray` pickling protocol — same
        semantics as the reference ``sheeprl/utils/memmap.py:240-258``): the rows
        already live on disk, so copying them into the pickle would cost minutes
        of wall-clock and a full extra buffer of disk PER CHECKPOINT.  The
        checkpoint therefore points at the run's live memmap files — exact when
        resuming the latest checkpoint; an older one sees the ring's newer rows
        (bounded skew, identical to the reference's behavior).  RAM-backed
        storage still snapshots by value.

        Disk lifecycle: once a run checkpoints its buffer, its ``memmap_buffer``
        directory outlives the process (that is what makes the references
        resumable) and is reclaimed by deleting the run directory — at most one
        buffer-sized footprint per checkpointed run, the same profile as the
        reference's memmap runs."""
        buf = {}
        for k, v in self._buf.items():
            if isinstance(v, MemmapArray):
                v.flush()
                # The checkpoint now REFERENCES the backing file, so the buffer
                # must stop deleting it at GC/exit (``__del__`` still flushes) —
                # checkpointed memmap storage outlives the run by design.
                v.has_ownership = False
                buf[k] = v
            else:
                buf[k] = _np(v).copy()
        return {"buffer": buf, "pos": self._pos, "full": self._full}

    def load_state_dict(self, state: Dict[str, Any]) -> "ReplayBuffer":
        """Restore a checkpointed buffer.  Memmap references are COPIED into this
        buffer's own (fresh) storage rather than reattached in place: reattaching
        would make the resumed run write into files that older checkpoints still
        reference, silently corrupting them.  The one-time copy is the price of
        keeping every checkpoint's view immutable.  Source files are opened
        read-only, so resuming from a read-only archive works; a missing source
        (the original run's ``memmap_buffer`` dir was deleted or the checkpoint
        was moved without it) fails with a clear error."""
        for k, v in state["buffer"].items():
            if isinstance(v, MemmapArray):
                try:
                    src = np.memmap(v.filename, dtype=v.dtype, mode="r", shape=v.shape)
                except (FileNotFoundError, OSError) as exc:
                    raise RuntimeError(
                        f"buffer checkpoint for key '{k}' references memmap storage at "
                        f"{v.filename!r}, which is not readable. Memmap buffers are "
                        "checkpointed by reference — resuming needs the original run's "
                        "memmap_buffer directory alongside the checkpoint."
                    ) from exc
            else:
                src = v
            if k not in self._buf:
                self._init_storage(k, src.shape[2:], src.dtype)
            self._buf[k][:] = _np(src)
        self._pos = state["pos"]
        self._full = state["full"]
        # Rebuild approximate write stamps (checkpoints predate staleness tracking):
        # rows are stamped by their ring order ending at the write cursor, so ages
        # resume sensible instead of treating every restored row as brand new.
        n = len(self)
        self._stamps[:] = 0
        if n:
            self._stamps[(self._pos - 1 - np.arange(n)) % self._buffer_size] = n - 1 - np.arange(n)
        self._rows_added = n
        return self


class SequentialReplayBuffer(ReplayBuffer):
    """Contiguous-sequence sampling, ignoring episode boundaries (reference ``:363-526``)."""

    batch_axis: int = 2

    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        sequence_length: int = 1,
        **kwargs: Any,
    ) -> Dict[str, np.ndarray]:
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be greater than 0")
        if self.empty:
            raise ValueError("No sample has been added to the buffer. Please add at least one via `add()`")
        if not self._full and self._pos - sequence_length + 1 < 1:
            raise ValueError(
                f"Cannot sample a sequence of length {sequence_length}. Data added so far: {self._pos}"
            )
        if self._full and sequence_length > len(self):
            raise ValueError(f"Sequence length ({sequence_length}) longer than buffer ({len(self)})")
        batch_dim = batch_size * n_samples
        starts = self.sample_start_idxes(batch_dim, sequence_length)
        offsets = np.arange(sequence_length, dtype=np.intp)[None, :]
        idxes = (starts[:, None] + offsets) % self._buffer_size  # [B*N, T]
        return self._gather_sequences(idxes, batch_size, n_samples, sequence_length, sample_next_obs, clone)

    def sample_start_idxes(self, batch_dim: int, sequence_length: int) -> np.ndarray:
        """Uniform valid sequence-start rows (used directly by the device-resident
        mirror, which gathers on device from these indices)."""
        if self._full:
            # Valid starts are those whose sequence does not cross the write cursor:
            # [0, pos - seq_len] ∪ [pos, end-of-wrappable-range]  (reference ``:439-456``)
            first_range_end = self._pos - sequence_length + 1
            second_range_end = self._buffer_size if first_range_end >= 0 else self._buffer_size + first_range_end
            valid = np.concatenate(
                [np.arange(0, max(first_range_end, 0)), np.arange(self._pos, second_range_end)]
            ).astype(np.intp)
            starts = valid[self._rng.integers(0, len(valid), size=batch_dim)]
        else:
            starts = self._rng.integers(0, self._pos - sequence_length + 1, size=batch_dim)
        self._note_sample_ages(starts)
        return starts

    def _gather_sequences(
        self,
        idxes: np.ndarray,
        batch_size: int,
        n_samples: int,
        sequence_length: int,
        sample_next_obs: bool,
        clone: bool,
    ) -> Dict[str, np.ndarray]:
        batch_dim = batch_size * n_samples
        # One environment per sequence.
        env_idxes = self._rng.integers(0, self._n_envs, size=batch_dim)
        starts = idxes[:, 0].astype(np.int64)  # idxes rows are (start + t) % size
        env64 = env_idxes.astype(np.int64)
        env_idxes_tiled = None
        out: Dict[str, np.ndarray] = {}
        from sheeprl_tpu import native

        for k, v in self._buf.items():
            arr = _np(v)
            # Native one-pass gather straight into the time-major [N, T, B, ...]
            # layout (no transpose copy, GIL released); numpy fallback below.
            picked = native.gather_seq(arr, starts, env64, n_samples, sequence_length, batch_size)
            if picked is not None:
                out[k] = picked
            else:
                if env_idxes_tiled is None:
                    env_idxes_tiled = np.repeat(env_idxes[:, None], sequence_length, axis=1)
                picked = arr[idxes.ravel(), env_idxes_tiled.ravel()]
                picked = picked.reshape(n_samples, batch_size, sequence_length, *arr.shape[2:])
                out[k] = np.swapaxes(picked, 1, 2)  # [n_samples, T, B, ...]
                if clone:
                    out[k] = out[k].copy()
            if sample_next_obs and k in self._obs_keys:
                nxt = native.gather_seq(
                    arr, starts, env64, n_samples, sequence_length, batch_size, start_offset=1
                )
                if nxt is not None:
                    out[f"next_{k}"] = nxt
                else:
                    if env_idxes_tiled is None:
                        env_idxes_tiled = np.repeat(env_idxes[:, None], sequence_length, axis=1)
                    nxt = arr[(idxes.ravel() + 1) % self._buffer_size, env_idxes_tiled.ravel()]
                    nxt = nxt.reshape(n_samples, batch_size, sequence_length, *arr.shape[2:])
                    out[f"next_{k}"] = np.swapaxes(nxt, 1, 2)
                    if clone:
                        out[f"next_{k}"] = out[f"next_{k}"].copy()
        return out


class EnvIndependentReplayBuffer:
    """One sub-buffer per environment (reference ``:529-743``)."""

    def __init__(
        self,
        buffer_size: int,
        n_envs: int = 1,
        obs_keys: Sequence[str] = ("observations",),
        memmap: bool = False,
        memmap_dir: Optional[os.PathLike] = None,
        memmap_mode: str = "r+",
        buffer_cls: Type[ReplayBuffer] = ReplayBuffer,
        **kwargs: Any,
    ):
        if buffer_size <= 0:
            raise ValueError(f"The buffer size must be greater than zero, got: {buffer_size}")
        if n_envs <= 0:
            raise ValueError(f"The number of environments must be greater than zero, got: {n_envs}")
        if memmap and memmap_dir is None:
            raise ValueError("memmap=True requires a `memmap_dir`.")
        self._n_envs = n_envs
        self._buffer_size = buffer_size
        self._buffer_cls = buffer_cls
        self._concat_along_axis = buffer_cls.batch_axis
        self._buf: Sequence[ReplayBuffer] = [
            buffer_cls(
                buffer_size=buffer_size,
                n_envs=1,
                obs_keys=obs_keys,
                memmap=memmap,
                memmap_dir=None if memmap_dir is None else Path(memmap_dir) / f"env_{i}",
                memmap_mode=memmap_mode,
                **kwargs,
            )
            for i in range(n_envs)
        ]
        self._rng = np.random.default_rng()

    @property
    def buffer(self) -> Sequence[ReplayBuffer]:
        return self._buf

    @property
    def buffer_size(self) -> int:
        return self._buffer_size

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def full(self) -> Sequence[bool]:
        return [b.full for b in self._buf]

    @property
    def empty(self) -> Sequence[bool]:
        return [b.empty for b in self._buf]

    @property
    def is_memmap(self) -> Sequence[bool]:
        return [b.is_memmap for b in self._buf]

    def __len__(self) -> int:
        return sum(len(b) for b in self._buf)

    def seed(self, seed: Optional[int] = None) -> None:
        self._rng = np.random.default_rng(seed)
        for i, b in enumerate(self._buf):
            b.seed(None if seed is None else seed + i)

    def add(self, data: Dict[str, np.ndarray], indices: Optional[Sequence[int]] = None, validate_args: bool = False) -> None:
        if isinstance(data, ReplayBuffer):
            data = data.buffer
        if indices is None:
            indices = tuple(range(self._n_envs))
        if validate_args and len(indices) != next(iter(data.values())).shape[1]:
            raise ValueError("`indices` must match data's env dimension")
        for i, env_idx in enumerate(indices):
            self._buf[env_idx].add({k: np.asarray(v)[:, i : i + 1] for k, v in data.items()}, validate_args=validate_args)

    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        **kwargs: Any,
    ) -> Dict[str, np.ndarray]:
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be greater than 0")
        # Split the batch uniformly across non-empty sub-buffers (reference ``:684-699``).
        valid = [i for i, b in enumerate(self._buf) if len(b) > 0]
        if not valid:
            raise ValueError("No sample has been added to the buffer.")
        picks = self._rng.integers(0, len(valid), size=batch_size)
        counts = np.bincount(picks, minlength=len(valid))
        parts = []
        for j, i in enumerate(valid):
            if counts[j] > 0:
                parts.append(
                    self._buf[i].sample(
                        batch_size=int(counts[j]),
                        sample_next_obs=sample_next_obs,
                        clone=clone,
                        n_samples=n_samples,
                        **kwargs,
                    )
                )
        keys = parts[0].keys()
        return {k: np.concatenate([p[k] for p in parts], axis=self._concat_along_axis) for k in keys}

    def sample_tensors(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        n_samples: int = 1,
        dtype: Optional[Any] = None,
        sharding: Optional["jax.sharding.Sharding"] = None,
        **kwargs: Any,
    ) -> Dict[str, "jax.Array"]:
        samples = self.sample(batch_size=batch_size, sample_next_obs=sample_next_obs, n_samples=n_samples, **kwargs)
        return to_device(samples, dtype=dtype, sharding=sharding)

    def sample_idx(
        self, batch_size: int, sequence_length: int, env_range: Optional[Sequence[int]] = None
    ) -> "Tuple[np.ndarray, np.ndarray]":
        """Index-only sequence sampling for the device-resident mirror
        (``data/device_buffer.py``): same env-split + start-validity distribution as
        :meth:`sample`, but returns ``(env_ids [B], starts [B])`` instead of data.
        ``env_range`` restricts the draw to a subset of envs (the sharded mirror
        samples each data shard's own env block)."""
        # Same eligibility conditions SequentialReplayBuffer.sample() enforces —
        # bypassing them would surface as a raw numpy 'low >= high' in
        # sample_start_idxes mid-run instead of a descriptive sampling error.
        candidates = range(self._n_envs) if env_range is None else env_range
        valid = [
            i
            for i in candidates
            if (self._buf[i].full and sequence_length <= len(self._buf[i]))
            or (not self._buf[i].full and self._buf[i]._pos - sequence_length + 1 >= 1)
        ]
        if not valid:
            raise ValueError(
                f"Cannot sample a sequence of length {sequence_length}: no env buffer "
                f"in {list(candidates)} holds enough data "
                f"(per-env sizes: {[len(b) for b in self._buf]})."
            )
        env_ids = np.asarray(valid, np.intp)[self._rng.integers(0, len(valid), size=batch_size)]
        starts = np.empty(batch_size, np.intp)
        for i in np.unique(env_ids):
            sel = env_ids == i
            starts[sel] = self._buf[i].sample_start_idxes(int(sel.sum()), sequence_length)
        return env_ids, starts

    def sample_age_metrics(self) -> Dict[str, float]:
        """Aggregate staleness over the per-env sub-buffers (each counts age in its
        own add-steps): mean of sub-buffer means, max of maxes."""
        stats = [s for s in (b.sample_age_metrics() for b in self._buf) if s]
        if not stats:
            return {}
        return {
            "Health/replay_age_mean": float(np.mean([s["Health/replay_age_mean"] for s in stats])),
            "Health/replay_age_max": float(max(s["Health/replay_age_max"] for s in stats)),
        }

    def state_dict(self) -> Dict[str, Any]:
        return {"buffers": [b.state_dict() for b in self._buf]}

    def load_state_dict(self, state: Dict[str, Any]) -> "EnvIndependentReplayBuffer":
        for b, s in zip(self._buf, state["buffers"]):
            b.load_state_dict(s)
        return self


class EpisodeBuffer:
    """Whole-episode store (reference ``:746-1155``)."""

    batch_axis: int = 2

    def __init__(
        self,
        buffer_size: int,
        minimum_episode_length: int,
        n_envs: int = 1,
        obs_keys: Sequence[str] = ("observations",),
        prioritize_ends: bool = False,
        memmap: bool = False,
        memmap_dir: Optional[os.PathLike] = None,
        memmap_mode: str = "r+",
        **kwargs: Any,
    ):
        if buffer_size <= 0:
            raise ValueError(f"The buffer size must be greater than zero, got: {buffer_size}")
        if minimum_episode_length <= 0:
            raise ValueError(f"The minimum episode length must be greater than zero, got: {minimum_episode_length}")
        if buffer_size < minimum_episode_length:
            raise ValueError(
                f"The minimum episode length must be lower than the buffer size, got: bs={buffer_size} ml={minimum_episode_length}"
            )
        self._buffer_size = buffer_size
        self._minimum_episode_length = minimum_episode_length
        self._n_envs = n_envs
        self._obs_keys = tuple(obs_keys)
        self._prioritize_ends = prioritize_ends
        self._memmap = memmap
        self._memmap_dir = Path(memmap_dir) if memmap_dir is not None else None
        self._memmap_mode = memmap_mode
        if memmap and self._memmap_dir is None:
            raise ValueError("memmap=True requires a `memmap_dir`.")
        if self._memmap_dir is not None:
            self._memmap_dir.mkdir(parents=True, exist_ok=True)
        self._open_episodes: Sequence[list] = [[] for _ in range(n_envs)]
        self._cum_lengths: list = []
        self._buf: list = []
        self._rng = np.random.default_rng()

    @property
    def buffer(self) -> Sequence[Dict[str, np.ndarray]]:
        return self._buf

    @property
    def obs_keys(self) -> Sequence[str]:
        return self._obs_keys

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def buffer_size(self) -> int:
        return self._buffer_size

    @property
    def minimum_episode_length(self) -> int:
        return self._minimum_episode_length

    @property
    def prioritize_ends(self) -> bool:
        return self._prioritize_ends

    @prioritize_ends.setter
    def prioritize_ends(self, value: bool) -> None:
        self._prioritize_ends = bool(value)

    @property
    def is_memmap(self) -> bool:
        return self._memmap

    @property
    def full(self) -> bool:
        return len(self) + self._minimum_episode_length > self._buffer_size

    def __len__(self) -> int:
        return self._cum_lengths[-1] if self._cum_lengths else 0

    def seed(self, seed: Optional[int] = None) -> None:
        self._rng = np.random.default_rng(seed)

    def add(
        self,
        data: "ReplayBuffer" | Dict[str, np.ndarray],
        env_idxes: Optional[Sequence[int]] = None,
        validate_args: bool = False,
    ) -> None:
        if isinstance(data, ReplayBuffer):
            data = data.buffer
        if validate_args:
            if not isinstance(data, dict):
                raise ValueError(f"`data` must be a dictionary of numpy arrays, got {type(data)}")
            if "terminated" not in data or "truncated" not in data:
                raise RuntimeError(f"data must contain `terminated` and `truncated` keys, got: {list(data)}")
            if env_idxes is not None and (np.asarray(env_idxes) >= self._n_envs).any():
                raise ValueError(f"env indices must be in [0, {self._n_envs}), given {env_idxes}")
        if env_idxes is None:
            env_idxes = range(self._n_envs)
        for i, env in enumerate(env_idxes):
            env_data = {k: np.asarray(v)[:, i] for k, v in data.items()}
            done = np.logical_or(env_data["terminated"], env_data["truncated"]).reshape(-1)
            ends = done.nonzero()[0].tolist()
            if not ends:
                self._open_episodes[env].append(env_data)
                continue
            start = 0
            for end in ends + ([len(done) - 1] if ends[-1] != len(done) - 1 else []):
                chunk = {k: v[start : end + 1] for k, v in env_data.items()}
                if len(next(iter(chunk.values()))) > 0:
                    self._open_episodes[env].append(chunk)
                start = end + 1
                last = self._open_episodes[env][-1] if self._open_episodes[env] else None
                if last is not None and bool(np.logical_or(last["terminated"][-1], last["truncated"][-1]).any()):
                    self._save_episode(self._open_episodes[env])
                    self._open_episodes[env] = []

    def _save_episode(self, chunks: Sequence[Dict[str, np.ndarray]]) -> None:
        if not chunks:
            raise RuntimeError("Invalid episode: an empty sequence was given.")
        episode = {k: np.concatenate([c[k] for c in chunks], axis=0) for k in chunks[0]}
        ends = np.logical_or(episode["terminated"], episode["truncated"]).reshape(-1)
        ep_len = ends.shape[0]
        if ends.nonzero()[0].size != 1 or not ends[-1]:
            raise RuntimeError("The episode must contain exactly one done at its last step")
        if ep_len < self._minimum_episode_length:
            raise RuntimeError(f"Episode too short (min {self._minimum_episode_length}), got {ep_len} steps")
        if ep_len > self._buffer_size:
            raise RuntimeError(f"Episode too long (max {self._buffer_size}), got {ep_len} steps")
        # Evict oldest episodes until the new one fits (reference ``:994-1014``).
        while self._buf and len(self) + ep_len > self._buffer_size:
            evicted = self._buf.pop(0)
            self._cum_lengths = [c - self._cum_lengths[0] for c in self._cum_lengths[1:]]
            if self._memmap:
                dirname = os.path.dirname(next(iter(evicted.values())).filename)
                for v in evicted.values():
                    v.has_ownership = True
                evicted.clear()
                shutil.rmtree(dirname, ignore_errors=True)
        if self._memmap:
            ep_dir = self._memmap_dir / f"episode_{uuid.uuid4().hex}"
            episode = {k: MemmapArray.from_array(v, filename=ep_dir / f"{k}.memmap") for k, v in episode.items()}
        self._buf.append(episode)
        self._cum_lengths.append(len(self) + ep_len)

    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        n_samples: int = 1,
        clone: bool = False,
        sequence_length: int = 1,
        **kwargs: Any,
    ) -> Dict[str, np.ndarray]:
        """Sample ``[n_samples, sequence_length, batch_size, ...]`` (reference ``:1033-1120``)."""
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be greater than 0")
        lengths = np.diff([0] + self._cum_lengths)
        min_len = sequence_length + (1 if sample_next_obs else 0)
        valid = [ep for ep, ln in zip(self._buf, lengths) if ln >= min_len and (not sample_next_obs or ln > sequence_length)]
        if not valid:
            raise RuntimeError(
                "No valid episodes in the buffer; add at least one episode of length >= "
                f"{sequence_length}."
            )
        batch_dim = batch_size * n_samples
        ep_choice = self._rng.integers(0, len(valid), size=batch_dim)
        offsets = np.arange(sequence_length, dtype=np.intp)[None, :]
        parts: Dict[str, list] = {k: [] for k in valid[0].keys()}
        if sample_next_obs:
            for k in self._obs_keys:
                parts[f"next_{k}"] = []
        for b in range(batch_dim):
            ep = valid[ep_choice[b]]
            ep_len = _np(ep["terminated"]).shape[0]
            if sample_next_obs:
                ep_len -= 1
            upper = ep_len - sequence_length + 1
            if self._prioritize_ends:
                upper += sequence_length
            start = min(int(self._rng.integers(0, upper)), ep_len - sequence_length)
            idx = start + offsets[0]
            for k in ep.keys():
                parts[k].append(_np(ep[k])[idx])
                if sample_next_obs and k in self._obs_keys:
                    parts[f"next_{k}"].append(_np(ep[k])[idx + 1])
        out = {}
        for k, v in parts.items():
            if v:
                stacked = np.stack(v, axis=0).reshape(n_samples, batch_size, sequence_length, *v[0].shape[1:])
                out[k] = np.swapaxes(stacked, 1, 2)
                if clone:
                    out[k] = out[k].copy()
        return out

    def sample_tensors(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        n_samples: int = 1,
        dtype: Optional[Any] = None,
        sharding: Optional["jax.sharding.Sharding"] = None,
        **kwargs: Any,
    ) -> Dict[str, "jax.Array"]:
        samples = self.sample(batch_size=batch_size, sample_next_obs=sample_next_obs, n_samples=n_samples, **kwargs)
        return to_device(samples, dtype=dtype, sharding=sharding)

    def state_dict(self) -> Dict[str, Any]:
        return {
            "episodes": [{k: _np(v).copy() for k, v in ep.items()} for ep in self._buf],
            "cum_lengths": list(self._cum_lengths),
            "open_episodes": [[{k: np.asarray(v).copy() for k, v in c.items()} for c in chunks] for chunks in self._open_episodes],
        }

    def load_state_dict(self, state: Dict[str, Any]) -> "EpisodeBuffer":
        self._buf = []
        self._cum_lengths = []
        for ep in state["episodes"]:
            if self._memmap:
                ep_dir = self._memmap_dir / f"episode_{uuid.uuid4().hex}"
                ep = {k: MemmapArray.from_array(v, filename=ep_dir / f"{k}.memmap") for k, v in ep.items()}
            self._buf.append(ep)
            ln = next(iter(ep.values())).shape[0]
            self._cum_lengths.append((self._cum_lengths[-1] if self._cum_lengths else 0) + ln)
        self._open_episodes = state["open_episodes"]
        return self


def to_device(
    samples: Dict[str, np.ndarray],
    dtype: Optional[Any] = None,
    sharding: Optional["jax.sharding.Sharding"] = None,
) -> Dict[str, "jax.Array"]:
    """Host→device transfer of a sample dict, optionally pre-sharded over a mesh."""
    import jax
    import jax.numpy as jnp

    out = {}
    for k, v in samples.items():
        arr = np.asarray(v)
        if dtype is not None and np.issubdtype(arr.dtype, np.floating):
            arr = arr.astype(dtype)
        out[k] = jax.device_put(arr, sharding) if sharding is not None else jnp.asarray(arr)
    return out
