"""Metric aggregation (reference: ``sheeprl/utils/metric.py:17-195``).

TPU-native re-design: no torchmetrics.  Metrics are plain host-side accumulators fed with
python floats or jax scalars; ``compute()`` returns means and drops NaNs the way the
reference does (``metric.py:109-143``).  Cross-process reduction happens explicitly via
``jax.experimental.multihost_utils`` in the caller when needed — metrics themselves stay
host-local so logging never blocks the device.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Iterable, Optional

import numpy as np


class MeanMetric:
    def __init__(self):
        self._sum = 0.0
        self._count = 0

    def update(self, value: Any) -> None:
        # Drop non-finite values at update time: one NaN loss would otherwise poison
        # the running sum for the whole log window (the reference only filters at
        # compute time, after the damage is done).
        arr = np.asarray(value, dtype=np.float64).reshape(-1)
        finite = arr[np.isfinite(arr)]
        self._sum += float(finite.sum())
        self._count += int(finite.size)

    def compute(self) -> float:
        if self._count == 0:
            return float("nan")
        return self._sum / self._count

    def reset(self) -> None:
        self._sum = 0.0
        self._count = 0


class SumMetric(MeanMetric):
    def compute(self) -> float:
        return self._sum


class LastMetric(MeanMetric):
    def __init__(self):
        super().__init__()
        self._last = float("nan")

    def update(self, value: Any) -> None:
        self._last = float(np.asarray(value).reshape(-1)[-1])
        self._count += 1

    def compute(self) -> float:
        return self._last


class HistogramMetric:
    """Latency-distribution accumulator for the span tracer's percentile export.

    ``compute()`` returns a dict (``p50/p95/p99/mean/count``) instead of a float;
    ``MetricAggregator.compute`` flattens it into ``<name>/<key>`` scalars so the
    percentiles ride the existing logger pipeline unchanged.  Bounded by a ring
    buffer: after ``max_samples`` values the oldest are overwritten, keeping the
    window recent without unbounded growth over a long run.
    """

    KEYS = ("p50", "p95", "p99", "mean", "count")

    def __init__(self, max_samples: int = 65536):
        self._max = int(max_samples)
        self._values: list = []
        self._next = 0  # ring-buffer write head once the buffer is full
        self._count = 0

    def update(self, value: Any) -> None:
        arr = np.asarray(value, dtype=np.float64).reshape(-1)
        for v in arr[np.isfinite(arr)]:
            if len(self._values) < self._max:
                self._values.append(float(v))
            else:
                self._values[self._next] = float(v)
                self._next = (self._next + 1) % self._max
            self._count += 1

    def compute(self) -> Optional[Dict[str, float]]:
        if not self._values:
            return None
        vals = np.asarray(self._values)
        p50, p95, p99 = np.percentile(vals, [50.0, 95.0, 99.0])
        return {
            "p50": float(p50),
            "p95": float(p95),
            "p99": float(p99),
            "mean": float(vals.mean()),
            "count": float(self._count),
        }

    def reset(self) -> None:
        self._values = []
        self._next = 0
        self._count = 0


_METRIC_TYPES = {
    "mean": MeanMetric,
    "sum": SumMetric,
    "last": LastMetric,
    "histogram": HistogramMetric,
}


class MetricAggregator:
    """Named metric collection with a global disable switch.

    Reference semantics: ``MetricAggregator`` (``metric.py:17-143``) — a dict of named
    metrics; ``compute()`` returns a flat dict, skipping NaN/empty metrics.
    """

    disabled: bool = False

    def __init__(self, metrics: Optional[Dict[str, Any]] = None):
        self.metrics: Dict[str, Any] = {}
        for name, spec in (metrics or {}).items():
            self.add(name, spec)

    def add(self, name: str, metric: Any = "mean") -> None:
        if isinstance(metric, str):
            metric = _METRIC_TYPES[metric]()
        elif isinstance(metric, dict):
            metric = _METRIC_TYPES[metric.get("type", "mean")]()
        self.metrics[name] = metric

    def update(self, name: str, value: Any) -> None:
        if MetricAggregator.disabled:
            return
        if name not in self.metrics:
            self.add(name)
        v = value
        if hasattr(v, "item") and getattr(v, "size", 1) == 1:
            v = v.item()
        self.metrics[name].update(v)

    def __contains__(self, name: str) -> bool:
        return name in self.metrics

    def keep(self, keys: Iterable[str]) -> None:
        """Prune to a whitelist (reference: AGGREGATOR_KEYS pruning, cli.py:151-165)."""
        keys = set(keys)
        self.metrics = {k: v for k, v in self.metrics.items() if k in keys}

    def compute(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        if MetricAggregator.disabled:
            return out
        for name, metric in self.metrics.items():
            v = metric.compute()
            if v is None or (isinstance(v, float) and math.isnan(v)):
                continue
            if isinstance(v, dict):
                # Dict-valued metrics (HistogramMetric) flatten to <name>/<key> scalars.
                for sub, sv in v.items():
                    if isinstance(sv, float) and math.isnan(sv):
                        continue
                    out[f"{name}/{sub}"] = float(sv)
            else:
                out[name] = v
        return out

    def reset(self) -> None:
        for m in self.metrics.values():
            m.reset()


class RankIndependentMetricAggregator:
    """Per-rank metrics with a cross-process gather at compute time
    (reference ``metric.py:146-195``).

    Each process accumulates its own values; ``compute()`` all-gathers the per-rank
    results over DCN via ``multihost_utils.process_allgather`` and returns the
    cross-rank MEAN of each metric (every rank sees the same values, like the
    reference's broadcast-back).  ``compute_per_rank()`` exposes the raw
    ``[world_size]`` vectors."""

    def __init__(self, metrics: Optional[Dict[str, Any] | MetricAggregator] = None):
        self._aggregator = metrics if isinstance(metrics, MetricAggregator) else MetricAggregator(metrics)

    @property
    def metrics(self) -> Dict[str, Any]:
        return self._aggregator.metrics

    def add(self, name: str, metric: Any = "mean") -> None:
        self._aggregator.add(name, metric)

    def update(self, name: str, value: Any) -> None:
        self._aggregator.update(name, value)

    def keep(self, keys: Iterable[str]) -> None:
        """Prune AND pre-register the whitelist: every rank must carry the SAME metric
        name set or the fixed-shape cross-process gather breaks (lazy registration via
        update() would make the set rank-dependent, e.g. Rewards/rew_avg appearing only
        on ranks that finished an episode)."""
        self._aggregator.keep(keys)
        for k in sorted(keys):
            if k not in self._aggregator.metrics:
                self._aggregator.add(k)

    def __contains__(self, name: str) -> bool:
        return name in self._aggregator

    def compute_per_rank(self) -> Dict[str, np.ndarray]:
        """Gather each metric's local value from every process → ``[world]`` arrays.
        Absent-on-this-rank metrics gather as NaN so ranks stay aligned."""
        import jax

        local = self._aggregator.compute()
        if jax.process_count() == 1:
            return {k: np.asarray([v]) for k, v in local.items()}
        from jax.experimental import multihost_utils

        # One fixed-order vector per rank keeps the gather shape static across ranks.
        # Histogram metrics flatten to a deterministic key set, so expanding them here
        # keeps every rank's vector aligned even when some ranks saw no samples.
        names: list = []
        for n in sorted(self._aggregator.metrics):
            if isinstance(self._aggregator.metrics[n], HistogramMetric):
                names.extend(f"{n}/{k}" for k in HistogramMetric.KEYS)
            else:
                names.append(n)
        vec = np.asarray([local.get(n, np.nan) for n in names], dtype=np.float64)
        gathered = np.asarray(multihost_utils.process_allgather(vec))  # [world, n_metrics]
        return {n: gathered[:, i] for i, n in enumerate(names)}

    def compute(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for name, values in self.compute_per_rank().items():
            finite = values[np.isfinite(values)]
            if finite.size:
                out[name] = float(finite.mean())
        return out

    def reset(self) -> None:
        self._aggregator.reset()


def make_aggregator(metrics: Optional[Dict[str, Any]] = None):
    """MetricAggregator, rank-aware when running multi-process (reference picks
    ``RankIndependentMetricAggregator`` for cross-rank metrics)."""
    import jax

    if jax.process_count() > 1:
        return RankIndependentMetricAggregator(metrics)
    return MetricAggregator(metrics)


def record_episode_stats(aggregator: MetricAggregator, info: Dict[str, Any]) -> None:
    """Feed ``RecordEpisodeStatistics`` vector-env info into the aggregator.

    Handles both gymnasium layouts: ``info["final_info"]["episode"]`` (SAME_STEP
    autoreset) and a top-level ``info["episode"]``.
    """
    src = None
    if "final_info" in info and isinstance(info["final_info"], dict) and "episode" in info["final_info"]:
        src = info["final_info"]
    elif "episode" in info:
        src = info
    if src is None:
        return
    ep = src["episode"]
    mask = np.asarray(src.get("_episode", np.ones(np.asarray(ep["r"]).shape, dtype=bool)))
    for r, l in zip(np.asarray(ep["r"])[mask], np.asarray(ep["l"])[mask]):
        aggregator.update("Rewards/rew_avg", float(r))
        aggregator.update("Game/ep_len_avg", float(l))
