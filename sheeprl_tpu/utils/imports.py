"""Optional-dependency guards + dotted-path instantiation.

``instantiate`` replaces ``hydra.utils.instantiate`` (used by the reference at
``sheeprl/utils/env.py:73`` to build env adapters from ``_target_`` config nodes).
"""

from __future__ import annotations

import importlib
import importlib.util
from typing import Any, Dict


def _available(module: str) -> bool:
    try:
        return importlib.util.find_spec(module) is not None
    except (ImportError, ModuleNotFoundError, ValueError):
        return False


_IS_MLFLOW_AVAILABLE = _available("mlflow")
_IS_DMC_AVAILABLE = _available("dm_control")
_IS_CRAFTER_AVAILABLE = _available("crafter")
_IS_DIAMBRA_AVAILABLE = _available("diambra")
_IS_MINEDOJO_AVAILABLE = _available("minedojo")
_IS_MINERL_AVAILABLE = _available("minerl")
_IS_SMB_AVAILABLE = _available("gym_super_mario_bros")
_IS_ATARI_AVAILABLE = _available("ale_py")
_IS_MUJOCO_AVAILABLE = _available("mujoco")
_IS_BOX2D_AVAILABLE = _available("Box2D") or _available("box2d")


def resolve(path: str) -> Any:
    module_name, _, attr = path.rpartition(".")
    if not module_name:
        raise ImportError(f"Cannot resolve '{path}': no module component")
    module = importlib.import_module(module_name)
    return getattr(module, attr)


def instantiate(node: Dict[str, Any], **overrides: Any) -> Any:
    """Instantiate ``{_target_: 'pkg.mod.Class', **kwargs}`` config nodes."""
    if not isinstance(node, dict) or "_target_" not in node:
        raise ValueError(f"instantiate() requires a dict with a '_target_' key, got: {node!r}")
    node = dict(node)
    target = node.pop("_target_")
    node.pop("_convert_", None)
    partial = node.pop("_partial_", False)
    kwargs = {**node, **overrides}
    cls = resolve(target)
    if partial:
        import functools

        return functools.partial(cls, **kwargs)
    return cls(**kwargs)
