"""Checkpoint → act-fn reconstruction, shared by evaluation and the serve path.

``sheeprl_tpu.eval`` and ``python -m sheeprl_tpu.serve`` both need the same
pipeline: rebuild the agent a checkpoint was trained with (from the run's saved
config), load the checkpoint through :class:`CheckpointManager`, dig the policy
params out of whatever layout the run used (host-loop ``params``, Anakin scan
``carry``, population member axis), and wrap the actor in a pure batched
``act_fn(params, obs_dict, key) -> actions`` that jit/AOT-compiles at any batch
size.  This module is that pipeline, factored out of the per-algo ``evaluate``
entries so the serve tier does not duplicate it.

Servable families:

* ``ppo`` — ``ppo``, ``ppo_decoupled``, ``a2c``: dict observations through the
  shared encoder; greedy mode takes the distribution mode.
* ``sac`` — ``sac``, ``sac_decoupled``: vector observations concatenated in-graph;
  the action is ``tanh(mean)`` rescaled to the env bounds (the reference's
  eval-time policy).
* ``ppo_recurrent`` — *stateful*: the act fn is ``act_fn(params, obs, is_first,
  state, key) -> (actions, new_state)`` where ``state = {"rnn": carry, "prev":
  one-hot prev actions}``; ``LoadedPolicy.stateful`` is True and
  ``zero_state_fn(n)`` builds the fresh-episode state batch.  The serve tier
  keeps the state device-resident per session
  (:class:`sheeprl_tpu.serve.state_cache.SessionStateCache`).

World-model policies (the Dreamer family) are not reconstructable through this
path; :func:`policy_family` rejects them with an actionable error.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

#: algo name -> servable family
PPO_FAMILY = ("ppo", "ppo_decoupled", "a2c")
SAC_FAMILY = ("sac", "sac_decoupled")
RECURRENT_PPO_FAMILY = ("ppo_recurrent",)


def policy_family(algo_name: str) -> str:
    """The act-fn family for ``algo_name``; raises for world-model policies."""
    if algo_name in PPO_FAMILY:
        return "ppo"
    if algo_name in SAC_FAMILY:
        return "sac"
    if algo_name in RECURRENT_PPO_FAMILY:
        return "ppo_recurrent"
    raise ValueError(
        f"algorithm {algo_name!r} has no act-fn builder: only "
        f"{', '.join(PPO_FAMILY + SAC_FAMILY + RECURRENT_PPO_FAMILY)} can be "
        "evaluated/served through this path (world-model policies rebuild "
        "through their own evaluate entries)"
    )


def extract_policy_params(state: Dict[str, Any], cfg: Any, algo: str) -> Any:
    """Policy params from a loaded checkpoint state, whatever the run layout.

    Host-loop checkpoints store ``params`` directly; Anakin runs
    (``algo.anakin=True``) checkpoint the whole scan carry with params inside
    (``engine/anakin.py``); population carries add a leading member axis, of
    which member 0 — the base-seed member — is the one evaluation and serving
    use (``howto/population.md``).
    """
    params = state["carry"]["params"] if "params" not in state else state["params"]
    if "params" not in state:
        from sheeprl_tpu.engine.population import PopulationSpec, slice_member

        if PopulationSpec.from_cfg(cfg, algo).enabled:
            params = slice_member(params, 0)
    return params


@dataclass
class LoadedPolicy:
    """A served/evaluated policy: the pure act fn plus everything a caller needs
    to feed it (obs template) and interpret its output (action metadata)."""

    algo: str
    family: str
    act_fn: Callable[[Any, Dict[str, Any], Any], Any]
    params: Any  # device pytree, exactly what act_fn's first argument expects
    obs_template: Dict[str, Tuple[Tuple[int, ...], str]]  # key -> (shape, dtype str)
    is_continuous: bool
    action_dims: List[int]
    cfg: Any = field(repr=False, default=None)
    precision: str = "f32"  # serving precision tier: f32 | bf16 | int8
    stateful: bool = False  # act fn threads per-session state (recurrent families)
    zero_state_fn: Optional[Callable[[int], Any]] = field(repr=False, default=None)

    def zero_obs(self, batch: int) -> Dict[str, np.ndarray]:
        """A zero-filled obs batch matching the template (precompile ladders)."""
        return {
            k: np.zeros((batch, *shape), dtype=np.dtype(dtype))
            for k, (shape, dtype) in self.obs_template.items()
        }


def _ppo_act_fn(agent, greedy: bool):
    from sheeprl_tpu.algos.ppo.utils import sample_actions

    def act_fn(params, obs, key):
        actor_out, _ = agent.apply(params, obs)
        env_act, _, _ = sample_actions(key, actor_out, agent.is_continuous, greedy=greedy)
        return env_act

    return act_fn


def _recurrent_ppo_act_fn(agent, greedy: bool):
    """Stateful act fn: one recurrent step per request batch.

    ``state = {"rnn": carry, "prev": [B, sum(action_dims)] float32}``.  The
    previous action is re-encoded in-graph (one-hot per discrete head, raw for
    continuous) exactly as the training loop's ``_onehot_actions``; episode
    starts need no host-side zeroing because ``RecurrentPPOAgent.step`` masks
    both the carry and ``prev`` by ``is_first`` in-graph.  The new state is cast
    to float32 so cached storage keeps one dtype across precision tiers.
    """
    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.algos.ppo.utils import sample_actions
    from sheeprl_tpu.algos.ppo_recurrent.agent import RecurrentPPOAgent

    dims = [int(d) for d in agent.action_dims]

    def act_fn(params, obs, is_first, state, key):
        actor_out, _, new_rnn = agent.apply(
            params, obs, state["prev"], is_first, state["rnn"], method=RecurrentPPOAgent.step
        )
        env_act, _, _ = sample_actions(key, actor_out, agent.is_continuous, greedy=greedy)
        if agent.is_continuous:
            prev = env_act.astype(jnp.float32)
        else:
            prev = jnp.concatenate(
                [jax.nn.one_hot(env_act[..., i], d, dtype=jnp.float32) for i, d in enumerate(dims)],
                axis=-1,
            )
        new_state = {
            "rnn": jax.tree.map(lambda x: x.astype(jnp.float32), new_rnn),
            "prev": prev,
        }
        return env_act, new_state

    return act_fn


def _sac_act_fn(actor, mlp_keys: List[str], act_space):
    import jax.numpy as jnp

    low = np.asarray(act_space.low, np.float32)
    high = np.asarray(act_space.high, np.float32)
    rescale = bool(np.isfinite(low).all() and np.isfinite(high).all())

    def act_fn(params, obs, key):
        arrs = [
            obs[k].reshape((obs[k].shape[0], -1)) if obs[k].ndim > 1 else obs[k][:, None]
            for k in mlp_keys
        ]
        x = jnp.concatenate(arrs, axis=-1)
        mean, _ = actor.apply(params, x)
        act = jnp.tanh(mean)
        if rescale:
            act = low + (act + 1.0) * 0.5 * (high - low)
        return act

    return act_fn


def _obs_template(obs_space, cnn_keys: List[str], mlp_keys: List[str]):
    """Per-key (shape, dtype) the act fn expects: uint8 images pass through, vector
    keys are float32 (mirrors the prepare_obs helpers)."""
    template: Dict[str, Tuple[Tuple[int, ...], str]] = {}
    for k in cnn_keys:
        template[k] = (tuple(obs_space[k].shape), str(np.dtype(obs_space[k].dtype)))
    for k in mlp_keys:
        template[k] = (tuple(obs_space[k].shape), "float32")
    return template


def build_policy(ctx, cfg, obs_space, act_space, greedy: bool = True) -> Tuple[LoadedPolicy, Any]:
    """Build the agent + act fn for ``cfg.algo.name`` against explicit spaces.

    Returns ``(policy, template_params)`` where ``template_params`` is the FULL
    freshly-initialised parameter pytree (the checkpoint-load template — for SAC
    that is the actor+critics dict even though the act fn only consumes the actor
    slice).  ``policy.params`` holds the act-fn slice of those fresh params;
    callers that loaded a checkpoint swap it via :func:`load_policy`.
    """
    algo = cfg.algo.name
    family = policy_family(algo)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cnn_keys = list(cfg.algo.cnn_keys.encoder) if family in ("ppo", "ppo_recurrent") else []
    stateful = False
    zero_state_fn = None
    if family == "ppo":
        from sheeprl_tpu.algos.ppo.agent import build_agent

        agent, params = build_agent(ctx, act_space, obs_space, cfg)
        act_fn = _ppo_act_fn(agent, greedy)
        act_params = params
        is_continuous = bool(agent.is_continuous)
        action_dims = [int(d) for d in agent.action_dims]
    elif family == "ppo_recurrent":
        import jax.numpy as jnp

        from sheeprl_tpu.algos.ppo_recurrent.agent import build_agent, make_zero_state

        agent, params = build_agent(ctx, act_space, obs_space, cfg)
        act_fn = _recurrent_ppo_act_fn(agent, greedy)
        act_params = params
        is_continuous = bool(agent.is_continuous)
        action_dims = [int(d) for d in agent.action_dims]
        stateful = True
        zero_rnn = make_zero_state(cfg)
        act_sum = int(sum(action_dims))

        def zero_state_fn(n: int, _zero_rnn=zero_rnn, _act_sum=act_sum):
            return {"rnn": _zero_rnn(n), "prev": jnp.zeros((n, _act_sum), jnp.float32)}

    else:
        from sheeprl_tpu.algos.sac.agent import build_agent

        actor, _, params = build_agent(ctx, act_space, obs_space, cfg)
        act_fn = _sac_act_fn(actor, mlp_keys, act_space)
        act_params = params["actor"]
        is_continuous = True
        action_dims = [int(np.prod(act_space.shape))]
    policy = LoadedPolicy(
        algo=algo,
        family=family,
        act_fn=act_fn,
        params=act_params,
        obs_template=_obs_template(obs_space, cnn_keys, mlp_keys),
        is_continuous=is_continuous,
        action_dims=action_dims,
        cfg=cfg,
        stateful=stateful,
        zero_state_fn=zero_state_fn,
    )
    return policy, params


def wrap_policy_precision(policy: LoadedPolicy, precision: Any) -> LoadedPolicy:
    """Apply a serving precision tier to a freshly built/loaded policy in place.

    * ``f32`` (or null) — no-op, the checkpoint serves verbatim;
    * ``bf16`` — float param leaves cast to bfloat16 (the act fn's compute dtype
      must already be bf16: :func:`load_policy` forces ``algo.precision`` before
      the agent build);
    * ``int8`` — every 2-D float kernel is replaced by a per-channel symmetric
      :class:`~sheeprl_tpu.precision.quantize.Int8Weight` and the act fn
      dequantizes in-jit, so XLA fuses the dequant into the matmul
      (weights-only quantization; activations stay float).
    """
    key = str(precision if precision is not None else "f32").lower()
    if key in ("", "none", "null", "f32", "fp32", "float32"):
        policy.precision = "f32"
        return policy
    if key in ("bf16", "bfloat16"):
        import jax
        import jax.numpy as jnp

        policy.params = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            policy.params,
        )
        policy.precision = "bf16"
        return policy
    if key == "int8":
        from sheeprl_tpu.precision import dequantize_params, quantize_params

        policy.params = quantize_params(policy.params)
        base_act_fn = policy.act_fn

        def act_fn(params, *rest):  # same trailing args for stateless and stateful fns
            return base_act_fn(dequantize_params(params), *rest)

        policy.act_fn = act_fn
        policy.precision = "int8"
        return policy
    raise ValueError(f"Unknown serve precision {precision!r}; expected f32, bf16 or int8")


def parity_stamp(policy: LoadedPolicy, reference: LoadedPolicy, n_obs: int = 256, seed: int = 0) -> Dict[str, Any]:
    """Greedy-action agreement between a reduced-precision policy and its f32
    reference on seeded random observations — the parity report the server
    stamps into ready_file / pong / the exit summary (howto/precision.md)."""
    import jax

    from sheeprl_tpu.precision import action_agreement

    rng = np.random.default_rng(seed)
    obs: Dict[str, np.ndarray] = {}
    for k, (shape, dtype) in policy.obs_template.items():
        if np.issubdtype(np.dtype(dtype), np.integer):
            obs[k] = rng.integers(0, 256, size=(n_obs, *shape)).astype(np.dtype(dtype))
        else:
            obs[k] = rng.standard_normal((n_obs, *shape)).astype(np.dtype(dtype))
    key = np.zeros((2,), np.uint32)
    if policy.stateful:
        # Fresh-episode step: zero state + is_first=1 on both sides, compare actions.
        is_first = np.ones((n_obs, 1), np.float32)
        got = jax.device_get(
            jax.jit(policy.act_fn)(policy.params, obs, is_first, policy.zero_state_fn(n_obs), key)[0]
        )
        want = jax.device_get(
            jax.jit(reference.act_fn)(
                reference.params, obs, is_first, reference.zero_state_fn(n_obs), key
            )[0]
        )
    else:
        got = jax.device_get(jax.jit(policy.act_fn)(policy.params, obs, key))
        want = jax.device_get(jax.jit(reference.act_fn)(reference.params, obs, key))
    return {
        "precision": policy.precision,
        "reference": reference.precision,
        "n_obs": int(n_obs),
        "action_agreement": float(
            action_agreement(want, got, continuous=policy.is_continuous)
        ),
    }


def load_policy(
    ctx, cfg, ckpt_path: str, greedy: bool = True, precision: Optional[str] = None
) -> LoadedPolicy:
    """The full pipeline: spaces from the run's env, agent rebuild, checkpoint
    load (checksum-verified), param extraction, device placement.

    ``cfg`` is the run's saved config (mutated: video capture and env count are
    forced to the single-env serve/eval shape before the env is instantiated to
    read its spaces).

    ``precision`` is the serve-tier override (``serve.precision``): ``None``
    keeps the run config's own ``algo.precision`` resolution (eval parity with
    training); ``f32``/``bf16``/``int8`` pin the act fn's tier — ``bf16`` builds
    the agent at bf16 compute and casts the loaded params, ``f32``/``int8``
    force a full-precision build (int8 then quantizes the loaded kernels, see
    :func:`wrap_policy_precision`).
    """
    import jax

    from sheeprl_tpu.checkpoint.manager import CheckpointManager
    from sheeprl_tpu.utils.env import make_env

    cfg.env.capture_video = False
    cfg.env.num_envs = 1
    if precision is not None:
        key = str(precision).lower()
        cfg.algo.precision = "bf16" if key in ("bf16", "bfloat16") else "f32"
    env = make_env(cfg, cfg.seed, 0, None, "serve")()
    obs_space = env.observation_space
    act_space = env.action_space
    env.close()

    policy, template_params = build_policy(ctx, cfg, obs_space, act_space, greedy=greedy)
    state = CheckpointManager.load(
        ckpt_path, templates={"params": jax.device_get(template_params)}
    )
    params = extract_policy_params(state, cfg, policy.family)
    if policy.family == "sac":
        params = params["actor"]
    policy.params = ctx.replicate(params)
    if precision is not None:
        policy = wrap_policy_precision(policy, precision)
    return policy
