"""Checkpoint → act-fn reconstruction, shared by evaluation and the serve path.

``sheeprl_tpu.eval`` and ``python -m sheeprl_tpu.serve`` both need the same
pipeline: rebuild the agent a checkpoint was trained with (from the run's saved
config), load the checkpoint through :class:`CheckpointManager`, dig the policy
params out of whatever layout the run used (host-loop ``params``, Anakin scan
``carry``, population member axis), and wrap the actor in a pure batched
``act_fn(params, obs_dict, key) -> actions`` that jit/AOT-compiles at any batch
size.  This module is that pipeline, factored out of the per-algo ``evaluate``
entries so the serve tier does not duplicate it.

Servable families (stateless feed-forward policies):

* ``ppo`` — ``ppo``, ``ppo_decoupled``, ``a2c``: dict observations through the
  shared encoder; greedy mode takes the distribution mode.
* ``sac`` — ``sac``, ``sac_decoupled``: vector observations concatenated in-graph;
  the action is ``tanh(mean)`` rescaled to the env bounds (the reference's
  eval-time policy).

Recurrent and world-model policies (``ppo_recurrent``, the Dreamer family) carry
per-client latent state between steps — a stateless request/reply server cannot
serve them; :func:`policy_family` rejects them with an actionable error.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

#: algo name -> servable family
PPO_FAMILY = ("ppo", "ppo_decoupled", "a2c")
SAC_FAMILY = ("sac", "sac_decoupled")


def policy_family(algo_name: str) -> str:
    """The act-fn family for ``algo_name``; raises for stateful policies."""
    if algo_name in PPO_FAMILY:
        return "ppo"
    if algo_name in SAC_FAMILY:
        return "sac"
    raise ValueError(
        f"algorithm {algo_name!r} has no stateless act-fn builder: only "
        f"{', '.join(PPO_FAMILY + SAC_FAMILY)} can be evaluated/served through this "
        "path (recurrent and world-model policies carry per-step latent state)"
    )


def extract_policy_params(state: Dict[str, Any], cfg: Any, algo: str) -> Any:
    """Policy params from a loaded checkpoint state, whatever the run layout.

    Host-loop checkpoints store ``params`` directly; Anakin runs
    (``algo.anakin=True``) checkpoint the whole scan carry with params inside
    (``engine/anakin.py``); population carries add a leading member axis, of
    which member 0 — the base-seed member — is the one evaluation and serving
    use (``howto/population.md``).
    """
    params = state["carry"]["params"] if "params" not in state else state["params"]
    if "params" not in state:
        from sheeprl_tpu.engine.population import PopulationSpec, slice_member

        if PopulationSpec.from_cfg(cfg, algo).enabled:
            params = slice_member(params, 0)
    return params


@dataclass
class LoadedPolicy:
    """A served/evaluated policy: the pure act fn plus everything a caller needs
    to feed it (obs template) and interpret its output (action metadata)."""

    algo: str
    family: str
    act_fn: Callable[[Any, Dict[str, Any], Any], Any]
    params: Any  # device pytree, exactly what act_fn's first argument expects
    obs_template: Dict[str, Tuple[Tuple[int, ...], str]]  # key -> (shape, dtype str)
    is_continuous: bool
    action_dims: List[int]
    cfg: Any = field(repr=False, default=None)
    precision: str = "f32"  # serving precision tier: f32 | bf16 | int8

    def zero_obs(self, batch: int) -> Dict[str, np.ndarray]:
        """A zero-filled obs batch matching the template (precompile ladders)."""
        return {
            k: np.zeros((batch, *shape), dtype=np.dtype(dtype))
            for k, (shape, dtype) in self.obs_template.items()
        }


def _ppo_act_fn(agent, greedy: bool):
    from sheeprl_tpu.algos.ppo.utils import sample_actions

    def act_fn(params, obs, key):
        actor_out, _ = agent.apply(params, obs)
        env_act, _, _ = sample_actions(key, actor_out, agent.is_continuous, greedy=greedy)
        return env_act

    return act_fn


def _sac_act_fn(actor, mlp_keys: List[str], act_space):
    import jax.numpy as jnp

    low = np.asarray(act_space.low, np.float32)
    high = np.asarray(act_space.high, np.float32)
    rescale = bool(np.isfinite(low).all() and np.isfinite(high).all())

    def act_fn(params, obs, key):
        arrs = [
            obs[k].reshape((obs[k].shape[0], -1)) if obs[k].ndim > 1 else obs[k][:, None]
            for k in mlp_keys
        ]
        x = jnp.concatenate(arrs, axis=-1)
        mean, _ = actor.apply(params, x)
        act = jnp.tanh(mean)
        if rescale:
            act = low + (act + 1.0) * 0.5 * (high - low)
        return act

    return act_fn


def _obs_template(obs_space, cnn_keys: List[str], mlp_keys: List[str]):
    """Per-key (shape, dtype) the act fn expects: uint8 images pass through, vector
    keys are float32 (mirrors the prepare_obs helpers)."""
    template: Dict[str, Tuple[Tuple[int, ...], str]] = {}
    for k in cnn_keys:
        template[k] = (tuple(obs_space[k].shape), str(np.dtype(obs_space[k].dtype)))
    for k in mlp_keys:
        template[k] = (tuple(obs_space[k].shape), "float32")
    return template


def build_policy(ctx, cfg, obs_space, act_space, greedy: bool = True) -> Tuple[LoadedPolicy, Any]:
    """Build the agent + act fn for ``cfg.algo.name`` against explicit spaces.

    Returns ``(policy, template_params)`` where ``template_params`` is the FULL
    freshly-initialised parameter pytree (the checkpoint-load template — for SAC
    that is the actor+critics dict even though the act fn only consumes the actor
    slice).  ``policy.params`` holds the act-fn slice of those fresh params;
    callers that loaded a checkpoint swap it via :func:`load_policy`.
    """
    algo = cfg.algo.name
    family = policy_family(algo)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cnn_keys = list(cfg.algo.cnn_keys.encoder) if family == "ppo" else []
    if family == "ppo":
        from sheeprl_tpu.algos.ppo.agent import build_agent

        agent, params = build_agent(ctx, act_space, obs_space, cfg)
        act_fn = _ppo_act_fn(agent, greedy)
        act_params = params
        is_continuous = bool(agent.is_continuous)
        action_dims = [int(d) for d in agent.action_dims]
    else:
        from sheeprl_tpu.algos.sac.agent import build_agent

        actor, _, params = build_agent(ctx, act_space, obs_space, cfg)
        act_fn = _sac_act_fn(actor, mlp_keys, act_space)
        act_params = params["actor"]
        is_continuous = True
        action_dims = [int(np.prod(act_space.shape))]
    policy = LoadedPolicy(
        algo=algo,
        family=family,
        act_fn=act_fn,
        params=act_params,
        obs_template=_obs_template(obs_space, cnn_keys, mlp_keys),
        is_continuous=is_continuous,
        action_dims=action_dims,
        cfg=cfg,
    )
    return policy, params


def wrap_policy_precision(policy: LoadedPolicy, precision: Any) -> LoadedPolicy:
    """Apply a serving precision tier to a freshly built/loaded policy in place.

    * ``f32`` (or null) — no-op, the checkpoint serves verbatim;
    * ``bf16`` — float param leaves cast to bfloat16 (the act fn's compute dtype
      must already be bf16: :func:`load_policy` forces ``algo.precision`` before
      the agent build);
    * ``int8`` — every 2-D float kernel is replaced by a per-channel symmetric
      :class:`~sheeprl_tpu.precision.quantize.Int8Weight` and the act fn
      dequantizes in-jit, so XLA fuses the dequant into the matmul
      (weights-only quantization; activations stay float).
    """
    key = str(precision if precision is not None else "f32").lower()
    if key in ("", "none", "null", "f32", "fp32", "float32"):
        policy.precision = "f32"
        return policy
    if key in ("bf16", "bfloat16"):
        import jax
        import jax.numpy as jnp

        policy.params = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            policy.params,
        )
        policy.precision = "bf16"
        return policy
    if key == "int8":
        from sheeprl_tpu.precision import dequantize_params, quantize_params

        policy.params = quantize_params(policy.params)
        base_act_fn = policy.act_fn

        def act_fn(params, obs, key):
            return base_act_fn(dequantize_params(params), obs, key)

        policy.act_fn = act_fn
        policy.precision = "int8"
        return policy
    raise ValueError(f"Unknown serve precision {precision!r}; expected f32, bf16 or int8")


def parity_stamp(policy: LoadedPolicy, reference: LoadedPolicy, n_obs: int = 256, seed: int = 0) -> Dict[str, Any]:
    """Greedy-action agreement between a reduced-precision policy and its f32
    reference on seeded random observations — the parity report the server
    stamps into ready_file / pong / the exit summary (howto/precision.md)."""
    import jax

    from sheeprl_tpu.precision import action_agreement

    rng = np.random.default_rng(seed)
    obs: Dict[str, np.ndarray] = {}
    for k, (shape, dtype) in policy.obs_template.items():
        if np.issubdtype(np.dtype(dtype), np.integer):
            obs[k] = rng.integers(0, 256, size=(n_obs, *shape)).astype(np.dtype(dtype))
        else:
            obs[k] = rng.standard_normal((n_obs, *shape)).astype(np.dtype(dtype))
    key = np.zeros((2,), np.uint32)
    got = jax.device_get(jax.jit(policy.act_fn)(policy.params, obs, key))
    want = jax.device_get(jax.jit(reference.act_fn)(reference.params, obs, key))
    return {
        "precision": policy.precision,
        "reference": reference.precision,
        "n_obs": int(n_obs),
        "action_agreement": float(
            action_agreement(want, got, continuous=policy.is_continuous)
        ),
    }


def load_policy(
    ctx, cfg, ckpt_path: str, greedy: bool = True, precision: Optional[str] = None
) -> LoadedPolicy:
    """The full pipeline: spaces from the run's env, agent rebuild, checkpoint
    load (checksum-verified), param extraction, device placement.

    ``cfg`` is the run's saved config (mutated: video capture and env count are
    forced to the single-env serve/eval shape before the env is instantiated to
    read its spaces).

    ``precision`` is the serve-tier override (``serve.precision``): ``None``
    keeps the run config's own ``algo.precision`` resolution (eval parity with
    training); ``f32``/``bf16``/``int8`` pin the act fn's tier — ``bf16`` builds
    the agent at bf16 compute and casts the loaded params, ``f32``/``int8``
    force a full-precision build (int8 then quantizes the loaded kernels, see
    :func:`wrap_policy_precision`).
    """
    import jax

    from sheeprl_tpu.checkpoint.manager import CheckpointManager
    from sheeprl_tpu.utils.env import make_env

    cfg.env.capture_video = False
    cfg.env.num_envs = 1
    if precision is not None:
        key = str(precision).lower()
        cfg.algo.precision = "bf16" if key in ("bf16", "bfloat16") else "f32"
    env = make_env(cfg, cfg.seed, 0, None, "serve")()
    obs_space = env.observation_space
    act_space = env.action_space
    env.close()

    policy, template_params = build_policy(ctx, cfg, obs_space, act_space, greedy=greedy)
    state = CheckpointManager.load(
        ckpt_path, templates={"params": jax.device_get(template_params)}
    )
    params = extract_policy_params(state, cfg, policy.family)
    if policy.family == "sac":
        params = params["actor"]
    policy.params = ctx.replicate(params)
    if precision is not None:
        policy = wrap_policy_precision(policy, precision)
    return policy
