"""Checkpoint → act-fn reconstruction, shared by evaluation and the serve path.

``sheeprl_tpu.eval`` and ``python -m sheeprl_tpu.serve`` both need the same
pipeline: rebuild the agent a checkpoint was trained with (from the run's saved
config), load the checkpoint through :class:`CheckpointManager`, dig the policy
params out of whatever layout the run used (host-loop ``params``, Anakin scan
``carry``, population member axis), and wrap the actor in a pure batched
``act_fn(params, obs_dict, key) -> actions`` that jit/AOT-compiles at any batch
size.  This module is that pipeline, factored out of the per-algo ``evaluate``
entries so the serve tier does not duplicate it.

Servable families (stateless feed-forward policies):

* ``ppo`` — ``ppo``, ``ppo_decoupled``, ``a2c``: dict observations through the
  shared encoder; greedy mode takes the distribution mode.
* ``sac`` — ``sac``, ``sac_decoupled``: vector observations concatenated in-graph;
  the action is ``tanh(mean)`` rescaled to the env bounds (the reference's
  eval-time policy).

Recurrent and world-model policies (``ppo_recurrent``, the Dreamer family) carry
per-client latent state between steps — a stateless request/reply server cannot
serve them; :func:`policy_family` rejects them with an actionable error.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

#: algo name -> servable family
PPO_FAMILY = ("ppo", "ppo_decoupled", "a2c")
SAC_FAMILY = ("sac", "sac_decoupled")


def policy_family(algo_name: str) -> str:
    """The act-fn family for ``algo_name``; raises for stateful policies."""
    if algo_name in PPO_FAMILY:
        return "ppo"
    if algo_name in SAC_FAMILY:
        return "sac"
    raise ValueError(
        f"algorithm {algo_name!r} has no stateless act-fn builder: only "
        f"{', '.join(PPO_FAMILY + SAC_FAMILY)} can be evaluated/served through this "
        "path (recurrent and world-model policies carry per-step latent state)"
    )


def extract_policy_params(state: Dict[str, Any], cfg: Any, algo: str) -> Any:
    """Policy params from a loaded checkpoint state, whatever the run layout.

    Host-loop checkpoints store ``params`` directly; Anakin runs
    (``algo.anakin=True``) checkpoint the whole scan carry with params inside
    (``engine/anakin.py``); population carries add a leading member axis, of
    which member 0 — the base-seed member — is the one evaluation and serving
    use (``howto/population.md``).
    """
    params = state["carry"]["params"] if "params" not in state else state["params"]
    if "params" not in state:
        from sheeprl_tpu.engine.population import PopulationSpec, slice_member

        if PopulationSpec.from_cfg(cfg, algo).enabled:
            params = slice_member(params, 0)
    return params


@dataclass
class LoadedPolicy:
    """A served/evaluated policy: the pure act fn plus everything a caller needs
    to feed it (obs template) and interpret its output (action metadata)."""

    algo: str
    family: str
    act_fn: Callable[[Any, Dict[str, Any], Any], Any]
    params: Any  # device pytree, exactly what act_fn's first argument expects
    obs_template: Dict[str, Tuple[Tuple[int, ...], str]]  # key -> (shape, dtype str)
    is_continuous: bool
    action_dims: List[int]
    cfg: Any = field(repr=False, default=None)

    def zero_obs(self, batch: int) -> Dict[str, np.ndarray]:
        """A zero-filled obs batch matching the template (precompile ladders)."""
        return {
            k: np.zeros((batch, *shape), dtype=np.dtype(dtype))
            for k, (shape, dtype) in self.obs_template.items()
        }


def _ppo_act_fn(agent, greedy: bool):
    from sheeprl_tpu.algos.ppo.utils import sample_actions

    def act_fn(params, obs, key):
        actor_out, _ = agent.apply(params, obs)
        env_act, _, _ = sample_actions(key, actor_out, agent.is_continuous, greedy=greedy)
        return env_act

    return act_fn


def _sac_act_fn(actor, mlp_keys: List[str], act_space):
    import jax.numpy as jnp

    low = np.asarray(act_space.low, np.float32)
    high = np.asarray(act_space.high, np.float32)
    rescale = bool(np.isfinite(low).all() and np.isfinite(high).all())

    def act_fn(params, obs, key):
        arrs = [
            obs[k].reshape((obs[k].shape[0], -1)) if obs[k].ndim > 1 else obs[k][:, None]
            for k in mlp_keys
        ]
        x = jnp.concatenate(arrs, axis=-1)
        mean, _ = actor.apply(params, x)
        act = jnp.tanh(mean)
        if rescale:
            act = low + (act + 1.0) * 0.5 * (high - low)
        return act

    return act_fn


def _obs_template(obs_space, cnn_keys: List[str], mlp_keys: List[str]):
    """Per-key (shape, dtype) the act fn expects: uint8 images pass through, vector
    keys are float32 (mirrors the prepare_obs helpers)."""
    template: Dict[str, Tuple[Tuple[int, ...], str]] = {}
    for k in cnn_keys:
        template[k] = (tuple(obs_space[k].shape), str(np.dtype(obs_space[k].dtype)))
    for k in mlp_keys:
        template[k] = (tuple(obs_space[k].shape), "float32")
    return template


def build_policy(ctx, cfg, obs_space, act_space, greedy: bool = True) -> Tuple[LoadedPolicy, Any]:
    """Build the agent + act fn for ``cfg.algo.name`` against explicit spaces.

    Returns ``(policy, template_params)`` where ``template_params`` is the FULL
    freshly-initialised parameter pytree (the checkpoint-load template — for SAC
    that is the actor+critics dict even though the act fn only consumes the actor
    slice).  ``policy.params`` holds the act-fn slice of those fresh params;
    callers that loaded a checkpoint swap it via :func:`load_policy`.
    """
    algo = cfg.algo.name
    family = policy_family(algo)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cnn_keys = list(cfg.algo.cnn_keys.encoder) if family == "ppo" else []
    if family == "ppo":
        from sheeprl_tpu.algos.ppo.agent import build_agent

        agent, params = build_agent(ctx, act_space, obs_space, cfg)
        act_fn = _ppo_act_fn(agent, greedy)
        act_params = params
        is_continuous = bool(agent.is_continuous)
        action_dims = [int(d) for d in agent.action_dims]
    else:
        from sheeprl_tpu.algos.sac.agent import build_agent

        actor, _, params = build_agent(ctx, act_space, obs_space, cfg)
        act_fn = _sac_act_fn(actor, mlp_keys, act_space)
        act_params = params["actor"]
        is_continuous = True
        action_dims = [int(np.prod(act_space.shape))]
    policy = LoadedPolicy(
        algo=algo,
        family=family,
        act_fn=act_fn,
        params=act_params,
        obs_template=_obs_template(obs_space, cnn_keys, mlp_keys),
        is_continuous=is_continuous,
        action_dims=action_dims,
        cfg=cfg,
    )
    return policy, params


def load_policy(ctx, cfg, ckpt_path: str, greedy: bool = True) -> LoadedPolicy:
    """The full pipeline: spaces from the run's env, agent rebuild, checkpoint
    load (checksum-verified), param extraction, device placement.

    ``cfg`` is the run's saved config (mutated: video capture and env count are
    forced to the single-env serve/eval shape before the env is instantiated to
    read its spaces).
    """
    import jax

    from sheeprl_tpu.checkpoint.manager import CheckpointManager
    from sheeprl_tpu.utils.env import make_env

    cfg.env.capture_video = False
    cfg.env.num_envs = 1
    env = make_env(cfg, cfg.seed, 0, None, "serve")()
    obs_space = env.observation_space
    act_space = env.action_space
    env.close()

    policy, template_params = build_policy(ctx, cfg, obs_space, act_space, greedy=greedy)
    state = CheckpointManager.load(
        ckpt_path, templates={"params": jax.device_get(template_params)}
    )
    params = extract_policy_params(state, cfg, policy.family)
    if policy.family == "sac":
        params = params["actor"]
    policy.params = ctx.replicate(params)
    return policy
