"""Logging: versioned run directories + TensorBoard writer.

Reference behavior (``sheeprl/utils/logger.py:12-89``): rank-0 creates a versioned log
dir ``logs/runs/<algo>/<env>/<timestamp>/version_N`` and broadcasts it to all ranks.  In
single-controller JAX there is one python process per host; the dir is created by
process 0 and shared via ``multihost_utils`` when running multi-host.
"""

from __future__ import annotations

import json
import os
import pathlib
import time
from typing import Any, Dict, Optional

import jax
import numpy as np


def get_log_dir(cfg: Dict[str, Any], root_dir: Optional[str] = None, run_name: Optional[str] = None) -> str:
    root_dir = root_dir if root_dir is not None else cfg["root_dir"]
    run_name = run_name if run_name is not None else cfg["run_name"]
    base = pathlib.Path(cfg.get("log_root", "logs")) / "runs" / root_dir / run_name
    if jax.process_index() == 0:
        base.mkdir(parents=True, exist_ok=True)
        versions = [int(p.name.split("_")[1]) for p in base.glob("version_*") if p.name.split("_")[-1].isdigit()]
        version = max(versions) + 1 if versions else 0
        log_dir = base / f"version_{version}"
        log_dir.mkdir(parents=True, exist_ok=True)
        path = str(log_dir)
    else:
        path = ""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        path = multihost_utils.broadcast_one_to_all(
            np.frombuffer(path.ljust(512).encode(), dtype=np.uint8)
        )
        path = bytes(np.asarray(path)).decode().rstrip()
    return path


class TensorBoardLogger:
    """Minimal TB scalar writer; uses tensorboard's SummaryWriter when available and
    falls back to JSONL so logging never becomes a hard dependency."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        self._writer = None
        self._jsonl = None
        self._closed = False
        if jax.process_index() != 0:
            return
        try:
            # tensorboardX first: pure-python writer.  torch.utils.tensorboard pulls
            # in a TensorFlow runtime whose GL-adjacent symbols segfault MuJoCo's
            # EGL renderer in-process (dm_control pixel envs).
            from tensorboardX import SummaryWriter

            self._writer = SummaryWriter(log_dir=log_dir)
        except Exception:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._writer = SummaryWriter(log_dir=log_dir)
            except Exception:
                self._jsonl = open(os.path.join(log_dir, "metrics.jsonl"), "a")

    def log_metrics(self, metrics: Dict[str, float], step: int) -> None:
        if jax.process_index() != 0 or self._closed:
            return
        if self._writer is not None:
            for k, v in metrics.items():
                self._writer.add_scalar(k, float(v), global_step=step)
        elif self._jsonl is not None:
            self._jsonl.write(json.dumps({"step": step, "time": time.time(), **metrics}) + "\n")
            self._jsonl.flush()

    def log_hyperparams(self, cfg: Dict[str, Any]) -> None:
        if self._writer is not None:
            try:
                self._writer.add_text("config", "```yaml\n" + json.dumps(cfg, default=str, indent=2) + "\n```")
            except Exception:
                pass

    def close(self) -> None:
        self._closed = True
        if self._writer is not None:
            self._writer.close()
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None


class MlflowLogger:
    """MLflow experiment-tracking logger (reference ``utils/logger.py:12-36`` +
    ``configs/logger/mlflow.yaml:1``), sharing the run-dir contract with the TB
    logger: the versioned ``log_dir`` still holds config.yaml/checkpoints; metrics
    additionally stream to the MLflow tracking server.  Rank-0 only, like the
    reference's rank-zero-experiment guard."""

    def __init__(
        self,
        log_dir: str,
        tracking_uri: Optional[str] = None,
        experiment_name: Optional[str] = None,
        run_name: Optional[str] = None,
        run_id: Optional[str] = None,
    ):
        self.log_dir = log_dir
        self._run = None
        if jax.process_index() != 0:
            return
        import mlflow  # guarded by get_logger

        self._mlflow = mlflow
        if tracking_uri or os.environ.get("MLFLOW_TRACKING_URI"):
            mlflow.set_tracking_uri(tracking_uri or os.environ["MLFLOW_TRACKING_URI"])
        if experiment_name:
            mlflow.set_experiment(experiment_name)
        self._run = mlflow.start_run(run_id=run_id, run_name=run_name)
        self.run_id = self._run.info.run_id

    def log_metrics(self, metrics: Dict[str, float], step: int) -> None:
        if self._run is None:
            return
        self._mlflow.log_metrics({k: float(v) for k, v in metrics.items()}, step=int(step))

    def log_hyperparams(self, cfg: Dict[str, Any]) -> None:
        if self._run is None:
            return

        def _flatten(d, prefix=""):
            out = {}
            for k, v in d.items():
                key = f"{prefix}{k}"
                if isinstance(v, dict):
                    out.update(_flatten(v, key + "."))
                else:
                    out[key] = str(v)[:500]  # mlflow param value limit
            return out

        try:
            self._mlflow.log_params(_flatten(dict(cfg)))
        except Exception:
            pass  # params exceeding server limits must not kill the run

    def close(self) -> None:
        if self._run is not None:
            self._mlflow.end_run()
            self._run = None


def get_logger(cfg: Dict[str, Any], log_dir: str) -> Optional[TensorBoardLogger]:
    if cfg.get("metric", {}).get("log_level", 1) == 0:
        return None
    logger_cfg = cfg.get("logger", {}) or {}
    if logger_cfg.get("name") == "mlflow":
        from sheeprl_tpu.utils.imports import _IS_MLFLOW_AVAILABLE

        if not _IS_MLFLOW_AVAILABLE:
            raise ModuleNotFoundError(
                "logger=mlflow requires the 'mlflow' package (reference guards it the "
                "same way, utils/imports.py); install it or use logger=default"
            )
        return MlflowLogger(
            log_dir,
            tracking_uri=logger_cfg.get("tracking_uri"),
            experiment_name=logger_cfg.get("experiment_name"),
            run_name=logger_cfg.get("run_name"),
            run_id=logger_cfg.get("run_id"),
        )
    return TensorBoardLogger(log_dir)
