"""Environment factory (reference: ``/root/reference/sheeprl/utils/env.py:26-231``).

Builds the wrapper pipeline: adapter → ActionRepeat → MaskVelocity → dict-obs coercion →
cv2 resize/grayscale → FrameStack → ActionsAsObservation → RewardAsObservation →
TimeLimit → RecordEpisodeStatistics → RecordVideo.  Observation contract downstream:
every env exposes a ``Dict`` space; CNN keys are uint8 channel-first ``[C, H, W]``
(``[stack, C, H, W]`` with frame stacking); MLP keys are flat float arrays.

Vector envs use gymnasium's Sync/AsyncVectorEnv in ``SAME_STEP`` autoreset mode, which
matches the reference's gym-0.29 semantics (reset obs returned on the done step, final
obs in ``info["final_obs"]``).
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Callable, Dict, Optional

import cv2
import gymnasium as gym
import numpy as np

from sheeprl_tpu.envs.wrappers import (
    ActionRepeat,
    ActionsAsObservationWrapper,
    FrameStack,
    GrayscaleRenderWrapper,
    MaskVelocityWrapper,
    RewardAsObservationWrapper,
)
from sheeprl_tpu.utils.imports import instantiate


class _PixelObservationWrapper(gym.Wrapper):
    """Add a render-based pixel key to a vector-only env (replaces the removed
    ``gym.wrappers.PixelObservationWrapper`` the reference relied on)."""

    def __init__(self, env: gym.Env, pixel_key: str, state_key: Optional[str] = None):
        super().__init__(env)
        self._pixel_key = pixel_key
        self._state_key = state_key
        frame = self._render_frame(reset_first=True)
        spaces = {pixel_key: gym.spaces.Box(0, 255, shape=frame.shape, dtype=np.uint8)}
        if state_key is not None:
            spaces[state_key] = env.observation_space
        self.observation_space = gym.spaces.Dict(spaces)

    def _render_frame(self, reset_first: bool = False) -> np.ndarray:
        if reset_first:
            self.env.reset()
        frame = self.env.render()
        if frame is None:
            raise RuntimeError(
                "Pixel observations requested but env.render() returned None; "
                "construct the env with render_mode='rgb_array'."
            )
        return np.asarray(frame)

    def _obs(self, obs: Any) -> Dict[str, Any]:
        out = {self._pixel_key: self._render_frame()}
        if self._state_key is not None:
            out[self._state_key] = obs
        return out

    def step(self, action):
        obs, reward, done, truncated, info = self.env.step(action)
        return self._obs(obs), reward, done, truncated, info

    def reset(self, seed=None, options=None):
        obs, info = self.env.reset(seed=seed, options=options)
        return self._obs(obs), info


class _DictObservation(gym.ObservationWrapper):
    """Wrap a plain Box observation into a single-key dict."""

    def __init__(self, env: gym.Env, key: str):
        super().__init__(env)
        self._key = key
        self.observation_space = gym.spaces.Dict({key: env.observation_space})

    def observation(self, observation):
        return {self._key: observation}


class _ImageTransform(gym.ObservationWrapper):
    """Resize / grayscale / channel-first coercion of CNN keys (reference ``:161-198``)."""

    def __init__(self, env: gym.Env, cnn_keys, screen_size: int, grayscale: bool):
        super().__init__(env)
        self._cnn_keys = list(cnn_keys)
        self._screen_size = screen_size
        self._grayscale = grayscale
        spaces = dict(env.observation_space.spaces)
        channels = 1 if grayscale else 3
        for k in self._cnn_keys:
            spaces[k] = gym.spaces.Box(0, 255, (channels, screen_size, screen_size), np.uint8)
        self.observation_space = gym.spaces.Dict(spaces)

    def observation(self, observation):
        observation = dict(observation)
        for k in self._cnn_keys:
            img = np.asarray(observation[k])
            is_3d = img.ndim == 3
            is_gray = not is_3d or img.shape[0] == 1 or img.shape[-1] == 1
            channel_first = not is_3d or img.shape[0] in (1, 3)
            if not is_3d:
                img = img[None]
            if channel_first:
                img = np.transpose(img, (1, 2, 0))
            if img.shape[:2] != (self._screen_size, self._screen_size):
                img = cv2.resize(img, (self._screen_size, self._screen_size), interpolation=cv2.INTER_AREA)
            if self._grayscale and not is_gray:
                img = cv2.cvtColor(img, cv2.COLOR_RGB2GRAY)
            if img.ndim == 2:
                img = img[..., None]
                if not self._grayscale:
                    img = np.repeat(img, 3, axis=-1)
            observation[k] = np.transpose(img, (2, 0, 1)).astype(np.uint8)
        return observation


def make_env(
    cfg: Dict[str, Any],
    seed: int,
    rank: int,
    run_name: Optional[str] = None,
    prefix: str = "",
    vector_env_idx: int = 0,
) -> Callable[[], gym.Env]:
    def thunk() -> gym.Env:
        instantiate_kwargs = {}
        if "seed" in cfg.env.wrapper:
            instantiate_kwargs["seed"] = seed
        if "rank" in cfg.env.wrapper:
            instantiate_kwargs["rank"] = rank + vector_env_idx
        env = instantiate(cfg.env.wrapper, **instantiate_kwargs)

        if cfg.env.action_repeat > 1:
            env = ActionRepeat(env, cfg.env.action_repeat)
        if cfg.env.get("mask_velocities", False):
            env = MaskVelocityWrapper(env)

        cnn_sel = list(cfg.algo.cnn_keys.encoder or [])
        mlp_sel = list(cfg.algo.mlp_keys.encoder or [])
        if len(cnn_sel) + len(mlp_sel) == 0:
            raise ValueError(
                "`algo.cnn_keys.encoder` and `algo.mlp_keys.encoder` must be lists with at "
                f"least one key overall, got: cnn={cnn_sel} mlp={mlp_sel}"
            )

        # Coerce the observation space to a Dict (reference ``:98-140``).
        obs_space = env.observation_space
        if isinstance(obs_space, gym.spaces.Box) and len(obs_space.shape) < 2:
            if cnn_sel:
                if len(cnn_sel) > 1:
                    warnings.warn(f"Only one pixel obs allowed for {cfg.env.id}; keeping {cnn_sel[0]}")
                env = _PixelObservationWrapper(
                    env, pixel_key=cnn_sel[0], state_key=mlp_sel[0] if mlp_sel else None
                )
            else:
                if len(mlp_sel) > 1:
                    warnings.warn(f"Only one vector obs allowed for {cfg.env.id}; keeping {mlp_sel[0]}")
                env = _DictObservation(env, mlp_sel[0])
        elif isinstance(obs_space, gym.spaces.Box) and 2 <= len(obs_space.shape) <= 3:
            if not cnn_sel:
                raise ValueError(
                    "Pixel observation selected but no cnn key specified: set `algo.cnn_keys.encoder=[your_key]`"
                )
            if len(cnn_sel) > 1:
                warnings.warn(f"Only one pixel obs allowed for {cfg.env.id}; keeping {cnn_sel[0]}")
            env = _DictObservation(env, cnn_sel[0])

        if not isinstance(env.observation_space, gym.spaces.Dict):
            raise RuntimeError(f"Unsupported observation space: {env.observation_space}")
        env_keys = set(env.observation_space.spaces.keys())
        if not env_keys.intersection(cnn_sel + mlp_sel):
            raise ValueError(
                f"The user-specified keys {cnn_sel + mlp_sel} are not a subset of the "
                f"environment observation keys {sorted(env_keys)}."
            )

        env_cnn_keys = {k for k in env_keys if len(env.observation_space[k].shape) in (2, 3)}
        cnn_keys = sorted(env_cnn_keys.intersection(cnn_sel))
        if cnn_keys:
            env = _ImageTransform(env, cnn_keys, cfg.env.screen_size, cfg.env.grayscale)
            if cfg.env.frame_stack > 1:
                if cfg.env.frame_stack_dilation <= 0:
                    raise ValueError(
                        f"The frame stack dilation argument must be greater than zero, got: {cfg.env.frame_stack_dilation}"
                    )
                env = FrameStack(env, cfg.env.frame_stack, cnn_keys, cfg.env.frame_stack_dilation)

        if cfg.env.actions_as_observation.num_stack > 0:
            env = ActionsAsObservationWrapper(env, **cfg.env.actions_as_observation)
        if cfg.env.reward_as_observation:
            env = RewardAsObservationWrapper(env)

        env.action_space.seed(seed)
        env.observation_space.seed(seed)
        if cfg.env.max_episode_steps and cfg.env.max_episode_steps > 0:
            env = gym.wrappers.TimeLimit(env, max_episode_steps=cfg.env.max_episode_steps)
        env = gym.wrappers.RecordEpisodeStatistics(env)
        if cfg.env.capture_video and rank == 0 and vector_env_idx == 0 and run_name is not None:
            if cfg.env.grayscale:
                env = GrayscaleRenderWrapper(env)
            video_dir = os.path.join(run_name, prefix + "_videos" if prefix else "videos")
            try:
                env = gym.wrappers.RecordVideo(env, video_dir, disable_logger=True)
            except Exception as e:  # moviepy missing, no render_mode, ...
                warnings.warn(f"Disabling video capture: {e}")
        return env

    return thunk


def make_vector_env(
    cfg: Dict[str, Any],
    seed: int,
    rank: int,
    run_name: Optional[str] = None,
    prefix: str = "",
    restart_on_exception: bool = False,
) -> gym.vector.VectorEnv:
    """Build the vectorized env stack used by every training loop."""
    from gymnasium.vector import AsyncVectorEnv, AutoresetMode, SyncVectorEnv

    from sheeprl_tpu.envs.wrappers import RestartOnException

    n_envs = cfg.env.num_envs
    thunks = [
        make_env(cfg, seed + rank * n_envs + i, rank, run_name, prefix=prefix, vector_env_idx=i)
        for i in range(n_envs)
    ]
    if restart_on_exception:
        thunks = [(lambda fn=fn: RestartOnException(fn)) for fn in thunks]

    # Shared-memory multi-process pool (sheeprl_tpu/rollout): same SAME_STEP
    # semantics, workers stepping concurrently, watchdog + restart robustness.
    pool_cfg = cfg.env.get("pool") or {}
    if pool_cfg.get("enabled", False):
        from sheeprl_tpu.rollout import EnvPool

        rollout_cfg = cfg.get("rollout") or {}
        return EnvPool(
            thunks,
            num_workers=pool_cfg.get("num_workers"),
            step_timeout_s=rollout_cfg.get("step_timeout_s", 60.0),
            heartbeat_interval_s=rollout_cfg.get("heartbeat_interval_s", 2.0),
            max_restarts=rollout_cfg.get("max_restarts", 3),
            restart_backoff_s=rollout_cfg.get("restart_backoff_s", 0.5),
            start_method=rollout_cfg.get("start_method"),
            autoreset_mode=AutoresetMode.SAME_STEP,
        )
    vector_cls = SyncVectorEnv if cfg.env.sync_env else AsyncVectorEnv
    return vector_cls(thunks, autoreset_mode=AutoresetMode.SAME_STEP)


def get_dummy_env(id_: str, **kwargs: Any) -> gym.Env:
    """Factory for the dummy envs by short id (``discrete_dummy`` etc.)."""
    from sheeprl_tpu.envs.dummy import ContinuousDummyEnv, DiscreteDummyEnv, MultiDiscreteDummyEnv

    if "continuous" in id_:
        return ContinuousDummyEnv(**kwargs)
    if "multidiscrete" in id_:
        return MultiDiscreteDummyEnv(**kwargs)
    if "discrete" in id_:
        return DiscreteDummyEnv(**kwargs)
    raise ValueError(f"Unknown dummy env id: {id_}")
