"""Batched gradient-step dispatch: run G gradient steps as ONE jitted call.

The reference dispatches each gradient step eagerly (its train() call per step,
``/root/reference/sheeprl/algos/dreamer_v3/dreamer_v3.py:682``); on a remote
accelerator every dispatch is a host→device round trip, and with replay ratios of
0.5–1 the per-call latency — not the math — floors the end-to-end step rate.  Here
the per-step batches are stacked to ``[G, T, B, ...]`` and a ``lax.scan`` over the
leading axis executes the whole block inside one jit:

* ONE dispatch (and one traversal of params/opt-state through the program) per
  iteration instead of G;
* per-step PRNG keys are split INSIDE the jit from a single base key (no per-step
  host-side key-split round trips);
* the ``update_target`` cadence (every Nth cumulative step) is computed inside the
  scan from the starting step count.

``G`` is a static shape, so each distinct block size compiles once.  ``chunk_sizes``
decomposes large/irregular G (e.g. the Ratio governor's one-off pretrain burst) into
a bounded set of sizes — powers of two up to ``max_chunk`` — keeping the number of
compiled programs small no matter what replay ratio the user picks.
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence

import jax
import jax.numpy as jnp


def chunk_sizes(n: int, max_chunk: int = 8) -> List[int]:
    """Decompose ``n`` into descending powers of two ≤ ``max_chunk``.

    Every chunk size is a power of two, so across a whole run only
    ``log2(max_chunk)+1`` distinct block programs ever compile.
    """
    if n <= 0:
        return []
    out: List[int] = []
    size = max_chunk
    while n > 0 and size > 1:
        while n >= size:
            out.append(size)
            n -= size
        size //= 2
    out.extend([1] * n)
    return out


def make_train_block(step_fn: Callable, target_update_freq: int = 1, count_offset: int = 1) -> Callable:
    """Wrap a per-step ``step_fn(carry, batch, key, update_target) -> (carry,
    metrics)`` into a jitted ``block(carry, stacked_batch, base_key, start_count)``
    that scans over the leading ``G`` axis of ``stacked_batch``.

    ``carry`` is the algorithm's whole train state pytree (params, optimizer states,
    moments, ...).  ``start_count`` is the cumulative gradient-step count BEFORE this
    block; each scan step's ``update_target`` flag is computed from it, matching the
    eager loop's ``cumulative % freq == 0`` cadence — with ``count_offset=1`` the
    count is tested AFTER the increment (DV3), with ``0`` before it (DV2's hard copy
    fires on the very first step).  Returns the final carry and the LAST step's
    metrics (what the loops log).  The carry is not donated: the loops keep live
    references to params/opt-states between calls (checkpointing, acting).
    """
    freq = max(int(target_update_freq), 1)

    def block(carry, step_batches, base_key, start_count):
        # Stack the per-step batches INSIDE the jit: an eager jnp.stack per leaf
        # would cost one dispatch round trip each on a remote accelerator — the
        # exact latency this block exists to remove.
        if len(step_batches) == 1:
            stacked = jax.tree.map(lambda x: x[None], step_batches[0])
        else:
            stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *step_batches)
        G = len(step_batches)
        # Per-step keys derived in-jit from a long-lived base key + the running
        # step count: deterministic, and no host-side key-split dispatches.
        keys = jax.random.split(jax.random.fold_in(base_key, start_count), G)
        counts = jnp.asarray(start_count, jnp.int32) + count_offset + jnp.arange(G, dtype=jnp.int32)

        def step(carry, x):
            batch, key, count = x
            carry, metrics = step_fn(carry, batch, key, (count % freq) == 0)
            return carry, metrics

        carry, metrics = jax.lax.scan(step, carry, (stacked, keys, counts))
        last = jax.tree.map(lambda m: m[-1], metrics)
        return carry, last

    return jax.jit(block, static_argnames=())


class WindowedFutures:
    """Deferred metrics + window-based throughput bookkeeping.

    Training loops ``track()`` each dispatched block's metrics (device futures — no
    sync), ``drain()`` them into the aggregator at the log cadence (the window's only
    blocking device_get), and read ``pop_window_sps()`` for an honest end-to-end
    grad-steps/s over the window's wall-clock.
    """

    def __init__(self, max_pending: int = 256, max_spill: int = 8192):
        self._pending: List[Any] = []
        self._spill: List[Any] = []  # host-side metrics fetched early (backlog cap)
        self._max_pending = max_pending
        self._max_spill = max_spill
        self._warned_trim = False
        self._window_grad_steps = 0
        self._window_t0 = 0.0

    def track(self, metrics: Any, n_steps: int) -> None:
        import time

        if self._window_grad_steps == 0:
            self._window_t0 = time.perf_counter()
        self._pending.append(metrics)
        self._window_grad_steps += n_steps
        if len(self._pending) >= self._max_pending:
            # Bound the device-future backlog between flushes; the values are kept
            # host-side so the next drain still aggregates them.  Only if no drain
            # ever comes (e.g. logging disabled) does the spill itself get trimmed —
            # bounded memory beats an unobservable full history — and trimming warns
            # once, since with logging enabled it means log_every spans more blocks
            # than the window can hold.
            self._spill.extend(jax.device_get(self._pending))
            self._pending.clear()
            if len(self._spill) > self._max_spill:
                if not self._warned_trim:
                    self._warned_trim = True
                    import logging

                    logging.getLogger(__name__).warning(
                        "metrics window exceeded %d gradient blocks without a drain; "
                        "oldest entries dropped (lower metric.log_every to keep full "
                        "window statistics).",
                        self._max_spill,
                    )
                del self._spill[: len(self._spill) - self._max_spill]

    def drain(self, aggregator) -> None:
        if not self._pending and not self._spill:
            return
        fetched = self._spill + (jax.device_get(self._pending) if self._pending else [])
        self._pending.clear()
        self._spill.clear()
        if aggregator is not None:
            for chunk in fetched:
                for k, v in chunk.items():
                    aggregator.update(k, float(v))

    def pop_window_sps(self):
        import time

        if self._window_grad_steps == 0:
            return None
        sps = self._window_grad_steps / max(time.perf_counter() - self._window_t0, 1e-9)
        self._window_grad_steps = 0
        return sps


class BlockDispatcher:
    """Per-loop driver around :func:`make_train_block`: dispatches an iteration's
    gradient steps as chunked scan calls, keeps the metrics ON DEVICE as futures, and
    reports a window-based end-to-end grad-steps/s.

    Usage per iteration (BEFORE stepping the envs, so the device trains while the
    host walks the environments)::

        carry = dispatcher.dispatch(carry, sample_entries, key, start_count)

    and at the log cadence::

        dispatcher.drain(aggregator)          # the window's only blocking sync
        sps = dispatcher.pop_window_sps()     # grad-steps/s over the window, or None
    """

    def __init__(
        self,
        step_fn: Callable,
        target_update_freq: int = 1,
        max_chunk: int = 8,
        count_offset: int = 1,
        base_key=None,
    ):
        self._block = make_train_block(step_fn, target_update_freq, count_offset)
        self._max_chunk = max_chunk
        self._futures = WindowedFutures()
        # Long-lived device-resident base key: per-chunk keys derive from it
        # in-jit (fold_in with the running step count), so dispatch() performs
        # zero host-side PRNG ops.  Must be process-identical in multi-host runs
        # (pass ctx.rng()).
        self._base_key = base_key

    def dispatch(self, carry, entries: Sequence[Any], start_count: int):
        """Run ``len(entries)`` gradient steps (chunked powers of two); returns the
        new carry (device futures — nothing blocks here)."""
        offset = 0
        for size in chunk_sizes(len(entries), self._max_chunk):
            chunk = tuple(entries[offset : offset + size])
            offset += size
            carry, metrics = self._block(carry, chunk, self._base_key, start_count)
            start_count += size
            self._futures.track(metrics, size)
        return carry

    def drain(self, aggregator) -> None:
        """Fetch every pending metrics future (one blocking device_get) and feed the
        aggregator; the sync point that makes the window wall-clock honest."""
        self._futures.drain(aggregator)

    def pop_window_sps(self):
        """End-to-end grad-steps/s since the window opened (None if no steps ran);
        resets the window.  Call right after :meth:`drain`."""
        return self._futures.pop_window_sps()


class FusedRingDispatcher:
    """Dispatcher for the SAC family's fused scanned update blocks over the
    device-resident transition ring (``data/device_buffer.py``).

    Where :class:`IndexedBlockDispatcher` still ships host-sampled ``[G, B]``
    index arrays, here even the index sampling happens INSIDE the jit from the
    carried PRNG key: the host passes only the ring handle, the filled-row count
    and the cumulative step counters, so a whole K-step UTD block (DroQ: 20 critic
    updates + the actor update) is ONE dispatch with zero per-step host work.

    ``block_builder(k, last)`` returns the python block function for a ``k``-step
    chunk; ``last`` marks the chunk that closes the iteration's block (DroQ runs
    its once-per-iteration actor update only there — builders without per-block
    tails ignore it, and ``last_sensitive=False`` caches on ``k`` alone).  Blocks
    are jitted with ``donate_argnums=(0,)``: the carry (params + optimizer state)
    is donated and updated in place — callers MUST rebind the carry from the
    return value and never reuse a pre-dispatch reference (jaxlint JL005).

    Program-count bound: each distinct ``k`` compiles once and is dispatched
    exactly K→1; once ``max_programs`` distinct sizes exist, new irregular sizes
    decompose into cached powers of two (:func:`chunk_sizes`) instead of
    compiling more programs.  The steady-state Ratio/UTD count is constant, so
    real runs stay at one program (plus the pretrain burst's chunks).
    """

    def __init__(
        self,
        block_builder: Callable,
        base_key=None,
        max_programs: int = 8,
        max_chunk: int = 8,
        last_sensitive: bool = False,
        futures: "WindowedFutures" = None,
        cfg=None,
        perf_name: str = None,
    ):
        self._builder = block_builder
        self._blocks: dict = {}
        # Perf cost-model registration (obs/perf.py): each distinct chunk size is
        # its own compiled program, so each registers its own FLOPs model.
        self._cfg = cfg
        self._perf_name = perf_name
        self._base_key = base_key
        self._max_programs = max_programs
        self._max_chunk = max_chunk
        self._last_sensitive = last_sensitive
        # Loops that mix host/device paths pass their own WindowedFutures so one
        # drain covers whichever path dispatched.
        self._futures = futures if futures is not None else WindowedFutures()
        # dispatches() counts jit calls — the parity tests assert K→1 per block.
        self.dispatch_count = 0

    def _plan(self, n: int) -> List[int]:
        if n <= 0:
            return []
        if any(k == n for (k, _) in self._blocks) or len(self._blocks) < self._max_programs:
            return [n]
        return chunk_sizes(n, self._max_chunk)

    def _get(self, k: int, last: bool):
        cache_key = (k, last if self._last_sensitive else True)
        block = self._blocks.get(cache_key)
        if block is None:
            block = jax.jit(self._builder(k, cache_key[1]), donate_argnums=(0,))
            if self._perf_name:
                from sheeprl_tpu.obs import perf as obs_perf

                block = obs_perf.instrument(self._cfg, f"{self._perf_name}_k{k}", block)
            self._blocks[cache_key] = block
        return block

    def dispatch(self, carry, ring_arrays: dict, filled: int, rows_added: int, n: int, start_count: int):
        """Run ``n`` gradient steps as one fused block (or cached-size chunks);
        returns the new carry.  Nothing blocks here — metrics stay device futures."""
        sizes = self._plan(n)
        for i, size in enumerate(sizes):
            block = self._get(size, i == len(sizes) - 1)
            carry, metrics = block(carry, ring_arrays, filled, rows_added, self._base_key, start_count)
            self.dispatch_count += 1
            start_count += size
            self._futures.track(metrics, size)
        return carry

    def drain(self, aggregator) -> None:
        self._futures.drain(aggregator)

    def pop_window_sps(self):
        return self._futures.pop_window_sps()


class IndexedBlockDispatcher:
    """BlockDispatcher variant for the device-resident replay mirror
    (``data/device_buffer.py``): the host ships only ``[G, B]`` (env, start) index
    arrays; each scan step GATHERS its ``[T, B]`` batch from the mirror inside the
    jit before running the train step.  Zero bulk host→device traffic per block."""

    def __init__(
        self,
        step_fn: Callable,
        gather_fn: Callable,
        target_update_freq: int = 1,
        max_chunk: int = 8,
        count_offset: int = 1,
        base_key=None,
        globalize: Callable = None,
    ):
        freq = max(int(target_update_freq), 1)

        def block(carry, mirror, envs, starts, base_key, start_count):
            G = envs.shape[0]
            keys = jax.random.split(jax.random.fold_in(base_key, start_count), G)
            counts = jnp.asarray(start_count, jnp.int32) + count_offset + jnp.arange(G, dtype=jnp.int32)

            def step(carry, x):
                e, s, key, count = x
                batch = gather_fn(mirror, e, s)
                carry, metrics = step_fn(carry, batch, key, (count % freq) == 0)
                return carry, metrics

            carry, metrics = jax.lax.scan(step, carry, (envs, starts, keys, counts))
            return carry, jax.tree.map(lambda m: m[-1], metrics)

        self._block = jax.jit(block)
        self._max_chunk = max_chunk
        self._futures = WindowedFutures()
        self._base_key = base_key
        # Multi-process hook (MultiProcessDeviceReplayMirror.globalize_indices):
        # turns each chunk's per-process [size, B_local] numpy index block into
        # batch-sharded global arrays.  None = single-process, numpy goes in as-is.
        self._globalize = globalize

    def dispatch(self, carry, mirror: dict, envs, starts, start_count: int):
        """``envs``/``starts``: ``[G, B]`` numpy int arrays (per-process local under
        multi-process).  Returns the new carry (device futures — nothing blocks
        here)."""
        import numpy as np

        G = envs.shape[0]
        offset = 0
        for size in chunk_sizes(G, self._max_chunk):
            e = np.ascontiguousarray(envs[offset : offset + size], dtype=np.int32)
            s = np.ascontiguousarray(starts[offset : offset + size], dtype=np.int32)
            offset += size
            if self._globalize is not None:
                e, s = self._globalize(e, s)
            carry, metrics = self._block(carry, mirror, e, s, self._base_key, start_count)
            start_count += size
            self._futures.track(metrics, size)
        return carry

    def drain(self, aggregator) -> None:
        self._futures.drain(aggregator)

    def pop_window_sps(self):
        return self._futures.pop_window_sps()


