"""Algorithm / evaluation registries.

Mirrors the decorator-based registry of the reference
(``/root/reference/sheeprl/utils/registry.py:11-108``): each algorithm module registers a
train entrypoint with ``@register_algorithm()`` and an eval entrypoint with
``@register_evaluation()``; the CLI dispatches by ``cfg.algo.name``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

# name -> {"module": str, "entrypoint": callable, "decoupled": bool}
algorithm_registry: Dict[str, Dict[str, Any]] = {}
# name -> callable
evaluation_registry: Dict[str, Callable] = {}


def register_algorithm(name: str | None = None, decoupled: bool = False):
    def decorator(fn: Callable) -> Callable:
        algo_name = name or fn.__module__.split(".")[-1]
        algorithm_registry[algo_name] = {
            "module": fn.__module__,
            "entrypoint": fn,
            "decoupled": decoupled,
        }
        return fn

    return decorator


def register_evaluation(algorithms: str | list | None = None):
    def decorator(fn: Callable) -> Callable:
        names = algorithms
        if names is None:
            names = [fn.__module__.split(".")[-2]]
        if isinstance(names, str):
            names = [names]
        for n in names:
            evaluation_registry[n] = fn
        return fn

    return decorator


def get_algorithm(name: str) -> Dict[str, Any]:
    if name not in algorithm_registry:
        raise ValueError(
            f"Algorithm '{name}' is not registered. Available: {sorted(algorithm_registry)}"
        )
    return algorithm_registry[name]


def get_evaluation(name: str) -> Callable:
    if name not in evaluation_registry:
        raise ValueError(
            f"No evaluation registered for '{name}'. Available: {sorted(evaluation_registry)}"
        )
    return evaluation_registry[name]
