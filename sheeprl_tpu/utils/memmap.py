"""Disk-backed ndarray with ownership transfer (reference: ``sheeprl/utils/memmap.py:22-270``).

Host-side only: replay data lives in numpy memmaps on the host; device transfer happens
explicitly at the train-step boundary.  Semantics preserved from the reference:

* ``MemmapArray(dtype, shape, mode, filename)`` creates/open a ``np.memmap``;
* ``from_array`` copies an existing ndarray in;
* pickling drops the mmap handle and transfers *ownership is not* carried across
  processes (``__getstate__`` semantics, reference ``:240-258``);
* the owner flushes and removes the file on ``__del__`` (reference ``:213-227``).
"""

from __future__ import annotations

import os
import tempfile
from pathlib import Path
from typing import Any, Optional, Tuple

import numpy as np


class MemmapArray:
    def __init__(
        self,
        dtype: Any = np.float32,
        shape: Tuple[int, ...] = (),
        mode: str = "r+",
        filename: Optional[os.PathLike] = None,
    ):
        self._dtype = np.dtype(dtype)
        self._shape = tuple(shape)
        if filename is None:
            fd, filename = tempfile.mkstemp(suffix=".memmap")
            os.close(fd)
            mode = "w+"
        else:
            Path(filename).parent.mkdir(parents=True, exist_ok=True)
            if not Path(filename).exists():
                mode = "w+"
        self._filename = str(Path(filename).resolve())
        self._mode = mode
        self._array: Optional[np.memmap] = np.memmap(self._filename, dtype=self._dtype, mode=mode, shape=self._shape)
        self._has_ownership = True

    @property
    def filename(self) -> str:
        return self._filename

    @property
    def dtype(self):
        return self._dtype

    @property
    def shape(self) -> Tuple[int, ...]:
        return self._shape

    @property
    def has_ownership(self) -> bool:
        return self._has_ownership

    @has_ownership.setter
    def has_ownership(self, value: bool) -> None:
        self._has_ownership = bool(value)

    @property
    def array(self) -> np.memmap:
        if self._array is None:
            self._array = np.memmap(self._filename, dtype=self._dtype, mode="r+", shape=self._shape)
        return self._array

    @array.setter
    def array(self, value: np.ndarray) -> None:
        if value.shape != self._shape:
            raise ValueError(f"shape mismatch: {value.shape} vs {self._shape}")
        self.array[:] = value

    @classmethod
    def from_array(
        cls,
        array: np.ndarray,
        filename: Optional[os.PathLike] = None,
    ) -> "MemmapArray":
        if isinstance(array, MemmapArray):
            src = array.array
            out = cls(dtype=src.dtype, shape=src.shape, filename=filename)
            same_file = out.filename == array.filename
            if not same_file:
                out.array[:] = src
            else:
                # Same backing file: the new instance does not steal ownership.
                out._has_ownership = False
            return out
        array = np.asarray(array)
        out = cls(dtype=array.dtype, shape=array.shape, filename=filename)
        out.array[:] = array
        return out

    # -- numpy interop ------------------------------------------------------
    def __array__(self, dtype=None, copy=None) -> np.ndarray:
        arr = self.array
        if dtype is not None:
            arr = arr.astype(dtype, copy=False)
        return np.array(arr, copy=True) if copy else np.asarray(arr)

    def __getitem__(self, idx):
        return self.array[idx]

    def __setitem__(self, idx, value):
        self.array[idx] = value

    def __len__(self) -> int:
        return self._shape[0] if self._shape else 0

    def flush(self) -> None:
        """Force buffered writes to the backing file (checkpoint durability)."""
        if self._array is not None:
            self._array.flush()

    def __repr__(self) -> str:
        return f"MemmapArray(shape={self._shape}, dtype={self._dtype}, file={self._filename})"

    # -- pickling: drop the live mmap handle (reference :240-258) -----------
    def __getstate__(self) -> dict:
        state = self.__dict__.copy()
        state["_array"] = None
        state["_has_ownership"] = False
        return state

    def __setstate__(self, state: dict) -> None:
        self.__dict__.update(state)

    def __del__(self) -> None:
        try:
            if self._array is not None:
                self._array.flush()
            if getattr(self, "_has_ownership", False) and os.path.isfile(self._filename):
                del self._array
                self._array = None
                os.unlink(self._filename)
        except Exception:
            pass
