"""Named wall-clock timers (reference: ``sheeprl/utils/timer.py:16-83``).

Class-level registry of named accumulating timers usable as context managers; drives the
``Time/sps_train`` / ``Time/sps_env_interaction`` throughput metrics.

Every timed block is also a *span*: when a ``sheeprl_tpu.obs`` tracer is active, the
``with timer(...)`` instrumentation already present in the algorithm loops feeds the
hierarchical span tracer (Chrome-trace export + latency histograms) for free.  With no
tracer active the hook is one global load + ``is None`` check.
"""

from __future__ import annotations

import time
from typing import Dict

from sheeprl_tpu.obs import tracer as _tracer


class timer:
    disabled: bool = False
    _registry: Dict[str, float] = {}

    def __init__(self, name: str):
        self.name = name
        self._start = 0.0

    def __enter__(self):
        if not timer.disabled:
            _tracer.maybe_begin(self.name)
            self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if not timer.disabled:
            elapsed = time.perf_counter() - self._start
            timer._registry[self.name] = timer._registry.get(self.name, 0.0) + elapsed
            _tracer.maybe_end(self.name)
        return False

    @classmethod
    def to_dict(cls, reset: bool = True) -> Dict[str, float]:
        out = dict(cls._registry)
        if reset:
            cls._registry.clear()
        return out

    @classmethod
    def reset(cls) -> None:
        cls._registry.clear()
