"""Named wall-clock timers (reference: ``sheeprl/utils/timer.py:16-83``).

Class-level registry of named accumulating timers usable as context managers; drives the
``Time/sps_train`` / ``Time/sps_env_interaction`` throughput metrics.
"""

from __future__ import annotations

import time
from typing import Dict


class timer:
    disabled: bool = False
    _registry: Dict[str, float] = {}

    def __init__(self, name: str):
        self.name = name
        self._start = 0.0

    def __enter__(self):
        if not timer.disabled:
            self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if not timer.disabled:
            elapsed = time.perf_counter() - self._start
            timer._registry[self.name] = timer._registry.get(self.name, 0.0) + elapsed
        return False

    @classmethod
    def to_dict(cls, reset: bool = True) -> Dict[str, float]:
        out = dict(cls._registry)
        if reset:
            cls._registry.clear()
        return out

    @classmethod
    def reset(cls) -> None:
        cls._registry.clear()
