"""Persistent XLA compilation cache wiring (PR 8), shared by train and serve.

``compile_cache.enabled=True`` points ``jax_compilation_cache_dir`` at a disk
cache keyed by HLO, with the min-compile-time / entry-size floors zeroed so even
small programs cache — a cold start wants the WHOLE program set warm, not just
the multi-second flagship dispatches.  The cache initializes lazily on the first
compile and then ignores config updates, so :func:`enable_compile_cache` also
resets it: back-to-back runs (or a serve replica started from a test harness
that already compiled something) still land in the requested dir.

``cli.run_algorithm`` calls this for training; the serve startup calls it before
precompiling its batch ladder — that cache hit is the whole warm-restart story
(``serve_startup_seconds`` in ``benchmarks/serve_bench.py``).
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Dict, Optional


def enable_compile_cache(compile_cache_cfg: Optional[Dict[str, Any]]) -> Optional[str]:
    """Wire the persistent cache when ``enabled``; returns the cache dir used."""
    compile_cache = compile_cache_cfg or {}
    if not compile_cache.get("enabled", False):
        return None
    import jax

    cache_dir = str(
        compile_cache.get("dir") or Path.home() / ".cache" / "sheeprl_tpu" / "xla_cache"
    )
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    try:
        from jax.experimental.compilation_cache import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # pragma: no cover - experimental API surface
        pass
    return cache_dir
