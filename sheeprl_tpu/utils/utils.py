"""Core math / misc utilities, JAX-native.

Re-designs of the reference helpers in ``/root/reference/sheeprl/utils/utils.py``:

* ``gae`` (reference ``:63-100``) — generalized advantage estimation as a reverse
  ``lax.scan`` instead of a python loop, so it fuses into the jitted update.
* ``symlog``/``symexp`` (``:148-153``), ``two_hot_encoder/decoder`` (``:156-205``) —
  pure ``jnp`` functions, vectorized (no scatter loop; a distance kernel over the
  support works better on the VPU).
* ``polynomial_decay`` (``:133``), ``normalize_tensor`` (``:120``) — direct equivalents.
* ``Ratio`` (``:259-300``) — host-side replay-ratio governor, Hafner semantics with
  identical state-dict fields so resume bookkeeping matches.
"""

from __future__ import annotations

import os
import random
import warnings
from typing import Any, Dict, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.config.core import DotDict as dotdict  # noqa: F401  (re-export)


def seed_everything(seed: int) -> jax.Array:
    """Seed python/numpy RNGs and return a JAX PRNG key."""
    random.seed(seed)
    np.random.seed(seed % (2**32))
    os.environ.setdefault("PYTHONHASHSEED", str(seed))
    return jax.random.PRNGKey(seed)


# ---------------------------------------------------------------------------
# Returns / advantages
# ---------------------------------------------------------------------------


def gae(
    rewards: jax.Array,
    values: jax.Array,
    dones: jax.Array,
    next_value: jax.Array,
    num_steps: int,
    gamma: float,
    gae_lambda: float,
) -> Tuple[jax.Array, jax.Array]:
    """GAE over a ``[T, n_envs, 1]`` rollout (reference: utils/utils.py:63-100).

    ``dones[t]`` marks that the episode ended *at* step t (so the bootstrap for step t is
    masked).  Returns ``(returns, advantages)`` with the same shape as ``rewards``.
    """
    not_done = 1.0 - dones.astype(values.dtype)
    next_values = jnp.concatenate([values[1:], next_value[None]], axis=0)

    def step(adv, t):
        r, v, nv, nd = t
        delta = r + gamma * nv * nd - v
        adv = delta + gamma * gae_lambda * nd * adv
        return adv, adv

    _, advs = jax.lax.scan(
        step,
        jnp.zeros_like(next_value),
        (rewards, values, next_values, not_done),
        length=num_steps,
        reverse=True,
        unroll=8,
    )
    returns = advs + values
    return returns, advs


def lambda_returns(
    rewards: jax.Array,
    values: jax.Array,
    continues: jax.Array,
    lmbda: float = 0.95,
) -> jax.Array:
    """TD(λ) returns for Dreamer-style imagination (reference: dreamer_v3/utils.py:66-77).

    All inputs ``[T, B, 1]``; ``continues`` already includes the γ factor.  Output is the
    λ-return for steps ``0..T-2`` (length T-1), bootstrapped from ``values[-1]``.
    """
    interm = rewards + continues * values * (1 - lmbda)

    def step(carry, t):
        inp, disc = t
        carry = inp + disc * lmbda * carry
        return carry, carry

    _, rets = jax.lax.scan(
        step,
        values[-1],
        (interm[:-1], continues[:-1]),
        reverse=True,
        unroll=8,
    )
    return rets


# ---------------------------------------------------------------------------
# Symlog / two-hot
# ---------------------------------------------------------------------------


def symlog(x: jax.Array) -> jax.Array:
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x: jax.Array) -> jax.Array:
    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1)


def two_hot_encoder(x: jax.Array, support_range: int = 300, num_buckets: Optional[int] = None) -> jax.Array:
    """Two-hot encode scalars ``[..., 1] -> [..., num_buckets]``.

    Matches reference ``utils/utils.py:156-194``: linear support in
    ``[-support_range, support_range]``, odd bucket count, weights proportional to the
    distance to the two neighbouring bins.
    """
    if num_buckets is None:
        num_buckets = support_range * 2 + 1
    if num_buckets % 2 == 0:
        raise ValueError("num_buckets must be odd")
    x = jnp.clip(x, -support_range, support_range)
    buckets = jnp.linspace(-support_range, support_range, num_buckets, dtype=x.dtype)
    bucket_size = (2.0 * support_range) / (num_buckets - 1) if num_buckets > 1 else 1.0
    # right index: first bucket >= x (searchsorted semantics of torch.bucketize)
    right = jnp.searchsorted(buckets, x, side="left").clip(0, num_buckets - 1)
    left = jnp.clip(right - 1, 0, num_buckets - 1)
    left_w = jnp.abs(buckets[right] - x) / bucket_size
    right_w = 1.0 - left_w
    oh_left = jax.nn.one_hot(left[..., 0], num_buckets, dtype=x.dtype) * left_w
    oh_right = jax.nn.one_hot(right[..., 0], num_buckets, dtype=x.dtype) * right_w
    return oh_left + oh_right


def two_hot_decoder(t: jax.Array, support_range: int) -> jax.Array:
    num_buckets = t.shape[-1]
    if num_buckets % 2 == 0:
        raise ValueError("support size must be odd")
    support = jnp.linspace(-support_range, support_range, num_buckets, dtype=t.dtype)
    return jnp.sum(t * support, axis=-1, keepdims=True)


# ---------------------------------------------------------------------------
# Misc
# ---------------------------------------------------------------------------


def polynomial_decay(
    current_step: int,
    *,
    initial: float = 1.0,
    final: float = 0.0,
    max_decay_steps: int = 100,
    power: float = 1.0,
) -> float:
    if current_step > max_decay_steps or initial == final:
        return final
    return (initial - final) * ((1 - current_step / max_decay_steps) ** power) + final


def normalize_tensor(x: jax.Array, eps: float = 1e-8, mask: Optional[jax.Array] = None) -> jax.Array:
    if mask is None:
        return (x - x.mean()) / (x.std() + eps)
    m = mask.astype(x.dtype)
    n = m.sum()
    mean = (x * m).sum() / n
    var = (((x - mean) ** 2) * m).sum() / jnp.maximum(n - 1, 1)
    return (x - mean) / (jnp.sqrt(var) + eps)


class Ratio:
    """Replay-ratio governor (Hafner); reference ``utils/utils.py:259-300``.

    Called with the cumulative policy-step count; returns how many gradient steps to run
    this iteration so the long-run ratio converges to ``ratio``.
    """

    def __init__(self, ratio: float, pretrain_steps: int = 0):
        if pretrain_steps < 0:
            raise ValueError(f"'pretrain_steps' must be non-negative, got {pretrain_steps}")
        if ratio < 0:
            raise ValueError(f"'ratio' must be non-negative, got {ratio}")
        self._pretrain_steps = pretrain_steps
        self._ratio = ratio
        self._prev: Optional[float] = None

    def __call__(self, step: int) -> int:
        if self._ratio == 0:
            return 0
        if self._prev is None:
            self._prev = step
            repeats = int(step * self._ratio)
            if self._pretrain_steps > 0:
                if step < self._pretrain_steps:
                    warnings.warn(
                        "pretrain_steps > current steps; clamping pretrain_steps to the "
                        "current step count to keep the requested replay ratio."
                    )
                    self._pretrain_steps = step
                repeats = int(self._pretrain_steps * self._ratio)
            return repeats
        repeats = int((step - self._prev) * self._ratio)
        self._prev += repeats / self._ratio
        return repeats

    def state_dict(self) -> Dict[str, Any]:
        return {"_ratio": self._ratio, "_prev": self._prev, "_pretrain_steps": self._pretrain_steps}

    def load_state_dict(self, state: Mapping[str, Any]) -> "Ratio":
        self._ratio = state["_ratio"]
        self._prev = state["_prev"]
        self._pretrain_steps = state["_pretrain_steps"]
        return self
