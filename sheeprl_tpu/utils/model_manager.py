"""Model registry (reference: ``/root/reference/sheeprl/utils/mlflow.py:75-328`` +
registration CLI ``cli.py:408``).

Two backends behind one API:

* ``LocalModelManager`` — a filesystem registry (JSON index + copied checkpoint
  payloads under ``<registry_dir>``).  The TPU-native default: works on any shared
  filesystem with zero extra services, which is how multi-host TPU jobs usually share
  artifacts.
* ``MlflowModelManager`` — mirrors the reference's MLflow registry operations
  (register / transition / delete / download) when ``mlflow`` is installed.

Both expose: ``register_model(ckpt_path, name, model_keys, metadata)``,
``get_models()``, ``transition_model(name, version, stage)``, ``delete_model(name,
version)`` and ``download_model(name, version, output_dir)``.

Concurrency: every ``LocalModelManager`` mutation is a read-modify-write of
``registry.json``.  Writers serialize on an ``fcntl`` advisory lock
(``registry.lock``) held across load→mutate→save, and the save itself goes
through a *unique* temp file + ``os.replace`` so readers never observe a torn
index.  A population run registering K members concurrently (or the serve CLI
racing a trainer's end-of-run registration) therefore cannot drop entries.  On
filesystems without ``flock`` support (some NFS mounts) the lock degrades to
best-effort: writes stay atomic individually, but concurrent writers should then
retry registration on a lost-version check.
"""

from __future__ import annotations

import contextlib
import json
import os
import shutil
import tempfile
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

from sheeprl_tpu.utils.imports import _IS_MLFLOW_AVAILABLE

REGISTRY_INDEX = "registry.json"
REGISTRY_LOCK = "registry.lock"


class LocalModelManager:
    def __init__(self, registry_dir: str = "models_registry"):
        self.registry_dir = Path(registry_dir)
        self.registry_dir.mkdir(parents=True, exist_ok=True)
        self._index_path = self.registry_dir / REGISTRY_INDEX
        self._lock_path = self.registry_dir / REGISTRY_LOCK

    # -- index ---------------------------------------------------------------
    @contextlib.contextmanager
    def _locked(self):
        """Advisory inter-process lock around a read-modify-write of the index."""
        try:
            import fcntl
        except ImportError:  # pragma: no cover - non-POSIX fallback
            yield
            return
        with open(self._lock_path, "a+") as lock_f:
            try:
                fcntl.flock(lock_f.fileno(), fcntl.LOCK_EX)
            except OSError:  # pragma: no cover - e.g. NFS without lock support
                yield
                return
            try:
                yield
            finally:
                fcntl.flock(lock_f.fileno(), fcntl.LOCK_UN)

    def _load(self) -> Dict[str, Any]:
        if self._index_path.is_file():
            with open(self._index_path) as f:
                return json.load(f)
        return {}

    def _save(self, index: Dict[str, Any]) -> None:
        # Unique temp name per writer: a shared .tmp would let two concurrent
        # savers interleave write/replace and publish a torn or stale index.
        fd, tmp_name = tempfile.mkstemp(
            prefix=f".{REGISTRY_INDEX}.", suffix=".tmp", dir=self.registry_dir
        )
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(index, f, indent=2)
            os.replace(tmp_name, self._index_path)
        except BaseException:
            with contextlib.suppress(OSError):
                os.unlink(tmp_name)
            raise

    @staticmethod
    def _find_run_config(src: Path) -> Optional[Path]:
        """The training run's config.yaml for a checkpoint dir, searched the same
        way ``cli._load_checkpoint_cfg`` does (run dir, then the checkpoints dir,
        then inside the payload itself for re-registered downloads)."""
        candidates = [src / "config.yaml"] if src.is_dir() else []
        candidates += [src.parent.parent / "config.yaml", src.parent / "config.yaml"]
        for cand in candidates:
            if cand.is_file():
                return cand
        return None

    # -- API -----------------------------------------------------------------
    def register_model(
        self,
        ckpt_path: str,
        name: str,
        model_keys: Optional[List[str]] = None,
        metadata: Optional[Dict[str, Any]] = None,
    ) -> int:
        """Copy the checkpoint payload into the registry as a new version of ``name``
        (reference ``register_model``, ``mlflow.py:75-150``).

        The run's ``config.yaml`` rides along inside the version dir so the payload
        is self-contained: evaluation and the serve CLI can rebuild the agent from
        the registry alone, without the original run directory."""
        src = Path(ckpt_path)
        run_cfg = self._find_run_config(src)
        with self._locked():
            index = self._load()
            entry = index.setdefault(name, {"versions": []})
            versions = entry["versions"]
            version = (max((v["version"] for v in versions), default=0)) + 1
            dest = self.registry_dir / name / f"v{version}"
            dest.parent.mkdir(parents=True, exist_ok=True)
            if src.is_dir():
                shutil.copytree(src, dest, dirs_exist_ok=True)
            else:
                dest.mkdir(parents=True, exist_ok=True)
                shutil.copy2(src, dest / src.name)
            if run_cfg is not None and not (dest / "config.yaml").is_file():
                shutil.copy2(run_cfg, dest / "config.yaml")
            versions.append(
                {
                    "version": version,
                    "path": str(dest),
                    "source_checkpoint": str(src),
                    "model_keys": list(model_keys or []),
                    "stage": "None",
                    "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
                    "metadata": metadata or {},
                }
            )
            self._save(index)
        return version

    def get_models(self) -> Dict[str, Any]:
        return self._load()

    def _version_entry(self, index, name: str, version: Optional[int]):
        if name not in index or not index[name]["versions"]:
            raise ValueError(f"No registered model named {name!r}")
        versions = index[name]["versions"]
        if version is None:
            return versions[-1]
        for entry in versions:
            if entry["version"] == version:
                return entry
        raise ValueError(f"Model {name!r} has no version {version}")

    def transition_model(self, name: str, version: Optional[int], stage: str) -> None:
        """Move a version to a stage (staging/production/archived), like the reference's
        MLflow stage transition (``mlflow.py:152-200``)."""
        with self._locked():
            index = self._load()
            self._version_entry(index, name, version)["stage"] = stage
            self._save(index)

    def delete_model(self, name: str, version: Optional[int] = None) -> None:
        with self._locked():
            index = self._load()
            if version is None:
                for entry in index.get(name, {}).get("versions", []):
                    shutil.rmtree(entry["path"], ignore_errors=True)
                index.pop(name, None)
            else:
                entry = self._version_entry(index, name, version)
                shutil.rmtree(entry["path"], ignore_errors=True)
                index[name]["versions"] = [
                    e for e in index[name]["versions"] if e["version"] != version
                ]
            self._save(index)

    def download_model(self, name: str, version: Optional[int], output_dir: str) -> Path:
        index = self._load()
        entry = self._version_entry(index, name, version)
        dest = Path(output_dir) / name / f"v{entry['version']}"
        shutil.copytree(entry["path"], dest, dirs_exist_ok=True)
        return dest


class MlflowModelManager:
    """Reference-parity MLflow backend (``mlflow.py:75-328``); requires ``mlflow``."""

    def __init__(self, tracking_uri: Optional[str] = None):
        if not _IS_MLFLOW_AVAILABLE:
            raise ModuleNotFoundError("mlflow is not installed; use LocalModelManager instead")
        import mlflow

        if tracking_uri:
            mlflow.set_tracking_uri(tracking_uri)
        self._mlflow = mlflow
        self._client = mlflow.MlflowClient()

    def register_model(self, ckpt_path, name, model_keys=None, metadata=None) -> int:
        with self._mlflow.start_run(run_name=f"register_{name}") as run:
            self._mlflow.log_artifacts(str(ckpt_path), artifact_path="checkpoint")
            if metadata:
                self._mlflow.log_params({k: str(v) for k, v in metadata.items()})
            version = self._mlflow.register_model(f"runs:/{run.info.run_id}/checkpoint", name)
        return int(version.version)

    def get_models(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for model in self._client.search_registered_models():
            out[model.name] = {
                "versions": [
                    {"version": int(v.version), "stage": v.current_stage, "path": v.source}
                    for v in model.latest_versions
                ]
            }
        return out

    def transition_model(self, name, version, stage) -> None:
        self._client.transition_model_version_stage(name, str(version), stage)

    def delete_model(self, name, version=None) -> None:
        if version is None:
            self._client.delete_registered_model(name)
        else:
            self._client.delete_model_version(name, str(version))

    def download_model(self, name, version, output_dir) -> Path:
        import mlflow.artifacts

        uri = f"models:/{name}/{version}"
        return Path(mlflow.artifacts.download_artifacts(artifact_uri=uri, dst_path=output_dir))


def build_model_manager(cfg) -> LocalModelManager | MlflowModelManager:
    mm_cfg = cfg.get("model_manager", {}) or {}
    backend = str(mm_cfg.get("backend", "local")).lower()
    if backend == "mlflow":
        return MlflowModelManager(tracking_uri=mm_cfg.get("tracking_uri"))
    return LocalModelManager(registry_dir=mm_cfg.get("registry_dir", "models_registry"))


def maybe_register_models(cfg, log_dir: str) -> Optional[int]:
    """End-of-training registration hook (reference calls ``register_model`` at the end
    of every algo main, e.g. ``dreamer_v3.py:769-780``)."""
    mm_cfg = cfg.get("model_manager", {}) or {}
    if mm_cfg.get("disabled", True):
        return None
    from sheeprl_tpu.checkpoint.manager import CheckpointManager

    ckpts = CheckpointManager(Path(log_dir) / "checkpoints").list_checkpoints()
    if not ckpts:
        return None
    name = mm_cfg.get("name") or f"{cfg.algo.name}_{cfg.env.id}"
    manager = build_model_manager(cfg)
    return manager.register_model(
        str(ckpts[-1]),
        name,
        model_keys=list(mm_cfg.get("models", {}) or []),
        metadata={"algo": cfg.algo.name, "env": cfg.env.id, "seed": cfg.seed, "run_name": cfg.get("run_name", "")},
    )
