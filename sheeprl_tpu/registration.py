"""Model-registration launcher (reference ``sheeprl_model_manager.py`` / console
script ``sheeprl-registration``, ``cli.py:408``):

    python -m sheeprl_tpu.registration checkpoint_path=<run>/checkpoints/ckpt_N \
        [model_manager.name=...] [overrides]

Registers a training checkpoint's models in the configured registry (local
filesystem by default, MLflow when ``model_manager.backend=mlflow``).
"""

from sheeprl_tpu.cli import registration

if __name__ == "__main__":
    registration()
