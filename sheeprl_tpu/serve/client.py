"""Synchronous serve clients: one obs in, one action (plus latency stamps) out.

A :class:`PolicyClient` wraps one framed-TCP channel and does strict
request/reply round-trips — concurrency is *many clients*, not pipelining on
one socket (the transport's ``recv`` is single-consumer).  The benchmark and
the CI smoke drive 4-32 of these from threads; a production fleet would run
one per actor process, exactly like the Sebulba actors drive their learner
channel.

A :class:`FleetClient` adds the availability layer: it takes *several*
endpoints (fleet fronts or bare replicas), fails over between them, and
retries ``draining`` / dead-connection failures with bounded exponential
backoff — the client-side half of the zero-loss contract.  Stateful policies
pass ``session=<client id>`` so the fleet keeps their recurrent act state on
one replica.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from sheeprl_tpu.distributed.transport import Channel, ChannelClosed, connect

_REQ_COUNTER = itertools.count()
_REQ_LOCK = threading.Lock()


class ServerDraining(ConnectionError):
    """The replica is draining (SIGTERM'd): retry against another replica."""


def _next_req_id() -> int:
    with _REQ_LOCK:
        return next(_REQ_COUNTER)


class PolicyClient:
    def __init__(self, host: str, port: int, timeout_s: float = 30.0):
        self.channel: Channel = connect(host, port, timeout_s=timeout_s)

    def ping(self, timeout: float = 10.0) -> Dict[str, Any]:
        """Readiness probe; returns the server's ``{policies, aliases, draining}``."""
        self.channel.send("ping")
        kind, meta, _ = self.channel.recv(timeout=timeout)
        if kind != "pong":
            raise RuntimeError(f"expected pong, got {kind!r}: {meta}")
        return meta

    def act(
        self,
        obs: Dict[str, np.ndarray],
        policy: str,
        timeout: float = 30.0,
        session: Optional[str] = None,
        reset: bool = False,
    ) -> Tuple[np.ndarray, Dict[str, Any]]:
        """One round-trip: ``(action_row, reply_meta)``.

        ``reply_meta`` carries the SLO stamps: ``queue_ms`` / ``infer_ms`` /
        ``batch_fill`` / ``bucket`` / ``p99_ms`` (the server's rolling p99 at
        reply time).  ``session`` names this client for stateful (recurrent)
        policies — the serve tier keeps the session's act state device-resident
        between calls; ``reset=True`` forces an episode restart for it.
        """
        req_id = _next_req_id()
        extra: Dict[str, Any] = {}
        if session is not None:
            extra["session"] = session
        if reset:
            extra["reset"] = True
        self.channel.send("act", payload=dict(obs), policy=policy, req_id=req_id, **extra)
        kind, meta, payload = self.channel.recv(timeout=timeout)
        if kind == "draining":
            raise ServerDraining(f"request {req_id} rejected: replica is draining")
        if kind == "error":
            raise RuntimeError(f"server error for request {req_id}: {meta.get('error')}")
        if kind != "act_result" or meta.get("req_id") != req_id:
            raise RuntimeError(f"unexpected reply {kind!r} (meta={meta}) for request {req_id}")
        return np.asarray(payload["action"]), meta

    def close(self) -> None:
        self.channel.close()

    def __enter__(self) -> "PolicyClient":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


def parse_endpoint(endpoint: Union[str, Tuple[str, int]]) -> Tuple[str, int]:
    """``"host:port"`` (or a ready ``(host, port)`` pair) → ``(host, port)``."""
    if isinstance(endpoint, (tuple, list)):
        host, port = endpoint
        return str(host), int(port)
    host, _, port = str(endpoint).rpartition(":")
    return host or "127.0.0.1", int(port)


class FleetClient:
    """Failover + retry over several serve endpoints (fronts or bare replicas).

    Each :meth:`act` keeps one endpoint until it fails: ``draining`` replies,
    dead connections and connect failures rotate to the next endpoint and retry
    after a bounded exponential backoff (``backoff_s`` doubling per consecutive
    failure up to ``backoff_max_s``, at most ``max_attempts`` tries per call).
    Server-side ``error`` replies are NOT retried — they are deterministic
    (unknown policy, malformed obs) and would fail everywhere.

    ``session`` (constructor or per-call) tags requests for stateful policies;
    note that failing over to a *different* endpoint restarts the session's
    episode on the new fleet (the state lives server-side).
    """

    def __init__(
        self,
        endpoints: Sequence[Union[str, Tuple[str, int]]],
        timeout_s: float = 30.0,
        max_attempts: int = 8,
        backoff_s: float = 0.05,
        backoff_max_s: float = 2.0,
        session: Optional[str] = None,
    ):
        if not endpoints:
            raise ValueError("FleetClient needs at least one endpoint")
        self.endpoints: List[Tuple[str, int]] = [parse_endpoint(e) for e in endpoints]
        self.timeout_s = float(timeout_s)
        self.max_attempts = int(max_attempts)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.session = session
        self._index = 0  # current endpoint
        self._client: Optional[PolicyClient] = None
        self.failovers = 0
        self.retries = 0

    def _connected(self) -> PolicyClient:
        if self._client is None:
            host, port = self.endpoints[self._index]
            self._client = PolicyClient(host, port, timeout_s=self.timeout_s)
        return self._client

    def _rotate(self) -> None:
        if self._client is not None:
            try:
                self._client.close()
            except Exception:
                pass
            self._client = None
        self._index = (self._index + 1) % len(self.endpoints)
        self.failovers += 1

    def act(
        self,
        obs: Dict[str, np.ndarray],
        policy: str,
        timeout: Optional[float] = None,
        session: Optional[str] = None,
        reset: bool = False,
    ) -> Tuple[np.ndarray, Dict[str, Any]]:
        timeout = self.timeout_s if timeout is None else float(timeout)
        session = session if session is not None else self.session
        consecutive = 0
        last: Optional[Exception] = None
        for _ in range(self.max_attempts):
            try:
                return self._connected().act(
                    obs, policy, timeout=timeout, session=session, reset=reset
                )
            except (ServerDraining, ChannelClosed, ConnectionError, OSError, TimeoutError) as e:
                last = e
                consecutive += 1
                self.retries += 1
                self._rotate()
                time.sleep(min(self.backoff_s * (2 ** (consecutive - 1)), self.backoff_max_s))
        raise ConnectionError(
            f"act failed after {self.max_attempts} attempts across "
            f"{len(self.endpoints)} endpoint(s): {last}"
        )

    def ping(self, timeout: float = 10.0) -> Dict[str, Any]:
        consecutive = 0
        last: Optional[Exception] = None
        for _ in range(self.max_attempts):
            try:
                return self._connected().ping(timeout=timeout)
            except (ChannelClosed, ConnectionError, OSError, TimeoutError) as e:
                last = e
                consecutive += 1
                self._rotate()
                time.sleep(min(self.backoff_s * (2 ** (consecutive - 1)), self.backoff_max_s))
        raise ConnectionError(
            f"ping failed after {self.max_attempts} attempts across "
            f"{len(self.endpoints)} endpoint(s): {last}"
        )

    def close(self) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None

    def __enter__(self) -> "FleetClient":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


def wait_for_server(
    host: str, port: int, timeout_s: float = 120.0, interval_s: float = 0.25
) -> Dict[str, Any]:
    """Poll until a replica answers a ping (startup includes AOT compilation, so
    the window is generous); returns the pong meta."""
    deadline = time.monotonic() + timeout_s
    last: Optional[Exception] = None
    while time.monotonic() < deadline:
        try:
            client = PolicyClient(host, port, timeout_s=min(5.0, timeout_s))
            try:
                return client.ping()
            finally:
                client.close()
        except Exception as e:  # noqa: BLE001 - any failure means "not up yet"
            last = e
            time.sleep(interval_s)
    raise TimeoutError(f"no serve replica at {host}:{port} within {timeout_s}s: {last}")
