"""Synchronous serve client: one obs in, one action (plus latency stamps) out.

A :class:`PolicyClient` wraps one framed-TCP channel and does strict
request/reply round-trips — concurrency is *many clients*, not pipelining on
one socket (the transport's ``recv`` is single-consumer).  The benchmark and
the CI smoke drive 4-32 of these from threads; a production fleet would run
one per actor process, exactly like the Sebulba actors drive their learner
channel.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from sheeprl_tpu.distributed.transport import Channel, connect

_REQ_COUNTER = itertools.count()
_REQ_LOCK = threading.Lock()


class ServerDraining(ConnectionError):
    """The replica is draining (SIGTERM'd): retry against another replica."""


def _next_req_id() -> int:
    with _REQ_LOCK:
        return next(_REQ_COUNTER)


class PolicyClient:
    def __init__(self, host: str, port: int, timeout_s: float = 30.0):
        self.channel: Channel = connect(host, port, timeout_s=timeout_s)

    def ping(self, timeout: float = 10.0) -> Dict[str, Any]:
        """Readiness probe; returns the server's ``{policies, aliases, draining}``."""
        self.channel.send("ping")
        kind, meta, _ = self.channel.recv(timeout=timeout)
        if kind != "pong":
            raise RuntimeError(f"expected pong, got {kind!r}: {meta}")
        return meta

    def act(
        self,
        obs: Dict[str, np.ndarray],
        policy: str,
        timeout: float = 30.0,
    ) -> Tuple[np.ndarray, Dict[str, Any]]:
        """One round-trip: ``(action_row, reply_meta)``.

        ``reply_meta`` carries the SLO stamps: ``queue_ms`` / ``infer_ms`` /
        ``batch_fill`` / ``bucket`` / ``p99_ms`` (the server's rolling p99 at
        reply time).
        """
        req_id = _next_req_id()
        self.channel.send("act", payload=dict(obs), policy=policy, req_id=req_id)
        kind, meta, payload = self.channel.recv(timeout=timeout)
        if kind == "draining":
            raise ServerDraining(f"request {req_id} rejected: replica is draining")
        if kind == "error":
            raise RuntimeError(f"server error for request {req_id}: {meta.get('error')}")
        if kind != "act_result" or meta.get("req_id") != req_id:
            raise RuntimeError(f"unexpected reply {kind!r} (meta={meta}) for request {req_id}")
        return np.asarray(payload["action"]), meta

    def close(self) -> None:
        self.channel.close()

    def __enter__(self) -> "PolicyClient":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


def wait_for_server(
    host: str, port: int, timeout_s: float = 120.0, interval_s: float = 0.25
) -> Dict[str, Any]:
    """Poll until a replica answers a ping (startup includes AOT compilation, so
    the window is generous); returns the pong meta."""
    deadline = time.monotonic() + timeout_s
    last: Optional[Exception] = None
    while time.monotonic() < deadline:
        try:
            client = PolicyClient(host, port, timeout_s=min(5.0, timeout_s))
            try:
                return client.ping()
            finally:
                client.close()
        except Exception as e:  # noqa: BLE001 - any failure means "not up yet"
            last = e
            time.sleep(interval_s)
    raise TimeoutError(f"no serve replica at {host}:{port} within {timeout_s}s: {last}")
