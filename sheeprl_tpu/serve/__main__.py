"""``python -m sheeprl_tpu.serve`` — run one policy-server replica.

Overrides use the same grammar as training::

    python -m sheeprl_tpu.serve \\
        serve.policies='[cartpole_ppo:latest]' \\
        model_manager.registry_dir=models_registry \\
        serve.port=7557 serve.max_batch_size=32

Composes the ``serve_cli`` root config (serve + model_manager + analysis +
fault groups; the persistent compile cache defaults ON because warm-restart
speed is the point), installs the SIGTERM→drain handlers, and exits 75
(``RESUMABLE_EXIT_CODE``) after a preemption drain so the supervisor's
``--serve`` mode respawns the replica.
"""

from __future__ import annotations

import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    overrides = list(sys.argv[1:] if argv is None else argv)
    from sheeprl_tpu.config.core import compose

    cfg = compose(config_name="serve_cli", overrides=overrides)

    from sheeprl_tpu.utils.compile_cache import enable_compile_cache

    cache_dir = enable_compile_cache(cfg.get("compile_cache", {}) or {})
    if cache_dir:
        print(f"[serve] persistent compile cache: {cache_dir}", flush=True)

    from sheeprl_tpu.fault.preemption import install_signal_handlers

    install_signal_handlers()

    from sheeprl_tpu.serve.server import PolicyServer

    return PolicyServer(cfg).run()


if __name__ == "__main__":
    sys.exit(main())
