"""The policy inference server: registry-backed, AOT-precompiled, continuously
batched, drain-on-SIGTERM.

One :class:`PolicyServer` hosts any number of registered policies.  Startup does
ALL the expensive work: each ``serve.policies`` spec resolves through the
registry router, rebuilds its agent from the run config copied into the version
payload, loads the checkpoint (checksum-verified), and AOT-compiles the full
batch ladder (``precompile.precompile_ladder``) — with the persistent compile
cache wired, a warm replica restart deserializes every executable from disk.
After ``mark_warm()`` the steady state is numpy in, ``Compiled`` call, numpy
out: zero traces, zero compiles, enforced by the PR-1 recompile watchdog
(``analysis.strict=True`` upgrades any violation to :class:`RecompileError`).

Threads (all I/O-bound; the GIL is irrelevant because dispatch blocks in XLA):

* the **accept loop** (``run()``, main thread) — admits connections, watches the
  preemption flag;
* one **reader** per client channel — decodes requests and routes them onto the
  owning endpoint's bounded queue (a full queue blocks the reader, which blocks
  the client's TCP stream: backpressure, not unbounded buffering);
* one **dispatcher** per endpoint — pulls continuous batches
  (``batching.collect_batch``), pads to the ladder bucket, runs the
  precompiled executable, and replies to every request in the batch with
  latency/queue stamps.

Wire protocol (framed transport from ``distributed.transport``):

* ``("ping", {}) → ("pong", {policies, draining, queue_depth, p99_ms})`` —
  readiness + load probe (the fleet front routes on the load stamps);
* ``("act", {policy, req_id}, obs_dict) → ("act_result", {req_id, queue_ms,
  infer_ms, batch_fill, bucket, p99_ms}, {"action": row})`` — one observation
  in, one action out; stateful (recurrent) policies also accept ``session``
  (client id whose device-resident act state continues across requests —
  :class:`~sheeprl_tpu.serve.state_cache.SessionStateCache`) and ``reset``
  (force an episode restart for that session);
* ``("act", ...) during drain → ("draining", {req_id})`` — the client retries
  against another replica;
* unknown policy / malformed obs → ``("error", {req_id, error})``.

Drain contract (chaos-tested): on SIGTERM the server stops accepting, answers
new requests with ``draining``, dispatches everything already queued, replies to
every accepted request, writes its summary, and exits ``RESUMABLE_EXIT_CODE``
(75) so the supervisor's serving mode respawns it.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
import warnings
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, List, Optional

import numpy as np

from sheeprl_tpu.distributed.transport import Channel, ChannelClosed, Listener
from sheeprl_tpu.fault import preemption as fault_preemption
from sheeprl_tpu.obs import perf as obs_perf
from sheeprl_tpu.obs.fleet import maybe_exporter
from sheeprl_tpu.serve.batching import bucket_ladder, collect_batch, pad_obs_batch, pick_bucket
from sheeprl_tpu.serve.precompile import dispatch_key, precompile_ladder, zero_key
from sheeprl_tpu.serve.router import resolve_policy
from sheeprl_tpu.utils.metric import MetricAggregator

#: Env var override for where the exit summary lands (CI smoke / chaos harness).
SERVE_SUMMARY_ENV_VAR = "SHEEPRL_TPU_SERVE_SUMMARY"


@dataclass
class _Request:
    channel: Channel
    req_id: Any
    obs: Dict[str, np.ndarray]
    t_enq: float
    session: Optional[str] = None  # stateful policies: the client id owning act state
    reset: bool = False  # force an episode restart for that session


class _Endpoint:
    """One loaded policy: its precompiled ladder, request queue, dispatcher state."""

    def __init__(self, name: str, version: int, policy, compiled, ladder, queue_depth: int, seed: int):
        import queue as _queue

        self.name = name
        self.version = version
        self.policy = policy
        self.compiled = compiled
        self.ladder = ladder
        self.queue: "_queue.Queue[_Request]" = _queue.Queue(maxsize=queue_depth)
        self.seed = seed
        self.state_cache = None  # SessionStateCache for stateful policies
        self.dispatch_counter = 0
        # Per-bucket dispatch count + infer seconds — with the registered XLA
        # cost models (obs/perf.py) this yields per-bucket MFU in the exit
        # summary.  Single writer (the dispatcher thread); readers tolerate a
        # torn [count, seconds] pair (one 1 Hz gauge sample, self-correcting).
        self.bucket_stats: Dict[int, List[float]] = {}
        # accepted is bumped by one reader thread per client connection; an
        # unguarded += is a read-modify-write that loses updates (JL008), which
        # would silently break the accepted == replied + dropped summary
        # invariant.  replied/dropped/dispatch_counter have a single writer
        # (the endpoint's dispatcher thread) and stay lock-free.
        self.stats_lock = threading.Lock()
        self.accepted = 0
        self.replied = 0
        self.dropped = 0
        self.slo_violations = 0  # replies whose end-to-end latency beat serve.slo_ms
        self.metrics = MetricAggregator(
            {
                "Serve/latency_ms": "histogram",
                "Serve/infer_ms": "histogram",
                "Serve/batch_fill": "mean",
                "Serve/queue_depth": "mean",
                "Serve/dispatches": "sum",
            }
        )

    @property
    def canonical(self) -> str:
        return f"{self.name}:{self.version}"


class PolicyServer:
    """Load → precompile → serve → drain.  One instance per replica process."""

    def __init__(self, cfg: Any):
        self.cfg = cfg
        serve_cfg = cfg.serve
        self.serve_cfg = serve_cfg
        self.max_batch = int(serve_cfg.max_batch_size)
        self.delay_s = float(serve_cfg.max_batch_delay_ms) / 1000.0
        self.drain_timeout_s = float(serve_cfg.drain_timeout_s)
        self.log_every_s = float(serve_cfg.log_every_s)
        self.greedy = bool(serve_cfg.greedy)
        slo = serve_cfg.get("slo_ms", None)
        self.slo_ms: Optional[float] = float(slo) if slo else None
        self.precision = _normalize_precision(serve_cfg.get("precision", "f32"))
        self.parity: Dict[str, Dict[str, Any]] = {}  # canonical -> parity stamp
        self._draining = False
        self._stop = threading.Event()
        self._channels: List[Channel] = []
        self._channels_lock = threading.Lock()
        self._threads: List[threading.Thread] = []
        self.endpoints: Dict[str, _Endpoint] = {}  # canonical "name:version" -> endpoint
        self.aliases: Dict[str, str] = {}  # request spec -> canonical
        self.listener: Optional[Listener] = None
        self.startup_seconds = 0.0
        self.precompile_seconds = 0.0
        self.watchdog = None
        self._stats_lock = threading.Lock()  # guards rejected_draining (readers race)
        self.rejected_draining = 0
        self._fleet = None  # FleetExporter, attached in run()

        t0 = time.perf_counter()
        self._perf_t0 = t0  # perf attribution clock: startup compiles count too
        self._load_policies()
        self.startup_seconds = time.perf_counter() - t0

    # ------------------------------------------------------------------ startup
    def _load_policies(self) -> None:
        import jax

        from sheeprl_tpu.config.core import load_config
        from sheeprl_tpu.obs.watchdog import RecompileWatchdog
        from sheeprl_tpu.parallel.mesh import MeshContext, build_mesh
        from sheeprl_tpu.utils.model_manager import build_model_manager
        from sheeprl_tpu.utils.policy import load_policy, parity_stamp

        specs = list(self.serve_cfg.policies)
        if not specs:
            raise ValueError("serve.policies is empty: nothing to serve")
        self.watchdog = RecompileWatchdog()
        manager = build_model_manager(self.cfg)
        ladder = bucket_ladder(self.max_batch, self.serve_cfg.buckets)
        seed = int(self.cfg.seed)
        for spec in specs:
            name, entry = resolve_policy(manager, spec)
            canonical = f"{name}:{int(entry['version'])}"
            if canonical in self.endpoints:
                self.aliases.setdefault(str(spec), canonical)
                continue
            payload_dir = Path(entry["path"])
            run_cfg_path = payload_dir / "config.yaml"
            if not run_cfg_path.is_file():
                raise FileNotFoundError(
                    f"{canonical}: no config.yaml inside the registered payload "
                    f"{payload_dir} (re-register the model; registration now copies "
                    "the run config into the version payload)"
                )
            run_cfg = load_config(run_cfg_path)
            precision = (run_cfg.get("mesh") or {}).get("precision", "fp32")
            ctx = MeshContext(
                mesh=build_mesh(devices=jax.devices()[:1]), precision=precision, seed=seed
            )
            policy = load_policy(
                ctx, run_cfg, str(payload_dir), greedy=self.greedy, precision=self.precision
            )
            if self.precision != "f32":
                # Parity stamp: reload at f32 (fresh run cfg — load_policy mutates
                # it) and compare greedy actions on seeded random obs.  Runs
                # before mark_warm, so its compiles are startup work, not
                # watchdog violations.
                reference = load_policy(
                    MeshContext(
                        mesh=build_mesh(devices=jax.devices()[:1]), precision=precision, seed=seed
                    ),
                    load_config(run_cfg_path),
                    str(payload_dir),
                    greedy=self.greedy,
                    precision="f32",
                )
                self.parity[canonical] = parity_stamp(policy, reference, seed=seed)
                print(f"[serve] {canonical}: parity {self.parity[canonical]}", flush=True)
            compiled, secs = precompile_ladder(
                policy,
                ladder,
                perf_name=f"serve/{canonical}" if obs_perf.perf_enabled(self.cfg) else None,
            )
            self.precompile_seconds += secs
            ep = _Endpoint(
                name=name,
                version=int(entry["version"]),
                policy=policy,
                compiled=compiled,
                ladder=ladder,
                queue_depth=int(self.serve_cfg.queue_depth),
                seed=seed,
            )
            if policy.stateful:
                from sheeprl_tpu.serve.state_cache import SessionStateCache

                ep.state_cache = SessionStateCache(
                    policy.zero_state_fn, capacity=int(self.serve_cfg.session_capacity)
                )

                # Warm gather/scatter THROUGH the compiled act fn: its output
                # sharding is what dispatch-time scatters (and, once committed
                # to the storage, gathers) trace against.
                def _warm_step(bucket: int, state: Any, _ep: _Endpoint = ep) -> Any:
                    warm_obs = _ep.policy.zero_obs(bucket)
                    warm_first = np.ones((bucket, 1), np.float32)
                    _, new_state = _ep.compiled[bucket](
                        _ep.policy.params, warm_obs, warm_first, state, zero_key()
                    )
                    return new_state

                ep.state_cache.warmup(ladder, step_fn=_warm_step)
            self.endpoints[canonical] = ep
            self._register_aliases(spec, ep, entry)
            print(
                f"[serve] {canonical}: algo={policy.algo} ladder={ladder} "
                f"precompile={secs:.2f}s",
                flush=True,
            )
        # Everything compiled from here on is a recompile.
        self.watchdog.mark_warm()

    def _register_aliases(self, spec: str, ep: _Endpoint, entry: Dict[str, Any]) -> None:
        """Route keys for one endpoint: the spec as configured, the canonical
        ``name:version``, the bare name and ``name:latest`` (first loaded version
        of a name wins those two — pin ``name:version`` to be explicit)."""
        self.aliases[ep.canonical] = ep.canonical
        self.aliases.setdefault(str(spec), ep.canonical)
        self.aliases.setdefault(ep.name, ep.canonical)
        self.aliases.setdefault(f"{ep.name}:latest", ep.canonical)
        stage = str(entry.get("stage", "") or "")
        if stage and stage.lower() != "none":
            self.aliases.setdefault(f"{ep.name}:{stage}", ep.canonical)

    # ------------------------------------------------------------------ serving
    def run(self) -> int:
        """Listen, serve until stop/preemption, drain, summarize.  Returns the
        process exit code (75 when preempted, 0 on a clean ``shutdown()``)."""
        serve_cfg = self.serve_cfg
        self.listener = Listener(host=str(serve_cfg.host), port=int(serve_cfg.port))
        self._write_ready_file()
        print(
            f"[serve] listening on {self.listener.address} "
            f"(policies: {sorted(self.endpoints)})",
            flush=True,
        )
        for ep in self.endpoints.values():
            t = threading.Thread(
                target=self._dispatch_loop, args=(ep,), name=f"serve-dispatch-{ep.canonical}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
        # Fleet telemetry: the replica generation is the supervisor's restart
        # counter, so respawned replicas land in a fresh snapshot slot lineage;
        # the fleet manager numbers replica slots via SHEEPRL_TPU_SERVE_SLOT so
        # N replicas show as serve0..serveN-1 instead of colliding on serve0.
        self._fleet = maybe_exporter(
            self.cfg,
            "serve",
            actor_id=int(os.environ.get("SHEEPRL_TPU_SERVE_SLOT", "0") or 0),
            generation=int(os.environ.get("SHEEPRL_TPU_FAULT_RESTARTS", "0") or 0),
        )
        last_log = time.monotonic()
        last_fleet = 0.0
        try:
            while not self._stop.is_set() and not fault_preemption.preemption_requested():
                try:
                    ch = self.listener.accept(timeout=0.2)
                except TimeoutError:
                    pass
                except OSError:
                    break
                else:
                    with self._channels_lock:
                        self._channels.append(ch)
                    t = threading.Thread(
                        target=self._reader_loop, args=(ch,), name="serve-reader", daemon=True
                    )
                    t.start()
                    self._threads.append(t)
                if self.log_every_s > 0 and time.monotonic() - last_log >= self.log_every_s:
                    last_log = time.monotonic()
                    self._log_status()
                if self._fleet is not None and time.monotonic() - last_fleet >= 1.0:
                    last_fleet = time.monotonic()
                    self._fleet_update()
        finally:
            preempted = fault_preemption.preemption_requested()
            self._drain()
            if self._fleet is not None:
                self._fleet_update()  # final counters cover the drained queue
                try:
                    self._fleet.close()
                except Exception:
                    pass
            self._write_summary(preempted=preempted)
            self._write_perf_report()
            self._close()
        return fault_preemption.RESUMABLE_EXIT_CODE if preempted else 0

    def shutdown(self) -> None:
        """Clean stop (tests/benchmarks): same drain path, exit code 0."""
        self._stop.set()

    # ------------------------------------------------------------------ readers
    def _reader_loop(self, ch: Channel) -> None:
        while not ch.closed:
            try:
                kind, meta, payload = ch.recv(timeout=0.5)
            except TimeoutError:
                continue
            except (ChannelClosed, Exception):
                return
            try:
                self._handle(ch, kind, meta, payload)
            except ChannelClosed:
                return

    def _handle(self, ch: Channel, kind: str, meta: Dict[str, Any], payload: Any) -> None:
        if kind == "ping":
            p99 = float("nan")
            for ep in self.endpoints.values():
                hist = ep.metrics.metrics["Serve/latency_ms"].compute()
                if hist:
                    p = float(hist["p99"])
                    if not (p99 == p99) or p > p99:  # max over endpoints, NaN-safe
                        p99 = p
            ch.send(
                "pong",
                policies=sorted(self.endpoints),
                aliases=sorted(self.aliases),
                draining=bool(self._draining),
                precision=self.precision,
                parity=self.parity,
                # Load stamps: the fleet front's routing probe.
                queue_depth=sum(ep.queue.qsize() for ep in self.endpoints.values()),
                p99_ms=p99 if p99 == p99 else None,
            )
            return
        if kind != "act":
            ch.send("error", req_id=meta.get("req_id"), error=f"unknown message kind {kind!r}")
            return
        req_id = meta.get("req_id")
        if self._draining:
            with self._stats_lock:
                self.rejected_draining += 1
            ch.send("draining", req_id=req_id)
            return
        spec = str(meta.get("policy", ""))
        canonical = self.aliases.get(spec)
        if canonical is None:
            ch.send(
                "error",
                req_id=req_id,
                error=f"no policy routed as {spec!r} (served: {sorted(self.aliases)})",
            )
            return
        ep = self.endpoints[canonical]
        if not isinstance(payload, dict):
            ch.send("error", req_id=req_id, error="act payload must be an obs dict")
            return
        session = meta.get("session")
        ep.queue.put(
            _Request(
                channel=ch,
                req_id=req_id,
                obs=payload,
                t_enq=time.monotonic(),
                session=str(session) if session is not None else None,
                reset=bool(meta.get("reset", False)),
            )
        )
        with ep.stats_lock:
            ep.accepted += 1

    # --------------------------------------------------------------- dispatcher
    def _dispatch_loop(self, ep: _Endpoint) -> None:
        while True:
            batch = collect_batch(ep.queue, self.max_batch, self.delay_s, first_timeout_s=0.05)
            if not batch:
                if self._stop.is_set() or self._draining:
                    if ep.queue.empty():
                        return
                continue
            try:
                self._dispatch(ep, batch)
            except Exception as e:  # reply rather than killing the dispatcher
                from sheeprl_tpu.obs.watchdog import RecompileError

                for req in batch:
                    try:
                        req.channel.send("error", req_id=req.req_id, error=str(e))
                    except ChannelClosed:
                        ep.dropped += 1
                if isinstance(e, RecompileError):
                    raise

    def _dispatch(self, ep: _Endpoint, batch: List[_Request]) -> None:
        import jax

        from sheeprl_tpu.obs.watchdog import RecompileError, RecompileWarning

        n = len(batch)
        bucket = pick_bucket(ep.ladder, n)
        try:
            obs = pad_obs_batch([r.obs for r in batch], ep.policy.obs_template, bucket)
        except (KeyError, ValueError) as e:
            for req in batch:
                try:
                    req.channel.send("error", req_id=req.req_id, error=str(e))
                except ChannelClosed:
                    ep.dropped += 1
            return
        key = dispatch_key(ep.seed, ep.dispatch_counter)
        ep.dispatch_counter += 1
        t0 = time.monotonic()
        if ep.state_cache is not None:
            # Stateful dispatch: map sessions to device state rows, pad with the
            # scratch row (padding scatters there harmlessly), one recurrent step.
            cache = ep.state_cache
            idx, is_first = cache.assign([r.session for r in batch], [r.reset for r in batch])
            idx_p = np.full((bucket,), cache.scratch, np.int32)
            idx_p[:n] = idx
            is_first_p = np.ones((bucket, 1), np.float32)
            is_first_p[:n] = is_first
            state = cache.gather(idx_p)
            out, new_state = ep.compiled[bucket](ep.policy.params, obs, is_first_p, state, key)
            actions = np.asarray(jax.device_get(out))
            cache.scatter(idx_p, new_state)
        else:
            actions = np.asarray(jax.device_get(ep.compiled[bucket](ep.policy.params, obs, key)))
        t1 = time.monotonic()

        new_compiles = self.watchdog.poll_new() if self.watchdog is not None else 0
        if new_compiles:
            msg = (
                f"{ep.canonical}: {new_compiles} post-warmup compile(s) during a "
                f"bucket-{bucket} dispatch — the AOT ladder should make this impossible"
            )
            if bool(self.cfg.analysis.strict):
                raise RecompileError(msg)
            warnings.warn(msg, RecompileWarning)

        stats = ep.bucket_stats.setdefault(bucket, [0, 0.0])
        stats[0] += 1
        stats[1] += t1 - t0

        infer_ms = (t1 - t0) * 1000.0
        ep.metrics.update("Serve/infer_ms", infer_ms)
        ep.metrics.update("Serve/batch_fill", n / bucket)
        ep.metrics.update("Serve/queue_depth", ep.queue.qsize())
        ep.metrics.update("Serve/dispatches", 1.0)
        latencies = [(t1 - r.t_enq) * 1000.0 for r in batch]
        ep.metrics.update("Serve/latency_ms", latencies)
        if self.slo_ms is not None:
            ep.slo_violations += sum(1 for lat in latencies if lat > self.slo_ms)
        hist = ep.metrics.metrics["Serve/latency_ms"].compute()
        p99 = float(hist["p99"]) if hist else float("nan")
        for i, req in enumerate(batch):
            try:
                req.channel.send(
                    "act_result",
                    payload={"action": actions[i]},
                    req_id=req.req_id,
                    queue_ms=(t0 - req.t_enq) * 1000.0,
                    infer_ms=infer_ms,
                    batch_fill=n / bucket,
                    bucket=bucket,
                    p99_ms=p99,
                )
                ep.replied += 1
            except ChannelClosed:
                ep.dropped += 1

    # ------------------------------------------------------------------ teardown
    def _drain(self) -> None:
        """Stop admitting, flush every queue, reply to everything accepted."""
        self._draining = True
        time.sleep(0.05)  # let in-flight reader enqueues land before emptiness checks
        deadline = time.monotonic() + self.drain_timeout_s
        for ep in self.endpoints.values():
            while not ep.queue.empty() and time.monotonic() < deadline:
                time.sleep(0.01)
        self._stop.set()
        for t in self._threads:
            if t.name.startswith("serve-dispatch"):
                t.join(timeout=max(deadline - time.monotonic(), 1.0))

    def _close(self) -> None:
        if self.listener is not None:
            self.listener.close()
        with self._channels_lock:
            channels = list(self._channels)
        for ch in channels:
            ch.close()

    def _fleet_update(self) -> None:
        """Push replica-wide counters/gauges to the fleet plane.  Dict writes +
        one framed send on the exporter's own thread — nothing here touches the
        dispatchers' hot path."""
        exporter = self._fleet
        if exporter is None:
            return
        accepted = sum(ep.accepted for ep in self.endpoints.values())
        replied = sum(ep.replied for ep in self.endpoints.values())
        dropped = sum(ep.dropped for ep in self.endpoints.values())
        dispatches = sum(ep.dispatch_counter for ep in self.endpoints.values())
        violations = sum(ep.slo_violations for ep in self.endpoints.values())
        exporter.counter("requests_accepted", accepted)
        exporter.counter("requests_replied", replied)
        exporter.counter("requests_dropped", dropped)
        exporter.counter("dispatches", dispatches)
        exporter.counter("slo_violations", violations)
        exporter.gauge("Serve/queue_depth", sum(ep.queue.qsize() for ep in self.endpoints.values()))
        if self.slo_ms is not None:
            exporter.gauge("Serve/slo_ms", self.slo_ms)
            exporter.gauge("Serve/slo_burn", violations / max(replied, 1))
        p99 = float("nan")
        for ep in self.endpoints.values():
            hist = ep.metrics.metrics["Serve/latency_ms"].compute()
            if hist:
                p = float(hist["p99"])
                if not (p99 == p99) or p > p99:  # max over endpoints, NaN-safe
                    p99 = p
        if p99 == p99:
            exporter.gauge("Serve/latency_p99_ms", p99)
        if obs_perf.perf_enabled(self.cfg):
            perf = self.perf_summary()
            exporter.gauge("Perf/mfu", perf["mfu"])
            exporter.gauge("Perf/goodput", perf["goodput"])

    def _log_status(self) -> None:
        for ep in self.endpoints.values():
            computed = ep.metrics.compute()
            p99 = computed.get("Serve/latency_ms/p99", float("nan"))
            fill = computed.get("Serve/batch_fill", float("nan"))
            print(
                f"[serve] {ep.canonical}: accepted={ep.accepted} replied={ep.replied} "
                f"p99={p99:.2f}ms fill={fill:.2f} depth={ep.queue.qsize()}",
                flush=True,
            )

    # ------------------------------------------------------------------ artifacts
    def _write_ready_file(self) -> None:
        ready = self.serve_cfg.ready_file
        if not ready:
            return
        doc = {
            "host": self.listener.host,
            "port": self.listener.port,
            "policies": sorted(self.endpoints),
            "startup_seconds": self.startup_seconds,
            "precompile_seconds": self.precompile_seconds,
            "precision": self.precision,
            "parity": self.parity,
        }
        _atomic_write_json(Path(ready), doc)

    def summary(self, preempted: bool = False) -> Dict[str, Any]:
        per_policy = {}
        for canonical, ep in self.endpoints.items():
            per_policy[canonical] = {
                "accepted": ep.accepted,
                "replied": ep.replied,
                "dropped": ep.dropped,
                "dispatches": ep.dispatch_counter,
                "slo_violations": ep.slo_violations,
                "metrics": ep.metrics.compute(),
            }
            if ep.state_cache is not None:
                per_policy[canonical]["sessions"] = ep.state_cache.stats()
        total_replied = sum(ep.replied for ep in self.endpoints.values())
        total_violations = sum(ep.slo_violations for ep in self.endpoints.values())
        return {
            "preempted": bool(preempted),
            "drained": True,
            "rejected_draining": self.rejected_draining,
            "accepted": sum(ep.accepted for ep in self.endpoints.values()),
            "replied": total_replied,
            "dropped": sum(ep.dropped for ep in self.endpoints.values()),
            "slo_ms": self.slo_ms,
            "slo_violations": total_violations,
            "slo_burn": total_violations / max(total_replied, 1),
            "recompiles": int(self.watchdog.recompiles) if self.watchdog else 0,
            "startup_seconds": self.startup_seconds,
            "precompile_seconds": self.precompile_seconds,
            "precision": self.precision,
            "parity": self.parity,
            "policies": per_policy,
            "perf": self.perf_summary() if obs_perf.perf_enabled(self.cfg) else None,
        }

    def perf_summary(self) -> Dict[str, Any]:
        """Cost-model MFU + goodput for this replica (``obs/perf.py`` plane).

        MFU is over the whole process lifetime (startup included), so an idle
        replica honestly reads near zero; per-bucket MFU uses each bucket's own
        infer seconds, so it reads the hardware efficiency of the compiled
        program itself.  Goodput classifies infer time as compute and the
        ladder's AOT compiles as recompile; the rest (queue waits, idle accept
        loop) is other.
        """
        import jax

        device = jax.devices()[0]
        peak = obs_perf.peak_flops(device)
        models = obs_perf.registered_cost_models()
        per_policy: Dict[str, Any] = {}
        total_flops = total_bytes = total_infer_s = 0.0
        for canonical, ep in self.endpoints.items():
            buckets: Dict[str, Any] = {}
            for bucket, (count, seconds) in sorted(ep.bucket_stats.items()):
                model = models.get(f"serve/{canonical}/b{bucket}", {})
                flops_per_dispatch = float(model.get("flops", 0.0))
                flops = flops_per_dispatch * count
                total_flops += flops
                total_bytes += float(model.get("bytes_accessed", 0.0)) * count
                total_infer_s += seconds
                buckets[str(bucket)] = {
                    "dispatches": int(count),
                    "infer_s": seconds,
                    "flops_per_dispatch": flops_per_dispatch,
                    "mfu": flops / seconds / peak if seconds > 0 and peak > 0 else 0.0,
                }
            per_policy[canonical] = buckets
        elapsed = max(time.perf_counter() - self._perf_t0, 1e-9)
        ledger = obs_perf.GoodputLedger()
        fractions = ledger.classify(
            {"Time/phase_dispatch": total_infer_s},
            elapsed,
            recompile_s=self.watchdog.compile_seconds if self.watchdog is not None else 0.0,
        )
        return {
            "role": "serve",
            "device_kind": str(getattr(device, "device_kind", "") or ""),
            "peak_flops": peak,
            "elapsed_s": elapsed,
            "total_flops": total_flops,
            "total_bytes_accessed": total_bytes,
            "infer_s": total_infer_s,
            "achieved_flops_per_sec": total_flops / elapsed,
            "mfu": total_flops / elapsed / peak if peak > 0 else 0.0,
            "goodput": fractions["compute"] + fractions["env"],
            "goodput_fractions": fractions,
            "per_policy": per_policy,
            "cost_models": {k: v for k, v in models.items() if k.startswith("serve/")},
        }

    def _write_summary(self, preempted: bool) -> None:
        path = os.environ.get(SERVE_SUMMARY_ENV_VAR) or self.serve_cfg.summary_path
        if not path:
            return
        _atomic_write_json(Path(path), self.summary(preempted=preempted))

    def _write_perf_report(self) -> None:
        """``perf_report.json``: env override, else next to the exit summary."""
        if not obs_perf.perf_enabled(self.cfg):
            return
        path = os.environ.get(obs_perf.PERF_REPORT_ENV_VAR)
        if not path:
            summary_path = os.environ.get(SERVE_SUMMARY_ENV_VAR) or self.serve_cfg.summary_path
            if summary_path:
                path = str(Path(summary_path).parent / "perf_report.json")
        if not path:
            return
        try:
            _atomic_write_json(Path(path), self.perf_summary())
        except OSError:
            pass


def _normalize_precision(spec: Any) -> str:
    """serve.precision → canonical tier name (f32 | bf16 | int8)."""
    key = str(spec if spec is not None else "f32").lower()
    if key in ("", "none", "null", "f32", "fp32", "float32"):
        return "f32"
    if key in ("bf16", "bfloat16"):
        return "bf16"
    if key == "int8":
        return "int8"
    raise ValueError(f"Unknown serve.precision {spec!r}; expected f32, bf16 or int8")


def _atomic_write_json(path: Path, doc: Dict[str, Any]) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp_name = tempfile.mkstemp(prefix=f".{path.name}.", suffix=".tmp", dir=path.parent)
    with os.fdopen(fd, "w") as f:
        json.dump(doc, f, indent=2)
    os.replace(tmp_name, path)
