"""The fleet manager: front + N replicas under one supervising loop.

``python -m sheeprl_tpu.supervise --serve serve.fleet.enabled=True ...`` lands
here (dispatched by :func:`sheeprl_tpu.fault.supervisor.supervise_serve`).  The
manager owns processes, not requests:

* it spawns the front (``python -m sheeprl_tpu.serve.fleet``) and
  ``serve.fleet.min_replicas`` replicas (each ``python -m sheeprl_tpu.serve``
  on an ephemeral port), writing a record file into
  ``<serve.fleet.dir>/replicas/`` once a replica's ready file appears — that is
  how the front admits it;
* every child death is classified the supervisor way: rc 75 (drained
  preemption) respawns immediately with a bumped generation; a crash backs off
  on the slot's *consecutive*-crash count (reset by any clean preemption) and
  is bounded by ``fault.max_retries`` per slot; a SIGKILL mid-flight is just a
  crash — the front reroutes the dead replica's in-flight requests while the
  manager respawns it, and the warm persistent compile cache makes the respawn
  cheap;
* the autoscaler (:class:`~sheeprl_tpu.serve.fleet.autoscale.AutoscaleDecider`)
  reads the front's ``front_status.json`` and grows the fleet on sustained
  queue depth / drains one replica (SIGTERM → rc 75 → slot retired) on
  sustained idle, between ``min_replicas`` and ``max_replicas``;
* ``serve.fleet.canary.spec`` adds a dedicated canary slot serving the
  candidate version (``serve.policies=[spec]``); it is never autoscaled away
  and the front routes the canary fraction to it.

Like every supervising loop, the manager writes a lifetime summary JSON
(``fault.summary_path`` / ``SHEEPRL_TPU_SUPERVISE_SUMMARY``) on ALL exit
paths: spawns, respawns, scale events, per-slot retry/preemption counts.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, List, Optional

from sheeprl_tpu.fault import preemption as fault_preemption
from sheeprl_tpu.fault.counters import RESTARTS_ENV_VAR
from sheeprl_tpu.fault.preemption import RESUMABLE_EXIT_CODE
from sheeprl_tpu.fault.supervisor import (
    _strip_override,
    backoff_seconds,
    fault_cfg,
    write_supervisor_summary,
)
from sheeprl_tpu.serve.fleet.autoscale import AutoscaleDecider
from sheeprl_tpu.serve.fleet.front import RECORDS_SUBDIR

#: Env var carrying the replica's fleet slot index (telemetry row identity).
SERVE_SLOT_ENV_VAR = "SHEEPRL_TPU_SERVE_SLOT"


@dataclass
class _Slot:
    name: str  # "front", "replica<N>", "canary0"
    index: int  # telemetry slot id (SHEEPRL_TPU_SERVE_SLOT)
    role: str  # "front" | "replica"
    canary: bool = False
    proc: Optional[subprocess.Popen] = None
    generation: int = 0  # bumped per respawn → fresh telemetry lineage
    retries: int = 0  # total crashes, bounded by fault.max_retries
    consecutive: int = 0  # backoff input; reset by a clean preemption
    preemptions: int = 0
    desired: bool = True  # False once scale-down / abandonment retired it
    abandoned: bool = False
    ready_recorded: bool = False
    next_spawn_at: float = 0.0  # monotonic; crash backoff scheduling
    ready_file: Optional[Path] = None
    record_path: Optional[Path] = None


class FleetManager:
    def __init__(self, overrides: List[str], cfg: Any):
        self.overrides = list(overrides)
        self.cfg = cfg
        fleet_cfg = cfg.serve.fleet
        self.fleet_cfg = fleet_cfg
        self.fleet_dir = (
            Path(str(fleet_cfg.dir))
            if fleet_cfg.dir
            else Path(tempfile.mkdtemp(prefix="sheeprl_fleet_"))
        )
        self.fleet_dir.mkdir(parents=True, exist_ok=True)
        self.records_dir = self.fleet_dir / RECORDS_SUBDIR
        self.records_dir.mkdir(parents=True, exist_ok=True)
        self.min_replicas = int(fleet_cfg.min_replicas)
        self.max_replicas = int(fleet_cfg.max_replicas)
        self.decider = AutoscaleDecider(
            min_replicas=self.min_replicas,
            max_replicas=self.max_replicas,
            scale_up_queue_depth=float(fleet_cfg.scale_up_queue_depth),
            scale_up_after_s=float(fleet_cfg.scale_up_after_s),
            scale_down_after_s=float(fleet_cfg.scale_down_after_s),
            cooldown_s=float(fleet_cfg.scale_cooldown_s),
        )
        canary_cfg = fleet_cfg.get("canary") or {}
        self.canary_spec: Optional[str] = (
            str(canary_cfg.get("spec")) if canary_cfg.get("spec") else None
        )
        f_cfg = fault_cfg(cfg)
        self.f_cfg = f_cfg
        self.max_retries = int(f_cfg.get("max_retries", 3))
        self.max_preemptions = f_cfg.get("max_preemptions")
        self.base_backoff = float(f_cfg.get("backoff_s", 2.0))
        self.max_backoff = float(f_cfg.get("backoff_max_s", 60.0))
        self.drain_timeout_s = float(cfg.serve.drain_timeout_s)

        self.slots: Dict[str, _Slot] = {}
        self.fleet = None  # FleetAggregator (obs.fleet.dir)
        self.trace_id: Optional[str] = None
        self.summary: Dict[str, Any] = {
            "mode": "fleet",
            "fleet_dir": str(self.fleet_dir),
            "events": [],
            "scale_ups": 0,
            "scale_downs": 0,
            "slots": {},
            "outcome": None,
            "rc": None,
        }

    # ------------------------------------------------------------------- argv
    def _front_argv(self) -> List[str]:
        ov, _ = _strip_override(self.overrides, "serve.fleet.dir")
        ov = ov + [f"serve.fleet.dir={self.fleet_dir}"]
        if not self.fleet_cfg.ready_file:
            ov, _ = _strip_override(ov, "serve.fleet.ready_file")
            ov += [f"serve.fleet.ready_file={self.fleet_dir / 'front_ready.json'}"]
        if not self.fleet_cfg.summary_path:
            ov, _ = _strip_override(ov, "serve.fleet.summary_path")
            ov += [f"serve.fleet.summary_path={self.fleet_dir / 'front_summary.json'}"]
        return [sys.executable, "-m", "sheeprl_tpu.serve.fleet"] + ov

    def _replica_argv(self, slot: _Slot) -> List[str]:
        ov = list(self.overrides)
        for key in ("serve.port", "serve.ready_file", "serve.summary_path"):
            ov, _ = _strip_override(ov, key)
        ov += [
            "serve.port=0",
            f"serve.ready_file={slot.ready_file}",
            f"serve.summary_path={self.fleet_dir / (slot.name + '_summary.json')}",
        ]
        if slot.canary:
            ov, _ = _strip_override(ov, "serve.policies")
            ov += [f"serve.policies=[{self.canary_spec}]"]
        return [sys.executable, "-m", "sheeprl_tpu.serve"] + ov

    # ------------------------------------------------------------------ spawning
    def _make_slot(self, name: str, index: int, role: str, canary: bool = False) -> _Slot:
        slot = _Slot(
            name=name,
            index=index,
            role=role,
            canary=canary,
            ready_file=self.fleet_dir / f"{name}_ready.json",
            record_path=(self.records_dir / f"{name}.json") if role == "replica" else None,
        )
        self.slots[name] = slot
        return slot

    def _spawn(self, slot: _Slot) -> None:
        if slot.ready_file is not None:
            slot.ready_file.unlink(missing_ok=True)
        if slot.record_path is not None:
            slot.record_path.unlink(missing_ok=True)
        slot.ready_recorded = False
        env = dict(os.environ)
        env[RESTARTS_ENV_VAR] = str(slot.generation)
        if slot.role == "replica":
            env[SERVE_SLOT_ENV_VAR] = str(slot.index)
        from sheeprl_tpu.obs.fleet import FLEET_ENV_VAR, TRACE_ID_ENV_VAR

        env.pop(FLEET_ENV_VAR, None)
        if self.fleet is not None:
            env[FLEET_ENV_VAR] = self.fleet.address
        if self.trace_id:
            env[TRACE_ID_ENV_VAR] = self.trace_id
        argv = self._front_argv() if slot.role == "front" else self._replica_argv(slot)
        slot.proc = subprocess.Popen(argv, env=env)
        self._event("spawn", slot, generation=slot.generation, pid=slot.proc.pid)
        self._log(f"spawned {slot.name} (gen {slot.generation}, pid {slot.proc.pid})")

    def _event(self, kind: str, slot: Optional[_Slot] = None, **extra: Any) -> None:
        row = {"kind": kind, "time": time.time(), **extra}
        if slot is not None:
            row["slot"] = slot.name
        self.summary["events"].append(row)

    # ------------------------------------------------------------------- lifecycle
    def _check_ready(self) -> None:
        """Replica ready file → record file: the front's admission signal."""
        for slot in self.slots.values():
            if (
                slot.ready_recorded
                or slot.proc is None
                or slot.ready_file is None
                or not slot.ready_file.is_file()
            ):
                continue
            try:
                ready = json.loads(slot.ready_file.read_text())
            except (OSError, ValueError):
                continue
            slot.ready_recorded = True
            self._event("ready", slot, generation=slot.generation)
            if slot.record_path is not None:
                record = {
                    "name": slot.name,
                    "host": ready.get("host", "127.0.0.1"),
                    "port": int(ready.get("port", 0)),
                    "canary": slot.canary,
                    "generation": slot.generation,
                    "pid": slot.proc.pid,
                }
                tmp = slot.record_path.with_suffix(".tmp")
                tmp.write_text(json.dumps(record, indent=2))
                os.replace(tmp, slot.record_path)
                self._log(f"{slot.name} ready at {record['host']}:{record['port']}")

    def _reap(self) -> Optional[int]:
        """Classify child deaths.  Returns an exit code when the fleet is done."""
        for slot in list(self.slots.values()):
            if slot.proc is None or slot.proc.poll() is None:
                continue
            rc = slot.proc.returncode
            slot.proc = None
            slot.ready_recorded = False
            if slot.record_path is not None:
                slot.record_path.unlink(missing_ok=True)
            if not slot.desired:
                # The drain we asked for (scale-down): the slot retires.
                self._event("retired", slot, rc=rc)
                self._log(f"{slot.name} retired (rc={rc})")
                del self.slots[slot.name]
                continue
            if rc == RESUMABLE_EXIT_CODE:
                slot.preemptions += 1
                slot.consecutive = 0  # a correct drain proves the binary healthy
                self._event("preemption", slot, rc=rc)
                if (
                    self.max_preemptions is not None
                    and slot.preemptions > int(self.max_preemptions)
                ):
                    self._log(f"{slot.name} exceeded fault.max_preemptions; giving up")
                    return self._finish("preemption_budget", rc)
                slot.generation += 1
                slot.next_spawn_at = 0.0  # respawn immediately: down = lost capacity
                self._log(f"{slot.name} drained on preemption; respawning immediately")
                continue
            if rc == 0 and slot.role == "front":
                self._log("front shut down cleanly; stopping the fleet")
                return self._finish("clean", 0)
            # Crash (or an unexpected clean replica exit — same respawn path,
            # but a true crash consumes the retry budget and backs off).
            if rc != 0:
                slot.retries += 1
                slot.consecutive += 1
                self._event("crash", slot, rc=rc)
                if self.fleet is not None:
                    try:
                        self.fleet.collect_blackboxes(f"{slot.name}_rc{rc}")
                    except Exception:
                        pass
                if slot.retries > self.max_retries:
                    slot.abandoned = True
                    slot.desired = False
                    self._event("abandoned", slot, rc=rc)
                    self._log(f"{slot.name} exceeded fault.max_retries={self.max_retries}")
                    if slot.role == "front" or not self._live_or_pending_replicas():
                        return self._finish("retry_budget", rc if rc else 1)
                    continue
                delay = backoff_seconds(slot.consecutive, self.base_backoff, self.max_backoff)
                self._log(
                    f"{slot.name} died (rc={rc}); retry {slot.retries}/{self.max_retries} "
                    f"(consecutive crash {slot.consecutive}) in {delay:.1f}s"
                )
            else:
                self._event("clean_exit", slot, rc=rc)
                delay = 0.0
            slot.generation += 1
            slot.next_spawn_at = time.monotonic() + delay
        return None

    def _live_or_pending_replicas(self) -> bool:
        return any(
            s.role == "replica" and s.desired and not s.canary for s in self.slots.values()
        )

    def _respawn_due(self) -> None:
        now = time.monotonic()
        for slot in self.slots.values():
            if slot.desired and slot.proc is None and not slot.abandoned and now >= slot.next_spawn_at:
                self._spawn(slot)

    # ------------------------------------------------------------------ autoscale
    def _free_replica_index(self) -> int:
        used = {s.index for s in self.slots.values() if s.role == "replica" and not s.canary}
        i = 0
        while i in used:
            i += 1
        return i

    def _autoscale(self) -> None:
        status = self._read_front_status()
        if status is None:
            return
        live = sum(
            1
            for s in self.slots.values()
            if s.role == "replica" and not s.canary and s.desired and s.ready_recorded
        )
        decision = self.decider.decide(time.monotonic(), live, float(status.get("pending", 0)))
        if decision == "up" and live < self.max_replicas:
            index = self._free_replica_index()
            slot = self._make_slot(f"replica{index}", index, "replica")
            # Warm scale-up: the persistent compile cache means the new replica
            # deserializes its ladder instead of compiling it.
            self._spawn(slot)
            self.summary["scale_ups"] += 1
            self._event("scale_up", slot, live=live)
            self._log(f"scale up -> {slot.name} (live {live} -> {live + 1})")
        elif decision == "down" and live > self.min_replicas:
            candidates = [
                s
                for s in self.slots.values()
                if s.role == "replica" and not s.canary and s.desired and s.proc is not None
                and s.ready_recorded
            ]
            if not candidates:
                return
            victim = max(candidates, key=lambda s: s.index)
            victim.desired = False
            try:
                victim.proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
            self.summary["scale_downs"] += 1
            self._event("scale_down", victim, live=live)
            self._log(f"scale down -> draining {victim.name} (live {live} -> {live - 1})")

    def _read_front_status(self) -> Optional[Dict[str, Any]]:
        try:
            return json.loads((self.fleet_dir / "front_status.json").read_text())
        except (OSError, ValueError):
            return None

    # ------------------------------------------------------------------ main loop
    def run(self) -> int:
        from sheeprl_tpu.obs.fleet import TRACE_ID_ENV_VAR, FleetAggregator, new_trace_id

        self.trace_id = os.environ.get(TRACE_ID_ENV_VAR) or new_trace_id()
        fault_preemption.install_signal_handlers()  # SIGTERM -> orderly fleet drain
        obs_fleet = dict((self.cfg.get("obs") or {}).get("fleet") or {})
        if bool(obs_fleet.get("enabled", True)) and obs_fleet.get("dir"):
            try:
                self.fleet = FleetAggregator(
                    str(obs_fleet["dir"]),
                    liveness_timeout_s=float(obs_fleet.get("liveness_timeout_s", 10.0)),
                    trace_id=self.trace_id,
                    max_timeline_mb=float(obs_fleet.get("max_timeline_mb", 64.0)),
                )
                self._log(f"fleet telemetry at {self.fleet.address} -> {obs_fleet['dir']}")
            except OSError as e:
                self._log(f"fleet telemetry disabled: {e}")
        try:
            self._spawn(self._make_slot("front", 0, "front"))
            for i in range(self.min_replicas):
                self._spawn(self._make_slot(f"replica{i}", i, "replica"))
            if self.canary_spec:
                # The canary slot id sits past max_replicas so it never collides
                # with an autoscaled incumbent's telemetry row.
                self._spawn(self._make_slot("canary0", self.max_replicas, "replica", canary=True))
            while not fault_preemption.preemption_requested():
                time.sleep(0.2)
                self._check_ready()
                done = self._reap()
                if done is not None:
                    return done
                self._respawn_due()
                self._autoscale()
            self._log("preempted; draining the fleet")
            return self._finish("preempted", self._shutdown_children())
        except BaseException:
            if self.summary["outcome"] is None:
                self.summary["outcome"] = "supervisor_crashed"
            raise
        finally:
            self._kill_stragglers()
            for slot in self.slots.values():
                self.summary["slots"][slot.name] = {
                    "role": slot.role,
                    "canary": slot.canary,
                    "generation": slot.generation,
                    "retries": slot.retries,
                    "preemptions": slot.preemptions,
                    "abandoned": slot.abandoned,
                }
            write_supervisor_summary(self.f_cfg, self.summary)
            if self.fleet is not None:
                self.fleet.close()

    def _finish(self, outcome: str, rc: int) -> int:
        self.summary["outcome"] = outcome
        self.summary["rc"] = rc
        return rc

    def _shutdown_children(self) -> int:
        """Orderly drain: the front first (clients see ``draining`` and every
        in-flight request flushes through the replicas), replicas after."""
        order = sorted(self.slots.values(), key=lambda s: 0 if s.role == "front" else 1)
        for slot in order:
            if slot.proc is not None and slot.proc.poll() is None:
                try:
                    slot.proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.monotonic() + self.drain_timeout_s + 5.0
        for slot in order:
            if slot.proc is None:
                continue
            try:
                slot.proc.wait(timeout=max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                pass
        return 0

    def _kill_stragglers(self) -> None:
        for slot in self.slots.values():
            if slot.proc is not None and slot.proc.poll() is None:
                try:
                    slot.proc.kill()
                    slot.proc.wait(timeout=5)
                except (OSError, subprocess.TimeoutExpired):
                    pass

    @staticmethod
    def _log(msg: str) -> None:
        print(f"[fleet] {msg}", flush=True)


def supervise_fleet(overrides: List[str], cfg: Any = None) -> int:
    """Entry point for ``supervise --serve`` with ``serve.fleet.enabled=True``."""
    if cfg is None:
        from sheeprl_tpu.config.core import compose

        cfg = compose(config_name="serve_cli", overrides=overrides)
    return FleetManager(overrides, cfg).run()
