"""Serving fleet: a load-balanced front over N policy-server replicas.

The front (``python -m sheeprl_tpu.serve.fleet``) speaks the PR-13 framed
transport on both sides: clients talk to it exactly like they talk to one
replica (same ``ping``/``act`` grammar), and it fans requests out to the
least-loaded live replica, rerouting on drain/death with zero accepted-request
loss.  The fleet manager (:mod:`sheeprl_tpu.serve.fleet.manager`, reached via
``python -m sheeprl_tpu.supervise --serve`` with ``serve.fleet.enabled=True``)
spawns the front plus ``serve.fleet.min_replicas`` replicas, respawns the dead,
and autoscales between ``min`` and ``max`` on sustained load.

Pure decision logic lives in its own modules so tests hit it without sockets:

* :mod:`~sheeprl_tpu.serve.fleet.routing` — least-loaded selection + the
  consistent-hash ring for session affinity;
* :mod:`~sheeprl_tpu.serve.fleet.autoscale` — the hysteresis scale-up/-down
  decider;
* :mod:`~sheeprl_tpu.serve.fleet.canary` — live greedy-agreement accounting
  for canary deployments (PR-15 ``precision.parity`` reused).
"""

from sheeprl_tpu.serve.fleet.autoscale import AutoscaleDecider
from sheeprl_tpu.serve.fleet.canary import CanaryTracker
from sheeprl_tpu.serve.fleet.routing import HashRing, ReplicaLoad, pick_replica

__all__ = ["AutoscaleDecider", "CanaryTracker", "HashRing", "ReplicaLoad", "pick_replica"]
