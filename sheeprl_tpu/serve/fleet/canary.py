"""Canary deployment accounting: live greedy agreement vs the incumbent.

``serve.fleet.canary={spec, fraction}`` makes the front route ``fraction`` of
the eligible live traffic to a replica serving the candidate registry version.
Every canary-routed request is *shadowed*: the same observation also goes to an
incumbent replica, the client gets the canary's answer (it is live traffic, not
a dark launch), and the two greedy actions are compared.  The running agreement
is stamped into the front's summary as the promotion gate:
``promote = compared > 0 and agreement >= min_agreement``.

The agreement metric is PR-15's parity contract
(:func:`sheeprl_tpu.precision.parity.action_agreement`): discrete actions must
match exactly, continuous actions agree when every component is within
``atol``.  It is re-implemented here on plain numpy — importing
``precision.parity`` would pull JAX into the router process, which must never
initialize an accelerator — and ``tests/test_serve/test_fleet_routing.py`` pins
the two implementations against each other on random batches.

Routing uses an error-diffusion accumulator rather than randomness, so exactly
``round(n * fraction)`` of n eligible requests hit the canary — deterministic
fractions make the CI assertion exact.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Dict, Optional

import numpy as np


def rows_agree(a: np.ndarray, b: np.ndarray, atol: float = 1e-2) -> bool:
    """One action row each: exact match for integer (discrete) actions,
    per-component ``atol`` for floats — ``parity.action_agreement`` on a
    batch of one."""
    a = np.asarray(a)
    b = np.asarray(b)
    if a.shape != b.shape:
        return False
    if np.issubdtype(a.dtype, np.floating) or np.issubdtype(b.dtype, np.floating):
        return bool(np.all(np.abs(a.astype(np.float64) - b.astype(np.float64)) <= atol))
    return bool(np.array_equal(a, b))


class CanaryTracker:
    """Thread-safe canary routing + agreement ledger (the front's replica
    readers record from their own threads)."""

    def __init__(self, spec: str, fraction: float, min_agreement: float = 0.99, atol: float = 1e-2):
        self.spec = str(spec)
        self.fraction = float(fraction)
        self.min_agreement = float(min_agreement)
        self.atol = float(atol)
        self.routed = 0
        self.compared = 0
        self.agreed = 0
        self._acc = 0.0
        self._lock = threading.Lock()

    def take(self) -> bool:
        """Should the next eligible request go to the canary?  Error-diffusion:
        the accumulator gains ``fraction`` per eligible request and a unit is
        spent per canary route."""
        if self.fraction <= 0.0:
            return False
        with self._lock:
            self._acc += self.fraction
            if self._acc >= 1.0:
                self._acc -= 1.0
                self.routed += 1
                return True
        return False

    def record(self, incumbent_action: Any, canary_action: Any) -> None:
        agree = rows_agree(incumbent_action, canary_action, atol=self.atol)
        with self._lock:
            self.compared += 1
            if agree:
                self.agreed += 1

    @property
    def agreement(self) -> float:
        with self._lock:
            return self.agreed / self.compared if self.compared else math.nan

    @property
    def promote(self) -> bool:
        with self._lock:
            compared, agreed = self.compared, self.agreed
        return compared > 0 and agreed / compared >= self.min_agreement

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            compared, agreed, routed = self.compared, self.agreed, self.routed
        agreement: Optional[float] = agreed / compared if compared else None
        return {
            "spec": self.spec,
            "fraction": self.fraction,
            "min_agreement": self.min_agreement,
            "routed": routed,
            "compared": compared,
            "agreement": agreement,
            "promote": compared > 0 and agreement is not None and agreement >= self.min_agreement,
        }
