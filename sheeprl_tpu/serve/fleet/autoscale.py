"""The autoscaler's decision function: sustained load up, sustained idle down.

Deliberately a pure state machine over ``(now, live_replicas, load)`` samples so
the no-flapping contract is unit-testable without processes:

* **scale up** when the mean in-flight-per-replica load has been at or above
  ``scale_up_queue_depth`` for ``scale_up_after_s`` continuously and the fleet
  is below ``max_replicas``;
* **scale down** when the fleet has been completely idle (zero pending) for
  ``scale_down_after_s`` continuously and the fleet is above ``min_replicas``;
* **hysteresis**: any load strictly between zero and the up-threshold resets
  BOTH clocks (the dead zone — a fleet hovering around the threshold neither
  grows nor shrinks), and every decision starts a ``cooldown_s`` window during
  which no further decision fires (a fresh replica needs time to absorb load
  before the sample means anything).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


@dataclass
class AutoscaleDecider:
    min_replicas: int = 1
    max_replicas: int = 4
    scale_up_queue_depth: float = 4.0  # mean pending per live replica
    scale_up_after_s: float = 3.0
    scale_down_after_s: float = 10.0
    cooldown_s: float = 5.0

    _hot_since: Optional[float] = field(default=None, repr=False)
    _idle_since: Optional[float] = field(default=None, repr=False)
    _last_decision: float = field(default=float("-inf"), repr=False)

    def decide(self, now: float, live: int, pending: float) -> Optional[str]:
        """One sample → ``"up"``, ``"down"`` or ``None``.

        ``live`` is the current routable replica count, ``pending`` the fleet's
        total outstanding requests (front in-flight + replica queues).
        """
        load = pending / max(live, 1)
        if load >= self.scale_up_queue_depth:
            self._idle_since = None
            if self._hot_since is None:
                self._hot_since = now
        elif pending <= 0:
            self._hot_since = None
            if self._idle_since is None:
                self._idle_since = now
        else:  # the dead zone: partial load is a reason to do nothing
            self._hot_since = None
            self._idle_since = None

        if now - self._last_decision < self.cooldown_s:
            return None
        if (
            self._hot_since is not None
            and now - self._hot_since >= self.scale_up_after_s
            and live < self.max_replicas
        ):
            self._last_decision = now
            self._hot_since = None
            return "up"
        if (
            self._idle_since is not None
            and now - self._idle_since >= self.scale_down_after_s
            and live > self.min_replicas
        ):
            self._last_decision = now
            self._idle_since = None
            return "down"
        return None
