"""The fleet front: one framed-TCP door, N policy-server replicas behind it.

Clients speak to the front exactly as they would to a single replica (same
``ping``/``act`` wire grammar, ``serve/server.py``), so :class:`PolicyClient`
and :class:`FleetClient` work unchanged.  Internally the front keeps one
upstream channel + reader thread per replica and an in-flight ledger per link:

* each ``act`` is re-stamped with a front-local request id, routed to the
  least-loaded live replica (``routing.pick_replica`` over the front's own
  in-flight counts + the queue depth/p99 the replicas report via pong probes
  and the PR-16 fleet telemetry snapshot), and the reply is forwarded to the
  client under its original id with a ``replica`` stamp added;
* a ``draining`` reply (the PR-14 drain contract) marks the link draining and
  instantly reroutes the request — clients never see the drain;
* a dead channel retires the link and resubmits every request it still owed —
  zero accepted-request loss as long as any replica lives (otherwise requests
  park and retry on re-admission, bounded by ``serve.fleet.park_timeout_s``);
* sessions (``act`` meta ``session=...``, the stateful-policy client id) route
  by consistent hash (``routing.HashRing``) so a recurrent policy's
  device-resident state stays on one replica; a replica death reassigns only
  its sessions (their recurrent state restarts — the server treats an unknown
  session as an episode start);
* ``serve.fleet.canary`` routes a deterministic fraction of the session-less
  traffic to the canary replica and shadows each such request to an incumbent,
  feeding :class:`~sheeprl_tpu.serve.fleet.canary.CanaryTracker` — the live
  agreement stamp in the front's summary.

Replicas are discovered from ``serve.fleet.replicas`` (static ``host:port``
list) and from the record files the fleet manager drops in
``<serve.fleet.dir>/replicas/`` as replicas come ready (respawns rewrite the
record with the new port/generation).  The front writes
``<serve.fleet.dir>/front_status.json`` every ``status_interval_s`` — the
manager's autoscaler input — and exports ``role="front"`` telemetry rows.

No JAX anywhere in this process: the front is pure routing and must never
initialize an accelerator.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Deque, Dict, List, Optional, Tuple

import numpy as np

from sheeprl_tpu.distributed.transport import Channel, ChannelClosed, FramingError, Listener, connect
from sheeprl_tpu.fault import preemption as fault_preemption
from sheeprl_tpu.obs.fleet import maybe_exporter
from sheeprl_tpu.serve.fleet.canary import CanaryTracker
from sheeprl_tpu.serve.fleet.routing import HashRing, ReplicaLoad, pick_replica, routable
from sheeprl_tpu.utils.metric import MetricAggregator

#: Env var override for where the front's exit summary lands (CI / chaos harness).
FRONT_SUMMARY_ENV_VAR = "SHEEPRL_TPU_FLEET_SUMMARY"

#: Replica record files the manager writes; the front polls them for admission.
RECORDS_SUBDIR = "replicas"

#: Connect budget when admitting a replica.  Kept short — and discovery runs off
#: the accept loop — so one dead endpoint can never stall live traffic.
CONNECT_TIMEOUT_S = 2.0

#: After a failed admission, leave the endpoint alone this long before retrying.
ADMIT_RETRY_S = 2.0


class _CanaryPair:
    """One canary-routed request and its incumbent shadow; completes when both
    actions arrived (a dead half just drops the comparison)."""

    __slots__ = ("lock", "actions")

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.actions: Dict[str, np.ndarray] = {}


@dataclass
class _FrontRequest:
    channel: Optional[Channel]  # client channel; None for a canary shadow
    req_id: Any  # the client's id (front ids are internal)
    policy: str
    obs: Any
    session: Optional[str]
    reset: bool
    t_enq: float
    attempts: int = 0
    pair: Optional[_CanaryPair] = None
    pair_role: Optional[str] = None  # "canary" | "incumbent"


class ReplicaLink:
    """One upstream replica: channel, reader thread, in-flight ledger, load."""

    def __init__(self, front: "FleetFront", name: str, host: str, port: int,
                 canary: bool = False, generation: int = 0, pid: Optional[int] = None):
        self.name = name
        self.host = host
        self.port = int(port)
        self.canary = bool(canary)
        self.generation = int(generation)
        self.pid = pid
        self.channel: Channel = connect(host, int(port), timeout_s=CONNECT_TIMEOUT_S)
        self.pending: Dict[int, _FrontRequest] = {}
        self.load = ReplicaLoad()
        self.routed = 0  # lifetime requests this link carried (share accounting)
        self.retired = False
        self.reader = threading.Thread(
            target=front._replica_reader, args=(self,), name=f"fleet-replica-{name}", daemon=True
        )
        self.reader.start()


class FleetFront:
    """Route → reroute → summarize.  One instance per front process."""

    def __init__(self, cfg: Any):
        self.cfg = cfg
        serve_cfg = cfg.serve
        fleet_cfg = serve_cfg.fleet
        self.fleet_cfg = fleet_cfg
        self.drain_timeout_s = float(serve_cfg.drain_timeout_s)
        self.probe_interval_s = float(fleet_cfg.probe_interval_s)
        self.status_interval_s = float(fleet_cfg.status_interval_s)
        self.max_route_attempts = int(fleet_cfg.max_route_attempts)
        self.park_timeout_s = float(fleet_cfg.park_timeout_s)
        self.affinity = bool(fleet_cfg.affinity)
        self.fleet_dir: Optional[Path] = Path(str(fleet_cfg.dir)) if fleet_cfg.dir else None
        records = fleet_cfg.get("replicas_dir") or (
            self.fleet_dir / RECORDS_SUBDIR if self.fleet_dir else None
        )
        self.records_dir: Optional[Path] = Path(str(records)) if records else None
        self.static_endpoints: List[str] = [str(e) for e in (fleet_cfg.replicas or [])]

        canary_cfg = fleet_cfg.get("canary") or {}
        spec = canary_cfg.get("spec")
        self.canary: Optional[CanaryTracker] = (
            CanaryTracker(
                str(spec),
                float(canary_cfg.get("fraction", 0.0)),
                min_agreement=float(canary_cfg.get("min_agreement", 0.99)),
            )
            if spec
            else None
        )

        self._fid = itertools.count(1)
        self._lock = threading.Lock()
        self.replicas: Dict[str, ReplicaLink] = {}
        self.ring = HashRing()
        self._parked: Deque[Tuple[_FrontRequest, float]] = deque()
        self._policies: set = set()
        self._draining = False
        self._stop = threading.Event()
        self._channels: List[Channel] = []
        self.listener: Optional[Listener] = None
        self._fleet = None  # FleetExporter

        # Counters (under self._lock unless noted).
        self.accepted = 0
        self.replied = 0
        self.rerouted = 0
        self.errors = 0
        self.dropped = 0  # replies whose client channel was gone
        self.rejected_draining = 0
        self.parked_expired = 0
        self.replicas_admitted = 0
        self.replicas_retired = 0
        self.metrics = MetricAggregator({"Fleet/latency_ms": "histogram"})
        self._admit_after: Dict[str, float] = {}  # name -> earliest retry (monotonic)
        self._discover_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------- admit
    def _admit(self, name: str, host: str, port: int, canary: bool = False,
               generation: int = 0, pid: Optional[int] = None) -> bool:
        endpoint = f"{host}:{port}"  # a respawn at a new port retries immediately
        if time.monotonic() < self._admit_after.get(endpoint, 0.0):
            return False  # recently failed to connect; don't hammer it
        try:
            link = ReplicaLink(self, name, host, port, canary=canary, generation=generation, pid=pid)
        except (ConnectionError, OSError, TimeoutError):
            self._admit_after[endpoint] = time.monotonic() + ADMIT_RETRY_S
            return False  # not up yet; a later discovery tick retries
        self._admit_after.pop(endpoint, None)
        with self._lock:
            self.replicas[name] = link
            self.replicas_admitted += 1
            if not canary:
                self.ring.add(name)
        self._log(f"admitted replica {name} at {host}:{port} (gen {generation})")
        try:
            link.channel.send("ping")
        except (ChannelClosed, OSError):
            pass
        self._retry_parked()
        return True

    def _retire(self, link: ReplicaLink, resubmit: bool = True) -> None:
        with self._lock:
            if link.retired:
                return
            link.retired = True
            link.load.alive = False
            if self.replicas.get(link.name) is link:
                del self.replicas[link.name]
            self.ring.remove(link.name)
            self.replicas_retired += 1
            owed = list(link.pending.values())
            link.pending.clear()
        try:
            link.channel.close()
        except Exception:
            pass
        if owed:
            self._log(f"replica {link.name} gone with {len(owed)} in flight; rerouting")
        for req in owed:
            with self._lock:
                self.rerouted += 1
            if resubmit:
                self._resubmit(req)

    def _discover(self) -> None:
        for i, endpoint in enumerate(self.static_endpoints):
            name = f"static{i}"
            canary = endpoint.startswith("canary@")
            hostport = endpoint.split("@", 1)[-1]
            host, _, port = hostport.rpartition(":")
            with self._lock:
                known = name in self.replicas
            if not known:
                self._admit(name, host or "127.0.0.1", int(port), canary=canary)
        if self.records_dir is None or not self.records_dir.is_dir():
            return
        for path in sorted(self.records_dir.glob("*.json")):
            try:
                rec = json.loads(path.read_text())
            except (OSError, ValueError):
                continue
            name = str(rec.get("name", path.stem))
            with self._lock:
                existing = self.replicas.get(name)
            if existing is not None:
                same = (existing.host, existing.port) == (rec.get("host"), int(rec.get("port", 0)))
                if same or not existing.retired:
                    continue  # live link, or the respawn's record already admitted
            self._admit(
                name,
                str(rec.get("host", "127.0.0.1")),
                int(rec.get("port", 0)),
                canary=bool(rec.get("canary")),
                generation=int(rec.get("generation", 0)),
                pid=rec.get("pid"),
            )

    # ------------------------------------------------------------------ routing
    def _loads(self) -> Dict[str, ReplicaLoad]:
        """Locked caller: the live load picture, in-flight from the ledger."""
        out: Dict[str, ReplicaLoad] = {}
        for name, link in self.replicas.items():
            load = link.load
            load.inflight = len(link.pending)
            out[name] = load
        return out

    def _canary_link(self) -> Optional[ReplicaLink]:
        with self._lock:
            for link in self.replicas.values():
                if link.canary and routable(link.load):
                    return link
        return None

    def _route_new(self, req: _FrontRequest) -> None:
        """First routing of a freshly-accepted request: canary split, then the
        normal least-loaded/affinity path."""
        if self.canary is not None and req.session is None:
            canary_link = self._canary_link()
            if canary_link is not None and self.canary.take():
                pair = _CanaryPair()
                req.pair, req.pair_role = pair, "canary"
                shadow = _FrontRequest(
                    channel=None, req_id=None, policy=req.policy, obs=req.obs,
                    session=None, reset=req.reset, t_enq=req.t_enq,
                    pair=pair, pair_role="incumbent",
                )
                self._send_to(canary_link, req)
                self._submit(shadow, exclude=(canary_link.name,))
                return
        self._submit(req)

    def _submit(self, req: _FrontRequest, exclude: Tuple[str, ...] = ()) -> None:
        target: Optional[ReplicaLink] = None
        with self._lock:
            exclude = exclude + tuple(n for n, l in self.replicas.items() if l.canary)
            if req.session is not None and self.affinity:
                owner = self.ring.assign(req.session)
                link = self.replicas.get(owner) if owner else None
                if link is not None and owner not in exclude and routable(link.load):
                    target = link
            if target is None:
                name = pick_replica(self._loads(), exclude=exclude)
                target = self.replicas.get(name) if name else None
        if target is None:
            self._park(req)
            return
        self._send_to(target, req)

    def _send_to(self, link: ReplicaLink, req: _FrontRequest) -> None:
        fid = next(self._fid)
        with self._lock:
            if link.retired:
                pass  # fall through to the failure path below via a closed send
            link.pending[fid] = req
            link.routed += 1
        meta: Dict[str, Any] = {"policy": req.policy, "req_id": fid}
        if req.session is not None:
            meta["session"] = req.session
        if req.reset:
            meta["reset"] = True
        try:
            link.channel.send("act", payload=req.obs, **meta)
        except (ChannelClosed, OSError):
            with self._lock:
                link.pending.pop(fid, None)
            self._retire(link, resubmit=True)
            self._resubmit(req)

    def _resubmit(self, req: _FrontRequest) -> None:
        if req.pair_role == "incumbent":
            return  # shadow lost its replica: the comparison is simply dropped
        if req.pair_role == "canary":
            req.pair, req.pair_role = None, None  # serve the client from the main pool
        req.attempts += 1
        if req.attempts > self.max_route_attempts:
            self._reply_error(req, f"no live replica after {req.attempts} attempts")
            return
        self._submit(req)

    def _park(self, req: _FrontRequest) -> None:
        if req.pair_role == "incumbent":
            return
        with self._lock:
            self._parked.append((req, time.monotonic() + self.park_timeout_s))

    def _retry_parked(self) -> None:
        with self._lock:
            parked = list(self._parked)
            self._parked.clear()
            any_routable = any(routable(l.load) for l in self.replicas.values())
        now = time.monotonic()
        for req, deadline in parked:
            if now >= deadline:
                with self._lock:
                    self.parked_expired += 1
                self._reply_error(req, f"no replica became available within {self.park_timeout_s}s")
            elif any_routable:
                self._submit(req)
            else:
                with self._lock:
                    self._parked.append((req, deadline))

    def _reply_error(self, req: _FrontRequest, error: str) -> None:
        with self._lock:
            self.errors += 1
        if req.channel is None:
            return
        try:
            req.channel.send("error", req_id=req.req_id, error=error)
        except (ChannelClosed, OSError):
            with self._lock:
                self.dropped += 1

    # ------------------------------------------------------------------ readers
    def _client_reader(self, ch: Channel) -> None:
        while not ch.closed:
            try:
                kind, meta, payload = ch.recv(timeout=0.5)
            except TimeoutError:
                continue
            except (ChannelClosed, FramingError, OSError):
                return
            try:
                self._handle_client(ch, kind, meta, payload)
            except ChannelClosed:
                return

    def _handle_client(self, ch: Channel, kind: str, meta: Dict[str, Any], payload: Any) -> None:
        if kind == "ping":
            with self._lock:
                replicas = {
                    name: {
                        "alive": link.load.alive,
                        "draining": link.load.draining,
                        "inflight": len(link.pending),
                        "canary": link.canary,
                    }
                    for name, link in self.replicas.items()
                }
                policies = sorted(self._policies)
            ch.send(
                "pong",
                policies=policies,
                aliases=policies,
                draining=bool(self._draining),
                fleet={
                    "replicas": replicas,
                    "canary": self.canary.summary() if self.canary else None,
                },
            )
            return
        if kind != "act":
            ch.send("error", req_id=meta.get("req_id"), error=f"unknown message kind {kind!r}")
            return
        req_id = meta.get("req_id")
        if self._draining:
            with self._lock:
                self.rejected_draining += 1
            ch.send("draining", req_id=req_id)
            return
        if not isinstance(payload, dict):
            ch.send("error", req_id=req_id, error="act payload must be an obs dict")
            return
        session = meta.get("session")
        req = _FrontRequest(
            channel=ch,
            req_id=req_id,
            policy=str(meta.get("policy", "")),
            obs=payload,
            session=str(session) if session is not None else None,
            reset=bool(meta.get("reset", False)),
            t_enq=time.monotonic(),
        )
        with self._lock:
            self.accepted += 1
        self._route_new(req)

    def _replica_reader(self, link: ReplicaLink) -> None:
        while True:
            try:
                kind, meta, payload = link.channel.recv(timeout=0.5)
            except TimeoutError:
                if link.retired:
                    return
                continue
            except (ChannelClosed, FramingError, OSError):
                break
            if kind == "act_result":
                self._on_act_result(link, meta, payload)
            elif kind == "draining":
                self._on_draining(link, meta)
            elif kind == "pong":
                self._on_pong(link, meta)
            elif kind == "error":
                self._on_replica_error(link, meta)
        self._retire(link, resubmit=True)

    def _on_act_result(self, link: ReplicaLink, meta: Dict[str, Any], payload: Any) -> None:
        fid = meta.get("req_id")
        with self._lock:
            req = link.pending.pop(fid, None)
            p99 = meta.get("p99_ms")
            if isinstance(p99, (int, float)) and p99 == p99:
                link.load.p99_ms = float(p99)
        if req is None:
            return
        if req.pair is not None and req.pair_role is not None:
            action = np.asarray((payload or {}).get("action"))
            with req.pair.lock:
                req.pair.actions[req.pair_role] = action
                complete = len(req.pair.actions) == 2
                actions = dict(req.pair.actions)
            if complete and self.canary is not None:
                self.canary.record(actions["incumbent"], actions["canary"])
        if req.channel is None:
            return  # shadow: accounted above, nothing to forward
        latency_ms = (time.monotonic() - req.t_enq) * 1000.0
        stamps = {
            k: meta[k] for k in ("queue_ms", "infer_ms", "batch_fill", "bucket", "p99_ms") if k in meta
        }
        try:
            req.channel.send(
                "act_result", payload=payload, req_id=req.req_id, replica=link.name,
                front_ms=latency_ms, **stamps,
            )
            with self._lock:
                self.replied += 1
            self.metrics.update("Fleet/latency_ms", latency_ms)
        except (ChannelClosed, OSError):
            with self._lock:
                self.dropped += 1

    def _on_draining(self, link: ReplicaLink, meta: Dict[str, Any]) -> None:
        with self._lock:
            was_draining = link.load.draining
            link.load.draining = True
            self.ring.remove(link.name)
            req = link.pending.pop(meta.get("req_id"), None)
            if req is not None:
                self.rerouted += 1
        if not was_draining:
            self._log(f"replica {link.name} is draining; rerouting its traffic")
        if req is not None:
            self._resubmit(req)

    def _on_pong(self, link: ReplicaLink, meta: Dict[str, Any]) -> None:
        with self._lock:
            link.load.draining = bool(meta.get("draining", link.load.draining))
            if link.load.draining:
                self.ring.remove(link.name)
            depth = meta.get("queue_depth")
            if isinstance(depth, (int, float)):
                link.load.queue_depth = float(depth)
            p99 = meta.get("p99_ms")
            if isinstance(p99, (int, float)) and p99 == p99:
                link.load.p99_ms = float(p99)
            for p in meta.get("policies") or []:
                self._policies.add(str(p))

    def _on_replica_error(self, link: ReplicaLink, meta: Dict[str, Any]) -> None:
        with self._lock:
            req = link.pending.pop(meta.get("req_id"), None)
        if req is None:
            return
        with self._lock:
            self.errors += 1
        if req.channel is not None:
            try:
                req.channel.send("error", req_id=req.req_id, error=meta.get("error"), replica=link.name)
            except (ChannelClosed, OSError):
                with self._lock:
                    self.dropped += 1

    # ------------------------------------------------------------------- probes
    def _probe(self) -> None:
        with self._lock:
            links = list(self.replicas.values())
        for link in links:
            try:
                link.channel.send("ping")
            except (ChannelClosed, OSError):
                pass  # the reader will retire it
        self._merge_snapshot_loads()

    def _merge_snapshot_loads(self) -> None:
        """Best-effort merge of the PR-16 telemetry snapshot: replica-side queue
        depth between pongs, matched by pid."""
        fleet_dir = ((self.cfg.get("obs") or {}).get("fleet") or {}).get("dir")
        if not fleet_dir:
            return
        try:
            with open(os.path.join(str(fleet_dir), "snapshot.json")) as f:
                snapshot = json.load(f)
        except (OSError, ValueError):
            return
        by_pid = {
            proc.get("pid"): proc.get("metrics") or {}
            for proc in (snapshot.get("processes") or {}).values()
            if proc.get("role") == "serve"
        }
        with self._lock:
            for link in self.replicas.values():
                metrics = by_pid.get(link.pid)
                if not metrics:
                    continue
                depth = metrics.get("Serve/queue_depth")
                if isinstance(depth, (int, float)):
                    link.load.queue_depth = float(depth)
                p99 = metrics.get("Serve/latency_p99_ms")
                if isinstance(p99, (int, float)) and p99 == p99:
                    link.load.p99_ms = float(p99)

    # ------------------------------------------------------------------ serving
    def run(self) -> int:
        """Listen, route until stop/preemption, drain, summarize.  Returns 75
        when preempted (the supervisor respawns the front) else 0."""
        fleet_cfg = self.fleet_cfg
        self.listener = Listener(host=str(fleet_cfg.host), port=int(fleet_cfg.port))
        self._discover()
        self._write_ready_file()
        self._log(f"front listening on {self.listener.address}")
        self._fleet = maybe_exporter(
            self.cfg,
            "front",
            generation=int(os.environ.get("SHEEPRL_TPU_FAULT_RESTARTS", "0") or 0),
        )
        last_probe = 0.0
        last_status = 0.0
        threads: List[threading.Thread] = []
        try:
            while not self._stop.is_set() and not fault_preemption.preemption_requested():
                try:
                    ch = self.listener.accept(timeout=0.2)
                except TimeoutError:
                    pass
                except OSError:
                    break
                else:
                    with self._lock:
                        self._channels.append(ch)
                    t = threading.Thread(
                        target=self._client_reader, args=(ch,), name="fleet-client", daemon=True
                    )
                    t.start()
                    threads.append(t)
                now = time.monotonic()
                if now - last_probe >= self.probe_interval_s:
                    last_probe = now
                    # discovery dials out (connects can block on a dead
                    # endpoint): keep it off the accept loop
                    if self._discover_thread is None or not self._discover_thread.is_alive():
                        self._discover_thread = threading.Thread(
                            target=self._discover, name="fleet-discover", daemon=True
                        )
                        self._discover_thread.start()
                    self._probe()
                if now - last_status >= self.status_interval_s:
                    last_status = now
                    self._write_status()
                    self._fleet_update()
                self._retry_parked()
        finally:
            preempted = fault_preemption.preemption_requested()
            self._drain()
            self._write_status()
            if self._fleet is not None:
                self._fleet_update()
                try:
                    self._fleet.close()
                except Exception:
                    pass
            self._write_summary(preempted=preempted)
            self._close()
        return fault_preemption.RESUMABLE_EXIT_CODE if preempted else 0

    def shutdown(self) -> None:
        """Clean stop (tests/benchmarks): same drain path, exit code 0."""
        self._stop.set()

    def _pending_total(self) -> int:
        with self._lock:
            return sum(len(l.pending) for l in self.replicas.values()) + len(self._parked)

    def _drain(self) -> None:
        """Stop admitting, let the replicas finish everything the front owes."""
        self._draining = True
        deadline = time.monotonic() + self.drain_timeout_s
        while self._pending_total() > 0 and time.monotonic() < deadline:
            self._retry_parked()
            time.sleep(0.02)
        with self._lock:
            parked = list(self._parked)
            self._parked.clear()
        for req, _ in parked:
            with self._lock:
                self.parked_expired += 1
            self._reply_error(req, "front shut down before a replica became available")

    def _close(self) -> None:
        if self.listener is not None:
            self.listener.close()
        with self._lock:
            links = list(self.replicas.values())
            channels = list(self._channels)
        for link in links:
            try:
                link.channel.close()
            except Exception:
                pass
        for ch in channels:
            ch.close()

    # ---------------------------------------------------------------- artifacts
    def _write_ready_file(self) -> None:
        ready = self.fleet_cfg.ready_file
        if not ready:
            return
        with self._lock:
            replicas = sorted(self.replicas)
        _atomic_write_json(
            Path(str(ready)),
            {"host": self.listener.host, "port": self.listener.port, "replicas": replicas},
        )

    def _write_status(self) -> None:
        if self.fleet_dir is None:
            return
        with self._lock:
            replicas = {
                name: {
                    "inflight": len(link.pending),
                    "queue_depth": link.load.queue_depth,
                    "draining": link.load.draining,
                    "canary": link.canary,
                    "generation": link.generation,
                    "routed": link.routed,
                }
                for name, link in self.replicas.items()
            }
            doc = {
                "written": time.time(),
                "draining": self._draining,
                "live": sum(
                    1 for l in self.replicas.values() if routable(l.load) and not l.canary
                ),
                "pending": sum(len(l.pending) for l in self.replicas.values()) + len(self._parked),
                "parked": len(self._parked),
                "accepted": self.accepted,
                "replied": self.replied,
                "rerouted": self.rerouted,
                "replicas": replicas,
            }
        try:
            _atomic_write_json(self.fleet_dir / "front_status.json", doc)
        except OSError:
            pass

    def _fleet_update(self) -> None:
        exporter = self._fleet
        if exporter is None:
            return
        with self._lock:
            routed_total = sum(l.routed for l in self.replicas.values())
            shares = {
                name: link.routed / max(routed_total, 1) for name, link in self.replicas.items()
            }
            live = sum(1 for l in self.replicas.values() if routable(l.load))
            pending = sum(len(l.pending) for l in self.replicas.values()) + len(self._parked)
            accepted, replied, rerouted = self.accepted, self.replied, self.rerouted
            admitted, retired = self.replicas_admitted, self.replicas_retired
        exporter.counter("requests_accepted", accepted)
        exporter.counter("requests_replied", replied)
        exporter.counter("requests_rerouted", rerouted)
        exporter.gauge("Fleet/reroutes", rerouted)
        exporter.gauge("Fleet/live_replicas", live)
        exporter.gauge("Fleet/pending", pending)
        exporter.gauge("Fleet/replicas_admitted", admitted)
        exporter.gauge("Fleet/replicas_retired", retired)
        for name, share in shares.items():
            exporter.gauge(f"Fleet/share/{name}", share)
        if self.canary is not None and self.canary.compared:
            exporter.gauge("Fleet/canary_agreement", self.canary.agreement)
        hist = self.metrics.metrics["Fleet/latency_ms"].compute()
        if hist:
            exporter.gauge("Fleet/latency_p99_ms", float(hist["p99"]))

    def summary(self, preempted: bool = False) -> Dict[str, Any]:
        with self._lock:
            per_replica = {
                name: {"routed": link.routed, "draining": link.load.draining, "canary": link.canary}
                for name, link in self.replicas.items()
            }
            doc: Dict[str, Any] = {
                "preempted": bool(preempted),
                "accepted": self.accepted,
                "replied": self.replied,
                "rerouted": self.rerouted,
                "errors": self.errors,
                "dropped": self.dropped,
                "rejected_draining": self.rejected_draining,
                "parked_expired": self.parked_expired,
                "replicas_admitted": self.replicas_admitted,
                "replicas_retired": self.replicas_retired,
                "replicas": per_replica,
            }
        computed = self.metrics.compute()
        doc["p99_ms"] = computed.get("Fleet/latency_ms/p99")
        doc["p50_ms"] = computed.get("Fleet/latency_ms/p50")
        doc["canary"] = self.canary.summary() if self.canary else None
        return doc

    def _write_summary(self, preempted: bool) -> None:
        path = os.environ.get(FRONT_SUMMARY_ENV_VAR) or self.fleet_cfg.summary_path
        if not path:
            return
        _atomic_write_json(Path(str(path)), self.summary(preempted=preempted))

    @staticmethod
    def _log(msg: str) -> None:
        print(f"[fleet-front] {msg}", flush=True)


def _atomic_write_json(path: Path, doc: Dict[str, Any]) -> None:
    import tempfile

    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp_name = tempfile.mkstemp(prefix=f".{path.name}.", suffix=".tmp", dir=path.parent)
    with os.fdopen(fd, "w") as f:
        json.dump(doc, f, indent=2)
    os.replace(tmp_name, path)
