"""``python -m sheeprl_tpu.serve.fleet`` — run the fleet front (router).

The front is a pure routing process: it composes the same ``serve_cli`` config
as a replica (so ``serve.fleet.*`` overrides use one grammar), but it never
imports JAX and never touches the compile cache — replicas own the
accelerator; the front owns the door.

Typically spawned by the fleet manager (``python -m sheeprl_tpu.supervise
--serve serve.fleet.enabled=True``); standalone use with a static replica
list::

    python -m sheeprl_tpu.serve.fleet \\
        serve.fleet.replicas='[127.0.0.1:7557,127.0.0.1:7558]' \\
        serve.fleet.port=7550

Exits 75 (``RESUMABLE_EXIT_CODE``) after a SIGTERM drain so the manager
respawns it like any replica.
"""

from __future__ import annotations

import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    overrides = list(sys.argv[1:] if argv is None else argv)
    from sheeprl_tpu.config.core import compose

    cfg = compose(config_name="serve_cli", overrides=overrides)

    from sheeprl_tpu.fault.preemption import install_signal_handlers

    install_signal_handlers()

    from sheeprl_tpu.serve.fleet.front import FleetFront

    return FleetFront(cfg).run()


if __name__ == "__main__":
    sys.exit(main())
