"""Fleet routing decisions: least-loaded selection + session-affine hashing.

Pure functions and a small ring class — no sockets, no threads — so the unit
tests pin the routing contract directly and the front just feeds it live
numbers.

Least-loaded: the score of a replica is its in-flight request count (the
front's own ledger, exact) plus the queue depth its last pong/telemetry row
reported (the replica-side backlog the front has not seen replies for yet).
Ties break on the replica's rolling p99 and then on name, so selection is
deterministic for a given load picture.

Session affinity: a consistent-hash ring (stable points per replica via
``blake2b``).  A session hashes to the first ring point clockwise of it, so

* the same session always lands on the same live replica (hash stability),
* adding a replica only steals the sessions between the new points and their
  predecessors (minimal churn), and
* removing a dead replica reassigns ONLY its sessions, each to the next point
  clockwise — everyone else keeps their slot (reassignment-on-death).
"""

from __future__ import annotations

import bisect
import hashlib
import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class ReplicaLoad:
    """One replica's load picture, as the front currently believes it."""

    inflight: int = 0  # requests the front has sent and not seen replied
    queue_depth: float = 0.0  # replica-reported backlog (pong / fleet telemetry)
    p99_ms: float = math.nan  # rolling reply-stamp p99
    draining: bool = False
    alive: bool = True

    @property
    def score(self) -> float:
        return float(self.inflight) + float(self.queue_depth)


def routable(load: ReplicaLoad) -> bool:
    return load.alive and not load.draining


def pick_replica(loads: Dict[str, ReplicaLoad], exclude: Tuple[str, ...] = ()) -> Optional[str]:
    """The least-loaded live, non-draining replica; ``None`` when nothing is
    routable.  ``exclude`` removes candidates (e.g. the canary, or the replica
    a request just bounced off)."""
    best: Optional[str] = None
    best_key: Optional[Tuple[float, float, str]] = None
    for name, load in loads.items():
        if name in exclude or not routable(load):
            continue
        p99 = load.p99_ms if load.p99_ms == load.p99_ms else float("inf")  # NaN-safe
        key = (load.score, p99, name)
        if best_key is None or key < best_key:
            best, best_key = name, key
    return best


def _point(label: str) -> int:
    return int.from_bytes(hashlib.blake2b(label.encode(), digest_size=8).digest(), "big")


@dataclass
class HashRing:
    """Consistent-hash ring for session-affine routing.

    ``vnodes`` virtual points per member keep the session shares balanced
    (~1/sqrt(vnodes) relative spread); 64 is plenty for single-digit fleets.
    """

    vnodes: int = 64
    _points: List[Tuple[int, str]] = field(default_factory=list)
    _members: Dict[str, List[int]] = field(default_factory=dict)

    def add(self, member: str) -> None:
        if member in self._members:
            return
        points = [_point(f"{member}#{i}") for i in range(self.vnodes)]
        self._members[member] = points
        for p in points:
            bisect.insort(self._points, (p, member))

    def remove(self, member: str) -> None:
        points = self._members.pop(member, None)
        if points is None:
            return
        drop = set(points)
        self._points = [(p, m) for p, m in self._points if not (m == member and p in drop)]

    def members(self) -> List[str]:
        return sorted(self._members)

    def __contains__(self, member: str) -> bool:
        return member in self._members

    def assign(self, session: str) -> Optional[str]:
        """The member owning ``session`` (first ring point clockwise); ``None``
        on an empty ring."""
        if not self._points:
            return None
        h = _point(f"session:{session}")
        i = bisect.bisect_right(self._points, (h, "￿"))
        if i == len(self._points):
            i = 0
        return self._points[i][1]
