"""Continuous batching primitives: bucket ladders, batch collection, padding.

Podracer (arXiv 2104.06272) keeps inference on the accelerator at *fixed,
precompiled shapes*; the pad-and-bucket discipline here is how a server with a
variable number of in-flight requests honors that.  The ladder is a small set of
batch sizes (powers of two up to ``serve.max_batch_size``); every dispatch pads
its request batch up to the smallest bucket that fits, so the only shapes XLA
ever sees are the ladder's — precompiled at startup, pinned by the IR006
compile-memory budgets, and immune to post-warmup recompiles.

The collection rule is classic continuous batching: the first request opens a
batch and starts the ``max_batch_delay_ms`` deadline clock; the batch dispatches
the moment it reaches ``max_batch_size`` *or* the deadline expires — latency is
bounded by the deadline even at one request per minute, and throughput reaches
one dispatch per full bucket under load.

Stdlib + numpy only: unit-testable without touching JAX.
"""

from __future__ import annotations

import queue
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


def bucket_ladder(max_batch: int, explicit: Optional[Sequence[int]] = None) -> List[int]:
    """The sorted batch-size ladder: powers of two up to ``max_batch`` (which is
    always included), or a validated explicit ladder (``serve.buckets``)."""
    max_batch = int(max_batch)
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    if explicit:
        ladder = sorted({int(b) for b in explicit})
        if ladder[0] < 1:
            raise ValueError(f"bucket sizes must be >= 1, got {ladder}")
        if ladder[-1] != max_batch:
            raise ValueError(
                f"explicit ladder {ladder} must top out at serve.max_batch_size={max_batch}"
            )
        return ladder
    ladder = []
    b = 1
    while b < max_batch:
        ladder.append(b)
        b *= 2
    ladder.append(max_batch)
    return ladder


def pick_bucket(ladder: Sequence[int], n: int) -> int:
    """Smallest ladder bucket that fits ``n`` requests."""
    for b in ladder:
        if n <= b:
            return int(b)
    raise ValueError(f"batch of {n} exceeds the ladder maximum {ladder[-1]}")


def collect_batch(
    q: "queue.Queue",
    max_batch: int,
    delay_s: float,
    first_timeout_s: float = 0.1,
) -> List[Any]:
    """Pull one continuous batch off ``q``.

    Blocks up to ``first_timeout_s`` for the first item (an empty list means idle
    — the caller re-checks its shutdown flag and loops).  Once a batch is open,
    keeps pulling until it holds ``max_batch`` items or ``delay_s`` has passed
    since the batch opened.
    """
    try:
        batch = [q.get(timeout=first_timeout_s)]
    except queue.Empty:
        return []
    deadline = time.monotonic() + max(float(delay_s), 0.0)
    while len(batch) < max_batch:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        try:
            batch.append(q.get(timeout=remaining))
        except queue.Empty:
            break
    return batch


def pad_obs_batch(
    obs_list: Sequence[Dict[str, np.ndarray]],
    template: Dict[str, Tuple[Tuple[int, ...], str]],
    bucket: int,
) -> Dict[str, np.ndarray]:
    """Stack per-request obs dicts into one zero-padded ``[bucket, ...]`` batch.

    Every request's arrays are cast to the policy's template dtypes (clients may
    send float64 rewards-of-habit numpy); rows past ``len(obs_list)`` stay zero —
    their outputs are computed and discarded, which is the price of pinned shapes.
    """
    if len(obs_list) > bucket:
        raise ValueError(f"{len(obs_list)} requests do not fit bucket {bucket}")
    out: Dict[str, np.ndarray] = {}
    for key, (shape, dtype) in template.items():
        arr = np.zeros((bucket, *shape), dtype=np.dtype(dtype))
        for i, obs in enumerate(obs_list):
            if key not in obs:
                raise KeyError(f"request {i} is missing obs key {key!r}")
            row = np.asarray(obs[key], dtype=np.dtype(dtype))
            if row.shape != tuple(shape):
                raise ValueError(
                    f"obs key {key!r}: request shape {row.shape} != policy shape {tuple(shape)}"
                )
            arr[i] = row
        out[key] = arr
    return out
