"""Policy-as-a-service: AOT-precompiled inference with continuous batching.

``python -m sheeprl_tpu.serve serve.policies=[name:selector,...]`` loads policies
from the model registry, precompiles a ladder of padded batch shapes at startup
(through the persistent XLA compilation cache, so warm restarts skip XLA
entirely), and serves observation requests over the PR-13 framed-TCP transport
with continuous batching: requests accumulate in a bounded queue and dispatch as
one padded device batch the moment the current bucket fills or the
``serve.max_batch_delay_ms`` deadline expires.  See ``howto/serving.md``.

Import cost is deliberately tiny — the heavy imports (jax, agents) live in
:mod:`sheeprl_tpu.serve.server` and load when a server actually starts.
"""

from sheeprl_tpu.serve.router import parse_spec, resolve_version

__all__ = ["parse_spec", "resolve_version"]
