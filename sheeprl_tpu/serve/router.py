"""Registry routing: ``name[:selector]`` policy specs → registry version entries.

One grammar everywhere — the serve CLI's ``serve.policies`` list, request headers
(``meta["policy"]``), and ``sheeprl_tpu.eval checkpoint_path=name:selector`` all
route through :func:`parse_spec` + :func:`resolve_version`:

* ``name`` / ``name:latest`` — the highest registered version;
* ``name:3`` — that exact version;
* ``name:production`` (any non-integer selector) — the newest version whose
  registry ``stage`` matches, case-insensitively (stages are set with
  ``transition_model`` / the registration CLI).

Import-light (stdlib only): the eval CLI resolves specs before JAX loads.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple, Union

Selector = Union[None, int, str]


def parse_spec(spec: str) -> Tuple[str, Selector]:
    """``"name[:selector]"`` → ``(name, selector)``; integer selectors are parsed."""
    name, sep, selector = str(spec).partition(":")
    name = name.strip()
    if not name:
        raise ValueError(f"empty policy name in spec {spec!r}")
    if not sep or not selector.strip():
        return name, None
    selector = selector.strip()
    try:
        return name, int(selector)
    except ValueError:
        return name, selector


def resolve_version(versions: List[Dict[str, Any]], selector: Selector) -> Dict[str, Any]:
    """Pick one registry version entry out of ``versions`` for ``selector``."""
    if not versions:
        raise ValueError("model has no registered versions")
    by_version = sorted(versions, key=lambda e: int(e["version"]))
    if selector is None or selector == "latest":
        return by_version[-1]
    if isinstance(selector, int):
        for entry in by_version:
            if int(entry["version"]) == selector:
                return entry
        raise ValueError(
            f"no version {selector} (registered: {[int(e['version']) for e in by_version]})"
        )
    stage = str(selector).lower()
    staged = [e for e in by_version if str(e.get("stage", "")).lower() == stage]
    if not staged:
        stages = sorted({str(e.get("stage", "None")) for e in by_version})
        raise ValueError(f"no version at stage {selector!r} (stages present: {stages})")
    return staged[-1]


def resolve_policy(manager, spec: str) -> Tuple[str, Dict[str, Any]]:
    """Resolve ``spec`` against a model manager's index → ``(name, version entry)``."""
    name, selector = parse_spec(spec)
    index = manager.get_models()
    if name not in index or not index[name].get("versions"):
        known = sorted(index)
        raise ValueError(f"no registered model named {name!r} (registry has: {known})")
    try:
        entry = resolve_version(index[name]["versions"], selector)
    except ValueError as e:
        raise ValueError(f"cannot resolve {spec!r}: {e}") from e
    return name, entry


def resolve_registry_checkpoint(
    spec: str, overrides: Optional[List[str]] = None
) -> Tuple[str, int, Path]:
    """``name[:selector]`` → ``(name, version, payload path)`` for the eval CLI.

    The registry location comes from a ``model_manager.registry_dir=...`` token in
    ``overrides`` (the same override the registration CLI takes), defaulting to the
    config group's ``models_registry``.  Only the local backend resolves here: a
    spec is a *filesystem* routing decision made before any config is composed.
    """
    from sheeprl_tpu.utils.model_manager import LocalModelManager

    registry_dir = "models_registry"
    for ov in overrides or []:
        if ov.startswith("model_manager.registry_dir="):
            registry_dir = ov.split("=", 1)[1]
    if not Path(registry_dir).is_dir():
        raise ValueError(
            f"checkpoint spec {spec!r} is not a path and no registry exists at "
            f"{registry_dir!r} (set model_manager.registry_dir=...)"
        )
    name, entry = resolve_policy(LocalModelManager(registry_dir=registry_dir), spec)
    return name, int(entry["version"]), Path(entry["path"])
