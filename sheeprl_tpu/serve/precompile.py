"""AOT precompilation of the serve batch ladder (+ the serve IR-audit hook).

The server never calls a plainly-jitted act fn at dispatch time — that would
leave compilation to first use and re-trace on any surprise.  Instead, startup
lowers and compiles every ladder bucket ahead of time
(``jit(act_fn).lower(...).compile()``) and the dispatch loop calls the returned
``Compiled`` executables directly: a shape outside the ladder is a hard error at
the batching layer, never a silent recompile, which is how steady-state serving
stays recompile-free under ``analysis.strict=True`` (the PR-1 watchdog enforces
it).

Compiles go through the persistent XLA compilation cache when
``compile_cache.enabled`` is on, so a warm replica restart deserializes the
whole ladder from disk — the ``serve_startup_seconds`` cold/warm A/B in
``benchmarks/serve_bench.py``.

``lower_for_audit()`` exposes the two servable act programs (PPO-family and
SAC-family, at one representative bucket) to the jaxlint-IR tier: donation,
dtype promotion and IR006 compile-memory budgets hold for serving exactly as
they do for training dispatches.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Sequence, Tuple

import numpy as np

#: dtype/shape of the PRNG key argument every act fn takes (raw threefry data;
#: the dispatch loop derives per-dispatch keys host-side as [seed, counter]).
KEY_SHAPE = (2,)
KEY_DTYPE = "uint32"


def zero_key() -> np.ndarray:
    return np.zeros(KEY_SHAPE, np.dtype(KEY_DTYPE))


def dispatch_key(seed: int, counter: int) -> np.ndarray:
    """Deterministic per-dispatch PRNG key, built host-side (no device op, so the
    steady-state loop never triggers an eager-op compile after warmup)."""
    return np.array([seed & 0xFFFFFFFF, counter & 0xFFFFFFFF], np.dtype(KEY_DTYPE))


def precompile_ladder(policy, ladder: Sequence[int], perf_name: str = None) -> Tuple[Dict[int, Any], float]:
    """AOT-compile ``policy.act_fn`` at every ladder bucket.

    Returns ``(bucket -> jax Compiled executable, seconds spent)``.  Each
    executable is also run once on zeros: the first real request must never pay
    first-call costs, and a ladder entry that compiles but cannot execute should
    fail at startup, not mid-traffic.

    ``perf_name`` registers every bucket's XLA cost model with the perf
    attribution plane (``obs/perf.py``) under ``<perf_name>/b<bucket>`` — the
    server turns dispatch counts into per-bucket MFU in its exit summary.
    """
    import jax

    t0 = time.perf_counter()
    jitted = jax.jit(policy.act_fn)
    key = zero_key()
    compiled: Dict[int, Any] = {}
    for bucket in ladder:
        obs = policy.zero_obs(int(bucket))
        if getattr(policy, "stateful", False):
            # Stateful signature: (params, obs, is_first, state, key) -> (actions, new_state).
            is_first = np.ones((int(bucket), 1), np.float32)
            state = policy.zero_state_fn(int(bucket))
            exe = jitted.lower(policy.params, obs, is_first, state, key).compile()
            jax.block_until_ready(exe(policy.params, obs, is_first, state, key))
        else:
            exe = jitted.lower(policy.params, obs, key).compile()
            jax.block_until_ready(exe(policy.params, obs, key))
        compiled[int(bucket)] = exe
        if perf_name:
            from sheeprl_tpu.obs import perf as obs_perf

            obs_perf.register_compiled(f"{perf_name}/b{int(bucket)}", exe)
    return compiled, time.perf_counter() - t0


# --------------------------------------------------------------------- IR audit
def lower_for_audit():
    """IR-audit hook (``python -m sheeprl_tpu.analysis.ir``): the serve-path act
    programs for both servable families, lowered through the same
    ``build_policy`` the server uses, at one representative ladder bucket."""
    from sheeprl_tpu.analysis.ir.synth import (
        box_act_space,
        compose_tiny,
        discrete_act_space,
        tiny_ctx,
        vector_space,
    )
    from sheeprl_tpu.analysis.ir.types import AuditEntry
    from sheeprl_tpu.utils.policy import build_policy

    import jax

    bucket = 4
    entries = []

    ppo_cfg = compose_tiny(
        [
            "exp=ppo",
            "env=discrete_dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.cnn_keys.encoder=[]",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.encoder.mlp_features_dim=8",
        ]
    )
    ppo_policy, _ = build_policy(
        tiny_ctx(ppo_cfg), ppo_cfg, vector_space(), discrete_act_space(), greedy=True
    )
    entries.append(
        AuditEntry(
            name="serve/ppo_act",
            fn=jax.jit(ppo_policy.act_fn),
            args=(ppo_policy.params, ppo_policy.zero_obs(bucket), zero_key()),
            covers=("serve_ppo",),
            precision=str(ppo_cfg.mesh.precision),
        )
    )

    sac_cfg = compose_tiny(
        [
            "exp=sac",
            "env=continuous_dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.hidden_size=8",
        ]
    )
    sac_policy, _ = build_policy(
        tiny_ctx(sac_cfg), sac_cfg, vector_space(), box_act_space(), greedy=True
    )
    entries.append(
        AuditEntry(
            name="serve/sac_act",
            fn=jax.jit(sac_policy.act_fn),
            args=(sac_policy.params, sac_policy.zero_obs(bucket), zero_key()),
            covers=("serve_sac",),
            precision=str(sac_cfg.mesh.precision),
        )
    )

    # int8 weights-only tier (serve.precision=int8): the same act programs with
    # every 2-D kernel stored as Int8Weight and dequantized in-jit — audited as
    # their own programs because the dequant must fuse into the dots (IR006)
    # and the params pytree shape the ladder compiles against changes.  Built at
    # f32 (mesh.precision=fp32) exactly like the server's int8 path.
    for exp, overrides, act_space in (
        (
            "ppo",
            [
                "exp=ppo",
                "env=discrete_dummy",
                "algo.mlp_keys.encoder=[state]",
                "algo.cnn_keys.encoder=[]",
                "algo.dense_units=8",
                "algo.mlp_layers=1",
                "algo.encoder.mlp_features_dim=8",
                "mesh.precision=fp32",
            ],
            discrete_act_space(),
        ),
        (
            "sac",
            [
                "exp=sac",
                "env=continuous_dummy",
                "algo.mlp_keys.encoder=[state]",
                "algo.hidden_size=8",
                "mesh.precision=fp32",
            ],
            box_act_space(),
        ),
    ):
        entries.append(_int8_entry(exp, overrides, act_space, bucket))
    return entries


def _int8_entry(exp, overrides, act_space, bucket):
    """One quantized act-dispatch audit entry (each call jits a distinct
    program — no shared cache to thrash)."""
    import jax

    from sheeprl_tpu.analysis.ir.synth import compose_tiny, tiny_ctx, vector_space
    from sheeprl_tpu.analysis.ir.types import AuditEntry
    from sheeprl_tpu.utils.policy import build_policy, wrap_policy_precision

    cfg = compose_tiny(overrides)
    policy, _ = build_policy(tiny_ctx(cfg), cfg, vector_space(), act_space, greedy=True)
    policy = wrap_policy_precision(policy, "int8")
    return AuditEntry(
        name=f"serve/{exp}_act_int8",
        fn=jax.jit(policy.act_fn),
        args=(policy.params, policy.zero_obs(bucket), zero_key()),
        covers=(f"serve_{exp}_int8",),
        precision="int8",
    )
