"""Device-resident per-session act-state for stateful (recurrent) policies.

A recurrent policy's act fn is ``act_fn(params, obs, is_first, state, key) ->
(actions, new_state)`` — the state (LSTM carry / attention window + the
previous one-hot action) must survive between requests, per client.  This cache
keeps it on device as ONE preallocated pytree of ``capacity + 1`` rows (slot
``capacity`` is scratch) and maps session ids to rows host-side:

* :meth:`assign` turns a batch's session ids into row indices + the ``is_first``
  column: a session seen before continues its episode (``is_first=0``); a new,
  evicted-and-returning, or explicitly ``reset`` session starts fresh
  (``is_first=1`` — the recurrent step masks the stale row in-graph, so slots
  never need host-side zeroing);
* :meth:`gather` / :meth:`scatter` are jitted row gather/scatter, one trace per
  batch-bucket shape (:meth:`warmup` pre-traces them alongside the act ladder
  so steady-state serving never compiles);
* eviction is LRU; session-less requests ride the scratch row (``is_first=1``),
  and the server pads short batches with scratch indices so padding rows
  scatter harmlessly.

A batch holding the same session twice is last-write-wins on the scatter (row
order); the front's session-affine routing makes that a same-client pipelining
artifact, not a correctness hazard.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np


class SessionStateCache:
    """Not thread-safe by design: owned by the server's dispatch loop."""

    def __init__(self, zero_state_fn: Callable[[int], Any], capacity: int):
        import jax

        self.capacity = int(capacity)
        self.scratch = self.capacity  # the extra row: session-less + padding traffic
        self.storage = zero_state_fn(self.capacity + 1)
        self._slots: "OrderedDict[str, int]" = OrderedDict()  # session -> row, LRU order
        self._free: List[int] = list(range(self.capacity))
        self.evictions = 0
        self._gather = jax.jit(lambda storage, idx: jax.tree.map(lambda x: x[idx], storage))
        self._scatter = jax.jit(
            lambda storage, idx, rows: jax.tree.map(
                lambda s, r: s.at[idx].set(r.astype(s.dtype)), storage, rows
            )
        )

    def __len__(self) -> int:
        return len(self._slots)

    def assign(
        self, sessions: Sequence[Optional[str]], resets: Sequence[bool]
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Row index + ``is_first`` per request.  Mutates the LRU order."""
        n = len(sessions)
        idx = np.full((n,), self.scratch, np.int32)
        is_first = np.ones((n, 1), np.float32)
        for i, (session, reset) in enumerate(zip(sessions, resets)):
            if session is None:
                continue  # scratch row, fresh state
            slot = self._slots.get(session)
            if slot is None:
                if self._free:
                    slot = self._free.pop()
                else:
                    _, slot = self._slots.popitem(last=False)  # evict the LRU session
                    self.evictions += 1
                self._slots[session] = slot
            else:
                self._slots.move_to_end(session)
                if not reset:
                    is_first[i, 0] = 0.0
            idx[i] = slot
        return idx, is_first

    def gather(self, idx: np.ndarray) -> Any:
        return self._gather(self.storage, idx)

    def scatter(self, idx: np.ndarray, rows: Any) -> None:
        self.storage = self._scatter(self.storage, idx, rows)

    def drop(self, session: str) -> None:
        slot = self._slots.pop(session, None)
        if slot is not None:
            self._free.append(slot)

    def warmup(
        self, buckets: Sequence[int], step_fn: Optional[Callable[[int, Any], Any]] = None
    ) -> None:
        """Trace gather/scatter per bucket shape before the server goes warm.

        ``step_fn(bucket, state) -> new_state`` runs the policy's compiled act
        between the gather and the scatter.  That matters beyond coverage: the
        act output's leaves carry the mesh's NamedSharding, which the jit cache
        keys on — and the first real scatter also commits that sharding onto
        the storage.  Two passes: pass one scatters act output into fresh
        storage (committing the sharding), pass two traces every bucket's
        gather/scatter against the now-committed storage — the steady-state
        signatures, so serving never compiles."""
        order = sorted(set(int(b) for b in buckets))
        for _ in range(2 if step_fn is not None else 1):
            for bucket in order:
                idx = np.full((bucket,), self.scratch, np.int32)
                rows = self.gather(idx)
                if step_fn is not None:
                    rows = step_fn(bucket, rows)
                self.scatter(idx, rows)

    def stats(self) -> dict:
        return {
            "capacity": self.capacity,
            "sessions": len(self._slots),
            "evictions": self.evictions,
        }
