"""``python -m sheeprl_tpu.supervise exp=... [overrides]``: autoresume supervisor.

Relaunches a crashed or preempted training run from the latest *valid* checkpoint
with bounded exponential-backoff retries; see ``sheeprl_tpu/fault/supervisor.py``
and ``howto/fault_tolerance.md``.

``--serve`` flips to serving mode: the supervisor keeps one stateless
``python -m sheeprl_tpu.serve`` replica alive instead — a SIGTERM'd replica
drains its accepted requests, exits 75, and is respawned immediately
(``howto/serving.md``).

``--serve`` with ``serve.fleet.enabled=True`` runs the whole serving *fleet*:
the load-balancing front plus ``serve.fleet.min_replicas`` replicas, per-slot
respawn, queue-depth autoscaling up to ``serve.fleet.max_replicas``, and an
optional canary replica (``howto/serving.md`` "Fleet").
"""

from sheeprl_tpu.fault.supervisor import main

if __name__ == "__main__":
    main()
