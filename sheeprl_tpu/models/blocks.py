"""Reusable flax.linen building blocks.

TPU-native re-design of ``/root/reference/sheeprl/models/models.py``:

* ``MLP`` (reference ``:16-119``) — dense stack with optional per-layer LayerNorm.
* ``CNN`` / ``DeCNN`` (``:122-287``) — conv stacks in **NHWC** (TPU-native layout; the
  reference is NCHW because torch).  Callers transpose channel-first observations once
  at the boundary.
* ``NatureCNN`` (``:288-330``) — the classic 3-conv Atari trunk + projection.
* ``LayerNormGRUCell`` (``:331-412``) — GRU with LayerNorm on the joint input/hidden
  projection and the Hafner ``update - 1`` bias trick.
* ``MultiEncoder`` / ``MultiDecoder`` (``:413-506``) — fuse dict observations: CNN keys
  concatenated channel-wise into one conv trunk, MLP keys concatenated into one dense
  trunk, outputs concatenated.

All modules take ``dtype`` (compute dtype, bf16 for TPU) and keep ``param_dtype``
float32 — the standard mixed-precision recipe for the MXU.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

Dtype = Any


def _activation(act: str | Callable | None) -> Optional[Callable]:
    if act is None or callable(act):
        return act
    table = {
        "relu": nn.relu,
        "tanh": jnp.tanh,
        "silu": nn.silu,
        "swish": nn.silu,
        "elu": nn.elu,
        "gelu": nn.gelu,
        "leaky_relu": nn.leaky_relu,
        "identity": None,
        "none": None,
    }
    return table[str(act).lower()]


class MLP(nn.Module):
    hidden_sizes: Sequence[int] = ()
    output_dim: Optional[int] = None
    activation: str | Callable = "tanh"
    layer_norm: bool = False
    norm_eps: float = 1e-5
    flatten_input: bool = False
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        act = _activation(self.activation)
        if self.flatten_input:
            x = x.reshape(*x.shape[:-1], -1) if x.ndim > 1 else x
        x = x.astype(self.dtype)
        for size in self.hidden_sizes:
            x = nn.Dense(size, dtype=self.dtype)(x)
            if self.layer_norm:
                x = nn.LayerNorm(epsilon=self.norm_eps, dtype=self.dtype)(x)
            if act is not None:
                x = act(x)
        if self.output_dim is not None:
            x = nn.Dense(self.output_dim, dtype=self.dtype)(x)
        return x


class CNN(nn.Module):
    """Conv stack over NHWC input. ``channels[i]`` with ``kernels[i]``/``strides[i]``."""

    channels: Sequence[int]
    kernels: Sequence[int] = (4,)
    strides: Sequence[int] = (2,)
    paddings: Sequence[Any] = ("SAME",)
    activation: str | Callable = "relu"
    layer_norm: bool = False
    norm_eps: float = 1e-5
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        act = _activation(self.activation)
        n = len(self.channels)
        kernels = list(self.kernels) * n if len(self.kernels) == 1 else self.kernels
        strides = list(self.strides) * n if len(self.strides) == 1 else self.strides
        paddings = list(self.paddings) * n if len(self.paddings) == 1 else self.paddings
        x = x.astype(self.dtype)
        for c, k, s, p in zip(self.channels, kernels, strides, paddings):
            pad = p if isinstance(p, str) else [(p, p), (p, p)]
            x = nn.Conv(c, (k, k), strides=(s, s), padding=pad, dtype=self.dtype)(x)
            if self.layer_norm:
                x = nn.LayerNorm(epsilon=self.norm_eps, dtype=self.dtype)(x)
            if act is not None:
                x = act(x)
        return x


class DeCNN(nn.Module):
    """Transposed-conv stack over NHWC input."""

    channels: Sequence[int]
    kernels: Sequence[int] = (4,)
    strides: Sequence[int] = (2,)
    paddings: Sequence[Any] = ("SAME",)
    activation: str | Callable = "relu"
    apply_act_last: bool = False
    layer_norm: bool = False
    norm_eps: float = 1e-5
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        act = _activation(self.activation)
        n = len(self.channels)
        kernels = list(self.kernels) * n if len(self.kernels) == 1 else self.kernels
        strides = list(self.strides) * n if len(self.strides) == 1 else self.strides
        paddings = list(self.paddings) * n if len(self.paddings) == 1 else self.paddings
        x = x.astype(self.dtype)
        for i, (c, k, s, p) in enumerate(zip(self.channels, kernels, strides, paddings)):
            last = i == n - 1
            pad = p if isinstance(p, str) else [(p, p), (p, p)]
            x = nn.ConvTranspose(c, (k, k), strides=(s, s), padding=pad, dtype=self.dtype)(x)
            if not last or self.apply_act_last:
                if self.layer_norm:
                    x = nn.LayerNorm(epsilon=self.norm_eps, dtype=self.dtype)(x)
                if act is not None:
                    x = act(x)
        return x


class NatureCNN(nn.Module):
    """DQN Nature trunk (reference ``models.py:288-330``): uint8 NHWC in, flat features out."""

    features_dim: int = 512
    activation: str | Callable = "relu"
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        act = _activation(self.activation)
        x = x.astype(self.dtype)
        x = act(nn.Conv(32, (8, 8), strides=(4, 4), padding="VALID", dtype=self.dtype)(x))
        x = act(nn.Conv(64, (4, 4), strides=(2, 2), padding="VALID", dtype=self.dtype)(x))
        x = act(nn.Conv(64, (3, 3), strides=(1, 1), padding="VALID", dtype=self.dtype)(x))
        x = x.reshape(*x.shape[:-3], -1)
        x = act(nn.Dense(self.features_dim, dtype=self.dtype)(x))
        return x


class LayerNormGRUCell(nn.Module):
    """GRU cell with LayerNorm on the fused projection (reference ``models.py:331-412``).

    One matmul computes all three gates from ``[input, hidden]`` — a single large MXU op
    instead of six small ones.  The update gate gets a ``-1`` bias (Hafner) so the cell
    starts out remembering.

    The post-matmul chain (LayerNorm + gates + state blend) can run as ONE fused Pallas
    VMEM pass (``sheeprl_tpu/ops/gru.py``) — enable with ``SHEEPRL_TPU_FUSED_GRU=1``
    (same param tree either way; the kernel consumes this cell's ``ln_scale``/``ln_bias``).
    """

    hidden_size: int
    layer_norm: bool = True
    norm_eps: float = 1e-3
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, h: jax.Array, x: jax.Array) -> Tuple[jax.Array, jax.Array]:
        from sheeprl_tpu.ops import fused_gru_enabled

        inp = jnp.concatenate([x, h], axis=-1).astype(self.dtype)
        fused = nn.Dense(3 * self.hidden_size, use_bias=not self.layer_norm, dtype=self.dtype)(inp)
        if self.layer_norm:
            # NOTE: ln_scale/ln_bias replaced the earlier nn.LayerNorm child module, so
            # the param tree changed (checkpoints from before this cell revision need a
            # LayerNorm_0/{scale,bias} -> ln_scale/ln_bias rename).
            gamma = self.param("ln_scale", nn.initializers.ones, (3 * self.hidden_size,), jnp.float32)
            beta = self.param("ln_bias", nn.initializers.zeros, (3 * self.hidden_size,), jnp.float32)
            h_cast = h.astype(self.dtype)
            from sheeprl_tpu.ops.gru import fused_supported

            if fused_gru_enabled() and fused.ndim == 2 and fused_supported(fused.shape[0]):
                from sheeprl_tpu.ops.gru import fused_layernorm_gru

                h_new = fused_layernorm_gru(fused, h_cast, gamma, beta, self.norm_eps)
            else:
                from sheeprl_tpu.ops.gru import reference_layernorm_gru

                h_new = reference_layernorm_gru(fused, h_cast, gamma, beta, self.norm_eps)
            return h_new, h_new
        reset, cand, update = jnp.split(fused, 3, axis=-1)
        reset = jax.nn.sigmoid(reset)
        cand = jnp.tanh(reset * cand)
        update = jax.nn.sigmoid(update - 1.0)
        h_new = update * cand + (1 - update) * h.astype(self.dtype)
        return h_new, h_new


def cnn_obs_to_nhwc(x: jax.Array, stacked: bool = False) -> jax.Array:
    """``[..., C, H, W]`` (or ``[..., S, C, H, W]`` when ``stacked``) uint8 →
    ``[..., H, W, C·S]`` float in [-0.5, 0.5].

    ``stacked`` must be passed explicitly (derived from the observation-space rank at
    build time): shape alone cannot distinguish a frame-stacked batch from a
    sequence batch ``[T, B, C, H, W]``."""
    if x.dtype == jnp.uint8:
        x = x.astype(jnp.float32) / 255.0 - 0.5
    if stacked:
        *lead, s, c, h, w = x.shape
        x = x.reshape(*lead, s * c, h, w)
    return jnp.moveaxis(x, -3, -1)


class MultiEncoder(nn.Module):
    """Fuse dict observations into one feature vector (reference ``models.py:413-477``).

    ``cnn_keys`` are concatenated channel-wise and passed through one conv trunk;
    ``mlp_keys`` are concatenated and passed through one dense trunk; outputs concat.
    """

    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    cnn_stacked: bool = False  # True when the env pipeline frame-stacks ([S, C, H, W] obs)
    cnn_channels: Sequence[int] = (32, 64, 64)
    cnn_kernels: Sequence[int] = (8, 4, 3)
    cnn_strides: Sequence[int] = (4, 2, 1)
    cnn_features_dim: int = 512
    mlp_hidden_sizes: Sequence[int] = (256, 256)
    mlp_features_dim: Optional[int] = None
    activation: str | Callable = "relu"
    layer_norm: bool = False
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        feats = []
        act = _activation(self.activation)
        if self.cnn_keys:
            imgs = jnp.concatenate(
                [cnn_obs_to_nhwc(obs[k], stacked=self.cnn_stacked) for k in self.cnn_keys], axis=-1
            )
            lead = imgs.shape[:-3]
            imgs = imgs.reshape(-1, *imgs.shape[-3:])
            x = CNN(
                channels=self.cnn_channels,
                kernels=self.cnn_kernels,
                strides=self.cnn_strides,
                paddings=("VALID",),
                activation=self.activation,
                layer_norm=self.layer_norm,
                dtype=self.dtype,
            )(imgs)
            x = x.reshape(*lead, -1)
            x = nn.Dense(self.cnn_features_dim, dtype=self.dtype)(x)
            if act is not None:
                x = act(x)
            feats.append(x)
        if self.mlp_keys:
            vec = jnp.concatenate([obs[k].astype(self.dtype) for k in self.mlp_keys], axis=-1)
            x = MLP(
                hidden_sizes=self.mlp_hidden_sizes,
                output_dim=self.mlp_features_dim,
                activation=self.activation,
                layer_norm=self.layer_norm,
                dtype=self.dtype,
            )(vec)
            feats.append(x)
        return jnp.concatenate(feats, axis=-1)


class MultiDecoder(nn.Module):
    """Decode a latent into per-key observation reconstructions (reference ``:478-506``)."""

    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    cnn_shapes: Dict[str, Tuple[int, ...]]  # per-key [C, H, W]
    mlp_shapes: Dict[str, Tuple[int, ...]]
    cnn_decoder_init: Tuple[int, int, int] = (4, 4, 128)  # H, W, C before deconvs
    cnn_channels: Sequence[int] = (64, 32, 3)
    cnn_kernels: Sequence[int] = (4, 4, 4)
    cnn_strides: Sequence[int] = (2, 2, 2)
    mlp_hidden_sizes: Sequence[int] = (256, 256)
    activation: str | Callable = "relu"
    layer_norm: bool = False
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, z: jax.Array) -> Dict[str, jax.Array]:
        out: Dict[str, jax.Array] = {}
        if self.cnn_keys:
            total_c = sum(int(np.prod(self.cnn_shapes[k][:-2])) for k in self.cnn_keys)
            h0, w0, c0 = self.cnn_decoder_init
            x = nn.Dense(h0 * w0 * c0, dtype=self.dtype)(z.astype(self.dtype))
            lead = x.shape[:-1]
            x = x.reshape(-1, h0, w0, c0)
            channels = list(self.cnn_channels[:-1]) + [total_c]
            x = DeCNN(
                channels=channels,
                kernels=self.cnn_kernels,
                strides=self.cnn_strides,
                paddings=("SAME",),
                activation=self.activation,
                layer_norm=self.layer_norm,
                dtype=self.dtype,
            )(x)
            x = jnp.moveaxis(x, -1, -3)  # back to channel-first for parity with obs
            x = x.reshape(*lead, *x.shape[-3:])
            offset = 0
            for k in self.cnn_keys:
                c = int(np.prod(self.cnn_shapes[k][:-2]))
                out[k] = x[..., offset : offset + c, :, :].reshape(*lead, *self.cnn_shapes[k])
                offset += c
        for k in self.mlp_keys:
            out[k] = MLP(
                hidden_sizes=self.mlp_hidden_sizes,
                output_dim=int(np.prod(self.mlp_shapes[k])),
                activation=self.activation,
                layer_norm=self.layer_norm,
                dtype=self.dtype,
                name=f"mlp_decoder_{k}",
            )(z)
        return out
