"""Lightweight Hydra-like configuration composition.

The reference framework's only user API is a Hydra config tree
(``/root/reference/sheeprl/cli.py:358``, ``sheeprl/configs/config.yaml``).  Hydra is not
available in this image, and a full dependency on it would buy us nothing on TPU, so this
module implements the subset of semantics the reference actually uses:

* a config *tree* of YAML files organised in groups (``algo/``, ``env/``, ``exp/`` ...),
* a root ``config.yaml`` whose ``defaults:`` list selects one option per group,
* experiment files (``exp/*.yaml``) that override anything globally,
* command-line overrides ``group=option`` and dotted assignments ``a.b.c=value``,
* ``${a.b.c}`` interpolation resolved after composition,
* a user-extensible search path via the ``SHEEPRL_TPU_SEARCH_PATH`` environment variable
  (mirrors ``hydra_plugins/sheeprl_search_path.py:10-33`` in the reference).

Composition rules (deliberately simpler than Hydra):

* A ``defaults`` list entry ``{group: option}`` loads ``<group>/<option>.yaml`` and
  merges its content under the ``group`` key (last path component), unless the file sets
  ``_global_: true`` in which case content merges at the root.  ``exp`` configs are
  implicitly global.
* Group files may have their own ``defaults`` which are processed first (recursively).
* ``???`` marks a required value; composition fails if any remain after overrides.
* Later merges win, dicts merge recursively, lists replace.
"""

from __future__ import annotations

import copy
import os
import re
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

import yaml

MISSING = "???"

_BUILTIN_CONFIG_DIR = Path(__file__).parent / "configs"


class _YamlLoader(yaml.SafeLoader):
    """SafeLoader with a YAML-1.2 float resolver (PyYAML reads ``1e-3`` as a string)."""


_YamlLoader.add_implicit_resolver(
    "tag:yaml.org,2002:float",
    re.compile(
        r"""^(?:[-+]?(?:[0-9][0-9_]*)\.[0-9_]*(?:[eE][-+]?[0-9]+)?
          |[-+]?(?:[0-9][0-9_]*)(?:[eE][-+]?[0-9]+)
          |\.[0-9_]+(?:[eE][-+][0-9]+)?
          |[-+]?\.(?:inf|Inf|INF)
          |\.(?:nan|NaN|NAN))$""",
        re.X,
    ),
    list("-+0123456789."),
)


def _yaml_load(text: str) -> Any:
    return yaml.load(text, Loader=_YamlLoader)


class DotDict(dict):
    """dict with attribute access, recursively applied (reference: utils/utils.py:34)."""

    def __getattr__(self, name: str) -> Any:
        try:
            return self[name]
        except KeyError as e:
            raise AttributeError(name) from e

    def __setattr__(self, name: str, value: Any) -> None:
        self[name] = value

    def __delattr__(self, name: str) -> None:
        try:
            del self[name]
        except KeyError as e:
            raise AttributeError(name) from e

    def __deepcopy__(self, memo):
        return DotDict({k: copy.deepcopy(v, memo) for k, v in self.items()})

    @staticmethod
    def wrap(obj: Any) -> Any:
        if isinstance(obj, dict):
            return DotDict({k: DotDict.wrap(v) for k, v in obj.items()})
        if isinstance(obj, (list, tuple)):
            return [DotDict.wrap(v) for v in obj]
        return obj

    def to_dict(self) -> dict:
        return unwrap(self)


def unwrap(obj: Any) -> Any:
    """Convert DotDicts back to plain dicts (for YAML dumping)."""
    if isinstance(obj, dict):
        return {k: unwrap(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [unwrap(v) for v in obj]
    return obj


def _merge(dst: dict, src: dict) -> dict:
    """Recursively merge ``src`` into ``dst`` (in place); ``src`` wins."""
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _merge(dst[k], v)
        else:
            dst[k] = copy.deepcopy(v)
    return dst


def _set_dotted(cfg: dict, key: str, value: Any) -> None:
    parts = key.split(".")
    node = cfg
    for p in parts[:-1]:
        if p not in node or not isinstance(node[p], dict):
            node[p] = {}
        node = node[p]
    node[parts[-1]] = value


def _get_dotted(cfg: dict, key: str) -> Any:
    node: Any = cfg
    for p in key.split("."):
        if isinstance(node, dict):
            node = node[p]
        elif isinstance(node, (list, tuple)):
            node = node[int(p)]
        else:
            raise KeyError(key)
    return node


def _parse_value(text: str) -> Any:
    """Parse an override value with YAML semantics (``null``/``true``/``1e-4``/lists)."""
    try:
        return _yaml_load(text)
    except yaml.YAMLError:
        return text


class ConfigSource:
    """Resolves ``group/option`` to YAML files across the search path."""

    def __init__(self, extra_dirs: Optional[Sequence[os.PathLike]] = None):
        dirs: List[Path] = [_BUILTIN_CONFIG_DIR]
        env_path = os.environ.get("SHEEPRL_TPU_SEARCH_PATH", "")
        for entry in env_path.split(";"):
            entry = entry.strip()
            if entry.startswith("file://"):
                entry = entry[len("file://") :]
            if entry:
                dirs.append(Path(entry))
        for d in extra_dirs or []:
            dirs.append(Path(d))
        self.dirs = dirs

    def find(self, rel: str) -> Optional[Path]:
        if not rel.endswith(".yaml"):
            rel += ".yaml"
        # Later search-path entries win (user dirs override builtins).
        for d in reversed(self.dirs):
            p = d / rel
            if p.is_file():
                return p
        return None

    def options(self, group: str) -> List[str]:
        out = set()
        for d in self.dirs:
            g = d / group
            if g.is_dir():
                out.update(p.stem for p in g.glob("*.yaml"))
        return sorted(out)


_INTERP_RE = re.compile(r"\$\{([^${}]+)\}")


def _resolve_interpolations(cfg: dict) -> None:
    """Resolve ``${dotted.path}`` references in string values, to a fixed point."""

    def resolve_str(s: str, depth: int = 0) -> Any:
        if depth > 16:
            raise ValueError(f"interpolation loop while resolving {s!r}")
        m = _INTERP_RE.fullmatch(s.strip())
        if m:  # whole-string reference: preserve the referenced type
            target = _lookup(m.group(1))
            if isinstance(target, str):
                return resolve_str(target, depth + 1)
            return copy.deepcopy(target)

        def sub(mm: re.Match) -> str:
            v = _lookup(mm.group(1))
            if isinstance(v, str):
                v = resolve_str(v, depth + 1)
            return str(v)

        return _INTERP_RE.sub(sub, s)

    def _lookup(path: str) -> Any:
        path = path.strip()
        if path.startswith("oc.env:") or path.startswith("env:"):
            name = path.split(":", 1)[1]
            name, _, default = name.partition(",")
            return os.environ.get(name.strip(), _parse_value(default.strip()) if default else None)
        try:
            return _get_dotted(cfg, path)
        except (KeyError, IndexError, ValueError) as e:
            raise KeyError(f"interpolation target '{path}' not found") from e

    def walk(node: Any) -> Any:
        if isinstance(node, dict):
            for k in list(node.keys()):
                node[k] = walk(node[k])
            return node
        if isinstance(node, list):
            return [walk(v) for v in node]
        if isinstance(node, str) and "${" in node:
            return resolve_str(node)
        return node

    walk(cfg)


def _check_missing(cfg: dict, prefix: str = "") -> List[str]:
    missing = []
    for k, v in cfg.items():
        path = f"{prefix}{k}"
        if isinstance(v, dict):
            missing += _check_missing(v, path + ".")
        elif isinstance(v, str) and v == MISSING:
            missing.append(path)
    return missing


class Composer:
    def __init__(self, source: ConfigSource, group_overrides: Optional[Dict[str, str]] = None):
        self.source = source
        # CLI group selections beat every defaults-list entry, wherever it appears.
        self.group_overrides = dict(group_overrides or {})
        # ``override group: option`` entries from enclosing files, in effect while
        # their siblings (e.g. an inherited parent exp) are being processed.
        self.scoped_overrides: Dict[str, Any] = {}
        self.applied_groups: set = set()
        # Groups declared mandatory (``???``) somewhere in the tree: a later
        # ``override group:`` entry is the legitimate way to satisfy them.
        self.mandatory_groups: set = set()
        # group -> option actually loaded; a group is re-loaded only when the
        # effective option differs (re-merging the same option after an exp's
        # content would clobber the exp's value overrides with group defaults).
        self.applied_options: Dict[str, str] = {}

    def _effective_option(self, group: str, option: Any) -> Any:
        if group in self.group_overrides:  # CLI wins over everything
            return self.group_overrides[group]
        return self.scoped_overrides.get(group, option)

    def process_defaults(self, cfg: dict, defaults: List[Any], parent_group: str = "") -> None:
        """Apply a ``defaults`` list with Hydra's ``override`` semantics: an
        ``override group: option`` entry re-selects which option the group loads
        *wherever* it is loaded (typically by an inherited parent exp) — it does NOT
        re-merge the group file after the parent's content, which would clobber the
        parent's value overrides with the group file's defaults."""
        overrides_here: List[tuple] = []
        plain: List[Any] = []
        for entry in defaults:
            # Classify per key: only keys of the form "override <group>" /
            # "override/<group>" are overrides.  A mixed dict entry keeps its plain
            # keys as plain selections, and a group whose name merely begins with
            # "override" (no separator) is a plain group, never truncated.
            if isinstance(entry, dict):
                plain_part: Dict[Any, Any] = {}
                for group, option in entry.items():
                    g = str(group)
                    if g.startswith("override ") or g.startswith("override/"):
                        overrides_here.append((g[len("override") :].strip().lstrip("/"), option))
                    else:
                        plain_part[group] = option
                if plain_part:
                    plain.append(plain_part)
            else:
                plain.append(entry)
        pushed = []
        for group, option in overrides_here:
            # An enclosing (child) config's override beats this one, CLI beats both.
            if group not in self.scoped_overrides:
                self.scoped_overrides[group] = option
                pushed.append(group)
        try:
            for entry in plain:
                self._apply_default(cfg, entry, parent_group=parent_group)
            # Override entries whose effective option no sibling loaded (directly or
            # via this scope's redirection): if the group exists anywhere in the
            # defaults tree processed so far (loaded earlier, e.g. by the root
            # config, or recorded as a mandatory ``???`` group), re-select it here.
            # A group that exists NOWHERE is an error, matching Hydra ("could not
            # find match for override") — catches typos like ``override /enviro:``.
            for group, option in overrides_here:
                if group not in self.applied_groups and group not in self.mandatory_groups:
                    raise ValueError(
                        f"Defaults-list override 'override /{group}: {option}' matches no "
                        f"'{group}' entry in the defaults tree. Overrides re-select an "
                        f"existing entry; use a plain '{group}: {option}' entry to add one."
                    )
                self._select_and_load(cfg, group, option)
        finally:
            for group in pushed:
                self.scoped_overrides.pop(group, None)

    def load_group_file(self, cfg: dict, group: str, option: str) -> None:
        rel = f"{group}/{option}" if group else option
        path = self.source.find(rel)
        if path is None:
            opts = self.source.options(group)
            raise FileNotFoundError(
                f"Config '{rel}.yaml' not found in search path "
                f"{[str(d) for d in self.source.dirs]}. Available options for "
                f"'{group}': {opts}"
            )
        raw = _yaml_load(path.read_text()) or {}
        defaults = raw.pop("defaults", [])
        is_global = bool(raw.pop("_global_", False)) or group == "exp"
        # Process nested defaults first so the file's own content wins.
        self.process_defaults(cfg, defaults, parent_group=group)
        if is_global:
            _merge(cfg, raw)
        else:
            key = group.split("/")[-1]
            node = cfg.setdefault(key, {})
            if not isinstance(node, dict):
                cfg[key] = {}
                node = cfg[key]
            _merge(node, raw)

    def _apply_default(self, cfg: dict, entry: Any, parent_group: str = "") -> None:
        if entry == "_self_":
            return
        if isinstance(entry, str):
            # "group/option" or bare "option" relative to the parent group.  Bare
            # within-group inheritance (e.g. algo/dreamer_v3_S ← dreamer_v3) is NOT
            # subject to scoped overrides — redirecting it would self-recurse.
            if "/" in entry:
                group, option = entry.rsplit("/", 1)
            else:
                group, option = parent_group, entry
            self.load_group_file(cfg, group, option)
            return
        if isinstance(entry, dict):
            for group, option in entry.items():
                self._select_and_load(cfg, str(group).strip().lstrip("/"), option)
            return
        raise ValueError(f"Unsupported defaults entry: {entry!r}")

    def _select_and_load(self, cfg: dict, group: str, option: Any) -> None:
        """Resolve a group selection (CLI > enclosing overrides > the entry itself)
        and load it, unless that exact option was already loaded or the selection is
        null/mandatory."""
        option = self._effective_option(group, option)
        if option is None or option == "null":
            return
        if str(option).startswith("???"):
            # Mandatory group: must be chosen by an override; record it.
            self.mandatory_groups.add(group)
            cfg.setdefault("_mandatory_groups_", []).append(group)
            return
        if self.applied_options.get(group) == str(option):
            return
        self.applied_groups.add(group)
        self.applied_options[group] = str(option)
        self.load_group_file(cfg, group, str(option))


def compose(
    config_name: str = "config",
    overrides: Optional[Sequence[str]] = None,
    extra_dirs: Optional[Sequence[os.PathLike]] = None,
    resolve: bool = True,
) -> DotDict:
    """Compose the configuration tree, mirroring the reference Hydra entry point.

    ``overrides`` are CLI-style tokens: ``exp=dreamer_v3``, ``env=atari``,
    ``algo.learning_rate=1e-4``, ``+extra.key=1`` (force-add), ``~key`` (delete).
    """
    overrides = list(overrides or [])
    source = ConfigSource(extra_dirs)
    cfg: dict = {}

    root_path = source.find(config_name)
    if root_path is None:
        raise FileNotFoundError(f"root config '{config_name}.yaml' not found")
    raw = _yaml_load(root_path.read_text()) or {}
    defaults = raw.pop("defaults", [])

    # Partition overrides: group selections vs dotted value assignments.
    group_overrides: Dict[str, str] = {}
    value_overrides: List[tuple] = []
    deletions: List[str] = []
    for ov in overrides:
        if ov.startswith("~"):
            deletions.append(ov[1:])
            continue
        if "=" not in ov:
            raise ValueError(f"Malformed override {ov!r} (expected key=value)")
        key, _, val = ov.partition("=")
        key = key.lstrip("+")
        if "." not in key and any((d / key).is_dir() for d in source.dirs):
            # The key names a config group: the value must be an existing option.
            if source.find(f"{key}/{val}") is None:
                raise FileNotFoundError(
                    f"Config group '{key}' has no option '{val}'. Available: {source.options(key)}"
                )
            group_overrides[key] = val
        else:
            value_overrides.append((key, _parse_value(val)))

    # Apply defaults; CLI group selections substitute in wherever the group appears
    # (root defaults or nested exp defaults).
    composer = Composer(source, group_overrides)
    if "_self_" in defaults:
        self_pos = defaults.index("_self_")
        composer.process_defaults(cfg, defaults[:self_pos])
        _merge(cfg, raw)
        composer.process_defaults(cfg, defaults[self_pos + 1 :])
    else:
        composer.process_defaults(cfg, defaults)
        _merge(cfg, raw)

    # Group overrides never consumed by any defaults list (e.g. exp=...).
    for group, option in group_overrides.items():
        if group not in composer.applied_groups:
            composer.load_group_file(cfg, group, option)

    # A mandatory group is satisfied when its key exists in the composed config
    # (whether via an explicit override or an exp file's defaults).
    mandatory = set(cfg.pop("_mandatory_groups_", []))  # jaxlint: disable=JL006 (internal sentinel)
    still_missing = {g for g in mandatory if g.split("/")[-1] not in cfg}
    if still_missing:
        raise ValueError(
            f"Mandatory config groups not chosen: {sorted(still_missing)}. "
            f"Select them with e.g. '{next(iter(still_missing))}=<option>' or an 'exp=' preset."
        )

    for key, val in value_overrides:
        _set_dotted(cfg, key, val)
    for key in deletions:
        try:
            parent = _get_dotted(cfg, key.rsplit(".", 1)[0]) if "." in key else cfg
            parent.pop(key.rsplit(".", 1)[-1], None)
        except KeyError:
            pass

    if resolve:
        _resolve_interpolations(cfg)
        missing = _check_missing(cfg)
        if missing:
            raise ValueError(f"Missing mandatory config values: {missing}")
    return DotDict.wrap(cfg)


def save_config(cfg: dict, path: os.PathLike) -> None:
    Path(path).parent.mkdir(parents=True, exist_ok=True)
    with open(path, "w") as f:
        yaml.safe_dump(unwrap(cfg), f, sort_keys=False)


def load_config(path: os.PathLike) -> DotDict:
    with open(path) as f:
        return DotDict.wrap(yaml.load(f, Loader=_YamlLoader) or {})


def print_config(cfg: dict, file=None) -> None:
    """Pretty-print the composed config (reference: utils/utils.py:208)."""
    print(yaml.safe_dump(unwrap(cfg), sort_keys=False), file=file)
