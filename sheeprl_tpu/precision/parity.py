"""Parity metrics between two policies at different precisions.

Used by the parity tests AND by the serve startup parity stamp
(``serve.precision != f32`` loads an f32 reference and records agreement).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


def action_agreement(
    actions_a: Any,
    actions_b: Any,
    continuous: bool = False,
    atol: float = 1e-2,
) -> float:
    """Fraction of rows on which two policies pick the same greedy action.

    Discrete actions must match exactly; continuous actions agree when every
    component is within ``atol``. Inputs are ``[batch, ...]`` arrays (or lists
    thereof for multi-discrete — compared per-component then ANDed).
    """
    if isinstance(actions_a, (list, tuple)):
        per = [
            np.asarray(action_agreement_mask(a, b, continuous=continuous, atol=atol))
            for a, b in zip(actions_a, actions_b)
        ]
        mask = np.logical_and.reduce(per)
        return float(mask.mean())
    mask = action_agreement_mask(actions_a, actions_b, continuous=continuous, atol=atol)
    return float(np.asarray(mask).mean())


def action_agreement_mask(
    actions_a: jax.Array,
    actions_b: jax.Array,
    continuous: bool = False,
    atol: float = 1e-2,
) -> np.ndarray:
    """Boolean per-row agreement mask (see :func:`action_agreement`)."""
    a = np.asarray(jax.device_get(actions_a))
    b = np.asarray(jax.device_get(actions_b))
    if continuous:
        close = np.abs(a.astype(np.float64) - b.astype(np.float64)) <= atol
        return close.reshape(close.shape[0], -1).all(axis=-1)
    return (a.reshape(a.shape[0], -1) == b.reshape(b.shape[0], -1)).all(axis=-1)


def categorical_kl(logits_p: jax.Array, logits_q: jax.Array) -> float:
    """Mean KL(p || q) between two batches of categorical logits, in nats."""
    p32 = jnp.asarray(logits_p, dtype=jnp.float32)
    q32 = jnp.asarray(logits_q, dtype=jnp.float32)
    logp = jax.nn.log_softmax(p32, axis=-1)
    logq = jax.nn.log_softmax(q32, axis=-1)
    kl = jnp.sum(jnp.exp(logp) * (logp - logq), axis=-1)
    return float(jnp.mean(kl))


def gaussian_mean_divergence(
    mean_p: jax.Array, mean_q: jax.Array, log_std_p: Optional[jax.Array] = None
) -> float:
    """Mean absolute divergence of continuous policy means, normalised by the
    reference std when available (a cheap stand-in for KL on tanh-squashed
    policies whose exact KL has no closed form)."""
    d = jnp.abs(jnp.asarray(mean_p, jnp.float32) - jnp.asarray(mean_q, jnp.float32))
    if log_std_p is not None:
        d = d / jnp.maximum(jnp.exp(jnp.asarray(log_std_p, jnp.float32)), 1e-6)
    return float(jnp.mean(d))
