"""Loss scaling for fp16 training (jmp-style).

bf16 shares f32's exponent range and needs none of this; the classes exist as
a library so an fp16 tier can be wired without redesign. All three are
pytree-registered so a scale can live inside a jitted train carry.

Usage pattern (inside a jitted step)::

    scaled_loss = scale.scale(loss_fn(params))
    grads = jax.grad(...)(params)          # grads of the SCALED loss
    grads = scale.unscale(grads)
    finite = all_finite(grads)
    scale = scale.adjust(finite)
    params = lax.cond(finite, apply_update, keep_params, ...)
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.tree_util import register_pytree_node_class


def all_finite(tree: Any) -> jax.Array:
    """True iff every float leaf of ``tree`` is finite everywhere."""
    leaves = [x for x in jax.tree.leaves(tree) if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)]
    if not leaves:
        return jnp.asarray(True)
    finite = [jnp.all(jnp.isfinite(x)) for x in leaves]
    return jnp.stack(finite).all()


@register_pytree_node_class
class NoOpLossScale:
    """Identity scaling — the policy for f32 and bf16 training."""

    def scale(self, loss: jax.Array) -> jax.Array:
        return loss

    def unscale(self, tree: Any) -> Any:
        return tree

    def adjust(self, grads_finite: jax.Array) -> "NoOpLossScale":
        del grads_finite
        return self

    def tree_flatten(self):
        return (), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        del aux, children
        return cls()


@register_pytree_node_class
class StaticLossScale:
    """Fixed multiplicative loss scale."""

    def __init__(self, scale: Any):
        self.loss_scale = jnp.asarray(scale, dtype=jnp.float32)

    def scale(self, loss: jax.Array) -> jax.Array:
        return loss * self.loss_scale.astype(loss.dtype)

    def unscale(self, tree: Any) -> Any:
        inv = (1.0 / self.loss_scale).astype(jnp.float32)
        return jax.tree.map(lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype), tree)

    def adjust(self, grads_finite: jax.Array) -> "StaticLossScale":
        del grads_finite
        return self

    def tree_flatten(self):
        return (self.loss_scale,), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        del aux
        (scale,) = children
        obj = cls.__new__(cls)
        obj.loss_scale = scale
        return obj


@register_pytree_node_class
class DynamicLossScale:
    """Doubling/halving loss scale (jmp semantics).

    On finite grads: after ``period`` consecutive finite steps the scale
    doubles. On non-finite grads: the scale halves (floored at ``min_scale``)
    and the counter resets. The caller is responsible for SKIPPING the update
    when grads are not finite.
    """

    def __init__(self, scale: Any = 2.0**15, counter: Any = 0, period: int = 2000, factor: int = 2, min_scale: float = 1.0):
        self.loss_scale = jnp.asarray(scale, dtype=jnp.float32)
        self.counter = jnp.asarray(counter, dtype=jnp.int32)
        self.period = int(period)
        self.factor = int(factor)
        self.min_scale = float(min_scale)

    def scale(self, loss: jax.Array) -> jax.Array:
        return loss * self.loss_scale.astype(loss.dtype)

    def unscale(self, tree: Any) -> Any:
        inv = (1.0 / self.loss_scale).astype(jnp.float32)
        return jax.tree.map(lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype), tree)

    def adjust(self, grads_finite: jax.Array) -> "DynamicLossScale":
        grow = self.counter == (self.period - 1)
        new_scale = jnp.where(
            grads_finite,
            jnp.where(grow, self.loss_scale * self.factor, self.loss_scale),
            jnp.maximum(self.loss_scale / self.factor, self.min_scale),
        )
        new_counter = jnp.where(grads_finite, jnp.where(grow, 0, self.counter + 1), 0).astype(jnp.int32)
        return DynamicLossScale(
            scale=new_scale, counter=new_counter, period=self.period, factor=self.factor, min_scale=self.min_scale
        )

    def tree_flatten(self):
        return (self.loss_scale, self.counter), (self.period, self.factor, self.min_scale)

    @classmethod
    def tree_unflatten(cls, aux, children):
        period, factor, min_scale = aux
        scale, counter = children
        obj = cls.__new__(cls)
        obj.loss_scale = scale
        obj.counter = counter
        obj.period = period
        obj.factor = factor
        obj.min_scale = min_scale
        return obj
