"""Precision tier: mixed-precision training policies and post-training quantization.

Three layers, consumed by the rest of the framework:

* :mod:`sheeprl_tpu.precision.policy` — jmp-style param/compute/output dtype
  triples (``PrecisionPolicy``) resolved from ``algo.precision`` (train path)
  with mesh inheritance, plus the boundary-cast helpers;
* :mod:`sheeprl_tpu.precision.loss_scale` — NoOp/Static/Dynamic loss scaling
  for fp16 (bf16 needs none: same exponent range as f32);
* :mod:`sheeprl_tpu.precision.quantize` — int8 weight-only quantization with
  per-output-channel scales (``Int8Weight`` pytree leaves, dequant-in-matmul)
  for the serving hot path (``serve.precision=int8``);
* :mod:`sheeprl_tpu.precision.parity` — the agreement/KL metrics the parity
  tests and the serve parity stamp are built on.
"""

from sheeprl_tpu.precision.loss_scale import (
    DynamicLossScale,
    NoOpLossScale,
    StaticLossScale,
    all_finite,
)
from sheeprl_tpu.precision.parity import (
    action_agreement,
    action_agreement_mask,
    categorical_kl,
    gaussian_mean_divergence,
)
from sheeprl_tpu.precision.policy import PrecisionPolicy, resolve_policy, train_policy
from sheeprl_tpu.precision.quantize import (
    Int8Weight,
    dequantize_params,
    quantize_params,
    quantize_weight,
)

__all__ = [
    "PrecisionPolicy",
    "resolve_policy",
    "train_policy",
    "NoOpLossScale",
    "StaticLossScale",
    "DynamicLossScale",
    "all_finite",
    "Int8Weight",
    "quantize_weight",
    "quantize_params",
    "dequantize_params",
    "action_agreement",
    "action_agreement_mask",
    "categorical_kl",
    "gaussian_mean_divergence",
]
