"""Post-training int8 weight quantization for the serving hot path.

Weight-only, per-output-channel symmetric quantization: each 2-D kernel
``w[in, out]`` becomes an :class:`Int8Weight` pytree leaf holding ``q`` (int8)
and a ``[1, out]`` f32 ``scale`` where ``q = round(w / scale)`` and
``scale = max(|w|, axis=in) / 127``. Dequantization (``q.astype(f32) * scale``)
happens INSIDE the jitted act fn — XLA fuses the convert+multiply into the
consuming dot, so HBM holds int8 weights (4× smaller than f32) while the MXU
still sees its native dtype. Biases, LayerNorm scales and every non-2-D leaf
stay in their original dtype: they are a rounding error of the working set
and quantizing them costs accuracy for nothing.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.tree_util import register_pytree_node_class

# Smallest representable scale: avoids div-by-zero on all-zero channels.
_MIN_SCALE = 1e-8


@register_pytree_node_class
class Int8Weight:
    """An int8 kernel + per-output-channel f32 scale, as one pytree leaf pair."""

    def __init__(self, q: jax.Array, scale: jax.Array):
        self.q = q
        self.scale = scale

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.q.dtype

    def dequantize(self, dtype: Any = jnp.float32) -> jax.Array:
        return self.q.astype(dtype) * self.scale.astype(dtype)

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        del aux
        q, scale = children
        return cls(q, scale)

    def __repr__(self) -> str:
        return f"Int8Weight(shape={tuple(self.q.shape)})"


def quantize_weight(w: jax.Array) -> Int8Weight:
    """Quantize one ``[in, out]`` float kernel to int8 with per-out-channel scales."""
    w32 = jnp.asarray(w, dtype=jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(w32), axis=0, keepdims=True) / 127.0, _MIN_SCALE)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return Int8Weight(q=q, scale=scale)


def quantize_params(params: Any) -> Any:
    """Replace every 2-D float leaf (Dense kernels) with an :class:`Int8Weight`."""

    def leaf(x):
        if hasattr(x, "ndim") and x.ndim == 2 and jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
            return quantize_weight(x)
        return x

    return jax.tree.map(leaf, params)


def dequantize_params(params: Any, dtype: Any = jnp.float32) -> Any:
    """Expand :class:`Int8Weight` leaves back to float — call INSIDE jit so XLA
    fuses the dequant into the consuming matmul."""

    def leaf(x):
        if isinstance(x, Int8Weight):
            return x.dequantize(dtype)
        return x

    return jax.tree.map(leaf, params, is_leaf=lambda x: isinstance(x, Int8Weight))
