"""Mixed-precision policies: param/compute/output dtype triples à la jmp.

The contract (documented in ``howto/precision.md``):

* **params** stay in ``param_dtype`` (f32 for every mixed policy) — flax
  modules built with ``dtype=compute_dtype`` but default ``param_dtype``
  already do this, so optimizer state stays f32 too;
* **compute** (matmuls, activations) runs in ``compute_dtype`` — flax's
  ``promote_dtype`` casts inputs and kernel to ``dtype`` inside each layer,
  and the train-fn builders additionally cast float observation batches at
  the loss boundary so the first matmul's operands are already low-precision;
* **outputs** (logits, values, losses, anything reduced) are cast back to
  ``output_dtype`` (f32) — the agent heads do this with ``.astype``.

``train_policy(cfg, ctx)`` is the single resolution point for the train
path: ``algo.precision`` defaults to ``"mesh"`` (inherit ``mesh.precision``,
the pre-existing behavior), or forces ``"f32"``/``"bf16"`` per-run.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """A (param, compute, output) dtype triple with boundary-cast helpers.

    The cast helpers touch only floating-point leaves — integer/bool leaves
    (discrete actions, done flags, ring cursors) pass through untouched.
    """

    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.float32
    output_dtype: Any = jnp.float32

    def _cast(self, tree: Any, dtype: Any) -> Any:
        def leaf(x):
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
                return x.astype(dtype)
            return x

        return jax.tree.map(leaf, tree)

    def cast_to_compute(self, tree: Any) -> Any:
        return self._cast(tree, self.compute_dtype)

    def cast_to_param(self, tree: Any) -> Any:
        return self._cast(tree, self.param_dtype)

    def cast_to_output(self, tree: Any) -> Any:
        return self._cast(tree, self.output_dtype)

    @property
    def is_mixed(self) -> bool:
        return self.compute_dtype != self.param_dtype

    def describe(self) -> str:
        return (
            f"params={jnp.dtype(self.param_dtype).name} "
            f"compute={jnp.dtype(self.compute_dtype).name} "
            f"output={jnp.dtype(self.output_dtype).name}"
        )


_POLICIES = {
    # full precision
    "f32": (jnp.float32, jnp.float32, jnp.float32),
    "fp32": (jnp.float32, jnp.float32, jnp.float32),
    "float32": (jnp.float32, jnp.float32, jnp.float32),
    "32-true": (jnp.float32, jnp.float32, jnp.float32),
    # bf16 mixed: f32 params/optimizer state, bf16 compute, f32 outputs
    "bf16": (jnp.float32, jnp.bfloat16, jnp.float32),
    "bf16-mixed": (jnp.float32, jnp.bfloat16, jnp.float32),
    # bf16 true: everything bf16 (params included)
    "bf16-true": (jnp.bfloat16, jnp.bfloat16, jnp.bfloat16),
    # fp16 mixed: needs loss scaling (see train_policy's guard)
    "fp16": (jnp.float32, jnp.float16, jnp.float32),
    "16-mixed": (jnp.float32, jnp.float16, jnp.float32),
}


def resolve_policy(spec: str) -> PrecisionPolicy:
    """Map a precision string (``algo.precision`` / ``mesh.precision``) to a policy."""
    key = str(spec).lower()
    if key not in _POLICIES:
        raise ValueError(
            f"Unknown precision spec {spec!r}; expected one of {sorted(_POLICIES)}"
        )
    param, compute, output = _POLICIES[key]
    return PrecisionPolicy(param_dtype=param, compute_dtype=compute, output_dtype=output)


def train_policy(cfg: Any, ctx: Optional[Any] = None) -> PrecisionPolicy:
    """Resolve the training-path precision policy from ``cfg.algo.precision``.

    ``"mesh"`` (the default) inherits ``mesh.precision`` — via ``ctx.precision``
    when a MeshContext is at hand (it may have been overridden at construction),
    else from the config tree — preserving the pre-existing behavior where the
    mesh knob alone picked the compute dtype. An EXPLICIT ``algo.precision=fp16``
    is rejected: fp16's narrow exponent range requires threading a
    ``DynamicLossScale`` state through every donated carry (breaking checkpoint
    layouts and the Anakin dispatch signature), and TPUs want bf16 anyway.
    Mesh-inherited fp16 passes through for legacy configs.
    """
    algo = cfg.get("algo") if hasattr(cfg, "get") else None
    spec = "mesh"
    if algo is not None:
        spec = str(algo.get("precision", "mesh") or "mesh")
    if spec.lower() == "mesh":
        if ctx is not None:
            mesh_spec = str(ctx.precision)
        else:
            mesh_spec = str((cfg.get("mesh") or {}).get("precision", "fp32"))
        return resolve_policy(mesh_spec)
    policy = resolve_policy(spec)
    if policy.compute_dtype == jnp.float16:
        raise ValueError(
            "algo.precision=fp16 is not supported: fp16 training requires dynamic "
            "loss scaling state in every train carry (sheeprl_tpu.precision."
            "loss_scale.DynamicLossScale is available as a library), which would "
            "change checkpoint layouts. Use algo.precision=bf16 on TPU instead."
        )
    return policy
