"""Checkpoint save/restore.

Reference behavior (``sheeprl/utils/callback.py:14-148`` + ``cli.py:23-58``): periodic
checkpoints of model/optimizer/aux state plus optional replay-buffer state, ``keep_last``
GC, and config-compatibility rules on resume.

TPU-native design: device pytrees (params, optimizer states, moments) are serialised
with ``flax.serialization`` to msgpack; host-side python state (Ratio, counters, buffer
state dicts) is pickled alongside.  Everything lands in one directory per checkpoint so
GC is an rmtree.
"""

from __future__ import annotations

import os
import pickle
import shutil
from pathlib import Path
from typing import Any, Dict, List, Optional

import jax
import numpy as np
from flax import serialization

PROTECTED_RESUME_KEYS = ("env", "algo", "buffer", "checkpoint", "distribution", "exp_name", "seed")


def _is_device_tree(value: Any) -> bool:
    # Leaves must be actual arrays, not merely dtype-carrying objects: gymnasium
    # spaces expose .dtype too, and a statics dict of spaces (flight-recorder
    # dumps) must take the pickle path, not msgpack.
    leaves = jax.tree.leaves(value)
    return len(leaves) > 0 and all(isinstance(leaf, (np.ndarray, np.generic, jax.Array)) for leaf in leaves)


class CheckpointManager:
    def __init__(self, ckpt_dir: os.PathLike, keep_last: Optional[int] = 5):
        self.ckpt_dir = Path(ckpt_dir)
        self.keep_last = keep_last

    # Host-local state saved by EVERY process under a rank suffix.  The reference
    # gathers per-rank replay buffers to rank-0 over gloo (callback.py:42-51); on TPU
    # pods the shared filesystem IS the gather — each host writes its own shard and
    # reads it back on resume, with zero DCN traffic.
    PER_RANK_KEYS = ("rb",)

    @staticmethod
    def _barrier(name: str) -> None:
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(name)

    def save(self, step: int, state: Dict[str, Any], sync: bool = True) -> Path:
        """``state`` maps names to either device pytrees or picklable host objects.
        Entries named in ``PER_RANK_KEYS`` are written by every process
        (``<name>.rank<k>.pkl``); everything else by process 0 only.

        Multi-host protocol: rank 0 builds the directory and atomically renames it
        into place, a global barrier publishes it, THEN the other ranks drop their
        shards in — no writer ever races the rename.

        ``sync=False`` is the crash-dump mode (``obs/flight_recorder.py``): no
        barriers, rank 0 writes everything it has and non-zero ranks write nothing —
        a post-mortem dump must never wait on peer processes that may already be
        dead."""
        out = self.ckpt_dir / f"ckpt_{step}"
        rank = jax.process_index()
        if rank != 0 and not sync:
            return out
        if rank != 0:
            per_rank = {k: v for k, v in state.items() if k in self.PER_RANK_KEYS}
            self._barrier(f"ckpt_{step}_published")  # rank 0 has renamed tmp -> out
            for name, value in per_rank.items():
                with open(out / f"{name}.rank{rank}.pkl", "wb") as f:
                    pickle.dump(value, f)
            self._barrier(f"ckpt_{step}_shards")
            return out
        tmp = self.ckpt_dir / f".tmp_ckpt_{step}"
        if tmp.exists():
            shutil.rmtree(tmp)
        tmp.mkdir(parents=True)
        manifest: Dict[str, str] = {}
        for name, value in state.items():
            if name in self.PER_RANK_KEYS:
                with open(tmp / f"{name}.rank0.pkl", "wb") as f:
                    pickle.dump(value, f)
                manifest[name] = "per_rank"
            elif _is_device_tree(value):
                host_value = jax.device_get(value)
                (tmp / f"{name}.msgpack").write_bytes(serialization.to_bytes(host_value))
                manifest[name] = "msgpack"
                # Template for structure restoration.
                with open(tmp / f"{name}.template.pkl", "wb") as f:
                    pickle.dump(jax.tree.map(lambda x: None, host_value), f)
            else:
                with open(tmp / f"{name}.pkl", "wb") as f:
                    pickle.dump(value, f)
                manifest[name] = "pickle"
        with open(tmp / "manifest.pkl", "wb") as f:
            pickle.dump({"step": step, "entries": manifest}, f)
        if out.exists():
            shutil.rmtree(out)
        tmp.rename(out)
        if sync:
            self._barrier(f"ckpt_{step}_published")
            self._barrier(f"ckpt_{step}_shards")  # all ranks' shards are on disk
        self._gc()
        return out

    def _gc(self) -> None:
        if not self.keep_last:
            return
        ckpts = self.list_checkpoints()
        for old in ckpts[: -self.keep_last]:
            shutil.rmtree(old, ignore_errors=True)

    def list_checkpoints(self) -> List[Path]:
        if not self.ckpt_dir.exists():
            return []
        ckpts = [p for p in self.ckpt_dir.iterdir() if p.is_dir() and p.name.startswith("ckpt_")]
        return sorted(ckpts, key=lambda p: int(p.name.split("_")[1]))

    @staticmethod
    def load(ckpt_path: os.PathLike, templates: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Load a checkpoint directory. ``templates`` provides target pytrees for
        msgpack entries (required to restore dtypes/shapes as jax arrays)."""
        ckpt_path = Path(ckpt_path)
        with open(ckpt_path / "manifest.pkl", "rb") as f:
            manifest = pickle.load(f)
        state: Dict[str, Any] = {"_step": manifest["step"]}
        for name, kind in manifest["entries"].items():
            if kind == "msgpack":
                raw = (ckpt_path / f"{name}.msgpack").read_bytes()
                if templates and name in templates:
                    state[name] = serialization.from_bytes(templates[name], raw)
                else:
                    state[name] = serialization.msgpack_restore(raw)
            elif kind == "per_rank":
                # Each process restores its own shard; fall back to rank 0's when the
                # world size changed between save and resume.
                shard = ckpt_path / f"{name}.rank{jax.process_index()}.pkl"
                if not shard.is_file():
                    shard = ckpt_path / f"{name}.rank0.pkl"
                with open(shard, "rb") as f:
                    state[name] = pickle.load(f)
            else:
                with open(ckpt_path / f"{name}.pkl", "rb") as f:
                    state[name] = pickle.load(f)
        return state


def validate_resume_config(old_cfg: Dict[str, Any], new_cfg: Dict[str, Any]) -> Dict[str, Any]:
    """Merge a checkpoint's config into the current one, protecting the keys the
    reference refuses to change on resume (``cli.py:48-52``)."""
    merged = dict(new_cfg)
    for key in PROTECTED_RESUME_KEYS:
        if key in old_cfg:
            merged[key] = old_cfg[key]
    return merged
