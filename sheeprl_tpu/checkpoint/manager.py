"""Checkpoint save/restore with end-to-end integrity.

Reference behavior (``sheeprl/utils/callback.py:14-148`` + ``cli.py:23-58``): periodic
checkpoints of model/optimizer/aux state plus optional replay-buffer state, ``keep_last``
GC, and config-compatibility rules on resume.

TPU-native design: device pytrees (params, optimizer states, moments) are serialised
with ``flax.serialization`` to msgpack; host-side python state (Ratio, counters, buffer
state dicts) is pickled alongside.  Everything lands in one directory per checkpoint so
GC is an rmtree.

Integrity model (``howto/fault_tolerance.md``): a checkpoint a resume decision rests on
must be *provably* intact —

* every file rank 0 writes is fsynced and sha256-summed into ``manifest.pkl``
  (``format: 2``); the tmp directory and its parent are fsynced around the publish
  rename, so a checkpoint either exists completely or not at all, even across a
  power cut (rename-then-crash cannot leave a half-written published dir);
* per-rank shards (written after the publish barrier by the other ranks) carry
  ``.sha256`` sidecars instead — they cannot be in rank 0's manifest;
* ``load()`` verifies checksums before deserializing and, on any damage, *falls back*
  to the newest earlier checkpoint that verifies (``Fault/checkpoint_fallbacks``
  counts the events) instead of crashing the resume on garbage bytes;
* manager init sweeps orphaned ``.tmp_ckpt_*`` dirs left by a killed writer;
* multi-host barriers time out (``SHEEPRL_TPU_BARRIER_TIMEOUT_S``) with an actionable
  error instead of hanging forever on a dead peer.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import shutil
import warnings
from pathlib import Path
from typing import Any, Dict, List, Optional

import jax
import numpy as np
from flax import serialization

PROTECTED_RESUME_KEYS = ("env", "algo", "buffer", "checkpoint", "distribution", "exp_name", "seed")

#: Manifest format written by this version: 2 = per-file sha256 checksums.
MANIFEST_FORMAT = 2


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed verification: missing/truncated/bit-flipped files or an
    unreadable manifest.  ``load(..., fallback=True)`` catches this internally and
    falls back to the newest earlier valid checkpoint; it escapes only when no
    valid checkpoint remains."""


def _is_device_tree(value: Any) -> bool:
    # Leaves must be actual arrays, not merely dtype-carrying objects: gymnasium
    # spaces expose .dtype too, and a statics dict of spaces (flight-recorder
    # dumps) must take the pickle path, not msgpack.
    leaves = jax.tree.leaves(value)
    return len(leaves) > 0 and all(isinstance(leaf, (np.ndarray, np.generic, jax.Array)) for leaf in leaves)


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _fsync_write(path: Path, data: bytes) -> str:
    """Write ``data`` durably (flush + fsync) and return its sha256 hex digest."""
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    return _sha256(data)


def _fsync_dir(path: Path) -> None:
    """fsync a directory so the entries (and the publish rename) hit the journal."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # platforms without directory fds: best effort
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _write_rank_shard(path: Path, value: Any) -> None:
    """Per-rank shard + ``.sha256`` sidecar (these files post-date rank 0's manifest)."""
    digest = _fsync_write(path, pickle.dumps(value))
    _fsync_write(Path(str(path) + ".sha256"), digest.encode())


class CheckpointManager:
    def __init__(self, ckpt_dir: os.PathLike, keep_last: Optional[int] = 5):
        self.ckpt_dir = Path(ckpt_dir)
        self.keep_last = keep_last
        self._sweep_orphan_tmp()

    # Host-local state saved by EVERY process under a rank suffix.  The reference
    # gathers per-rank replay buffers to rank-0 over gloo (callback.py:42-51); on TPU
    # pods the shared filesystem IS the gather — each host writes its own shard and
    # reads it back on resume, with zero DCN traffic.
    PER_RANK_KEYS = ("rb",)

    def _sweep_orphan_tmp(self) -> None:
        """Remove ``.tmp_ckpt_*`` dirs orphaned by a previous killed writer.

        Safe by construction: a tmp dir is invisible to resume (only the publish
        rename makes a checkpoint real), so anything still named ``.tmp_ckpt_*``
        when a manager starts is garbage from a crashed process.  Only rank 0
        sweeps — it is the only rank that ever writes tmp dirs."""
        if not self.ckpt_dir.exists():
            return
        try:
            if jax.process_index() != 0:
                return
        except Exception:
            pass  # no backend yet: single-process by definition
        orphans = [p for p in self.ckpt_dir.iterdir() if p.is_dir() and p.name.startswith(".tmp_ckpt_")]
        for orphan in orphans:
            shutil.rmtree(orphan, ignore_errors=True)
        if orphans:
            from sheeprl_tpu.fault import counters as _fault_counters
            from sheeprl_tpu.obs import flight_recorder

            _fault_counters.bump("Fault/orphan_tmp_swept", len(orphans))
            flight_recorder.record_event(
                "orphan_tmp_swept", dir=str(self.ckpt_dir), count=len(orphans)
            )
            warnings.warn(
                f"swept {len(orphans)} orphaned .tmp_ckpt_* dir(s) in {self.ckpt_dir} "
                "(leftovers of a checkpoint writer that died mid-save)"
            )

    @staticmethod
    def _barrier(name: str) -> None:
        if jax.process_count() > 1:
            from sheeprl_tpu.parallel.mesh import sync_global_devices_with_timeout

            sync_global_devices_with_timeout(name)

    def save(self, step: int, state: Dict[str, Any], sync: bool = True) -> Path:
        """``state`` maps names to either device pytrees or picklable host objects.
        Entries named in ``PER_RANK_KEYS`` are written by every process
        (``<name>.rank<k>.pkl``); everything else by process 0 only.

        Multi-host protocol: rank 0 builds the directory and atomically renames it
        into place, a global barrier publishes it, THEN the other ranks drop their
        shards in — no writer ever races the rename.

        ``sync=False`` is the crash-dump mode (``obs/flight_recorder.py``): no
        barriers, rank 0 writes everything it has and non-zero ranks write nothing —
        a post-mortem dump must never wait on peer processes that may already be
        dead."""
        out = self.ckpt_dir / f"ckpt_{step}"
        rank = jax.process_index()
        if rank != 0 and not sync:
            return out
        if rank != 0:
            per_rank = {k: v for k, v in state.items() if k in self.PER_RANK_KEYS}
            self._barrier(f"ckpt_{step}_published")  # rank 0 has renamed tmp -> out
            for name, value in per_rank.items():
                _write_rank_shard(out / f"{name}.rank{rank}.pkl", value)
            self._barrier(f"ckpt_{step}_shards")
            return out
        tmp = self.ckpt_dir / f".tmp_ckpt_{step}"
        if tmp.exists():
            shutil.rmtree(tmp)
        tmp.mkdir(parents=True)
        manifest: Dict[str, str] = {}
        checksums: Dict[str, str] = {}
        for name, value in state.items():
            if name in self.PER_RANK_KEYS:
                _write_rank_shard(tmp / f"{name}.rank0.pkl", value)
                manifest[name] = "per_rank"
            elif _is_device_tree(value):
                host_value = jax.device_get(value)
                fname = f"{name}.msgpack"
                checksums[fname] = _fsync_write(tmp / fname, serialization.to_bytes(host_value))
                manifest[name] = "msgpack"
                # Template for structure restoration.
                tname = f"{name}.template.pkl"
                checksums[tname] = _fsync_write(
                    tmp / tname, pickle.dumps(jax.tree.map(lambda x: None, host_value))
                )
            else:
                fname = f"{name}.pkl"
                checksums[fname] = _fsync_write(tmp / fname, pickle.dumps(value))
                manifest[name] = "pickle"
        _fsync_write(
            tmp / "manifest.pkl",
            pickle.dumps(
                {
                    "step": step,
                    "entries": manifest,
                    "checksums": checksums,
                    "format": MANIFEST_FORMAT,
                }
            ),
        )
        _fsync_dir(tmp)  # the entries themselves
        if out.exists():
            shutil.rmtree(out)
        tmp.rename(out)
        _fsync_dir(self.ckpt_dir)  # the rename: publish survives a power cut
        if sync:
            self._barrier(f"ckpt_{step}_published")
            self._barrier(f"ckpt_{step}_shards")  # all ranks' shards are on disk
        self._gc()
        return out

    def _gc(self) -> None:
        if not self.keep_last:
            return
        ckpts = self.list_checkpoints()
        for old in ckpts[: -self.keep_last]:
            shutil.rmtree(old, ignore_errors=True)

    def list_checkpoints(self) -> List[Path]:
        if not self.ckpt_dir.exists():
            return []
        ckpts = [p for p in self.ckpt_dir.iterdir() if p.is_dir() and p.name.startswith("ckpt_")]
        return sorted(ckpts, key=lambda p: int(p.name.split("_")[1]))

    # ------------------------------------------------------------------ integrity
    @staticmethod
    def _read_manifest(ckpt_path: Path) -> Dict[str, Any]:
        try:
            with open(ckpt_path / "manifest.pkl", "rb") as f:
                manifest = pickle.load(f)
        except (OSError, pickle.UnpicklingError, EOFError, AttributeError, ValueError) as e:
            raise CheckpointCorruptError(f"{ckpt_path}: unreadable manifest.pkl: {e}") from e
        if not isinstance(manifest, dict) or "entries" not in manifest:
            raise CheckpointCorruptError(f"{ckpt_path}: malformed manifest.pkl")
        return manifest

    @classmethod
    def verify(cls, ckpt_path: os.PathLike) -> bool:
        """True iff the checkpoint's manifest reads and every checksum matches."""
        try:
            cls._verify(Path(ckpt_path))
            return True
        except CheckpointCorruptError:
            return False

    @classmethod
    def _verify(cls, ckpt_path: Path) -> Dict[str, Any]:
        """Verify and return the manifest; raises :class:`CheckpointCorruptError`.

        Legacy (format 1) manifests have no checksums — only file existence is
        checkable; the deserialization wrappers in :meth:`load` still catch their
        bit rot, just without the fallback-before-parse guarantee."""
        manifest = cls._read_manifest(ckpt_path)
        for name, kind in manifest["entries"].items():
            if kind == "msgpack":
                expected = [f"{name}.msgpack", f"{name}.template.pkl"]
            elif kind == "per_rank":
                expected = []  # rank shards verify against their sidecars below
            else:
                expected = [f"{name}.pkl"]
            for fname in expected:
                if not (ckpt_path / fname).is_file():
                    raise CheckpointCorruptError(f"{ckpt_path}: missing {fname}")
        for fname, digest in (manifest.get("checksums") or {}).items():
            fpath = ckpt_path / fname
            if not fpath.is_file():
                raise CheckpointCorruptError(f"{ckpt_path}: missing {fname}")
            if _sha256(fpath.read_bytes()) != digest:
                raise CheckpointCorruptError(f"{ckpt_path}: checksum mismatch on {fname}")
        for sidecar in ckpt_path.glob("*.rank*.pkl.sha256"):
            shard = ckpt_path / sidecar.name[: -len(".sha256")]
            if not shard.is_file():
                raise CheckpointCorruptError(f"{ckpt_path}: missing shard {shard.name}")
            if _sha256(shard.read_bytes()) != sidecar.read_text().strip():
                raise CheckpointCorruptError(f"{ckpt_path}: checksum mismatch on {shard.name}")
        return manifest

    @classmethod
    def latest_valid(cls, ckpt_dir: os.PathLike) -> Optional[Path]:
        """Newest checkpoint under ``ckpt_dir`` that verifies; None when there is none.
        The supervisor and the autoresume path use this for resume discovery."""
        ckpt_dir = Path(ckpt_dir)
        if not ckpt_dir.exists():
            return None
        ckpts = sorted(
            (p for p in ckpt_dir.iterdir() if p.is_dir() and p.name.startswith("ckpt_")),
            key=lambda p: int(p.name.split("_")[1]),
            reverse=True,
        )
        for ckpt in ckpts:
            if cls.verify(ckpt):
                return ckpt
        return None

    # ------------------------------------------------------------------ load
    @classmethod
    def load(
        cls,
        ckpt_path: os.PathLike,
        templates: Optional[Dict[str, Any]] = None,
        fallback: bool = True,
    ) -> Dict[str, Any]:
        """Load a checkpoint directory. ``templates`` provides target pytrees for
        msgpack entries (required to restore dtypes/shapes as jax arrays).

        Verifies checksums first; on corruption (or a deserialization failure) with
        ``fallback=True``, walks earlier sibling ``ckpt_*`` dirs newest-first and
        loads the first one that verifies — losing a checkpoint interval beats
        losing the run.  Raises :class:`CheckpointCorruptError` when nothing valid
        remains (or with ``fallback=False``)."""
        ckpt_path = Path(ckpt_path)
        try:
            return cls._load_one(ckpt_path, templates)
        except CheckpointCorruptError as primary:
            if not fallback:
                raise
            candidates = sorted(
                (
                    p
                    for p in ckpt_path.parent.iterdir()
                    if p.is_dir() and p.name.startswith("ckpt_") and p != ckpt_path
                ),
                key=lambda p: int(p.name.split("_")[1]),
                reverse=True,
            ) if ckpt_path.parent.exists() else []
            for candidate in candidates:
                try:
                    state = cls._load_one(candidate, templates)
                except CheckpointCorruptError:
                    continue
                from sheeprl_tpu.fault import counters as _fault_counters
                from sheeprl_tpu.obs import flight_recorder

                _fault_counters.bump("Fault/checkpoint_fallbacks")
                flight_recorder.record_event(
                    "checkpoint_fallback", corrupt=str(ckpt_path), loaded=str(candidate)
                )
                warnings.warn(
                    f"checkpoint {ckpt_path} is corrupt ({primary}); "
                    f"fell back to {candidate} (step {state['_step']})"
                )
                return state
            raise CheckpointCorruptError(
                f"{ckpt_path} is corrupt and no earlier valid checkpoint exists in "
                f"{ckpt_path.parent}"
            ) from primary

    @classmethod
    def _load_one(cls, ckpt_path: Path, templates: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        manifest = cls._verify(ckpt_path)
        state: Dict[str, Any] = {"_step": manifest["step"]}
        for name, kind in manifest["entries"].items():
            try:
                if kind == "msgpack":
                    raw = (ckpt_path / f"{name}.msgpack").read_bytes()
                    if templates and name in templates:
                        state[name] = serialization.from_bytes(templates[name], raw)
                    else:
                        state[name] = serialization.msgpack_restore(raw)
                elif kind == "per_rank":
                    # Each process restores its own shard; fall back to rank 0's when
                    # the world size changed between save and resume.
                    shard = ckpt_path / f"{name}.rank{jax.process_index()}.pkl"
                    if not shard.is_file():
                        shard = ckpt_path / f"{name}.rank0.pkl"
                    with open(shard, "rb") as f:
                        state[name] = pickle.load(f)
                else:
                    with open(ckpt_path / f"{name}.pkl", "rb") as f:
                        state[name] = pickle.load(f)
            except CheckpointCorruptError:
                raise
            except Exception as e:
                # Checksummed bytes that still fail to parse (legacy format-1 rot, or
                # a template mismatch) — surface as corruption so fallback can act.
                raise CheckpointCorruptError(
                    f"{ckpt_path}: entry {name!r} ({kind}) failed to deserialize: {e}"
                ) from e
        return state


def validate_resume_config(old_cfg: Dict[str, Any], new_cfg: Dict[str, Any]) -> Dict[str, Any]:
    """Merge a checkpoint's config into the current one, protecting the keys the
    reference refuses to change on resume (``cli.py:48-52``)."""
    merged = dict(new_cfg)
    for key in PROTECTED_RESUME_KEYS:
        if key in old_cfg:
            merged[key] = old_cfg[key]
    return merged
