"""Anakin training mode: acting + env stepping + update fused into ONE dispatch.

PROFILE_r05 §1 measured the two remaining end-to-end walls as architectural:
~125 ms/iteration of player round trip (the host fetches one action from the
policy jit per env step) and ~150 ms of single-core host env stepping.  The
Podracer "Anakin" architecture (arxiv 2104.06272) removes both: the environment
itself is a pure JAX function (``sheeprl_tpu/envs/jax``), N instances vmap into
one tensor program, and env step, acting, transition writes and the gradient
update compile into a single donated jitted ``lax.scan`` — zero player RTT,
zero host env stepping, zero H2D per step.  The host's entire per-dispatch job
is one jit call plus counter bookkeeping.

This module is the shared acting/update engine ROADMAP item 1 names: the PPO
and SAC entry points delegate here when ``algo.anakin=True`` (requires a
``env.jax.enabled`` env), reusing their existing jitted update builders —

* PPO: the fused iteration collects a ``rollout_steps`` on-device rollout and
  then calls the UNCHANGED :class:`~sheeprl_tpu.algos.ppo.ppo.PPOTrainFns`
  ``train_fn`` on it, so the Anakin update is bit-identical to the host path
  given the same collected batch (pinned by ``tests/test_algos/test_anakin.py``);
* SAC (and DroQ via the same ``make_sac_step_fn``): each in-scan iteration steps
  the envs once, writes the transitions into the PR-5
  :class:`~sheeprl_tpu.data.device_buffer.DeviceTransitionRing` layout carried
  through the scan (``make_scan_writer``), and runs ``replay_ratio`` gradient
  steps off the ring with in-jit uniform sampling (``make_sample_gather``).

Metrics (``Rewards/rew_avg``, episode lengths, ``Loss/*``, ``Health/*``) are
accumulated inside the scan carry, returned per dispatch as device futures and
drained at the existing log cadence — zero extra host syncs per step.  The
scan carry (env states, ring + counters, PRNG key, params, optimizer state)
round-trips through :class:`~sheeprl_tpu.checkpoint.manager.CheckpointManager`
for mid-run resume, and the flight recorder stages a device-side copy of the
carry post-dispatch (the dispatch DONATES it) exactly like the PR-5 fused ring
blocks.  See ``howto/anakin.md``.
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any, Dict, Optional

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.analysis.strict import maybe_inject_nonfinite, nan_scan, strict_enabled, strict_guard
from sheeprl_tpu.checkpoint.manager import CheckpointManager
from sheeprl_tpu.config.core import save_config
from sheeprl_tpu.envs.jax import make_jax_env
from sheeprl_tpu.fault.guard import TrainingGuard
from sheeprl_tpu.obs import TrainingMonitor, flight_recorder
from sheeprl_tpu.obs import perf as obs_perf
from sheeprl_tpu.obs.health import health_enabled
from sheeprl_tpu.precision import train_policy
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import gae, polynomial_decay

EPISODE_SUM_KEYS = ("Episodes/return_sum", "Episodes/len_sum", "Episodes/count")


def anakin_enabled(cfg) -> bool:
    """The mode gate the entry points test before falling back to their host loop."""
    return bool(cfg.algo.get("anakin", False))


def anakin_env(cfg):
    """Build the pure-functional env + params from the config; hard errors beat a
    silent host fallback — the user asked for the fused mode explicitly."""
    if not bool(cfg.env.jax.get("enabled", False)):
        raise ValueError(
            "algo.anakin=True needs an on-device JAX environment: pick one with "
            "env=jax_cartpole / jax_pendulum / jax_mountain_car (or set "
            "env.jax.enabled=True with env.jax.env_id for a gymnax env)."
        )
    if jax.process_count() > 1:
        raise ValueError(
            "algo.anakin=True is single-process (the fused scan owns the whole "
            "env+learner state); use the host loops for multi-host runs."
        )
    env = make_jax_env(cfg.env.jax.env_id or cfg.env.id)
    return env, env.default_params()


def anakin_mlp_key(cfg) -> str:
    """Anakin envs expose ONE flat vector observation; map it to the single
    configured MLP key (the agents' obs-dict contract)."""
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    if cnn_keys or len(mlp_keys) != 1:
        raise ValueError(
            "algo.anakin=True supports exactly one MLP observation key and no CNN "
            f"keys (the jax envs are flat-vector); got cnn={cnn_keys} mlp={mlp_keys}."
        )
    return mlp_keys[0]


# --------------------------------------------------------------------- episodes
def init_episode_stats(num_envs: int) -> Dict[str, jax.Array]:
    """Per-env running episode accumulators + the dispatch-window sums, all carried
    through the scan (drained at the log cadence, never per step)."""
    return {
        "ep_return": jnp.zeros((num_envs,), jnp.float32),
        "ep_len": jnp.zeros((num_envs,), jnp.int32),
        "return_sum": jnp.zeros((), jnp.float32),
        "len_sum": jnp.zeros((), jnp.float32),
        "count": jnp.zeros((), jnp.float32),
    }


def reset_episode_sums(stats: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
    return {
        **stats,
        "return_sum": jnp.zeros((), jnp.float32),
        "len_sum": jnp.zeros((), jnp.float32),
        "count": jnp.zeros((), jnp.float32),
    }


def update_episode_stats(stats: Dict[str, jax.Array], reward: jax.Array, done: jax.Array):
    """One vectorized env step's bookkeeping: accumulate running returns/lengths,
    fold finished episodes into the window sums, reset the finished envs."""
    ep_return = stats["ep_return"] + reward
    ep_len = stats["ep_len"] + 1
    d = done.astype(jnp.float32)
    return {
        "ep_return": ep_return * (1.0 - d),
        "ep_len": ep_len * (1 - done.astype(jnp.int32)),
        "return_sum": stats["return_sum"] + jnp.sum(ep_return * d),
        "len_sum": stats["len_sum"] + jnp.sum(ep_len.astype(jnp.float32) * d),
        "count": stats["count"] + jnp.sum(d),
    }


def episode_metrics(stats: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
    return {
        "Episodes/return_sum": stats["return_sum"],
        "Episodes/len_sum": stats["len_sum"],
        "Episodes/count": stats["count"],
    }


class AnakinFutures:
    """Deferred per-dispatch metric futures (the Anakin cousin of
    ``utils.blocks.WindowedFutures``): ``track`` keeps the dispatch's metrics tree
    ON DEVICE, ``drain`` is the window's only blocking fetch — episode sums are
    folded into ``Rewards/rew_avg``/``Game/ep_len_avg`` and every other key feeds
    the aggregator.  Window wall-clock gives honest env-steps/s + grad-steps/s.

    Metric leaves may be scalars (plain Anakin) or carry a LEADING MEMBER AXIS
    (population dispatches, ``engine/population.py``).  Member-axis reductions,
    per metric (see ``howto/population.md``):

    * the PLAIN key keeps logging — as the cross-member mean — so existing
      dashboards stay meaningful;
    * ``Population/<key>/member_{m}`` logs each member's window value,
      ``Population/<key>/median`` the cross-member median, and
      ``Population/<key>/best`` the cross-member max (``Rewards/*`` / ``Game/*``
      / ``Episodes/*``) or min (``Loss/*``);
    * ``Rewards/rew_avg`` / ``Game/ep_len_avg`` derive per member from that
      member's episode sums (members with no finished episodes in the window
      are skipped), then reduce the same way.

    Everything still rides the window's single blocking ``device_get`` — zero
    extra host syncs per step regardless of the member count."""

    def __init__(self):
        self._pending = []
        self._window_env_steps = 0
        self._window_grad_steps = 0
        self._window_t0 = 0.0

    def track(self, metrics: Any, env_steps: int, grad_steps: int) -> None:
        if not self._pending and self._window_env_steps == 0:
            self._window_t0 = time.perf_counter()
        self._pending.append(metrics)
        self._window_env_steps += env_steps
        self._window_grad_steps += grad_steps

    def drain(self, aggregator: Optional[MetricAggregator]) -> Dict[str, float]:
        """Fetch every pending dispatch's metrics (one blocking device_get), feed
        the aggregator and return the window's derived rates/episode means plus
        any ``Population/*`` member reductions."""
        from sheeprl_tpu.engine.population import population_rows

        fetched = jax.device_get(self._pending) if self._pending else []
        self._pending.clear()
        ret_sum = len_sum = count = 0.0  # scalars or [K] member vectors
        window: Dict[str, list] = {}
        for tree in fetched:
            ret_sum = ret_sum + np.asarray(tree.pop("Episodes/return_sum", 0.0), np.float64)
            len_sum = len_sum + np.asarray(tree.pop("Episodes/len_sum", 0.0), np.float64)
            count = count + np.asarray(tree.pop("Episodes/count", 0.0), np.float64)
            for k, v in tree.items():
                arr = np.asarray(v)
                if arr.ndim == 0:  # plain Anakin: scalar leaves, historical path
                    if aggregator is not None:
                        aggregator.update(k, float(arr))
                else:  # population: leading member axis
                    if aggregator is not None:
                        aggregator.update(k, float(arr.mean()))
                    window.setdefault(k, []).append(arr)
        elapsed = max(time.perf_counter() - self._window_t0, 1e-9)
        out: Dict[str, float] = {}
        for k, arrs in window.items():
            out.update(population_rows(k, np.mean(np.stack(arrs), axis=0)))
        if np.ndim(count) == 0:
            if count > 0 and aggregator is not None:
                aggregator.update("Rewards/rew_avg", ret_sum / count)
                aggregator.update("Game/ep_len_avg", len_sum / count)
        else:
            with np.errstate(divide="ignore", invalid="ignore"):
                rew = np.where(count > 0, ret_sum / np.maximum(count, 1e-9), np.nan)
                length = np.where(count > 0, len_sum / np.maximum(count, 1e-9), np.nan)
            if np.isfinite(rew).any():
                if aggregator is not None:
                    aggregator.update("Rewards/rew_avg", float(np.nanmean(rew)))
                    aggregator.update("Game/ep_len_avg", float(np.nanmean(length)))
                out.update(population_rows("Rewards/rew_avg", rew))
                out.update(population_rows("Game/ep_len_avg", length))
        if self._window_env_steps > 0:
            out["Time/sps_env_interaction"] = self._window_env_steps / elapsed
        if self._window_grad_steps > 0:
            out["Time/sps_train"] = self._window_grad_steps / elapsed
        self._window_env_steps = 0
        self._window_grad_steps = 0
        return out


def reset_envs(env, env_params, num_envs: int, key: jax.Array):
    keys = jax.random.split(key, num_envs)
    return jax.vmap(env.reset, in_axes=(None, 0))(env_params, keys)


def stage_carry(recorder, carry, **scalars) -> None:
    """Post-dispatch flight-recorder staging: the dispatch DONATED the carry, so
    pre-step references are gone — stage a device-side copy (async, no host sync)
    of the state entering the NEXT dispatch, as the PR-5 fused ring blocks do."""
    if recorder is not None:
        recorder.stage_step(carry=jax.tree.map(jnp.copy, carry), scalars=scalars)


# -------------------------------------------------------------------------- PPO
def make_ppo_anakin_iteration(env, env_params, agent, fns, cfg, obs_key: str, return_batch: bool = False):
    """One fused PPO training iteration: an on-device ``rollout_steps`` collection
    scan (vmapped env + acting policy), GAE, then the UNCHANGED
    ``PPOTrainFns.train_fn`` — calling the already-jitted update inlines the same
    program, which is what makes the Anakin update bit-identical to the host path
    on the same batch.  ``return_batch=True`` (tests/bench) also returns the
    collected batch + the exact key fed to ``train_fn``."""
    from sheeprl_tpu.algos.ppo.utils import sample_actions

    num_envs = int(cfg.env.num_envs)
    rollout_steps = int(cfg.algo.rollout_steps)
    batch_n = rollout_steps * num_envs
    gamma, gae_lambda = cfg.algo.gamma, cfg.algo.gae_lambda
    clip_rewards = bool(cfg.env.clip_rewards)
    is_continuous = agent.is_continuous
    discrete_scalar = not is_continuous and len(agent.action_dims) == 1
    act_space = env.action_space(env_params)
    clip_act = is_continuous and bool(
        np.isfinite(act_space.low).all() and np.isfinite(act_space.high).all()
    )
    act_low = jnp.asarray(getattr(act_space, "low", 0.0), jnp.float32)
    act_high = jnp.asarray(getattr(act_space, "high", 0.0), jnp.float32)
    vstep = jax.vmap(env.step_autoreset, in_axes=(None, 0, 0, 0))
    # Precision boundary (howto/precision.md): a CAST COPY of the obs feeds the
    # acting forward; the stored trajectory keeps the env's f32 observations.
    cast_obs = train_policy(cfg).cast_to_compute

    def iteration(carry, clip_coef, ent_coef):
        params = carry["params"]
        stats0 = reset_episode_sums(carry["episode_stats"])

        def act_step(c, _):
            env_state, obs, key, stats = c
            key, k_act, k_step = jax.random.split(key, 3)
            actor_out, value = agent.apply(params, {obs_key: cast_obs(obs)})
            env_act, stored_act, logprob = sample_actions(k_act, actor_out, is_continuous)
            if clip_act:
                env_actions = jnp.clip(env_act, act_low, act_high)
            elif discrete_scalar:
                env_actions = env_act[..., 0].astype(jnp.int32)
            else:
                env_actions = env_act
            step_keys = jax.random.split(k_step, num_envs)
            env_state, next_obs, reward, done, _info = vstep(env_params, env_state, env_actions, step_keys)
            if clip_rewards:
                reward = jnp.clip(reward, -1, 1)
            stats = update_episode_stats(stats, reward, done)
            ys = {
                obs_key: obs,
                "actions": stored_act.reshape(num_envs, -1).astype(jnp.float32),
                "logprobs": logprob.reshape(num_envs),
                "values": value[..., 0],
                "rewards": reward.astype(jnp.float32),
                "dones": done.astype(jnp.float32),
            }
            return (env_state, next_obs, key, stats), ys

        (env_state, obs, key, stats), traj = jax.lax.scan(
            act_step, (carry["env_state"], carry["obs"], carry["key"], stats0), None, length=rollout_steps
        )
        _, next_value = agent.apply(params, {obs_key: cast_obs(obs)})
        returns, advantages = gae(
            traj["rewards"][..., None],
            traj["values"][..., None],
            traj["dones"][..., None],
            next_value[..., 0:1],
            rollout_steps,
            gamma,
            gae_lambda,
        )
        data = {
            obs_key: traj[obs_key],
            "actions": traj["actions"],
            "logprobs": traj["logprobs"],
            "values": traj["values"],
            "returns": returns[..., 0],
            "advantages": advantages[..., 0],
        }
        data = jax.tree.map(lambda x: x.reshape(batch_n, *x.shape[2:]), data)

        key, k_train = jax.random.split(key)
        params, opt_state, metrics = fns.train_fn(
            params, carry["opt_state"], data, k_train, clip_coef, ent_coef
        )
        metrics = {**metrics, **episode_metrics(stats)}
        new_carry = {
            "params": params,
            "opt_state": opt_state,
            "env_state": env_state,
            "obs": obs,
            "key": key,
            "episode_stats": stats,
        }
        if return_batch:
            return new_carry, metrics, data, k_train
        return new_carry, metrics

    return iteration


def ppo_anakin(ctx, cfg) -> None:
    """The Anakin PPO entry path (``algo.anakin=True``), called by
    ``sheeprl_tpu.algos.ppo.ppo.main``.  With ``algo.population.size=K`` (or a
    sweep) every piece of per-run state gains a leading member axis and K
    independent members train in the same single donated dispatch
    (``engine/population.py``; howto/population.md)."""
    from sheeprl_tpu.algos.ppo.agent import build_agent
    from sheeprl_tpu.algos.ppo.ppo import PPOTrainFns
    from sheeprl_tpu.algos.ppo.utils import AGGREGATOR_KEYS, test
    from sheeprl_tpu.engine.population import (
        PopulationSpec,
        member_keys,
        population_transform,
        set_injected_lr,
        slice_member,
        stack_members,
    )

    env, env_params = anakin_env(cfg)
    obs_key = anakin_mlp_key(cfg)
    pop = PopulationSpec.from_cfg(cfg, "ppo")
    members = pop.size if pop.enabled else 1
    log_dir = get_log_dir(cfg)
    if ctx.is_global_zero:
        save_config(cfg, Path(log_dir) / "config.yaml")
    logger = get_logger(cfg, log_dir)
    monitor = TrainingMonitor(cfg, log_dir)

    obs_space = gym.spaces.Dict({obs_key: env.observation_space(env_params)})
    act_space = env.action_space(env_params)
    agent, params = build_agent(ctx, act_space, obs_space, cfg)

    num_envs = int(cfg.env.num_envs)
    rollout_steps = int(cfg.algo.rollout_steps)
    policy_steps_per_iter = num_envs * rollout_steps
    total_steps = int(cfg.algo.total_steps)
    num_updates = max(total_steps // policy_steps_per_iter, 1) if not cfg.dry_run else 1

    sweeps_lr = pop.enabled and pop.sweeps_lr("optimizer.lr")
    fns = PPOTrainFns(ctx, agent, cfg, [obs_key], num_updates, inject_lr=sweeps_lr)
    iteration = make_ppo_anakin_iteration(env, env_params, agent, fns, cfg, obs_key)
    # The whole iteration is ONE donated jit: env scan + GAE + the update block —
    # for a population, lifted over the member axis first (howto/population.md).
    if pop.enabled:
        dispatch = obs_perf.instrument(
            cfg,
            "anakin/ppo_pop_dispatch",
            strict_guard(
                cfg,
                "anakin/ppo_pop_dispatch",
                jax.jit(population_transform(iteration, pop.vectorize, n_args=2), donate_argnums=(0,)),
            ),
        )
    else:
        dispatch = obs_perf.instrument(
            cfg,
            "anakin/ppo_dispatch",
            strict_guard(cfg, "anakin/ppo_dispatch", jax.jit(iteration, donate_argnums=(0,))),
        )

    if pop.enabled:
        # Per-member init: member 0 draws exactly what the plain path draws
        # (population.size=1 is then bit-identical to plain Anakin); members
        # m > 0 get fresh init draws / folded key streams.
        member_params = [params] + [build_agent(ctx, act_space, obs_space, cfg)[1] for _ in range(1, members)]
        lr_values = pop.values("optimizer.lr", cfg.algo.optimizer.lr)
        member_carries = []
        reset_keys = member_keys(ctx.local_rng(), members)
        carry_keys = member_keys(ctx.rng(), members)
        for m in range(members):
            opt_m = fns.opt.init(member_params[m])
            if sweeps_lr:
                opt_m = set_injected_lr(opt_m, lr_values[m])
            env_state_m, obs0_m = reset_envs(env, env_params, num_envs, reset_keys[m])
            member_carries.append(
                {
                    "params": member_params[m],
                    "opt_state": ctx.replicate(opt_m),
                    "env_state": env_state_m,
                    "obs": obs0_m,
                    "key": carry_keys[m],
                    "episode_stats": init_episode_stats(num_envs),
                }
            )
        carry = stack_members(member_carries)
    else:
        env_state, obs0 = reset_envs(env, env_params, num_envs, ctx.local_rng())
        carry = {
            "params": params,
            "opt_state": ctx.replicate(fns.opt.init(params)),
            "env_state": env_state,
            "obs": obs0,
            "key": ctx.rng(),
            "episode_stats": init_episode_stats(num_envs),
        }

    aggregator = MetricAggregator(cfg.metric.aggregator.get("metrics", {}))
    aggregator.keep(AGGREGATOR_KEYS | set(cfg.metric.aggregator.get("metrics", {})))
    ckpt_manager = CheckpointManager(Path(log_dir) / "checkpoints", keep_last=cfg.checkpoint.keep_last)
    futures = AnakinFutures()
    recorder = flight_recorder.get_active()
    if recorder is not None:
        recorder.arm_replay("sheeprl_tpu.engine.anakin:replay_update", num_updates=num_updates)

    start_update, policy_step, last_log, last_checkpoint = 1, 0, 0, 0
    if cfg.checkpoint.get("resume_from"):
        state = CheckpointManager.load(
            cfg.checkpoint.resume_from, templates={"carry": jax.device_get(carry)}
        )
        carry = ctx.replicate(state["carry"])
        start_update = state["update"] + 1
        policy_step = state["policy_step"]
        last_log = state.get("last_log", 0)
        last_checkpoint = state.get("last_checkpoint", 0)

    grad_steps_per_update = fns.grad_steps_per_update
    clip0 = pop.values("clip_coef", cfg.algo.clip_coef) if pop.enabled else [float(cfg.algo.clip_coef)]
    ent0 = pop.values("ent_coef", cfg.algo.ent_coef) if pop.enabled else [float(cfg.algo.ent_coef)]

    guard = TrainingGuard(cfg, log_dir)

    def save_ckpt():
        nonlocal last_checkpoint
        with monitor.phase("checkpoint"):
            path = ckpt_manager.save(
                policy_step,
                {
                    "carry": carry,
                    "update": update,
                    "policy_step": policy_step,
                    "last_log": last_log,
                    "last_checkpoint": policy_step,
                },
            )
        last_checkpoint = policy_step
        return path

    for update in range(start_update, num_updates + 1):
        monitor.advance()
        clip_coef, ent_coef = list(clip0), list(ent0)
        if cfg.algo.anneal_clip_coef:  # per member, each from its own swept initial value
            clip_coef = [
                polynomial_decay(update, initial=c, final=0.0, max_decay_steps=num_updates) for c in clip_coef
            ]
        if cfg.algo.anneal_ent_coef:
            ent_coef = [
                polynomial_decay(update, initial=e, final=0.0, max_decay_steps=num_updates) for e in ent_coef
            ]
        if pop.enabled:
            coef_args = (jnp.asarray(clip_coef, jnp.float32), jnp.asarray(ent_coef, jnp.float32))
            staged_coefs = {"clip_coef": [float(c) for c in clip_coef], "ent_coef": [float(e) for e in ent_coef]}
        else:
            coef_args = (float(clip_coef[0]), float(ent_coef[0]))
            staged_coefs = {"clip_coef": float(clip_coef[0]), "ent_coef": float(ent_coef[0])}
        with timer("Time/train_time"), monitor.phase("dispatch"):
            carry, metrics = dispatch(carry, *coef_args)
        futures.track(metrics, policy_steps_per_iter * members, grad_steps_per_update * members)
        policy_step += policy_steps_per_iter
        stage_carry(recorder, carry, update=update, **staged_coefs)

        if logger is not None and (
            policy_step - last_log >= cfg.metric.log_every or update == num_updates or cfg.dry_run
        ):
            out = futures.drain(aggregator)  # the window's only blocking device sync
            out.update(aggregator.compute())
            if not sweeps_lr:
                out["Params/lr"] = (
                    float(fns.lr_schedule(update * grad_steps_per_update))
                    if fns.lr_schedule is not None
                    else float(cfg.algo.optimizer.lr)
                )
            if pop.enabled:  # the sweep table is static — log it with every flush
                for name, values in pop.sweep.items():
                    for m, v in enumerate(values):
                        out[f"Population/Params/{name}/member_{m}"] = float(v)
            monitor.log_metrics(logger, out, policy_step)
            aggregator.reset()
            last_log = policy_step

        if (
            cfg.checkpoint.every > 0
            and (policy_step - last_checkpoint) >= cfg.checkpoint.every
            or update == num_updates
            and cfg.checkpoint.save_last
        ):
            save_ckpt()
        guard.boundary(policy_step, save_ckpt)

    monitor.close()
    if cfg.algo.run_test and ctx.is_global_zero:
        # population: the greedy test episode runs member 0's policy (the member
        # continuing the run's base seed stream — see howto/population.md)
        test_params = slice_member(carry["params"], 0) if pop.enabled else carry["params"]
        reward = test(agent, test_params, ctx, cfg, log_dir)
        if logger is not None:
            logger.log_metrics({"Test/cumulative_reward": reward}, policy_step)
    if logger is not None:
        logger.close()


# -------------------------------------------------------------------------- SAC
def make_sac_anakin_dispatch(env, env_params, actor, critic, cfg, act_space, ring, batch_size: int, inject_lr=()):
    """Builder of fused SAC Anakin dispatch programs: ``builder(steps,
    grad_per_step, train)`` returns the python function for a ``steps``-iteration
    scan where each iteration steps the vmapped envs once, writes the transition
    row into the ring arrays CARRIED through the scan
    (:meth:`DeviceTransitionRing.make_scan_writer`), and — when ``train`` — runs
    ``grad_per_step`` :func:`~sheeprl_tpu.algos.sac.sac.make_sac_step_fn` updates
    off in-jit uniform ring sampling.  ``train=False`` is the prefill program
    (uniform random actions, no updates).  DroQ rides the same shape through its
    own step fn."""
    from sheeprl_tpu.algos.sac.sac import make_sac_step_fn

    # inject_lr: population lr sweeps carry per-member rates in the opt state.
    actor_opt, critic_opt, alpha_opt, step_update = make_sac_step_fn(
        actor, critic, cfg, act_space, inject_lr=inject_lr
    )
    sample_gather = ring.make_sample_gather(batch_size)
    write_row = ring.make_scan_writer()
    num_envs = ring.n_envs
    cap = ring.capacity
    strict = strict_enabled(cfg)
    health = health_enabled(cfg)
    clip_rewards = bool(cfg.env.clip_rewards)
    act_low = jnp.asarray(act_space.low, jnp.float32)
    act_high = jnp.asarray(act_space.high, jnp.float32)
    rescale = bool(np.isfinite(act_space.low).all() and np.isfinite(act_space.high).all())
    vstep = jax.vmap(env.step_autoreset, in_axes=(None, 0, 0, 0))
    vsample = jax.vmap(env.sample_action, in_axes=(None, 0))
    # Precision boundary: acting casts a COPY of the obs; ring rows keep the
    # buffer's storage dtype (buffer.store_dtype handles the ring plane).
    cast_obs = train_policy(cfg).cast_to_compute

    def builder(steps: int, grad_per_step: int, train: bool):
        def dispatch(carry):
            def iter_step(c, _):
                params, o_state, env_state, obs, arrays, rows_added, gstep, key, stats = c
                key, k_act, k_step = jax.random.split(key, 3)
                if train:  # trace-time constant: prefill compiles its own program
                    mean, log_std = actor.apply(params["actor"], cast_obs(obs))
                    tanh_act = actor.dist(mean, log_std).sample(k_act)
                else:
                    raw = vsample(env_params, jax.random.split(k_act, num_envs))
                    tanh_act = 2 * (raw - act_low) / (act_high - act_low) - 1 if rescale else raw
                env_act = act_low + (tanh_act + 1) * 0.5 * (act_high - act_low) if rescale else tanh_act
                step_keys = jax.random.split(k_step, num_envs)
                env_state, next_obs, reward, done, info = vstep(env_params, env_state, env_act, step_keys)
                if clip_rewards:
                    reward = jnp.clip(reward, -1, 1)
                stats = update_episode_stats(stats, reward, done)
                rows = {
                    "obs": obs,
                    # the TRUE final obs of finishing episodes (autoreset already
                    # swapped ``next_obs``), mirroring the host loops' final_obs fixup
                    "next_obs": info["final_obs"],
                    "actions": tanh_act,
                    "rewards": reward[:, None].astype(jnp.float32),
                    # truncated episodes still bootstrap (done=0 in the TD target)
                    "dones": info["terminated"][:, None].astype(jnp.float32),
                }
                arrays = write_row(arrays, rows, rows_added)
                rows_added = rows_added + 1
                metrics = {}
                if train and grad_per_step > 0:
                    filled = jnp.minimum(rows_added, cap)

                    def gstep_fn(cc, x):
                        p, o = cc
                        count, k = x
                        k_sample, k_update = jax.random.split(k)
                        batch, age_metrics = sample_gather(arrays, filled, rows_added, k_sample)
                        p, o, m = step_update(p, o, count, batch, k_update)
                        if health:  # replay staleness rides the same metrics tree
                            m = {**m, **age_metrics}
                        return (p, o), m

                    key, k_grad = jax.random.split(key)
                    counts = gstep + jnp.arange(grad_per_step, dtype=jnp.int32)
                    gkeys = jax.random.split(k_grad, grad_per_step)
                    (params, o_state), metrics = jax.lax.scan(
                        gstep_fn, (params, o_state), (counts, gkeys)
                    )
                    metrics = jax.tree.map(jnp.mean, metrics)
                    gstep = gstep + grad_per_step
                return (params, o_state, env_state, next_obs, arrays, rows_added, gstep, key, stats), metrics

            stats0 = reset_episode_sums(carry["episode_stats"])
            init = (
                carry["params"],
                carry["opt_state"],
                carry["env_state"],
                carry["obs"],
                carry["ring"],
                carry["rows_added"],
                carry["gstep"],
                carry["key"],
                stats0,
            )
            (params, o_state, env_state, obs, arrays, rows_added, gstep, key, stats), metrics = jax.lax.scan(
                iter_step, init, None, length=steps
            )
            metrics = jax.tree.map(jnp.mean, metrics)
            metrics = {**metrics, **episode_metrics(stats)}
            metrics = maybe_inject_nonfinite(cfg, metrics)
            if strict:  # trace-time constant: the callback only exists in strict runs
                nan_scan(metrics, "anakin/sac_dispatch")
            new_carry = {
                "params": params,
                "opt_state": o_state,
                "env_state": env_state,
                "obs": obs,
                "ring": arrays,
                "rows_added": rows_added,
                "gstep": gstep,
                "key": key,
                "episode_stats": stats,
            }
            return new_carry, metrics

        return dispatch

    return actor_opt, critic_opt, alpha_opt, builder


class SacAnakinDispatcher:
    """Compile-once cache of the SAC dispatch programs keyed on (steps,
    grad_per_step, train) — the steady state uses exactly one program; the
    prefill and a tail remainder add at most two more.  ``transform`` lifts each
    program over the population member axis before jitting
    (``engine/population.py``: ``lax.map`` by default, ``vmap`` when
    ``algo.population.vectorize=True``)."""

    def __init__(self, builder, cfg, transform=None):
        self._builder = builder
        self._cfg = cfg
        self._transform = transform
        self._programs: dict = {}

    def __call__(self, carry, steps: int, grad_per_step: int, train: bool):
        sig = (steps, grad_per_step, train)
        prog = self._programs.get(sig)
        if prog is None:
            fn = self._builder(steps, grad_per_step, train)
            name = f"anakin/sac_dispatch_{steps}x{grad_per_step}{'t' if train else 'p'}"
            if self._transform is not None:
                fn = self._transform(fn)
                name = f"anakin/sac_pop_dispatch_{steps}x{grad_per_step}{'t' if train else 'p'}"
            prog = obs_perf.instrument(
                self._cfg, name, strict_guard(self._cfg, name, jax.jit(fn, donate_argnums=(0,)))
            )
            self._programs[sig] = prog
        return prog(carry)


def sac_anakin(ctx, cfg) -> None:
    """The Anakin SAC entry path (``algo.anakin=True``), called by
    ``sheeprl_tpu.algos.sac.sac.main``.  ``algo.population.size=K`` trains K
    independent members — each with its own params, optimizer state, env states,
    replay ring and PRNG streams — in one donated dispatch
    (``engine/population.py``; howto/population.md)."""
    from sheeprl_tpu.algos.sac.agent import build_agent
    from sheeprl_tpu.algos.sac.utils import AGGREGATOR_KEYS, test
    from sheeprl_tpu.data.device_buffer import DeviceTransitionRing, resolve_store_dtype
    from sheeprl_tpu.engine.population import (
        PopulationSpec,
        member_keys,
        population_transform,
        set_injected_lr,
        slice_member,
        stack_members,
    )

    env, env_params = anakin_env(cfg)
    mlp_key = anakin_mlp_key(cfg)
    pop = PopulationSpec.from_cfg(cfg, "sac")
    members = pop.size if pop.enabled else 1
    replay_ratio = float(cfg.algo.replay_ratio)
    grad_per_step = int(round(replay_ratio))
    if grad_per_step < 1 or abs(replay_ratio - grad_per_step) > 1e-9:
        raise ValueError(
            f"algo.anakin=True needs an integer algo.replay_ratio >= 1 (the fused "
            f"scan runs a static number of gradient steps per env step); got "
            f"{replay_ratio}."
        )

    log_dir = get_log_dir(cfg)
    if ctx.is_global_zero:
        save_config(cfg, Path(log_dir) / "config.yaml")
    logger = get_logger(cfg, log_dir)
    monitor = TrainingMonitor(cfg, log_dir)

    obs_space_box = env.observation_space(env_params)
    act_space = env.action_space(env_params)
    if not isinstance(act_space, gym.spaces.Box):
        raise ValueError("SAC anakin needs a continuous (Box) jax env, e.g. env=jax_pendulum")
    obs_space = gym.spaces.Dict({mlp_key: obs_space_box})
    actor, critic, params = build_agent(ctx, act_space, obs_space, cfg)
    # Donation safety: critic_target aliases critic's buffers at init — a donated
    # carry must not contain the same buffer twice (see the host ring path).
    params = jax.tree.map(jnp.copy, params)

    num_envs = int(cfg.env.num_envs)
    obs_dim = int(np.prod(obs_space_box.shape))
    act_dim = int(np.prod(act_space.shape))
    batch_size = int(cfg.algo.per_rank_batch_size)
    capacity = max(int(cfg.buffer.size) // max(num_envs, 1), 1)
    ring = DeviceTransitionRing(
        capacity,
        num_envs,
        {
            "obs": ((obs_dim,), jnp.float32),
            "next_obs": ((obs_dim,), jnp.float32),
            "actions": ((act_dim,), jnp.float32),
            "rewards": ((1,), jnp.float32),
            "dones": ((1,), jnp.float32),
        },
        store_dtype=resolve_store_dtype(cfg.buffer.get("store_dtype")),
    )
    inject = tuple(n for n in ("actor", "critic", "alpha") if f"{n}.optimizer.lr" in pop.sweep)
    actor_opt, critic_opt, alpha_opt, builder = make_sac_anakin_dispatch(
        env, env_params, actor, critic, cfg, act_space, ring, batch_size, inject_lr=inject
    )

    def init_opt_state(p, member=0):
        o = {
            "actor": actor_opt.init(p["actor"]),
            "critic": critic_opt.init(p["critic"]),
            "alpha": alpha_opt.init(p["log_alpha"]),
        }
        for n in inject:  # stamp the member's swept rate into its own state
            o[n] = set_injected_lr(o[n], pop.sweep[f"{n}.optimizer.lr"][member])
        return ctx.replicate(o)

    if pop.enabled:
        dispatcher = SacAnakinDispatcher(
            builder, cfg, transform=lambda fn: population_transform(fn, pop.vectorize)
        )
        # Per-member init: member 0 draws exactly what the plain path draws
        # (population.size=1 is bit-identical to plain Anakin); m > 0 members
        # get fresh param inits and folded key streams.
        member_params = [params] + [
            jax.tree.map(jnp.copy, build_agent(ctx, act_space, obs_space, cfg)[2]) for _ in range(1, members)
        ]
        reset_keys = member_keys(ctx.local_rng(), members)
        carry_keys = member_keys(ctx.rng(), members)
        member_carries = []
        for m in range(members):
            env_state_m, obs0_m = reset_envs(env, env_params, num_envs, reset_keys[m])
            member_carries.append(
                {
                    "params": member_params[m],
                    "opt_state": init_opt_state(member_params[m], m),
                    "env_state": env_state_m,
                    "obs": obs0_m,
                    "rows_added": jnp.zeros((), jnp.int32),
                    "gstep": jnp.zeros((), jnp.int32),
                    "key": carry_keys[m],
                    "episode_stats": init_episode_stats(num_envs),
                }
            )
        carry = stack_members(member_carries)
        # member-axis ring arrays built at the stacked shape directly (stacking
        # K per-member copies would transiently allocate K extra rings)
        carry["ring"] = ring.population_arrays(members)
    else:
        dispatcher = SacAnakinDispatcher(builder, cfg)
        env_state, obs0 = reset_envs(env, env_params, num_envs, ctx.local_rng())
        carry = {
            "params": params,
            "opt_state": init_opt_state(params),
            "env_state": env_state,
            "obs": obs0,
            "ring": ring.arrays,
            "rows_added": jnp.zeros((), jnp.int32),
            "gstep": jnp.zeros((), jnp.int32),
            "key": ctx.rng(),
            "episode_stats": init_episode_stats(num_envs),
        }

    aggregator = MetricAggregator(cfg.metric.aggregator.get("metrics", {}))
    aggregator.keep(AGGREGATOR_KEYS | set(cfg.metric.aggregator.get("metrics", {})))
    ckpt_manager = CheckpointManager(Path(log_dir) / "checkpoints", keep_last=cfg.checkpoint.keep_last)
    futures = AnakinFutures()
    recorder = flight_recorder.get_active()
    if recorder is not None:
        recorder.arm_replay("sheeprl_tpu.engine.anakin:replay_update")

    total_steps = int(cfg.algo.total_steps)
    num_iters = max(total_steps // max(num_envs, 1), 1) if not cfg.dry_run else 1
    prefill_steps = int(cfg.algo.learning_starts) // max(num_envs, 1) if not cfg.dry_run else 0
    prefill_steps = min(prefill_steps, num_iters - 1) if num_iters > 1 else 0
    steps_per_dispatch = max(int(cfg.algo.anakin_steps_per_dispatch), 1) if not cfg.dry_run else 1

    iter_num, policy_step, last_log, last_checkpoint = 0, 0, 0, 0
    resumed = False
    if cfg.checkpoint.get("resume_from"):
        ckpt_carry = carry if cfg.buffer.checkpoint else {k: v for k, v in carry.items() if k != "ring"}
        state = CheckpointManager.load(
            cfg.checkpoint.resume_from, templates={"carry": jax.device_get(ckpt_carry)}
        )
        restored = ctx.replicate(state["carry"])
        if "ring" not in restored:
            # buffer.checkpoint=False dropped the ring: restart replay from empty
            # (rows_added derives the in-jit sampling range, so it resets too).
            restored = {**restored, "ring": carry["ring"], "rows_added": carry["rows_added"]}
        carry = restored
        iter_num = state["iter_num"]
        policy_step = state["policy_step"]
        last_log = state.get("last_log", 0)
        last_checkpoint = state.get("last_checkpoint", 0)
        resumed = True

    def _maybe_log(final: bool) -> None:
        nonlocal last_log
        if logger is not None and (
            policy_step - last_log >= cfg.metric.log_every or final or cfg.dry_run
        ):
            out = futures.drain(aggregator)  # the window's only blocking device sync
            out.update(aggregator.compute())
            if policy_step > 0:
                out["Params/replay_ratio"] = grad_per_step  # static by construction
            if pop.enabled:  # the sweep table is static — log it with every flush
                for name, values in pop.sweep.items():
                    for m, v in enumerate(values):
                        out[f"Population/Params/{name}/member_{m}"] = float(v)
            monitor.log_metrics(logger, out, policy_step)
            aggregator.reset()
            last_log = policy_step

    def save_ckpt():
        nonlocal last_checkpoint
        ckpt_carry = carry if cfg.buffer.checkpoint else {k: v for k, v in carry.items() if k != "ring"}
        with monitor.phase("checkpoint"):
            path = ckpt_manager.save(
                policy_step,
                {
                    "carry": ckpt_carry,
                    "iter_num": iter_num,
                    "policy_step": policy_step,
                    "last_log": last_log,
                    "last_checkpoint": policy_step,
                },
            )
        last_checkpoint = policy_step
        return path

    def _maybe_checkpoint(final: bool) -> None:
        if (
            cfg.checkpoint.every > 0
            and (policy_step - last_checkpoint) >= cfg.checkpoint.every
            or final
            and cfg.checkpoint.save_last
        ):
            save_ckpt()

    guard = TrainingGuard(cfg, log_dir)

    # Prefill: one dispatch of uniform random acting (a resumed run already has a
    # trained policy and a restored ring — skip it, like the host loops).
    if prefill_steps > 0 and iter_num < prefill_steps and not resumed:
        monitor.advance()
        with timer("Time/env_interaction_time"), monitor.phase("dispatch"):
            carry, metrics = dispatcher(carry, prefill_steps - iter_num, 0, False)
        futures.track(metrics, (prefill_steps - iter_num) * num_envs * members, 0)
        policy_step += (prefill_steps - iter_num) * num_envs
        iter_num = prefill_steps
        stage_carry(recorder, carry, iter_num=iter_num)
        guard.boundary(policy_step, save_ckpt)

    while iter_num < num_iters:
        monitor.advance()
        steps = min(steps_per_dispatch, num_iters - iter_num)
        with timer("Time/train_time"), monitor.phase("dispatch"):
            carry, metrics = dispatcher(carry, steps, grad_per_step, True)
        futures.track(metrics, steps * num_envs * members, steps * grad_per_step * members)
        policy_step += steps * num_envs
        iter_num += steps
        stage_carry(recorder, carry, iter_num=iter_num)
        final = iter_num >= num_iters
        _maybe_log(final)
        _maybe_checkpoint(final)
        guard.boundary(policy_step, save_ckpt)

    monitor.close()
    if cfg.algo.run_test and ctx.is_global_zero:
        # population: the greedy test episode runs member 0's policy
        test_params = slice_member(carry["params"], 0) if pop.enabled else carry["params"]
        reward = test(actor, test_params, ctx, cfg, log_dir)
        if logger is not None:
            logger.log_metrics({"Test/cumulative_reward": reward}, policy_step)
    if logger is not None:
        logger.close()


# ------------------------------------------------------------------ replay
def replay_update(cfg, dump_dir, member: Optional[int] = None):
    """Flight-recorder replay builder: an Anakin blackbox stages the carry
    entering the NEXT dispatch (post-dispatch device-side copy — the dispatch
    donates its input), so replay rebuilds the fused program from the dumped
    config and re-executes that one dispatch on CPU.

    Population dumps (``algo.population``) stage the FULL stacked carry.
    ``member=None`` replays the whole population dispatch; ``member=m`` slices
    member ``m``'s carry off the member axis and replays it through the PLAIN
    single-member program with that member's swept hyperparameters — under the
    default ``vectorize=False`` mode this is the exact program the member ran
    (``python -m sheeprl_tpu.obs.replay_blackbox <dir> --member m``)."""
    from sheeprl_tpu.engine.population import PopulationSpec, population_transform, slice_member
    from sheeprl_tpu.obs import replay_blackbox
    from sheeprl_tpu.parallel.mesh import make_mesh_context

    ctx = make_mesh_context(cfg)
    env, env_params = anakin_env(cfg)
    obs_key = anakin_mlp_key(cfg)
    obs_space = gym.spaces.Dict({obs_key: env.observation_space(env_params)})
    act_space = env.action_space(env_params)
    num_envs = int(cfg.env.num_envs)
    algo_name = str(cfg.algo.name)
    pop = PopulationSpec.from_cfg(cfg, "ppo" if algo_name.startswith("ppo") else "sac")
    if member is not None and not pop.enabled:
        raise ValueError("--member replay needs a population dump (algo.population in the dumped config)")
    if member is not None and not 0 <= int(member) < pop.size:
        raise ValueError(f"--member {member} out of range for population size {pop.size}")

    def pop_template(template):
        """Population dumps stage the stacked carry: stack K structure copies."""
        if not pop.enabled:
            return template
        return jax.tree.map(lambda x: jnp.stack([x] * pop.size), template)

    env_state0, obs0 = reset_envs(env, env_params, num_envs, jax.random.PRNGKey(0))

    if algo_name.startswith("ppo"):
        from sheeprl_tpu.algos.ppo.agent import build_agent
        from sheeprl_tpu.algos.ppo.ppo import PPOTrainFns

        agent, params0 = build_agent(ctx, act_space, obs_space, cfg)
        raw = replay_blackbox.load_state(dump_dir)
        num_updates = int(raw["statics"].get("num_updates", 1))
        fns = PPOTrainFns(
            ctx, agent, cfg, [obs_key], num_updates, inject_lr=pop.enabled and pop.sweeps_lr("optimizer.lr")
        )
        template = {
            "params": params0,
            "opt_state": fns.opt.init(params0),
            "env_state": env_state0,
            "obs": obs0,
            "key": jax.random.PRNGKey(0),
            "episode_stats": init_episode_stats(num_envs),
        }
        state = replay_blackbox.load_state(dump_dir, {"carry": jax.device_get(pop_template(template))})
        iteration = make_ppo_anakin_iteration(env, env_params, agent, fns, cfg, obs_key)
        scalars = state.get("scalars", {})
        clip = scalars.get("clip_coef", cfg.algo.clip_coef)
        ent = scalars.get("ent_coef", cfg.algo.ent_coef)
        staged = ctx.replicate(state["carry"])
        if pop.enabled and member is None:
            clip = np.broadcast_to(np.asarray(clip, np.float32), (pop.size,))
            ent = np.broadcast_to(np.asarray(ent, np.float32), (pop.size,))
            carry, metrics = jax.jit(population_transform(iteration, pop.vectorize, n_args=2))(
                staged, jnp.asarray(clip), jnp.asarray(ent)
            )
        else:
            if member is not None:
                staged = slice_member(staged, int(member))
                clip = np.reshape(np.broadcast_to(np.asarray(clip, np.float64), (pop.size,)), -1)[int(member)]
                ent = np.reshape(np.broadcast_to(np.asarray(ent, np.float64), (pop.size,)), -1)[int(member)]
            carry, metrics = jax.jit(iteration)(staged, float(clip), float(ent))
    else:
        from sheeprl_tpu.algos.sac.agent import build_agent
        from sheeprl_tpu.data.device_buffer import DeviceTransitionRing, resolve_store_dtype

        actor, critic, params0 = build_agent(ctx, act_space, obs_space, cfg)
        obs_dim = int(np.prod(obs_space[obs_key].shape))
        act_dim = int(np.prod(act_space.shape))
        capacity = max(int(cfg.buffer.size) // max(num_envs, 1), 1)
        ring = DeviceTransitionRing(
            capacity,
            num_envs,
            {
                "obs": ((obs_dim,), jnp.float32),
                "next_obs": ((obs_dim,), jnp.float32),
                "actions": ((act_dim,), jnp.float32),
                "rewards": ((1,), jnp.float32),
                "dones": ((1,), jnp.float32),
            },
            store_dtype=resolve_store_dtype(cfg.buffer.get("store_dtype")),
        )
        inject = tuple(n for n in ("actor", "critic", "alpha") if f"{n}.optimizer.lr" in pop.sweep)
        actor_opt, critic_opt, alpha_opt, builder = make_sac_anakin_dispatch(
            env, env_params, actor, critic, cfg, act_space, ring, int(cfg.algo.per_rank_batch_size),
            inject_lr=inject,
        )
        template = {
            "params": params0,
            "opt_state": {
                "actor": actor_opt.init(params0["actor"]),
                "critic": critic_opt.init(params0["critic"]),
                "alpha": alpha_opt.init(params0["log_alpha"]),
            },
            "env_state": env_state0,
            "obs": obs0,
            "ring": ring.arrays,
            "rows_added": jnp.zeros((), jnp.int32),
            "gstep": jnp.zeros((), jnp.int32),
            "key": jax.random.PRNGKey(0),
            "episode_stats": init_episode_stats(num_envs),
        }
        state = replay_blackbox.load_state(dump_dir, {"carry": jax.device_get(pop_template(template))})
        grad_per_step = int(round(float(cfg.algo.replay_ratio)))
        program = builder(1, grad_per_step, True)
        staged = ctx.replicate(state["carry"])
        if pop.enabled and member is None:
            carry, metrics = jax.jit(population_transform(program, pop.vectorize))(staged)
        else:
            if member is not None:
                staged = slice_member(staged, int(member))
            carry, metrics = jax.jit(program)(staged)

    host_metrics = jax.device_get(metrics)
    import optax

    out = {
        "metrics": host_metrics,
        "new_param_norm": float(jax.device_get(optax.global_norm(carry["params"]))),
    }
    if member is not None:
        out["member"] = int(member)
    return out


def lower_for_audit():
    """IR-audit hook (``python -m sheeprl_tpu.analysis.ir``): BOTH Anakin
    dispatch programs — the fused PPO iteration (env scan + GAE + the unchanged
    ``PPOTrainFns.train_fn``) and the fused SAC dispatch (env step + ring write +
    in-jit-sampled gradient steps) — each as the DONATED jit the engine
    dispatches, at tiny vmapped-env shapes."""
    from sheeprl_tpu.algos.ppo.agent import build_agent as build_ppo_agent
    from sheeprl_tpu.algos.ppo.ppo import PPOTrainFns
    from sheeprl_tpu.algos.sac.agent import build_agent as build_sac_agent
    from sheeprl_tpu.analysis.ir.synth import compose_tiny, tiny_ctx
    from sheeprl_tpu.analysis.ir.types import AuditEntry
    from sheeprl_tpu.data.device_buffer import DeviceTransitionRing, resolve_store_dtype

    entries = []

    # ------------------------------------------------------------- PPO dispatch
    cfg = compose_tiny(
        [
            "exp=ppo",
            "env=jax_cartpole",
            "algo.anakin=True",
            "algo.mlp_keys.encoder=[state]",
            "algo.rollout_steps=4",
            "algo.per_rank_batch_size=4",
            "algo.update_epochs=1",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.encoder.mlp_features_dim=8",
            "env.num_envs=2",
        ]
    )
    ctx = tiny_ctx(cfg)
    env, env_params = anakin_env(cfg)
    obs_key = anakin_mlp_key(cfg)
    obs_space = gym.spaces.Dict({obs_key: env.observation_space(env_params)})
    act_space = env.action_space(env_params)
    agent, params = build_ppo_agent(ctx, act_space, obs_space, cfg)
    num_envs = int(cfg.env.num_envs)
    fns = PPOTrainFns(ctx, agent, cfg, [obs_key], num_updates=4)
    iteration = make_ppo_anakin_iteration(env, env_params, agent, fns, cfg, obs_key)
    dispatch = jax.jit(iteration, donate_argnums=(0,))
    env_state, obs0 = reset_envs(env, env_params, num_envs, jax.random.PRNGKey(1))
    carry = {
        "params": params,
        "opt_state": fns.opt.init(params),
        "env_state": env_state,
        "obs": obs0,
        "key": jax.random.PRNGKey(0),
        "episode_stats": init_episode_stats(num_envs),
    }
    entries.append(
        AuditEntry(
            name="anakin/ppo_dispatch",
            fn=dispatch,
            args=(carry, 0.2, 0.0),
            covers=("anakin_ppo",),
            precision=str(cfg.mesh.precision),
        )
    )

    # Population variant (K=2, default member-scan mode): the same iteration
    # lifted over the member axis — audited as its own donated program because
    # the member axis must thread through every carry consumer without breaking
    # the donation contract (IR001) or blowing the compile-memory budget (IR006).
    from sheeprl_tpu.engine.population import population_transform

    pop_carry = jax.tree.map(lambda x: jnp.stack([x, x]), carry)
    pop_dispatch = jax.jit(population_transform(iteration, vectorize=False, n_args=2), donate_argnums=(0,))
    entries.append(
        AuditEntry(
            name="anakin/ppo_pop_dispatch",
            fn=pop_dispatch,
            args=(pop_carry, jnp.full((2,), 0.2, jnp.float32), jnp.zeros((2,), jnp.float32)),
            covers=("anakin_ppo_pop",),
            precision=str(cfg.mesh.precision),
        )
    )

    # ------------------------------------------------------------- SAC dispatch
    cfg = compose_tiny(
        [
            "exp=sac",
            "env=jax_pendulum",
            "algo.anakin=True",
            "algo.mlp_keys.encoder=[state]",
            "algo.hidden_size=8",
            "algo.per_rank_batch_size=4",
            "algo.replay_ratio=1",
            "env.num_envs=2",
            "buffer.size=64",
        ]
    )
    ctx = tiny_ctx(cfg)
    env, env_params = anakin_env(cfg)
    mlp_key = anakin_mlp_key(cfg)
    obs_space_box = env.observation_space(env_params)
    act_space = env.action_space(env_params)
    obs_space = gym.spaces.Dict({mlp_key: obs_space_box})
    actor, critic, params = build_sac_agent(ctx, act_space, obs_space, cfg)
    params = jax.tree.map(jnp.copy, params)  # donation safety (critic_target aliases)
    num_envs = int(cfg.env.num_envs)
    obs_dim = int(np.prod(obs_space_box.shape))
    act_dim = int(np.prod(act_space.shape))
    capacity = max(int(cfg.buffer.size) // max(num_envs, 1), 1)
    ring = DeviceTransitionRing(
        capacity,
        num_envs,
        {
            "obs": ((obs_dim,), jnp.float32),
            "next_obs": ((obs_dim,), jnp.float32),
            "actions": ((act_dim,), jnp.float32),
            "rewards": ((1,), jnp.float32),
            "dones": ((1,), jnp.float32),
        },
        store_dtype=resolve_store_dtype(cfg.buffer.get("store_dtype")),
    )
    actor_opt, critic_opt, alpha_opt, builder = make_sac_anakin_dispatch(
        env, env_params, actor, critic, cfg, act_space, ring, int(cfg.algo.per_rank_batch_size)
    )
    env_state, obs0 = reset_envs(env, env_params, num_envs, jax.random.PRNGKey(1))
    carry = {
        "params": params,
        "opt_state": {
            "actor": actor_opt.init(params["actor"]),
            "critic": critic_opt.init(params["critic"]),
            "alpha": alpha_opt.init(params["log_alpha"]),
        },
        "env_state": env_state,
        "obs": obs0,
        "ring": ring.arrays,
        "rows_added": jnp.zeros((), jnp.int32),
        "gstep": jnp.zeros((), jnp.int32),
        "key": jax.random.PRNGKey(0),
        "episode_stats": init_episode_stats(num_envs),
    }
    dispatch = jax.jit(builder(2, 1, True), donate_argnums=(0,))
    entries.append(
        AuditEntry(
            name="anakin/sac_dispatch",
            fn=dispatch,
            args=(carry,),
            covers=("anakin_sac",),
            precision=str(cfg.mesh.precision),
        )
    )

    # Population variant (K=2): ring arrays + counters + params all gain the
    # member axis; the fused env-step/ring-write/update program is unchanged.
    pop_carry = jax.tree.map(lambda x: jnp.stack([x, x]), carry)
    pop_dispatch = jax.jit(population_transform(builder(2, 1, True), vectorize=False), donate_argnums=(0,))
    entries.append(
        AuditEntry(
            name="anakin/sac_pop_dispatch",
            fn=pop_dispatch,
            args=(pop_carry,),
            covers=("anakin_sac_pop",),
            precision=str(cfg.mesh.precision),
        )
    )

    # ----------------------------------------------------- bf16 algo.precision
    # The same two dispatch programs with mesh.precision pinned to fp32 and the
    # algo.precision=bf16 knob doing ALL the work — IR002 then proves the
    # algo-level override alone puts bf16 on the dots (params stay f32; the
    # existing entries above already exercise mesh-inherited bf16-mixed).
    cfg = compose_tiny(
        [
            "exp=ppo",
            "env=jax_cartpole",
            "algo.anakin=True",
            "algo.mlp_keys.encoder=[state]",
            "algo.rollout_steps=4",
            "algo.per_rank_batch_size=4",
            "algo.update_epochs=1",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.encoder.mlp_features_dim=8",
            "env.num_envs=2",
            "mesh.precision=fp32",
            "algo.precision=bf16",
        ]
    )
    ctx = tiny_ctx(cfg)
    env, env_params = anakin_env(cfg)
    obs_key = anakin_mlp_key(cfg)
    obs_space = gym.spaces.Dict({obs_key: env.observation_space(env_params)})
    act_space = env.action_space(env_params)
    agent, params = build_ppo_agent(ctx, act_space, obs_space, cfg)
    num_envs = int(cfg.env.num_envs)
    fns = PPOTrainFns(ctx, agent, cfg, [obs_key], num_updates=4)
    iteration = make_ppo_anakin_iteration(env, env_params, agent, fns, cfg, obs_key)
    env_state, obs0 = reset_envs(env, env_params, num_envs, jax.random.PRNGKey(1))
    carry = {
        "params": params,
        "opt_state": fns.opt.init(params),
        "env_state": env_state,
        "obs": obs0,
        "key": jax.random.PRNGKey(0),
        "episode_stats": init_episode_stats(num_envs),
    }
    entries.append(
        AuditEntry(
            name="anakin/ppo_dispatch_bf16",
            fn=jax.jit(iteration, donate_argnums=(0,)),
            args=(carry, 0.2, 0.0),
            covers=("anakin_ppo_bf16",),
            precision="bf16",
        )
    )

    cfg = compose_tiny(
        [
            "exp=sac",
            "env=jax_pendulum",
            "algo.anakin=True",
            "algo.mlp_keys.encoder=[state]",
            "algo.hidden_size=8",
            "algo.per_rank_batch_size=4",
            "algo.replay_ratio=1",
            "env.num_envs=2",
            "buffer.size=64",
            "mesh.precision=fp32",
            "algo.precision=bf16",
        ]
    )
    ctx = tiny_ctx(cfg)
    env, env_params = anakin_env(cfg)
    mlp_key = anakin_mlp_key(cfg)
    obs_space_box = env.observation_space(env_params)
    act_space = env.action_space(env_params)
    obs_space = gym.spaces.Dict({mlp_key: obs_space_box})
    actor, critic, params = build_sac_agent(ctx, act_space, obs_space, cfg)
    params = jax.tree.map(jnp.copy, params)  # donation safety (critic_target aliases)
    num_envs = int(cfg.env.num_envs)
    obs_dim = int(np.prod(obs_space_box.shape))
    act_dim = int(np.prod(act_space.shape))
    capacity = max(int(cfg.buffer.size) // max(num_envs, 1), 1)
    ring = DeviceTransitionRing(
        capacity,
        num_envs,
        {
            "obs": ((obs_dim,), jnp.float32),
            "next_obs": ((obs_dim,), jnp.float32),
            "actions": ((act_dim,), jnp.float32),
            "rewards": ((1,), jnp.float32),
            "dones": ((1,), jnp.float32),
        },
        store_dtype=resolve_store_dtype(cfg.buffer.get("store_dtype")),
    )
    actor_opt, critic_opt, alpha_opt, builder = make_sac_anakin_dispatch(
        env, env_params, actor, critic, cfg, act_space, ring, int(cfg.algo.per_rank_batch_size)
    )
    env_state, obs0 = reset_envs(env, env_params, num_envs, jax.random.PRNGKey(1))
    carry = {
        "params": params,
        "opt_state": {
            "actor": actor_opt.init(params["actor"]),
            "critic": critic_opt.init(params["critic"]),
            "alpha": alpha_opt.init(params["log_alpha"]),
        },
        "env_state": env_state,
        "obs": obs0,
        "ring": ring.arrays,
        "rows_added": jnp.zeros((), jnp.int32),
        "gstep": jnp.zeros((), jnp.int32),
        "key": jax.random.PRNGKey(0),
        "episode_stats": init_episode_stats(num_envs),
    }
    entries.append(
        AuditEntry(
            name="anakin/sac_dispatch_bf16",
            fn=jax.jit(builder(2, 1, True), donate_argnums=(0,)),
            args=(carry,),
            covers=("anakin_sac_bf16",),
            precision="bf16",
        )
    )
    return entries
