"""Population Anakin: the member axis for multi-seed / multi-hyperparameter runs.

Podracer (arxiv 2104.06272) trains "multiple independent agents per chip" by
mapping the whole agent+env loop over a population axis; ROADMAP item 4 names it
the cheapest scenario-diversity multiplier and the fix for the single-seed
evidence weakness.  This module is the engine-side machinery: with
``algo.population.size=K`` the Anakin engine stacks K members' ENTIRE carries —
env states, agent params, optimizer state, :class:`~sheeprl_tpu.data.
device_buffer.DeviceTransitionRing` arrays, PRNG keys, episode/health
accumulators — under one leading member axis and trains all of them in ONE
donated jitted dispatch, for both ``ppo_anakin`` and ``sac_anakin``.

Two member-axis execution modes, one program shape:

* ``vectorize=False`` (default): the member axis runs through ``jax.lax.map`` —
  a ``lax.scan`` whose body is EXACTLY the single-member program, so every
  member is bit-identical to the run a standalone dispatch would produce
  (``tests/test_engine/test_population.py`` pins it member-for-member).  On a
  host CPU this is also the fastest mode: the per-dispatch and per-scan
  overheads amortize across members (the ``anakin_population_steps_per_sec``
  bench records per-member efficiency).
* ``vectorize=True``: the member axis runs through ``jax.vmap`` — the classic
  Podracer layout that batches all members' tensor ops into wide kernels for
  parallel hardware (TPU/GPU).  XLA may fuse the batched ops differently from
  the unbatched program (observed at ~1e-8 on CPU matvec chains), so this mode
  trades the bitwise guarantee for utilization; statistically it is the same
  training run.

``algo.population.sweep`` maps named scalar hyperparameters across members on
top of the seed axis (``{ent_coef: [0.0, 0.01, ...]}``; list length must equal
``size``).  Sweepable names per algorithm:

* PPO: ``clip_coef`` / ``ent_coef`` (already traced scalars of the fused
  iteration — they simply become ``[K]`` vectors) and ``optimizer.lr``;
* SAC: ``actor.optimizer.lr`` / ``critic.optimizer.lr`` / ``alpha.optimizer.lr``.

Learning rates cannot become traced arguments of the existing update closures
(optax bakes them into ``opt.update``), so swept learning rates ride the
*optimizer state*: the optimizer is built with ``optax.inject_hyperparams`` and
each member's ``opt_state`` carries its own ``learning_rate`` leaf
(:func:`set_injected_lr`) — the vmapped-by-hyperparameter optimizer init.  The
update program stays identical across members.

PRNG contract (:func:`member_keys`): member 0 continues the run's base stream
unchanged — so a population of one reproduces the plain engine bit-for-bit —
and member ``m > 0`` folds its index into the stream (``fold_in(base, m)``),
giving every member an independent, reproducible seed.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Mapping, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: sweepable hyperparameter names per Anakin algorithm family (see module docs)
SWEEPABLE = {
    "ppo": ("clip_coef", "ent_coef", "optimizer.lr"),
    "sac": ("actor.optimizer.lr", "critic.optimizer.lr", "alpha.optimizer.lr"),
}


def _flatten(prefix: str, node: Any, out: Dict[str, Any]) -> None:
    if isinstance(node, Mapping):
        for k, v in node.items():
            _flatten(f"{prefix}.{k}" if prefix else str(k), v, out)
    else:
        out[prefix] = node


@dataclass(frozen=True)
class PopulationSpec:
    """Validated ``algo.population`` config: member count, execution mode and the
    flattened sweep table (``name -> (v_0, ..., v_{K-1})``)."""

    size: int = 1
    vectorize: bool = False
    sweep: Dict[str, Tuple[float, ...]] = field(default_factory=dict)

    @property
    def enabled(self) -> bool:
        """The engine takes the population path for K > 1 or any sweep (a sweep
        of length 1 is a valid single-member population)."""
        return self.size > 1 or bool(self.sweep)

    @classmethod
    def from_cfg(cls, cfg, algo: str) -> "PopulationSpec":
        pop = cfg.algo.get("population", {}) or {}
        size = int(pop.get("size", 1) or 1)
        if size < 1:
            raise ValueError(f"algo.population.size must be >= 1; got {size}")
        vectorize = bool(pop.get("vectorize", False))
        raw = pop.get("sweep", {}) or {}
        flat: Dict[str, Any] = {}
        _flatten("", raw, flat)
        allowed = SWEEPABLE.get(algo, ())
        sweep: Dict[str, Tuple[float, ...]] = {}
        for name, values in flat.items():
            if name not in allowed:
                raise ValueError(
                    f"algo.population.sweep.{name} is not sweepable for {algo!r}; "
                    f"supported: {list(allowed)}"
                )
            if not isinstance(values, (list, tuple)):
                raise ValueError(
                    f"algo.population.sweep.{name} must be a per-member list; got {values!r}"
                )
            if len(values) != size:
                raise ValueError(
                    f"algo.population.sweep.{name} has {len(values)} values but "
                    f"algo.population.size={size}: one value per member required"
                )
            sweep[name] = tuple(float(v) for v in values)
        return cls(size=size, vectorize=vectorize, sweep=sweep)

    def values(self, name: str, default: float) -> List[float]:
        """Per-member values for hyperparameter ``name``: the sweep row, or the
        config default broadcast across members."""
        if name in self.sweep:
            return list(self.sweep[name])
        return [float(default)] * self.size

    def sweeps_lr(self, *names: str) -> bool:
        return any(n in self.sweep for n in names)


def member_keys(base: jax.Array, size: int) -> jax.Array:
    """``[K, 2]`` per-member PRNG keys.  Member 0 continues the base stream
    unchanged (``population.size=1`` then reproduces a plain Anakin run
    bit-for-bit); member m > 0 gets ``fold_in(base, m)``."""
    keys = [base] + [jax.random.fold_in(base, m) for m in range(1, size)]
    return jnp.stack(keys)


def stack_members(carries: Sequence[Any]) -> Any:
    """Stack per-member carries under a leading member axis (leaf-wise)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *carries)


def slice_member(tree: Any, member: int) -> Any:
    """Member ``member``'s slice of a population pytree (drops the member axis)."""
    return jax.tree.map(lambda x: x[member], tree)


def population_transform(fn: Callable, vectorize: bool, n_args: int = 0) -> Callable:
    """Lift a single-member program ``fn(carry, *scalars)`` over a leading member
    axis on the carry AND every scalar argument (each becomes a ``[K]`` vector).

    ``vectorize=False`` maps members through ``lax.scan`` (``jax.lax.map``): the
    body jaxpr is the unbatched program, so each member computes bit-identically
    to a standalone dispatch.  ``vectorize=True`` batches members with
    ``jax.vmap`` for parallel hardware.  Both shapes are ONE jitted dispatch.
    """
    if vectorize:
        return jax.vmap(fn, in_axes=(0,) * (1 + n_args))

    def mapped(carry, *scalars):
        return jax.lax.map(lambda xs: fn(*xs), (carry, *scalars))

    return mapped


def set_injected_lr(opt_state: Any, lr: float) -> Any:
    """Rewrite every ``optax.inject_hyperparams`` state's ``learning_rate`` leaf
    inside ``opt_state`` (recursing through chain tuples/lists only — never into
    param dicts, whose leaves are arrays, not optimizer states).  This is how a
    swept learning rate becomes per-member: init the shared injected optimizer
    once per member, then stamp the member's rate into its own state."""
    def rewrite(state):
        # Duck-typed: optax spells the state InjectHyperparamsState or
        # InjectStatefulHyperparamsState depending on version — both are
        # NamedTuples with a ``hyperparams`` dict field.
        if hasattr(state, "_fields") and "hyperparams" in getattr(state, "_fields", ()):
            hp = dict(state.hyperparams)
            if "learning_rate" not in hp:
                raise ValueError("inject_hyperparams state has no learning_rate to sweep")
            hp["learning_rate"] = jnp.asarray(lr, jnp.asarray(hp["learning_rate"]).dtype)
            return state._replace(hyperparams=hp)
        if isinstance(state, tuple):
            rewritten = tuple(rewrite(s) for s in state)
            return type(state)(*rewritten) if hasattr(state, "_fields") else rewritten
        if isinstance(state, list):
            return [rewrite(s) for s in state]
        return state

    out = rewrite(opt_state)
    if all(l1 is l2 for l1, l2 in zip(jax.tree.leaves(out), jax.tree.leaves(opt_state))):
        raise ValueError(
            "no inject_hyperparams learning_rate found in the optimizer state: "
            "build the optimizer with inject_lr=True to sweep its learning rate"
        )
    return out


# ------------------------------------------------------------------- metrics
#: key prefixes whose population "best" is the MINIMUM across members; every
#: other reduced namespace (Rewards/, Game/, Episodes/) takes the maximum.
#: Health/* and Params/* get member rows + median only (no meaningful "best").
_BEST_MIN_PREFIXES = ("Loss/",)
_BEST_MAX_PREFIXES = ("Rewards/", "Game/", "Episodes/")


def population_rows(key: str, member_values: np.ndarray) -> Dict[str, float]:
    """The drained ``Population/*`` rows for one metric: per-member values plus
    the cross-member ``median`` and (where a direction exists) ``best``.

    Reductions, per namespace (documented contract — howto/population.md):

    * ``Loss/*``            — best = min over members;
    * ``Rewards/*`` / ``Game/*`` / ``Episodes/*`` — best = max over members;
    * everything else (``Health/*``, ``Params/*``, ...) — members + median only.
    """
    vals = np.asarray(member_values, np.float64).reshape(-1)
    # non-finite member entries mean "no data this window" (e.g. no finished
    # episode for that member) — skip the row rather than logging NaN
    out = {f"Population/{key}/member_{m}": float(v) for m, v in enumerate(vals) if np.isfinite(v)}
    finite = vals[np.isfinite(vals)]
    if finite.size:
        out[f"Population/{key}/median"] = float(np.median(finite))
        if key.startswith(_BEST_MIN_PREFIXES):
            out[f"Population/{key}/best"] = float(finite.min())
        elif key.startswith(_BEST_MAX_PREFIXES):
            out[f"Population/{key}/best"] = float(finite.max())
    return out
