"""Shared acting/update engine (ROADMAP item 1): the Anakin training mode fuses
vmapped on-device envs, acting, replay-ring writes and the gradient update into
one donated jitted ``lax.scan`` dispatch — see :mod:`sheeprl_tpu.engine.anakin`."""

from sheeprl_tpu.engine.anakin import anakin_enabled, ppo_anakin, sac_anakin

__all__ = ["anakin_enabled", "ppo_anakin", "sac_anakin"]
