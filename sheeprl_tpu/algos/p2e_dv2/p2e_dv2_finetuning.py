"""P2E-DV2 finetuning (reference: ``/root/reference/sheeprl/algos/p2e_dv2/p2e_dv2_finetuning.py``).

Loads the exploration checkpoint and finetunes the task policy with the standard
DreamerV2 train step applied to the ``{world_model, actor_task, critic_task,
target_critic_task}`` slice; the player switches from the exploration to the task actor
at the first gradient step."""

from __future__ import annotations

import time
from pathlib import Path
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v2.agent import exploration_amount
from sheeprl_tpu.algos.dreamer_v2.dreamer_v2 import make_buffer, make_train_step as make_dv2_train_step
from sheeprl_tpu.algos.p2e import load_exploration_config
from sheeprl_tpu.algos.p2e_dv2.agent import PlayerState, build_agent, make_player_step, parse_actions_dim
from sheeprl_tpu.algos.p2e_dv2.p2e_dv2_exploration import make_train_step as make_expl_train_step
from sheeprl_tpu.algos.p2e_dv2.utils import AGGREGATOR_KEYS, prepare_obs, test
from sheeprl_tpu.checkpoint.manager import CheckpointManager
from sheeprl_tpu.fault.guard import TrainingGuard
from sheeprl_tpu.config.core import save_config
from sheeprl_tpu.data.device_buffer import make_device_replay
from sheeprl_tpu.obs import TrainingMonitor
from sheeprl_tpu.obs.health import replay_age_metrics
from sheeprl_tpu.utils.env import make_vector_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, record_episode_stats
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio


@register_algorithm(name="p2e_dv2_finetuning")
def main(ctx, cfg, exploration_cfg=None) -> None:
    if exploration_cfg is None:
        exploration_cfg = load_exploration_config(cfg)
    rank = ctx.process_index
    log_dir = get_log_dir(cfg)
    if ctx.is_global_zero:
        save_config(cfg, Path(log_dir) / "config.yaml")
    logger = get_logger(cfg, log_dir)
    monitor = TrainingMonitor(cfg, log_dir)

    envs = make_vector_env(cfg, cfg.seed, rank, log_dir if cfg.env.capture_video else None)
    obs_space = envs.single_observation_space
    act_space = envs.single_action_space
    is_continuous, actions_dim = parse_actions_dim(act_space)
    act_dim_sum = int(sum(actions_dim))
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    num_envs = cfg.env.num_envs
    world = jax.process_count()

    world_model, actor, critic, ensemble_mlp, params, _ = build_agent(
        ctx, actions_dim, is_continuous, cfg, obs_space
    )
    _, expl_init_opt = make_expl_train_step(world_model, actor, critic, ensemble_mlp, cfg, cnn_keys, mlp_keys)
    expl_opt_template = expl_init_opt(params)
    expl_opt_host = jax.device_get(expl_opt_template)

    train_step, init_opt_states = make_dv2_train_step(world_model, actor, critic, cfg, cnn_keys, mlp_keys)
    # One jitted scan per iteration's gradient block (utils/blocks.py); the hard
    # target copy tests the count BEFORE the increment (fires on the first step).
    def _block_step(carry, batch, key, update_target):
        params, opt_states = carry
        params, opt_states, metrics = train_step(params, opt_states, batch, key, update_target)
        return (params, opt_states), metrics

    def task_view(p):
        return {
            "world_model": p["world_model"],
            "actor": p["actor_task"],
            "critic": p["critic_task"],
            "target_critic": p["target_critic_task"],
        }

    def merge_task_view(p, view):
        p = dict(p)
        p["world_model"] = view["world_model"]
        p["actor_task"] = view["actor"]
        p["critic_task"] = view["critic"]
        p["target_critic_task"] = view["target_critic"]
        return p

    resume_from = cfg.checkpoint.get("resume_from")
    ckpt_to_load = resume_from or cfg.checkpoint.exploration_ckpt_path
    state = CheckpointManager.load(
        ckpt_to_load,
        templates={"params": jax.device_get(params), "opt_states": expl_opt_host},
    )
    params = ctx.replicate(state["params"])
    loaded_opts = state["opt_states"]
    opt_states = ctx.replicate(
        {
            "world_model": loaded_opts["world_model"],
            "actor": loaded_opts["actor_task"],
            "critic": loaded_opts["critic_task"],
        }
    )

    player_step = make_player_step(world_model, actor, actions_dim, is_continuous)
    player_jit = jax.jit(player_step, static_argnames=("greedy",))
    actor_type = cfg.algo.player.get("actor_type", "exploration")
    stoch_size = cfg.algo.world_model.stochastic_size * cfg.algo.world_model.discrete_size
    rec_size = cfg.algo.world_model.recurrent_model.recurrent_state_size

    def player_params():
        key = "actor_exploration" if actor_type == "exploration" else "actor_task"
        return {"world_model": params["world_model"], "actor": params[key]}

    def player_state_init(n: int) -> PlayerState:
        return PlayerState(
            recurrent_state=jnp.zeros((n, rec_size)),
            stochastic_state=jnp.zeros((n, stoch_size)),
            actions=jnp.zeros((n, act_dim_sum)),
        )

    rb = make_buffer(cfg, num_envs, obs_keys, log_dir, rank, world)
    rb.seed(cfg.seed + rank)
    if (resume_from or cfg.buffer.get("load_from_exploration")) and "rb" in state:
        rb.load_state_dict(state["rb"])

    # Device-vs-host replay data path, one shared implementation
    # (data/device_buffer.py); the mirror is rebuilt from the restored host buffer
    # (resume or exploration hand-off) before training starts.
    dispatcher, mirror, prefetcher, _run_block, rb_add = make_device_replay(
        ctx,
        cfg,
        rb,
        cnn_keys,
        mlp_keys,
        obs_space,
        act_dim_sum,
        _block_step,
        dispatcher_kwargs=dict(
            target_update_freq=cfg.algo.critic.per_rank_target_network_update_freq, count_offset=0
        ),
        require_sequential=True,
    )
    if mirror is not None and len(rb) > 0:
        mirror.load_from(rb)

    aggregator = MetricAggregator(cfg.metric.aggregator.get("metrics", {}))
    aggregator.keep(AGGREGATOR_KEYS | set(cfg.metric.aggregator.get("metrics", {})))
    ckpt_manager = CheckpointManager(Path(log_dir) / "checkpoints", keep_last=cfg.checkpoint.keep_last)
    guard = TrainingGuard(cfg, log_dir)
    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)

    batch_size = cfg.algo.per_rank_batch_size
    seq_len = cfg.algo.per_rank_sequence_length
    policy_steps_per_iter = num_envs * world * cfg.env.action_repeat
    total_steps = int(cfg.algo.total_steps)
    num_iters = max(total_steps // policy_steps_per_iter, 1) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    target_update_freq = cfg.algo.critic.per_rank_target_network_update_freq
    expl_cfg = cfg.algo.actor

    start_iter = 1
    policy_step = 0
    last_log = 0
    last_checkpoint = 0
    cumulative_grad_steps = 0
    if resume_from:
        ratio.load_state_dict(state["ratio"])
        start_iter = state["iter_num"] + 1
        policy_step = state["policy_step"]
        last_log = state.get("last_log", 0)
        last_checkpoint = state.get("last_checkpoint", 0)
        cumulative_grad_steps = state.get("cumulative_grad_steps", 0)
        learning_starts += start_iter
        actor_type = state.get("actor_type", actor_type)

    def _obs_row(o, idxs=None):
        row = {}
        for k in cnn_keys:
            v = np.asarray(o[k]) if idxs is None else np.asarray(o[k])[idxs]
            row[k] = v.reshape(1, v.shape[0], -1, *v.shape[-2:])
        for k in mlp_keys:
            v = np.asarray(o[k], dtype=np.float32) if idxs is None else np.asarray(o[k], dtype=np.float32)[idxs]
            row[k] = v.reshape(1, v.shape[0], -1)
        return row

    obs, _ = envs.reset(seed=cfg.seed + rank)
    player_state = player_state_init(num_envs)
    step_data: Dict[str, np.ndarray] = _obs_row(obs)
    step_data["rewards"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["terminated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["truncated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["is_first"] = np.ones((1, num_envs, 1), np.float32)
    is_first_np = np.ones((num_envs, 1), dtype=np.float32)
    prefill_iters = max(learning_starts - 1, 0)

    for iter_num in range(start_iter, num_iters + 1):
        monitor.advance()
        env_t0 = time.perf_counter()
        expl_amount = exploration_amount(
            expl_cfg.get("expl_amount", 0.0), expl_cfg.get("expl_decay", 0.0), expl_cfg.get("expl_min", 0.0), policy_step
        )
        with timer("Time/env_interaction_time"):
            obs_t = prepare_obs(obs, cnn_keys, mlp_keys, num_envs)
            actions, stored, player_state = player_jit(
                player_params(), player_state, obs_t, jnp.asarray(is_first_np), ctx.local_rng(), jnp.asarray(expl_amount)
            )
            # ONE device_get for everything the host needs (per-array fetches
            # would each pay a transfer round trip on a remote accelerator).
            stored_np, acts_list = jax.device_get((stored, list(actions)))
            stored_actions = np.asarray(stored_np)
            acts_np = [np.asarray(a) for a in acts_list]
            if is_continuous:
                env_actions = acts_np[0]
            elif len(actions_dim) == 1:
                env_actions = acts_np[0].argmax(-1)
            else:
                env_actions = np.stack([a.argmax(-1) for a in acts_np], -1)

            step_data["actions"] = stored_actions.reshape(1, num_envs, -1)
            rb_add(step_data, validate_args=cfg.buffer.validate_args)
        env_time = time.perf_counter() - env_t0

        # Dispatch this iteration's gradient block BEFORE stepping the envs: the
        # device trains while the host walks the environments below (acting above
        # used the previous iteration's params, exactly as the eager ordering did).
        grad_steps = 0
        if iter_num >= learning_starts:
            # The player switches to the TASK actor at the first training iteration
            # (reference p2e finetuning :350-352).
            if actor_type != "task":
                actor_type = "task"
            grad_steps = ratio(
                (policy_step + policy_steps_per_iter - prefill_iters * policy_steps_per_iter) / world
            )
            if grad_steps > 0:
                view, opt_states = _run_block(
                    (task_view(params), opt_states), grad_steps, cumulative_grad_steps, stage_next=iter_num < num_iters
                )
                params = merge_task_view(params, view)
                cumulative_grad_steps += grad_steps

        env_t0 = time.perf_counter()
        with timer("Time/env_interaction_time"):
            next_obs, reward, terminated, truncated, info = envs.step(env_actions)
            if cfg.env.clip_rewards:
                reward = np.tanh(reward)
            done = np.logical_or(terminated, truncated)
            reward = np.asarray(reward, dtype=np.float32).reshape(num_envs, 1)

            real_next_obs = {k: np.asarray(next_obs[k]).copy() for k in obs_keys}
            if done.any() and "final_obs" in info:
                for i in np.nonzero(done)[0]:
                    if info["final_obs"][i] is not None:
                        for k in obs_keys:
                            real_next_obs[k][i] = np.asarray(info["final_obs"][i][k])

            step_data = _obs_row(next_obs)
            step_data["rewards"] = reward.reshape(1, num_envs, 1).copy()
            step_data["terminated"] = terminated.astype(np.float32).reshape(1, num_envs, 1)
            step_data["truncated"] = truncated.astype(np.float32).reshape(1, num_envs, 1)
            step_data["is_first"] = np.zeros((1, num_envs, 1), np.float32)

            done_idxs = np.nonzero(done)[0].tolist()
            if done_idxs:
                reset_data = _obs_row(real_next_obs, idxs=done_idxs)
                reset_data["rewards"] = step_data["rewards"][:, done_idxs]
                reset_data["terminated"] = step_data["terminated"][:, done_idxs]
                reset_data["truncated"] = step_data["truncated"][:, done_idxs]
                reset_data["actions"] = np.zeros((1, len(done_idxs), act_dim_sum), np.float32)
                reset_data["is_first"] = np.zeros_like(reset_data["terminated"])
                rb_add(reset_data, done_idxs, validate_args=cfg.buffer.validate_args)
                step_data["rewards"][:, done_idxs] = 0.0
                step_data["terminated"][:, done_idxs] = 0.0
                step_data["truncated"][:, done_idxs] = 0.0
                step_data["is_first"][:, done_idxs] = 1.0

            is_first_np = done.astype(np.float32).reshape(num_envs, 1)
            obs = next_obs
            policy_step += policy_steps_per_iter
            record_episode_stats(aggregator, info)
        env_time += time.perf_counter() - env_t0

        if logger is not None and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == num_iters or cfg.dry_run
        ):
            dispatcher.drain(aggregator)  # the window's only blocking device sync
            metrics = aggregator.compute()
            metrics.update(replay_age_metrics(rb))
            window_sps = dispatcher.pop_window_sps()
            if window_sps is not None:
                metrics["Time/sps_train"] = window_sps
            metrics["Time/sps_env_interaction"] = (
                policy_steps_per_iter / world / env_time if env_time > 0 else 0.0
            )
            metrics["Params/replay_ratio"] = (
                cumulative_grad_steps * world / policy_step if policy_step > 0 else 0.0
            )
            monitor.log_metrics(logger, metrics, policy_step)
            aggregator.reset()
            last_log = policy_step

        def save_ckpt():
            nonlocal last_checkpoint
            full_opts = dict(loaded_opts)
            on_device = jax.device_get(opt_states)
            full_opts["world_model"] = on_device["world_model"]
            full_opts["actor_task"] = on_device["actor"]
            full_opts["critic_task"] = on_device["critic"]
            ckpt_state = {
                "params": params,
                "opt_states": full_opts,
                "ratio": ratio.state_dict(),
                "iter_num": iter_num,
                "policy_step": policy_step,
                "last_log": last_log,
                "last_checkpoint": policy_step,
                "cumulative_grad_steps": cumulative_grad_steps,
                "actor_type": actor_type,
            }
            if cfg.buffer.checkpoint:
                ckpt_state["rb"] = rb.state_dict()
            path = ckpt_manager.save(policy_step, ckpt_state)
            last_checkpoint = policy_step
            return path

        if (
            cfg.checkpoint.every > 0
            and (policy_step - last_checkpoint) >= cfg.checkpoint.every
            or iter_num == num_iters
            and cfg.checkpoint.save_last
        ):
            # untrained entries keep the optimizer moments loaded from the exploration ckpt
            save_ckpt()
        guard.boundary(policy_step, save_ckpt)

    monitor.close()
    envs.close()
    if prefetcher is not None:
        prefetcher.close()
    if cfg.algo.run_test and ctx.is_global_zero:
        reward = test(
            player_step,
            {"world_model": params["world_model"], "actor": params["actor_task"]},
            player_state_init,
            ctx,
            cfg,
            log_dir,
        )
        if logger is not None:
            logger.log_metrics({"Test/cumulative_reward": reward}, policy_step)
    if logger is not None:
        logger.close()
