"""P2E-DV2 agent builder (reference: ``/root/reference/sheeprl/algos/p2e_dv2/agent.py``).

DreamerV2 stack + exploration actor, ONE exploration critic with a hard-copy target
(reference ``agent.py:118-147``), and a disagreement ensemble predicting the next
stochastic state with a unit-variance Gaussian likelihood."""

from __future__ import annotations

from typing import Any, Dict, Sequence

import gymnasium
import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v2.agent import (
    ActorV2,
    CriticV2,
    PlayerState,  # noqa: F401
    _xavier_normal_init,
    build_agent as dv2_build_agent,
    make_player_step,  # noqa: F401
)
from sheeprl_tpu.algos.dreamer_v3.agent import parse_actions_dim  # noqa: F401
from sheeprl_tpu.algos.p2e import build_ensembles


def build_agent(
    ctx,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
):
    world_model, actor, critic, dv2_params, latent_size = dv2_build_agent(
        ctx, actions_dim, is_continuous, cfg, obs_space
    )

    actor_expl_params = actor.init(ctx.rng(), jnp.zeros((1, latent_size)), ctx.rng())
    actor_expl_params = {"params": _xavier_normal_init(actor_expl_params["params"], ctx.rng())}
    critic_expl_params = critic.init(ctx.rng(), jnp.zeros((1, latent_size)))
    critic_expl_params = {"params": _xavier_normal_init(critic_expl_params["params"], ctx.rng())}

    wm_cfg = cfg.algo.world_model
    stoch_size = wm_cfg.stochastic_size * wm_cfg.discrete_size
    ens_cfg = cfg.algo.ensembles
    ensemble_mlp, ensemble_params = build_ensembles(
        ctx.rng(),
        n=ens_cfg.n,
        input_dim=int(sum(actions_dim)) + wm_cfg.recurrent_model.recurrent_state_size + stoch_size,
        output_dim=stoch_size,
        dense_units=ens_cfg.dense_units,
        mlp_layers=ens_cfg.mlp_layers,
        activation=cfg.algo.dense_act,
        layer_norm=cfg.algo.layer_norm,
        dtype=ctx.compute_dtype,
    )

    params = {
        "world_model": dv2_params["world_model"],
        "actor_task": dv2_params["actor"],
        "critic_task": dv2_params["critic"],
        "target_critic_task": dv2_params["target_critic"],
        "actor_exploration": ctx.replicate(actor_expl_params),
        "critic_exploration": ctx.replicate(critic_expl_params),
        "target_critic_exploration": ctx.replicate(jax.tree.map(lambda x: x, critic_expl_params)),
        "ensembles": ctx.replicate(ensemble_params),
    }
    return world_model, actor, critic, ensemble_mlp, params, latent_size
