"""P2E-DV2 evaluation (reference: ``algos/p2e_dv2/evaluate.py``)."""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.p2e_dv2.agent import PlayerState, build_agent, make_player_step, parse_actions_dim
from sheeprl_tpu.algos.p2e_dv2.utils import test
from sheeprl_tpu.checkpoint.manager import CheckpointManager
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms=["p2e_dv2_exploration", "p2e_dv2_finetuning"])
def evaluate_p2e_dv2(ctx, cfg: Dict[str, Any], ckpt_path: str) -> float:
    log_dir = get_log_dir(cfg)
    env = make_env(cfg, cfg.seed, 0, log_dir, "test")()
    obs_space = env.observation_space
    act_space = env.action_space
    env.close()
    is_continuous, actions_dim = parse_actions_dim(act_space)

    world_model, actor, critic, ensemble_mlp, params, _ = build_agent(
        ctx, actions_dim, is_continuous, cfg, obs_space
    )
    state = CheckpointManager.load(ckpt_path, templates={"params": jax.device_get(params)})
    params = ctx.replicate(state["params"])

    actor_type = cfg.algo.player.get("actor_type", "exploration")
    if "finetuning" in cfg.algo.name:
        actor_type = "task"
    actor_key = "actor_exploration" if actor_type == "exploration" else "actor_task"

    stoch_size = cfg.algo.world_model.stochastic_size * cfg.algo.world_model.discrete_size
    rec_size = cfg.algo.world_model.recurrent_model.recurrent_state_size
    act_dim_sum = int(sum(actions_dim))

    def player_state_init(n: int) -> PlayerState:
        return PlayerState(
            recurrent_state=jnp.zeros((n, rec_size)),
            stochastic_state=jnp.zeros((n, stoch_size)),
            actions=jnp.zeros((n, act_dim_sum)),
        )

    player_step = make_player_step(world_model, actor, actions_dim, is_continuous)
    player_view = {"world_model": params["world_model"], "actor": params[actor_key]}
    reward = test(player_step, player_view, player_state_init, ctx, cfg, log_dir)
    print(f"Test/cumulative_reward: {reward}")
    return reward
