"""DreamerV3 world-model loss (reference: ``/root/reference/sheeprl/algos/dreamer_v3/loss.py``).

Pure jnp.  The two-sided KL balancing with free nats (reference ``loss.py:63-75``) is the
heart of the algorithm — stop-gradient placement is exactly mirrored:
``dyn_loss = KL(sg(post) || prior)``, ``repr_loss = KL(post || sg(prior))``."""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp


def categorical_kl(post_logits: jax.Array, prior_logits: jax.Array) -> jax.Array:
    """KL over the last (discrete) axis, summed over the stochastic axis.
    Inputs ``[..., stoch, discrete]`` raw logits → output ``[...]``."""
    post_logp = jax.nn.log_softmax(post_logits, -1)
    prior_logp = jax.nn.log_softmax(prior_logits, -1)
    kl = (jnp.exp(post_logp) * (post_logp - prior_logp)).sum(-1)
    return kl.sum(-1)


def reconstruction_loss(
    observation_log_probs: jax.Array,  # [T, B] summed over obs keys
    reward_log_prob: jax.Array,  # [T, B]
    priors_logits: jax.Array,  # [T, B, stoch, discrete]
    posteriors_logits: jax.Array,  # [T, B, stoch, discrete]
    kl_dynamic: float = 0.5,
    kl_representation: float = 0.1,
    kl_free_nats: float = 1.0,
    kl_regularizer: float = 1.0,
    continue_log_prob: Optional[jax.Array] = None,  # [T, B]
    continue_scale_factor: float = 1.0,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    observation_loss = -observation_log_probs
    reward_loss = -reward_log_prob
    kl = categorical_kl(jax.lax.stop_gradient(posteriors_logits), priors_logits)
    dyn_loss = kl_dynamic * jnp.maximum(kl, kl_free_nats)
    repr_kl = categorical_kl(posteriors_logits, jax.lax.stop_gradient(priors_logits))
    repr_loss = kl_representation * jnp.maximum(repr_kl, kl_free_nats)
    kl_loss = dyn_loss + repr_loss
    if continue_log_prob is not None:
        continue_loss = continue_scale_factor * -continue_log_prob
    else:
        continue_loss = jnp.zeros_like(reward_loss)
    rec_loss = (kl_regularizer * kl_loss + observation_loss + reward_loss + continue_loss).mean()
    metrics = {
        "Loss/world_model_loss": rec_loss,
        "Loss/observation_loss": observation_loss.mean(),
        "Loss/reward_loss": reward_loss.mean(),
        "Loss/state_loss": kl_loss.mean(),
        "Loss/continue_loss": continue_loss.mean(),
        "State/kl": kl.mean(),
    }
    return rec_loss, metrics
