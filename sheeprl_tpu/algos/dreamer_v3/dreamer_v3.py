"""DreamerV3 training loop (reference: ``/root/reference/sheeprl/algos/dreamer_v3/dreamer_v3.py``).

TPU-first structure — the reference's hot loops (SURVEY §3.1) become scans inside ONE
jitted ``train_step``:

* the 64-step RSSM unroll (reference python loop ``dreamer_v3.py:134-145``) is a
  ``lax.scan`` inside the world-model loss;
* the 15-step imagination rollout (``:235-241``) is a ``lax.scan`` inside the actor
  loss (differentiable through the dynamics for the continuous/backprop objective);
* world-model, actor and critic optimizer steps + the EMA target-critic update +
  the ``Moments`` percentile-normalizer update all run in the same jit;
* gradient sync over the ``data`` mesh axis is GSPMD-inserted (batch sharded, params
  replicated, losses are global means) — no explicit collectives.

Environment interaction timeline matches the reference exactly
(``dreamer_v3.py:82-91``): ``obs[t]`` precedes ``action[t]``; stored actions are
shifted right by one inside the train step with a zero first action."""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.analysis.strict import maybe_inject_nonfinite, nan_scan, strict_enabled
from sheeprl_tpu.algos.dreamer_v3.agent import (
    PlayerState,
    WorldModel,
    build_agent,
    make_player_step,
    parse_actions_dim,
)
from sheeprl_tpu.algos.dreamer_v3.loss import reconstruction_loss
from sheeprl_tpu.algos.dreamer_v3.utils import (
    AGGREGATOR_KEYS,
    init_moments,
    prepare_obs,
    test,
    update_moments,
)
from sheeprl_tpu.algos.ppo.ppo import make_optimizer
from sheeprl_tpu.checkpoint.manager import CheckpointManager
from sheeprl_tpu.fault.guard import TrainingGuard
from sheeprl_tpu.config.core import save_config
from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, SequentialReplayBuffer
from sheeprl_tpu.data.device_buffer import make_device_replay
from sheeprl_tpu.distributions import (
    BernoulliSafeMode,
    Independent,
    MSEDistribution,
    OneHotCategorical,
    SymlogDistribution,
    TwoHotEncodingDistribution,
)
from sheeprl_tpu.obs import TrainingMonitor, flight_recorder
from sheeprl_tpu.obs.health import diagnostics, health_enabled, replay_age_metrics
from sheeprl_tpu.rollout import PipelinedPlayer, rollout_metrics
from sheeprl_tpu.utils.env import make_vector_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, make_aggregator, record_episode_stats
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio


def make_train_step(world_model, actor, critic, cfg, cnn_keys, mlp_keys, obs_shapes):
    """Build the single-jit train step closure."""
    wm_cfg = cfg.algo.world_model
    stoch = wm_cfg.stochastic_size
    discrete = wm_cfg.discrete_size
    stoch_size = stoch * discrete
    rec_size = wm_cfg.recurrent_model.recurrent_state_size
    horizon = cfg.algo.horizon
    gamma = cfg.algo.gamma
    lmbda = cfg.algo.lmbda
    ent_coef = cfg.algo.actor.ent_coef
    is_continuous = actor.is_continuous
    actions_dim = tuple(actor.actions_dim)
    tau = cfg.algo.critic.tau
    moments_cfg = cfg.algo.actor.moments

    wm_opt = make_optimizer(wm_cfg.optimizer, wm_cfg.clip_gradients)
    actor_opt = make_optimizer(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients)
    critic_opt = make_optimizer(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients)

    def init_opt_states(params):
        return {
            "world_model": wm_opt.init(params["world_model"]),
            "actor": actor_opt.init(params["actor"]),
            "critic": critic_opt.init(params["critic"]),
        }

    def train_step(params, opt_states, moments_state, data, key, update_target: bool):
        T, B = data["rewards"].shape[:2]
        k_wm, k_img, k_a0 = jax.random.split(key, 3)

        batch_obs = {k: data[k] for k in cnn_keys + mlp_keys}
        is_first = data["is_first"].at[0].set(1.0)
        batch_actions = jnp.concatenate([jnp.zeros_like(data["actions"][:1]), data["actions"][:-1]], 0)

        # ------------------------------------------------ world model update
        decoupled = wm_cfg.get("decoupled_rssm", False)

        def wm_loss_fn(wm_params):
            embed = world_model.apply(wm_params, batch_obs, method=WorldModel.encode)  # [T,B,E]

            if decoupled:
                # DecoupledRSSM (reference agent.py:501-593): q(z|o) has no recurrent
                # dependency, so the WHOLE posterior batch is one vectorized call and
                # only the prior chain runs in the scan.
                k_repr, k_scan = jax.random.split(k_wm)
                post_logits, post_samples = world_model.apply(
                    wm_params, embed, k_repr, method=WorldModel.representation_from_embed
                )
                posts = post_samples.reshape(T, B, -1)
                prev_posts = jnp.concatenate([jnp.zeros_like(posts[:1]), posts[:-1]], 0)

                def step(rec, x):
                    prev_post, action, first, k = x
                    rec, _, prior_logits = world_model.apply(
                        wm_params, prev_post, rec, action, first, k, method=WorldModel.dynamic
                    )
                    return rec, (rec, prior_logits)

                keys = jax.random.split(k_scan, T)
                # unroll: the per-step GRU work is tiny at batch B, so amortising the
                # loop structure over several steps keeps the MXU fed
                _, (recs, prior_logits) = jax.lax.scan(
                    step, jnp.zeros((B, rec_size)), (prev_posts, batch_actions, is_first, keys), unroll=8
                )
            else:

                def step(carry, x):
                    post, rec = carry
                    action, emb, first, k = x
                    rec, post, _, post_logits, prior_logits = world_model.apply(
                        wm_params, post, rec, action, emb, first, k, method=WorldModel.dynamic
                    )
                    return (post, rec), (rec, post, post_logits, prior_logits)

                keys = jax.random.split(k_wm, T)
                init = (jnp.zeros((B, stoch_size)), jnp.zeros((B, rec_size)))
                # unroll: the per-step GRU work is tiny at batch B, so amortising the
                # loop structure over several steps keeps the MXU fed
                _, (recs, posts, post_logits, prior_logits) = jax.lax.scan(
                    step, init, (batch_actions, embed, is_first, keys), unroll=8
                )
            latents = jnp.concatenate([posts, recs], -1)  # [T,B,L]
            recon = world_model.apply(wm_params, latents, method=WorldModel.decode)

            obs_lp = 0.0
            for k in cnn_keys:
                target = data[k].astype(jnp.float32) / 255.0 - 0.5
                target = target.reshape(T, B, -1, *target.shape[-2:])
                obs_lp = obs_lp + MSEDistribution(recon[k], dims=3).log_prob(target)
            for k in mlp_keys:
                obs_lp = obs_lp + SymlogDistribution(recon[k], dims=1).log_prob(data[k])

            reward_lp = TwoHotEncodingDistribution(
                world_model.apply(wm_params, latents, method=WorldModel.reward), dims=1
            ).log_prob(data["rewards"])
            continue_lp = (
                Independent(
                    BernoulliSafeMode(world_model.apply(wm_params, latents, method=WorldModel.continues)), 1
                ).log_prob(1.0 - data["terminated"])
            )

            post_logits_s = post_logits.reshape(T, B, stoch, discrete)
            prior_logits_s = prior_logits.reshape(T, B, stoch, discrete)
            rec_loss, metrics = reconstruction_loss(
                obs_lp,
                reward_lp,
                prior_logits_s,
                post_logits_s,
                wm_cfg.kl_dynamic,
                wm_cfg.kl_representation,
                wm_cfg.kl_free_nats,
                wm_cfg.kl_regularizer,
                continue_lp,
                wm_cfg.continue_scale_factor,
            )
            metrics["State/post_entropy"] = (
                Independent(OneHotCategorical(post_logits_s), 1).entropy().mean()
            )
            metrics["State/prior_entropy"] = (
                Independent(OneHotCategorical(prior_logits_s), 1).entropy().mean()
            )
            return rec_loss, (posts, recs, metrics)

        (rec_loss, (posts, recs, wm_metrics)), wm_grads = jax.value_and_grad(wm_loss_fn, has_aux=True)(
            params["world_model"]
        )
        wm_updates, new_wm_opt = wm_opt.update(wm_grads, opt_states["world_model"], params["world_model"])
        new_wm_params = optax.apply_updates(params["world_model"], wm_updates)

        # ------------------------------------------------ imagination + actor
        latent0 = jax.lax.stop_gradient(jnp.concatenate([posts, recs], -1)).reshape(T * B, -1)
        prior0 = jax.lax.stop_gradient(posts).reshape(T * B, stoch_size)
        rec0 = jax.lax.stop_gradient(recs).reshape(T * B, rec_size)
        true_continue0 = (1.0 - data["terminated"]).reshape(T * B, 1)

        def actor_loss_fn(actor_params):
            a0_tuple, _ = actor.apply(actor_params, latent0, k_a0)
            a0 = jnp.concatenate(a0_tuple, -1)

            def img_step(carry, k):
                prior, rec, action = carry
                k_dyn, k_act = jax.random.split(k)
                prior, rec = world_model.apply(new_wm_params, prior, rec, action, k_dyn, method=WorldModel.imagination)
                latent = jnp.concatenate([prior, rec], -1)
                acts, _ = actor.apply(actor_params, jax.lax.stop_gradient(latent), k_act)
                action = jnp.concatenate(acts, -1)
                return (prior, rec, action), (latent, action)

            keys = jax.random.split(k_img, horizon)
            _, (latents_img, actions_img) = jax.lax.scan(img_step, (prior0, rec0, a0), keys, unroll=5)
            traj = jnp.concatenate([latent0[None], latents_img], 0)  # [H+1, TB, L]
            imagined_actions = jnp.concatenate([a0[None], actions_img], 0)  # [H+1, TB, A]

            values = TwoHotEncodingDistribution(critic.apply(params["critic"], traj), dims=1).mean
            rewards_img = TwoHotEncodingDistribution(
                world_model.apply(new_wm_params, traj, method=WorldModel.reward), dims=1
            ).mean
            continues = BernoulliSafeMode(
                world_model.apply(new_wm_params, traj, method=WorldModel.continues)
            ).mode  # [H+1, TB, 1]
            continues = jnp.concatenate([true_continue0[None], continues[1:]], 0)

            # λ-returns over the imagined trajectory (reference utils.py:66-77).
            interm = rewards_img[1:] + continues[1:] * gamma * values[1:] * (1 - lmbda)

            def lam_step(carry, x):
                it, ct = x
                carry = it + ct * gamma * lmbda * carry
                return carry, carry

            _, lambda_values = jax.lax.scan(
                lam_step, values[-1], (interm, continues[1:]), reverse=True, unroll=8
            )

            discount = jax.lax.stop_gradient(jnp.cumprod(continues * gamma, 0) / gamma)

            offset, invscale, new_moments = update_moments(
                moments_state,
                lambda_values,
                decay=moments_cfg.decay,
                max_=moments_cfg.max,
                percentile_low=moments_cfg.percentile.low,
                percentile_high=moments_cfg.percentile.high,
            )
            normed_lambda = (lambda_values - offset) / invscale
            normed_baseline = (values[:-1] - offset) / invscale
            advantage = normed_lambda - normed_baseline

            _, dists = actor.apply(actor_params, jax.lax.stop_gradient(traj), None)
            if is_continuous:
                objective = advantage
                entropy = ent_coef * dists[0].entropy().sum(-1)
            else:
                logpis = []
                offset_a = 0
                for i, d in enumerate(dists):
                    act_i = jax.lax.stop_gradient(imagined_actions[..., offset_a : offset_a + actions_dim[i]])
                    logpis.append(d.log_prob(act_i)[:-1])
                    offset_a += actions_dim[i]
                objective = sum(logpis)[..., None] * jax.lax.stop_gradient(advantage)
                entropy = ent_coef * sum(d.entropy() for d in dists)
            policy_loss = -jnp.mean(discount[:-1] * (objective + entropy[:-1][..., None]))
            aux = {
                "traj": jax.lax.stop_gradient(traj),
                "lambda_values": jax.lax.stop_gradient(lambda_values),
                "discount": discount,
                "moments": new_moments,
            }
            return policy_loss, aux

        (policy_loss, actor_aux), actor_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(params["actor"])
        actor_updates, new_actor_opt = actor_opt.update(actor_grads, opt_states["actor"], params["actor"])
        new_actor_params = optax.apply_updates(params["actor"], actor_updates)

        # ------------------------------------------------ critic
        traj = actor_aux["traj"]
        lambda_values = actor_aux["lambda_values"]
        discount = actor_aux["discount"]

        def critic_loss_fn(critic_params):
            qv = TwoHotEncodingDistribution(critic.apply(critic_params, traj[:-1]), dims=1)
            target_values = TwoHotEncodingDistribution(
                critic.apply(params["target_critic"], traj[:-1]), dims=1
            ).mean
            loss = -qv.log_prob(lambda_values) - qv.log_prob(jax.lax.stop_gradient(target_values))
            return jnp.mean(loss * discount[:-1][..., 0])

        value_loss, critic_grads = jax.value_and_grad(critic_loss_fn)(params["critic"])
        critic_updates, new_critic_opt = critic_opt.update(critic_grads, opt_states["critic"], params["critic"])
        new_critic_params = optax.apply_updates(params["critic"], critic_updates)

        # EMA target critic (reference dreamer_v3.py:674-680).
        new_target = jax.lax.cond(
            update_target,
            lambda: jax.tree.map(lambda tp, cp: (1 - tau) * tp + tau * cp, params["target_critic"], new_critic_params),
            lambda: params["target_critic"],
        )

        new_params = {
            "world_model": new_wm_params,
            "actor": new_actor_params,
            "critic": new_critic_params,
            "target_critic": new_target,
        }
        new_opt_states = {"world_model": new_wm_opt, "actor": new_actor_opt, "critic": new_critic_opt}
        metrics = dict(wm_metrics)
        metrics["Loss/policy_loss"] = policy_loss
        metrics["Loss/value_loss"] = value_loss
        metrics["Grads/world_model"] = optax.global_norm(wm_grads)
        metrics["Grads/actor"] = optax.global_norm(actor_grads)
        metrics["Grads/critic"] = optax.global_norm(critic_grads)
        if health_enabled(cfg):  # trace-time constant (obs/health.py)
            metrics.update(
                diagnostics(
                    grads={"world_model": wm_grads, "actor": actor_grads, "critic": critic_grads},
                    params=new_params,
                    updates={"world_model": wm_updates, "actor": actor_updates, "critic": critic_updates},
                    aux={"critic_value_mean": lambda_values.mean(), "critic_value_std": lambda_values.std()},
                )
            )
        metrics = maybe_inject_nonfinite(cfg, metrics)
        if strict_enabled(cfg):  # trace-time constant: callback exists only in strict runs
            nan_scan(metrics, "dreamer_v3/train_step")
        return new_params, new_opt_states, actor_aux["moments"], metrics

    return train_step, init_opt_states


@register_algorithm(name="dreamer_v3")
def main(ctx, cfg) -> None:
    rank = ctx.process_index
    log_dir = get_log_dir(cfg)
    if ctx.is_global_zero:
        save_config(cfg, Path(log_dir) / "config.yaml")
    logger = get_logger(cfg, log_dir)
    monitor = TrainingMonitor(cfg, log_dir)

    envs = make_vector_env(cfg, cfg.seed, rank, log_dir if cfg.env.capture_video else None)
    obs_space = envs.single_observation_space
    act_space = envs.single_action_space
    is_continuous, actions_dim = parse_actions_dim(act_space)
    act_dim_sum = int(sum(actions_dim))
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    num_envs = cfg.env.num_envs
    world = jax.process_count()

    world_model, actor, critic, params, latent_size = build_agent(ctx, actions_dim, is_continuous, cfg, obs_space)
    train_step, init_opt_states = make_train_step(
        world_model, actor, critic, cfg, cnn_keys, mlp_keys, {k: obs_space[k].shape for k in obs_keys}
    )
    # Flight recorder: replay_update rebuilds this exact train step from the dump.
    recorder = flight_recorder.get_active()
    if recorder is not None:
        recorder.arm_replay(
            "sheeprl_tpu.algos.dreamer_v3.dreamer_v3:replay_update",
            obs_space=obs_space,
            actions_dim=tuple(int(d) for d in actions_dim),
            is_continuous=bool(is_continuous),
        )
    # opt states mirror the params' (possibly tensor-parallel) placement
    opt_states = ctx.shard_params(init_opt_states(params))
    moments_state = ctx.replicate(init_moments())
    target_update_freq = cfg.algo.critic.per_rank_target_network_update_freq

    # The whole iteration's gradient steps run as ONE jitted scan (utils/blocks.py):
    # one dispatch per iteration, per-step keys split inside the jit, target-critic
    # cadence computed from the running step count.
    def _block_step(carry, batch, key, update_target):
        params, opt_states, moments = carry
        params, opt_states, moments, metrics = train_step(
            params, opt_states, moments, batch, key, update_target
        )
        return (params, opt_states, moments), metrics

    # Device-resident replay (buffer.device): rows live in HBM, the host ships only
    # (env, start) indices, and each scan step gathers its batch in-jit — removes
    # the host→device batch traffic that otherwise floors e2e throughput.  Under
    # data parallelism the ring's env axis is sharded over the `data` mesh axis
    # (per-shard sampling + shard_map gather); multi-process runs keep the fast
    # path too via per-process local rings + a zero-copy global view
    # (data/device_buffer.py: MultiProcessDeviceReplayMirror).

    player_step = make_player_step(world_model, actor, actions_dim, cfg.algo.world_model.discrete_size)
    player_jit = jax.jit(player_step, static_argnames=("greedy",))
    stoch_size = cfg.algo.world_model.stochastic_size * cfg.algo.world_model.discrete_size
    rec_size = cfg.algo.world_model.recurrent_model.recurrent_state_size

    def player_state_init(n: int) -> PlayerState:
        return PlayerState(
            recurrent_state=jnp.zeros((n, rec_size)),
            stochastic_state=jnp.zeros((n, stoch_size)),
            actions=jnp.zeros((n, act_dim_sum)),
        )

    buffer_size = max(int(cfg.buffer.size) // max(num_envs * world, 1), 1)
    rb = EnvIndependentReplayBuffer(
        buffer_size,
        n_envs=num_envs,
        obs_keys=obs_keys,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}") if cfg.buffer.memmap else None,
        buffer_cls=SequentialReplayBuffer,
    )
    rb.seed(cfg.seed + rank)

    # Device-vs-host replay data path, one shared implementation
    # (data/device_buffer.py): HBM mirror + index-only sampling when
    # buffer.device=True on a single chip, async host prefetch otherwise.
    dispatcher, mirror, prefetcher, _run_block, rb_add = make_device_replay(
        ctx,
        cfg,
        rb,
        cnn_keys,
        mlp_keys,
        obs_space,
        act_dim_sum,
        _block_step,
        dispatcher_kwargs=dict(target_update_freq=target_update_freq),
    )

    # rank-independent (cross-process gathering) when multi-host
    aggregator = make_aggregator(cfg.metric.aggregator.get("metrics", {}))
    aggregator.keep(AGGREGATOR_KEYS | set(cfg.metric.aggregator.get("metrics", {})))
    ckpt_manager = CheckpointManager(Path(log_dir) / "checkpoints", keep_last=cfg.checkpoint.keep_last)
    guard = TrainingGuard(cfg, log_dir)
    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)

    batch_size = cfg.algo.per_rank_batch_size
    seq_len = cfg.algo.per_rank_sequence_length
    policy_steps_per_iter = num_envs * world * cfg.env.action_repeat
    total_steps = int(cfg.algo.total_steps)
    num_iters = max(total_steps // policy_steps_per_iter, 1) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0

    start_iter = 1
    policy_step = 0
    last_log = 0
    last_checkpoint = 0
    cumulative_grad_steps = 0
    if cfg.checkpoint.get("resume_from"):
        state = CheckpointManager.load(
            cfg.checkpoint.resume_from,
            templates={
                "params": jax.device_get(params),
                "opt_states": jax.device_get(opt_states),
                "moments": jax.device_get(moments_state),
            },
        )
        params = ctx.shard_params(state["params"])
        opt_states = ctx.shard_params(state["opt_states"])
        moments_state = ctx.replicate(state["moments"])
        ratio.load_state_dict(state["ratio"])
        start_iter = state["iter_num"] + 1
        policy_step = state["policy_step"]
        last_log = state.get("last_log", 0)
        last_checkpoint = state.get("last_checkpoint", 0)
        cumulative_grad_steps = state.get("cumulative_grad_steps", 0)
        learning_starts += start_iter
        if cfg.buffer.checkpoint and "rb" in state:
            rb.load_state_dict(state["rb"])
            if mirror is not None:
                mirror.load_from(rb)

    # Pending-row storage (reference ``dreamer_v3.py:538-651``): row t holds obs_t
    # together with the reward/terminated/truncated received when ARRIVING at obs_t
    # (zeros + is_first=1 after a reset); the action taken FROM obs_t is filled in just
    # before the row is committed.  On episode end an extra terminal row stores the
    # true final observation with a zero action.
    def _obs_row(o, idxs=None):
        row = {}
        for k in cnn_keys:
            v = np.asarray(o[k]) if idxs is None else np.asarray(o[k])[idxs]
            row[k] = v.reshape(1, v.shape[0], -1, *v.shape[-2:])
        for k in mlp_keys:
            v = np.asarray(o[k], dtype=np.float32) if idxs is None else np.asarray(o[k], dtype=np.float32)[idxs]
            row[k] = v.reshape(1, v.shape[0], -1)
        return row


    obs, _ = envs.reset(seed=cfg.seed + rank)
    player_state = player_state_init(num_envs)

    # Acting pipeline (sheeprl_tpu/rollout): depth 0 is the historical synchronous
    # dispatch -> one device_get -> env.step path, bit-for-bit; depth>=1 overlaps
    # the policy jit and the action fetch with the workers' env step (policy lag).
    def _pipeline_policy(cur_obs):
        nonlocal player_state
        obs_t = prepare_obs(cur_obs, cnn_keys, mlp_keys, num_envs)
        actions, stored, player_state = player_jit(
            params, player_state, obs_t, jnp.asarray(is_first_np), ctx.local_rng()
        )
        return (stored, list(actions))

    def _pipeline_post(fetched):
        # ONE device_get for everything the host needs (per-array fetches would
        # each pay a transfer round trip on a remote accelerator).
        stored_np, acts_list = fetched
        stored_actions = np.asarray(stored_np)
        acts_np = [np.asarray(a) for a in acts_list]
        if is_continuous:
            env_actions = acts_np[0]
        elif len(actions_dim) == 1:
            env_actions = acts_np[0].argmax(-1)
        else:
            env_actions = np.stack([a.argmax(-1) for a in acts_np], -1)
        return env_actions, stored_actions

    rollout_player = PipelinedPlayer(
        envs, _pipeline_policy, _pipeline_post, depth=int((cfg.get("rollout") or {}).get("pipeline_depth", 0))
    )

    step_data: Dict[str, np.ndarray] = _obs_row(obs)
    step_data["rewards"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["terminated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["truncated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["is_first"] = np.ones((1, num_envs, 1), np.float32)
    is_first_np = np.ones((num_envs, 1), dtype=np.float32)
    prefill_iters = max(learning_starts - 1, 0)

    try:
        for iter_num in range(start_iter, num_iters + 1):
            monitor.advance()
            env_time = 0.0
            env_t0 = time.perf_counter()
            with timer("Time/env_interaction_time"), monitor.phase("player"):
                if iter_num <= learning_starts and not cfg.checkpoint.get("resume_from"):
                    if is_continuous:
                        stored_actions = np.stack([act_space.sample() for _ in range(num_envs)]).astype(np.float32)
                        env_actions = stored_actions
                    else:
                        sampled = np.stack([act_space.sample() for _ in range(num_envs)])
                        sampled = sampled.reshape(num_envs, -1)
                        onehots = []
                        for i, d in enumerate(actions_dim):
                            oh = np.zeros((num_envs, d), dtype=np.float32)
                            oh[np.arange(num_envs), sampled[:, i]] = 1.0
                            onehots.append(oh)
                        stored_actions = np.concatenate(onehots, -1)
                        env_actions = sampled.squeeze(-1) if len(actions_dim) == 1 else sampled
                    # keep the player state in sync with the executed action
                    player_state = player_state._replace(actions=jnp.asarray(stored_actions))
                else:
                    env_actions, stored_actions = rollout_player.act(obs)

                # Commit the pending row with the action taken from its observation
                # (under the prefetcher's lock: the sampler thread must not read rows
                # mid-write).
                step_data["actions"] = stored_actions.reshape(1, num_envs, -1)
                with monitor.phase("buffer_add"):
                    rb_add(step_data, validate_args=cfg.buffer.validate_args)
            env_time += time.perf_counter() - env_t0

            # ---- dispatch this iteration's gradient block BEFORE stepping the envs:
            # the device executes it while the host walks the environments below
            # (acting above used the params from the end of the previous iteration,
            # exactly as the eager ordering did).  No device_get here — metrics are
            # futures, fetched at the log cadence.
            grad_steps = 0
            if iter_num >= learning_starts:
                grad_steps = ratio(
                    (policy_step + policy_steps_per_iter - prefill_iters * policy_steps_per_iter) / world
                )
                if grad_steps > 0:
                    with monitor.phase("dispatch"):
                        params, opt_states, moments_state = _run_block(
                            (params, opt_states, moments_state),
                            grad_steps,
                            cumulative_grad_steps,
                            stage_next=iter_num < num_iters,
                        )
                    cumulative_grad_steps += grad_steps

            env_t0 = time.perf_counter()
            with timer("Time/env_interaction_time"), monitor.phase("env_step"):
                next_obs, reward, terminated, truncated, info = rollout_player.env_step(env_actions)
                if cfg.env.clip_rewards:
                    reward = np.clip(reward, -1, 1)
                done = np.logical_or(terminated, truncated)
                reward = np.asarray(reward, dtype=np.float32).reshape(num_envs, 1)

                # True final observation for done envs (SAME_STEP autoreset returns the
                # reset obs; the final one lives in info["final_obs"]).
                real_next_obs = {k: np.asarray(next_obs[k]).copy() for k in obs_keys}
                if done.any() and "final_obs" in info:
                    for i in np.nonzero(done)[0]:
                        if info["final_obs"][i] is not None:
                            for k in obs_keys:
                                real_next_obs[k][i] = np.asarray(info["final_obs"][i][k])

                # Build the next pending row: obs_{t+1} + arrival reward/flags.
                step_data = _obs_row(next_obs)
                step_data["rewards"] = reward.reshape(1, num_envs, 1).copy()
                step_data["terminated"] = terminated.astype(np.float32).reshape(1, num_envs, 1)
                step_data["truncated"] = truncated.astype(np.float32).reshape(1, num_envs, 1)
                step_data["is_first"] = np.zeros((1, num_envs, 1), np.float32)

                done_idxs = np.nonzero(done)[0].tolist()
                if done_idxs:
                    # Terminal row: final obs + arrival reward/flags + zero action.
                    reset_data = _obs_row(real_next_obs, idxs=done_idxs)
                    reset_data["rewards"] = step_data["rewards"][:, done_idxs]
                    reset_data["terminated"] = step_data["terminated"][:, done_idxs]
                    reset_data["truncated"] = step_data["truncated"][:, done_idxs]
                    reset_data["actions"] = np.zeros((1, len(done_idxs), act_dim_sum), np.float32)
                    reset_data["is_first"] = np.zeros_like(reset_data["terminated"])
                    rb_add(reset_data, indices=done_idxs, validate_args=cfg.buffer.validate_args)
                    # The pending row for reset envs starts a fresh episode.
                    step_data["rewards"][:, done_idxs] = 0.0
                    step_data["terminated"][:, done_idxs] = 0.0
                    step_data["truncated"][:, done_idxs] = 0.0
                    step_data["is_first"][:, done_idxs] = 1.0

                is_first_np = done.astype(np.float32).reshape(num_envs, 1)
                obs = next_obs
                policy_step += policy_steps_per_iter
                record_episode_stats(aggregator, info)
            env_time += time.perf_counter() - env_t0

            # Checkpoint BEFORE the log flush so phase_checkpoint lands in the
            # window it was paid in (and the final save_last is not dropped from
            # the breakdown).
            def save_ckpt():
                nonlocal last_checkpoint
                state = {
                    "params": params,
                    "opt_states": opt_states,
                    "moments": moments_state,
                    "ratio": ratio.state_dict(),
                    "iter_num": iter_num,
                    "policy_step": policy_step,
                    "last_log": last_log,
                    "last_checkpoint": policy_step,
                    "cumulative_grad_steps": cumulative_grad_steps,
                }
                with monitor.phase("checkpoint"):
                    if cfg.buffer.checkpoint:
                        state["rb"] = rb.state_dict()
                    path = ckpt_manager.save(policy_step, state)
                last_checkpoint = policy_step
                return path

            if (
                cfg.checkpoint.every > 0
                and (policy_step - last_checkpoint) >= cfg.checkpoint.every
                or iter_num == num_iters
                and cfg.checkpoint.save_last
            ):
                save_ckpt()

            if logger is not None and (
                policy_step - last_log >= cfg.metric.log_every or iter_num == num_iters or cfg.dry_run
            ):
                # The drain below is the window's only blocking sync: it waits for
                # every gradient block dispatched in the window, so the window
                # wall-clock is an honest end-to-end grad-steps/s denominator.
                with monitor.phase("drain"):
                    dispatcher.drain(aggregator)
                metrics = aggregator.compute()
                # The per-phase Time/phase_* breakdown is folded in by
                # monitor.log_metrics (the nested player timer includes
                # buffer_add — subtract when reading).
                window_sps = dispatcher.pop_window_sps()
                if window_sps is not None:
                    metrics["Time/sps_train"] = window_sps
                metrics["Time/sps_env_interaction"] = (
                    policy_steps_per_iter / world / env_time if env_time > 0 else 0.0
                )
                metrics["Params/replay_ratio"] = (
                    cumulative_grad_steps * world / policy_step if policy_step > 0 else 0.0
                )
                metrics.update(replay_age_metrics(rb))
                metrics.update(rollout_metrics(envs))
                monitor.log_metrics(logger, metrics, policy_step)
                aggregator.reset()
                last_log = policy_step
            guard.boundary(policy_step, save_ckpt)

    finally:
        monitor.close()
        envs.close()
        if prefetcher is not None:
            prefetcher.close()
    if cfg.algo.run_test and ctx.is_global_zero:
        reward = test(player_step, params, player_state_init, ctx, cfg, log_dir)
        if logger is not None:
            logger.log_metrics({"Test/cumulative_reward": reward}, policy_step)
    if not cfg.get("model_manager", {}).get("disabled", True) and ctx.is_global_zero:
        from sheeprl_tpu.utils.model_manager import maybe_register_models

        maybe_register_models(cfg, log_dir)
    if logger is not None:
        logger.close()


def replay_update(cfg, dump_dir):
    """Flight-recorder replay builder: re-execute the dumped DreamerV3 gradient
    block on CPU — the same ``make_train_block`` chunking the dispatcher used, fed
    the dumped per-step batches, carry and base key, so the re-execution is
    bit-equivalent to the crashed dispatch."""
    from sheeprl_tpu.obs import replay_blackbox
    from sheeprl_tpu.parallel.mesh import make_mesh_context
    from sheeprl_tpu.utils.blocks import chunk_sizes, make_train_block

    ctx = make_mesh_context(cfg)
    raw = replay_blackbox.load_state(dump_dir)
    statics = raw["statics"]
    obs_space = statics["obs_space"]
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    world_model, actor, critic, params0, _ = build_agent(
        ctx, tuple(statics["actions_dim"]), statics["is_continuous"], cfg, obs_space
    )
    train_step, init_opt_states = make_train_step(
        world_model, actor, critic, cfg, cnn_keys, mlp_keys, {k: obs_space[k].shape for k in obs_keys}
    )
    carry0 = (params0, init_opt_states(params0), init_moments())
    state = replay_blackbox.load_state(dump_dir, templates={"carry": jax.device_get(carry0)})
    batches = replay_blackbox.as_step_list(state["batches"])
    bk = dict(statics.get("block_kwargs") or {})

    def _block_step(carry, batch, key, update_target):
        params, opt_states, moments = carry
        params, opt_states, moments, metrics = train_step(
            params, opt_states, moments, batch, key, update_target
        )
        return (params, opt_states, moments), metrics

    block = make_train_block(_block_step, bk.get("target_update_freq", 1), bk.get("count_offset", 1))
    carry = tuple(state["carry"])
    start_count = int(state["scalars"]["start_count"])
    base_key = jnp.asarray(state["base_key"])
    last_metrics, offset = {}, 0
    for size in chunk_sizes(len(batches), bk.get("max_chunk", 8)):
        chunk = tuple(batches[offset : offset + size])
        offset += size
        carry, metrics = block(carry, chunk, base_key, start_count)
        start_count += size
        last_metrics = jax.device_get(metrics)
    return {"metrics": last_metrics}


def lower_for_audit():
    """IR-audit hook (``python -m sheeprl_tpu.analysis.ir``): the DreamerV3
    gradient block — ``make_train_step`` wrapped in the same ``make_train_block``
    scan the dispatcher jits — at tiny MLP-only synthetic shapes."""
    from sheeprl_tpu.analysis.ir.synth import (
        DREAMER_DISCRETE_OVERRIDES,
        DREAMER_TINY_OVERRIDES,
        compose_tiny,
        sequence_batch,
        tiny_ctx,
        vector_space,
    )
    from sheeprl_tpu.analysis.ir.types import AuditEntry
    from sheeprl_tpu.utils.blocks import make_train_block

    cfg = compose_tiny(
        ["exp=dreamer_v3_dummy", "env=discrete_dummy", *DREAMER_TINY_OVERRIDES, *DREAMER_DISCRETE_OVERRIDES]
    )
    ctx = tiny_ctx(cfg)
    obs_space = vector_space()
    actions_dim, is_continuous = (3,), False
    world_model, actor, critic, params, _ = build_agent(ctx, actions_dim, is_continuous, cfg, obs_space)
    train_step, init_opt_states = make_train_step(
        world_model, actor, critic, cfg, [], ["state"], {"state": obs_space["state"].shape}
    )
    carry = (params, init_opt_states(params), init_moments())

    def _block_step(carry, batch, key, update_target):
        params, opt_states, moments = carry
        params, opt_states, moments, metrics = train_step(
            params, opt_states, moments, batch, key, update_target
        )
        return (params, opt_states, moments), metrics

    block = make_train_block(_block_step, cfg.algo.critic.per_rank_target_network_update_freq, 1)
    batch = sequence_batch(
        {"state": obs_space["state"].shape},
        act_dim=int(sum(actions_dim)),
        T=int(cfg.algo.per_rank_sequence_length),
        B=int(cfg.algo.per_rank_batch_size),
    )
    return [
        AuditEntry(
            name="dreamer_v3/train_block",
            fn=block,
            args=(carry, (batch,), jax.random.PRNGKey(0), 0),
            covers=("dreamer_v3", "p2e_dv3_finetuning"),
            precision=str(cfg.mesh.precision),
        )
    ]
