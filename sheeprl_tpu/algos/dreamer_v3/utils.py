"""DreamerV3 helpers (reference: ``/root/reference/sheeprl/algos/dreamer_v3/utils.py``)."""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.obs.tracer import trace_span

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/kl",
    "State/post_entropy",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
    "State/prior_entropy",
}
MODELS_TO_REGISTER = {"world_model", "actor", "critic", "target_critic", "moments"}


def init_moments() -> Dict[str, jax.Array]:
    return {"low": jnp.zeros(()), "high": jnp.zeros(())}


def update_moments(
    state: Dict[str, jax.Array],
    x: jax.Array,
    decay: float = 0.99,
    max_: float = 1.0,
    percentile_low: float = 0.05,
    percentile_high: float = 0.95,
) -> Tuple[jax.Array, jax.Array, Dict[str, jax.Array]]:
    """Percentile return normalizer (reference ``utils.py:40-63`` ``Moments``).

    The reference all-gathers across ranks before the quantile; here ``x`` is a global
    (mesh-sharded) array inside jit, so the quantile already spans every shard.
    Returns ``(offset, invscale, new_state)``.
    """
    x = jax.lax.stop_gradient(x.astype(jnp.float32))
    low = jnp.quantile(x, percentile_low)
    high = jnp.quantile(x, percentile_high)
    new_low = decay * state["low"] + (1 - decay) * low
    new_high = decay * state["high"] + (1 - decay) * high
    invscale = jnp.maximum(1.0 / max_, new_high - new_low)
    return new_low, invscale, {"low": new_low, "high": new_high}


@trace_span("Time/h2d_transfer")
def prepare_obs(
    obs: Dict[str, np.ndarray], cnn_keys: Sequence[str], mlp_keys: Sequence[str], num_envs: int = 1
) -> Dict[str, jax.Array]:
    """numpy env obs → [num_envs, ...] device arrays; images stay uint8 channel-first
    (the encoder normalises), vectors flattened float.  ``mask*`` entries (MineDojo
    action masks) ride along as bools for the masked actor."""
    out: Dict[str, jax.Array] = {}
    for k in cnn_keys:
        v = np.asarray(obs[k])
        out[k] = jnp.asarray(v.reshape(num_envs, -1, *v.shape[-2:]))
    for k in mlp_keys:
        out[k] = jnp.asarray(np.asarray(obs[k], dtype=np.float32).reshape(num_envs, -1))
    for k in obs:
        if k.startswith("mask"):
            out[k] = jnp.asarray(np.asarray(obs[k], dtype=bool).reshape(num_envs, -1))
    return out


def test(player_step, params, player_state_init, ctx, cfg, log_dir: str, greedy: bool = True, test_name: str = "test"):
    """Greedy single-env rollout (reference ``utils.py:94-139``)."""
    from sheeprl_tpu.utils.env import make_env

    env = make_env(cfg, cfg.seed, 0, log_dir, test_name)()
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    step_jit = jax.jit(player_step, static_argnames=("greedy",))

    obs, _ = env.reset(seed=cfg.seed)
    state = player_state_init(1)
    is_first = jnp.ones((1, 1))
    done, cum_reward = False, 0.0
    while not done:
        obs_t = prepare_obs({k: np.asarray(v)[None] for k, v in obs.items()}, cnn_keys, mlp_keys, 1)
        actions, _, state = step_jit(params, state, obs_t, is_first, ctx.rng(), greedy=greedy)
        is_first = jnp.zeros((1, 1))
        env_action = _to_env_action(actions, env.action_space)
        obs, reward, terminated, truncated, _ = env.step(env_action)
        done = bool(terminated or truncated)
        cum_reward += float(reward)
    env.close()
    return cum_reward


def _to_env_action(actions: Sequence[jax.Array], action_space) -> Any:
    import gymnasium

    acts = [np.asarray(jax.device_get(a))[0] for a in actions]
    if isinstance(action_space, gymnasium.spaces.Box):
        return acts[0].reshape(action_space.shape)
    if isinstance(action_space, gymnasium.spaces.Discrete):
        return int(acts[0].argmax(-1))
    return np.stack([a.argmax(-1) for a in acts])
