"""DreamerV3 agent modules (reference: ``/root/reference/sheeprl/algos/dreamer_v3/agent.py``).

TPU-native design:

* All modules are flax with ``setup``-style submodules so RSSM methods
  (``dynamic`` / ``imagination`` / ``_representation`` / ``_transition``) can be invoked
  through ``module.apply(params, ..., method=...)`` inside ``lax.scan`` bodies — the
  reference's per-step python loops (``dreamer_v3.py:134-145``, ``:235-241``) become
  scans inside ONE jitted train step.
* Convolutions run NHWC (TPU layout); observations stay channel-first at rest for
  reference parity and are transposed once at the encoder boundary.
* Sampling is explicit-key (pure): every stochastic method takes a PRNG key.
* The stateful ``PlayerDV3`` (reference ``agent.py:596-691``) becomes an explicit
  carried-state pytree + a pure ``player_step`` function; per-env resets are mask-folds
  of the learned initial state, exactly like ``RSSM.dynamic``'s ``is_first`` handling.

Reference components mapped: ``CNNEncoder`` (``agent.py:42-97``), ``MLPEncoder``
(``:100-151``), ``CNNDecoder`` (``:154-226``), ``MLPDecoder`` (``:229-278``),
``RecurrentModel`` (``:281-341``), ``RSSM`` (``:344-498``), ``Actor`` (``:694-845``),
``build_agent`` (``:935-1236``, incl. Hafner init ``:1170-1180``).
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import flax.linen as nn
import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.distributions import (
    Independent,
    Normal,
    OneHotCategoricalStraightThrough,
    TanhNormal,
    TruncatedNormal,
    unimix_logits,
)
from sheeprl_tpu.models.blocks import MLP, LayerNormGRUCell, _activation
from sheeprl_tpu.utils.utils import symlog

Dtype = Any


def compute_stochastic_state(key: Optional[jax.Array], logits: jax.Array, discrete: int = 32, sample: bool = True) -> jax.Array:
    """Sample the [..., stoch, discrete] one-hot state with straight-through gradients
    (reference: ``dreamer_v2/utils.py:44-61``)."""
    shaped = logits.reshape(*logits.shape[:-1], -1, discrete)
    dist = OneHotCategoricalStraightThrough(shaped)
    return dist.rsample(key) if sample else dist.mode


class CNNEncoder(nn.Module):
    """4-stage stride-2 conv trunk (reference ``agent.py:42-97``): 64×64 → 4×4,
    channels ``m, 2m, 4m, 8m``, LayerNorm (channel-last) + SiLU, flattened output."""

    channels_multiplier: int = 32
    stages: int = 4
    layer_norm: bool = True
    norm_eps: float = 1e-3
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        # x: [..., H, W, C] float in [-0.5, 0.5]; flatten leading dims for conv.
        lead = x.shape[:-3]
        x = x.reshape(-1, *x.shape[-3:]).astype(self.dtype)
        for i in range(self.stages):
            ch = self.channels_multiplier * (2**i)
            x = nn.Conv(ch, (4, 4), strides=(2, 2), padding=((1, 1), (1, 1)), use_bias=not self.layer_norm, dtype=self.dtype)(x)
            if self.layer_norm:
                x = nn.LayerNorm(epsilon=self.norm_eps, dtype=self.dtype)(x)
            x = nn.silu(x)
        return x.reshape(*lead, -1)


class MLPEncoder(nn.Module):
    """symlog → dense stack (reference ``agent.py:100-151``)."""

    dense_units: int = 512
    mlp_layers: int = 2
    layer_norm: bool = True
    norm_eps: float = 1e-3
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = symlog(x)
        return MLP(
            hidden_sizes=(self.dense_units,) * self.mlp_layers,
            activation="silu",
            layer_norm=self.layer_norm,
            norm_eps=self.norm_eps,
            dtype=self.dtype,
        )(x)


class Encoder(nn.Module):
    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    cnn_channels_multiplier: int = 32
    cnn_stages: int = 4
    dense_units: int = 512
    mlp_layers: int = 2
    layer_norm: bool = True
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        feats = []
        if self.cnn_keys:
            # channel-first uint8/float [..., C, H, W] → NHWC in [-0.5, 0.5]
            imgs = []
            for k in self.cnn_keys:
                img = obs[k]
                if img.dtype == jnp.uint8:
                    img = img.astype(jnp.float32) / 255.0 - 0.5
                imgs.append(jnp.moveaxis(img, -3, -1))
            x = jnp.concatenate(imgs, axis=-1)
            feats.append(
                CNNEncoder(
                    channels_multiplier=self.cnn_channels_multiplier,
                    stages=self.cnn_stages,
                    layer_norm=self.layer_norm,
                    dtype=self.dtype,
                    name="cnn_encoder",
                )(x)
            )
        if self.mlp_keys:
            vec = jnp.concatenate([obs[k].astype(jnp.float32) for k in self.mlp_keys], axis=-1)
            feats.append(
                MLPEncoder(
                    dense_units=self.dense_units,
                    mlp_layers=self.mlp_layers,
                    layer_norm=self.layer_norm,
                    dtype=self.dtype,
                    name="mlp_encoder",
                )(vec)
            )
        return jnp.concatenate(feats, axis=-1).astype(jnp.float32)


class CNNDecoder(nn.Module):
    """Latent → stacked image reconstruction, mirror of the encoder
    (reference ``agent.py:154-226``).  Output is channel-first for obs parity."""

    output_shapes: Dict[str, Tuple[int, ...]]  # per-key [C, H, W]
    channels_multiplier: int = 32
    stages: int = 4
    layer_norm: bool = True
    norm_eps: float = 1e-3
    image_size: int = 64
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, z: jax.Array) -> Dict[str, jax.Array]:
        total_c = sum(s[0] for s in self.output_shapes.values())
        h0 = self.image_size // (2**self.stages)
        c0 = self.channels_multiplier * (2 ** (self.stages - 1))
        x = nn.Dense(h0 * h0 * c0, dtype=self.dtype, name="latent_proj")(z.astype(self.dtype))
        lead = x.shape[:-1]
        x = x.reshape(-1, h0, h0, c0)
        for i in reversed(range(self.stages - 1)):
            ch = self.channels_multiplier * (2**i)
            x = nn.ConvTranspose(ch, (4, 4), strides=(2, 2), padding="SAME", use_bias=not self.layer_norm, dtype=self.dtype)(x)
            if self.layer_norm:
                x = nn.LayerNorm(epsilon=self.norm_eps, dtype=self.dtype)(x)
            x = nn.silu(x)
        x = nn.ConvTranspose(total_c, (4, 4), strides=(2, 2), padding="SAME", dtype=self.dtype, name="head")(x)
        x = jnp.moveaxis(x, -1, -3).astype(jnp.float32)  # [N, C, H, W]
        x = x.reshape(*lead, *x.shape[-3:])
        out, offset = {}, 0
        for k, shape in self.output_shapes.items():
            out[k] = x[..., offset : offset + shape[0], :, :]
            offset += shape[0]
        return out


class MLPDecoder(nn.Module):
    """Latent → per-key vector reconstructions (reference ``agent.py:229-278``)."""

    output_shapes: Dict[str, Tuple[int, ...]]
    dense_units: int = 512
    mlp_layers: int = 2
    layer_norm: bool = True
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, z: jax.Array) -> Dict[str, jax.Array]:
        x = MLP(
            hidden_sizes=(self.dense_units,) * self.mlp_layers,
            activation="silu",
            layer_norm=self.layer_norm,
            norm_eps=1e-3,
            dtype=self.dtype,
        )(z)
        return {
            k: nn.Dense(int(np.prod(shape)), dtype=self.dtype, name=f"head_{k}")(x).astype(jnp.float32)
            for k, shape in self.output_shapes.items()
        }


class RecurrentModel(nn.Module):
    """Dense+LN+SiLU → LayerNormGRUCell (reference ``agent.py:281-341``)."""

    recurrent_state_size: int
    dense_units: int = 512
    dtype: Dtype = jnp.float32

    def setup(self):
        self.mlp = MLP(
            hidden_sizes=(self.dense_units,),
            activation="silu",
            layer_norm=True,
            norm_eps=1e-3,
            dtype=self.dtype,
            name="input_proj",
        )
        self.rnn = LayerNormGRUCell(hidden_size=self.recurrent_state_size, layer_norm=True, dtype=self.dtype)

    def __call__(self, x: jax.Array, recurrent_state: jax.Array) -> jax.Array:
        feat = self.mlp(x)
        h, _ = self.rnn(recurrent_state, feat)
        return h.astype(jnp.float32)


class RSSM(nn.Module):
    """Recurrent State-Space Model (reference ``agent.py:344-498``)."""

    stochastic_size: int = 32
    discrete_size: int = 32
    recurrent_state_size: int = 512
    dense_units: int = 512
    transition_hidden_size: int = 512
    representation_hidden_size: int = 512
    unimix: float = 0.01
    learnable_initial_recurrent_state: bool = True
    dtype: Dtype = jnp.float32

    def setup(self):
        stoch_out = self.stochastic_size * self.discrete_size
        self.recurrent_model = RecurrentModel(
            recurrent_state_size=self.recurrent_state_size, dense_units=self.dense_units, dtype=self.dtype
        )
        self.representation_model = nn.Sequential(
            [
                MLP(
                    hidden_sizes=(self.representation_hidden_size,),
                    activation="silu",
                    layer_norm=True,
                    norm_eps=1e-3,
                    dtype=self.dtype,
                ),
                nn.Dense(stoch_out, dtype=self.dtype, name="repr_logits"),
            ]
        )
        self.transition_model = nn.Sequential(
            [
                MLP(
                    hidden_sizes=(self.transition_hidden_size,),
                    activation="silu",
                    layer_norm=True,
                    norm_eps=1e-3,
                    dtype=self.dtype,
                ),
                nn.Dense(stoch_out, dtype=self.dtype, name="trans_logits"),
            ]
        )
        if self.learnable_initial_recurrent_state:
            self.initial_recurrent_state = self.param(
                "initial_recurrent_state", nn.initializers.zeros, (self.recurrent_state_size,), jnp.float32
            )
        else:
            self.initial_recurrent_state = jnp.zeros(self.recurrent_state_size, dtype=jnp.float32)

    def _uniform_mix(self, logits: jax.Array) -> jax.Array:
        shaped = logits.reshape(*logits.shape[:-1], self.stochastic_size, self.discrete_size)
        mixed = unimix_logits(shaped, self.unimix)
        return mixed.reshape(*logits.shape[:-1], -1)

    def _representation(self, recurrent_state: jax.Array, embedded_obs: jax.Array, key: Optional[jax.Array], sample: bool = True):
        logits = self.representation_model(jnp.concatenate([recurrent_state, embedded_obs], -1)).astype(jnp.float32)
        logits = self._uniform_mix(logits)
        return logits, compute_stochastic_state(key, logits, self.discrete_size, sample)

    def _transition(self, recurrent_state: jax.Array, key: Optional[jax.Array], sample: bool = True):
        logits = self.transition_model(recurrent_state).astype(jnp.float32)
        logits = self._uniform_mix(logits)
        return logits, compute_stochastic_state(key, logits, self.discrete_size, sample)

    def get_initial_states(self, batch_shape: Sequence[int]) -> Tuple[jax.Array, jax.Array]:
        """tanh'd learnable initial recurrent state + its prior mode
        (reference ``agent.py:382-394``)."""
        h0 = jnp.tanh(self.initial_recurrent_state)
        h0 = jnp.broadcast_to(h0, (*batch_shape, self.recurrent_state_size))
        _, z0 = self._transition(h0, key=None, sample=False)
        return h0, z0.reshape(*batch_shape, -1)

    def dynamic(
        self,
        posterior: jax.Array,  # [B, stoch*discrete] (flattened)
        recurrent_state: jax.Array,  # [B, R]
        action: jax.Array,  # [B, A]
        embedded_obs: jax.Array,  # [B, E]
        is_first: jax.Array,  # [B, 1]
        key: jax.Array,
    ):
        """One posterior step (reference ``agent.py:396-435``): is-first masking resets
        state/action to the learned initial state, then GRU → prior → posterior."""
        action = (1 - is_first) * action
        h0, z0 = self.get_initial_states(recurrent_state.shape[:-1])
        recurrent_state = (1 - is_first) * recurrent_state + is_first * h0
        posterior = (1 - is_first) * posterior + is_first * z0
        recurrent_state = self.recurrent_model(jnp.concatenate([posterior, action], -1), recurrent_state)
        k1, k2 = jax.random.split(key)
        prior_logits, prior = self._transition(recurrent_state, k1)
        posterior_logits, posterior_sample = self._representation(recurrent_state, embedded_obs, k2)
        posterior_flat = posterior_sample.reshape(*posterior_sample.shape[:-2], -1)
        return recurrent_state, posterior_flat, prior, posterior_logits, prior_logits

    def imagination(self, prior: jax.Array, recurrent_state: jax.Array, actions: jax.Array, key: jax.Array):
        """One prior-only step (reference ``agent.py:482-498``)."""
        recurrent_state = self.recurrent_model(jnp.concatenate([prior, actions], -1), recurrent_state)
        _, imagined = self._transition(recurrent_state, key)
        return imagined.reshape(*imagined.shape[:-2], -1), recurrent_state


class DecoupledRSSM(RSSM):
    """RSSM whose posterior depends on the observation embedding ALONE
    (reference ``agent.py:501-593``): ``q(z_t | o_t)`` instead of ``q(z_t | h_t, o_t)``.

    TPU payoff: the posterior for the whole ``[T, B]`` batch is ONE vectorized
    representation call (no recurrent dependency), so only the prior runs in the
    ``lax.scan``."""

    def _representation(self, embedded_obs: jax.Array, key: Optional[jax.Array], sample: bool = True):  # type: ignore[override]
        logits = self.representation_model(embedded_obs).astype(jnp.float32)
        logits = self._uniform_mix(logits)
        return logits, compute_stochastic_state(key, logits, self.discrete_size, sample)

    def dynamic(  # type: ignore[override]
        self,
        posterior: jax.Array,  # [B, stoch*discrete] — the PREVIOUS step's posterior
        recurrent_state: jax.Array,
        action: jax.Array,
        is_first: jax.Array,
        key: jax.Array,
    ):
        """Prior-only step (reference ``agent.py:542-580``): the posterior is supplied
        (already computed from the embedding); returns only recurrent state + prior."""
        action = (1 - is_first) * action
        h0, z0 = self.get_initial_states(recurrent_state.shape[:-1])
        recurrent_state = (1 - is_first) * recurrent_state + is_first * h0
        posterior = (1 - is_first) * posterior + is_first * z0
        recurrent_state = self.recurrent_model(jnp.concatenate([posterior, action], -1), recurrent_state)
        prior_logits, prior = self._transition(recurrent_state, key)
        return recurrent_state, prior, prior_logits


class WorldModel(nn.Module):
    """Encoder + RSSM + decoders + reward/continue heads under one params tree
    (one optimizer, reference ``agent.py:707`` WorldModel wrapper)."""

    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    cnn_shapes: Dict[str, Tuple[int, ...]]
    mlp_shapes: Dict[str, Tuple[int, ...]]
    cnn_channels_multiplier: int = 32
    dense_units: int = 512
    mlp_layers: int = 2
    stochastic_size: int = 32
    discrete_size: int = 32
    recurrent_state_size: int = 512
    transition_hidden_size: int = 512
    representation_hidden_size: int = 512
    unimix: float = 0.01
    reward_bins: int = 255
    image_size: int = 64
    learnable_initial_recurrent_state: bool = True
    decoupled_rssm: bool = False
    dtype: Dtype = jnp.float32

    def setup(self):
        self.encoder = Encoder(
            cnn_keys=self.cnn_keys,
            mlp_keys=self.mlp_keys,
            cnn_channels_multiplier=self.cnn_channels_multiplier,
            dense_units=self.dense_units,
            mlp_layers=self.mlp_layers,
            dtype=self.dtype,
        )
        rssm_cls = DecoupledRSSM if self.decoupled_rssm else RSSM
        self.rssm = rssm_cls(
            stochastic_size=self.stochastic_size,
            discrete_size=self.discrete_size,
            recurrent_state_size=self.recurrent_state_size,
            dense_units=self.dense_units,
            transition_hidden_size=self.transition_hidden_size,
            representation_hidden_size=self.representation_hidden_size,
            unimix=self.unimix,
            learnable_initial_recurrent_state=self.learnable_initial_recurrent_state,
            dtype=self.dtype,
        )
        if self.cnn_keys:
            self.observation_model_cnn = CNNDecoder(
                output_shapes=self.cnn_shapes,
                channels_multiplier=self.cnn_channels_multiplier,
                image_size=self.image_size,
                dtype=self.dtype,
            )
        if self.mlp_keys:
            self.observation_model_mlp = MLPDecoder(
                output_shapes=self.mlp_shapes,
                dense_units=self.dense_units,
                mlp_layers=self.mlp_layers,
                dtype=self.dtype,
            )
        self.reward_model = nn.Sequential(
            [
                MLP(
                    hidden_sizes=(self.dense_units,) * self.mlp_layers,
                    activation="silu",
                    layer_norm=True,
                    norm_eps=1e-3,
                    dtype=self.dtype,
                ),
                nn.Dense(self.reward_bins, dtype=self.dtype, name="reward_head"),
            ]
        )
        self.continue_model = nn.Sequential(
            [
                MLP(
                    hidden_sizes=(self.dense_units,) * self.mlp_layers,
                    activation="silu",
                    layer_norm=True,
                    norm_eps=1e-3,
                    dtype=self.dtype,
                ),
                nn.Dense(1, dtype=self.dtype, name="continue_head"),
            ]
        )

    # -- method entry points for module.apply(..., method=...) --------------
    def encode(self, obs: Dict[str, jax.Array]) -> jax.Array:
        return self.encoder(obs)

    def decode(self, latent: jax.Array) -> Dict[str, jax.Array]:
        out: Dict[str, jax.Array] = {}
        if self.cnn_keys:
            out.update(self.observation_model_cnn(latent))
        if self.mlp_keys:
            out.update(self.observation_model_mlp(latent))
        return out

    def reward(self, latent: jax.Array) -> jax.Array:
        return self.reward_model(latent).astype(jnp.float32)

    def continues(self, latent: jax.Array) -> jax.Array:
        return self.continue_model(latent).astype(jnp.float32)

    def dynamic(self, *args, **kwargs):
        return self.rssm.dynamic(*args, **kwargs)

    def imagination(self, *args, **kwargs):
        return self.rssm.imagination(*args, **kwargs)

    def initial_states(self, batch_shape):
        return self.rssm.get_initial_states(batch_shape)

    def representation(self, recurrent_state, embedded_obs, key, sample=True):
        if self.decoupled_rssm:
            return self.rssm._representation(embedded_obs, key, sample)
        return self.rssm._representation(recurrent_state, embedded_obs, key, sample)

    def representation_from_embed(self, embedded_obs, key, sample=True):
        """Vectorized posterior over a whole [T, B] batch (DecoupledRSSM only)."""
        return self.rssm._representation(embedded_obs, key, sample)

    def __call__(self, obs: Dict[str, jax.Array], action: jax.Array, key: jax.Array):
        """Init path: touch every submodule once (both RSSM variants)."""
        embed = self.encoder(obs)
        batch_shape = embed.shape[:-1]
        h0, z0 = self.rssm.get_initial_states(batch_shape)
        if self.decoupled_rssm:
            _, post = self.rssm._representation(embed, key)
            z = post.reshape(*post.shape[:-2], -1)
            h, prior, prior_logits = self.rssm.dynamic(z0, h0, action, jnp.ones((*batch_shape, 1)), key)
        else:
            h, z, prior, post_logits, prior_logits = self.rssm.dynamic(
                z0, h0, action, embed, jnp.ones((*batch_shape, 1)), key
            )
        latent = jnp.concatenate([z, h], -1)
        recon = self.decode(latent)
        return self.reward(latent), self.continues(latent), recon


class DreamerActor(nn.Module):
    """Policy head over latent states (reference ``agent.py:694-845``)."""

    actions_dim: Sequence[int]
    is_continuous: bool
    distribution: str = "auto"
    dense_units: int = 512
    mlp_layers: int = 2
    unimix: float = 0.01
    init_std: float = 2.0
    min_std: float = 0.1
    max_std: float = 1.0
    action_clip: float = 1.0
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, state: jax.Array, key: Optional[jax.Array] = None, greedy: bool = False, mask=None):
        dist_type = self.distribution
        if dist_type == "auto":
            dist_type = "scaled_normal" if self.is_continuous else "discrete"
        supported = ("discrete",) if not self.is_continuous else ("tanh_normal", "normal", "trunc_normal", "scaled_normal")
        if dist_type not in supported:
            raise ValueError(f"distribution.type={dist_type!r} not supported for this action space; use one of {supported}")
        x = MLP(
            hidden_sizes=(self.dense_units,) * self.mlp_layers,
            activation="silu",
            layer_norm=True,
            norm_eps=1e-3,
            dtype=self.dtype,
        )(state)
        if self.is_continuous:
            out = nn.Dense(2 * sum(self.actions_dim), dtype=self.dtype, name="head")(x).astype(jnp.float32)
            mean, std = jnp.split(out, 2, -1)
            if dist_type == "tanh_normal":
                mean = 5 * jnp.tanh(mean / 5)
                std = jax.nn.softplus(std + self.init_std) + self.min_std
                dist = TanhNormal(mean, std)
            elif dist_type == "normal":
                dist = Normal(mean, std)
            elif dist_type == "trunc_normal":
                std = 2 * jax.nn.sigmoid((std + self.init_std) / 2) + self.min_std
                dist = TruncatedNormal(jnp.tanh(mean), std, -1.0, 1.0)
            else:  # scaled_normal
                std = (self.max_std - self.min_std) * jax.nn.sigmoid(std + self.init_std) + self.min_std
                dist = Normal(jnp.tanh(mean), std)
            if greedy or key is None:
                actions = dist.mode
            else:
                actions = dist.rsample(key)
            if self.action_clip > 0:
                clip = jnp.full_like(actions, self.action_clip)
                actions = actions * jax.lax.stop_gradient(clip / jnp.maximum(clip, jnp.abs(actions)))
            return (actions,), (dist,)
        heads = [nn.Dense(d, dtype=self.dtype, name=f"head_{i}")(x).astype(jnp.float32) for i, d in enumerate(self.actions_dim)]
        actions, dists = [], []
        keys = jax.random.split(key, len(heads)) if key is not None else [None] * len(heads)
        for logits, k in zip(heads, keys):
            d = OneHotCategoricalStraightThrough(unimix_logits(logits, self.unimix))
            dists.append(d)
            actions.append(d.mode if (greedy or k is None) else d.rsample(k))
        return tuple(actions), tuple(dists)


class MinedojoActor(nn.Module):
    """Hierarchical masked actor for MineDojo (reference ``agent.py:848-932``).

    Three discrete heads — (action-type, craft-arg, item-arg) — sampled in order: the
    craft/item heads are masked *conditionally on the sampled action-type* (craft-arg
    only constrains when action 15 is chosen; item-arg when 16/17 equip/place or 18
    destroy is chosen).  The reference masks with a python double loop over [T, B];
    here the conditional masks are vectorized ``jnp.where`` selects."""

    actions_dim: Sequence[int]  # (len(ACTION_MAP), n_craft, n_items)
    is_continuous: bool = False
    distribution: str = "auto"
    dense_units: int = 512
    mlp_layers: int = 2
    unimix: float = 0.01
    init_std: float = 2.0
    min_std: float = 0.1
    max_std: float = 1.0
    action_clip: float = 1.0
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, state: jax.Array, key: Optional[jax.Array] = None, greedy: bool = False, mask=None):
        if self.is_continuous:
            raise ValueError("MinedojoActor only supports the functional MultiDiscrete action space")
        x = MLP(
            hidden_sizes=(self.dense_units,) * self.mlp_layers,
            activation="silu",
            layer_norm=True,
            norm_eps=1e-3,
            dtype=self.dtype,
        )(state)
        heads = [nn.Dense(d, dtype=self.dtype, name=f"head_{i}")(x).astype(jnp.float32) for i, d in enumerate(self.actions_dim)]
        keys = jax.random.split(key, len(heads)) if key is not None else [None] * len(heads)
        neg_inf = jnp.finfo(jnp.float32).min

        actions, dists = [], []
        functional_action = None
        for i, logits in enumerate(heads):
            logits = unimix_logits(logits, self.unimix)
            if mask is not None:
                if i == 0:
                    logits = jnp.where(mask["mask_action_type"], logits, neg_inf)
                elif i == 1:
                    # the craft argument constrains only when action-type 15 (craft)
                    is_craft = (functional_action == 15)[..., None]
                    allowed = jnp.where(is_craft, mask["mask_craft_smelt"], True)
                    logits = jnp.where(allowed, logits, neg_inf)
                elif i == 2:
                    is_equip_place = jnp.logical_or(functional_action == 16, functional_action == 17)[..., None]
                    is_destroy = (functional_action == 18)[..., None]
                    allowed = jnp.where(is_equip_place, mask["mask_equip_place"], True)
                    allowed = jnp.where(is_destroy, mask["mask_destroy"], allowed)
                    logits = jnp.where(allowed, logits, neg_inf)
            d = OneHotCategoricalStraightThrough(logits)
            dists.append(d)
            actions.append(d.mode if (greedy or keys[i] is None) else d.rsample(keys[i]))
            if functional_action is None:
                functional_action = actions[0].argmax(-1)
        return tuple(actions), tuple(dists)


class DreamerCritic(nn.Module):
    """Two-hot value head (reference ``build_agent`` critic MLP, ``agent.py:1117-…``)."""

    dense_units: int = 512
    mlp_layers: int = 2
    bins: int = 255
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, state: jax.Array) -> jax.Array:
        x = MLP(
            hidden_sizes=(self.dense_units,) * self.mlp_layers,
            activation="silu",
            layer_norm=True,
            norm_eps=1e-3,
            dtype=self.dtype,
        )(state)
        return nn.Dense(self.bins, dtype=self.dtype, name="head")(x).astype(jnp.float32)


# ---------------------------------------------------------------------------
# Hafner initialization (reference utils.py:143-182 + agent.py:1170-1180)
# ---------------------------------------------------------------------------


def _variance_scaling_uniform(key, shape, dtype, scale: float):
    fan_in, fan_out = shape[0], shape[-1]
    denom = (fan_in + fan_out) / 2.0
    limit = np.sqrt(3.0 * scale / denom)
    return jax.random.uniform(key, shape, dtype, -limit, limit)


def apply_hafner_init(params: Dict[str, Any], key: jax.Array) -> Dict[str, Any]:
    """Uniform(scale=1) re-init of output-head kernels (reference ``agent.py:1171-1180``):
    actor heads (``head`` / ``head_i``), RSSM logits heads, continue head and decoder
    heads.  Zero-init of reward/critic heads is done separately via
    ``zero_init_head`` (which also zeroes the bias)."""
    import flax

    uniform_parents = {"repr_logits", "trans_logits", "continue_head", "head"}
    flat = flax.traverse_util.flatten_dict(params)
    keys = jax.random.split(key, len(flat))
    new = {}
    for i, (path, value) in enumerate(flat.items()):
        parent = str(path[-2]) if len(path) >= 2 else ""
        is_uniform = parent in uniform_parents or parent.startswith("head_")
        if str(path[-1]) == "kernel" and is_uniform:
            new[path] = _variance_scaling_uniform(keys[i], value.shape, value.dtype, 1.0)
        else:
            new[path] = value
    return flax.traverse_util.unflatten_dict(new)


def zero_init_head(params: Dict[str, Any], head_name: str = "head") -> Dict[str, Any]:
    """Zero the kernel+bias of a module's top-level output head (critic/reward)."""
    import flax

    flat = flax.traverse_util.flatten_dict(params)
    new = {}
    for path, value in flat.items():
        name = "/".join(str(p) for p in path)
        if f"{head_name}/kernel" in name or f"{head_name}/bias" in name:
            new[path] = jnp.zeros_like(value)
        else:
            new[path] = value
    return flax.traverse_util.unflatten_dict(new)


# ---------------------------------------------------------------------------
# Player: explicit carried state (reference PlayerDV3, agent.py:596-691)
# ---------------------------------------------------------------------------


class PlayerState(NamedTuple):
    recurrent_state: jax.Array  # [n_envs, R]
    stochastic_state: jax.Array  # [n_envs, S*D]
    actions: jax.Array  # [n_envs, sum(actions_dim)]


def parse_actions_dim(action_space: gymnasium.spaces.Space) -> Tuple[bool, Tuple[int, ...]]:
    if isinstance(action_space, gymnasium.spaces.Box):
        return True, (int(np.prod(action_space.shape)),)
    if isinstance(action_space, gymnasium.spaces.Discrete):
        return False, (int(action_space.n),)
    if isinstance(action_space, gymnasium.spaces.MultiDiscrete):
        return False, tuple(int(n) for n in action_space.nvec)
    raise ValueError(f"Unsupported action space: {type(action_space)}")


def build_agent(
    ctx,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
):
    """Construct world model / actor / critic modules + params (replicated)."""
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cnn_shapes = {k: tuple(obs_space[k].shape) for k in cnn_keys}
    mlp_shapes = {k: tuple(obs_space[k].shape) for k in mlp_keys}
    wm_cfg = cfg.algo.world_model

    world_model = WorldModel(
        cnn_keys=cnn_keys,
        mlp_keys=mlp_keys,
        cnn_shapes=cnn_shapes,
        mlp_shapes=mlp_shapes,
        cnn_channels_multiplier=wm_cfg.encoder.cnn_channels_multiplier,
        dense_units=cfg.algo.dense_units,
        mlp_layers=cfg.algo.mlp_layers,
        stochastic_size=wm_cfg.stochastic_size,
        discrete_size=wm_cfg.discrete_size,
        recurrent_state_size=wm_cfg.recurrent_model.recurrent_state_size,
        transition_hidden_size=wm_cfg.transition_model.hidden_size,
        representation_hidden_size=wm_cfg.representation_model.hidden_size,
        unimix=cfg.algo.unimix,
        reward_bins=wm_cfg.reward_model.bins,
        image_size=cfg.env.screen_size,
        learnable_initial_recurrent_state=wm_cfg.learnable_initial_recurrent_state,
        decoupled_rssm=wm_cfg.get("decoupled_rssm", False),
        dtype=ctx.compute_dtype,
    )
    latent_size = (
        wm_cfg.stochastic_size * wm_cfg.discrete_size + wm_cfg.recurrent_model.recurrent_state_size
    )
    is_minedojo = "minedojo" in str(cfg.env.get("wrapper", {}).get("_target_", "")).lower()
    actor_cls = MinedojoActor if is_minedojo else DreamerActor
    actor = actor_cls(
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        distribution=cfg.distribution.get("type", "auto"),
        dense_units=cfg.algo.actor.dense_units,
        mlp_layers=cfg.algo.actor.mlp_layers,
        unimix=cfg.algo.actor.unimix,
        init_std=cfg.algo.actor.init_std,
        min_std=cfg.algo.actor.min_std,
        max_std=cfg.algo.actor.max_std,
        action_clip=cfg.algo.actor.action_clip,
        dtype=ctx.compute_dtype,
    )
    critic = DreamerCritic(
        dense_units=cfg.algo.critic.dense_units,
        mlp_layers=cfg.algo.critic.mlp_layers,
        bins=cfg.algo.critic.bins,
        dtype=ctx.compute_dtype,
    )

    dummy_obs = {}
    for k in cnn_keys:
        dummy_obs[k] = jnp.zeros((1, *cnn_shapes[k]), dtype=jnp.uint8)
    for k in mlp_keys:
        dummy_obs[k] = jnp.zeros((1, *mlp_shapes[k]), dtype=jnp.float32)
    act_dim_sum = int(sum(actions_dim))
    key = ctx.rng()
    wm_params = world_model.init(key, dummy_obs, jnp.zeros((1, act_dim_sum)), ctx.rng())
    actor_params = actor.init(ctx.rng(), jnp.zeros((1, latent_size)), ctx.rng())
    critic_params = critic.init(ctx.rng(), jnp.zeros((1, latent_size)))

    if cfg.algo.hafner_initialization:
        wm_params = {"params": apply_hafner_init(wm_params["params"], ctx.rng())}
        wm_params = {"params": zero_init_head(wm_params["params"], "reward_head")}
        actor_params = {"params": apply_hafner_init(actor_params["params"], ctx.rng())}
        critic_params = {"params": zero_init_head(critic_params["params"], "head")}

    target_critic_params = jax.tree.map(lambda x: x, critic_params)
    # shard_params == replicate on a model=1 mesh; with mesh.model>1 the large kernels
    # are column-sharded over the model axis (tensor parallelism via GSPMD).
    params = {
        "world_model": ctx.shard_params(wm_params),
        "actor": ctx.shard_params(actor_params),
        "critic": ctx.shard_params(critic_params),
        "target_critic": ctx.shard_params(target_critic_params),
    }
    return world_model, actor, critic, params, latent_size


def make_player_step(world_model: WorldModel, actor: DreamerActor, actions_dim: Sequence[int], discrete_size: int):
    """Build the pure player-step function: (params, state, obs, is_first, key) →
    (env_actions, stored_actions, new_state).  ``obs`` entries whose key starts with
    ``mask`` are forwarded to the actor (MinedojoActor's hierarchical action masks,
    reference ``PlayerDV3.get_actions`` mask plumbing)."""

    def player_step(params, state: PlayerState, obs, is_first, key, greedy: bool = False):
        k_repr, k_act = jax.random.split(key)
        wm, ap = params["world_model"], params["actor"]
        mask = {k: v for k, v in obs.items() if k.startswith("mask")} or None
        embed = world_model.apply(wm, obs, method=WorldModel.encode)
        h0, z0 = world_model.apply(wm, state.recurrent_state.shape[:-1], method=WorldModel.initial_states)
        recurrent = (1 - is_first) * state.recurrent_state + is_first * h0
        stoch = (1 - is_first) * state.stochastic_state + is_first * z0
        prev_actions = (1 - is_first) * state.actions
        recurrent = world_model.apply(
            wm,
            jnp.concatenate([stoch, prev_actions], -1),
            recurrent,
            method=lambda m, x, h: m.rssm.recurrent_model(x, h),
        )
        _, stoch_sample = world_model.apply(wm, recurrent, embed, k_repr, method=WorldModel.representation)
        stoch = stoch_sample.reshape(*stoch_sample.shape[:-2], -1)
        latent = jnp.concatenate([stoch, recurrent], -1)
        actions, _ = actor.apply(ap, latent, k_act, greedy, mask)
        stored = jnp.concatenate(actions, -1)
        return actions, stored, PlayerState(recurrent, stoch, stored)

    return player_step
