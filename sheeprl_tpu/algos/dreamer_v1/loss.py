"""DreamerV1 losses (reference: ``/root/reference/sheeprl/algos/dreamer_v1/loss.py``).

ELBO with a Normal-KL state loss clipped below by free nats (Eq. 10 of the PlaNet/DV1
papers, reference ``loss.py:41-95``): ``state_loss = max(KL(post || prior).mean(),
free_nats)``.  No KL balancing (that arrives in DV2).

Note: the reference's continue term (``loss.py:91``) reads ``+ qc.log_prob(targets)``
without negation — a sign slip that is dormant because ``use_continues`` defaults to
False for DV1; this implementation uses the correct negative log-likelihood.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp


def normal_kl(post_mean, post_std, prior_mean, prior_std) -> jax.Array:
    """KL( N(post) || N(prior) ) summed over the stochastic dimension."""
    var_ratio = (post_std / prior_std) ** 2
    t1 = ((post_mean - prior_mean) / prior_std) ** 2
    return 0.5 * jnp.sum(var_ratio + t1 - 1.0 - jnp.log(var_ratio), axis=-1)


def reconstruction_loss(
    observation_lp: jax.Array,  # [T, B]
    reward_lp: jax.Array,  # [T, B]
    posterior_mean_std: Tuple[jax.Array, jax.Array],
    prior_mean_std: Tuple[jax.Array, jax.Array],
    kl_free_nats: float = 3.0,
    kl_regularizer: float = 1.0,
    continue_lp: Optional[jax.Array] = None,
    continue_scale_factor: float = 10.0,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    observation_loss = -observation_lp.mean()
    reward_loss = -reward_lp.mean()
    kl = normal_kl(*posterior_mean_std, *prior_mean_std).mean()
    state_loss = jnp.maximum(kl, kl_free_nats)
    if continue_lp is not None:
        continue_loss = continue_scale_factor * -continue_lp.mean()
    else:
        continue_loss = jnp.zeros_like(reward_loss)
    total = kl_regularizer * state_loss + observation_loss + reward_loss + continue_loss
    metrics = {
        "Loss/world_model_loss": total,
        "Loss/observation_loss": observation_loss,
        "Loss/reward_loss": reward_loss,
        "Loss/state_loss": state_loss,
        "Loss/continue_loss": continue_loss,
        "State/kl": kl,
    }
    return total, metrics
