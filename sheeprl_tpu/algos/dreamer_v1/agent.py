"""DreamerV1 agent modules (reference: ``/root/reference/sheeprl/algos/dreamer_v1/agent.py``).

DV1 shares the DV2 encoder/decoder/actor/critic (the reference imports them,
``dreamer_v1/agent.py:16-27``); what is specific to DV1:

* **continuous Gaussian stochastic state** (size 30, no discrete categoricals):
  representation/transition MLPs emit ``2·stoch`` (mean, std) with
  ``std = softplus(std) + min_std`` (reference ``dreamer_v1/utils.py:80-108``);
* a plain GRU recurrent model — Dense+ELU into a standard (non-LayerNorm,
  no update-bias) GRU cell (reference ``agent.py:31-61``);
* **no ``is_first`` masking** in ``dynamic`` (reference ``agent.py:97-134``) — state
  resets happen only on the player side via ``init_states``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import gymnasium
import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v2.agent import (
    ActorV2,
    CNNDecoderV2,
    CriticV2,
    EncoderV2,
    MinedojoActorV2,
    MLPDecoderV2,
    _xavier_normal_init,
    add_exploration_noise,
    exploration_amount,
)
from sheeprl_tpu.algos.dreamer_v3.agent import PlayerState, parse_actions_dim  # noqa: F401
from sheeprl_tpu.models.blocks import MLP

Dtype = Any


def compute_stochastic_state(
    key: Optional[jax.Array], state_information: jax.Array, min_std: float = 0.1
) -> Tuple[Tuple[jax.Array, jax.Array], jax.Array]:
    """(mean, std) split + reparameterised Gaussian sample (reference
    ``dreamer_v1/utils.py:80-108``)."""
    mean, std = jnp.split(state_information, 2, -1)
    std = jax.nn.softplus(std) + min_std
    if key is None:
        return (mean, std), mean
    sample = mean + std * jax.random.normal(key, mean.shape)
    return (mean, std), sample


class RecurrentModelV1(nn.Module):
    """Dense+act → plain GRU cell (reference ``agent.py:31-61``)."""

    recurrent_state_size: int
    activation: str = "elu"
    dtype: Dtype = jnp.float32

    def setup(self):
        self.mlp = MLP(
            hidden_sizes=(self.recurrent_state_size,),
            activation=self.activation,
            layer_norm=False,
            dtype=self.dtype,
            name="input_proj",
        )
        self.rnn = nn.GRUCell(features=self.recurrent_state_size, dtype=self.dtype)

    def __call__(self, x: jax.Array, recurrent_state: jax.Array) -> jax.Array:
        feat = self.mlp(x)
        h, _ = self.rnn(recurrent_state.astype(self.dtype), feat)
        return h.astype(jnp.float32)


class RSSMV1(nn.Module):
    """Continuous-Gaussian RSSM (reference ``agent.py:64-189``)."""

    stochastic_size: int = 30
    recurrent_state_size: int = 200
    transition_hidden_size: int = 200
    representation_hidden_size: int = 200
    min_std: float = 0.1
    activation: str = "elu"
    dtype: Dtype = jnp.float32

    def setup(self):
        self.recurrent_model = RecurrentModelV1(
            recurrent_state_size=self.recurrent_state_size, activation=self.activation, dtype=self.dtype
        )
        self.representation_model = MLP(
            hidden_sizes=(self.representation_hidden_size,),
            output_dim=self.stochastic_size * 2,
            activation=self.activation,
            dtype=self.dtype,
        )
        self.transition_model = MLP(
            hidden_sizes=(self.transition_hidden_size,),
            output_dim=self.stochastic_size * 2,
            activation=self.activation,
            dtype=self.dtype,
        )

    def _representation(self, recurrent_state: jax.Array, embedded_obs: jax.Array, key: Optional[jax.Array]):
        out = self.representation_model(jnp.concatenate([recurrent_state, embedded_obs], -1)).astype(jnp.float32)
        return compute_stochastic_state(key, out, self.min_std)

    def _transition(self, recurrent_state: jax.Array, key: Optional[jax.Array]):
        out = self.transition_model(recurrent_state).astype(jnp.float32)
        return compute_stochastic_state(key, out, self.min_std)

    def dynamic(self, posterior: jax.Array, recurrent_state: jax.Array, action: jax.Array, embedded_obs: jax.Array, key: jax.Array):
        """One posterior step — NO ``is_first`` reset, per DV1 (reference ``agent.py:97-134``)."""
        k1, k2 = jax.random.split(key)
        recurrent_state = self.recurrent_model(jnp.concatenate([posterior, action], -1), recurrent_state)
        prior_mean_std, prior = self._transition(recurrent_state, k1)
        posterior_mean_std, posterior_sample = self._representation(recurrent_state, embedded_obs, k2)
        return recurrent_state, posterior_sample, prior, posterior_mean_std, prior_mean_std

    def imagination(self, stochastic_state: jax.Array, recurrent_state: jax.Array, actions: jax.Array, key: jax.Array):
        recurrent_state = self.recurrent_model(jnp.concatenate([stochastic_state, actions], -1), recurrent_state)
        _, imagined = self._transition(recurrent_state, key)
        return imagined, recurrent_state


class WorldModelV1(nn.Module):
    """Encoder + Gaussian RSSM + decoders + reward (+ optional continue) heads."""

    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    cnn_shapes: Dict[str, Tuple[int, ...]]
    mlp_shapes: Dict[str, Tuple[int, ...]]
    cnn_channels_multiplier: int = 32
    dense_units: int = 400
    mlp_layers: int = 4
    stochastic_size: int = 30
    recurrent_state_size: int = 200
    transition_hidden_size: int = 200
    representation_hidden_size: int = 200
    min_std: float = 0.1
    dense_act: str = "elu"
    cnn_act: str = "relu"
    use_continues: bool = False
    image_size: int = 64
    dtype: Dtype = jnp.float32

    def setup(self):
        self.encoder = EncoderV2(
            cnn_keys=self.cnn_keys,
            mlp_keys=self.mlp_keys,
            cnn_channels_multiplier=self.cnn_channels_multiplier,
            dense_units=self.dense_units,
            mlp_layers=self.mlp_layers,
            activation=self.dense_act,
            layer_norm=False,
            dtype=self.dtype,
        )
        self.rssm = RSSMV1(
            stochastic_size=self.stochastic_size,
            recurrent_state_size=self.recurrent_state_size,
            transition_hidden_size=self.transition_hidden_size,
            representation_hidden_size=self.representation_hidden_size,
            min_std=self.min_std,
            activation=self.dense_act,
            dtype=self.dtype,
        )
        if self.cnn_keys:
            final = (self.image_size - 4) // 2 + 1
            for _ in range(3):
                final = (final - 4) // 2 + 1
            self.observation_model_cnn = CNNDecoderV2(
                output_shapes=self.cnn_shapes,
                cnn_encoder_output_dim=final * final * self.cnn_channels_multiplier * 8,
                channels_multiplier=self.cnn_channels_multiplier,
                activation=self.cnn_act,
                layer_norm=False,
                dtype=self.dtype,
            )
        if self.mlp_keys:
            self.observation_model_mlp = MLPDecoderV2(
                output_shapes=self.mlp_shapes,
                dense_units=self.dense_units,
                mlp_layers=self.mlp_layers,
                activation=self.dense_act,
                layer_norm=False,
                dtype=self.dtype,
            )
        self.reward_model = MLP(
            hidden_sizes=(self.dense_units,) * self.mlp_layers,
            output_dim=1,
            activation=self.dense_act,
            dtype=self.dtype,
        )
        if self.use_continues:
            self.continue_model = MLP(
                hidden_sizes=(self.dense_units,) * self.mlp_layers,
                output_dim=1,
                activation=self.dense_act,
                dtype=self.dtype,
            )

    def encode(self, obs: Dict[str, jax.Array]) -> jax.Array:
        return self.encoder(obs)

    def decode(self, latent: jax.Array) -> Dict[str, jax.Array]:
        out: Dict[str, jax.Array] = {}
        if self.cnn_keys:
            out.update(self.observation_model_cnn(latent))
        if self.mlp_keys:
            out.update(self.observation_model_mlp(latent))
        return out

    def reward(self, latent: jax.Array) -> jax.Array:
        return self.reward_model(latent).astype(jnp.float32)

    def continues(self, latent: jax.Array) -> jax.Array:
        return self.continue_model(latent).astype(jnp.float32)

    def dynamic(self, *args, **kwargs):
        return self.rssm.dynamic(*args, **kwargs)

    def imagination(self, *args, **kwargs):
        return self.rssm.imagination(*args, **kwargs)

    def representation(self, recurrent_state, embedded_obs, key):
        return self.rssm._representation(recurrent_state, embedded_obs, key)

    def __call__(self, obs: Dict[str, jax.Array], action: jax.Array, key: jax.Array):
        embed = self.encoder(obs)
        batch_shape = embed.shape[:-1]
        h0 = jnp.zeros((*batch_shape, self.recurrent_state_size))
        z0 = jnp.zeros((*batch_shape, self.stochastic_size))
        h, z, prior, post_ms, prior_ms = self.rssm.dynamic(z0, h0, action, embed, key)
        latent = jnp.concatenate([z, h], -1)
        recon = self.decode(latent)
        out = self.reward(latent)
        if self.use_continues:
            out = out + 0.0 * self.continues(latent)
        return out, recon


def build_agent(
    ctx,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
):
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cnn_shapes = {k: tuple(obs_space[k].shape) for k in cnn_keys}
    mlp_shapes = {k: tuple(obs_space[k].shape) for k in mlp_keys}
    wm_cfg = cfg.algo.world_model

    world_model = WorldModelV1(
        cnn_keys=cnn_keys,
        mlp_keys=mlp_keys,
        cnn_shapes=cnn_shapes,
        mlp_shapes=mlp_shapes,
        cnn_channels_multiplier=wm_cfg.encoder.cnn_channels_multiplier,
        dense_units=cfg.algo.dense_units,
        mlp_layers=cfg.algo.mlp_layers,
        stochastic_size=wm_cfg.stochastic_size,
        recurrent_state_size=wm_cfg.recurrent_model.recurrent_state_size,
        transition_hidden_size=wm_cfg.transition_model.hidden_size,
        representation_hidden_size=wm_cfg.representation_model.hidden_size,
        min_std=wm_cfg.min_std,
        dense_act=cfg.algo.dense_act,
        cnn_act=cfg.algo.cnn_act,
        use_continues=wm_cfg.use_continues,
        image_size=cfg.env.screen_size,
        dtype=ctx.compute_dtype,
    )
    latent_size = wm_cfg.stochastic_size + wm_cfg.recurrent_model.recurrent_state_size
    is_minedojo = "minedojo" in str(cfg.env.get("wrapper", {}).get("_target_", "")).lower()
    actor_cls = MinedojoActorV2 if is_minedojo else ActorV2
    actor = actor_cls(
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        distribution=cfg.distribution.get("type", "auto"),
        dense_units=cfg.algo.actor.dense_units,
        mlp_layers=cfg.algo.actor.mlp_layers,
        activation=cfg.algo.dense_act,
        layer_norm=False,
        init_std=cfg.algo.actor.init_std,
        min_std=cfg.algo.actor.min_std,
        dtype=ctx.compute_dtype,
    )
    critic = CriticV2(
        dense_units=cfg.algo.critic.dense_units,
        mlp_layers=cfg.algo.critic.mlp_layers,
        activation=cfg.algo.dense_act,
        layer_norm=False,
        dtype=ctx.compute_dtype,
    )

    dummy_obs = {}
    for k in cnn_keys:
        dummy_obs[k] = jnp.zeros((1, *cnn_shapes[k]), dtype=jnp.uint8)
    for k in mlp_keys:
        dummy_obs[k] = jnp.zeros((1, *mlp_shapes[k]), dtype=jnp.float32)
    act_dim_sum = int(sum(actions_dim))
    wm_params = world_model.init(ctx.rng(), dummy_obs, jnp.zeros((1, act_dim_sum)), ctx.rng())
    actor_params = actor.init(ctx.rng(), jnp.zeros((1, latent_size)), ctx.rng())
    critic_params = critic.init(ctx.rng(), jnp.zeros((1, latent_size)))

    wm_params = {"params": _xavier_normal_init(wm_params["params"], ctx.rng())}
    actor_params = {"params": _xavier_normal_init(actor_params["params"], ctx.rng())}
    critic_params = {"params": _xavier_normal_init(critic_params["params"], ctx.rng())}

    params = {
        "world_model": ctx.replicate(wm_params),
        "actor": ctx.replicate(actor_params),
        "critic": ctx.replicate(critic_params),
    }
    return world_model, actor, critic, params, latent_size


def make_player_step(world_model: WorldModelV1, actor: ActorV2, actions_dim: Sequence[int], is_continuous: bool):
    """Pure player step (reference ``PlayerDV1``, ``agent.py:219-326``): zero resets on
    ``is_first`` (the functional analogue of ``init_states``), optional exploration noise."""

    def player_step(params, state: PlayerState, obs, is_first, key, expl_amount=0.0, greedy: bool = False):
        k_repr, k_act, k_expl = jax.random.split(key, 3)
        wm, ap = params["world_model"], params["actor"]
        mask = {k: v for k, v in obs.items() if k.startswith("mask")} or None
        embed = world_model.apply(wm, obs, method=WorldModelV1.encode)
        recurrent = (1 - is_first) * state.recurrent_state
        stoch = (1 - is_first) * state.stochastic_state
        prev_actions = (1 - is_first) * state.actions
        recurrent = world_model.apply(
            wm,
            jnp.concatenate([stoch, prev_actions], -1),
            recurrent,
            method=lambda m, x, h: m.rssm.recurrent_model(x, h),
        )
        _, stoch = world_model.apply(wm, recurrent, embed, k_repr, method=WorldModelV1.representation)
        latent = jnp.concatenate([stoch, recurrent], -1)
        actions, _ = actor.apply(ap, latent, k_act, greedy, mask)
        if not greedy:
            actions = add_exploration_noise(actions, jnp.asarray(expl_amount), k_expl, is_continuous)
        stored = jnp.concatenate(actions, -1)
        return actions, stored, PlayerState(recurrent, stoch, stored)

    return player_step
