"""DreamerV1 helpers (reference: ``/root/reference/sheeprl/algos/dreamer_v1/utils.py``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v3.utils import prepare_obs, test  # noqa: F401

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/kl",
    "State/post_entropy",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
    "State/prior_entropy",
    "Params/exploration_amount",
}
MODELS_TO_REGISTER = {"world_model", "actor", "critic"}


def compute_lambda_values(
    rewards: jax.Array,  # [H, N, 1] rewards at imagined states 0..H-1
    values: jax.Array,  # [H, N, 1]
    continues: jax.Array,  # [H, N, 1] (γ-scaled)
    lmbda: float = 0.95,
) -> jax.Array:
    """DV1 λ-targets (reference ``dreamer_v1/utils.py:42-78``): ``H-1`` targets where
    ``λ[i] = r[i] + c[i]·(1-λ)·V[i+1] + λ·c[i]·λ[i+1]`` for ``i < H-2`` and the last
    entry bootstraps the full value: ``λ[H-2] = r[H-2] + c[H-2]·V[H-1]``."""
    horizon = rewards.shape[0]
    next_vals = jnp.concatenate([values[1 : horizon - 1] * (1 - lmbda), values[horizon - 1 : horizon]], 0)
    inputs = rewards[: horizon - 1] + continues[: horizon - 1] * next_vals

    def step(agg, x):
        inp, cont = x
        agg = inp + cont * lmbda * agg
        return agg, agg

    _, lv = jax.lax.scan(step, jnp.zeros_like(values[0]), (inputs, continues[: horizon - 1]), reverse=True)
    return lv
