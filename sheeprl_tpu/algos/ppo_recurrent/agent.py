"""Recurrent PPO agent (reference: ``/root/reference/sheeprl/algos/ppo_recurrent/agent.py:83-…``).

Encoder → (pre-RNN MLP) → LSTM → (post-RNN MLP) → actor/critic heads.  The LSTM input is
the encoded observation concatenated with the previous action (reference ``:133-138``).

TPU-native deviation (documented): instead of the reference's padded per-episode
sequences with masks (``ppo_recurrent.py:39-118``), sequences are the fixed-shape
``[rollout_steps, num_envs]`` rollout with hidden-state resets at episode starts applied
*inside* the scan (``is_first`` masking, same trick as the RSSM).  The objective is the
same; shapes are static so the whole update stays one jit."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.ppo.agent import parse_action_space
from sheeprl_tpu.models.blocks import MLP, MultiEncoder


class RecurrentPPOAgent(nn.Module):
    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    action_dims: Sequence[int]
    is_continuous: bool
    cnn_stacked: bool = False
    cnn_features_dim: int = 512
    mlp_features_dim: int = 64
    dense_units: int = 64
    mlp_layers: int = 1
    dense_act: str = "tanh"
    layer_norm: bool = False
    lstm_hidden_size: int = 64
    pre_rnn_mlp: bool = False
    post_rnn_mlp: bool = False
    dtype: Any = jnp.float32

    def setup(self):
        self.feature_extractor = MultiEncoder(
            cnn_keys=self.cnn_keys,
            mlp_keys=self.mlp_keys,
            cnn_stacked=self.cnn_stacked,
            cnn_features_dim=self.cnn_features_dim,
            mlp_hidden_sizes=(self.dense_units,) * self.mlp_layers,
            mlp_features_dim=self.mlp_features_dim,
            activation=self.dense_act,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
        )
        if self.pre_rnn_mlp:
            self.pre_mlp = MLP(
                hidden_sizes=(self.dense_units,),
                activation=self.dense_act,
                layer_norm=self.layer_norm,
                dtype=self.dtype,
            )
        self.cell = nn.OptimizedLSTMCell(self.lstm_hidden_size, dtype=self.dtype)
        if self.post_rnn_mlp:
            self.post_mlp = MLP(
                hidden_sizes=(self.dense_units,),
                activation=self.dense_act,
                layer_norm=self.layer_norm,
                dtype=self.dtype,
            )
        self.actor_backbone = MLP(
            hidden_sizes=(self.dense_units,) * self.mlp_layers,
            activation=self.dense_act,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
        )
        if self.is_continuous:
            self.actor_heads = [nn.Dense(2 * self.action_dims[0], dtype=self.dtype)]
        else:
            self.actor_heads = [nn.Dense(d, dtype=self.dtype) for d in self.action_dims]
        self.critic = MLP(
            hidden_sizes=(self.dense_units,) * self.mlp_layers,
            output_dim=1,
            activation=self.dense_act,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
        )

    def _heads(self, hidden: jax.Array) -> Tuple[List[jax.Array], jax.Array]:
        feat = self.post_mlp(hidden) if self.post_rnn_mlp else hidden
        pre_actor = self.actor_backbone(feat)
        actor_out = [h(pre_actor).astype(jnp.float32) for h in self.actor_heads]
        value = self.critic(feat).astype(jnp.float32)
        return actor_out, value

    def _rnn_input(self, obs: Dict[str, jax.Array], prev_actions: jax.Array) -> jax.Array:
        feat = self.feature_extractor(obs)
        x = jnp.concatenate([feat, prev_actions.astype(feat.dtype)], -1)
        if self.pre_rnn_mlp:
            x = self.pre_mlp(x)
        return x

    def step(
        self,
        obs: Dict[str, jax.Array],  # [B, ...]
        prev_actions: jax.Array,  # [B, A]
        is_first: jax.Array,  # [B, 1]
        state: Tuple[jax.Array, jax.Array],
    ):
        """Single env-side step: returns (actor_out, value, new_state)."""
        c, h = state
        c = (1 - is_first) * c
        h = (1 - is_first) * h
        x = self._rnn_input(obs, (1 - is_first) * prev_actions)
        (c, h), out = self.cell((c, h), x)
        actor_out, value = self._heads(out.astype(jnp.float32))
        return actor_out, value, (c, h)

    def __call__(
        self,
        obs: Dict[str, jax.Array],  # [T, B, ...]
        prev_actions: jax.Array,  # [T, B, A]
        is_first: jax.Array,  # [T, B, 1]
        initial_state: Tuple[jax.Array, jax.Array],  # ([B,H], [B,H])
    ):
        """Sequence forward with in-scan resets; returns (actor_out [T,B,...], values)."""
        xs = self._rnn_input(obs, prev_actions * (1 - is_first))

        def scan_step(carry, t):
            c, h = carry
            x, first = t
            c = (1 - first) * c
            h = (1 - first) * h
            (c, h), out = self.cell((c, h), x)
            return (c, h), out

        _, outs = nn.scan(
            lambda mdl, carry, t: scan_step(carry, t),
            variable_broadcast="params",
            split_rngs={"params": False},
        )(self, initial_state, (xs, is_first))
        actor_out, values = self._heads(outs.astype(jnp.float32))
        return actor_out, values


def build_agent(ctx, action_space, obs_space, cfg) -> Tuple[RecurrentPPOAgent, Any]:
    is_continuous, dims = parse_action_space(action_space)
    agent = RecurrentPPOAgent(
        cnn_keys=list(cfg.algo.cnn_keys.encoder),
        mlp_keys=list(cfg.algo.mlp_keys.encoder),
        action_dims=dims,
        is_continuous=is_continuous,
        cnn_stacked=any(len(obs_space[k].shape) == 4 for k in cfg.algo.cnn_keys.encoder),
        cnn_features_dim=cfg.algo.encoder.cnn_features_dim,
        mlp_features_dim=cfg.algo.encoder.mlp_features_dim,
        dense_units=cfg.algo.dense_units,
        mlp_layers=cfg.algo.mlp_layers,
        dense_act=cfg.algo.dense_act,
        layer_norm=cfg.algo.layer_norm,
        lstm_hidden_size=cfg.algo.rnn.lstm.hidden_size,
        pre_rnn_mlp=cfg.algo.rnn.pre_rnn_mlp.apply,
        post_rnn_mlp=cfg.algo.rnn.post_rnn_mlp.apply,
        dtype=ctx.compute_dtype,
    )
    dummy_obs = {}
    for k in list(cfg.algo.cnn_keys.encoder) + list(cfg.algo.mlp_keys.encoder):
        space = obs_space[k]
        dummy_obs[k] = jnp.zeros((1, *space.shape), dtype=space.dtype)
    act_sum = int(sum(dims))
    h = cfg.algo.rnn.lstm.hidden_size
    state0 = (jnp.zeros((1, h)), jnp.zeros((1, h)))
    params = agent.init(
        ctx.rng(), dummy_obs, jnp.zeros((1, act_sum)), jnp.ones((1, 1)), state0, method=RecurrentPPOAgent.step
    )
    params = ctx.replicate(params)
    return agent, params
