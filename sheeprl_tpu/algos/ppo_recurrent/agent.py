"""Recurrent PPO agent (reference: ``/root/reference/sheeprl/algos/ppo_recurrent/agent.py:83-…``).

Encoder → (pre-RNN MLP) → LSTM → (post-RNN MLP) → actor/critic heads.  The LSTM input is
the encoded observation concatenated with the previous action (reference ``:133-138``).

TPU-native deviation (documented): instead of the reference's padded per-episode
sequences with masks (``ppo_recurrent.py:39-118``), sequences are the fixed-shape
``[rollout_steps, num_envs]`` rollout with hidden-state resets at episode starts applied
*inside* the scan (``is_first`` masking, same trick as the RSSM).  The objective is the
same; shapes are static so the whole update stays one jit."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.ppo.agent import parse_action_space
from sheeprl_tpu.models.blocks import MLP, MultiEncoder
from sheeprl_tpu.ops.ring_attention import reference_attention


class RecurrentPPOAgent(nn.Module):
    """``sequence_model="lstm"`` (default, reference parity) or ``"attention"`` — a
    causal windowed self-attention sequence mixer in place of the LSTM.  The
    attention variant is the ``sequence`` mesh axis's training-path consumer: with
    ``attention_fn`` set (built from ``make_ring_attention``) the training-time
    attention runs sequence-parallel over the ring; env-side steps carry a rolling
    window of the last ``attn_window`` inputs (reset at episode starts), which
    matches the training masks exactly because the loop also resets the window at
    every rollout start."""

    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    action_dims: Sequence[int]
    is_continuous: bool
    cnn_stacked: bool = False
    cnn_features_dim: int = 512
    mlp_features_dim: int = 64
    dense_units: int = 64
    mlp_layers: int = 1
    dense_act: str = "tanh"
    layer_norm: bool = False
    lstm_hidden_size: int = 64
    pre_rnn_mlp: bool = False
    post_rnn_mlp: bool = False
    sequence_model: str = "lstm"
    attn_heads: int = 4
    attn_window: int = 64
    attention_fn: Any = None  # static; sequence-parallel ring attention when set
    dtype: Any = jnp.float32

    def setup(self):
        self.feature_extractor = MultiEncoder(
            cnn_keys=self.cnn_keys,
            mlp_keys=self.mlp_keys,
            cnn_stacked=self.cnn_stacked,
            cnn_features_dim=self.cnn_features_dim,
            mlp_hidden_sizes=(self.dense_units,) * self.mlp_layers,
            mlp_features_dim=self.mlp_features_dim,
            activation=self.dense_act,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
        )
        if self.pre_rnn_mlp:
            self.pre_mlp = MLP(
                hidden_sizes=(self.dense_units,),
                activation=self.dense_act,
                layer_norm=self.layer_norm,
                dtype=self.dtype,
            )
        if self.sequence_model == "attention":
            h = self.lstm_hidden_size  # model width shared with the lstm variant
            self.attn_in = nn.Dense(h, dtype=self.dtype)
            self.attn_q = nn.Dense(h, dtype=self.dtype)
            self.attn_k = nn.Dense(h, dtype=self.dtype)
            self.attn_v = nn.Dense(h, dtype=self.dtype)
            self.attn_out = nn.Dense(h, dtype=self.dtype)
            self.attn_ln = nn.LayerNorm(dtype=self.dtype)
        else:
            self.cell = nn.OptimizedLSTMCell(self.lstm_hidden_size, dtype=self.dtype)
        if self.post_rnn_mlp:
            self.post_mlp = MLP(
                hidden_sizes=(self.dense_units,),
                activation=self.dense_act,
                layer_norm=self.layer_norm,
                dtype=self.dtype,
            )
        self.actor_backbone = MLP(
            hidden_sizes=(self.dense_units,) * self.mlp_layers,
            activation=self.dense_act,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
        )
        if self.is_continuous:
            self.actor_heads = [nn.Dense(2 * self.action_dims[0], dtype=self.dtype)]
        else:
            self.actor_heads = [nn.Dense(d, dtype=self.dtype) for d in self.action_dims]
        self.critic = MLP(
            hidden_sizes=(self.dense_units,) * self.mlp_layers,
            output_dim=1,
            activation=self.dense_act,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
        )

    def _heads(self, hidden: jax.Array) -> Tuple[List[jax.Array], jax.Array]:
        feat = self.post_mlp(hidden) if self.post_rnn_mlp else hidden
        pre_actor = self.actor_backbone(feat)
        actor_out = [h(pre_actor).astype(jnp.float32) for h in self.actor_heads]
        value = self.critic(feat).astype(jnp.float32)
        return actor_out, value

    def _rnn_input(self, obs: Dict[str, jax.Array], prev_actions: jax.Array) -> jax.Array:
        feat = self.feature_extractor(obs)
        x = jnp.concatenate([feat, prev_actions.astype(feat.dtype)], -1)
        if self.pre_rnn_mlp:
            x = self.pre_mlp(x)
        return x

    def _split_heads(self, x: jax.Array) -> jax.Array:
        *lead, h = x.shape
        return x.reshape(*lead, self.attn_heads, h // self.attn_heads)

    def step(
        self,
        obs: Dict[str, jax.Array],  # [B, ...]
        prev_actions: jax.Array,  # [B, A]
        is_first: jax.Array,  # [B, 1]
        state: Tuple[jax.Array, jax.Array],
    ):
        """Single env-side step: returns (actor_out, value, new_state)."""
        x = self._rnn_input(obs, (1 - is_first) * prev_actions)
        if self.sequence_model == "attention":
            window, valid = state  # [B, W, H], [B, W]
            xp = self.attn_in(x)
            # Episode start: forget the previous episode's window.
            window = (1 - is_first[..., None]) * window
            valid = (1 - is_first) * valid
            window = jnp.concatenate([window[:, 1:], xp[:, None].astype(window.dtype)], 1)
            valid = jnp.concatenate([valid[:, 1:], jnp.ones_like(valid[:, :1])], 1)
            q = self._split_heads(self.attn_q(xp))[:, None]  # [B, 1, nh, hd]
            k = self._split_heads(self.attn_k(window.astype(xp.dtype)))
            v = self._split_heads(self.attn_v(window.astype(xp.dtype)))
            s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
            s = s / jnp.sqrt(jnp.asarray(k.shape[-1], jnp.float32))
            s = jnp.where(valid[:, None, None, :] > 0, s, jnp.finfo(jnp.float32).min)
            p = jax.nn.softmax(s, -1)
            o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
            o = o.reshape(xp.shape[0], -1).astype(xp.dtype)
            out = self.attn_ln(xp + self.attn_out(o))
            actor_out, value = self._heads(out.astype(jnp.float32))
            return actor_out, value, (window, valid)
        c, h = state
        c = (1 - is_first) * c
        h = (1 - is_first) * h
        (c, h), out = self.cell((c, h), x)
        actor_out, value = self._heads(out.astype(jnp.float32))
        return actor_out, value, (c, h)

    def __call__(
        self,
        obs: Dict[str, jax.Array],  # [T, B, ...]
        prev_actions: jax.Array,  # [T, B, A]
        is_first: jax.Array,  # [T, B, 1]
        initial_state: Tuple[jax.Array, jax.Array],  # ([B,H], [B,H])
    ):
        """Sequence forward with in-scan resets; returns (actor_out [T,B,...], values)."""
        xs = self._rnn_input(obs, prev_actions * (1 - is_first))

        if self.sequence_model == "attention":
            # Causal windowed attention over the rollout, masked at episode
            # boundaries (segments = running count of is_first).  ``initial_state``
            # is unused: the loop resets the acting window at every rollout start,
            # so training and acting see identical contexts.
            T, B = xs.shape[:2]
            xp = self.attn_in(xs)  # [T, B, H]
            xbt = jnp.swapaxes(xp, 0, 1)  # [B, T, H]
            q = self._split_heads(self.attn_q(xbt))
            k = self._split_heads(self.attn_k(xbt))
            v = self._split_heads(self.attn_v(xbt))
            segs = jnp.swapaxes(jnp.cumsum(is_first[..., 0], axis=0), 0, 1).astype(jnp.int32)
            if self.attention_fn is not None:  # sequence-parallel ring
                o = self.attention_fn(q, k, v, segs)
            else:
                o = reference_attention(
                    q, k, v, causal=True, segment_ids=segs, window=self.attn_window
                )
            o = jnp.swapaxes(o.reshape(B, T, -1), 0, 1).astype(xp.dtype)  # [T, B, H]
            outs = self.attn_ln(xp + self.attn_out(o))
            actor_out, values = self._heads(outs.astype(jnp.float32))
            return actor_out, values

        def scan_step(mdl, carry, t):
            # The body must touch submodules through the TRANSFORMED module
            # ``mdl`` nn.scan hands it — reaching through the closed-over
            # ``self`` mixes the outer module with the scan's inner trace, which
            # newer flax rejects with JaxTransformError.
            c, h = carry
            x, first = t
            c = (1 - first) * c
            h = (1 - first) * h
            (c, h), out = mdl.cell((c, h), x)
            return (c, h), out

        _, outs = nn.scan(
            scan_step,
            variable_broadcast="params",
            split_rngs={"params": False},
        )(self, initial_state, (xs, is_first))
        actor_out, values = self._heads(outs.astype(jnp.float32))
        return actor_out, values


def make_zero_state(cfg):
    """Per-env zero carry matching ``algo.sequence_model``: LSTM ``(c, h)`` or the
    attention variant's ``(window, valid)`` rolling context."""
    h = cfg.algo.rnn.lstm.hidden_size
    if cfg.algo.get("sequence_model", "lstm") == "attention":
        w = int(cfg.algo.attention.window)

        def zero_state(n: int):
            return (jnp.zeros((n, w, h)), jnp.zeros((n, w)))

    else:

        def zero_state(n: int):
            return (jnp.zeros((n, h)), jnp.zeros((n, h)))

    return zero_state


def build_agent(ctx, action_space, obs_space, cfg) -> Tuple[RecurrentPPOAgent, Any]:
    is_continuous, dims = parse_action_space(action_space)
    sequence_model = cfg.algo.get("sequence_model", "lstm")
    attention_fn = None
    if sequence_model == "attention" and ctx.mesh.shape.get("sequence", 1) > 1:
        from sheeprl_tpu.ops.ring_attention import make_ring_attention

        attention_fn = make_ring_attention(
            ctx.mesh, causal=True, window=int(cfg.algo.attention.window)
        )
    agent = RecurrentPPOAgent(
        cnn_keys=list(cfg.algo.cnn_keys.encoder),
        mlp_keys=list(cfg.algo.mlp_keys.encoder),
        action_dims=dims,
        is_continuous=is_continuous,
        cnn_stacked=any(len(obs_space[k].shape) == 4 for k in cfg.algo.cnn_keys.encoder),
        cnn_features_dim=cfg.algo.encoder.cnn_features_dim,
        mlp_features_dim=cfg.algo.encoder.mlp_features_dim,
        dense_units=cfg.algo.dense_units,
        mlp_layers=cfg.algo.mlp_layers,
        dense_act=cfg.algo.dense_act,
        layer_norm=cfg.algo.layer_norm,
        lstm_hidden_size=cfg.algo.rnn.lstm.hidden_size,
        pre_rnn_mlp=cfg.algo.rnn.pre_rnn_mlp.apply,
        post_rnn_mlp=cfg.algo.rnn.post_rnn_mlp.apply,
        sequence_model=sequence_model,
        attn_heads=int(cfg.algo.get("attention", {}).get("num_heads", 4)),
        attn_window=int(cfg.algo.get("attention", {}).get("window", 64)),
        attention_fn=attention_fn,
        dtype=ctx.compute_dtype,
    )
    dummy_obs = {}
    for k in list(cfg.algo.cnn_keys.encoder) + list(cfg.algo.mlp_keys.encoder):
        space = obs_space[k]
        dummy_obs[k] = jnp.zeros((1, *space.shape), dtype=space.dtype)
    act_sum = int(sum(dims))
    state0 = make_zero_state(cfg)(1)
    params = agent.init(
        ctx.rng(), dummy_obs, jnp.zeros((1, act_sum)), jnp.ones((1, 1)), state0, method=RecurrentPPOAgent.step
    )
    params = ctx.replicate(params)
    return agent, params
