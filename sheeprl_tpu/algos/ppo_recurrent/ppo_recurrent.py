"""Recurrent PPO training loop (reference: ``algos/ppo_recurrent/ppo_recurrent.py:120-…``).

Rollout carries the LSTM state per env (reset at episode starts); the update runs BPTT
over the fixed ``[rollout_steps, num_envs]`` sequences from the stored initial state,
minibatching over the env/sequence axis — ``update_epochs`` × sequence-minibatches in
one jitted ``lax.scan`` chain, like the feed-forward PPO."""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.ppo.loss import entropy_loss, policy_loss, value_loss
from sheeprl_tpu.algos.ppo.ppo import make_optimizer
from sheeprl_tpu.algos.ppo.utils import AGGREGATOR_KEYS, log_prob_and_entropy, prepare_obs, sample_actions
from sheeprl_tpu.algos.ppo_recurrent.agent import RecurrentPPOAgent, build_agent, make_zero_state
from sheeprl_tpu.analysis.strict import assert_finite, maybe_inject_nonfinite, strict_guard
from sheeprl_tpu.checkpoint.manager import CheckpointManager
from sheeprl_tpu.fault.guard import TrainingGuard
from sheeprl_tpu.config.core import save_config
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.obs import perf as obs_perf
from sheeprl_tpu.obs import TrainingMonitor, flight_recorder
from sheeprl_tpu.obs.health import diagnostics, health_enabled
from sheeprl_tpu.utils.env import make_vector_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, record_episode_stats
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import gae, normalize_tensor, polynomial_decay


def _onehot_actions(env_act: np.ndarray, actions_dim, is_continuous: bool) -> np.ndarray:
    if is_continuous:
        return env_act.astype(np.float32)
    n = env_act.shape[0]
    out = []
    acts = env_act.reshape(n, -1)
    for i, d in enumerate(actions_dim):
        oh = np.zeros((n, d), dtype=np.float32)
        oh[np.arange(n), acts[:, i].astype(int)] = 1.0
        out.append(oh)
    return np.concatenate(out, -1)


def make_ppo_recurrent_train_fn(ctx, agent, cfg, obs_keys):
    """Optimizer + the jitted BPTT sequence-minibatch update.

    Module-level (rather than a closure in ``main``) so the IR audit
    (``sheeprl_tpu.analysis.ir``) can AOT-lower the exact update the entry point
    jits — the same reason ``make_a2c_train_fn`` moved out for the flight
    recorder."""
    opt = make_optimizer(cfg.algo.optimizer, cfg.algo.max_grad_norm)
    is_continuous = agent.is_continuous
    health = health_enabled(cfg)  # trace-time constant (obs/health.py)
    num_envs = cfg.env.num_envs
    num_batches = max(int(cfg.algo.per_rank_num_batches), 1)
    if num_envs % num_batches != 0:
        raise ValueError(
            f"env.num_envs ({num_envs}) must be divisible by algo.per_rank_num_batches "
            f"({num_batches}): sequence minibatches must be equally sized for static shapes."
        )
    mb_envs = num_envs // num_batches

    def seq_loss_fn(p, batch, clip_coef, ent_coef):
        actor_out, values = agent.apply(
            p,
            {k: batch[k] for k in obs_keys},
            batch["prev_actions"],
            batch["is_first"],
            (batch["c0"], batch["h0"]),
        )
        logprob, entropy = log_prob_and_entropy(actor_out, batch["actions"], is_continuous)
        adv = batch["advantages"]
        if cfg.algo.normalize_advantages:
            adv = normalize_tensor(adv)
        pg = policy_loss(logprob, batch["logprobs"], adv, clip_coef, "mean")
        vf = value_loss(values[..., 0], batch["values"], batch["returns"], clip_coef, cfg.algo.clip_vloss, "mean")
        ent = entropy_loss(entropy, cfg.algo.loss_reduction)
        total = pg + cfg.algo.vf_coef * vf + cfg.algo.ent_coef * ent
        aux = {"Loss/policy_loss": pg, "Loss/value_loss": vf, "Loss/entropy_loss": -ent}
        if health:
            aux["Health/policy_entropy"] = entropy.mean()
            aux["Health/value_mean"] = values.mean()
        return total, aux

    # Shard each [T, mb_envs, ...] minibatch over the data axis (same pattern as
    # ppo.py:134,171) so gradient computation is data-parallel under GSPMD.
    dp_ok = ctx.data_parallel_size > 1 and mb_envs % ctx.data_parallel_size == 0
    mb_sharding = ctx.sharding(None, "data")

    @jax.jit
    def train_fn(p, o_state, seq_data, c0, h0, key, clip_coef, ent_coef):
        def mb_step(carry, env_idx):
            p, o_state = carry
            batch = jax.tree.map(lambda x: x[:, env_idx], seq_data)
            if dp_ok:
                batch = jax.tree.map(lambda x: jax.lax.with_sharding_constraint(x, mb_sharding), batch)
            batch["c0"] = c0[env_idx]
            batch["h0"] = h0[env_idx]
            (_, aux), grads = jax.value_and_grad(seq_loss_fn, has_aux=True)(p, batch, clip_coef, ent_coef)
            updates, o_state = opt.update(grads, o_state, p)
            p = optax.apply_updates(p, updates)
            if health:  # per-module norms/ratios, averaged by the scans below
                aux = {**aux, **diagnostics(grads=grads, params=p, updates=updates)}
            return (p, o_state), aux

        def epoch_step(carry, ekey):
            perm = jax.random.permutation(ekey, num_envs).reshape(num_batches, mb_envs)
            carry, auxs = jax.lax.scan(mb_step, carry, perm)
            return carry, jax.tree.map(jnp.mean, auxs)

        keys = jax.random.split(key, cfg.algo.update_epochs)
        (p, o_state), metrics = jax.lax.scan(epoch_step, (p, o_state), keys)
        metrics = jax.tree.map(jnp.mean, metrics)
        return p, o_state, maybe_inject_nonfinite(cfg, metrics)

    return opt, train_fn


@register_algorithm(name="ppo_recurrent")
def main(ctx, cfg) -> None:
    rank = ctx.process_index
    log_dir = get_log_dir(cfg)
    if ctx.is_global_zero:
        save_config(cfg, Path(log_dir) / "config.yaml")
    logger = get_logger(cfg, log_dir)
    monitor = TrainingMonitor(cfg, log_dir)

    envs = make_vector_env(cfg, cfg.seed, rank, log_dir if cfg.env.capture_video else None)
    obs_space = envs.single_observation_space
    act_space = envs.single_action_space
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys

    agent, params = build_agent(ctx, act_space, obs_space, cfg)
    is_continuous = agent.is_continuous
    actions_dim = agent.action_dims
    act_sum = int(sum(actions_dim))
    hidden = cfg.algo.rnn.lstm.hidden_size

    opt, train_fn = make_ppo_recurrent_train_fn(ctx, agent, cfg, obs_keys)
    opt_state = ctx.replicate(opt.init(params))

    num_envs = cfg.env.num_envs
    rollout_steps = cfg.algo.rollout_steps
    world = jax.process_count()
    policy_steps_per_iter = int(num_envs * rollout_steps * world)
    num_updates = max(int(cfg.algo.total_steps) // policy_steps_per_iter, 1) if not cfg.dry_run else 1
    num_batches = max(int(cfg.algo.per_rank_num_batches), 1)

    rb = ReplayBuffer(
        rollout_steps,
        num_envs,
        obs_keys=obs_keys,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}") if cfg.buffer.memmap else None,
    )
    rb.seed(cfg.seed + rank)
    aggregator = MetricAggregator(cfg.metric.aggregator.get("metrics", {}))
    aggregator.keep(AGGREGATOR_KEYS | set(cfg.metric.aggregator.get("metrics", {})))
    ckpt_manager = CheckpointManager(Path(log_dir) / "checkpoints", keep_last=cfg.checkpoint.keep_last)
    guard = TrainingGuard(cfg, log_dir)

    gamma, gae_lambda = cfg.algo.gamma, cfg.algo.gae_lambda

    @jax.jit
    def act_fn(p, obs, prev_actions, is_first, state, key):
        actor_out, value, new_state = agent.apply(
            p, obs, prev_actions, is_first, state, method=RecurrentPPOAgent.step
        )
        env_act, stored_act, logprob = sample_actions(key, actor_out, is_continuous)
        return env_act, logprob, value[..., 0], new_state

    gae_fn = jax.jit(lambda r, v, d, nv: gae(r, v, d, nv, rollout_steps, gamma, gae_lambda))

    # analysis.strict: signature guard on the jitted update (drift -> hard error)
    train_fn = obs_perf.instrument(cfg, "ppo_recurrent/train_fn", strict_guard(cfg, "ppo_recurrent/train_fn", train_fn))

    # Flight recorder: no replay builder for the recurrent update yet — staging
    # still dumps the offending batch + state for forensics.
    recorder = flight_recorder.get_active()

    start_update, policy_step, last_log, last_checkpoint = 1, 0, 0, 0
    if cfg.checkpoint.get("resume_from"):
        state = CheckpointManager.load(
            cfg.checkpoint.resume_from,
            templates={"params": jax.device_get(params), "opt_state": jax.device_get(opt_state)},
        )
        params = ctx.replicate(state["params"])
        opt_state = ctx.replicate(state["opt_state"])
        start_update = state["update"] + 1
        policy_step = state["policy_step"]
        last_log = state.get("last_log", 0)
        last_checkpoint = state.get("last_checkpoint", 0)

    obs, _ = envs.reset(seed=cfg.seed + rank)
    zero_state = make_zero_state(cfg)
    is_attention = cfg.algo.get("sequence_model", "lstm") == "attention"
    lstm_state = zero_state(num_envs)
    prev_stored = np.zeros((num_envs, act_sum), dtype=np.float32)
    is_first_np = np.ones((num_envs, 1), dtype=np.float32)
    step_data: Dict[str, np.ndarray] = {}

    for update in range(start_update, num_updates + 1):
        monitor.advance()
        if is_attention:
            # The attention context never crosses a rollout boundary: training
            # attends within the rollout only, so acting resets its window here —
            # the policies stay EXACTLY on-policy.
            lstm_state = zero_state(num_envs)
        c0, h0 = lstm_state
        env_t0 = time.perf_counter()
        with timer("Time/env_interaction_time"):
            for _ in range(rollout_steps):
                obs_t = prepare_obs(obs, cnn_keys, mlp_keys)
                env_act, logprob, value, lstm_state = act_fn(
                    params, obs_t, jnp.asarray(prev_stored), jnp.asarray(is_first_np), lstm_state, ctx.local_rng()
                )
                env_act_np = np.asarray(jax.device_get(env_act))
                if is_continuous:
                    low, high = act_space.low, act_space.high
                    env_actions = np.clip(env_act_np, low, high) if np.isfinite(low).all() else env_act_np
                elif len(actions_dim) == 1:
                    env_actions = env_act_np[..., 0]
                else:
                    env_actions = env_act_np
                next_obs, reward, terminated, truncated, info = envs.step(env_actions)
                done = np.logical_or(terminated, truncated)
                reward = np.asarray(reward, dtype=np.float32).reshape(num_envs)

                # Bootstrap truncated episodes with V(final_obs) under the current
                # recurrent state (reference ppo_recurrent.py:309-335).
                if truncated.any() and "final_obs" in info:
                    trunc_idx = np.nonzero(truncated)[0]
                    final_obs = {
                        k: np.stack([np.asarray(info["final_obs"][i][k]) for i in trunc_idx]) for k in obs_keys
                    }
                    sub_state = (lstm_state[0][trunc_idx], lstm_state[1][trunc_idx])
                    # local_rng: acting-side keys are per-process; drawing from the
                    # process-identical chain here would desynchronize it across
                    # ranks (truncations happen at different iterations per rank).
                    _, _, v_final, _ = act_fn(
                        params,
                        prepare_obs(final_obs, cnn_keys, mlp_keys),
                        jnp.asarray(prev_stored[trunc_idx]),
                        jnp.zeros((len(trunc_idx), 1)),
                        sub_state,
                        ctx.local_rng(),
                    )
                    reward[trunc_idx] += gamma * np.asarray(jax.device_get(v_final))

                for k in obs_keys:
                    step_data[k] = np.asarray(obs[k])[None]
                step_data["actions"] = env_act_np.reshape(num_envs, -1).astype(np.float32)[None]
                step_data["prev_actions"] = prev_stored[None].copy()
                step_data["is_first"] = is_first_np[None].copy()
                step_data["logprobs"] = np.asarray(jax.device_get(logprob)).reshape(num_envs, 1)[None]
                step_data["values"] = np.asarray(jax.device_get(value)).reshape(num_envs, 1)[None]
                step_data["rewards"] = reward.reshape(num_envs, 1)[None]
                step_data["dones"] = done.astype(np.float32).reshape(num_envs, 1)[None]
                rb.add(step_data, validate_args=cfg.buffer.validate_args)

                prev_stored = _onehot_actions(env_act_np, actions_dim, is_continuous)
                prev_stored[done] = 0.0
                is_first_np = done.astype(np.float32).reshape(num_envs, 1)
                obs = next_obs
                policy_step += num_envs * world
                record_episode_stats(aggregator, info)
        env_time = time.perf_counter() - env_t0

        local = rb.to_tensor()
        obs_t = prepare_obs(obs, cnn_keys, mlp_keys)
        _, _, next_value, _ = act_fn(
            params, obs_t, jnp.asarray(prev_stored), jnp.asarray(is_first_np), lstm_state, ctx.local_rng()
        )
        returns, advantages = gae_fn(local["rewards"], local["values"], local["dones"], next_value[:, None])
        seq_data = {
            **{k: local[k] for k in obs_keys},
            "actions": local["actions"],
            "prev_actions": local["prev_actions"],
            "is_first": local["is_first"],
            "logprobs": local["logprobs"][..., 0],
            "values": local["values"][..., 0],
            "returns": returns[..., 0],
            "advantages": advantages[..., 0],
        }

        clip_coef = cfg.algo.clip_coef
        ent_coef = cfg.algo.ent_coef
        if cfg.algo.anneal_clip_coef:
            clip_coef = polynomial_decay(update, initial=clip_coef, final=0.0, max_decay_steps=num_updates)
        if cfg.algo.anneal_ent_coef:
            ent_coef = polynomial_decay(update, initial=ent_coef, final=0.0, max_decay_steps=num_updates)

        key = ctx.rng()
        if recorder is not None:  # device-array references only: no host sync
            recorder.stage_step(
                batch=seq_data,
                carry={"params": params, "opt_state": opt_state, "c0": c0, "h0": h0},
                key=key,
                scalars={"clip_coef": float(clip_coef), "ent_coef": float(ent_coef), "update": update},
            )
        with timer("Time/train_time"), monitor.phase("dispatch"):
            t0 = time.perf_counter()
            params, opt_state, train_metrics = train_fn(
                params, opt_state, seq_data, c0, h0, key, clip_coef, ent_coef
            )
            train_metrics = jax.device_get(train_metrics)
            train_time = time.perf_counter() - t0
        assert_finite(cfg, train_metrics, "ppo_recurrent/update")
        for k, v in train_metrics.items():
            aggregator.update(k, float(v))

        if logger is not None and (policy_step - last_log >= cfg.metric.log_every or update == num_updates or cfg.dry_run):
            metrics = aggregator.compute()
            metrics["Time/sps_train"] = (
                cfg.algo.update_epochs * num_batches / train_time if train_time > 0 else 0.0
            )
            metrics["Time/sps_env_interaction"] = policy_steps_per_iter / world / env_time if env_time > 0 else 0.0
            monitor.log_metrics(logger, metrics, policy_step)
            aggregator.reset()
            last_log = policy_step

        def save_ckpt():
            nonlocal last_checkpoint
            path = ckpt_manager.save(
                policy_step,
                {
                    "params": params,
                    "opt_state": opt_state,
                    "update": update,
                    "policy_step": policy_step,
                    "last_log": last_log,
                    "last_checkpoint": policy_step,
                },
            )
            last_checkpoint = policy_step
            return path

        if (
            cfg.checkpoint.every > 0
            and (policy_step - last_checkpoint) >= cfg.checkpoint.every
            or update == num_updates
            and cfg.checkpoint.save_last
        ):
            save_ckpt()
        guard.boundary(policy_step, save_ckpt)

    monitor.close()
    envs.close()
    if cfg.algo.run_test and ctx.is_global_zero:
        reward = test(agent, params, ctx, cfg, log_dir)
        if logger is not None:
            logger.log_metrics({"Test/cumulative_reward": reward}, policy_step)
    if logger is not None:
        logger.close()


def test(agent, params, ctx, cfg, log_dir: str, greedy: bool = True) -> float:
    """Greedy single-env evaluation with carried LSTM state."""
    from sheeprl_tpu.utils.env import make_env

    env = make_env(cfg, cfg.seed, 0, log_dir, "test")()
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    act_sum = int(sum(agent.action_dims))

    @jax.jit
    def policy(p, obs, prev_actions, is_first, state, key):
        actor_out, _, new_state = agent.apply(p, obs, prev_actions, is_first, state, method=RecurrentPPOAgent.step)
        env_act, _, _ = sample_actions(key, actor_out, agent.is_continuous, greedy=greedy)
        return env_act, new_state

    obs, _ = env.reset(seed=cfg.seed)
    state = make_zero_state(cfg)(1)
    prev = np.zeros((1, act_sum), dtype=np.float32)
    is_first = np.ones((1, 1), dtype=np.float32)
    done, cum_reward = False, 0.0
    while not done:
        obs_t = prepare_obs({k: np.asarray(v)[None] for k, v in obs.items()}, cnn_keys, mlp_keys)
        act, state = policy(params, obs_t, jnp.asarray(prev), jnp.asarray(is_first), state, ctx.rng())
        act_np = np.asarray(jax.device_get(act))
        prev = _onehot_actions(act_np, agent.action_dims, agent.is_continuous)
        is_first = np.zeros((1, 1), dtype=np.float32)
        if agent.is_continuous:
            env_action = act_np[0]
        elif len(agent.action_dims) == 1:
            env_action = int(act_np[0, 0])
        else:
            env_action = act_np[0]
        obs, reward, terminated, truncated, _ = env.step(env_action)
        done = bool(terminated or truncated)
        cum_reward += float(reward)
    env.close()
    return cum_reward


def lower_for_audit():
    """IR-audit hook (``python -m sheeprl_tpu.analysis.ir``): the jitted BPTT
    update at tiny synthetic shapes, through ``make_ppo_recurrent_train_fn``."""
    from sheeprl_tpu.analysis.ir.synth import (
        compose_tiny,
        discrete_act_space,
        tiny_ctx,
        vector_space,
        zeros,
    )
    from sheeprl_tpu.analysis.ir.types import AuditEntry

    cfg = compose_tiny(
        [
            "exp=ppo_recurrent",
            "env=discrete_dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.rollout_steps=4",
            "algo.per_rank_num_batches=2",
            "algo.update_epochs=1",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.encoder.mlp_features_dim=8",
            "algo.rnn.lstm.hidden_size=8",
            "env.num_envs=2",
        ]
    )
    ctx = tiny_ctx(cfg)
    obs_space = vector_space()
    act_space = discrete_act_space()
    agent, params = build_agent(ctx, act_space, obs_space, cfg)
    opt, train_fn = make_ppo_recurrent_train_fn(ctx, agent, cfg, ["state"])
    opt_state = opt.init(params)
    T, N = int(cfg.algo.rollout_steps), int(cfg.env.num_envs)
    act_sum = int(sum(agent.action_dims))
    hidden = int(cfg.algo.rnn.lstm.hidden_size)
    seq_data = {
        "state": zeros((T, N, 5)),
        "actions": zeros((T, N, 1)),
        "prev_actions": zeros((T, N, act_sum)),
        "is_first": zeros((T, N, 1)),
        "logprobs": zeros((T, N)),
        "values": zeros((T, N)),
        "returns": zeros((T, N)),
        "advantages": zeros((T, N)),
    }
    return [
        AuditEntry(
            name="ppo_recurrent/train_fn",
            fn=train_fn,
            args=(params, opt_state, seq_data, zeros((N, hidden)), zeros((N, hidden)), jax.random.PRNGKey(0), 0.2, 0.0),
            covers=("ppo_recurrent",),
            precision=str(cfg.mesh.precision),
        )
    ]
