"""SAC evaluation entry (reference: ``/root/reference/sheeprl/algos/sac/evaluate.py``)."""

from __future__ import annotations

from typing import Any, Dict

import jax

from sheeprl_tpu.algos.sac.agent import build_agent
from sheeprl_tpu.algos.sac.utils import test
from sheeprl_tpu.checkpoint.manager import CheckpointManager
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir
from sheeprl_tpu.utils.policy import extract_policy_params
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms=["sac", "sac_decoupled"])
def evaluate_sac(ctx, cfg: Dict[str, Any], ckpt_path: str) -> float:
    log_dir = get_log_dir(cfg)
    env = make_env(cfg, cfg.seed, 0, log_dir, "test")()
    obs_space = env.observation_space
    act_space = env.action_space
    env.close()

    actor, _, params = build_agent(ctx, act_space, obs_space, cfg)
    state = CheckpointManager.load(ckpt_path, templates={"params": jax.device_get(params)})
    params = ctx.replicate(extract_policy_params(state, cfg, "sac"))
    reward = test(actor, params, ctx, cfg, log_dir)
    print(f"Test/cumulative_reward: {reward}")
    return reward
