"""Decoupled SAC — player/learner split (reference: ``/root/reference/sheeprl/algos/sac/sac_decoupled.py``).

Same TPU-native redesign as ``ppo_decoupled``: the reference's rank-0 player +
DDP-trainer-ranks protocol over torch collectives (``sac_decoupled.py:33,356,547``)
becomes two threads in the single-controller JAX process.

* **player**: steps the envs, owns the replay buffer, and — once the replay-ratio
  governor grants gradient steps — samples the ``[G, B, ...]`` batch block and queues it
  (the analogue of the reference's data scatter);
* **learner**: consumes the block, runs the scanned SAC update jitted over the mesh
  (batch sharded on the ``data`` axis), and publishes fresh params back;
* the player keeps acting with its latest received params while the learner's update is
  in flight, so env stepping and device compute overlap.
"""

from __future__ import annotations

import os
import queue
import sys
import threading
import time
from pathlib import Path
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.analysis.strict import assert_finite, strict_guard
from sheeprl_tpu.algos.sac.agent import build_agent
from sheeprl_tpu.algos.sac.sac import make_sac_fused_builder, make_sac_train_fn
from sheeprl_tpu.algos.sac.utils import AGGREGATOR_KEYS, prepare_obs, test
from sheeprl_tpu.checkpoint.manager import CheckpointManager
from sheeprl_tpu.fault.guard import TrainingGuard
from sheeprl_tpu.config.core import save_config
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.data.device_buffer import make_transition_ring
from sheeprl_tpu.distributed.placement import placement_from_cfg
from sheeprl_tpu.distributed.publish import evict_and_put, make_stamp, staleness_steps
from sheeprl_tpu.distributed.transport import maybe_digest
from sheeprl_tpu.obs import perf as obs_perf
from sheeprl_tpu.obs import TrainingMonitor
from sheeprl_tpu.utils.blocks import FusedRingDispatcher
from sheeprl_tpu.utils.env import make_vector_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, record_episode_stats
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio


@register_algorithm(name="sac_decoupled", decoupled=True)
def main(ctx, cfg) -> None:
    # Sebulba (distributed.mode=sebulba): the player/learner threads below become
    # placed processes — children land in sebulba.run, the launcher role places
    # them (howto/sebulba.md).
    spec = placement_from_cfg(cfg)
    if spec.is_sebulba:
        if spec.role == "launcher":
            from sheeprl_tpu.distributed import launcher

            raise SystemExit(launcher.launch(sys.argv[1:]))
        from sheeprl_tpu.distributed import sebulba

        return sebulba.run(ctx, cfg, spec, algo="sac")

    rank = ctx.process_index
    log_dir = get_log_dir(cfg)
    if ctx.is_global_zero:
        save_config(cfg, Path(log_dir) / "config.yaml")
    logger = get_logger(cfg, log_dir)
    monitor = TrainingMonitor(cfg, log_dir)

    envs = make_vector_env(cfg, cfg.seed, rank, log_dir if cfg.env.capture_video else None)
    obs_space = envs.single_observation_space
    act_space = envs.single_action_space
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    act_low, act_high = act_space.low, act_space.high
    rescale = np.isfinite(act_low).all() and np.isfinite(act_high).all()

    actor, critic, params = build_agent(ctx, act_space, obs_space, cfg)
    actor_opt, critic_opt, alpha_opt, train_fn = make_sac_train_fn(actor, critic, cfg, act_space)
    train_fn = obs_perf.instrument(cfg, "sac_decoupled/train_fn", strict_guard(cfg, "sac_decoupled/train_fn", train_fn))
    # Flight recorder: decoupled dumps replay through the coupled builder (same
    # make_sac_train_fn update).
    from sheeprl_tpu.obs import flight_recorder

    recorder = flight_recorder.get_active()
    if recorder is not None:
        recorder.arm_replay(
            "sheeprl_tpu.algos.sac.sac:replay_update",
            act_space=act_space,
            obs_space=obs_space,
        )
    opt_state = ctx.replicate(
        {
            "actor": actor_opt.init(params["actor"]),
            "critic": critic_opt.init(params["critic"]),
            "alpha": alpha_opt.init(params["log_alpha"]),
        }
    )

    num_envs = cfg.env.num_envs
    world = jax.process_count()
    rb = ReplayBuffer(
        max(int(cfg.buffer.size) // max(num_envs * world, 1), 1),
        num_envs,
        obs_keys=mlp_keys,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}") if cfg.buffer.memmap else None,
    )
    rb.seed(cfg.seed + rank)

    aggregator = MetricAggregator(cfg.metric.aggregator.get("metrics", {}))
    aggregator.keep(AGGREGATOR_KEYS | set(cfg.metric.aggregator.get("metrics", {})))
    # Written by the player (episode stats) and read/reset by the learner.
    agg_lock = threading.Lock()
    ckpt_manager = CheckpointManager(Path(log_dir) / "checkpoints", keep_last=cfg.checkpoint.keep_last)
    guard = TrainingGuard(cfg, log_dir)
    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    batch_size = cfg.algo.per_rank_batch_size

    # Device-resident replay (buffer.device=True, data/device_buffer.py): the
    # player scatters rows into the HBM transition ring and ships only counters;
    # the learner runs the whole gradient block as ONE donated fused dispatch
    # with in-jit index sampling.  ``ring_lock`` serialises the player's donating
    # scatter against the learner's dispatch — without it, the learner could
    # dispatch with ring buffers the scatter just donated.
    obs_dim = int(sum(np.prod(obs_space[k].shape) for k in mlp_keys))
    act_dim = int(np.prod(act_space.shape))
    ring = make_transition_ring(
        ctx,
        cfg,
        rb,
        {
            "obs": ((obs_dim,), jnp.float32),
            "next_obs": ((obs_dim,), jnp.float32),
            "actions": ((act_dim,), jnp.float32),
            "rewards": ((1,), jnp.float32),
            "dones": ((1,), jnp.float32),
        },
    )
    ring_lock = threading.Lock()
    fused = None
    if ring is not None:
        _, _, _, fused_builder = make_sac_fused_builder(actor, critic, cfg, act_space, ring, batch_size)
        fused = FusedRingDispatcher(
            fused_builder, base_key=ctx.rng(), cfg=cfg, perf_name="sac_decoupled/fused_block"
        )
        # Donation safety: critic_target aliases critic's buffers at init — a
        # donated carry must not contain the same buffer twice.
        params = jax.tree.map(jnp.copy, params)
        opt_state = jax.tree.map(jnp.copy, opt_state)

    @jax.jit
    def act_fn(p, obs, key):
        mean, log_std = actor.apply(p, obs)
        dist = actor.dist(mean, log_std)
        return dist.sample(key)

    policy_steps_per_iter = num_envs * world
    total_steps = int(cfg.algo.total_steps)
    num_iters = max(total_steps // policy_steps_per_iter, 1) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_iters = max(learning_starts - 1, 0)

    start_iter = 1
    policy_step0 = 0
    last_log = 0
    last_checkpoint = 0
    cumulative_grad_steps = 0
    if cfg.checkpoint.get("resume_from"):
        state = CheckpointManager.load(
            cfg.checkpoint.resume_from,
            templates={"params": jax.device_get(params), "opt_state": jax.device_get(opt_state)},
        )
        params = ctx.replicate(state["params"])
        opt_state = ctx.replicate(state["opt_state"])
        ratio.load_state_dict(state["ratio"])
        start_iter = state["iter_num"] + 1
        policy_step0 = state["policy_step"]
        last_log = state.get("last_log", 0)
        last_checkpoint = state.get("last_checkpoint", 0)
        cumulative_grad_steps = state.get("cumulative_grad_steps", 0)
        learning_starts += start_iter
        if cfg.buffer.checkpoint and "rb" in state:
            rb.load_state_dict(state["rb"])

    # ------------------------------------------------------------------ roles
    batch_q: "queue.Queue[Any]" = queue.Queue(maxsize=2)
    param_q: "queue.Queue[Any]" = queue.Queue(maxsize=2)
    stop = threading.Event()

    def player() -> None:
        """Env + buffer role (reference ``player()``, ``sac_decoupled.py:33-…``)."""
        key = jax.random.PRNGKey(cfg.seed + 10_000 + rank)
        # Ring path: the learner DONATES its params into every fused dispatch, so
        # the player must act on an independent copy (only the actor is needed);
        # published updates below are copies for the same reason.
        local_params = params if ring is None else {"actor": jax.tree.map(jnp.copy, params["actor"])}
        param_stamp: Dict[str, Any] = {}
        policy_step = policy_step0
        last_ckpt = last_checkpoint
        try:
            obs, _ = envs.reset(seed=cfg.seed + rank)
            step_data: Dict[str, np.ndarray] = {}
            for iter_num in range(start_iter, num_iters + 1):
                if stop.is_set():
                    return
                # Pick up the freshest published params without blocking.
                try:
                    while True:
                        local_params, param_stamp = param_q.get_nowait()
                except queue.Empty:
                    pass
                env_t0 = time.perf_counter()
                with timer("Time/env_interaction_time"):
                    if iter_num <= learning_starts and not cfg.checkpoint.get("resume_from"):
                        actions = np.stack([act_space.sample() for _ in range(num_envs)])
                        tanh_actions = (
                            2 * (actions - act_low) / (act_high - act_low) - 1 if rescale else actions
                        )
                    else:
                        key, sub = jax.random.split(key)
                        obs_t = prepare_obs(obs, mlp_keys)
                        tanh_actions = np.asarray(jax.device_get(act_fn(local_params["actor"], obs_t, sub)))
                        actions = (
                            act_low + (tanh_actions + 1) * 0.5 * (act_high - act_low) if rescale else tanh_actions
                        )
                    next_obs, reward, terminated, truncated, info = envs.step(actions)
                    done = np.logical_or(terminated, truncated)

                    real_next = {k: np.asarray(next_obs[k]).copy() for k in mlp_keys}
                    if done.any() and "final_obs" in info:
                        for i in np.nonzero(done)[0]:
                            if info["final_obs"][i] is not None:
                                for k in mlp_keys:
                                    real_next[k][i] = np.asarray(info["final_obs"][i][k])

                    for k in mlp_keys:
                        step_data[k] = np.asarray(obs[k])[None]
                        step_data[f"next_{k}"] = real_next[k][None]
                    step_data["actions"] = tanh_actions.astype(np.float32)[None]
                    step_data["rewards"] = np.asarray(reward, dtype=np.float32).reshape(num_envs, 1)[None]
                    step_data["dones"] = terminated.astype(np.float32).reshape(num_envs, 1)[None]
                    if ring is not None:
                        # Donating scatter: must not interleave with the learner's
                        # dispatch reading the ring handle (see ring_lock above).
                        with ring_lock:
                            ring.add_step(
                                {
                                    "obs": np.concatenate(
                                        [step_data[k].reshape(1, num_envs, -1) for k in mlp_keys], -1
                                    ),
                                    "next_obs": np.concatenate(
                                        [step_data[f"next_{k}"].reshape(1, num_envs, -1) for k in mlp_keys],
                                        -1,
                                    ),
                                    "actions": step_data["actions"],
                                    "rewards": step_data["rewards"],
                                    "dones": step_data["dones"],
                                },
                                rb._pos,
                                rb.rows_added,
                            )
                    rb.add(step_data, validate_args=cfg.buffer.validate_args)
                    obs = next_obs
                    policy_step += policy_steps_per_iter
                    with agg_lock:
                        record_episode_stats(aggregator, info)
                env_time = time.perf_counter() - env_t0

                grad_steps = 0
                batches = None
                if iter_num >= learning_starts:
                    grad_steps = ratio((policy_step - prefill_iters * policy_steps_per_iter) / world)
                    if grad_steps > 0 and ring is None:
                        sample = rb.sample(batch_size * grad_steps)
                        batches = {
                            "obs": np.concatenate(
                                [sample[k].reshape(grad_steps, batch_size, -1) for k in mlp_keys], -1
                            ),
                            "next_obs": np.concatenate(
                                [sample[f"next_{k}"].reshape(grad_steps, batch_size, -1) for k in mlp_keys], -1
                            ),
                            "actions": sample["actions"].reshape(grad_steps, batch_size, -1),
                            "rewards": sample["rewards"].reshape(grad_steps, batch_size, 1),
                            "dones": sample["dones"].reshape(grad_steps, batch_size, 1),
                        }
                # rb and ratio live in this thread; snapshot them coherently when a
                # checkpoint is due so the learner never reads them mid-mutation.
                ckpt_state = None
                if (
                    cfg.checkpoint.every > 0
                    and (policy_step - last_ckpt) >= cfg.checkpoint.every
                    or iter_num == num_iters
                    and cfg.checkpoint.save_last
                ):
                    ckpt_state = {"ratio": ratio.state_dict()}
                    if cfg.buffer.checkpoint:
                        ckpt_state["rb"] = rb.state_dict()
                    last_ckpt = policy_step
                item = {
                    "iter_num": iter_num,
                    "batches": batches,
                    "grad_steps": grad_steps,
                    "policy_step": policy_step,
                    "env_time": env_time,
                    "ckpt": ckpt_state,
                    # Ring path: the learner samples in-jit; ship only the row
                    # counters the sampler and the staleness stamps need.
                    "filled": len(rb),
                    "rows_added": rb.rows_added,
                    # Policy-step age of the params this iteration acted with —
                    # the learner logs it as Sebulba/param_staleness_steps.
                    "staleness": staleness_steps(param_stamp, policy_step),
                }
                while not stop.is_set():
                    try:
                        batch_q.put(item, timeout=1.0)
                        break
                    except queue.Full:
                        continue
        except Exception as exc:
            batch_q.put(exc)

    player_thread = threading.Thread(target=player, name="sac-player", daemon=True)
    player_thread.start()

    # ------------------------------------------------------------------ learner
    policy_step = policy_step0
    publish_seq = 0
    try:
        for iter_num in range(start_iter, num_iters + 1):
            monitor.advance()
            item = batch_q.get()
            if isinstance(item, Exception):
                raise item
            policy_step = item["policy_step"]
            env_time = item["env_time"]
            grad_steps = item["grad_steps"]
            if item.get("staleness") is not None:
                with agg_lock:
                    aggregator.update("Sebulba/param_staleness_steps", float(item["staleness"]))

            train_time = 0.0
            if grad_steps > 0 and ring is not None:
                with timer("Time/train_time"), monitor.phase("dispatch"):
                    t0 = time.perf_counter()
                    with ring_lock:
                        carry = fused.dispatch(
                            {"params": params, "opt_state": opt_state},
                            ring.arrays,
                            item["filled"],
                            item["rows_added"],
                            grad_steps,
                            cumulative_grad_steps,
                        )
                    params, opt_state = carry["params"], carry["opt_state"]
                    # Publish a COPY of the fresh actor: the next dispatch donates
                    # ``params``, and the player must never read a donated buffer.
                    # Freshest-wins: evict any unconsumed publish (a blind
                    # put_nowait would keep STALE params on a slow player).
                    publish_seq += 1
                    evict_and_put(
                        param_q,
                        (
                            {"actor": jax.tree.map(jnp.copy, params["actor"])},
                            make_stamp(publish_seq, cumulative_grad_steps + grad_steps, policy_step),
                        ),
                    )
                    with agg_lock:
                        fused.drain(aggregator)  # one blocking device_get/iter, as before
                    train_time = time.perf_counter() - t0
                cumulative_grad_steps += grad_steps
                if recorder is not None:
                    # The pre-step state was DONATED into the block; re-stage
                    # post-dispatch with a device-side copy (async, no host sync).
                    recorder.stage_step(
                        carry=jax.tree.map(jnp.copy, carry),
                        scalars={
                            "grad_step0": int(cumulative_grad_steps),
                            "filled": int(item["filled"]),
                            "rows_added": int(item["rows_added"]),
                        },
                    )
            elif grad_steps > 0:
                maybe_digest(f"sac:{item['iter_num']}", item["batches"])
                batches = ctx.put_batch(item["batches"], batch_axis=1)
                key = ctx.rng()
                if recorder is not None:  # device-array references only: no host sync
                    recorder.stage_step(
                        batch=batches,
                        carry={"params": params, "opt_state": opt_state},
                        key=key,
                        scalars={"grad_step0": int(cumulative_grad_steps)},
                    )
                with timer("Time/train_time"), monitor.phase("dispatch"):
                    t0 = time.perf_counter()
                    params, opt_state, train_metrics = train_fn(
                        params, opt_state, batches, key, jnp.asarray(cumulative_grad_steps)
                    )
                    # Publish the (asynchronously dispatched) params immediately;
                    # freshest-wins eviction — the player only wants the latest.
                    publish_seq += 1
                    evict_and_put(
                        param_q,
                        (params, make_stamp(publish_seq, cumulative_grad_steps + grad_steps, policy_step)),
                    )
                    train_metrics = jax.device_get(train_metrics)
                    assert_finite(cfg, train_metrics, "sac_decoupled/update")
                    train_time = time.perf_counter() - t0
                cumulative_grad_steps += grad_steps
                with agg_lock:
                    for k, v in train_metrics.items():
                        aggregator.update(k, float(v))

            if logger is not None and (
                policy_step - last_log >= cfg.metric.log_every or iter_num == num_iters or cfg.dry_run
            ):
                with agg_lock:
                    metrics = aggregator.compute()
                    aggregator.reset()
                if train_time > 0:
                    metrics["Time/sps_train"] = grad_steps / train_time
                metrics["Time/sps_env_interaction"] = (
                    policy_steps_per_iter / world / env_time if env_time > 0 else 0.0
                )
                metrics["Params/replay_ratio"] = (
                    cumulative_grad_steps * world / policy_step if policy_step > 0 else 0.0
                )
                monitor.log_metrics(logger, metrics, policy_step)
                last_log = policy_step

            if item["ckpt"] is not None:
                state = {
                    "params": params,
                    "opt_state": opt_state,
                    "ratio": item["ckpt"]["ratio"],
                    "iter_num": iter_num,
                    "policy_step": policy_step,
                    "last_log": last_log,
                    "last_checkpoint": policy_step,
                    "cumulative_grad_steps": cumulative_grad_steps,
                }
                if "rb" in item["ckpt"]:
                    state["rb"] = item["ckpt"]["rb"]
                ckpt_manager.save(policy_step, state)
                last_checkpoint = policy_step

            def save_ckpt():
                # Preemption-time save. The replay buffer lives in the player
                # thread and cannot be snapshotted coherently from here, so the
                # emergency checkpoint carries everything but "rb" (resume
                # tolerates its absence); ratio's state_dict is a plain scalar
                # copy and safe to read across threads.
                nonlocal last_checkpoint
                state = {
                    "params": params,
                    "opt_state": opt_state,
                    "ratio": ratio.state_dict(),
                    "iter_num": iter_num,
                    "policy_step": policy_step,
                    "last_log": last_log,
                    "last_checkpoint": policy_step,
                    "cumulative_grad_steps": cumulative_grad_steps,
                }
                path = ckpt_manager.save(policy_step, state)
                last_checkpoint = policy_step
                return path

            guard.boundary(policy_step, save_ckpt)
    finally:
        stop.set()
        player_thread.join(timeout=30)
        monitor.close()

    if player_thread.is_alive():
        raise RuntimeError("decoupled player thread did not shut down cleanly")
    envs.close()
    if cfg.algo.run_test and ctx.is_global_zero:
        reward = test(actor, params, ctx, cfg, log_dir)
        if logger is not None:
            logger.log_metrics({"Test/cumulative_reward": reward}, policy_step)
    if logger is not None:
        logger.close()
