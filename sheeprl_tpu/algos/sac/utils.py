"""SAC helpers (reference: ``/root/reference/sheeprl/algos/sac/utils.py``)."""

from __future__ import annotations

from typing import Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.obs.tracer import trace_span

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/alpha_loss",
}
MODELS_TO_REGISTER = {"agent"}


@trace_span("Time/h2d_transfer")
def prepare_obs(obs: Dict[str, np.ndarray], mlp_keys: Sequence[str]) -> jax.Array:
    """Concatenate (flattened) vector keys: SAC is vector-obs only (reference parity)."""
    arrs = [np.asarray(obs[k], dtype=np.float32) for k in mlp_keys]
    arrs = [a.reshape(a.shape[0], -1) if a.ndim > 1 else a[:, None] for a in arrs]
    return jnp.asarray(np.concatenate(arrs, axis=-1))


def test(actor, params, ctx, cfg, log_dir: str) -> float:
    from sheeprl_tpu.utils.env import make_env

    env = make_env(cfg, cfg.seed, 0, log_dir, "test")()
    mlp_keys = list(cfg.algo.mlp_keys.encoder)

    @jax.jit
    def policy(p, obs):
        mean, _ = actor.apply(p, obs)
        return jnp.tanh(mean)

    obs, _ = env.reset(seed=cfg.seed)
    done, cum_reward = False, 0.0
    while not done:
        obs_t = prepare_obs({k: np.asarray(v)[None] for k, v in obs.items()}, mlp_keys)
        act = np.asarray(jax.device_get(policy(params["actor"], obs_t)))[0]
        low, high = env.action_space.low, env.action_space.high
        if np.isfinite(low).all() and np.isfinite(high).all():
            act = low + (act + 1) * 0.5 * (high - low)
        obs, reward, terminated, truncated, _ = env.step(act)
        done = bool(terminated or truncated)
        cum_reward += float(reward)
    env.close()
    return cum_reward
