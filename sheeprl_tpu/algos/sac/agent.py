"""SAC agent (reference: ``/root/reference/sheeprl/algos/sac/agent.py``).

TPU-native design decisions:

* the twin critics (reference ``SACCritic`` instances in a ModuleList, ``agent.py:145``)
  are ONE ``nn.vmap``-ensembled module — a single batched matmul per layer over the
  ensemble axis instead of N sequential small matmuls (MXU-friendly);
* target networks are a second params pytree updated with a fused EMA inside the jitted
  step (reference ``:265`` does a python-side polyak loop);
* the temperature ``log_alpha`` is a 0-d param pytree with its own optimizer
  (reference ``:145`` keeps it as an nn.Parameter).
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import flax.linen as nn
import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.distributions import TanhNormal
from sheeprl_tpu.models.blocks import MLP
from sheeprl_tpu.precision import train_policy

LOG_STD_MIN, LOG_STD_MAX = -5.0, 2.0


class SACActor(nn.Module):
    act_dim: int
    hidden_size: int = 256
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
        x = MLP(hidden_sizes=(self.hidden_size, self.hidden_size), activation="relu", dtype=self.dtype)(obs)
        out = nn.Dense(2 * self.act_dim, dtype=self.dtype)(x).astype(jnp.float32)
        mean, log_std = jnp.split(out, 2, axis=-1)
        # tanh-clamped log-std in [LOG_STD_MIN, LOG_STD_MAX] (reference agent.py:88-92)
        log_std = jnp.tanh(log_std)
        log_std = LOG_STD_MIN + 0.5 * (LOG_STD_MAX - LOG_STD_MIN) * (log_std + 1)
        return mean, log_std

    def dist(self, mean: jax.Array, log_std: jax.Array) -> TanhNormal:
        return TanhNormal(mean, jnp.exp(log_std))


class SACCriticEnsemble(nn.Module):
    n_critics: int = 2
    hidden_size: int = 256
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array, action: jax.Array) -> jax.Array:
        x = jnp.concatenate([obs, action], axis=-1)
        ensemble = nn.vmap(
            MLP,
            in_axes=None,
            out_axes=0,
            axis_size=self.n_critics,
            variable_axes={"params": 0},
            split_rngs={"params": True},
        )
        # [n_critics, batch, 1]
        return ensemble(
            hidden_sizes=(self.hidden_size, self.hidden_size),
            output_dim=1,
            activation="relu",
            dtype=self.dtype,
        )(x).astype(jnp.float32)


def build_agent(
    ctx,
    action_space: gymnasium.spaces.Space,
    obs_space: gymnasium.spaces.Dict,
    cfg: Dict[str, Any],
) -> Tuple[SACActor, SACCriticEnsemble, Dict[str, Any]]:
    if not isinstance(action_space, gymnasium.spaces.Box):
        raise ValueError("SAC supports continuous (Box) action spaces only (reference parity)")
    act_dim = int(np.prod(action_space.shape))
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_dim = int(sum(np.prod(obs_space[k].shape) for k in mlp_keys))

    # algo.precision resolves the compute dtype ("mesh" inherits ctx.compute_dtype);
    # flax param_dtype stays f32 so params/optimizer state are full precision
    # under every mixed policy (howto/precision.md).
    compute_dtype = train_policy(cfg, ctx).compute_dtype
    actor = SACActor(act_dim=act_dim, hidden_size=cfg.algo.actor.hidden_size, dtype=compute_dtype)
    critic = SACCriticEnsemble(
        n_critics=cfg.algo.critic.n, hidden_size=cfg.algo.critic.hidden_size, dtype=compute_dtype
    )
    dummy_obs = jnp.zeros((1, obs_dim))
    dummy_act = jnp.zeros((1, act_dim))
    params = {
        "actor": actor.init(ctx.rng(), dummy_obs),
        "critic": critic.init(ctx.rng(), dummy_obs, dummy_act),
        "log_alpha": jnp.asarray(jnp.log(cfg.algo.alpha.alpha), dtype=jnp.float32),
    }
    params["critic_target"] = jax.tree.map(lambda x: x, params["critic"])
    params = ctx.replicate(params)
    return actor, critic, params
