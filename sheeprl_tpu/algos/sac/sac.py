"""SAC training loop (reference: ``/root/reference/sheeprl/algos/sac/sac.py:81-…``).

TPU-first structure: each iteration steps the envs once, then runs ALL of this
iteration's gradient steps in one jitted call — the host samples
``G × batch`` transitions from the replay buffer, ships them as a ``[G, B, ...]``
block, and a ``lax.scan`` consumes one minibatch per step (the reference python-loops
``train()`` G times, ``sac.py:343-355``).  The EMA target update is fused into the same
scan.  The replay-ratio ``Ratio`` governor decides G exactly as in the reference."""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.analysis.strict import maybe_inject_nonfinite, nan_scan, strict_enabled, strict_guard
from sheeprl_tpu.algos.ppo.ppo import make_optimizer
from sheeprl_tpu.algos.sac.agent import build_agent
from sheeprl_tpu.algos.sac.loss import actor_loss, alpha_loss, critic_loss
from sheeprl_tpu.algos.sac.utils import AGGREGATOR_KEYS, prepare_obs, test
from sheeprl_tpu.checkpoint.manager import CheckpointManager
from sheeprl_tpu.fault.guard import TrainingGuard
from sheeprl_tpu.config.core import save_config
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.data.device_buffer import make_transition_ring
from sheeprl_tpu.data.prefetch import maybe_prefetcher
from sheeprl_tpu.obs import perf as obs_perf
from sheeprl_tpu.obs import TrainingMonitor, flight_recorder
from sheeprl_tpu.obs.health import diagnostics, health_enabled, replay_age_metrics
from sheeprl_tpu.precision import train_policy
from sheeprl_tpu.rollout import PipelinedPlayer, rollout_metrics
from sheeprl_tpu.utils.blocks import FusedRingDispatcher, WindowedFutures
from sheeprl_tpu.utils.env import make_vector_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, record_episode_stats
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio


def make_sac_step_fn(actor, critic, cfg, act_space, inject_lr=()):
    """The per-gradient-step SAC update as a pure function, shared by the host-batch
    scan (:func:`make_sac_train_fn`) and the fused device-ring block
    (:func:`make_sac_fused_builder`):

        step_update(p, o_state, gstep, batch, key) -> (p, o_state, metrics)

    ``gstep`` is the cumulative gradient-step count BEFORE this step (the EMA
    target cadence tests it post-increment, matching the eager reference).
    Returns the optimizers too — the callers init/restore optimizer state.

    ``inject_lr`` names optimizers (``"actor"`` / ``"critic"`` / ``"alpha"``)
    whose learning rate should live in the optimizer STATE
    (``optax.inject_hyperparams``) instead of the update closure — the
    population engine's per-member learning-rate sweep
    (``engine/population.py``)."""
    act_dim = int(np.prod(act_space.shape))
    target_entropy = -act_dim
    tau = cfg.algo.tau
    gamma = cfg.algo.gamma

    health = health_enabled(cfg)  # trace-time constant (obs/health.py)
    # Precision boundary (howto/precision.md): sampled float obs are cast to the
    # policy's compute dtype before the first matmul; losses/targets stay f32
    # (the agents' heads cast their outputs back up).
    precision = train_policy(cfg)
    actor_opt = make_optimizer(
        cfg.algo.actor.optimizer, cfg.algo.get("max_grad_norm", 0.0), inject_lr="actor" in inject_lr
    )
    critic_opt = make_optimizer(
        cfg.algo.critic.optimizer, cfg.algo.get("max_grad_norm", 0.0), inject_lr="critic" in inject_lr
    )
    alpha_opt = make_optimizer(cfg.algo.alpha.optimizer, 0.0, inject_lr="alpha" in inject_lr)

    def _losses(p, batch, key):
        key_next, key_new = jax.random.split(key)
        obs, next_obs = precision.cast_to_compute((batch["obs"], batch["next_obs"]))
        action, reward, done = batch["actions"], batch["rewards"], batch["dones"]
        alpha = jnp.exp(p["log_alpha"])

        # --- critic target (reference sac.py:39-47)
        next_mean, next_log_std = actor.apply(p["actor"], next_obs)
        next_act, next_logp = actor.dist(next_mean, next_log_std).sample_and_log_prob(key_next)
        next_logp = next_logp.sum(-1, keepdims=True)
        q_next = critic.apply(p["critic_target"], next_obs, next_act).min(axis=0)
        target = reward + (1.0 - done) * gamma * (q_next - alpha * next_logp)
        target = jax.lax.stop_gradient(target)

        def c_loss(cp):
            qs = critic.apply(cp, obs, action)
            return critic_loss(qs, target), {"q_mean": qs.mean(), "q_std": qs.std(), "target_q_mean": target.mean()}

        # --- actor (reference sac.py:50-58); takes the critic params explicitly so the
        # caller can pass the POST-update critic (reference updates critic first).
        def a_loss(ap, critic_params):
            mean, log_std = actor.apply(ap, obs)
            new_act, logp = actor.dist(mean, log_std).sample_and_log_prob(key_new)
            logp = logp.sum(-1, keepdims=True)
            min_q = critic.apply(critic_params, obs, new_act).min(axis=0)
            return actor_loss(alpha, logp, min_q), logp

        # --- alpha (reference sac.py:61-79)
        def t_loss(log_a, logp):
            return alpha_loss(log_a, logp, target_entropy)

        return c_loss, a_loss, t_loss

    target_update_freq = max(int(cfg.algo.critic.get("target_network_frequency", 1)), 1)

    def step_update(p, o_state, gstep, batch, key):
        c_loss, a_loss, t_loss = _losses(p, batch, key)

        (cl, q_aux), c_grads = jax.value_and_grad(c_loss, has_aux=True)(p["critic"])
        c_updates, new_c_state = critic_opt.update(c_grads, o_state["critic"], p["critic"])
        p = {**p, "critic": optax.apply_updates(p["critic"], c_updates)}

        # Actor minimises against the freshly-updated critic (reference sac.py:49-63).
        (al, logp), a_grads = jax.value_and_grad(a_loss, has_aux=True)(p["actor"], p["critic"])
        a_updates, new_a_state = actor_opt.update(a_grads, o_state["actor"], p["actor"])
        p = {**p, "actor": optax.apply_updates(p["actor"], a_updates)}

        tl, t_grads = jax.value_and_grad(t_loss)(p["log_alpha"], logp)
        t_updates, new_t_state = alpha_opt.update(t_grads, o_state["alpha"], p["log_alpha"])
        p = {**p, "log_alpha": optax.apply_updates(p["log_alpha"], t_updates)}

        # EMA target update, gated on critic.target_network_frequency (reference
        # sac.py:349-355 gates on the update counter; freq=1 ⇒ every step).
        do_update = ((gstep + 1) % target_update_freq) == 0
        p = {
            **p,
            "critic_target": jax.tree.map(
                lambda tp, cp: jnp.where(do_update, (1 - tau) * tp + tau * cp, tp),
                p["critic_target"],
                p["critic"],
            ),
        }
        o_state = {"actor": new_a_state, "critic": new_c_state, "alpha": new_t_state}
        metrics = {"Loss/value_loss": cl, "Loss/policy_loss": al, "Loss/alpha_loss": tl}
        if health:  # per-module norms/ratios + entropy/Q stats, one scalar tree
            metrics.update(
                diagnostics(
                    grads={"critic": c_grads, "actor": a_grads, "alpha": t_grads},
                    params=p,
                    updates={"critic": c_updates, "actor": a_updates, "alpha": t_updates},
                    aux={"policy_entropy": -logp.mean(), **q_aux},
                )
            )
        return p, o_state, metrics

    return actor_opt, critic_opt, alpha_opt, step_update


def make_sac_train_fn(actor, critic, cfg, act_space):
    """Optimizers + the jitted scanned SAC update over host-shipped ``[G, B, ...]``
    batch blocks; shared by the coupled and decoupled entry points (host replay
    path) and the flight-recorder replay builder."""
    strict = strict_enabled(cfg)
    actor_opt, critic_opt, alpha_opt, step_update = make_sac_step_fn(actor, critic, cfg, act_space)

    @jax.jit
    def train_fn(p, o_state, batches, key, grad_step0):
        def step(carry, batch):
            p, o_state, gstep = carry
            p, o_state, metrics = step_update(p, o_state, gstep, batch, batch.pop("_key"))
            return (p, o_state, gstep + 1), metrics

        g = batches["obs"].shape[0]
        batches["_key"] = jax.random.split(key, g)
        (p, o_state, _), metrics = jax.lax.scan(step, (p, o_state, grad_step0), batches)
        metrics = jax.tree.map(jnp.mean, metrics)
        metrics = maybe_inject_nonfinite(cfg, metrics)
        if strict:  # trace-time constant: the callback only exists in strict runs
            nan_scan(metrics, "sac/train_fn")
        return p, o_state, metrics

    return actor_opt, critic_opt, alpha_opt, train_fn


def make_sac_fused_builder(actor, critic, cfg, act_space, ring, batch_size: int):
    """Block builder for :class:`~sheeprl_tpu.utils.blocks.FusedRingDispatcher`:
    the whole K-step UTD block — in-jit uniform index sampling from the carried
    PRNG key, HBM batch gather, and K scanned :func:`make_sac_step_fn` updates —
    compiles to ONE jit with the carry (params + opt state) donated.

    Per-step keys derive as ``fold_in(base_key, cumulative_step)``, so any chunk
    decomposition of a block is bit-identical to the fused whole (the parity
    contract ``tests/test_algos/test_fused_blocks.py`` pins).

    Returns ``(optimizers..., builder)`` where ``builder(k, last)`` is the
    dispatcher's block factory (``last`` is ignored — SAC has no per-block tail).
    """
    strict = strict_enabled(cfg)
    health = health_enabled(cfg)
    actor_opt, critic_opt, alpha_opt, step_update = make_sac_step_fn(actor, critic, cfg, act_space)
    sample_gather = ring.make_sample_gather(batch_size)

    def builder(k, last):
        def block(carry, arrays, filled, rows_added, base_key, start_count):
            def step(c, count):
                p, o_state = c
                k_sample, k_update = jax.random.split(jax.random.fold_in(base_key, count))
                batch, age_metrics = sample_gather(arrays, filled, rows_added, k_sample)
                p, o_state, metrics = step_update(p, o_state, count, batch, k_update)
                if health:  # replay staleness rides the same deferred-metrics tree
                    metrics = {**metrics, **age_metrics}
                return (p, o_state), metrics

            counts = jnp.asarray(start_count, jnp.int32) + jnp.arange(k, dtype=jnp.int32)
            (p, o_state), metrics = jax.lax.scan(step, (carry["params"], carry["opt_state"]), counts)
            metrics = jax.tree.map(jnp.mean, metrics)
            metrics = maybe_inject_nonfinite(cfg, metrics)
            if strict:  # trace-time constant: the callback only exists in strict runs
                nan_scan(metrics, "sac/fused_block")
            return {"params": p, "opt_state": o_state}, metrics

        return block

    return actor_opt, critic_opt, alpha_opt, builder


@register_algorithm(name="sac")
def main(ctx, cfg) -> None:
    if cfg.algo.anakin:
        # Anakin mode (howto/anakin.md): jax envs + ring writes + the fused UTD
        # update all inside one donated scan — the engine owns the loop.
        from sheeprl_tpu.engine.anakin import sac_anakin

        return sac_anakin(ctx, cfg)
    rank = ctx.process_index
    log_dir = get_log_dir(cfg)
    if ctx.is_global_zero:
        save_config(cfg, Path(log_dir) / "config.yaml")
    logger = get_logger(cfg, log_dir)
    monitor = TrainingMonitor(cfg, log_dir)

    envs = make_vector_env(cfg, cfg.seed, rank, log_dir if cfg.env.capture_video else None)
    obs_space = envs.single_observation_space
    act_space = envs.single_action_space
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    act_low, act_high = act_space.low, act_space.high
    rescale = np.isfinite(act_low).all() and np.isfinite(act_high).all()

    actor, critic, params = build_agent(ctx, act_space, obs_space, cfg)
    actor_opt, critic_opt, alpha_opt, train_fn = make_sac_train_fn(actor, critic, cfg, act_space)
    train_fn = obs_perf.instrument(cfg, "sac/train_fn", strict_guard(cfg, "sac/train_fn", train_fn))
    recorder = flight_recorder.get_active()
    if recorder is not None:
        recorder.arm_replay(
            "sheeprl_tpu.algos.sac.sac:replay_update",
            act_space=act_space,
            obs_space=obs_space,
        )
    opt_state = ctx.replicate(
        {
            "actor": actor_opt.init(params["actor"]),
            "critic": critic_opt.init(params["critic"]),
            "alpha": alpha_opt.init(params["log_alpha"]),
        }
    )

    num_envs = cfg.env.num_envs
    world = jax.process_count()
    # Per-env row count: total capacity is cfg.buffer.size transitions across all envs
    # and ranks (reference sac.py:183).
    rb = ReplayBuffer(
        max(int(cfg.buffer.size) // max(num_envs * world, 1), 1),
        num_envs,
        obs_keys=mlp_keys,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}") if cfg.buffer.memmap else None,
    )
    rb.seed(cfg.seed + rank)

    aggregator = MetricAggregator(cfg.metric.aggregator.get("metrics", {}))
    aggregator.keep(AGGREGATOR_KEYS | set(cfg.metric.aggregator.get("metrics", {})))
    ckpt_manager = CheckpointManager(Path(log_dir) / "checkpoints", keep_last=cfg.checkpoint.keep_last)
    guard = TrainingGuard(cfg, log_dir)
    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)

    batch_size = cfg.algo.per_rank_batch_size
    futures = WindowedFutures()

    # Device-resident replay (buffer.device=True, data/device_buffer.py): the
    # transition ring lives in HBM, index sampling happens inside the fused
    # scanned block from the carried PRNG key, and a whole Ratio-sized gradient
    # block is ONE jit dispatch with the train state donated.
    obs_dim = int(sum(np.prod(obs_space[k].shape) for k in mlp_keys))
    act_dim = int(np.prod(act_space.shape))
    ring = make_transition_ring(
        ctx,
        cfg,
        rb,
        {
            "obs": ((obs_dim,), jnp.float32),
            "next_obs": ((obs_dim,), jnp.float32),
            "actions": ((act_dim,), jnp.float32),
            "rewards": ((1,), jnp.float32),
            "dones": ((1,), jnp.float32),
        },
    )
    fused = None
    if ring is not None:
        _, _, _, fused_builder = make_sac_fused_builder(actor, critic, cfg, act_space, ring, batch_size)
        fused = FusedRingDispatcher(
            fused_builder, base_key=ctx.rng(), futures=futures, cfg=cfg, perf_name="sac/fused_block"
        )
        # Donation safety: critic_target aliases critic's buffers at init (the
        # identity tree.map in build_agent) — a donated carry must not contain the
        # same buffer twice, so deep-copy the train state once up front.
        params = jax.tree.map(jnp.copy, params)
        opt_state = jax.tree.map(jnp.copy, opt_state)

    def _ring_transitions():
        return {
            "obs": np.concatenate([step_data[k].reshape(1, num_envs, -1) for k in mlp_keys], -1),
            "next_obs": np.concatenate(
                [step_data[f"next_{k}"].reshape(1, num_envs, -1) for k in mlp_keys], -1
            ),
            "actions": step_data["actions"],
            "rewards": step_data["rewards"],
            "dones": step_data["dones"],
        }

    @jax.jit
    def act_fn(p, obs, key):
        mean, log_std = actor.apply(p, obs)
        dist = actor.dist(mean, log_std)
        return dist.sample(key)

    # ------------------------------------------------------------------ counters
    policy_steps_per_iter = num_envs * world
    total_steps = int(cfg.algo.total_steps)
    num_iters = max(total_steps // policy_steps_per_iter, 1) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_iters = max(learning_starts - 1, 0)

    start_iter = 1
    policy_step = 0
    last_log = 0
    last_checkpoint = 0
    cumulative_grad_steps = 0
    if cfg.checkpoint.get("resume_from"):
        state = CheckpointManager.load(
            cfg.checkpoint.resume_from,
            templates={"params": jax.device_get(params), "opt_state": jax.device_get(opt_state)},
        )
        params = ctx.replicate(state["params"])
        opt_state = ctx.replicate(state["opt_state"])
        ratio.load_state_dict(state["ratio"])
        start_iter = state["iter_num"] + 1
        policy_step = state["policy_step"]
        last_log = state.get("last_log", 0)
        last_checkpoint = state.get("last_checkpoint", 0)
        cumulative_grad_steps = state.get("cumulative_grad_steps", 0)
        learning_starts += start_iter
        if cfg.buffer.checkpoint and "rb" in state:
            rb.load_state_dict(state["rb"])
            if ring is not None and len(rb) > 0:
                # The host buffer stays the source of truth: rebuild the HBM ring
                # (and its staleness stamps) from the restored rows.
                ring.load_from_transitions(
                    {
                        "obs": np.concatenate(
                            [rb[k].reshape(rb.buffer_size, num_envs, -1) for k in mlp_keys], -1
                        ),
                        "next_obs": np.concatenate(
                            [rb[f"next_{k}"].reshape(rb.buffer_size, num_envs, -1) for k in mlp_keys], -1
                        ),
                        "actions": rb["actions"],
                        "rewards": rb["rewards"],
                        "dones": rb["dones"],
                    },
                    stamps=rb.row_stamps,
                )

    obs, _ = envs.reset(seed=cfg.seed + rank)
    step_data: Dict[str, np.ndarray] = {}

    # Acting pipeline (sheeprl_tpu/rollout): depth 0 is the historical synchronous
    # path bit-for-bit; depth>=1 overlaps the actor jit + action fetch with the env
    # workers (policy lag — benign for SAC's replay-based update).
    def _pipeline_policy(cur_obs):
        obs_t = prepare_obs(cur_obs, mlp_keys)
        return act_fn(params["actor"], obs_t, ctx.local_rng())

    def _pipeline_post(fetched):
        tanh_np = np.asarray(fetched)
        env_acts = act_low + (tanh_np + 1) * 0.5 * (act_high - act_low) if rescale else tanh_np
        return env_acts, tanh_np

    rollout_player = PipelinedPlayer(
        envs, _pipeline_policy, _pipeline_post, depth=int((cfg.get("rollout") or {}).get("pipeline_depth", 0))
    )

    # Async host-side sampling (SURVEY §7): the worker draws + ships the next [G, B]
    # block while the device executes the current one; ``rb.add`` holds the sampler's
    # lock so the worker never reads a row mid-write.  ``next_{k}`` keys are stored
    # explicitly (with final-obs correction), so no derived next-obs sampling is
    # needed.  Batch axis 1 of the [G, B, ...] block is sharded over the data axis —
    # GSPMD inserts the gradient all-reduce (params stay replicated).
    def _sample_block(n: int):
        sample = rb.sample(batch_size * n)
        batches = {
            "obs": np.concatenate([sample[k].reshape(n, batch_size, -1) for k in mlp_keys], -1),
            "next_obs": np.concatenate(
                [sample[f"next_{k}"].reshape(n, batch_size, -1) for k in mlp_keys], -1
            ),
            "actions": sample["actions"].reshape(n, batch_size, -1),
            "rewards": sample["rewards"].reshape(n, batch_size, 1),
            "dones": sample["dones"].reshape(n, batch_size, 1),
        }
        return ctx.put_batch(batches, batch_axis=1)

    prefetcher, rb_lock = maybe_prefetcher(cfg, _sample_block, enabled=ring is None)

    def _dispatch_train(grad_steps: int, stage_next: bool) -> None:
        nonlocal params, opt_state, cumulative_grad_steps
        if ring is not None:
            # Fused device-ring block: ONE donated dispatch for the whole K-step
            # UTD block; even the index sampling runs in-jit off the carried key.
            carry = fused.dispatch(
                {"params": params, "opt_state": opt_state},
                ring.arrays,
                len(rb),
                rb.rows_added,
                grad_steps,
                cumulative_grad_steps,
            )
            params, opt_state = carry["params"], carry["opt_state"]
            cumulative_grad_steps += grad_steps
            if recorder is not None:
                # The pre-step state was DONATED into the block — its buffers no
                # longer exist, so re-stage post-dispatch with a device-side copy
                # (async, no host sync); the dump then carries the state entering
                # the NEXT block plus the counters that derive its in-jit samples.
                recorder.stage_step(
                    carry=jax.tree.map(jnp.copy, carry),
                    scalars={
                        "grad_step0": int(cumulative_grad_steps),
                        "filled": len(rb),
                        "rows_added": rb.rows_added,
                    },
                )
            return
        batches = (
            prefetcher.get(grad_steps, stage_next=stage_next)
            if prefetcher is not None
            else _sample_block(grad_steps)
        )
        key = ctx.rng()
        if recorder is not None:  # device-array references only: no host sync
            recorder.stage_step(
                batch=batches,
                carry={"params": params, "opt_state": opt_state},
                key=key,
                scalars={"grad_step0": int(cumulative_grad_steps)},
            )
        params, opt_state, train_metrics = train_fn(
            params, opt_state, batches, key, jnp.asarray(cumulative_grad_steps)
        )
        futures.track(train_metrics, grad_steps)
        cumulative_grad_steps += grad_steps

    for iter_num in range(start_iter, num_iters + 1):
        monitor.advance()
        env_t0 = time.perf_counter()
        with timer("Time/env_interaction_time"):
            # A resumed run already has a trained policy — don't replay the random
            # prefill (reference resume branch; dreamer_v3.py has the same guard).
            if iter_num <= learning_starts and not cfg.checkpoint.get("resume_from"):
                actions = np.stack([act_space.sample() for _ in range(num_envs)])
                tanh_actions = (
                    2 * (actions - act_low) / (act_high - act_low) - 1 if rescale else actions
                )
            else:
                with monitor.phase("player"):
                    actions, tanh_actions = rollout_player.act(obs)
        env_time = time.perf_counter() - env_t0

        # Dispatch this iteration's gradient block BEFORE stepping the envs so the
        # device trains while the host walks the environments (acting above used the
        # previous iteration's params, as before).  SAC rows are committed only
        # after env.step (they carry next_obs), so the very first training
        # iteration — empty buffer — defers its dispatch until after the row lands.
        grad_steps = 0
        deferred_dispatch = False
        if iter_num >= learning_starts:
            # Offset by the prefill so the governor doesn't demand the whole
            # prefill's worth of gradient steps in one burst (reference sac.py:301).
            grad_steps = ratio(
                (policy_step + policy_steps_per_iter - prefill_iters * policy_steps_per_iter) / world
            )
            if grad_steps > 0:
                if rb.empty:
                    deferred_dispatch = True
                else:
                    with monitor.phase("dispatch"):
                        _dispatch_train(grad_steps, stage_next=iter_num < num_iters)

        env_t0 = time.perf_counter()
        with timer("Time/env_interaction_time"):
            with monitor.phase("env_step"):
                next_obs, reward, terminated, truncated, info = rollout_player.env_step(actions)
            done = np.logical_or(terminated, truncated)

            # Store the TRUE next observation for done envs (SAME_STEP autoreset
            # returns the reset obs; reference uses final_observation similarly).
            real_next = {k: np.asarray(next_obs[k]).copy() for k in mlp_keys}
            if done.any() and "final_obs" in info:
                for i in np.nonzero(done)[0]:
                    if info["final_obs"][i] is not None:
                        for k in mlp_keys:
                            real_next[k][i] = np.asarray(info["final_obs"][i][k])

            for k in mlp_keys:
                step_data[k] = np.asarray(obs[k])[None]
                step_data[f"next_{k}"] = real_next[k][None]
            step_data["actions"] = tanh_actions.astype(np.float32)[None]
            step_data["rewards"] = np.asarray(reward, dtype=np.float32).reshape(num_envs, 1)[None]
            # Truncated episodes still bootstrap (done=0 in the TD target).
            step_data["dones"] = terminated.astype(np.float32).reshape(num_envs, 1)[None]
            with monitor.phase("buffer_add"), rb_lock:
                if ring is not None:  # donated scatter at the host cursor, pre-add
                    ring.add_step(_ring_transitions(), rb._pos, rb.rows_added)
                rb.add(step_data, validate_args=cfg.buffer.validate_args)
            obs = next_obs
            policy_step += policy_steps_per_iter
            record_episode_stats(aggregator, info)
        env_time += time.perf_counter() - env_t0

        if deferred_dispatch:
            with monitor.phase("dispatch"):
                _dispatch_train(grad_steps, stage_next=iter_num < num_iters)

        if logger is not None and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == num_iters or cfg.dry_run
        ):
            futures.drain(aggregator)  # the window's only blocking device sync
            metrics = aggregator.compute()
            window_sps = futures.pop_window_sps()
            if window_sps is not None:
                metrics["Time/sps_train"] = window_sps
            metrics["Time/sps_env_interaction"] = policy_steps_per_iter / world / env_time if env_time > 0 else 0.0
            metrics["Params/replay_ratio"] = (
                cumulative_grad_steps * world / policy_step if policy_step > 0 else 0.0
            )
            metrics.update(replay_age_metrics(rb))
            metrics.update(rollout_metrics(envs))
            monitor.log_metrics(logger, metrics, policy_step)
            aggregator.reset()
            last_log = policy_step

        def save_ckpt():
            nonlocal last_checkpoint
            state = {
                "params": params,
                "opt_state": opt_state,
                "ratio": ratio.state_dict(),
                "iter_num": iter_num,
                "policy_step": policy_step,
                "last_log": last_log,
                "last_checkpoint": policy_step,
                "cumulative_grad_steps": cumulative_grad_steps,
            }
            with monitor.phase("checkpoint"):
                if cfg.buffer.checkpoint:
                    state["rb"] = rb.state_dict()
                path = ckpt_manager.save(policy_step, state)
            last_checkpoint = policy_step
            return path

        if (
            cfg.checkpoint.every > 0
            and (policy_step - last_checkpoint) >= cfg.checkpoint.every
            or iter_num == num_iters
            and cfg.checkpoint.save_last
        ):
            save_ckpt()
        guard.boundary(policy_step, save_ckpt)

    monitor.close()
    envs.close()
    if prefetcher is not None:
        prefetcher.close()
    if cfg.algo.run_test and ctx.is_global_zero:
        reward = test(actor, params, ctx, cfg, log_dir)
        if logger is not None:
            logger.log_metrics({"Test/cumulative_reward": reward}, policy_step)
    if not cfg.get("model_manager", {}).get("disabled", True) and ctx.is_global_zero:
        from sheeprl_tpu.utils.model_manager import maybe_register_models

        maybe_register_models(cfg, log_dir)
    if logger is not None:
        logger.close()


def lower_for_audit():
    """IR-audit hook (``python -m sheeprl_tpu.analysis.ir``): both real SAC
    dispatch shapes — the host-batch ``[G, B]`` scanned update shared by the
    coupled and decoupled entry points, and the DONATED fused device-ring block
    (``buffer.device=True``) whose donation contract IR001 exists to guard."""
    from sheeprl_tpu.analysis.ir.synth import (
        box_act_space,
        compose_tiny,
        tiny_ctx,
        transition_ring,
        vector_space,
        zeros,
    )
    from sheeprl_tpu.analysis.ir.types import AuditEntry

    cfg = compose_tiny(
        [
            "exp=sac",
            "env=continuous_dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.hidden_size=8",
            "algo.per_rank_batch_size=4",
            "env.num_envs=2",
        ]
    )
    ctx = tiny_ctx(cfg)
    obs_space, act_space = vector_space(), box_act_space()
    actor, critic, params = build_agent(ctx, act_space, obs_space, cfg)
    precision = str(cfg.mesh.precision)
    key = jax.random.PRNGKey(0)

    actor_opt, critic_opt, alpha_opt, train_fn = make_sac_train_fn(actor, critic, cfg, act_space)
    opt_state = {
        "actor": actor_opt.init(params["actor"]),
        "critic": critic_opt.init(params["critic"]),
        "alpha": alpha_opt.init(params["log_alpha"]),
    }
    G, B = 2, 4
    batches = {
        "obs": zeros((G, B, 5)),
        "next_obs": zeros((G, B, 5)),
        "actions": zeros((G, B, 2)),
        "rewards": zeros((G, B, 1)),
        "dones": zeros((G, B, 1)),
    }
    entries = [
        AuditEntry(
            name="sac/train_fn",
            fn=train_fn,
            args=(params, opt_state, batches, key, jnp.zeros((), jnp.int32)),
            covers=("sac", "sac_decoupled"),
            precision=precision,
        )
    ]

    ring, filled, rows_added = transition_ring(obs_dim=5, act_dim=2)
    _, _, _, builder = make_sac_fused_builder(actor, critic, cfg, act_space, ring, B)
    block = jax.jit(builder(2, True), donate_argnums=(0,))
    carry = {"params": params, "opt_state": opt_state}
    entries.append(
        AuditEntry(
            name="sac/fused_block",
            fn=block,
            args=(carry, ring.arrays, filled, rows_added, key, 0),
            covers=("sac", "sac_decoupled"),
            precision=precision,
        )
    )
    return entries


def replay_update(cfg, dump_dir):
    """Flight-recorder replay builder: re-execute the dumped SAC gradient block on
    CPU.  Shared by the coupled and decoupled entry points (same
    ``make_sac_train_fn`` update)."""
    from sheeprl_tpu.obs import replay_blackbox
    from sheeprl_tpu.parallel.mesh import make_mesh_context

    ctx = make_mesh_context(cfg)
    raw = replay_blackbox.load_state(dump_dir)
    statics = raw["statics"]
    actor, critic, params0 = build_agent(ctx, statics["act_space"], statics["obs_space"], cfg)
    actor_opt, critic_opt, alpha_opt, train_fn = make_sac_train_fn(actor, critic, cfg, statics["act_space"])
    opt0 = {
        "actor": actor_opt.init(params0["actor"]),
        "critic": critic_opt.init(params0["critic"]),
        "alpha": alpha_opt.init(params0["log_alpha"]),
    }
    templates = {"carry": jax.device_get({"params": params0, "opt_state": opt0})}
    state = replay_blackbox.load_state(dump_dir, templates)
    carry = state["carry"]
    if "batch" not in state:
        # Device-ring dump (buffer.device=True): the donated fused block stages
        # the post-block state + the counters that derive its in-jit samples, not
        # a batch (see howto/device_replay.md).  Re-executing needs the run's
        # checkpointed host buffer; report what IS replayable instead of KeyError.
        raise RuntimeError(
            "this blackbox dump comes from the device-ring fused path: it stages "
            "the train state entering the failing block plus its sampling "
            f"counters ({ {k: v for k, v in state.get('scalars', {}).items()} }), "
            "but no batch. Rebuild the batch from the run's checkpointed replay "
            "buffer (buffer.checkpoint=True) and the dumped counters, or rerun "
            "with buffer.device=False to capture host-shipped batches."
        )
    new_params, _, metrics = train_fn(
        ctx.replicate(carry["params"]),
        ctx.replicate(carry["opt_state"]),
        state["batch"],
        jnp.asarray(state["key"]),
        jnp.asarray(state["scalars"]["grad_step0"]),
    )
    return {
        "metrics": jax.device_get(metrics),
        "new_param_norm": float(jax.device_get(optax.global_norm(new_params))),
    }
