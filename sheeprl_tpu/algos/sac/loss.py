"""SAC losses (reference: ``/root/reference/sheeprl/algos/sac/sac.py:32-79``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def critic_loss(qs: jax.Array, target: jax.Array) -> jax.Array:
    """Sum of per-critic MSEs against the shared target; ``qs``: [n_critics, B, 1]."""
    return ((qs - target[None]) ** 2).mean(axis=(1, 2)).sum()


def actor_loss(alpha: jax.Array, logp: jax.Array, min_q: jax.Array) -> jax.Array:
    return (alpha * logp - min_q).mean()


def alpha_loss(log_alpha: jax.Array, logp: jax.Array, target_entropy: float) -> jax.Array:
    """α loss with stop-gradient on the log-probs; the cross-rank mean of the α gradient
    (reference all_reduce at ``sac.py:73``) falls out of the global batch mean under
    GSPMD."""
    return -(jnp.exp(log_alpha) * (jax.lax.stop_gradient(logp) + target_entropy)).mean()
