"""P2E-DV3 helpers (reference: ``/root/reference/sheeprl/algos/p2e_dv3/utils.py``)."""

from __future__ import annotations

from sheeprl_tpu.algos.dreamer_v3.utils import (  # noqa: F401
    init_moments,
    prepare_obs,
    test,
    update_moments,
)

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "Loss/ensemble_loss",
    "Loss/policy_loss_task",
    "Loss/value_loss_task",
    "Loss/policy_loss_exploration",
    "State/kl",
    "State/post_entropy",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
    "State/prior_entropy",
}
MODELS_TO_REGISTER = {
    "world_model",
    "ensembles",
    "actor_exploration",
    "actor_task",
    "critic_task",
    "target_critic_task",
    "moments_task",
    "moments_exploration",
}
