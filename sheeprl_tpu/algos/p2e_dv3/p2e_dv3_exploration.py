"""P2E-DV3 exploration (reference: ``/root/reference/sheeprl/algos/p2e_dv3/p2e_dv3_exploration.py``).

Plan2Explore on the DreamerV3 stack, as ONE jitted train step with four phases
(reference ``train``, ``p2e_dv3_exploration.py:41-…``):

1. **Dynamic learning** — the DV3 world-model update, except the reward/continue heads
   train on *detached* latents (reference ``:160,163``);
2. **Ensemble learning** — N vmapped MLPs learn to predict the next stochastic state
   from ``(posterior, recurrent, action)`` (reference ``:205-230``);
3. **Exploration behaviour** — the exploration actor maximises a weighted mix of
   per-critic advantages; intrinsic critics use the ensemble-disagreement reward
   (``next_state_embedding.var(0).mean(-1) × multiplier``, reference ``:270-287``),
   task-reward critics use the learned reward model; each critic has its own Moments
   normaliser and EMA target (reference ``:261-369``);
4. **Task behaviour (zero-shot)** — the standard DV3 actor/critic update on the task
   reward, trained on the exploration data (reference ``:374-…``).

The env-interaction loop is the DV3 one; the player acts with the exploration actor
(``algo.player.actor_type: exploration``).
"""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.analysis.strict import maybe_inject_nonfinite, nan_scan, strict_enabled
from sheeprl_tpu.algos.dreamer_v3.agent import PlayerState, WorldModel, make_player_step
from sheeprl_tpu.algos.dreamer_v3.loss import reconstruction_loss
from sheeprl_tpu.algos.p2e import ensemble_loss, intrinsic_reward
from sheeprl_tpu.algos.p2e_dv3.agent import build_agent, parse_actions_dim
from sheeprl_tpu.algos.p2e_dv3.utils import (
    AGGREGATOR_KEYS,
    init_moments,
    prepare_obs,
    test,
    update_moments,
)
from sheeprl_tpu.algos.ppo.ppo import make_optimizer
from sheeprl_tpu.checkpoint.manager import CheckpointManager
from sheeprl_tpu.fault.guard import TrainingGuard
from sheeprl_tpu.config.core import save_config
from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, SequentialReplayBuffer
from sheeprl_tpu.data.device_buffer import make_device_replay
from sheeprl_tpu.distributions import (
    BernoulliSafeMode,
    Independent,
    MSEDistribution,
    OneHotCategorical,
    SymlogDistribution,
    TwoHotEncodingDistribution,
)
from sheeprl_tpu.obs import TrainingMonitor
from sheeprl_tpu.obs.health import diagnostics, health_enabled, replay_age_metrics
from sheeprl_tpu.utils.env import make_vector_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, record_episode_stats
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio


def make_train_step(world_model, actor, critic, ensemble_mlp, cfg, cnn_keys, mlp_keys, critic_cfgs):
    """``critic_cfgs``: static ``{name: {"weight", "reward_type"}}`` of the enabled
    exploration critics (config iteration is static under jit)."""
    wm_cfg = cfg.algo.world_model
    stoch = wm_cfg.stochastic_size
    discrete = wm_cfg.discrete_size
    stoch_size = stoch * discrete
    rec_size = wm_cfg.recurrent_model.recurrent_state_size
    horizon = cfg.algo.horizon
    gamma = cfg.algo.gamma
    lmbda = cfg.algo.lmbda
    ent_coef = cfg.algo.actor.ent_coef
    is_continuous = actor.is_continuous
    actions_dim = tuple(actor.actions_dim)
    tau = cfg.algo.critic.tau
    moments_cfg = cfg.algo.actor.moments
    intr_mult = cfg.algo.intrinsic_reward_multiplier
    weights_sum = sum(c["weight"] for c in critic_cfgs.values())

    wm_opt = make_optimizer(wm_cfg.optimizer, wm_cfg.clip_gradients)
    actor_opt = make_optimizer(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients)
    critic_opt = make_optimizer(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients)
    ens_opt = make_optimizer(cfg.algo.ensembles.optimizer, cfg.algo.ensembles.clip_gradients)

    def init_opt_states(params):
        return {
            "world_model": wm_opt.init(params["world_model"]),
            "actor_task": actor_opt.init(params["actor_task"]),
            "critic_task": critic_opt.init(params["critic_task"]),
            "actor_exploration": actor_opt.init(params["actor_exploration"]),
            "critics_exploration": {
                k: critic_opt.init(params["critics_exploration"][k]["module"]) for k in critic_cfgs
            },
            "ensembles": ens_opt.init(params["ensembles"]),
        }

    def init_moments_state():
        return {"task": init_moments(), "expl": {k: init_moments() for k in critic_cfgs}}

    def _moments(mstate, lambda_values):
        return update_moments(
            mstate,
            lambda_values,
            decay=moments_cfg.decay,
            max_=moments_cfg.max,
            percentile_low=moments_cfg.percentile.low,
            percentile_high=moments_cfg.percentile.high,
        )

    def _lambda_values(reward, values, continues):
        interm = reward[1:] + continues[1:] * gamma * values[1:] * (1 - lmbda)

        def lam_step(carry, x):
            it, ct = x
            carry = it + ct * gamma * lmbda * carry
            return carry, carry

        _, lv = jax.lax.scan(lam_step, values[-1], (interm, continues[1:]), reverse=True, unroll=8)
        return lv

    def _imagine(actor_params, wm_params, prior0, rec0, latent0, k_img, k_a0):
        """DV3-style imagination rollout returning [H+1] latents + actions."""
        a0_tuple, _ = actor.apply(actor_params, latent0, k_a0)
        a0 = jnp.concatenate(a0_tuple, -1)

        def img_step(carry, k):
            prior, rec, action = carry
            k_dyn, k_act = jax.random.split(k)
            prior, rec = world_model.apply(wm_params, prior, rec, action, k_dyn, method=WorldModel.imagination)
            latent = jnp.concatenate([prior, rec], -1)
            acts, _ = actor.apply(actor_params, jax.lax.stop_gradient(latent), k_act)
            action = jnp.concatenate(acts, -1)
            return (prior, rec, action), (latent, action)

        keys = jax.random.split(k_img, horizon)
        _, (latents_img, actions_img) = jax.lax.scan(img_step, (prior0, rec0, a0), keys, unroll=5)
        traj = jnp.concatenate([latent0[None], latents_img], 0)
        imagined_actions = jnp.concatenate([a0[None], actions_img], 0)
        return traj, imagined_actions

    def _policy_loss(actor_params, traj, imagined_actions, advantage, discount):
        _, dists = actor.apply(actor_params, jax.lax.stop_gradient(traj), None)
        if is_continuous:
            objective = advantage
            entropy = ent_coef * dists[0].entropy().sum(-1)
        else:
            logpis = []
            offset_a = 0
            for i, d in enumerate(dists):
                act_i = jax.lax.stop_gradient(imagined_actions[..., offset_a : offset_a + actions_dim[i]])
                logpis.append(d.log_prob(act_i)[:-1])
                offset_a += actions_dim[i]
            objective = sum(logpis)[..., None] * jax.lax.stop_gradient(advantage)
            entropy = ent_coef * sum(d.entropy() for d in dists)
        return -jnp.mean(discount[:-1] * (objective + entropy[:-1][..., None]))

    def _critic_loss(critic_params, target_params, traj, lambda_values, discount):
        qv = TwoHotEncodingDistribution(critic.apply(critic_params, traj[:-1]), dims=1)
        target_values = TwoHotEncodingDistribution(critic.apply(target_params, traj[:-1]), dims=1).mean
        loss = -qv.log_prob(lambda_values) - qv.log_prob(jax.lax.stop_gradient(target_values))
        return jnp.mean(loss * discount[:-1][..., 0])

    def train_step(params, opt_states, moments_state, data, key, update_target):
        T, B = data["rewards"].shape[:2]
        k_wm, k_img_e, k_a0_e, k_img_t, k_a0_t = jax.random.split(key, 5)
        sg = jax.lax.stop_gradient

        batch_obs = {k: data[k] for k in cnn_keys + mlp_keys}
        is_first = data["is_first"].at[0].set(1.0)
        batch_actions = jnp.concatenate([jnp.zeros_like(data["actions"][:1]), data["actions"][:-1]], 0)

        # ---------------------------------------------------- 1. world model
        def wm_loss_fn(wm_params):
            embed = world_model.apply(wm_params, batch_obs, method=WorldModel.encode)

            def step(carry, x):
                post, rec = carry
                action, emb, first, k = x
                rec, post, _, post_logits, prior_logits = world_model.apply(
                    wm_params, post, rec, action, emb, first, k, method=WorldModel.dynamic
                )
                return (post, rec), (rec, post, post_logits, prior_logits)

            keys = jax.random.split(k_wm, T)
            init = (jnp.zeros((B, stoch_size)), jnp.zeros((B, rec_size)))
            _, (recs, posts, post_logits, prior_logits) = jax.lax.scan(
                step, init, (batch_actions, embed, is_first, keys), unroll=8
            )
            latents = jnp.concatenate([posts, recs], -1)
            recon = world_model.apply(wm_params, latents, method=WorldModel.decode)

            obs_lp = 0.0
            for k in cnn_keys:
                target = data[k].astype(jnp.float32) / 255.0 - 0.5
                target = target.reshape(T, B, -1, *target.shape[-2:])
                obs_lp = obs_lp + MSEDistribution(recon[k], dims=3).log_prob(target)
            for k in mlp_keys:
                obs_lp = obs_lp + SymlogDistribution(recon[k], dims=1).log_prob(data[k])

            # Reward/continue heads train on DETACHED latents (reference :160,:163).
            reward_lp = TwoHotEncodingDistribution(
                world_model.apply(wm_params, sg(latents), method=WorldModel.reward), dims=1
            ).log_prob(data["rewards"])
            continue_lp = Independent(
                BernoulliSafeMode(world_model.apply(wm_params, sg(latents), method=WorldModel.continues)), 1
            ).log_prob(1.0 - data["terminated"])

            post_logits_s = post_logits.reshape(T, B, stoch, discrete)
            prior_logits_s = prior_logits.reshape(T, B, stoch, discrete)
            rec_loss, metrics = reconstruction_loss(
                obs_lp,
                reward_lp,
                prior_logits_s,
                post_logits_s,
                wm_cfg.kl_dynamic,
                wm_cfg.kl_representation,
                wm_cfg.kl_free_nats,
                wm_cfg.kl_regularizer,
                continue_lp,
                wm_cfg.continue_scale_factor,
            )
            metrics["State/post_entropy"] = Independent(OneHotCategorical(post_logits_s), 1).entropy().mean()
            metrics["State/prior_entropy"] = Independent(OneHotCategorical(prior_logits_s), 1).entropy().mean()
            return rec_loss, (posts, recs, metrics)

        (rec_loss, (posts, recs, wm_metrics)), wm_grads = jax.value_and_grad(wm_loss_fn, has_aux=True)(
            params["world_model"]
        )
        wm_updates, new_wm_opt = wm_opt.update(wm_grads, opt_states["world_model"], params["world_model"])
        new_wm_params = optax.apply_updates(params["world_model"], wm_updates)

        # ---------------------------------------------------- 2. ensembles
        ens_inputs = jnp.concatenate([sg(posts), sg(recs), data["actions"]], -1)
        ens_targets = sg(posts)[1:]

        def ens_loss_fn(ens_params):
            return ensemble_loss(ensemble_mlp, ens_params, ens_inputs, ens_targets)

        ens_loss_val, ens_grads = jax.value_and_grad(ens_loss_fn)(params["ensembles"])
        ens_updates, new_ens_opt = ens_opt.update(ens_grads, opt_states["ensembles"], params["ensembles"])
        new_ens_params = optax.apply_updates(params["ensembles"], ens_updates)

        # ---------------------------------------------------- 3. exploration behaviour
        latent0 = sg(jnp.concatenate([posts, recs], -1)).reshape(T * B, -1)
        prior0 = sg(posts).reshape(T * B, stoch_size)
        rec0 = sg(recs).reshape(T * B, rec_size)
        true_continue0 = (1.0 - data["terminated"]).reshape(T * B, 1)

        def expl_actor_loss_fn(actor_params):
            traj, imagined_actions = _imagine(actor_params, new_wm_params, prior0, rec0, latent0, k_img_e, k_a0_e)
            continues = BernoulliSafeMode(
                world_model.apply(new_wm_params, traj, method=WorldModel.continues)
            ).mode
            continues = jnp.concatenate([true_continue0[None], continues[1:]], 0)
            discount = sg(jnp.cumprod(continues * gamma, 0) / gamma)

            advantages = []
            per_critic = {}
            new_moments_expl = {}
            metrics = {}
            for k, ccfg in critic_cfgs.items():
                values = TwoHotEncodingDistribution(
                    critic.apply(params["critics_exploration"][k]["module"], traj), dims=1
                ).mean
                if ccfg["reward_type"] == "intrinsic":
                    reward = intrinsic_reward(
                        ensemble_mlp,
                        new_ens_params,
                        jnp.concatenate([sg(traj), sg(imagined_actions)], -1),
                        intr_mult,
                    )
                    metrics[f"Rewards/intrinsic_{k}"] = reward.mean()
                else:
                    reward = TwoHotEncodingDistribution(
                        world_model.apply(new_wm_params, traj, method=WorldModel.reward), dims=1
                    ).mean
                lambda_values = _lambda_values(reward, values, continues)
                offset, invscale, new_m = _moments(moments_state["expl"][k], lambda_values)
                advantages.append(
                    (((lambda_values - offset) / invscale) - ((values[:-1] - offset) / invscale))
                    * ccfg["weight"]
                    / weights_sum
                )
                per_critic[k] = sg(lambda_values)
                new_moments_expl[k] = new_m
                metrics[f"Values_exploration/predicted_values_{k}"] = values.mean()
                metrics[f"Values_exploration/lambda_values_{k}"] = lambda_values.mean()

            advantage = sum(advantages)
            loss = _policy_loss(actor_params, traj, imagined_actions, advantage, discount)
            aux = {
                "traj": sg(traj),
                "discount": discount,
                "lambda_values": per_critic,
                "moments": new_moments_expl,
                "metrics": metrics,
            }
            return loss, aux

        (policy_loss_expl, expl_aux), expl_grads = jax.value_and_grad(expl_actor_loss_fn, has_aux=True)(
            params["actor_exploration"]
        )
        ae_updates, new_ae_opt = actor_opt.update(
            expl_grads, opt_states["actor_exploration"], params["actor_exploration"]
        )
        new_actor_expl = optax.apply_updates(params["actor_exploration"], ae_updates)

        new_critics_expl = {}
        new_critic_expl_opts = {}
        critic_metrics = {}
        for k in critic_cfgs:
            cur = params["critics_exploration"][k]
            loss_k, grads_k = jax.value_and_grad(_critic_loss)(
                cur["module"], cur["target"], expl_aux["traj"], expl_aux["lambda_values"][k], expl_aux["discount"]
            )
            upd_k, new_opt_k = critic_opt.update(grads_k, opt_states["critics_exploration"][k], cur["module"])
            new_module = optax.apply_updates(cur["module"], upd_k)
            new_target = jax.lax.cond(
                update_target,
                lambda nm=new_module, tg=cur["target"]: jax.tree.map(
                    lambda tp, cp: (1 - tau) * tp + tau * cp, tg, nm
                ),
                lambda tg=cur["target"]: tg,
            )
            new_critics_expl[k] = {"module": new_module, "target": new_target}
            new_critic_expl_opts[k] = new_opt_k
            critic_metrics[f"Loss/value_loss_exploration_{k}"] = loss_k

        # ---------------------------------------------------- 4. task behaviour
        def task_actor_loss_fn(actor_params):
            traj, imagined_actions = _imagine(actor_params, new_wm_params, prior0, rec0, latent0, k_img_t, k_a0_t)
            values = TwoHotEncodingDistribution(critic.apply(params["critic_task"], traj), dims=1).mean
            rewards_img = TwoHotEncodingDistribution(
                world_model.apply(new_wm_params, traj, method=WorldModel.reward), dims=1
            ).mean
            continues = BernoulliSafeMode(
                world_model.apply(new_wm_params, traj, method=WorldModel.continues)
            ).mode
            continues = jnp.concatenate([true_continue0[None], continues[1:]], 0)
            discount = sg(jnp.cumprod(continues * gamma, 0) / gamma)

            lambda_values = _lambda_values(rewards_img, values, continues)
            offset, invscale, new_m = _moments(moments_state["task"], lambda_values)
            advantage = ((lambda_values - offset) / invscale) - ((values[:-1] - offset) / invscale)
            loss = _policy_loss(actor_params, traj, imagined_actions, advantage, discount)
            aux = {
                "traj": sg(traj),
                "discount": discount,
                "lambda_values": sg(lambda_values),
                "moments": new_m,
            }
            return loss, aux

        (policy_loss_task, task_aux), task_grads = jax.value_and_grad(task_actor_loss_fn, has_aux=True)(
            params["actor_task"]
        )
        at_updates, new_at_opt = actor_opt.update(task_grads, opt_states["actor_task"], params["actor_task"])
        new_actor_task = optax.apply_updates(params["actor_task"], at_updates)

        value_loss_task, ct_grads = jax.value_and_grad(_critic_loss)(
            params["critic_task"],
            params["target_critic_task"],
            task_aux["traj"],
            task_aux["lambda_values"],
            task_aux["discount"],
        )
        ct_updates, new_ct_opt = critic_opt.update(ct_grads, opt_states["critic_task"], params["critic_task"])
        new_critic_task = optax.apply_updates(params["critic_task"], ct_updates)
        new_target_task = jax.lax.cond(
            update_target,
            lambda: jax.tree.map(
                lambda tp, cp: (1 - tau) * tp + tau * cp, params["target_critic_task"], new_critic_task
            ),
            lambda: params["target_critic_task"],
        )

        new_params = {
            "world_model": new_wm_params,
            "actor_task": new_actor_task,
            "critic_task": new_critic_task,
            "target_critic_task": new_target_task,
            "actor_exploration": new_actor_expl,
            "critics_exploration": new_critics_expl,
            "ensembles": new_ens_params,
        }
        new_opt_states = {
            "world_model": new_wm_opt,
            "actor_task": new_at_opt,
            "critic_task": new_ct_opt,
            "actor_exploration": new_ae_opt,
            "critics_exploration": new_critic_expl_opts,
            "ensembles": new_ens_opt,
        }
        new_moments = {"task": task_aux["moments"], "expl": expl_aux["moments"]}
        metrics = dict(wm_metrics)
        metrics.update(expl_aux["metrics"])
        metrics.update(critic_metrics)
        metrics["Loss/ensemble_loss"] = ens_loss_val
        metrics["Loss/policy_loss_exploration"] = policy_loss_expl
        metrics["Loss/policy_loss_task"] = policy_loss_task
        metrics["Loss/value_loss_task"] = value_loss_task
        if health_enabled(cfg):  # trace-time constant (obs/health.py)
            metrics.update(
                diagnostics(
                    grads={
                        "world_model": wm_grads,
                        "ensembles": ens_grads,
                        "actor_exploration": expl_grads,
                        "actor_task": task_grads,
                        "critic_task": ct_grads,
                    },
                    params=new_params,
                    updates={
                        "world_model": wm_updates,
                        "ensembles": ens_updates,
                        "actor_exploration": ae_updates,
                        "actor_task": at_updates,
                        "critic_task": ct_updates,
                    },
                )
            )
        metrics = maybe_inject_nonfinite(cfg, metrics)
        if strict_enabled(cfg):  # trace-time constant: callback exists only in strict runs
            nan_scan(metrics, "p2e_dv3/train_step")
        return new_params, new_opt_states, new_moments, metrics

    return train_step, init_opt_states, init_moments_state


@register_algorithm(name="p2e_dv3_exploration")
def main(ctx, cfg) -> None:
    rank = ctx.process_index
    log_dir = get_log_dir(cfg)
    if ctx.is_global_zero:
        save_config(cfg, Path(log_dir) / "config.yaml")
    logger = get_logger(cfg, log_dir)
    monitor = TrainingMonitor(cfg, log_dir)

    envs = make_vector_env(cfg, cfg.seed, rank, log_dir if cfg.env.capture_video else None)
    obs_space = envs.single_observation_space
    act_space = envs.single_action_space
    is_continuous, actions_dim = parse_actions_dim(act_space)
    act_dim_sum = int(sum(actions_dim))
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    num_envs = cfg.env.num_envs
    world = jax.process_count()

    critic_cfgs = {
        k: {"weight": v["weight"], "reward_type": v["reward_type"]}
        for k, v in cfg.algo.critics_exploration.items()
        if v["weight"] > 0
    }
    world_model, actor, critic, ensemble_mlp, params, _ = build_agent(
        ctx, actions_dim, is_continuous, cfg, obs_space
    )
    train_step, init_opt_states, init_moments_state = make_train_step(
        world_model, actor, critic, ensemble_mlp, cfg, cnn_keys, mlp_keys, critic_cfgs
    )
    opt_states = ctx.shard_params(init_opt_states(params))
    moments_state = ctx.replicate(init_moments_state())
    # One jitted scan per iteration's gradient block (utils/blocks.py); the EMA
    # target cadence tests the count BEFORE the increment, as the eager loop did.
    def _block_step(carry, batch, key, update_target):
        params, opt_states, moments = carry
        params, opt_states, moments, metrics = train_step(
            params, opt_states, moments, batch, key, update_target
        )
        return (params, opt_states, moments), metrics

    player_step = make_player_step(world_model, actor, actions_dim, cfg.algo.world_model.discrete_size)
    player_jit = jax.jit(player_step, static_argnames=("greedy",))
    actor_type = cfg.algo.player.get("actor_type", "exploration")
    player_actor_key = "actor_exploration" if actor_type == "exploration" else "actor_task"
    stoch_size = cfg.algo.world_model.stochastic_size * cfg.algo.world_model.discrete_size
    rec_size = cfg.algo.world_model.recurrent_model.recurrent_state_size

    def player_params():
        return {"world_model": params["world_model"], "actor": params[player_actor_key]}

    def player_state_init(n: int) -> PlayerState:
        return PlayerState(
            recurrent_state=jnp.zeros((n, rec_size)),
            stochastic_state=jnp.zeros((n, stoch_size)),
            actions=jnp.zeros((n, act_dim_sum)),
        )

    buffer_size = max(int(cfg.buffer.size) // max(num_envs * world, 1), 1)
    rb = EnvIndependentReplayBuffer(
        buffer_size,
        n_envs=num_envs,
        obs_keys=obs_keys,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}") if cfg.buffer.memmap else None,
        buffer_cls=SequentialReplayBuffer,
    )
    rb.seed(cfg.seed + rank)

    # Device-vs-host replay data path, one shared implementation
    # (data/device_buffer.py): P2E-DV3 is pixels-first, so the HBM mirror removes
    # exactly the per-block batch transfer that otherwise floors its throughput.
    dispatcher, mirror, prefetcher, _run_block, rb_add = make_device_replay(
        ctx,
        cfg,
        rb,
        cnn_keys,
        mlp_keys,
        obs_space,
        act_dim_sum,
        _block_step,
        dispatcher_kwargs=dict(
            target_update_freq=cfg.algo.critic.per_rank_target_network_update_freq, count_offset=0
        ),
    )

    aggregator = MetricAggregator(cfg.metric.aggregator.get("metrics", {}))
    aggregator.keep(AGGREGATOR_KEYS | set(cfg.metric.aggregator.get("metrics", {})))
    ckpt_manager = CheckpointManager(Path(log_dir) / "checkpoints", keep_last=cfg.checkpoint.keep_last)
    guard = TrainingGuard(cfg, log_dir)
    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)

    batch_size = cfg.algo.per_rank_batch_size
    seq_len = cfg.algo.per_rank_sequence_length
    policy_steps_per_iter = num_envs * world * cfg.env.action_repeat
    total_steps = int(cfg.algo.total_steps)
    num_iters = max(total_steps // policy_steps_per_iter, 1) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    target_update_freq = cfg.algo.critic.per_rank_target_network_update_freq

    start_iter = 1
    policy_step = 0
    last_log = 0
    last_checkpoint = 0
    cumulative_grad_steps = 0
    if cfg.checkpoint.get("resume_from"):
        state = CheckpointManager.load(
            cfg.checkpoint.resume_from,
            templates={
                "params": jax.device_get(params),
                "opt_states": jax.device_get(opt_states),
                "moments": jax.device_get(moments_state),
            },
        )
        params = ctx.shard_params(state["params"])
        opt_states = ctx.shard_params(state["opt_states"])
        moments_state = ctx.replicate(state["moments"])
        ratio.load_state_dict(state["ratio"])
        start_iter = state["iter_num"] + 1
        policy_step = state["policy_step"]
        last_log = state.get("last_log", 0)
        last_checkpoint = state.get("last_checkpoint", 0)
        cumulative_grad_steps = state.get("cumulative_grad_steps", 0)
        learning_starts += start_iter
        if cfg.buffer.checkpoint and "rb" in state:
            rb.load_state_dict(state["rb"])
            if mirror is not None:
                mirror.load_from(rb)

    def _obs_row(o, idxs=None):
        row = {}
        for k in cnn_keys:
            v = np.asarray(o[k]) if idxs is None else np.asarray(o[k])[idxs]
            row[k] = v.reshape(1, v.shape[0], -1, *v.shape[-2:])
        for k in mlp_keys:
            v = np.asarray(o[k], dtype=np.float32) if idxs is None else np.asarray(o[k], dtype=np.float32)[idxs]
            row[k] = v.reshape(1, v.shape[0], -1)
        return row

    obs, _ = envs.reset(seed=cfg.seed + rank)
    player_state = player_state_init(num_envs)
    step_data: Dict[str, np.ndarray] = _obs_row(obs)
    step_data["rewards"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["terminated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["truncated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["is_first"] = np.ones((1, num_envs, 1), np.float32)
    is_first_np = np.ones((num_envs, 1), dtype=np.float32)
    prefill_iters = max(learning_starts - 1, 0)

    for iter_num in range(start_iter, num_iters + 1):
        monitor.advance()
        env_t0 = time.perf_counter()
        with timer("Time/env_interaction_time"):
            if iter_num <= learning_starts and not cfg.checkpoint.get("resume_from"):
                if is_continuous:
                    stored_actions = np.stack([act_space.sample() for _ in range(num_envs)]).astype(np.float32)
                    env_actions = stored_actions
                else:
                    sampled = np.stack([act_space.sample() for _ in range(num_envs)]).reshape(num_envs, -1)
                    onehots = []
                    for i, d in enumerate(actions_dim):
                        oh = np.zeros((num_envs, d), dtype=np.float32)
                        oh[np.arange(num_envs), sampled[:, i]] = 1.0
                        onehots.append(oh)
                    stored_actions = np.concatenate(onehots, -1)
                    env_actions = sampled.squeeze(-1) if len(actions_dim) == 1 else sampled
                player_state = player_state._replace(actions=jnp.asarray(stored_actions))
            else:
                obs_t = prepare_obs(obs, cnn_keys, mlp_keys, num_envs)
                actions, stored, player_state = player_jit(
                    player_params(), player_state, obs_t, jnp.asarray(is_first_np), ctx.local_rng()
                )
                # ONE device_get for everything the host needs (per-array fetches
                # would each pay a transfer round trip on a remote accelerator).
                stored_np, acts_list = jax.device_get((stored, list(actions)))
                stored_actions = np.asarray(stored_np)
                acts_np = [np.asarray(a) for a in acts_list]
                if is_continuous:
                    env_actions = acts_np[0]
                elif len(actions_dim) == 1:
                    env_actions = acts_np[0].argmax(-1)
                else:
                    env_actions = np.stack([a.argmax(-1) for a in acts_np], -1)

            step_data["actions"] = stored_actions.reshape(1, num_envs, -1)
            rb_add(step_data, validate_args=cfg.buffer.validate_args)
        env_time = time.perf_counter() - env_t0

        # Dispatch this iteration's gradient block BEFORE stepping the envs: the
        # device trains while the host walks the environments below (acting above
        # used the previous iteration's params, exactly as the eager ordering did).
        grad_steps = 0
        if iter_num >= learning_starts:
            grad_steps = ratio(
                (policy_step + policy_steps_per_iter - prefill_iters * policy_steps_per_iter) / world
            )
            if grad_steps > 0:
                params, opt_states, moments_state = _run_block(
                    (params, opt_states, moments_state),
                    grad_steps,
                    cumulative_grad_steps,
                    stage_next=iter_num < num_iters,
                )
                cumulative_grad_steps += grad_steps

        env_t0 = time.perf_counter()
        with timer("Time/env_interaction_time"):
            next_obs, reward, terminated, truncated, info = envs.step(env_actions)
            if cfg.env.clip_rewards:
                reward = np.clip(reward, -1, 1)
            done = np.logical_or(terminated, truncated)
            reward = np.asarray(reward, dtype=np.float32).reshape(num_envs, 1)

            real_next_obs = {k: np.asarray(next_obs[k]).copy() for k in obs_keys}
            if done.any() and "final_obs" in info:
                for i in np.nonzero(done)[0]:
                    if info["final_obs"][i] is not None:
                        for k in obs_keys:
                            real_next_obs[k][i] = np.asarray(info["final_obs"][i][k])

            step_data = _obs_row(next_obs)
            step_data["rewards"] = reward.reshape(1, num_envs, 1).copy()
            step_data["terminated"] = terminated.astype(np.float32).reshape(1, num_envs, 1)
            step_data["truncated"] = truncated.astype(np.float32).reshape(1, num_envs, 1)
            step_data["is_first"] = np.zeros((1, num_envs, 1), np.float32)

            done_idxs = np.nonzero(done)[0].tolist()
            if done_idxs:
                reset_data = _obs_row(real_next_obs, idxs=done_idxs)
                reset_data["rewards"] = step_data["rewards"][:, done_idxs]
                reset_data["terminated"] = step_data["terminated"][:, done_idxs]
                reset_data["truncated"] = step_data["truncated"][:, done_idxs]
                reset_data["actions"] = np.zeros((1, len(done_idxs), act_dim_sum), np.float32)
                reset_data["is_first"] = np.zeros_like(reset_data["terminated"])
                rb_add(reset_data, done_idxs, validate_args=cfg.buffer.validate_args)
                step_data["rewards"][:, done_idxs] = 0.0
                step_data["terminated"][:, done_idxs] = 0.0
                step_data["truncated"][:, done_idxs] = 0.0
                step_data["is_first"][:, done_idxs] = 1.0

            is_first_np = done.astype(np.float32).reshape(num_envs, 1)
            obs = next_obs
            policy_step += policy_steps_per_iter
            record_episode_stats(aggregator, info)
        env_time += time.perf_counter() - env_t0

        if logger is not None and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == num_iters or cfg.dry_run
        ):
            dispatcher.drain(aggregator)  # the window's only blocking device sync
            metrics = aggregator.compute()
            metrics.update(replay_age_metrics(rb))
            window_sps = dispatcher.pop_window_sps()
            if window_sps is not None:
                metrics["Time/sps_train"] = window_sps
            metrics["Time/sps_env_interaction"] = (
                policy_steps_per_iter / world / env_time if env_time > 0 else 0.0
            )
            metrics["Params/replay_ratio"] = (
                cumulative_grad_steps * world / policy_step if policy_step > 0 else 0.0
            )
            monitor.log_metrics(logger, metrics, policy_step)
            aggregator.reset()
            last_log = policy_step

        def save_ckpt():
            nonlocal last_checkpoint
            state = {
                "params": params,
                "opt_states": opt_states,
                "moments": moments_state,
                "ratio": ratio.state_dict(),
                "iter_num": iter_num,
                "policy_step": policy_step,
                "last_log": last_log,
                "last_checkpoint": policy_step,
                "cumulative_grad_steps": cumulative_grad_steps,
            }
            if cfg.buffer.checkpoint:
                state["rb"] = rb.state_dict()
            path = ckpt_manager.save(policy_step, state)
            last_checkpoint = policy_step
            return path

        if (
            cfg.checkpoint.every > 0
            and (policy_step - last_checkpoint) >= cfg.checkpoint.every
            or iter_num == num_iters
            and cfg.checkpoint.save_last
        ):
            save_ckpt()
        guard.boundary(policy_step, save_ckpt)

    monitor.close()
    envs.close()
    if prefetcher is not None:
        prefetcher.close()
    if cfg.algo.run_test and ctx.is_global_zero:
        reward = test(player_step, player_params(), player_state_init, ctx, cfg, log_dir)
        if logger is not None:
            logger.log_metrics({"Test/cumulative_reward": reward}, policy_step)
    if logger is not None:
        logger.close()


def lower_for_audit():
    """IR-audit hook (``python -m sheeprl_tpu.analysis.ir``): the P2E-DV3
    exploration gradient block (DV3 world model + task head + per-critic
    exploration heads/moments + intrinsic ensembles) at tiny MLP-only shapes."""
    from sheeprl_tpu.analysis.ir.synth import (
        DREAMER_DISCRETE_OVERRIDES,
        DREAMER_TINY_OVERRIDES,
        compose_tiny,
        sequence_batch,
        tiny_ctx,
        vector_space,
    )
    from sheeprl_tpu.analysis.ir.types import AuditEntry
    from sheeprl_tpu.utils.blocks import make_train_block

    cfg = compose_tiny(
        [
            "exp=p2e_dv3_dummy",
            "env=discrete_dummy",
            *DREAMER_TINY_OVERRIDES,
            *DREAMER_DISCRETE_OVERRIDES,
            "algo.ensembles.n=2",
            "algo.ensembles.dense_units=8",
            "algo.ensembles.mlp_layers=1",
        ]
    )
    ctx = tiny_ctx(cfg)
    obs_space = vector_space()
    actions_dim, is_continuous = (3,), False
    critic_cfgs = {
        k: {"weight": v["weight"], "reward_type": v["reward_type"]}
        for k, v in cfg.algo.critics_exploration.items()
        if v["weight"] > 0
    }
    world_model, actor, critic, ensemble_mlp, params, _ = build_agent(
        ctx, actions_dim, is_continuous, cfg, obs_space
    )
    train_step, init_opt_states, init_moments_state = make_train_step(
        world_model, actor, critic, ensemble_mlp, cfg, [], ["state"], critic_cfgs
    )
    carry = (params, init_opt_states(params), init_moments_state())

    def _block_step(carry, batch, key, update_target):
        params, opt_states, moments = carry
        params, opt_states, moments, metrics = train_step(
            params, opt_states, moments, batch, key, update_target
        )
        return (params, opt_states, moments), metrics

    block = make_train_block(_block_step, cfg.algo.critic.per_rank_target_network_update_freq, 0)
    batch = sequence_batch(
        {"state": obs_space["state"].shape},
        act_dim=int(sum(actions_dim)),
        T=int(cfg.algo.per_rank_sequence_length),
        B=int(cfg.algo.per_rank_batch_size),
    )
    return [
        AuditEntry(
            name="p2e_dv3/train_block",
            fn=block,
            args=(carry, (batch,), jax.random.PRNGKey(0), 0),
            covers=("p2e_dv3_exploration",),
            precision=str(cfg.mesh.precision),
        )
    ]
