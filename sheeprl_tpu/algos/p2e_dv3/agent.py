"""P2E-DV3 agent builder (reference: ``/root/reference/sheeprl/algos/p2e_dv3/agent.py``).

Extends the DreamerV3 agent with:

* an **exploration actor** (same ``DreamerActor`` class as the task actor);
* a dict of **exploration critics** — each entry carries a weight and a reward type
  (``intrinsic`` = ensemble disagreement, ``task`` = learned reward model), with its own
  EMA target critic (reference ``agent.py:118-156``);
* a **disagreement ensemble** predicting the next stochastic state from
  ``(latent, action)`` — vmapped stacked params, see ``sheeprl_tpu/algos/p2e``.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import gymnasium
import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v3.agent import (
    DreamerActor,
    DreamerCritic,
    PlayerState,  # noqa: F401
    apply_hafner_init,
    build_agent as dv3_build_agent,
    make_player_step,  # noqa: F401
    parse_actions_dim,  # noqa: F401
    zero_init_head,
)
from sheeprl_tpu.algos.p2e import build_ensembles


def build_agent(
    ctx,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
):
    """Returns ``(world_model, actor, critic, ensemble_mlp, params, latent_size)`` where
    ``actor``/``critic`` are the module definitions shared by the task and exploration
    heads (pure-functional params make sharing a module across heads free)."""
    world_model, actor, critic, dv3_params, latent_size = dv3_build_agent(
        ctx, actions_dim, is_continuous, cfg, obs_space
    )

    actor_expl_params = actor.init(ctx.rng(), jnp.zeros((1, latent_size)), ctx.rng())
    if cfg.algo.hafner_initialization:
        actor_expl_params = {"params": apply_hafner_init(actor_expl_params["params"], ctx.rng())}

    critics_exploration: Dict[str, Dict[str, Any]] = {}
    intrinsic_critics = 0
    for k, v in cfg.algo.critics_exploration.items():
        if v["weight"] > 0:
            if v["reward_type"] == "intrinsic":
                intrinsic_critics += 1
            cp = critic.init(ctx.rng(), jnp.zeros((1, latent_size)))
            if cfg.algo.hafner_initialization:
                cp = {"params": zero_init_head(cp["params"], "head")}
            critics_exploration[k] = {
                "module": ctx.replicate(cp),
                "target": ctx.replicate(jax.tree.map(lambda x: x, cp)),
            }
    if intrinsic_critics == 0:
        raise RuntimeError("You must specify at least one intrinsic critic (`reward_type='intrinsic'`)")

    wm_cfg = cfg.algo.world_model
    stoch_size = wm_cfg.stochastic_size * wm_cfg.discrete_size
    ens_cfg = cfg.algo.ensembles
    ensemble_mlp, ensemble_params = build_ensembles(
        ctx.rng(),
        n=ens_cfg.n,
        input_dim=int(sum(actions_dim)) + wm_cfg.recurrent_model.recurrent_state_size + stoch_size,
        output_dim=stoch_size,
        dense_units=ens_cfg.dense_units,
        mlp_layers=ens_cfg.mlp_layers,
        activation="silu",
        layer_norm=True,
        dtype=ctx.compute_dtype,
    )

    params = {
        "world_model": dv3_params["world_model"],
        "actor_task": dv3_params["actor"],
        "critic_task": dv3_params["critic"],
        "target_critic_task": dv3_params["target_critic"],
        "actor_exploration": ctx.replicate(actor_expl_params),
        "critics_exploration": critics_exploration,
        "ensembles": ctx.replicate(ensemble_params),
    }
    return world_model, actor, critic, ensemble_mlp, params, latent_size
