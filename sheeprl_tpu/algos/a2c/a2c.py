"""A2C (reference: ``/root/reference/sheeprl/algos/a2c/a2c.py``).

Shares the PPO agent and rollout machinery.  The reference accumulates gradients across
minibatches and steps once per rollout (``a2c.py:63-110``) — on TPU that's simply ONE
jitted full-batch gradient step with the configured ``loss_reduction``."""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.analysis.strict import assert_finite, maybe_inject_nonfinite, strict_guard
from sheeprl_tpu.algos.ppo.agent import build_agent
from sheeprl_tpu.algos.ppo.loss import entropy_loss, value_loss
from sheeprl_tpu.algos.ppo.ppo import make_optimizer
from sheeprl_tpu.algos.ppo.utils import log_prob_and_entropy, prepare_obs, sample_actions, test
from sheeprl_tpu.checkpoint.manager import CheckpointManager
from sheeprl_tpu.fault.guard import TrainingGuard
from sheeprl_tpu.config.core import save_config
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.obs import perf as obs_perf
from sheeprl_tpu.obs import TrainingMonitor, flight_recorder
from sheeprl_tpu.obs.health import diagnostics, health_enabled
from sheeprl_tpu.utils.env import make_vector_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, record_episode_stats
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import gae, normalize_tensor

AGGREGATOR_KEYS = {"Rewards/rew_avg", "Game/ep_len_avg", "Loss/value_loss", "Loss/policy_loss"}


def make_a2c_train_fn(ctx, agent, cfg, obs_keys):
    """Optimizer + the jitted full-batch A2C update.

    Module-level (rather than a closure in ``main``) so the flight recorder's
    :func:`replay_update` can rebuild the exact update from a blackbox dump."""
    opt = make_optimizer(cfg.algo.optimizer, cfg.algo.max_grad_norm)
    reduction = cfg.algo.loss_reduction
    is_continuous = agent.is_continuous
    health = health_enabled(cfg)  # trace-time constant (obs/health.py)

    def loss_fn(p, data):
        actor_out, new_values = agent.apply(p, {k: data[k] for k in obs_keys})
        logprob, entropy = log_prob_and_entropy(actor_out, data["actions"], is_continuous)
        adv = data["advantages"]
        if cfg.algo.normalize_advantages:
            adv = normalize_tensor(adv)
        obj = logprob * adv
        pg = -(obj.mean() if reduction == "mean" else obj.sum())
        vf = value_loss(new_values[..., 0], data["values"], data["returns"], 0.0, False, reduction)
        ent = entropy_loss(entropy, reduction)
        total = pg + cfg.algo.vf_coef * vf + cfg.algo.ent_coef * ent
        aux = {"Loss/policy_loss": pg, "Loss/value_loss": vf}
        if health:
            aux["Health/policy_entropy"] = entropy.mean()
            aux["Health/value_mean"] = new_values.mean()
        return total, aux

    @jax.jit
    def train_fn(p, o_state, data):
        (_, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(p, data)
        updates, o_state = opt.update(grads, o_state, p)
        p = optax.apply_updates(p, updates)
        if health:
            aux = {**aux, **diagnostics(grads=grads, params=p, updates=updates)}
        aux = maybe_inject_nonfinite(cfg, aux)
        return p, o_state, aux

    return opt, train_fn


@register_algorithm(name="a2c")
def main(ctx, cfg) -> None:
    rank = ctx.process_index
    log_dir = get_log_dir(cfg)
    if ctx.is_global_zero:
        save_config(cfg, Path(log_dir) / "config.yaml")
    logger = get_logger(cfg, log_dir)
    monitor = TrainingMonitor(cfg, log_dir)

    envs = make_vector_env(cfg, cfg.seed, rank, log_dir if cfg.env.capture_video else None)
    obs_space = envs.single_observation_space
    act_space = envs.single_action_space
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys

    agent, params = build_agent(ctx, act_space, obs_space, cfg)
    is_continuous = agent.is_continuous
    opt, train_fn = make_a2c_train_fn(ctx, agent, cfg, obs_keys)
    opt_state = ctx.replicate(opt.init(params))

    num_envs = cfg.env.num_envs
    rollout_steps = cfg.algo.rollout_steps
    world = jax.process_count()
    policy_steps_per_iter = int(num_envs * rollout_steps * world)
    num_updates = max(int(cfg.algo.total_steps) // policy_steps_per_iter, 1) if not cfg.dry_run else 1

    rb = ReplayBuffer(
        rollout_steps,
        num_envs,
        obs_keys=obs_keys,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}") if cfg.buffer.memmap else None,
    )
    rb.seed(cfg.seed + rank)
    aggregator = MetricAggregator(cfg.metric.aggregator.get("metrics", {}))
    aggregator.keep(AGGREGATOR_KEYS | set(cfg.metric.aggregator.get("metrics", {})))
    ckpt_manager = CheckpointManager(Path(log_dir) / "checkpoints", keep_last=cfg.checkpoint.keep_last)
    guard = TrainingGuard(cfg, log_dir)

    gamma, gae_lambda = cfg.algo.gamma, cfg.algo.gae_lambda

    @jax.jit
    def act_fn(p, obs, key):
        actor_out, value = agent.apply(p, obs)
        env_act, stored_act, logprob = sample_actions(key, actor_out, is_continuous)
        return env_act, stored_act, logprob, value[..., 0]

    @jax.jit
    def values_fn(p, obs):
        return agent.apply(p, obs)[1][..., 0]

    gae_fn = jax.jit(lambda r, v, d, nv: gae(r, v, d, nv, rollout_steps, gamma, gae_lambda))

    # analysis.strict: signature guard on the jitted update (drift -> hard error)
    train_fn = obs_perf.instrument(cfg, "a2c/train_fn", strict_guard(cfg, "a2c/train_fn", train_fn))

    # Flight recorder: arm the replay builder with everything needed to rebuild
    # this update from the dump alone.
    recorder = flight_recorder.get_active()
    if recorder is not None:
        recorder.arm_replay(
            "sheeprl_tpu.algos.a2c.a2c:replay_update",
            act_space=act_space,
            obs_space=obs_space,
        )

    start_update, policy_step, last_log, last_checkpoint = 1, 0, 0, 0
    if cfg.checkpoint.get("resume_from"):
        state = CheckpointManager.load(
            cfg.checkpoint.resume_from, templates={"params": jax.device_get(params), "opt_state": jax.device_get(opt_state)}
        )
        params = ctx.replicate(state["params"])
        opt_state = ctx.replicate(state["opt_state"])
        start_update = state["update"] + 1
        policy_step = state["policy_step"]
        last_log = state.get("last_log", 0)
        last_checkpoint = state.get("last_checkpoint", 0)

    obs, _ = envs.reset(seed=cfg.seed + rank)
    step_data: Dict[str, np.ndarray] = {}

    for update in range(start_update, num_updates + 1):
        monitor.advance()
        env_t0 = time.perf_counter()
        with timer("Time/env_interaction_time"):
            for _ in range(rollout_steps):
                with monitor.phase("player"):
                    obs_t = prepare_obs(obs, cnn_keys, mlp_keys)
                    env_act, _, logprob, value = act_fn(params, obs_t, ctx.local_rng())
                env_act_np = np.asarray(jax.device_get(env_act))
                if is_continuous:
                    low, high = act_space.low, act_space.high
                    env_actions = np.clip(env_act_np, low, high) if np.isfinite(low).all() else env_act_np
                elif len(agent.action_dims) == 1:
                    env_actions = env_act_np[..., 0]
                else:
                    env_actions = env_act_np
                with monitor.phase("env_step"):
                    next_obs, reward, terminated, truncated, info = envs.step(env_actions)
                done = np.logical_or(terminated, truncated)
                reward = np.asarray(reward, dtype=np.float32).reshape(num_envs)
                if truncated.any() and "final_obs" in info:
                    trunc_idx = np.nonzero(truncated)[0]
                    final_obs = {
                        k: np.stack([np.asarray(info["final_obs"][i][k]) for i in trunc_idx]) for k in obs_keys
                    }
                    v_final = np.asarray(jax.device_get(values_fn(params, prepare_obs(final_obs, cnn_keys, mlp_keys))))
                    reward[trunc_idx] += gamma * v_final
                for k in obs_keys:
                    step_data[k] = np.asarray(obs[k])[None]
                step_data["actions"] = env_act_np.reshape(num_envs, -1).astype(np.float32)[None]
                step_data["values"] = np.asarray(jax.device_get(value)).reshape(num_envs, 1)[None]
                step_data["rewards"] = reward.reshape(num_envs, 1)[None]
                step_data["dones"] = done.astype(np.float32).reshape(num_envs, 1)[None]
                with monitor.phase("buffer_add"):
                    rb.add(step_data, validate_args=cfg.buffer.validate_args)
                obs = next_obs
                policy_step += num_envs * world
                record_episode_stats(aggregator, info)
        env_time = time.perf_counter() - env_t0

        local = rb.to_tensor()
        next_value = values_fn(params, prepare_obs(obs, cnn_keys, mlp_keys))[:, None]
        returns, advantages = gae_fn(local["rewards"], local["values"], local["dones"], next_value)
        batch_n = rollout_steps * num_envs
        data = {
            **{k: local[k] for k in obs_keys},
            "actions": local["actions"],
            "values": local["values"][..., 0],
            "returns": returns[..., 0],
            "advantages": advantages[..., 0],
        }
        data = jax.tree.map(lambda x: x.reshape(batch_n, *x.shape[2:]), data)
        data = ctx.put_batch(data, batch_axis=0)

        if recorder is not None:  # device-array references only: no host sync
            recorder.stage_step(
                batch=data,
                carry={"params": params, "opt_state": opt_state},
                scalars={"update": update},
            )
        with timer("Time/train_time"), monitor.phase("dispatch"):
            t0 = time.perf_counter()
            params, opt_state, train_metrics = train_fn(params, opt_state, data)
            train_metrics = jax.device_get(train_metrics)
            train_time = time.perf_counter() - t0
        assert_finite(cfg, train_metrics, "a2c/update")
        for k, v in train_metrics.items():
            aggregator.update(k, float(v))

        if logger is not None and (policy_step - last_log >= cfg.metric.log_every or update == num_updates or cfg.dry_run):
            metrics = aggregator.compute()
            metrics["Time/sps_train"] = 1.0 / train_time if train_time > 0 else 0.0
            metrics["Time/sps_env_interaction"] = policy_steps_per_iter / world / env_time if env_time > 0 else 0.0
            monitor.log_metrics(logger, metrics, policy_step)
            aggregator.reset()
            last_log = policy_step

        def save_ckpt():
            nonlocal last_checkpoint
            with monitor.phase("checkpoint"):
                path = ckpt_manager.save(
                    policy_step,
                    {
                        "params": params,
                        "opt_state": opt_state,
                        "update": update,
                        "policy_step": policy_step,
                        "last_log": last_log,
                        "last_checkpoint": policy_step,
                    },
                )
            last_checkpoint = policy_step
            return path

        if (
            cfg.checkpoint.every > 0
            and (policy_step - last_checkpoint) >= cfg.checkpoint.every
            or update == num_updates
            and cfg.checkpoint.save_last
        ):
            save_ckpt()
        guard.boundary(policy_step, save_ckpt)

    monitor.close()
    envs.close()
    if cfg.algo.run_test and ctx.is_global_zero:
        reward = test(agent, params, ctx, cfg, log_dir)
        if logger is not None:
            logger.log_metrics({"Test/cumulative_reward": reward}, policy_step)
    if logger is not None:
        logger.close()


def replay_update(cfg, dump_dir):
    """Flight-recorder replay builder: re-execute the dumped A2C update on CPU."""
    from sheeprl_tpu.obs import replay_blackbox
    from sheeprl_tpu.parallel.mesh import make_mesh_context

    ctx = make_mesh_context(cfg)
    raw = replay_blackbox.load_state(dump_dir)
    statics = raw["statics"]
    obs_keys = list(cfg.algo.cnn_keys.encoder) + list(cfg.algo.mlp_keys.encoder)
    agent, params0 = build_agent(ctx, statics["act_space"], statics["obs_space"], cfg)
    opt, train_fn = make_a2c_train_fn(ctx, agent, cfg, obs_keys)
    templates = {"carry": jax.device_get({"params": params0, "opt_state": opt.init(params0)})}
    state = replay_blackbox.load_state(dump_dir, templates)
    new_params, _, metrics = train_fn(
        ctx.replicate(state["carry"]["params"]),
        ctx.replicate(state["carry"]["opt_state"]),
        state["batch"],
    )
    return {
        "metrics": jax.device_get(metrics),
        "new_param_norm": float(jax.device_get(optax.global_norm(new_params))),
    }


def lower_for_audit():
    """IR-audit hook (``python -m sheeprl_tpu.analysis.ir``): the jitted full-batch
    A2C update at tiny synthetic shapes, through ``make_a2c_train_fn``."""
    from sheeprl_tpu.analysis.ir.synth import (
        compose_tiny,
        discrete_act_space,
        tiny_ctx,
        vector_space,
        zeros,
    )
    from sheeprl_tpu.analysis.ir.types import AuditEntry

    cfg = compose_tiny(
        [
            "exp=a2c",
            "env=discrete_dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.rollout_steps=4",
            "algo.per_rank_batch_size=4",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.encoder.mlp_features_dim=8",
            "env.num_envs=2",
        ]
    )
    ctx = tiny_ctx(cfg)
    agent, params = build_agent(ctx, discrete_act_space(), vector_space(), cfg)
    opt, train_fn = make_a2c_train_fn(ctx, agent, cfg, ["state"])
    opt_state = opt.init(params)
    n = int(cfg.algo.rollout_steps * cfg.env.num_envs)
    data = {
        "state": zeros((n, 5)),
        "actions": zeros((n, 1)),
        "values": zeros((n,)),
        "returns": zeros((n,)),
        "advantages": zeros((n,)),
    }
    return [
        AuditEntry(
            name="a2c/train_fn",
            fn=train_fn,
            args=(params, opt_state, data),
            covers=("a2c",),
            precision=str(cfg.mesh.precision),
        )
    ]
