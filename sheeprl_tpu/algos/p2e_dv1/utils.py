"""P2E-DV1 helpers (reference: ``/root/reference/sheeprl/algos/p2e_dv1/utils.py``)."""

from __future__ import annotations

from sheeprl_tpu.algos.dreamer_v1.utils import compute_lambda_values, prepare_obs, test  # noqa: F401

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "Loss/ensemble_loss",
    "Loss/policy_loss_task",
    "Loss/value_loss_task",
    "Loss/policy_loss_exploration",
    "Loss/value_loss_exploration",
    "State/kl",
    "State/post_entropy",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
    "State/prior_entropy",
    "Params/exploration_amount",
    "Rewards/intrinsic",
    "Values_exploration/predicted_values",
    "Values_exploration/lambda_values",
}
MODELS_TO_REGISTER = {
    "world_model",
    "ensembles",
    "actor_exploration",
    "critic_exploration",
    "actor_task",
    "critic_task",
}
