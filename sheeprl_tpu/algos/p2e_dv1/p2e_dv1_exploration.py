"""P2E-DV1 exploration (reference: ``/root/reference/sheeprl/algos/p2e_dv1/p2e_dv1_exploration.py``).

Plan2Explore on the DreamerV1 stack, one jitted train step with four phases:

1. DV1 world-model update (Normal-KL ELBO) with reward/continue heads on *detached*
   latents;
2. ensemble learning — next observation embedding under a unit-variance Gaussian
   (reference ``:168-184``);
3. exploration behaviour — DV1 dynamics-backprop actor on the intrinsic disagreement
   reward, Gaussian critic without a target (reference ``:186-263``);
4. task behaviour — the DV1 update on the learned reward model (reference ``:268-325``).
"""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.analysis.strict import maybe_inject_nonfinite, nan_scan, strict_enabled
from sheeprl_tpu.algos.dreamer_v1.agent import WorldModelV1
from sheeprl_tpu.algos.dreamer_v1.loss import reconstruction_loss
from sheeprl_tpu.algos.dreamer_v2.agent import exploration_amount
from sheeprl_tpu.algos.p2e import ensemble_loss_normal, intrinsic_reward
from sheeprl_tpu.algos.p2e_dv1.agent import (
    PlayerState,
    build_agent,
    make_player_step,
    parse_actions_dim,
)
from sheeprl_tpu.algos.p2e_dv1.utils import (
    AGGREGATOR_KEYS,
    compute_lambda_values,
    prepare_obs,
    test,
)
from sheeprl_tpu.algos.ppo.ppo import make_optimizer
from sheeprl_tpu.checkpoint.manager import CheckpointManager
from sheeprl_tpu.fault.guard import TrainingGuard
from sheeprl_tpu.config.core import save_config
from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, SequentialReplayBuffer
from sheeprl_tpu.data.device_buffer import make_device_replay
from sheeprl_tpu.distributions import BernoulliSafeMode, Independent, Normal
from sheeprl_tpu.obs import TrainingMonitor
from sheeprl_tpu.obs.health import diagnostics, health_enabled, replay_age_metrics
from sheeprl_tpu.utils.env import make_vector_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, record_episode_stats
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio


def make_train_step(world_model, actor, critic, ensemble_mlp, cfg, cnn_keys, mlp_keys):
    wm_cfg = cfg.algo.world_model
    stoch_size = wm_cfg.stochastic_size
    rec_size = wm_cfg.recurrent_model.recurrent_state_size
    horizon = cfg.algo.horizon
    gamma = cfg.algo.gamma
    lmbda = cfg.algo.lmbda
    use_continues = wm_cfg.use_continues
    intr_mult = cfg.algo.intrinsic_reward_multiplier

    wm_opt = make_optimizer(wm_cfg.optimizer, wm_cfg.clip_gradients)
    actor_opt = make_optimizer(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients)
    critic_opt = make_optimizer(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients)
    ens_opt = make_optimizer(cfg.algo.ensembles.optimizer, cfg.algo.ensembles.clip_gradients)

    def init_opt_states(params):
        return {
            "world_model": wm_opt.init(params["world_model"]),
            "actor_task": actor_opt.init(params["actor_task"]),
            "critic_task": critic_opt.init(params["critic_task"]),
            "actor_exploration": actor_opt.init(params["actor_exploration"]),
            "critic_exploration": critic_opt.init(params["critic_exploration"]),
            "ensembles": ens_opt.init(params["ensembles"]),
        }

    def _imagine(actor_params, wm_params, prior0, rec0, latent0, k_img):
        """DV1 rollout: H latents EXCLUDING the start, plus the action taken at each
        visited state (reference ``:198-204``)."""

        def img_step(carry, k):
            prior, rec, latent = carry
            k_act, k_dyn = jax.random.split(k)
            acts, _ = actor.apply(actor_params, jax.lax.stop_gradient(latent), k_act)
            action = jnp.concatenate(acts, -1)
            prior, rec = world_model.apply(wm_params, prior, rec, action, k_dyn, method=WorldModelV1.imagination)
            new_latent = jnp.concatenate([prior, rec], -1)
            return (prior, rec, new_latent), (new_latent, action)

        keys = jax.random.split(k_img, horizon)
        _, (traj, actions) = jax.lax.scan(img_step, (prior0, rec0, latent0), keys, unroll=5)
        return traj, actions  # both [H, N, ...]

    def _continues(wm_params, traj, like):
        if use_continues:
            return jax.nn.sigmoid(world_model.apply(wm_params, traj, method=WorldModelV1.continues))
        return jnp.ones_like(like) * gamma

    def _critic_loss(critic_params, traj, lambda_values, discount):
        qv = Independent(Normal(critic.apply(critic_params, traj[:-1]), 1.0), 1)
        return -jnp.mean(discount[..., 0] * qv.log_prob(lambda_values))

    def train_step(params, opt_states, data, key):
        T, B = data["rewards"].shape[:2]
        k_wm, k_img_e, k_img_t = jax.random.split(key, 3)
        sg = jax.lax.stop_gradient

        batch_obs = {k: data[k] for k in cnn_keys + mlp_keys}
        batch_actions = jnp.concatenate([jnp.zeros_like(data["actions"][:1]), data["actions"][:-1]], 0)

        # ---------------------------------------------------- 1. world model
        def wm_loss_fn(wm_params):
            embed = world_model.apply(wm_params, batch_obs, method=WorldModelV1.encode)

            def step(carry, x):
                post, rec = carry
                action, emb, k = x
                rec, post, _, post_ms, prior_ms = world_model.apply(
                    wm_params, post, rec, action, emb, k, method=WorldModelV1.dynamic
                )
                return (post, rec), (rec, post, post_ms, prior_ms)

            keys = jax.random.split(k_wm, T)
            init = (jnp.zeros((B, stoch_size)), jnp.zeros((B, rec_size)))
            _, (recs, posts, post_ms, prior_ms) = jax.lax.scan(step, init, (batch_actions, embed, keys), unroll=8)
            latents = jnp.concatenate([posts, recs], -1)
            recon = world_model.apply(wm_params, latents, method=WorldModelV1.decode)

            obs_lp = 0.0
            for k in cnn_keys:
                target = data[k].astype(jnp.float32) / 255.0 - 0.5
                target = target.reshape(T, B, -1, *target.shape[-2:])
                obs_lp = obs_lp + Independent(Normal(recon[k], jnp.ones_like(recon[k])), 3).log_prob(target)
            for k in mlp_keys:
                obs_lp = obs_lp + Independent(Normal(recon[k], jnp.ones_like(recon[k])), 1).log_prob(data[k])

            reward_lp = Independent(
                Normal(world_model.apply(wm_params, sg(latents), method=WorldModelV1.reward), 1.0), 1
            ).log_prob(data["rewards"])
            continue_lp = None
            if use_continues:
                continue_lp = Independent(
                    BernoulliSafeMode(world_model.apply(wm_params, sg(latents), method=WorldModelV1.continues)), 1
                ).log_prob((1.0 - data["terminated"]) * gamma)

            rec_loss, metrics = reconstruction_loss(
                obs_lp,
                reward_lp,
                post_ms,
                prior_ms,
                wm_cfg.kl_free_nats,
                wm_cfg.kl_regularizer,
                continue_lp,
                wm_cfg.continue_scale_factor,
            )
            metrics["State/post_entropy"] = Independent(Normal(*post_ms), 1).entropy().mean()
            metrics["State/prior_entropy"] = Independent(Normal(*prior_ms), 1).entropy().mean()
            return rec_loss, (posts, recs, sg(embed), metrics)

        (rec_loss, (posts, recs, embed, wm_metrics)), wm_grads = jax.value_and_grad(wm_loss_fn, has_aux=True)(
            params["world_model"]
        )
        wm_updates, new_wm_opt = wm_opt.update(wm_grads, opt_states["world_model"], params["world_model"])
        new_wm_params = optax.apply_updates(params["world_model"], wm_updates)

        # ---------------------------------------------------- 2. ensembles
        ens_inputs = jnp.concatenate([sg(posts), sg(recs), data["actions"]], -1)
        ens_targets = embed[1:]
        ens_loss_val, ens_grads = jax.value_and_grad(
            lambda p: ensemble_loss_normal(ensemble_mlp, p, ens_inputs, ens_targets)
        )(params["ensembles"])
        ens_updates, new_ens_opt = ens_opt.update(ens_grads, opt_states["ensembles"], params["ensembles"])
        new_ens_params = optax.apply_updates(params["ensembles"], ens_updates)

        # ---------------------------------------------------- 3. exploration behaviour
        prior0 = sg(posts).reshape(T * B, stoch_size)
        rec0 = sg(recs).reshape(T * B, rec_size)
        latent0 = jnp.concatenate([prior0, rec0], -1)

        def expl_actor_loss_fn(actor_params):
            traj, actions = _imagine(actor_params, new_wm_params, prior0, rec0, latent0, k_img_e)
            values = critic.apply(params["critic_exploration"], traj)
            reward = intrinsic_reward(
                ensemble_mlp, new_ens_params, jnp.concatenate([sg(traj), sg(actions)], -1), intr_mult
            )
            continues = _continues(new_wm_params, traj, reward)
            lambda_values = compute_lambda_values(reward, values, continues, lmbda)  # [H-1, N, 1]
            discount = sg(
                jnp.cumprod(jnp.concatenate([jnp.ones_like(continues[:1]), continues[:-2]], 0), 0)
            )
            loss = -jnp.mean(discount * lambda_values)
            aux = {
                "traj": sg(traj),
                "lambda_values": sg(lambda_values),
                "discount": discount,
                "metrics": {
                    "Rewards/intrinsic": reward.mean(),
                    "Values_exploration/predicted_values": values.mean(),
                    "Values_exploration/lambda_values": lambda_values.mean(),
                },
            }
            return loss, aux

        (policy_loss_expl, expl_aux), expl_grads = jax.value_and_grad(expl_actor_loss_fn, has_aux=True)(
            params["actor_exploration"]
        )
        ae_updates, new_ae_opt = actor_opt.update(
            expl_grads, opt_states["actor_exploration"], params["actor_exploration"]
        )
        new_actor_expl = optax.apply_updates(params["actor_exploration"], ae_updates)

        value_loss_expl, ce_grads = jax.value_and_grad(_critic_loss)(
            params["critic_exploration"], expl_aux["traj"], expl_aux["lambda_values"], expl_aux["discount"]
        )
        ce_updates, new_ce_opt = critic_opt.update(
            ce_grads, opt_states["critic_exploration"], params["critic_exploration"]
        )
        new_critic_expl = optax.apply_updates(params["critic_exploration"], ce_updates)

        # ---------------------------------------------------- 4. task behaviour
        def task_actor_loss_fn(actor_params):
            traj, _ = _imagine(actor_params, new_wm_params, prior0, rec0, latent0, k_img_t)
            values = critic.apply(params["critic_task"], traj)
            reward = world_model.apply(new_wm_params, traj, method=WorldModelV1.reward)
            continues = _continues(new_wm_params, traj, reward)
            lambda_values = compute_lambda_values(reward, values, continues, lmbda)
            discount = sg(
                jnp.cumprod(jnp.concatenate([jnp.ones_like(continues[:1]), continues[:-2]], 0), 0)
            )
            loss = -jnp.mean(discount * lambda_values)
            aux = {"traj": sg(traj), "lambda_values": sg(lambda_values), "discount": discount}
            return loss, aux

        (policy_loss_task, task_aux), task_grads = jax.value_and_grad(task_actor_loss_fn, has_aux=True)(
            params["actor_task"]
        )
        at_updates, new_at_opt = actor_opt.update(task_grads, opt_states["actor_task"], params["actor_task"])
        new_actor_task = optax.apply_updates(params["actor_task"], at_updates)

        value_loss_task, ct_grads = jax.value_and_grad(_critic_loss)(
            params["critic_task"], task_aux["traj"], task_aux["lambda_values"], task_aux["discount"]
        )
        ct_updates, new_ct_opt = critic_opt.update(ct_grads, opt_states["critic_task"], params["critic_task"])
        new_critic_task = optax.apply_updates(params["critic_task"], ct_updates)

        new_params = {
            "world_model": new_wm_params,
            "actor_task": new_actor_task,
            "critic_task": new_critic_task,
            "actor_exploration": new_actor_expl,
            "critic_exploration": new_critic_expl,
            "ensembles": new_ens_params,
        }
        new_opt_states = {
            "world_model": new_wm_opt,
            "actor_task": new_at_opt,
            "critic_task": new_ct_opt,
            "actor_exploration": new_ae_opt,
            "critic_exploration": new_ce_opt,
            "ensembles": new_ens_opt,
        }
        metrics = dict(wm_metrics)
        metrics.update(expl_aux["metrics"])
        metrics["Loss/ensemble_loss"] = ens_loss_val
        metrics["Loss/policy_loss_exploration"] = policy_loss_expl
        metrics["Loss/value_loss_exploration"] = value_loss_expl
        metrics["Loss/policy_loss_task"] = policy_loss_task
        metrics["Loss/value_loss_task"] = value_loss_task
        if health_enabled(cfg):  # trace-time constant (obs/health.py)
            metrics.update(
                diagnostics(
                    grads={"world_model": wm_grads, "ensembles": ens_grads, "actor_exploration": expl_grads, "critic_exploration": ce_grads, "actor_task": task_grads, "critic_task": ct_grads},
                    params=new_params,
                    updates={"world_model": wm_updates, "ensembles": ens_updates, "actor_exploration": ae_updates, "critic_exploration": ce_updates, "actor_task": at_updates, "critic_task": ct_updates},
                )
            )
        metrics = maybe_inject_nonfinite(cfg, metrics)
        if strict_enabled(cfg):  # trace-time constant: callback exists only in strict runs
            nan_scan(metrics, "p2e_dv1/train_step")
        return new_params, new_opt_states, metrics

    return train_step, init_opt_states


@register_algorithm(name="p2e_dv1_exploration")
def main(ctx, cfg) -> None:
    rank = ctx.process_index
    log_dir = get_log_dir(cfg)
    if ctx.is_global_zero:
        save_config(cfg, Path(log_dir) / "config.yaml")
    logger = get_logger(cfg, log_dir)
    monitor = TrainingMonitor(cfg, log_dir)

    envs = make_vector_env(cfg, cfg.seed, rank, log_dir if cfg.env.capture_video else None)
    obs_space = envs.single_observation_space
    act_space = envs.single_action_space
    is_continuous, actions_dim = parse_actions_dim(act_space)
    act_dim_sum = int(sum(actions_dim))
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    num_envs = cfg.env.num_envs
    world = jax.process_count()

    world_model, actor, critic, ensemble_mlp, params, _ = build_agent(
        ctx, actions_dim, is_continuous, cfg, obs_space
    )
    train_step, init_opt_states = make_train_step(world_model, actor, critic, ensemble_mlp, cfg, cnn_keys, mlp_keys)
    opt_states = ctx.replicate(init_opt_states(params))
    # One jitted scan per iteration's gradient block (utils/blocks.py).
    def _block_step(carry, batch, key, update_target):
        del update_target
        params, opt_states = carry
        params, opt_states, metrics = train_step(params, opt_states, batch, key)
        return (params, opt_states), metrics

    player_step = make_player_step(world_model, actor, actions_dim, is_continuous)
    player_jit = jax.jit(player_step, static_argnames=("greedy",))
    actor_type = cfg.algo.player.get("actor_type", "exploration")
    player_actor_key = "actor_exploration" if actor_type == "exploration" else "actor_task"
    stoch_size = cfg.algo.world_model.stochastic_size
    rec_size = cfg.algo.world_model.recurrent_model.recurrent_state_size

    def player_params():
        return {"world_model": params["world_model"], "actor": params[player_actor_key]}

    def player_state_init(n: int) -> PlayerState:
        return PlayerState(
            recurrent_state=jnp.zeros((n, rec_size)),
            stochastic_state=jnp.zeros((n, stoch_size)),
            actions=jnp.zeros((n, act_dim_sum)),
        )

    buffer_size = max(int(cfg.buffer.size) // max(num_envs * world, 1), 1)
    rb = EnvIndependentReplayBuffer(
        buffer_size,
        n_envs=num_envs,
        obs_keys=obs_keys,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}") if cfg.buffer.memmap else None,
        buffer_cls=SequentialReplayBuffer,
    )
    rb.seed(cfg.seed + rank)

    # Device-vs-host replay data path, one shared implementation
    # (data/device_buffer.py): HBM mirror + index-only sampling when
    # buffer.device=True, async host prefetch otherwise.
    dispatcher, mirror, prefetcher, _run_block, rb_add = make_device_replay(
        ctx, cfg, rb, cnn_keys, mlp_keys, obs_space, act_dim_sum, _block_step
    )

    aggregator = MetricAggregator(cfg.metric.aggregator.get("metrics", {}))
    aggregator.keep(AGGREGATOR_KEYS | set(cfg.metric.aggregator.get("metrics", {})))
    ckpt_manager = CheckpointManager(Path(log_dir) / "checkpoints", keep_last=cfg.checkpoint.keep_last)
    guard = TrainingGuard(cfg, log_dir)
    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)

    batch_size = cfg.algo.per_rank_batch_size
    seq_len = cfg.algo.per_rank_sequence_length
    policy_steps_per_iter = num_envs * world * cfg.env.action_repeat
    total_steps = int(cfg.algo.total_steps)
    num_iters = max(total_steps // policy_steps_per_iter, 1) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    expl_cfg = cfg.algo.actor

    start_iter = 1
    policy_step = 0
    last_log = 0
    last_checkpoint = 0
    cumulative_grad_steps = 0
    if cfg.checkpoint.get("resume_from"):
        state = CheckpointManager.load(
            cfg.checkpoint.resume_from,
            templates={"params": jax.device_get(params), "opt_states": jax.device_get(opt_states)},
        )
        params = ctx.replicate(state["params"])
        opt_states = ctx.replicate(state["opt_states"])
        ratio.load_state_dict(state["ratio"])
        start_iter = state["iter_num"] + 1
        policy_step = state["policy_step"]
        last_log = state.get("last_log", 0)
        last_checkpoint = state.get("last_checkpoint", 0)
        cumulative_grad_steps = state.get("cumulative_grad_steps", 0)
        learning_starts += start_iter
        if cfg.buffer.checkpoint and "rb" in state:
            rb.load_state_dict(state["rb"])
            if mirror is not None:
                mirror.load_from(rb)

    def _obs_row(o, idxs=None):
        row = {}
        for k in cnn_keys:
            v = np.asarray(o[k]) if idxs is None else np.asarray(o[k])[idxs]
            row[k] = v.reshape(1, v.shape[0], -1, *v.shape[-2:])
        for k in mlp_keys:
            v = np.asarray(o[k], dtype=np.float32) if idxs is None else np.asarray(o[k], dtype=np.float32)[idxs]
            row[k] = v.reshape(1, v.shape[0], -1)
        return row

    obs, _ = envs.reset(seed=cfg.seed + rank)
    player_state = player_state_init(num_envs)
    step_data: Dict[str, np.ndarray] = _obs_row(obs)
    step_data["rewards"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["terminated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["truncated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["is_first"] = np.ones((1, num_envs, 1), np.float32)
    is_first_np = np.ones((num_envs, 1), dtype=np.float32)
    prefill_iters = max(learning_starts - 1, 0)

    for iter_num in range(start_iter, num_iters + 1):
        monitor.advance()
        env_t0 = time.perf_counter()
        expl_amount = exploration_amount(
            expl_cfg.get("expl_amount", 0.0), expl_cfg.get("expl_decay", 0.0), expl_cfg.get("expl_min", 0.0), policy_step
        )
        with timer("Time/env_interaction_time"):
            if iter_num <= learning_starts and not cfg.checkpoint.get("resume_from"):
                if is_continuous:
                    stored_actions = np.stack([act_space.sample() for _ in range(num_envs)]).astype(np.float32)
                    env_actions = stored_actions
                else:
                    sampled = np.stack([act_space.sample() for _ in range(num_envs)]).reshape(num_envs, -1)
                    onehots = []
                    for i, d in enumerate(actions_dim):
                        oh = np.zeros((num_envs, d), dtype=np.float32)
                        oh[np.arange(num_envs), sampled[:, i]] = 1.0
                        onehots.append(oh)
                    stored_actions = np.concatenate(onehots, -1)
                    env_actions = sampled.squeeze(-1) if len(actions_dim) == 1 else sampled
                player_state = player_state._replace(actions=jnp.asarray(stored_actions))
            else:
                obs_t = prepare_obs(obs, cnn_keys, mlp_keys, num_envs)
                actions, stored, player_state = player_jit(
                    player_params(), player_state, obs_t, jnp.asarray(is_first_np), ctx.local_rng(), jnp.asarray(expl_amount)
                )
                # ONE device_get for everything the host needs (per-array fetches
                # would each pay a transfer round trip on a remote accelerator).
                stored_np, acts_list = jax.device_get((stored, list(actions)))
                stored_actions = np.asarray(stored_np)
                acts_np = [np.asarray(a) for a in acts_list]
                if is_continuous:
                    env_actions = acts_np[0]
                elif len(actions_dim) == 1:
                    env_actions = acts_np[0].argmax(-1)
                else:
                    env_actions = np.stack([a.argmax(-1) for a in acts_np], -1)

            step_data["actions"] = stored_actions.reshape(1, num_envs, -1)
            rb_add(step_data, validate_args=cfg.buffer.validate_args)
        env_time = time.perf_counter() - env_t0

        # Dispatch this iteration's gradient block BEFORE stepping the envs: the
        # device trains while the host walks the environments below (acting above
        # used the previous iteration's params, exactly as the eager ordering did).
        grad_steps = 0
        if iter_num >= learning_starts:
            grad_steps = ratio(
                (policy_step + policy_steps_per_iter - prefill_iters * policy_steps_per_iter) / world
            )
            if grad_steps > 0:
                params, opt_states = _run_block(
                    (params, opt_states), grad_steps, cumulative_grad_steps, stage_next=iter_num < num_iters
                )
                cumulative_grad_steps += grad_steps

        env_t0 = time.perf_counter()
        with timer("Time/env_interaction_time"):
            next_obs, reward, terminated, truncated, info = envs.step(env_actions)
            if cfg.env.clip_rewards:
                reward = np.tanh(reward)
            done = np.logical_or(terminated, truncated)
            reward = np.asarray(reward, dtype=np.float32).reshape(num_envs, 1)

            real_next_obs = {k: np.asarray(next_obs[k]).copy() for k in obs_keys}
            if done.any() and "final_obs" in info:
                for i in np.nonzero(done)[0]:
                    if info["final_obs"][i] is not None:
                        for k in obs_keys:
                            real_next_obs[k][i] = np.asarray(info["final_obs"][i][k])

            step_data = _obs_row(next_obs)
            step_data["rewards"] = reward.reshape(1, num_envs, 1).copy()
            step_data["terminated"] = terminated.astype(np.float32).reshape(1, num_envs, 1)
            step_data["truncated"] = truncated.astype(np.float32).reshape(1, num_envs, 1)
            step_data["is_first"] = np.zeros((1, num_envs, 1), np.float32)

            done_idxs = np.nonzero(done)[0].tolist()
            if done_idxs:
                reset_data = _obs_row(real_next_obs, idxs=done_idxs)
                reset_data["rewards"] = step_data["rewards"][:, done_idxs]
                reset_data["terminated"] = step_data["terminated"][:, done_idxs]
                reset_data["truncated"] = step_data["truncated"][:, done_idxs]
                reset_data["actions"] = np.zeros((1, len(done_idxs), act_dim_sum), np.float32)
                reset_data["is_first"] = np.zeros_like(reset_data["terminated"])
                rb_add(reset_data, done_idxs, validate_args=cfg.buffer.validate_args)
                step_data["rewards"][:, done_idxs] = 0.0
                step_data["terminated"][:, done_idxs] = 0.0
                step_data["truncated"][:, done_idxs] = 0.0
                step_data["is_first"][:, done_idxs] = 1.0

            is_first_np = done.astype(np.float32).reshape(num_envs, 1)
            obs = next_obs
            policy_step += policy_steps_per_iter
            record_episode_stats(aggregator, info)
        env_time += time.perf_counter() - env_t0

        if logger is not None and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == num_iters or cfg.dry_run
        ):
            dispatcher.drain(aggregator)  # the window's only blocking device sync
            metrics = aggregator.compute()
            metrics.update(replay_age_metrics(rb))
            window_sps = dispatcher.pop_window_sps()
            if window_sps is not None:
                metrics["Time/sps_train"] = window_sps
            metrics["Time/sps_env_interaction"] = (
                policy_steps_per_iter / world / env_time if env_time > 0 else 0.0
            )
            metrics["Params/replay_ratio"] = (
                cumulative_grad_steps * world / policy_step if policy_step > 0 else 0.0
            )
            metrics["Params/exploration_amount"] = expl_amount
            monitor.log_metrics(logger, metrics, policy_step)
            aggregator.reset()
            last_log = policy_step

        def save_ckpt():
            nonlocal last_checkpoint
            state = {
                "params": params,
                "opt_states": opt_states,
                "ratio": ratio.state_dict(),
                "iter_num": iter_num,
                "policy_step": policy_step,
                "last_log": last_log,
                "last_checkpoint": policy_step,
                "cumulative_grad_steps": cumulative_grad_steps,
            }
            if cfg.buffer.checkpoint:
                state["rb"] = rb.state_dict()
            path = ckpt_manager.save(policy_step, state)
            last_checkpoint = policy_step
            return path

        if (
            cfg.checkpoint.every > 0
            and (policy_step - last_checkpoint) >= cfg.checkpoint.every
            or iter_num == num_iters
            and cfg.checkpoint.save_last
        ):
            save_ckpt()
        guard.boundary(policy_step, save_ckpt)

    monitor.close()
    envs.close()
    if prefetcher is not None:
        prefetcher.close()
    if cfg.algo.run_test and ctx.is_global_zero:
        reward = test(player_step, player_params(), player_state_init, ctx, cfg, log_dir)
        if logger is not None:
            logger.log_metrics({"Test/cumulative_reward": reward}, policy_step)
    if logger is not None:
        logger.close()


def lower_for_audit():
    """IR-audit hook (``python -m sheeprl_tpu.analysis.ir``): the P2E-DV1
    exploration gradient block (world model + task/exploration heads + intrinsic
    ensembles in one ``make_train_block`` scan) at tiny MLP-only shapes."""
    from sheeprl_tpu.analysis.ir.synth import (
        DREAMER_TINY_OVERRIDES,
        compose_tiny,
        sequence_batch,
        tiny_ctx,
        vector_space,
    )
    from sheeprl_tpu.analysis.ir.types import AuditEntry
    from sheeprl_tpu.utils.blocks import make_train_block

    cfg = compose_tiny(
        [
            "exp=p2e_dv1_dummy",
            "env=discrete_dummy",
            *DREAMER_TINY_OVERRIDES,
            "algo.ensembles.n=2",
            "algo.ensembles.dense_units=8",
            "algo.ensembles.mlp_layers=1",
        ]
    )
    ctx = tiny_ctx(cfg)
    obs_space = vector_space()
    actions_dim, is_continuous = (3,), False
    world_model, actor, critic, ensemble_mlp, params, _ = build_agent(
        ctx, actions_dim, is_continuous, cfg, obs_space
    )
    train_step, init_opt_states = make_train_step(
        world_model, actor, critic, ensemble_mlp, cfg, [], ["state"]
    )
    carry = (params, init_opt_states(params))

    def _block_step(carry, batch, key, update_target):
        del update_target
        params, opt_states = carry
        params, opt_states, metrics = train_step(params, opt_states, batch, key)
        return (params, opt_states), metrics

    block = make_train_block(_block_step, 1, 1)
    batch = sequence_batch(
        {"state": obs_space["state"].shape},
        act_dim=int(sum(actions_dim)),
        T=int(cfg.algo.per_rank_sequence_length),
        B=int(cfg.algo.per_rank_batch_size),
    )
    return [
        AuditEntry(
            name="p2e_dv1/train_block",
            fn=block,
            args=(carry, (batch,), jax.random.PRNGKey(0), 0),
            covers=("p2e_dv1_exploration",),
            precision=str(cfg.mesh.precision),
        )
    ]
